"""Taint dataflow over the stdlib-ast IR (the v3 engine layer).

PR 6's call graph can say *who calls whom*; nothing in the engine can
say *where a value came from*.  This module adds that axis: a
may-taint analysis whose sources are the network reads — every value
decoded from a wire frame is attacker-controlled until it passes a
sanitizer — propagated per function in program order (def-use chains
with strong updates on assignment, the reaching-definitions view a
statement-ordered walk gives) and across functions through the call
graph.

The lattice is deliberately small.  A name's abstract value is a set
of *origins*:

- ``"wire"`` — the value derives from a network read
  (``reader.readexactly`` / ``sock.recv`` / the ``framing.read_*`` /
  ``recv_*`` helpers) or a struct-unpack of bytes that do;
- ``"param:<name>"`` — the value derives from the function's own
  parameter, used to build interprocedural summaries (a caller
  substitutes its argument origins for these labels).

Empty set = untrusted by nobody = clean.  May-taint only: branches
union, loops run to a (two-pass) fixpoint, and a strong update on
assignment kills prior taint.

Sanitizers clear origins:

- a call to a ``validate_*`` / ``*_in_range`` function sanitizes its
  return value AND the argument names it was given (the validator
  raises on bad input, so the names are in-range afterwards) —
  ``net.protocol.validate_count`` and ``core.geometry.validate_indices``
  are the sanctioned spellings;
- ``min(x, bound)`` / ``max(x, bound)`` with at least one clean
  operand is a clamp: the result is clean;
- a range/clamp comparison guard: names compared inside an ``if`` test
  are clean within the guarded body and the ``else``; when the body
  unconditionally escapes (raise/return/break/continue) they are clean
  after the ``if`` too;
- ``len()`` of anything is clean (exact-length reads make a buffer's
  length the reader's choice, not the peer's).

Interprocedural summaries (fixpoint over the call graph, like the
lock rules' blocking summaries):

- *return origins*: calling a function whose return derives from the
  wire taints the call result (``framing.read_u32`` needs no special
  casing — its body reads the socket, so the fixpoint marks it);
  ``param:`` labels in a summary are substituted with the caller's
  argument origins, so a pass-through helper (``self._read(coro)``)
  forwards taint faithfully;
- *param sinks*: a function whose parameter reaches a sink without a
  sanitizer exports ``(param, sink)``; a caller passing wire-tainted
  data to that parameter is flagged at the call site with the call
  path named — a one-level helper no longer hides an allocation.

Everything here is stdlib ``ast``; the package under analysis is never
imported, and the whole pass is a bounded number of AST walks per
function — comfortably inside the tier-1 gate's five-second budget.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Iterator, Optional

from distributedmandelbrot_tpu.analysis import callgraph
from distributedmandelbrot_tpu.analysis.astutil import (attr_chain,
                                                        cached_walk)
from distributedmandelbrot_tpu.analysis.engine import Project

__all__ = ["Sink", "TaintSummary", "ProjectTaint", "WIRE", "analyze"]

WIRE = "wire"

# Root source methods: the value returned IS bytes straight off a
# socket.  Receiver-independent — any ``.recv`` spelled like the socket
# API counts (the conservative reading for a security source).
_ROOT_SOURCE_METHODS = frozenset({"readexactly", "recv", "recv_into"})
# Helper names recognized as sources even when the callee is outside
# the analyzed project (test fixtures stub ``framing``; the installed
# package resolves these through summaries anyway).
_NAMED_SOURCES = frozenset({
    "read_exact", "read_u32", "read_byte",
    "recv_exact", "recv_u32", "recv_byte",
})
# struct methods that forward their input's taint to their output.
_UNPACKERS = frozenset({"unpack", "unpack_from", "iter_unpack"})
# Calls whose result is never attacker-sized regardless of arguments.
_CLEAN_CALLS = frozenset({"len", "bool", "isinstance", "id", "type",
                          "enumerate", "zip", "repr", "hash"})


def _is_sanitizer_name(name: str) -> bool:
    return name.startswith("validate_") or name.endswith("_in_range")


@dataclass(frozen=True)
class Sink:
    """One sink reached by tainted data inside a single function."""

    kind: str       # "alloc" | "index" | "loop" | "struct"
    line: int
    detail: str     # human fragment, e.g. "bytes() size"
    origins: frozenset


@dataclass
class TaintSummary:
    """Per-function facts exported to callers."""

    return_origins: frozenset = frozenset()
    # (param name, sink kind, sink detail, sink relpath, sink line)
    param_sinks: tuple = ()


@dataclass
class _FnResult:
    sinks: list = field(default_factory=list)
    return_origins: frozenset = frozenset()
    # call node id -> (callee qualname, per-arg origins) for sites whose
    # arguments were tainted when visited (interprocedural extension).
    tainted_calls: dict = field(default_factory=dict)


class _Env:
    """Dotted-name -> origin set.  Missing = clean."""

    def __init__(self, taint: Optional[dict] = None) -> None:
        self.taint: dict[str, frozenset] = dict(taint or {})

    def copy(self) -> "_Env":
        return _Env(self.taint)

    def merge(self, other: "_Env") -> None:
        for name, origins in other.taint.items():
            self.taint[name] = self.taint.get(name, frozenset()) | origins

    def get(self, name: str) -> frozenset:
        return self.taint.get(name, frozenset())

    def set(self, name: str, origins: frozenset) -> None:
        if origins:
            self.taint[name] = origins
        else:
            self.taint.pop(name, None)

    def sanitize(self, name: str) -> None:
        self.taint.pop(name, None)


class _FunctionTaint:
    """One program-order taint walk over a function body."""

    def __init__(self, project_taint: "ProjectTaint", qualname: str,
                 fn: callgraph.FunctionInfo) -> None:
        self.pt = project_taint
        self.qualname = qualname
        self.fn = fn
        self.result = _FnResult()

    # -- entry -------------------------------------------------------------

    def run(self) -> _FnResult:
        env = _Env()
        node = self.fn.node
        args = node.args
        for a in (args.posonlyargs + args.args + args.kwonlyargs):
            if a.arg in ("self", "cls"):
                continue
            env.set(a.arg, frozenset({f"param:{a.arg}"}))
        self._walk_body(node.body, env)
        return self.result

    # -- statements --------------------------------------------------------

    def _walk_body(self, body: list, env: _Env) -> None:
        for stmt in body:
            self._stmt(stmt, env)

    def _stmt(self, stmt: ast.stmt, env: _Env) -> None:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            return  # nested scopes run later; not part of this walk
        if isinstance(stmt, ast.Assign):
            origins = self._expr(stmt.value, env)
            for target in stmt.targets:
                self._assign(target, stmt.value, origins, env)
        elif isinstance(stmt, ast.AnnAssign):
            if stmt.value is not None:
                origins = self._expr(stmt.value, env)
                self._assign(stmt.target, stmt.value, origins, env)
        elif isinstance(stmt, ast.AugAssign):
            origins = self._expr(stmt.value, env)
            name = _dotted(stmt.target)
            if name is not None:
                env.set(name, env.get(name) | origins)
        elif isinstance(stmt, ast.Return):
            if stmt.value is not None:
                self.result.return_origins |= self._expr(stmt.value, env)
        elif isinstance(stmt, ast.Expr):
            self._expr(stmt.value, env)
        elif isinstance(stmt, ast.If):
            self._if(stmt, env)
        elif isinstance(stmt, (ast.For, ast.AsyncFor)):
            self._for(stmt, env)
        elif isinstance(stmt, ast.While):
            self._while(stmt, env)
        elif isinstance(stmt, (ast.With, ast.AsyncWith)):
            for item in stmt.items:
                origins = self._expr(item.context_expr, env)
                if item.optional_vars is not None:
                    self._assign(item.optional_vars, item.context_expr,
                                 origins, env)
            self._walk_body(stmt.body, env)
        elif isinstance(stmt, ast.Try):
            # Handlers/finally may run from any prefix of the body: walk
            # the body on the live env, then handlers on a union of the
            # pre-body and post-body states.
            pre = env.copy()
            self._walk_body(stmt.body, env)
            handler_env = env.copy()
            handler_env.merge(pre)
            for handler in stmt.handlers:
                h_env = handler_env.copy()
                self._walk_body(handler.body, h_env)
                env.merge(h_env)
            self._walk_body(stmt.orelse, env)
            self._walk_body(stmt.finalbody, env)
        elif isinstance(stmt, ast.Raise):
            if stmt.exc is not None:
                self._expr(stmt.exc, env)
        elif isinstance(stmt, (ast.Delete, ast.Assert)):
            for sub in cached_walk(stmt):
                if isinstance(sub, ast.expr):
                    self._expr_shallow_sinks(sub, env)
        # pass/break/continue/global/import: nothing to do

    def _if(self, stmt: ast.If, env: _Env) -> None:
        guarded = _compared_names(stmt.test)
        self._expr(stmt.test, env)
        body_env = env.copy()
        for name in guarded:
            body_env.sanitize(name)
        self._walk_body(stmt.body, body_env)
        else_env = env.copy()
        for name in guarded:
            else_env.sanitize(name)
        self._walk_body(stmt.orelse, else_env)
        if _escapes(stmt.body):
            # Only the else edge survives: the guard proved the names
            # in-range on every path that continues.
            env.taint = else_env.taint
        else:
            body_env.merge(else_env)
            env.taint = body_env.taint

    def _for(self, stmt: ast.For | ast.AsyncFor, env: _Env) -> None:
        iter_origins = self._expr(stmt.iter, env)
        self._check_loop_sink(stmt.iter, env)
        # Two passes: the second sees taint created on the first (loop-
        # carried flows); may-taint only grows, so two suffice in
        # practice and keep the walk linear.
        for _ in range(2):
            self._assign(stmt.target, None, iter_origins, env)
            self._walk_body(stmt.body, env)
        self._walk_body(stmt.orelse, env)

    def _while(self, stmt: ast.While, env: _Env) -> None:
        test_origins = frozenset()
        for sub in cached_walk(stmt.test):
            name = _dotted(sub) if isinstance(sub, ast.expr) else None
            if name is not None:
                test_origins |= env.get(name)
        if test_origins:
            self._sink("loop", stmt.lineno, "while-loop bound",
                       test_origins)
        for _ in range(2):
            self._walk_body(stmt.body, env)
        self._walk_body(stmt.orelse, env)

    def _assign(self, target: ast.expr, value: Optional[ast.expr],
                origins: frozenset, env: _Env) -> None:
        if isinstance(target, (ast.Tuple, ast.List)):
            elements = list(target.elts)
            values: list[Optional[ast.expr]] = [None] * len(elements)
            if isinstance(value, (ast.Tuple, ast.List)) \
                    and len(value.elts) == len(elements):
                values = list(value.elts)
            for elt, sub_value in zip(elements, values):
                sub_origins = (self._expr(sub_value, env)
                               if sub_value is not None else origins)
                self._assign(elt, sub_value, sub_origins, env)
            return
        if isinstance(target, ast.Starred):
            self._assign(target.value, None, origins, env)
            return
        if isinstance(target, ast.Subscript):
            self._check_index_sink(target, env)
            return  # container poisoning is out of scope
        name = _dotted(target)
        if name is not None:
            env.set(name, origins)

    # -- expressions -------------------------------------------------------

    def _expr(self, expr: ast.expr, env: _Env) -> frozenset:
        """Origin set of an expression; records sinks seen on the way."""
        if isinstance(expr, ast.Constant):
            return frozenset()
        if isinstance(expr, (ast.Name, ast.Attribute)):
            name = _dotted(expr)
            return env.get(name) if name is not None else frozenset()
        if isinstance(expr, ast.Await):
            return self._expr(expr.value, env)
        if isinstance(expr, ast.NamedExpr):
            origins = self._expr(expr.value, env)
            self._assign(expr.target, expr.value, origins, env)
            return origins
        if isinstance(expr, ast.Call):
            return self._call(expr, env)
        if isinstance(expr, ast.BinOp):
            return self._expr(expr.left, env) | self._expr(expr.right, env)
        if isinstance(expr, ast.UnaryOp):
            return self._expr(expr.operand, env)
        if isinstance(expr, ast.BoolOp):
            out = frozenset()
            for v in expr.values:
                out |= self._expr(v, env)
            return out
        if isinstance(expr, ast.Compare):
            self._expr(expr.left, env)
            for c in expr.comparators:
                self._expr(c, env)
            return frozenset()  # a boolean is not a size
        if isinstance(expr, ast.IfExp):
            self._expr(expr.test, env)
            return self._expr(expr.body, env) | self._expr(expr.orelse, env)
        if isinstance(expr, ast.Subscript):
            self._check_index_sink(expr, env)
            return self._expr(expr.value, env)
        if isinstance(expr, (ast.Tuple, ast.List, ast.Set)):
            out = frozenset()
            for elt in expr.elts:
                out |= self._expr(elt, env)
            return out
        if isinstance(expr, ast.Dict):
            out = frozenset()
            for k, v in zip(expr.keys, expr.values):
                if k is not None:
                    out |= self._expr(k, env)
                out |= self._expr(v, env)
            return out
        if isinstance(expr, ast.JoinedStr):
            out = frozenset()
            for part in expr.values:
                if isinstance(part, ast.FormattedValue):
                    out |= self._expr(part.value, env)
            return out
        if isinstance(expr, ast.FormattedValue):
            return self._expr(expr.value, env)
        if isinstance(expr, ast.Starred):
            return self._expr(expr.value, env)
        if isinstance(expr, (ast.ListComp, ast.SetComp, ast.GeneratorExp,
                             ast.DictComp)):
            return self._comprehension(expr, env)
        if isinstance(expr, ast.Slice):
            out = frozenset()
            for part in (expr.lower, expr.upper, expr.step):
                if part is not None:
                    out |= self._expr(part, env)
            return out
        if isinstance(expr, ast.Lambda):
            return frozenset()  # runs later, like a nested def
        return frozenset()

    def _expr_shallow_sinks(self, expr: ast.expr, env: _Env) -> None:
        if isinstance(expr, ast.Subscript):
            self._check_index_sink(expr, env)

    def _comprehension(self, expr, env: _Env) -> frozenset:
        inner = env.copy()
        for gen in expr.generators:
            origins = self._expr(gen.iter, inner)
            self._check_loop_sink(gen.iter, inner)
            self._assign(gen.target, None, origins, inner)
            for cond in gen.ifs:
                self._expr(cond, inner)
        if isinstance(expr, ast.DictComp):
            return self._expr(expr.key, inner) | self._expr(expr.value,
                                                            inner)
        return self._expr(expr.elt, inner)

    # -- calls: sources, sanitizers, sinks, summaries ----------------------

    def _call(self, call: ast.Call, env: _Env) -> frozenset:
        chain = attr_chain(call.func) or []
        name = chain[-1] if chain else ""
        arg_origins = [self._expr(a, env) for a in call.args]
        kw_origins = {kw.arg: self._expr(kw.value, env)
                      for kw in call.keywords}
        all_args = frozenset().union(*arg_origins, *kw_origins.values()) \
            if (arg_origins or kw_origins) else frozenset()

        # Sinks first: the arguments' taint is judged pre-sanitization.
        self._call_sinks(call, chain, name, arg_origins, kw_origins, env)

        # Sanitizers.
        if _is_sanitizer_name(name):
            for arg in call.args:
                dotted = _dotted(arg)
                if dotted is not None:
                    env.sanitize(dotted)
            return frozenset()
        if name in ("min", "max"):
            if any(not o for o in arg_origins):
                return frozenset()  # clamped against a clean bound
            return all_args
        if name in _CLEAN_CALLS:
            return frozenset()

        # Sources.
        if name in _ROOT_SOURCE_METHODS and len(chain) >= 2:
            return frozenset({WIRE})
        if name in _NAMED_SOURCES:
            return frozenset({WIRE})
        if name in _UNPACKERS:
            return all_args

        # Project callees: substitute the summary.
        callee = self.pt.graph.resolve_node(call)
        if callee is not None:
            summary = self.pt.summaries.get(callee)
            if summary is not None:
                if all_args:
                    self._note_tainted_call(call, callee, arg_origins,
                                            kw_origins)
                return self._substitute(summary.return_origins, call,
                                        arg_origins, kw_origins)
        # Unknown call: taint flows through (str/int casts, arithmetic
        # helpers); a clean result requires a recognized sanitizer.
        return all_args

    def _note_tainted_call(self, call: ast.Call, callee: str,
                           arg_origins, kw_origins) -> None:
        info = self.pt.graph.function(callee)
        if info is None:
            return
        by_param = _map_args_to_params(info.node, call, arg_origins,
                                       kw_origins)
        if by_param:
            self.result.tainted_calls[id(call)] = (callee, call.lineno,
                                                   by_param)

    def _substitute(self, origins: frozenset, call: ast.Call,
                    arg_origins, kw_origins) -> frozenset:
        out = set()
        callee = self.pt.graph.resolve_node(call)
        info = self.pt.graph.function(callee) if callee else None
        by_param = (_map_args_to_params(info.node, call, arg_origins,
                                        kw_origins)
                    if info is not None else {})
        for origin in origins:
            if origin == WIRE:
                out.add(WIRE)
            elif origin.startswith("param:"):
                out |= by_param.get(origin[len("param:"):], frozenset())
        return frozenset(out)

    def _call_sinks(self, call: ast.Call, chain: list, name: str,
                    arg_origins, kw_origins, env: _Env) -> None:
        def arg(i: int) -> frozenset:
            return arg_origins[i] if i < len(arg_origins) else frozenset()

        if name in ("bytes", "bytearray") and arg_origins:
            if arg(0):
                self._sink("alloc", call.lineno, f"{name}() size", arg(0))
        elif name in ("zeros", "empty", "ones", "full") and arg_origins:
            if arg(0):
                self._sink("alloc", call.lineno,
                           f"np.{name}() shape", arg(0))
        elif name == "frombuffer":
            count = kw_origins.get("count", frozenset())
            if count:
                self._sink("alloc", call.lineno, "np.frombuffer() count",
                           count)
        elif name in ("read_exact", "recv_exact"):
            if arg(1):
                self._sink("alloc", call.lineno,
                           f"{name}() length", arg(1))
        elif name == "readexactly":
            if arg(0):
                self._sink("alloc", call.lineno, "readexactly() length",
                           arg(0))
        elif name == "range":
            tainted = frozenset().union(*arg_origins) if arg_origins \
                else frozenset()
            if tainted:
                self._sink("loop", call.lineno, "range() bound", tainted)
        elif name in ("Struct", "calcsize", "pack", "pack_into") \
                or name in _UNPACKERS:
            fmt_origins = arg(0)
            if name == "pack_into":
                fmt_origins = frozenset()  # fmt precompiled on receiver
            if chain[:1] == ["struct"] or name in ("Struct", "calcsize"):
                if fmt_origins:
                    self._sink("struct", call.lineno,
                               f"struct {name}() format", fmt_origins)

    def _check_index_sink(self, sub: ast.Subscript, env: _Env) -> None:
        origins = self._expr(sub.slice, env)
        if origins:
            detail = ("slice bound" if isinstance(sub.slice, ast.Slice)
                      else "container index")
            self._sink("index", sub.lineno, detail, origins)

    def _check_loop_sink(self, iter_expr: ast.expr, env: _Env) -> None:
        # ``for _ in range(n)`` is caught by the range() call sink while
        # evaluating the iterable; nothing extra here.
        return None

    def _sink(self, kind: str, line: int, detail: str,
              origins: frozenset) -> None:
        self.result.sinks.append(Sink(kind, line, detail,
                                      frozenset(origins)))


# -- helpers ---------------------------------------------------------------

def _dotted(expr: ast.expr) -> Optional[str]:
    chain = attr_chain(expr)
    return ".".join(chain) if chain else None


def _compared_names(test: ast.expr) -> set[str]:
    """Dotted names that appear inside comparison operations in a guard
    test — the 'range/clamp comparison' sanitizer shape.  ``if flag:``
    sanitizes nothing; ``if n == 0 or n > MAX:`` sanitizes ``n``."""
    names: set[str] = set()
    for node in cached_walk(test):
        if isinstance(node, ast.Compare):
            for side in [node.left] + list(node.comparators):
                name = _dotted(side)
                if name is not None:
                    names.add(name)
    return names


def _escapes(body: list) -> bool:
    """True when the branch unconditionally leaves the enclosing flow."""
    if not body:
        return False
    last = body[-1]
    if isinstance(last, (ast.Raise, ast.Return, ast.Break, ast.Continue)):
        return True
    if isinstance(last, ast.If):
        return _escapes(last.body) and _escapes(last.orelse)
    return False


def _map_args_to_params(fn_node, call: ast.Call, arg_origins,
                        kw_origins) -> dict[str, frozenset]:
    """Param name -> origins of the argument the call passes it."""
    args = fn_node.args
    params = [a.arg for a in (args.posonlyargs + args.args)]
    if params and params[0] in ("self", "cls"):
        params = params[1:]
    out: dict[str, frozenset] = {}
    for i, origins in enumerate(arg_origins):
        if origins and i < len(params):
            out[params[i]] = out.get(params[i], frozenset()) | origins
    kwonly = {a.arg for a in args.kwonlyargs}
    for name, origins in kw_origins.items():
        if origins and name is not None \
                and (name in params or name in kwonly):
            out[name] = out.get(name, frozenset()) | origins
    return out


# -- project-level analysis ------------------------------------------------

class ProjectTaint:
    """Per-function taint results + interprocedural summaries."""

    def __init__(self, project: Project) -> None:
        self.project = project
        self.graph = callgraph.graph_for(project)
        self.summaries: dict[str, TaintSummary] = {
            qual: TaintSummary() for qual in self.graph.functions}
        self.results: dict[str, _FnResult] = {}
        self._fixpoint()

    def _fixpoint(self, max_rounds: int = 4) -> None:
        for _ in range(max_rounds):
            changed = False
            for qual, info in self.graph.functions.items():
                result = _FunctionTaint(self, qual, info).run()
                self.results[qual] = result
                summary = self._summarize(info, result)
                old = self.summaries[qual]
                if (summary.return_origins != old.return_origins
                        or summary.param_sinks != old.param_sinks):
                    self.summaries[qual] = summary
                    changed = True
            if not changed:
                return

    def _summarize(self, info: callgraph.FunctionInfo,
                   result: _FnResult) -> TaintSummary:
        param_sinks = []
        for sink in result.sinks:
            for origin in sorted(sink.origins):
                if origin.startswith("param:"):
                    param_sinks.append((origin[len("param:"):], sink.kind,
                                        sink.detail, info.relpath,
                                        sink.line))
        # Inherit the callees' param sinks through pass-through calls so
        # a two-hop helper chain still reaches the caller.
        for callee, line, by_param in result.tainted_calls.values():
            callee_summary = self.summaries.get(callee)
            if callee_summary is None:
                continue
            for (p, kind, detail, relpath, sline) in \
                    callee_summary.param_sinks:
                for origin in sorted(by_param.get(p, frozenset())):
                    if origin.startswith("param:"):
                        param_sinks.append((origin[len("param:"):], kind,
                                            detail, relpath, sline))
        return TaintSummary(result.return_origins,
                            tuple(sorted(set(param_sinks))))

    # -- rule-facing queries ----------------------------------------------

    def wire_sinks(self, qualname: str) -> Iterator[Sink]:
        """Sinks in ``qualname`` reached by wire-tainted data."""
        result = self.results.get(qualname)
        if result is None:
            return
        for sink in result.sinks:
            if WIRE in sink.origins:
                yield sink

    def wire_call_sinks(self, qualname: str
                        ) -> Iterator[tuple[int, str, str, str, str, int]]:
        """(line, callee, kind, detail, sink relpath, sink line) for calls
        in ``qualname`` that pass wire-tainted data to a parameter the
        callee's summary says reaches a sink unsanitized."""
        result = self.results.get(qualname)
        if result is None:
            return
        for callee, line, by_param in result.tainted_calls.values():
            summary = self.summaries.get(callee)
            if summary is None:
                continue
            for (p, kind, detail, relpath, sline) in summary.param_sinks:
                if WIRE in by_param.get(p, frozenset()):
                    yield line, callee, kind, detail, relpath, sline


def analyze(project: Project) -> ProjectTaint:
    """Build (or reuse) the project's taint analysis; cached alongside
    the call graph so the rule families share one pass."""
    cached = getattr(project, "_taint", None)
    if isinstance(cached, ProjectTaint) and cached.project is project:
        return cached
    taint = ProjectTaint(project)
    project._taint = taint
    return taint
