"""Checker engine for ``dmtpu check``: files, findings, suppressions, baseline.

The analysis package is a project-native static analyzer over the farm's
own invariants (lock discipline, async hygiene, wire-format parity, JAX
tracing purity) — the conventions the reference system enforced by hand
and paid for when a copy drifted (``DataChunk.cs:14-15`` duplicated into
worker and viewer).  Everything here is stdlib ``ast``: the engine MUST
run without importing jax (or the package under analysis) so the tier-1
gate test stays a sub-second subprocess.

Pieces:

- :class:`Rule` / :class:`Finding` — rule catalogue entries and located
  diagnostics; a finding's :meth:`~Finding.fingerprint` omits the line
  number so baselines survive unrelated edits above a finding.
- :class:`SourceFile` / :class:`Project` — parsed sources keyed by
  repo-relative posix path.  ``Project.from_root`` scans the installed
  package; ``Project.from_sources`` builds fixture projects for tests.
- inline suppressions — ``# dmtpu: ignore[rule-id]`` (comma-separated
  ids, ``*`` for all) on the finding's line or the line above.
- baseline — a committed JSON list of fingerprints for grandfathered
  findings (``tools/lint_baseline.json``); entries matching nothing are
  reported stale so the file can only shrink.
- reporters — one-line-per-finding text, and a versioned JSON document
  for tooling.
"""

from __future__ import annotations

import ast
import json
import re
from dataclasses import dataclass
from pathlib import Path
from typing import Iterable, Iterator, Mapping, Optional, Sequence

PACKAGE = "distributedmandelbrot_tpu"

SUPPRESS_RE = re.compile(r"#\s*dmtpu:\s*ignore\[([A-Za-z0-9_\-*, ]+)\]")

SEVERITIES = ("error", "warning")


@dataclass(frozen=True)
class Rule:
    """One checkable invariant: stable id, family, severity, one-line doc."""

    id: str
    family: str
    severity: str
    doc: str


@dataclass(frozen=True)
class Finding:
    rule: str
    severity: str
    path: str  # repo-relative posix path
    line: int
    message: str

    def fingerprint(self) -> str:
        """Line-independent identity for baseline matching."""
        return f"{self.rule}::{self.path}::{self.message}"

    def format(self) -> str:
        return (f"{self.path}:{self.line}: {self.severity}: "
                f"[{self.rule}] {self.message}")


# The engine's own diagnostic for files it cannot parse — reported as a
# finding (not raised) so one broken file doesn't hide the rest.
PARSE_ERROR = Rule("parse-error", "engine", "error",
                   "file does not parse as Python")


class SourceFile:
    """One parsed source: text, AST, and per-line suppression comments."""

    def __init__(self, relpath: str, text: str) -> None:
        self.relpath = relpath
        self.text = text
        self.lines = text.splitlines()
        self.tree = ast.parse(text, filename=relpath)  # may raise SyntaxError
        self.suppressions: dict[int, set[str]] = {}
        for lineno, line in enumerate(self.lines, 1):
            m = SUPPRESS_RE.search(line)
            if m:
                self.suppressions[lineno] = {
                    r.strip() for r in m.group(1).split(",") if r.strip()}

    def is_suppressed(self, line: int, rule: str) -> bool:
        """A ``# dmtpu: ignore[...]`` on the finding's line or the line
        directly above covers it (the line above carries the one-line
        justification when the flagged line is already full)."""
        for ln in (line, line - 1):
            ids = self.suppressions.get(ln)
            if ids and (rule in ids or "*" in ids):
                return True
        return False


class Project:
    """The file set one check run sees, keyed by repo-relative path."""

    def __init__(self, files: Mapping[str, SourceFile],
                 parse_failures: Optional[Mapping[str, str]] = None) -> None:
        self.files = dict(files)
        self.parse_failures = dict(parse_failures or {})

    @classmethod
    def from_sources(cls, sources: Mapping[str, str]) -> "Project":
        """Fixture constructor (tests): ``{relpath: source_text}``."""
        files: dict[str, SourceFile] = {}
        failures: dict[str, str] = {}
        for rel, text in sources.items():
            try:
                files[rel] = SourceFile(rel, text)
            except SyntaxError as e:
                failures[rel] = f"line {e.lineno}: {e.msg}"
        return cls(files, failures)

    @classmethod
    def from_root(cls, root: Path | str) -> "Project":
        """Every ``*.py`` under ``root/distributedmandelbrot_tpu/``."""
        root = Path(root)
        files: dict[str, SourceFile] = {}
        failures: dict[str, str] = {}
        pkg = root / PACKAGE
        for path in sorted(pkg.rglob("*.py")):
            rel = path.relative_to(root).as_posix()
            try:
                text = path.read_text(encoding="utf-8")
            except (OSError, UnicodeDecodeError) as e:
                failures[rel] = str(e)
                continue
            try:
                files[rel] = SourceFile(rel, text)
            except SyntaxError as e:
                failures[rel] = f"line {e.lineno}: {e.msg}"
        return cls(files, failures)

    def in_dirs(self, *subdirs: str) -> Iterator[SourceFile]:
        """Files under ``PACKAGE/<subdir>/`` for any named subdir."""
        prefixes = tuple(f"{PACKAGE}/{d.strip('/')}/" for d in subdirs)
        for rel in sorted(self.files):
            if rel.startswith(prefixes):
                yield self.files[rel]

    def file(self, relpath: str) -> Optional[SourceFile]:
        return self.files.get(relpath)


def default_root() -> Path:
    """The directory containing the installed package (the repo root when
    running from a checkout)."""
    return Path(__file__).resolve().parent.parent.parent


def project_at_ref(root: Path | str, ref: str) -> Project:
    """The package's file set as of a git ref, via ``git archive`` (one
    subprocess, parsed in memory — the worktree is never touched).  The
    whole tree is materialized, not just changed files, because the
    cross-file rules (wire parity, proto frames, the call graph) need
    the full old project to compute the old fingerprints faithfully."""
    import io
    import subprocess
    import tarfile
    try:
        proc = subprocess.run(["git", "archive", ref, "--", PACKAGE],
                              cwd=str(root), capture_output=True)
    except OSError as e:
        raise ValueError(f"cannot run git: {e}")
    if proc.returncode != 0:
        err = proc.stderr.decode("utf-8", "replace").strip()
        raise ValueError(f"git archive {ref} failed: {err}")
    sources: dict[str, str] = {}
    with tarfile.open(fileobj=io.BytesIO(proc.stdout)) as tf:
        for member in tf.getmembers():
            if member.isfile() and member.name.endswith(".py"):
                fobj = tf.extractfile(member)
                if fobj is not None:
                    sources[member.name] = fobj.read().decode(
                        "utf-8", "replace")
    return Project.from_sources(sources)


def fingerprints_at_ref(root: Path | str, ref: str,
                        rule_ids: Optional[Sequence[str]] = None
                        ) -> set[str]:
    """Fingerprints of every finding the given rules produce on the tree
    as of ``ref`` — ``--diff`` treats these as an ephemeral baseline so
    a check run only reports findings introduced since the ref."""
    return {f.fingerprint()
            for f in check_project(project_at_ref(root, ref), rule_ids)}


# -- rule registry ---------------------------------------------------------

def _rule_modules():
    # Imported lazily: rule modules import this module for Rule/Finding.
    from distributedmandelbrot_tpu.analysis import (rules_async, rules_exc,
                                                    rules_fsm, rules_jax,
                                                    rules_locks, rules_obs,
                                                    rules_proto, rules_res,
                                                    rules_taint, rules_wire)
    return (rules_locks, rules_async, rules_wire, rules_jax, rules_proto,
            rules_res, rules_obs, rules_taint, rules_exc, rules_fsm)


def all_rules() -> dict[str, Rule]:
    rules = {PARSE_ERROR.id: PARSE_ERROR}
    for mod in _rule_modules():
        for rule in mod.RULES:
            rules[rule.id] = rule
    return rules


def expand_rule_ids(rule_ids: Sequence[str]) -> list[str]:
    """Resolve a mix of rule ids and family names (``--rules proto res``)
    to concrete rule ids; raises ValueError on anything unknown."""
    known = all_rules()
    by_family: dict[str, list[str]] = {}
    for rule in known.values():
        by_family.setdefault(rule.family, []).append(rule.id)
    expanded: list[str] = []
    unknown: list[str] = []
    for rid in rule_ids:
        if rid in known:
            expanded.append(rid)
        elif rid in by_family:
            expanded.extend(by_family[rid])
        else:
            unknown.append(rid)
    if unknown:
        raise ValueError(
            f"unknown rule ids: {', '.join(sorted(set(unknown)))} "
            f"(known ids: {', '.join(sorted(known))}; "
            f"families: {', '.join(sorted(by_family))})")
    return expanded


def check_project(project: Project,
                  rule_ids: Optional[Sequence[str]] = None,
                  timings: Optional[dict] = None) -> list[Finding]:
    """Run every rule family; returns ALL findings (suppression and
    baseline filtering is :func:`run_check`'s job).  ``rule_ids`` may mix
    rule ids and family names.  When ``timings`` is given, per-family
    wall seconds are recorded into it keyed by module basename (the
    ``--profile`` feed: as families grow, the tier-1 gate's time budget
    stays attributable to the family that spent it)."""
    import time
    findings = [Finding(PARSE_ERROR.id, PARSE_ERROR.severity, rel, 1, msg)
                for rel, msg in sorted(project.parse_failures.items())]
    wanted = set(expand_rule_ids(rule_ids)) if rule_ids else None
    for mod in _rule_modules():
        t0 = time.perf_counter()
        findings.extend(mod.check(project))
        if timings is not None:
            name = mod.__name__.rsplit(".", 1)[-1]
            timings[name] = timings.get(name, 0.0) \
                + (time.perf_counter() - t0)
    if wanted is not None:
        findings = [f for f in findings if f.rule in wanted]
    findings.sort(key=lambda f: (f.path, f.line, f.rule, f.message))
    return findings


# -- run + filtering -------------------------------------------------------

@dataclass
class Report:
    """One check run, split by disposition."""

    findings: list[Finding]   # actionable: neither suppressed nor baselined
    suppressed: list[Finding]
    baselined: list[Finding]
    stale_baseline: list[str]  # baseline entries matching nothing anymore

    @property
    def clean(self) -> bool:
        return not self.findings


def run_check(project: Project,
              rule_ids: Optional[Sequence[str]] = None,
              baseline: Optional[Iterable[str]] = None,
              timings: Optional[dict] = None) -> Report:
    all_findings = check_project(project, rule_ids, timings=timings)
    base = set(baseline or ())
    actionable: list[Finding] = []
    suppressed: list[Finding] = []
    baselined: list[Finding] = []
    seen_fingerprints: set[str] = set()
    for f in all_findings:
        seen_fingerprints.add(f.fingerprint())
        sf = project.file(f.path)
        if sf is not None and sf.is_suppressed(f.line, f.rule):
            suppressed.append(f)
        elif f.fingerprint() in base:
            baselined.append(f)
        else:
            actionable.append(f)
    stale = sorted(base - seen_fingerprints)
    return Report(actionable, suppressed, baselined, stale)


# -- baseline IO -----------------------------------------------------------

BASELINE_VERSION = 1


def load_baseline(path: Path | str) -> set[str]:
    with open(path, encoding="utf-8") as f:
        doc = json.load(f)
    if not isinstance(doc, dict) or doc.get("version") != BASELINE_VERSION:
        raise ValueError(f"{path}: not a v{BASELINE_VERSION} baseline file")
    return set(doc.get("findings", []))


def save_baseline(path: Path | str, findings: Iterable[Finding]) -> None:
    doc = {"version": BASELINE_VERSION,
           "findings": sorted({f.fingerprint() for f in findings})}
    with open(path, "w", encoding="utf-8") as f:
        json.dump(doc, f, indent=1, sort_keys=True)
        f.write("\n")


# -- reporters -------------------------------------------------------------

def render_text(report: Report) -> str:
    lines = [f.format() for f in report.findings]
    errors = sum(1 for f in report.findings if f.severity == "error")
    warnings = len(report.findings) - errors
    summary = (f"dmtpu check: {errors} error(s), {warnings} warning(s)"
               f" ({len(report.suppressed)} suppressed,"
               f" {len(report.baselined)} baselined)")
    if report.stale_baseline:
        summary += f", {len(report.stale_baseline)} stale baseline entr(y/ies)"
        lines.extend(f"stale baseline entry: {fp}"
                     for fp in report.stale_baseline)
    lines.append(summary)
    return "\n".join(lines)


JSON_VERSION = 1


def render_json(report: Report) -> str:
    """Versioned machine-readable report.  Schema (v1)::

        {"version": 1,
         "counts": {"error": N, "warning": N, "total": N,
                    "suppressed": N, "baselined": N},
         "findings": [{"rule", "severity", "path", "line", "message"}],
         "stale_baseline": [fingerprint, ...]}
    """
    errors = sum(1 for f in report.findings if f.severity == "error")
    doc = {
        "version": JSON_VERSION,
        "counts": {
            "error": errors,
            "warning": len(report.findings) - errors,
            "total": len(report.findings),
            "suppressed": len(report.suppressed),
            "baselined": len(report.baselined),
        },
        "findings": [
            {"rule": f.rule, "severity": f.severity, "path": f.path,
             "line": f.line, "message": f.message}
            for f in report.findings],
        "stale_baseline": list(report.stale_baseline),
    }
    return json.dumps(doc, indent=1, sort_keys=True)
