"""``taint-*`` rules: untrusted wire input reaching a dangerous sink.

Every integer a peer sends is a suggestion until a sanitizer proves it
in-range.  The dataflow layer (``analysis/dataflow.py``) tracks values
from the network reads — ``reader.readexactly`` / ``sock.recv`` / the
``framing`` helpers / struct-unpacks of wire bytes — to the places a
hostile value does damage:

- ``taint-alloc``: a tainted value sizes an allocation — ``bytes(n)`` /
  ``bytearray(n)``, an exact-length read's byte count, a numpy shape or
  ``frombuffer`` count.  A 4 GiB ``count`` field should cost the peer a
  dropped connection, not the coordinator its heap.
- ``taint-index``: a tainted value indexes or slices a container.  The
  scheduler's dicts and the store's level arrays are keyed by validated
  geometry; raw wire integers must pass ``validate_indices`` /
  ``net.protocol`` bounds first.
- ``taint-loop``: a tainted value bounds a loop (``range(n)`` or a
  ``while`` condition) — the unbounded-iteration flavor of the same
  attack.
- ``taint-struct``: a tainted value reaches a ``struct`` format string
  (repeat counts compile attacker-chosen buffer sizes).

Sanitizers: ``net.protocol.validate_*`` (the sanctioned decode path),
``core.geometry.validate_indices``, any ``*_in_range`` predicate, a
range/clamp comparison guard, and ``min()`` against a clean bound.
Alloc/loop/struct sinks are also checked interprocedurally: passing a
tainted value to a helper whose parameter reaches such a sink
unsanitized fires at the call site, naming the flow.  Index sinks stay
intra-procedural — helpers like the scheduler guard keys dynamically,
and the boundary surfaces must sanitize before handing values inward
anyway.
"""

from __future__ import annotations

from distributedmandelbrot_tpu.analysis import dataflow
from distributedmandelbrot_tpu.analysis.engine import (Finding, Project,
                                                       Rule)

RULES = (
    Rule("taint-alloc", "taint", "error",
         "wire-tainted value sizes an allocation without a sanitizer"),
    Rule("taint-index", "taint", "error",
         "wire-tainted value indexes/slices a container without a "
         "sanitizer"),
    Rule("taint-loop", "taint", "error",
         "wire-tainted value bounds a loop without a sanitizer"),
    Rule("taint-struct", "taint", "error",
         "wire-tainted value reaches a struct format string"),
)

# Network surfaces only: these dirs speak to anonymous peers.  storage/,
# obs/, ops/ see data the coordinator already validated.
SCOPE_DIRS = ("net", "coordinator", "serve", "worker", "viewer")

_RULE_BY_KIND = {
    "alloc": RULES[0],
    "index": RULES[1],
    "loop": RULES[2],
    "struct": RULES[3],
}

# Interprocedural param-sink findings are limited to the resource-shaped
# sinks; see the module docstring for why index stays local.
_CALL_SINK_KINDS = frozenset({"alloc", "loop", "struct"})


def _in_scope(relpath: str) -> bool:
    # relpath carries the package prefix: "distributedmandelbrot_tpu/net/…"
    parts = relpath.split("/")
    return len(parts) >= 2 and parts[-2] in SCOPE_DIRS


def check(project: Project) -> list[Finding]:
    taint = dataflow.analyze(project)
    findings: list[Finding] = []
    for qual, info in taint.graph.functions.items():
        if not _in_scope(info.relpath):
            continue
        for sink in taint.wire_sinks(qual):
            rule = _RULE_BY_KIND[sink.kind]
            findings.append(Finding(
                rule.id, rule.severity, info.relpath, sink.line,
                f"wire-tainted value reaches {sink.detail} in "
                f"{info.name}() without a validate_* sanitizer"))
        seen_lines = {(s.kind, s.line) for s in taint.wire_sinks(qual)}
        for (line, callee, kind, detail, sink_rel, sink_line) \
                in taint.wire_call_sinks(qual):
            if kind not in _CALL_SINK_KINDS:
                continue
            if (kind, line) in seen_lines:
                continue  # already reported as a direct sink on this line
            rule = _RULE_BY_KIND[kind]
            callee_name = callee.rsplit("::", 1)[-1]
            findings.append(Finding(
                rule.id, rule.severity, info.relpath, line,
                f"wire-tainted value passed to {callee_name}() reaches "
                f"{detail} ({sink_rel}:{sink_line}) without a validate_* "
                f"sanitizer"))
    findings.sort(key=lambda f: (f.path, f.line, f.rule))
    return findings
