"""``obs-*`` rules: instrumentation names and the registry stay in sync.

``obs-name`` checks the forward direction (every instrumentation
literal is registered); ``obs-dead`` checks the reverse (every
registration is instrumented or at least referenced) — the registry
must describe the fleet's actual telemetry, not its aspirations.

The ``results_accepted`` collision (PR 2) happened because two call
sites spelled the same metric differently and nothing arbitrated.
``obs/names.py`` is the arbiter; this rule is its enforcement — every
string literal passed to an instrumentation method
(``counters.inc("...")``, ``registry.observe("...")``,
``spans.record("...", ...)``) must be a constant registered there or a
legacy alias spelling.

This used to live in ``tools/check_metrics.py --names`` (a side tool
the gate had to remember to run); folding it into ``dmtpu check``
makes name drift a tier-1 failure.  The tool still delegates here so
its ``--names`` flag keeps working.

Like every rule in this package, the known-name set is extracted from
the AST of ``obs/names.py`` — the module is never imported.  Projects
without a names module (rule fixtures) produce no findings.
"""

from __future__ import annotations

import ast
from typing import Iterator, Optional

from distributedmandelbrot_tpu.analysis.astutil import (attr_chain,
                                                        cached_walk)
from distributedmandelbrot_tpu.analysis.engine import (Finding, Project,
                                                       Rule, SourceFile)

RULES = (
    Rule("obs-name", "obs", "error",
         "metric/span name literals at instrumentation sites must be "
         "registered in obs/names.py"),
    Rule("obs-dead", "obs", "warning",
         "names registered in obs/names.py must be instrumented (or "
         "referenced) somewhere — unused registrations are drift"),
    Rule("obs-event", "obs", "error",
         "flight-recorder event literals must be registered in "
         "obs/events.py, and every registered event must be emitted "
         "(or referenced) somewhere"),
)

NAMES_SUFFIX = "obs/names.py"
EVENTS_SUFFIX = "obs/events.py"

# The flight recorder's emit surface: ``flight.note("...")`` (module
# call) — the same receiver-hint gating the metric tables use, so a
# dict's or notebook's unrelated ``.note`` never trips the scan.
EVENT_METHODS = {"note": ("flight",)}

# Method -> receiver spellings that identify the instrumented object
# (gating hints keep dict.get("key") from tripping the scan — same
# tables check_metrics --names used).
_METRIC_RECEIVERS = ("counter", "registry", "reg")
INSTRUMENT_METHODS = {
    "inc": _METRIC_RECEIVERS, "get": _METRIC_RECEIVERS,
    "observe": _METRIC_RECEIVERS, "set_gauge": _METRIC_RECEIVERS,
    "timed": _METRIC_RECEIVERS, "counter": _METRIC_RECEIVERS,
    "gauge": _METRIC_RECEIVERS, "histogram": _METRIC_RECEIVERS,
    "record": ("span",),
}


def _names_file(project: Project) -> Optional[str]:
    for rel in sorted(project.files):
        if rel.endswith(NAMES_SUFFIX):
            return rel
    return None


def known_names(project: Project) -> Optional[set[str]]:
    """Registered names from the names module's AST: every uppercase
    top-level string constant plus the LEGACY_ALIASES dict's legacy
    spellings.  None when the project has no names module."""
    rel = _names_file(project)
    if rel is None:
        return None
    known: set[str] = set()
    for node in project.files[rel].tree.body:
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name):
            target, value = node.targets[0].id, node.value
        elif isinstance(node, ast.AnnAssign) \
                and isinstance(node.target, ast.Name) \
                and node.value is not None:
            target, value = node.target.id, node.value
        else:
            continue
        if target.isupper() and isinstance(value, ast.Constant) \
                and isinstance(value.value, str):
            known.add(value.value)
        elif target == "LEGACY_ALIASES" and isinstance(value, ast.Dict):
            for v in value.values:
                if isinstance(v, ast.Constant) and isinstance(v.value, str):
                    known.add(v.value)
    return known


def iter_sites(project: Project) -> Iterator[tuple[SourceFile, int, str]]:
    """(file, line, literal) for every instrumentation site whose first
    argument is a string literal."""
    for rel in sorted(project.files):
        sf = project.files[rel]
        for node in cached_walk(sf.tree):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr in INSTRUMENT_METHODS):
                continue
            recv_chain = attr_chain(node.func.value)
            if not recv_chain:
                continue
            recv = recv_chain[-1].lower()
            if not any(h in recv for h in INSTRUMENT_METHODS[node.func.attr]):
                continue
            if not (node.args and isinstance(node.args[0], ast.Constant)
                    and isinstance(node.args[0].value, str)):
                continue
            yield sf, node.args[0].lineno, node.args[0].value


def registered_consts(project: Project
                      ) -> Optional[dict[str, tuple[str, int]]]:
    """Constant target -> (wire name, definition line) for every
    uppercase top-level string constant in the names module (legacy
    alias spellings are read-side compatibility, not registrations,
    so LEGACY_ALIASES is excluded here)."""
    rel = _names_file(project)
    if rel is None:
        return None
    out: dict[str, tuple[str, int]] = {}
    for node in project.files[rel].tree.body:
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name):
            target, value = node.targets[0].id, node.value
        elif isinstance(node, ast.AnnAssign) \
                and isinstance(node.target, ast.Name) \
                and node.value is not None:
            target, value = node.target.id, node.value
        else:
            continue
        if target.isupper() and isinstance(value, ast.Constant) \
                and isinstance(value.value, str):
            out[target] = (value.value, node.lineno)
    return out


def _dead_findings(project: Project) -> list[Finding]:
    """obs-dead: a registered constant nobody instruments.  'Used'
    means an ``<...names>.CONST`` attribute reference or a
    ``from ...obs.names import CONST`` anywhere outside the names
    module, or the wire spelling appearing as an instrumentation-site
    literal — anything else is a name the registry promises but no
    layer ever emits."""
    consts = registered_consts(project)
    if not consts:
        return []
    names_rel = _names_file(project)
    used: set[str] = set()
    for rel in sorted(project.files):
        if rel == names_rel:
            continue
        for node in cached_walk(project.files[rel].tree):
            if isinstance(node, ast.Attribute) and node.attr.isupper():
                chain = attr_chain(node)
                if chain and len(chain) >= 2 \
                        and "names" in chain[-2].lower():
                    used.add(node.attr)
            elif isinstance(node, ast.ImportFrom) and node.module \
                    and node.module.endswith("obs.names"):
                used.update(alias.name for alias in node.names)
    lit_used = {name for _, _, name in iter_sites(project)}
    rule = RULES[1]
    sf = project.files[names_rel]
    return [
        Finding(rule.id, rule.severity, sf.relpath, line,
                f"registered name {target} ({wire!r}) is never "
                f"instrumented or referenced outside obs/names.py")
        for target, (wire, line) in sorted(consts.items())
        if target not in used and wire not in lit_used]


def _events_file(project: Project) -> Optional[str]:
    for rel in sorted(project.files):
        if rel.endswith(EVENTS_SUFFIX):
            return rel
    return None


def known_events(project: Project) -> Optional[dict[str, tuple[str, int]]]:
    """Constant target -> (event name, definition line) from the events
    module's AST (uppercase top-level string constants, same extraction
    as the names module).  None when the project has no events module."""
    rel = _events_file(project)
    if rel is None:
        return None
    out: dict[str, tuple[str, int]] = {}
    for node in project.files[rel].tree.body:
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name):
            target, value = node.targets[0].id, node.value
        elif isinstance(node, ast.AnnAssign) \
                and isinstance(node.target, ast.Name) \
                and node.value is not None:
            target, value = node.target.id, node.value
        else:
            continue
        if target.isupper() and isinstance(value, ast.Constant) \
                and isinstance(value.value, str):
            out[target] = (value.value, node.lineno)
    return out


def iter_event_sites(project: Project
                     ) -> Iterator[tuple[SourceFile, int, str]]:
    """(file, line, literal) for every ``flight.note("...")`` whose
    first argument is a string literal."""
    for rel in sorted(project.files):
        sf = project.files[rel]
        for node in cached_walk(sf.tree):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr in EVENT_METHODS):
                continue
            recv_chain = attr_chain(node.func.value)
            if not recv_chain:
                continue
            recv = recv_chain[-1].lower()
            if not any(h in recv for h in EVENT_METHODS[node.func.attr]):
                continue
            if not (node.args and isinstance(node.args[0], ast.Constant)
                    and isinstance(node.args[0].value, str)):
                continue
            yield sf, node.args[0].lineno, node.args[0].value


def _event_findings(project: Project) -> list[Finding]:
    """obs-event, both directions: a ``flight.note`` literal outside
    the registry, and a registered event no layer ever emits.  'Used'
    means an ``<...events>.CONST`` attribute reference or a
    ``from ...obs.events import CONST`` outside the events module, or
    the event spelling appearing as a note-site literal — same
    semantics as obs-dead, because an event postmortem can never see
    is exactly as much drift as a metric nobody increments."""
    consts = known_events(project)
    if consts is None:
        return []
    rule = RULES[2]
    events_rel = _events_file(project)
    registered = {wire for wire, _ in consts.values()}
    out = [
        Finding(rule.id, rule.severity, sf.relpath, line,
                f"flight event {name!r} is not registered in "
                f"obs/events.py")
        for sf, line, name in iter_event_sites(project)
        if name not in registered]
    used: set[str] = set()
    for rel in sorted(project.files):
        if rel == events_rel:
            continue
        for node in cached_walk(project.files[rel].tree):
            if isinstance(node, ast.Attribute) and node.attr.isupper():
                chain = attr_chain(node)
                if chain and len(chain) >= 2 \
                        and "events" in chain[-2].lower():
                    used.add(node.attr)
            elif isinstance(node, ast.ImportFrom) and node.module \
                    and node.module.endswith("obs.events"):
                used.update(alias.name for alias in node.names)
    lit_used = {name for _, _, name in iter_event_sites(project)}
    sf = project.files[events_rel]
    out.extend(
        Finding(rule.id, rule.severity, sf.relpath, line,
                f"registered event {target} ({wire!r}) is never emitted "
                f"or referenced outside obs/events.py")
        for target, (wire, line) in sorted(consts.items())
        if target not in used and wire not in lit_used)
    return out


def check(project: Project) -> list[Finding]:
    out: list[Finding] = []
    known = known_names(project)
    if known is not None:
        rule = RULES[0]
        out.extend(
            Finding(rule.id, rule.severity, sf.relpath, line,
                    f"metric name {name!r} is not registered in "
                    f"obs/names.py")
            for sf, line, name in iter_sites(project)
            if name not in known)
        out.extend(_dead_findings(project))
    out.extend(_event_findings(project))
    return out
