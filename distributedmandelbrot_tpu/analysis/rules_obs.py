"""``obs-name`` rule: instrumentation literals must be registered names.

The ``results_accepted`` collision (PR 2) happened because two call
sites spelled the same metric differently and nothing arbitrated.
``obs/names.py`` is the arbiter; this rule is its enforcement — every
string literal passed to an instrumentation method
(``counters.inc("...")``, ``registry.observe("...")``,
``spans.record("...", ...)``) must be a constant registered there or a
legacy alias spelling.

This used to live in ``tools/check_metrics.py --names`` (a side tool
the gate had to remember to run); folding it into ``dmtpu check``
makes name drift a tier-1 failure.  The tool still delegates here so
its ``--names`` flag keeps working.

Like every rule in this package, the known-name set is extracted from
the AST of ``obs/names.py`` — the module is never imported.  Projects
without a names module (rule fixtures) produce no findings.
"""

from __future__ import annotations

import ast
from typing import Iterator, Optional

from distributedmandelbrot_tpu.analysis.astutil import attr_chain
from distributedmandelbrot_tpu.analysis.engine import (Finding, Project,
                                                       Rule, SourceFile)

RULES = (
    Rule("obs-name", "obs", "error",
         "metric/span name literals at instrumentation sites must be "
         "registered in obs/names.py"),
)

NAMES_SUFFIX = "obs/names.py"

# Method -> receiver spellings that identify the instrumented object
# (gating hints keep dict.get("key") from tripping the scan — same
# tables check_metrics --names used).
_METRIC_RECEIVERS = ("counter", "registry", "reg")
INSTRUMENT_METHODS = {
    "inc": _METRIC_RECEIVERS, "get": _METRIC_RECEIVERS,
    "observe": _METRIC_RECEIVERS, "set_gauge": _METRIC_RECEIVERS,
    "timed": _METRIC_RECEIVERS, "counter": _METRIC_RECEIVERS,
    "gauge": _METRIC_RECEIVERS, "histogram": _METRIC_RECEIVERS,
    "record": ("span",),
}


def known_names(project: Project) -> Optional[set[str]]:
    """Registered names from the names module's AST: every uppercase
    top-level string constant plus the LEGACY_ALIASES dict's legacy
    spellings.  None when the project has no names module."""
    for rel in sorted(project.files):
        if rel.endswith(NAMES_SUFFIX):
            break
    else:
        return None
    known: set[str] = set()
    for node in project.files[rel].tree.body:
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name):
            target, value = node.targets[0].id, node.value
        elif isinstance(node, ast.AnnAssign) \
                and isinstance(node.target, ast.Name) \
                and node.value is not None:
            target, value = node.target.id, node.value
        else:
            continue
        if target.isupper() and isinstance(value, ast.Constant) \
                and isinstance(value.value, str):
            known.add(value.value)
        elif target == "LEGACY_ALIASES" and isinstance(value, ast.Dict):
            for v in value.values:
                if isinstance(v, ast.Constant) and isinstance(v.value, str):
                    known.add(v.value)
    return known


def iter_sites(project: Project) -> Iterator[tuple[SourceFile, int, str]]:
    """(file, line, literal) for every instrumentation site whose first
    argument is a string literal."""
    for rel in sorted(project.files):
        sf = project.files[rel]
        for node in ast.walk(sf.tree):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr in INSTRUMENT_METHODS):
                continue
            recv_chain = attr_chain(node.func.value)
            if not recv_chain:
                continue
            recv = recv_chain[-1].lower()
            if not any(h in recv for h in INSTRUMENT_METHODS[node.func.attr]):
                continue
            if not (node.args and isinstance(node.args[0], ast.Constant)
                    and isinstance(node.args[0].value, str)):
                continue
            yield sf, node.args[0].lineno, node.args[0].value


def check(project: Project) -> list[Finding]:
    known = known_names(project)
    if known is None:
        return []
    rule = RULES[0]
    return [
        Finding(rule.id, rule.severity, sf.relpath, line,
                f"metric name {name!r} is not registered in obs/names.py")
        for sf, line, name in iter_sites(project)
        if name not in known]
