"""Async-hygiene rules for the asyncio layers.

``async-blocking`` — calls that block the event loop lexically inside an
``async def`` body: ``time.sleep``, the *sync* socket framing helpers
(``recv_*`` / ``send_*`` from :mod:`net.framing` — the async side is
``read_*`` / ``write_*``), raw socket ops, ``open()`` / file reads, a
``threading.Lock``-style ``.acquire()``, direct store/cache disk
reads (``load_payload`` / ``load`` / ``save``), and un-awaited
``.get()`` / ``.put()`` on a queue-named receiver (a sync
``queue.Queue`` — the worker pipeline's stage coupling — parks the
whole event loop; the asyncio flavor is awaited, which exempts it).
The sanctioned escape hatch — ``asyncio.to_thread(
self.store.load_payload, ...)`` — passes the function *uncalled*, so
no flagged Call node exists and it needs no special-casing.

``async-unawaited`` — a call to a coroutine function (an ``async def``
visible in the same file) used as a bare expression statement: the
coroutine is created, never scheduled, and silently garbage-collected.

``async-dropped-task`` — ``asyncio.create_task`` / ``ensure_future``
whose result is discarded (bare expression statement).  The loop keeps
only a weak reference to tasks, so a dropped result can be collected
mid-flight; the repo convention is ``self._tasks.add(task)`` plus a
``discard`` done-callback (serve/coalesce.py).

The blocking rule is scoped to coordinator/, serve/, and obs/ — the
directories that run an event loop; the other two are package-wide.
"""

from __future__ import annotations

import ast
from typing import Iterator

from distributedmandelbrot_tpu.analysis.astutil import (
    cached_walk, call_chain, class_defs, walk_skipping_nested_async)
from distributedmandelbrot_tpu.analysis.engine import (Finding, Project, Rule,
                                                       SourceFile)

RULES = (
    Rule("async-blocking", "async", "error",
         "blocking call inside an async def body"),
    Rule("async-unawaited", "async", "error",
         "coroutine call whose result is never awaited or scheduled"),
    Rule("async-dropped-task", "async", "warning",
         "create_task/ensure_future result dropped (task may be GC'd)"),
)

BLOCKING_SCOPE_DIRS = ("coordinator", "serve", "obs")

# Fully dotted calls that block.
BLOCKING_DOTTED = {
    "time.sleep": "time.sleep() blocks the event loop "
                  "(use asyncio.sleep)",
    "socket.create_connection": "synchronous socket connect blocks the "
                                "event loop",
}

# Sync framing helpers from net/framing.py (the async side is read_*/write_*).
SYNC_FRAMING = frozenset({
    "recv_exact", "recv_u32", "recv_byte",
    "send_all", "send_u32", "send_byte",
})

# Raw socket methods.
SOCKET_METHODS = frozenset({"recv", "recv_into", "sendall", "connect",
                            "accept"})

# Disk-touching store/cache methods; must go through asyncio.to_thread.
STORE_METHODS = frozenset({"load_payload", "load", "load_many", "save",
                           "read_text", "write_text", "read_bytes",
                           "write_bytes"})

# Receiver attribute names that look like a threading primitive, for the
# ``.acquire()`` check (so ``self.scheduler.acquire()`` — a workload
# grant, pure in-memory — is not confused with ``self._lock.acquire()``).
LOCKISH = ("lock", "mutex", "sem", "cond")

TASK_SPAWNERS = frozenset({"create_task", "ensure_future"})


def _async_defs(sf: SourceFile) -> Iterator[ast.AsyncFunctionDef]:
    for node in cached_walk(sf.tree):
        if isinstance(node, ast.AsyncFunctionDef):
            yield node


def _module_coroutine_names(sf: SourceFile) -> set[str]:
    return {n.name for n in sf.tree.body
            if isinstance(n, ast.AsyncFunctionDef)}


def _class_coroutine_methods(sf: SourceFile) -> dict[str, set[str]]:
    return {cls.name: {m.name for m in cls.body
                       if isinstance(m, ast.AsyncFunctionDef)}
            for cls in class_defs(sf.tree)}


def check(project: Project) -> list[Finding]:
    findings: list[Finding] = []
    blocking_files = {sf.relpath for sf in project.in_dirs(*BLOCKING_SCOPE_DIRS)}
    for rel in sorted(project.files):
        sf = project.files[rel]
        if rel in blocking_files:
            findings.extend(_check_blocking(sf))
        findings.extend(_check_unawaited(sf))
        findings.extend(_check_dropped_tasks(sf))
    return findings


# -- async-blocking ---------------------------------------------------------

def _check_blocking(sf: SourceFile) -> list[Finding]:
    out: list[Finding] = []
    for fn in _async_defs(sf):
        awaited = {node.value for node in walk_skipping_nested_async(fn)
                   if isinstance(node, ast.Await)}
        for node in walk_skipping_nested_async(fn):
            if not isinstance(node, ast.Call):
                continue
            msg = _blocking_message(node, node in awaited)
            if msg:
                out.append(Finding(
                    "async-blocking", "error", sf.relpath, node.lineno,
                    f"{msg} (in async def {fn.name})"))
    return out


def _blocking_message(call: ast.Call, is_awaited: bool) -> str | None:
    chain = call_chain(call)
    if chain is None:
        return None
    dotted = ".".join(chain)
    if dotted in BLOCKING_DOTTED:
        return BLOCKING_DOTTED[dotted]
    if chain == ["open"]:
        return "open() does blocking file I/O on the event loop"
    last = chain[-1]
    if last in SYNC_FRAMING:
        return (f"sync framing helper {last}() blocks the event loop "
                f"(use the async read_*/write_* side)")
    if last in SOCKET_METHODS and len(chain) >= 2 \
            and ("sock" in chain[-2].lower() or chain[-2] == "socket"):
        return f"raw socket .{last}() blocks the event loop"
    if last == "acquire" and not is_awaited and len(chain) >= 2 \
            and any(k in chain[-2].lower() for k in LOCKISH):
        return (f"{chain[-2]}.acquire() blocks the event loop "
                f"(threading primitive in a coroutine)")
    if last in STORE_METHODS and len(chain) >= 2 \
            and chain[-2] in ("store", "cache", "index", "path"):
        return (f"direct {chain[-2]}.{last}() does disk I/O on the event "
                f"loop (wrap in asyncio.to_thread)")
    if last in ("get", "put") and not is_awaited and len(chain) >= 2:
        recv = chain[-2].lower()
        # Queue-named receivers only: a bare dict .get() is everywhere
        # and harmless; a sync queue.Queue .get() parks the loop until
        # a pipeline thread feeds it.
        if recv in ("q", "queue") or recv.endswith("_q") \
                or "queue" in recv:
            return (f"sync queue .{last}() blocks the event loop "
                    f"(use asyncio.Queue and await, or _nowait)")
    return None


# -- async-unawaited --------------------------------------------------------

def _check_unawaited(sf: SourceFile) -> list[Finding]:
    out: list[Finding] = []
    module_coros = _module_coroutine_names(sf)
    class_coros = _class_coroutine_methods(sf)

    def scan_function(fn: ast.AsyncFunctionDef | ast.FunctionDef,
                      own_class: str | None) -> None:
        coros_of_self = class_coros.get(own_class or "", set())
        for node in walk_skipping_nested_async(fn):
            if not isinstance(node, ast.Expr) \
                    or not isinstance(node.value, ast.Call):
                continue
            chain = call_chain(node.value)
            if chain is None:
                continue
            name = None
            if len(chain) == 1 and chain[0] in module_coros:
                name = chain[0]
            elif len(chain) == 2 and chain[0] == "self" \
                    and chain[1] in coros_of_self:
                name = f"self.{chain[1]}"
            if name:
                out.append(Finding(
                    "async-unawaited", "error", sf.relpath, node.lineno,
                    f"{name}() returns a coroutine that is never awaited "
                    f"or scheduled"))

    for node in sf.tree.body:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            scan_function(node, None)
        elif isinstance(node, ast.ClassDef):
            for meth in node.body:
                if isinstance(meth, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    scan_function(meth, node.name)
    return out


# -- async-dropped-task -----------------------------------------------------

def _check_dropped_tasks(sf: SourceFile) -> list[Finding]:
    out: list[Finding] = []
    for node in cached_walk(sf.tree):
        if not isinstance(node, ast.Expr) \
                or not isinstance(node.value, ast.Call):
            continue
        chain = call_chain(node.value)
        if chain and chain[-1] in TASK_SPAWNERS:
            out.append(Finding(
                "async-dropped-task", "warning", sf.relpath, node.lineno,
                f"result of {chain[-1]}() is dropped; the loop holds only "
                f"a weak reference, so keep it (repo convention: add to a "
                f"task set with a discard callback)"))
    return out
