"""Small ``ast`` helpers shared by the rule modules (stdlib only)."""

from __future__ import annotations

import ast
from typing import Iterator, Optional

FunctionNode = ast.FunctionDef | ast.AsyncFunctionDef


def attr_chain(node: ast.expr) -> Optional[list[str]]:
    """Dotted name parts of a Name/Attribute chain, outermost first:
    ``self.store.load_payload`` -> ``["self", "store", "load_payload"]``.
    None when the chain passes through anything else (a call, a
    subscript), because then the receiver's identity isn't lexical."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return list(reversed(parts))
    return None


def call_chain(call: ast.Call) -> Optional[list[str]]:
    return attr_chain(call.func)


def self_attr(node: ast.expr) -> Optional[str]:
    """``attr`` when the expression is exactly ``self.<attr>``."""
    if (isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Name)
            and node.value.id == "self"):
        return node.attr
    return None


def subscript_base_self_attr(node: ast.expr) -> Optional[str]:
    """``attr`` when the expression is ``self.<attr>[...][...]``."""
    while isinstance(node, ast.Subscript):
        node = node.value
    return self_attr(node)


def dotted_names(node: ast.AST) -> Iterator[str]:
    """Every dotted name mentioned anywhere inside ``node`` (decorator
    matching: ``partial(jax.jit, ...)`` yields ``partial`` and
    ``jax.jit``)."""
    for sub in ast.walk(node):
        if isinstance(sub, (ast.Name, ast.Attribute)):
            chain = attr_chain(sub)
            if chain:
                yield ".".join(chain)


def class_defs(tree: ast.Module) -> Iterator[ast.ClassDef]:
    for node in tree.body:
        if isinstance(node, ast.ClassDef):
            yield node


def methods_of(cls: ast.ClassDef) -> Iterator[FunctionNode]:
    for node in cls.body:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield node


def walk_skipping_nested_async(node: ast.AST) -> Iterator[ast.AST]:
    """Like ``ast.walk`` over a function body, but does not descend into
    nested ``async def``s (each async def is analyzed as its own scope).
    Nested *sync* defs and lambdas ARE descended into: lexically they run
    wherever they are called from, which for our rules is the enclosing
    coroutine unless shipped off-loop (and then the call node we flag
    does not appear — ``asyncio.to_thread(f, x)`` passes ``f`` uncalled)."""
    stack = list(ast.iter_child_nodes(node))
    while stack:
        sub = stack.pop()
        if isinstance(sub, ast.AsyncFunctionDef):
            continue
        yield sub
        stack.extend(ast.iter_child_nodes(sub))
