"""Small ``ast`` helpers shared by the rule modules (stdlib only)."""

from __future__ import annotations

import ast
from typing import Iterator, Optional

FunctionNode = ast.FunctionDef | ast.AsyncFunctionDef


def cached_walk(node: ast.AST) -> tuple:
    """Memoized :func:`ast.walk`: the flat subtree tuple is cached on
    every node, built bottom-up so a walk of a function reuses the
    cached walks of its statements and a walk of the module reuses the
    functions'.  The many rule families that each re-scan the same file
    trees (and the dataflow fixpoint, which re-walks the same statements
    every pass) then pay one child traversal per node for the whole run
    instead of one subtree traversal per scan.  Yields the same node
    set as ``ast.walk`` in depth-first preorder (no rule depends on
    ``ast.walk``'s breadth-first order).  Safe because the analyzer
    never mutates parsed trees."""
    cached = getattr(node, "_dmtpu_walk", None)
    if cached is not None:
        return cached
    stack = [(node, False)]
    while stack:
        n, expanded = stack.pop()
        if expanded:
            parts = [n]
            for c in ast.iter_child_nodes(n):
                parts.extend(c._dmtpu_walk)
            n._dmtpu_walk = tuple(parts)
        else:
            stack.append((n, True))
            for c in ast.iter_child_nodes(n):
                if getattr(c, "_dmtpu_walk", None) is None:
                    stack.append((c, False))
    return node._dmtpu_walk


def attr_chain(node: ast.expr) -> Optional[list[str]]:
    """Dotted name parts of a Name/Attribute chain, outermost first:
    ``self.store.load_payload`` -> ``["self", "store", "load_payload"]``.
    None when the chain passes through anything else (a call, a
    subscript), because then the receiver's identity isn't lexical.
    Memoized on the node (callers only read the result); 0 is the
    unset sentinel since the answer is a list or None."""
    root = node
    cached = getattr(root, "_dmtpu_chain", 0)
    if cached != 0:
        return cached
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        parts.reverse()
        root._dmtpu_chain = parts
        return parts
    root._dmtpu_chain = None
    return None


def call_chain(call: ast.Call) -> Optional[list[str]]:
    return attr_chain(call.func)


def self_attr(node: ast.expr) -> Optional[str]:
    """``attr`` when the expression is exactly ``self.<attr>``."""
    if (isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Name)
            and node.value.id == "self"):
        return node.attr
    return None


def subscript_base_self_attr(node: ast.expr) -> Optional[str]:
    """``attr`` when the expression is ``self.<attr>[...][...]``."""
    while isinstance(node, ast.Subscript):
        node = node.value
    return self_attr(node)


def dotted_names(node: ast.AST) -> Iterator[str]:
    """Every dotted name mentioned anywhere inside ``node`` (decorator
    matching: ``partial(jax.jit, ...)`` yields ``partial`` and
    ``jax.jit``)."""
    for sub in cached_walk(node):
        if isinstance(sub, (ast.Name, ast.Attribute)):
            chain = attr_chain(sub)
            if chain:
                yield ".".join(chain)


def class_defs(tree: ast.Module) -> Iterator[ast.ClassDef]:
    for node in tree.body:
        if isinstance(node, ast.ClassDef):
            yield node


def methods_of(cls: ast.ClassDef) -> Iterator[FunctionNode]:
    for node in cls.body:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield node


def walk_skipping_nested_async(node: ast.AST) -> tuple:
    """Like ``ast.walk`` over a function body, but does not descend into
    nested ``async def``s (each async def is analyzed as its own scope).
    Nested *sync* defs and lambdas ARE descended into: lexically they run
    wherever they are called from, which for our rules is the enclosing
    coroutine unless shipped off-loop (and then the call node we flag
    does not appear — ``asyncio.to_thread(f, x)`` passes ``f`` uncalled).
    Memoized on the node like :func:`cached_walk` — the lock and async
    analyses re-walk the same statements every fixpoint pass."""
    cached = getattr(node, "_dmtpu_walk_na", None)
    if cached is not None:
        return cached
    out = []
    stack = list(ast.iter_child_nodes(node))
    while stack:
        sub = stack.pop()
        if isinstance(sub, ast.AsyncFunctionDef):
            continue
        out.append(sub)
        stack.extend(ast.iter_child_nodes(sub))
    node._dmtpu_walk_na = tuple(out)
    return node._dmtpu_walk_na
