"""Lock-discipline rules for the threaded layers.

``lock-guard`` — per-class inference: an attribute accessed under a
``with self.<lock>:`` block anywhere in the class is *guarded* by that
lock; a mutation of a guarded attribute while NOT holding its lock
(outside ``__init__`` — construction is single-threaded) is flagged.
The inference is deliberately evidence-based rather than annotation-
based: the codebase's convention IS the spec, and the rule catches the
one call site that forgets it.

``lock-order`` — a global lock-acquisition-order graph: acquiring lock B
while holding lock A adds edge ``A -> B``.  Edges come from lexical
``with`` nesting AND from the call graph: a call made under lock A
contributes edges from A to every lock the callee acquires
*transitively* (``analysis/callgraph.py``), so a cross-class inversion
hidden behind two helper hops is still a cycle.  Any cycle is a
deadlock risk.  Nodes are ``ClassName.lockattr``.

``lock-held-blocking`` — a call that can block indefinitely (queue
``get``/``put``, thread ``join``, semaphore ``acquire``, client
request/submit network exchanges, ``time.sleep``, event ``wait``) made
while a lock is held.  Since v2 the rule is interprocedural: a call
site under a lock is also flagged when the *callee* — or anything the
callee transitively reaches through resolvable calls — performs one of
the blocking operations, with the call path named in the message.  A
one-level wrapper no longer defeats the rule.  The stage-queue
pipeline's discipline is that every blocking wait happens OUTSIDE the
window lock — one queue ``get`` under it and the whole executor
convoys.  Calls on the held lock itself (``cond.wait`` / ``notify`` —
which release it) are sanctioned; unresolvable calls (callbacks,
``getattr``) are not searched, which keeps the rule quiet rather than
paranoid.

Scope: coordinator/, storage/, serve/, obs/, worker/ — the modules
where the asyncio loop and worker/pipeline threads genuinely share
state.
"""

from __future__ import annotations

import ast
from collections import Counter as _TallyCounter
from typing import Optional

from distributedmandelbrot_tpu.analysis import callgraph
from distributedmandelbrot_tpu.analysis.astutil import (FunctionNode,
                                                        cached_walk,
                                                        call_chain,
                                                        class_defs,
                                                        methods_of, self_attr,
                                                        subscript_base_self_attr)
from distributedmandelbrot_tpu.analysis.engine import (Finding, Project, Rule,
                                                       SourceFile)

RULES = (
    Rule("lock-guard", "locks", "error",
         "mutation of a lock-guarded attribute without holding its lock"),
    Rule("lock-order", "locks", "warning",
         "cycle in the lock acquisition-order graph (deadlock risk)"),
    Rule("lock-held-blocking", "locks", "error",
         "potentially unbounded blocking call while holding a lock"),
)

SCOPE_DIRS = ("coordinator", "storage", "serve", "obs", "worker")

# Method calls that mutate their receiver in place.
MUTATORS = frozenset({
    "append", "appendleft", "extend", "insert", "add", "update",
    "setdefault", "pop", "popleft", "popitem", "remove", "discard",
    "clear", "move_to_end", "sort", "reverse",
})


def _blocking_under_lock(chain: list[str]) -> Optional[str]:
    """Message when the call chain is a recognizably blocking operation
    (receiver-name heuristics keep ``dict.get`` and scheduler
    ``acquire`` — pure in-memory — out of it); None otherwise."""
    if chain == ["time", "sleep"]:
        return "time.sleep() under a lock stalls every other holder"
    if len(chain) < 2:
        return None
    recv = chain[-2].lower()
    last = chain[-1]
    if last in ("get", "put") and (
            recv in ("q", "queue") or recv.endswith("_q")
            or "queue" in recv):
        return (f"queue .{last}() can block indefinitely; move it "
                f"outside the lock")
    if last == "join" and "thread" in recv:
        return "thread .join() under a lock invites a deadlock"
    if last == "acquire" and "sem" in recv:
        return ("semaphore .acquire() under a lock blocks every other "
                "holder until a permit frees")
    if last == "wait" and ("stop" in recv or "event" in recv):
        return ("event .wait() under a lock stalls every other holder "
                "for the full wait")
    if last in ("request", "request_batch", "submit", "submit_batch") \
            and "client" in recv:
        return (f"network exchange .{last}() under a lock serializes "
                f"the pipeline on the round-trip")
    return None


class _ClassAnalysis:
    """Everything the two rules need from one class body."""

    def __init__(self, sf: SourceFile, cls: ast.ClassDef) -> None:
        self.sf = sf
        self.cls = cls
        self.lock_attrs = self._find_lock_attrs()
        # attr -> tally of the lock(s) held when it was accessed under one
        self.guard_evidence: dict[str, _TallyCounter] = {}
        # (attr, line, held, method) for every mutation site
        self.mutations: list[tuple[str, int, tuple[str, ...], str]] = []
        # lock -> lock lexical acquisition edges, with first line seen
        self.edges: dict[tuple[str, str], int] = {}
        # locks each method acquires directly
        self.method_locks: dict[str, set[str]] = {}
        # (held locks, call node) — every call made under a lock that was
        # neither flagged directly nor sanctioned; the interprocedural
        # pass resolves these through the call graph
        self.calls_held: list[tuple[tuple[str, ...], ast.Call]] = []
        # (line, innermost lock, message) — blocking call under a lock
        self.blocking: list[tuple[int, str, str]] = []
        for meth in methods_of(cls):
            self.method_locks.setdefault(meth.name, set())
            self._walk(meth, meth)

    def _find_lock_attrs(self) -> set[str]:
        """An attribute used as a bare ``with self.X:`` context anywhere
        in the class is a lock (covers both ``self._lock = Lock()`` and
        locks injected through ``__init__`` parameters)."""
        locks: set[str] = set()
        for node in cached_walk(self.cls):
            if isinstance(node, (ast.With, ast.AsyncWith)):
                for item in node.items:
                    attr = self_attr(item.context_expr)
                    if attr is not None:
                        locks.add(attr)
        return locks

    # -- the walk ---------------------------------------------------------

    def _walk(self, meth: FunctionNode, root: FunctionNode) -> None:
        def visit(node: ast.AST, held: tuple[str, ...]) -> None:
            if isinstance(node, (ast.With, ast.AsyncWith)):
                inner_held = held
                for item in node.items:
                    attr = self_attr(item.context_expr)
                    if attr is not None and attr in self.lock_attrs:
                        for outer in inner_held:
                            self.edges.setdefault(
                                (outer, attr), item.context_expr.lineno)
                        self.method_locks[meth.name].add(attr)
                        inner_held = inner_held + (attr,)
                    else:
                        visit(item.context_expr, held)
                    if item.optional_vars is not None:
                        visit(item.optional_vars, held)
                for stmt in node.body:
                    visit(stmt, inner_held)
                return
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda)) and node is not root:
                # A nested def's body runs at some later call, not under
                # the locks lexically around its definition — analyzing
                # it here would produce both false hits and false passes.
                return
            self._inspect(node, held, meth.name)
            for child in ast.iter_child_nodes(node):
                visit(child, held)

        for stmt in meth.body:
            visit(stmt, ())

    def _inspect(self, node: ast.AST, held: tuple[str, ...],
                 method: str) -> None:
        if isinstance(node, ast.Assign):
            for target in node.targets:
                self._mutation_target(target, held, method)
        elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
            if getattr(node, "value", None) is not None \
                    or isinstance(node, ast.AugAssign):
                self._mutation_target(node.target, held, method)
        elif isinstance(node, ast.Delete):
            for target in node.targets:
                self._mutation_target(target, held, method)
        elif isinstance(node, ast.Call):
            chain = call_chain(node)
            if chain and held:
                # Calls on a lock we HOLD are the sanctioned Condition
                # protocol (wait/notify release-and-reacquire).
                on_held_lock = (chain[0] == "self" and len(chain) >= 3
                                and chain[1] in held)
                msg = None if on_held_lock else _blocking_under_lock(chain)
                if msg is not None:
                    self.blocking.append((node.lineno, held[-1], msg))
                elif not on_held_lock:
                    self.calls_held.append((held, node))
            if chain and chain[0] == "self" and len(chain) >= 3 \
                    and chain[-1] in MUTATORS:
                self._record_mutation(chain[1], node.lineno, held, method)
        elif isinstance(node, ast.Attribute) \
                and isinstance(node.ctx, ast.Load) and held:
            attr = self_attr(node)
            if attr is not None and attr not in self.lock_attrs:
                tally = self.guard_evidence.setdefault(attr, _TallyCounter())
                tally[held[-1]] += 0  # presence only; reads don't pick a lock
                tally.update([held[-1]])

    def _mutation_target(self, target: ast.expr, held: tuple[str, ...],
                         method: str) -> None:
        if isinstance(target, (ast.Tuple, ast.List)):
            for elt in target.elts:
                self._mutation_target(elt, held, method)
            return
        attr = self_attr(target)
        if attr is None:
            attr = subscript_base_self_attr(target)
        if attr is not None and attr not in self.lock_attrs:
            self._record_mutation(attr, target.lineno, held, method)

    def _record_mutation(self, attr: str, line: int, held: tuple[str, ...],
                         method: str) -> None:
        self.mutations.append((attr, line, held, method))
        if held:
            self.guard_evidence.setdefault(
                attr, _TallyCounter()).update([held[-1]])


class _Summaries:
    """Per-function facts the interprocedural pass propagates: blocking
    operations a function performs directly, and locks it acquires
    directly — computed for EVERY function in the package (a scope-dir
    method may reach its blocking op through a helper anywhere)."""

    def __init__(self, project: Project) -> None:
        self.graph = callgraph.graph_for(project)
        self.own_blocking: dict[str, list[tuple[int, str]]] = {}
        self.own_locks: dict[str, set[str]] = {}
        self._reach: dict[str, dict[str, tuple[str, ...]]] = {}
        class_locks: dict[tuple[str, Optional[str]], set[str]] = {}
        for qual, fi in self.graph.functions.items():
            key = (fi.relpath, fi.cls)
            if key not in class_locks:
                info = self.graph.class_info(fi.relpath, fi.cls) \
                    if fi.cls else None
                class_locks[key] = _bare_with_attrs(info.node) \
                    if info is not None else set()
            locks = class_locks[key]
            blocking: list[tuple[int, str]] = []
            acquired: set[str] = set()
            for node in _walk_own(fi.node):
                if isinstance(node, ast.Call):
                    chain = call_chain(node)
                    msg = _blocking_under_lock(chain) if chain else None
                    if msg is not None:
                        blocking.append((node.lineno, msg))
                elif isinstance(node, (ast.With, ast.AsyncWith)):
                    for item in node.items:
                        attr = self_attr(item.context_expr)
                        if attr is not None and attr in locks:
                            acquired.add(f"{fi.cls}.{attr}")
            if blocking:
                self.own_blocking[qual] = blocking
            if acquired:
                self.own_locks[qual] = acquired

    def reach(self, qual: str) -> dict[str, tuple[str, ...]]:
        if qual not in self._reach:
            self._reach[qual] = self.graph.reachable(qual)
        return self._reach[qual]

    def blocking_via(self, callee: str
                     ) -> Optional[tuple[tuple[str, ...], str]]:
        """(call path ending at the blocking function, message) for the
        nearest blocking operation reachable from ``callee``."""
        if callee in self.own_blocking:
            return (callee,), self.own_blocking[callee][0][1]
        for qual, path in self.reach(callee).items():  # BFS order
            if qual in self.own_blocking:
                return path + (qual,), self.own_blocking[qual][0][1]
        return None

    def locks_via(self, callee: str) -> set[str]:
        """Every ``Class.lock`` acquired by ``callee`` or anything it
        transitively reaches."""
        out = set(self.own_locks.get(callee, ()))
        for qual in self.reach(callee):
            out.update(self.own_locks.get(qual, ()))
        return out


def _walk_own(fn: FunctionNode) -> tuple:
    """Walk a function body without descending into nested defs or
    lambdas (their bodies run at some later call).  Built as a filter
    over :func:`cached_walk`'s preorder tuples — a nested def's subtree
    is the contiguous run of its own cached walk, so skipping it is an
    index jump instead of a re-traversal."""
    cached = getattr(fn, "_dmtpu_walk_own", None)
    if cached is not None:
        return cached
    skip = (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
    out: list = []
    for stmt in fn.body:
        nodes = cached_walk(stmt)
        i, n = 0, len(nodes)
        while i < n:
            node = nodes[i]
            if isinstance(node, skip):
                i += len(node._dmtpu_walk)
                continue
            out.append(node)
            i += 1
    fn._dmtpu_walk_own = tuple(out)
    return fn._dmtpu_walk_own


def _bare_with_attrs(cls: ast.ClassDef) -> set[str]:
    """Same lock-attr evidence as :meth:`_ClassAnalysis._find_lock_attrs`
    but usable for classes outside the findings scope."""
    locks: set[str] = set()
    for node in cached_walk(cls):
        if isinstance(node, (ast.With, ast.AsyncWith)):
            for item in node.items:
                attr = self_attr(item.context_expr)
                if attr is not None:
                    locks.add(attr)
    return locks


def _display(qual: str) -> str:
    return qual.rsplit("::", 1)[-1]


def check(project: Project) -> list[Finding]:
    findings: list[Finding] = []
    summaries = _Summaries(project)
    # Global acquisition-order graph: node "Class.lock" -> successors,
    # with the (path, line) of the first edge for reporting.
    graph: dict[str, set[str]] = {}
    edge_site: dict[tuple[str, str], tuple[str, int]] = {}

    for sf in project.in_dirs(*SCOPE_DIRS):
        for cls in class_defs(sf.tree):
            info = _ClassAnalysis(sf, cls)
            if not info.lock_attrs:
                continue
            findings.extend(_guard_findings(sf, cls, info))
            for line, lock, msg in info.blocking:
                findings.append(Finding(
                    "lock-held-blocking", "error", sf.relpath, line,
                    f"{msg} (holding {cls.name}.{lock})"))
            for (outer, inner), line in info.edges.items():
                a, b = f"{cls.name}.{outer}", f"{cls.name}.{inner}"
                graph.setdefault(a, set()).add(b)
                edge_site.setdefault((a, b), (sf.relpath, line))
            # Interprocedural: resolve every call made under a held lock
            # and search what it reaches for blocking ops + acquisitions.
            for held, call in info.calls_held:
                callee = summaries.graph.resolve_node(call)
                if callee is None:
                    continue
                hit = summaries.blocking_via(callee)
                if hit is not None:
                    path, msg = hit
                    findings.append(Finding(
                        "lock-held-blocking", "error", sf.relpath,
                        call.lineno,
                        f"{msg} — reached via "
                        f"{' -> '.join(_display(q) for q in path)}() "
                        f"(holding {cls.name}.{held[-1]})"))
                for b in summaries.locks_via(callee):
                    for outer in held:
                        a = f"{cls.name}.{outer}"
                        if a != b:
                            graph.setdefault(a, set()).add(b)
                            edge_site.setdefault((a, b),
                                                 (sf.relpath, call.lineno))

    findings.extend(_order_findings(graph, edge_site))
    return findings


def _guard_findings(sf: SourceFile, cls: ast.ClassDef,
                    info: _ClassAnalysis) -> list[Finding]:
    out: list[Finding] = []
    guard_lock = {attr: tally.most_common(1)[0][0]
                  for attr, tally in info.guard_evidence.items() if tally}
    for attr, line, held, method in info.mutations:
        if method == "__init__":
            continue
        lock = guard_lock.get(attr)
        if lock is None or lock in held:
            continue
        out.append(Finding(
            "lock-guard", "error", sf.relpath, line,
            f"{cls.name}.{attr} is guarded by self.{lock} elsewhere in the "
            f"class but mutated in {method}() without holding it"))
    return out


def _order_findings(graph: dict[str, set[str]],
                    edge_site: dict[tuple[str, str], tuple[str, int]]
                    ) -> list[Finding]:
    """Report each strongly connected component with a cycle once."""
    out: list[Finding] = []
    for scc in _sccs(graph):
        nodes = sorted(scc)
        has_cycle = len(nodes) > 1 or (
            nodes and nodes[0] in graph.get(nodes[0], ()))
        if not has_cycle:
            continue
        site = min((edge_site[(a, b)] for a in nodes
                    for b in graph.get(a, ()) if b in scc
                    and (a, b) in edge_site), default=("<unknown>", 1))
        out.append(Finding(
            "lock-order", "warning", site[0], site[1],
            "lock acquisition-order cycle (deadlock risk): "
            + " -> ".join(nodes + [nodes[0]])))
    return out


def _sccs(graph: dict[str, set[str]]) -> list[set[str]]:
    """Tarjan's strongly connected components, iterative."""
    index: dict[str, int] = {}
    low: dict[str, int] = {}
    on_stack: set[str] = set()
    stack: list[str] = []
    sccs: list[set[str]] = []
    counter = [0]
    all_nodes = set(graph) | {b for succ in graph.values() for b in succ}

    for start in sorted(all_nodes):
        if start in index:
            continue
        work: list[tuple[str, Optional[str], int]] = [(start, None, 0)]
        while work:
            node, parent, child_i = work.pop()
            if child_i == 0:
                index[node] = low[node] = counter[0]
                counter[0] += 1
                stack.append(node)
                on_stack.add(node)
            recurse = False
            successors = sorted(graph.get(node, ()))
            for i in range(child_i, len(successors)):
                succ = successors[i]
                if succ not in index:
                    work.append((node, parent, i + 1))
                    work.append((succ, node, 0))
                    recurse = True
                    break
                if succ in on_stack:
                    low[node] = min(low[node], index[succ])
            if recurse:
                continue
            if low[node] == index[node]:
                scc: set[str] = set()
                while True:
                    w = stack.pop()
                    on_stack.discard(w)
                    scc.add(w)
                    if w == node:
                        break
                sccs.append(scc)
            if parent is not None:
                low[parent] = min(low[parent], low[node])
    return sccs
