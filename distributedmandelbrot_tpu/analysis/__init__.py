"""Project-native static analysis (``dmtpu check``).

Stdlib-``ast`` checkers for the farm's hand-enforced invariants: lock
discipline in the threaded layers (interprocedural since v2, over
``analysis/callgraph.py``), async hygiene in the event-loop layers,
wire-format parity between every speaker of the protocol, protocol
conversation conformance (dispatch arms, frame sequences, exact-length
reads), resource lifecycles (threads, sockets, queues, servers),
instrumentation-name registration, purity/precision rules inside
JAX-traced functions, and — since v3, over ``analysis/dataflow.py`` —
taint tracking from network reads to allocation/index/loop/struct sinks
(``taint-*``) plus exception-path resource-leak and silent-swallow
checks (``exc-*``).  Importing this package never imports jax (or the
modules under analysis) — the tier-1 gate runs it in a bare subprocess
inside a five-second budget.
"""

from distributedmandelbrot_tpu.analysis.engine import (Finding, Project,
                                                       Report, Rule,
                                                       SourceFile, all_rules,
                                                       check_project,
                                                       default_root,
                                                       expand_rule_ids,
                                                       fingerprints_at_ref,
                                                       load_baseline,
                                                       project_at_ref,
                                                       render_json,
                                                       render_text, run_check,
                                                       save_baseline)

__all__ = [
    "Finding", "Project", "Report", "Rule", "SourceFile",
    "all_rules", "check_project", "default_root", "expand_rule_ids",
    "fingerprints_at_ref", "project_at_ref",
    "load_baseline", "save_baseline",
    "render_json", "render_text", "run_check",
]
