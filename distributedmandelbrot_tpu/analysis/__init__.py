"""Project-native static analysis (``dmtpu check``).

Stdlib-``ast`` checkers for the farm's hand-enforced invariants: lock
discipline in the threaded layers, async hygiene in the event-loop
layers, wire-format parity between every speaker of the protocol, and
purity/precision rules inside JAX-traced functions.  Importing this
package never imports jax (or the modules under analysis) — the tier-1
gate runs it in a bare subprocess in well under a second.
"""

from distributedmandelbrot_tpu.analysis.engine import (Finding, Project,
                                                       Report, Rule,
                                                       SourceFile, all_rules,
                                                       check_project,
                                                       default_root,
                                                       load_baseline,
                                                       render_json,
                                                       render_text, run_check,
                                                       save_baseline)

__all__ = [
    "Finding", "Project", "Report", "Rule", "SourceFile",
    "all_rules", "check_project", "default_root",
    "load_baseline", "save_baseline",
    "render_json", "render_text", "run_check",
]
