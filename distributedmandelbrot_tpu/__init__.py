"""distributedmandelbrot_tpu — a TPU-native distributed fractal-rendering framework.

A pull-based tile farm with the capabilities of ofsouzap/DistributedMandelbrot,
re-designed TPU-first: JAX/Pallas escape-time kernels, ``shard_map`` tile
batching over device meshes, an asyncio coordinator with O(1) frontier
scheduling, a durable append-only tile index, and wire-compatible Distributer
and DataServer protocols.
"""

__version__ = "0.1.0"
