"""Latency/outcome recording and the end-of-run storm report.

The recorder is a thin, lock-cheap shim over the observability registry:
one phase-labeled histogram for completed-request latency plus the
``loadgen_*`` outcome counters.  The registry's instruments already take
a single short lock per update, so the open-loop runner can record from
thousands of concurrent request tasks without a private accounting
layer; percentile math is the registry's
(:meth:`~distributedmandelbrot_tpu.obs.metrics.Histogram.percentile`),
so the storm report and a scrape of ``/metrics`` agree by construction.

Outcome vocabulary (what the driver returns per request):

- ``ok`` — accepted, payload read in full (counts toward goodput);
- ``shed`` — explicit ``QUERY_OVERLOADED`` (admission control working);
- ``unavailable`` — ``QUERY_NOT_AVAILABLE`` / ``QUERY_REJECT``;
- ``error`` — transport failure, timeout, or protocol violation.

Latency is recorded for every *completed* exchange (ok and shed both —
a shed response's latency is the shed path's cost, and watching it stay
flat under overload is the point of the exercise), but the headline
percentiles in the report are goodput percentiles: ``ok`` only.
"""

from __future__ import annotations

import threading
from typing import Optional

from distributedmandelbrot_tpu.obs import names as obs_names
from distributedmandelbrot_tpu.obs.metrics import Histogram, Registry

OUTCOME_OK = "ok"
OUTCOME_SHED = "shed"
OUTCOME_UNAVAILABLE = "unavailable"
OUTCOME_ERROR = "error"

_OUTCOME_COUNTERS = {
    OUTCOME_OK: obs_names.LOADGEN_COMPLETED,
    OUTCOME_SHED: obs_names.LOADGEN_SHED,
    OUTCOME_UNAVAILABLE: obs_names.LOADGEN_UNAVAILABLE,
    OUTCOME_ERROR: obs_names.LOADGEN_ERRORS,
}


class StormRecorder:
    """Registry-backed request accounting for one load-generation run."""

    def __init__(self, registry: Optional[Registry] = None) -> None:
        self.registry = registry if registry is not None else Registry()

    # -- write side (hot path) --------------------------------------------

    def issued(self) -> None:
        """An arrival left the schedule (open loop: counted at issue
        time, not completion)."""
        self.registry.inc(obs_names.LOADGEN_REQUESTS)

    def saturated(self) -> None:
        """The *client* hit its in-flight ceiling — the measurement is
        load-generator-bound, not server-bound, and the report flags it."""
        self.registry.inc(obs_names.LOADGEN_CLIENT_SATURATED)

    def record(self, phase: str, outcome: str, latency: float,
               nbytes: int = 0) -> None:
        self.registry.inc(_OUTCOME_COUNTERS.get(outcome,
                                                obs_names.LOADGEN_ERRORS))
        if nbytes:
            self.registry.inc(obs_names.LOADGEN_BYTES, nbytes)
        if outcome in (OUTCOME_OK, OUTCOME_SHED):
            self.registry.observe(
                obs_names.HIST_LOADGEN_LATENCY_SECONDS, latency,
                labels={"phase": phase, "outcome": outcome})

    # -- read side (report) -----------------------------------------------

    def _count(self, name: str) -> int:
        return self.registry.counter_value(name) or 0

    def _ok_percentile(self, q: float,
                       phase: Optional[str] = None) -> Optional[float]:
        """Merged percentile over ``ok`` children (optionally one phase)."""
        children = [
            inst for (name, labels), inst in self.registry._iter_instruments()
            if name == obs_names.HIST_LOADGEN_LATENCY_SECONDS
            and isinstance(inst, Histogram)
            and ("outcome", OUTCOME_OK) in labels
            and (phase is None or ("phase", phase) in labels)]
        if not children:
            return None
        merged = Histogram(obs_names.HIST_LOADGEN_LATENCY_SECONDS, (),
                           threading.Lock(), children[0].bounds)
        for h in children:
            counts, total, count = h.state()
            for i, c in enumerate(counts):
                merged.counts[i] += c
            merged.sum += total
            merged.count += count
        return merged.percentile(q)

    def _phase_count(self, phase: str, outcome: str) -> int:
        """Completed-exchange count for one (phase, outcome) pair, read
        from the latency histogram's labeled children."""
        total = 0
        for (name, labels), inst in self.registry._iter_instruments():
            if name == obs_names.HIST_LOADGEN_LATENCY_SECONDS \
                    and isinstance(inst, Histogram) \
                    and ("outcome", outcome) in labels \
                    and ("phase", phase) in labels:
                total += inst.state()[2]
        return total

    def report(self, *, duration: float, offered: float,
               phases: Optional[list[str]] = None) -> dict:
        """The storm summary: percentiles, goodput vs offered, shedding.

        ``duration`` is the run's wall (or virtual) span in seconds,
        ``offered`` the schedule's mean arrival rate; ``phases`` adds a
        per-phase percentile block in schedule order.
        """
        issued = self._count(obs_names.LOADGEN_REQUESTS)
        completed = self._count(obs_names.LOADGEN_COMPLETED)
        shed = self._count(obs_names.LOADGEN_SHED)
        report = {
            "requests": issued,
            "completed": completed,
            "shed": shed,
            "unavailable": self._count(obs_names.LOADGEN_UNAVAILABLE),
            "errors": self._count(obs_names.LOADGEN_ERRORS),
            "client_saturated": self._count(
                obs_names.LOADGEN_CLIENT_SATURATED),
            "bytes": self._count(obs_names.LOADGEN_BYTES),
            "offered_rate": round(offered, 3),
            "goodput": round(completed / duration, 3) if duration > 0
            else 0.0,
            "shed_fraction": round(shed / issued, 4) if issued else 0.0,
            "p50": self._ok_percentile(50),
            "p99": self._ok_percentile(99),
            "p999": self._ok_percentile(99.9),
        }
        if phases:
            # Per-phase completed/shed counts make admission control
            # legible in one report: the spike phase sheds, the recovery
            # phase goes clean again.
            report["phases"] = {
                phase: {"completed": self._phase_count(phase, OUTCOME_OK),
                        "shed": self._phase_count(phase, OUTCOME_SHED),
                        "p50": self._ok_percentile(50, phase),
                        "p99": self._ok_percentile(99, phase),
                        "p999": self._ok_percentile(99.9, phase)}
                for phase in dict.fromkeys(phases)}
        return report
