"""The open-loop runner and its timebases.

Open loop means the schedule is law: every arrival is issued at its
scheduled instant whether or not earlier requests have completed.  The
runner never awaits a request before issuing the next — it spawns a task
per arrival and only gathers them at the end — so a slow or collapsing
server sees the *population's* rate, and queue growth shows up as
latency instead of being silently absorbed by client backpressure.

Time is abstracted behind a two-method timebase (``now()`` /
``sleep(dt)``) so the same runner drives both modes:

- :class:`RealTimebase` — ``time.monotonic`` + ``asyncio.sleep``, for
  storming an actual gateway;
- :class:`VirtualTimebase` — a heap of pending sleepers advanced by an
  explicit :meth:`~VirtualTimebase.drain` pump.  Tests run a "10 second"
  storm in milliseconds, with *exact* issue times: the pump only moves
  the clock when every runnable task has quiesced, so there is no real
  scheduler jitter to blur assertions.
"""

from __future__ import annotations

import asyncio
import heapq
import time
from typing import Awaitable, Callable, Optional

from distributedmandelbrot_tpu.loadgen.recorder import (OUTCOME_ERROR,
                                                        StormRecorder)
from distributedmandelbrot_tpu.loadgen.schedule import Request

# request callable: (level, index_real, index_imag) -> (outcome, nbytes)
RequestFn = Callable[[int, int, int], Awaitable[tuple[str, int]]]


class RealTimebase:
    """Wall-clock timebase (monotonic)."""

    def now(self) -> float:
        return time.monotonic()

    async def sleep(self, dt: float) -> None:
        await asyncio.sleep(dt)


class VirtualTimebase:
    """Deterministic manual clock for asyncio tests.

    ``sleep`` parks the caller on a heap keyed by wake time;
    :meth:`drain` repeatedly lets every runnable task make progress
    (a burst of zero-sleeps), then pops the earliest sleeper, jumps the
    clock to its wake time, and releases it.  Virtual time therefore
    advances only when nothing else can run — the discrete-event
    simulation contract.
    """

    # How many zero-sleep yields count as "everything runnable has run".
    # Each yield cycles asyncio's entire ready queue once; chained
    # awaits (task -> gather -> request fn) need a few cycles to settle.
    _YIELDS = 50

    def __init__(self, *, max_idle_rounds: int = 1000) -> None:
        # Grace before a pending target task with no sleepers is called
        # a deadlock.  The default suits pure-virtual tests; raise it
        # when real IO (sockets, threads) completes work off the virtual
        # clock and merely needs wall time — pair that with a bounded
        # per-request timeout so a true hang still terminates.
        self.max_idle_rounds = max_idle_rounds
        self._now = 0.0
        self._seq = 0
        self._sleepers: list[tuple[float, int, asyncio.Future]] = []

    def now(self) -> float:
        return self._now

    async def sleep(self, dt: float) -> None:
        if dt <= 0:
            await asyncio.sleep(0)
            return
        future = asyncio.get_running_loop().create_future()
        heapq.heappush(self._sleepers, (self._now + dt, self._seq, future))
        self._seq += 1
        await future

    async def _quiesce(self) -> None:
        for _ in range(self._YIELDS):
            await asyncio.sleep(0)

    async def drain(self, until: Optional[asyncio.Task] = None) -> None:
        """Pump virtual time until ``until`` completes (or, with no
        target task, until no sleeper remains)."""
        idle_rounds = 0
        while True:
            await self._quiesce()
            if until is not None and until.done():
                return
            if until is None and not self._sleepers:
                return
            if not self._sleepers:
                # Target task pending but nothing waiting on the clock:
                # it must be about to finish or about to sleep.  Give it
                # bounded grace, then call the deadlock.
                idle_rounds += 1
                if idle_rounds > self.max_idle_rounds:
                    raise RuntimeError(
                        "virtual clock deadlock: task pending, no sleepers")
                continue
            idle_rounds = 0
            wake, _, future = heapq.heappop(self._sleepers)
            self._now = max(self._now, wake)
            if not future.done():
                future.set_result(None)


class OpenLoopRunner:
    """Issue a schedule open-loop against an async request function.

    ``max_inflight`` is a *safety rail*, not backpressure: crossing it
    bumps ``loadgen_client_saturated`` (so the report can flag a
    generator-bound run) and, only at the hard ``2x`` ceiling, skips
    issuing — recorded as an error, never silently dropped.
    """

    def __init__(self, schedule: list[Request], request: RequestFn,
                 recorder: StormRecorder, *,
                 timebase: Optional[RealTimebase | VirtualTimebase] = None,
                 max_inflight: int = 10_000) -> None:
        self.schedule = schedule
        self.request = request
        self.recorder = recorder
        self.timebase = timebase if timebase is not None else RealTimebase()
        self.max_inflight = max_inflight
        self._inflight = 0
        self.issue_times: list[float] = []  # run-relative, for the tests

    async def run(self) -> float:
        """Play the schedule; returns the run's duration in timebase
        seconds (last completion - start)."""
        start = self.timebase.now()
        tasks: list[asyncio.Task] = []
        for item in self.schedule:
            delay = (start + item.time) - self.timebase.now()
            if delay > 0:
                await self.timebase.sleep(delay)
            self.recorder.issued()
            self.issue_times.append(self.timebase.now() - start)
            if self._inflight >= self.max_inflight:
                self.recorder.saturated()
                if self._inflight >= 2 * self.max_inflight:
                    self.recorder.record(item.phase, OUTCOME_ERROR, 0.0)
                    continue
            self._inflight += 1
            tasks.append(asyncio.ensure_future(self._issue(item)))
        if tasks:
            await asyncio.gather(*tasks)
        return self.timebase.now() - start

    async def _issue(self, item: Request) -> None:
        t0 = self.timebase.now()
        try:
            outcome, nbytes = await self.request(
                item.level, item.index_real, item.index_imag)
        except asyncio.CancelledError:
            raise
        except Exception:
            outcome, nbytes = OUTCOME_ERROR, 0
        finally:
            self._inflight -= 1
        self.recorder.record(item.phase, outcome,
                             self.timebase.now() - t0, nbytes)
