"""GatewayFleet: N read replicas over one shared object store.

The horizontal-read experiment in miniature.  Each replica is a full
serve stack — :class:`~distributedmandelbrot_tpu.storage.store.
ChunkStore` over an :class:`~distributedmandelbrot_tpu.storage.backends.
ObjectStoreBackend`, decoded-tile cache, :class:`~distributedmandelbrot_
tpu.serve.gateway.TileGateway` — running its own asyncio loop on its own
thread, bound to an ephemeral loopback port.  All replicas hand their
backend the *same* object-store fake (``MemoryObjectStore`` or
``DirObjectStore``), so any replica serves any tile and adding a replica
adds serving capacity without data movement: the bench's >= 1.6x
1 -> 2 replica goodput criterion is measured against exactly this class.

No on-demand compute: a fleet is a *read* tier.  Misses answer
``QUERY_NOT_AVAILABLE``, which keeps the scaling measurement about the
read path instead of farm scheduling.

``sessions=True`` attaches a :class:`~distributedmandelbrot_tpu.
sessions.SessionService` per replica (no scheduler, so capability
negotiation grants prefetch-by-cache-warming and refuses refinement):
the jax-free way to storm the session wire, measure prefetch hit
ratios, and exercise per-session fair admission.
"""

from __future__ import annotations

import asyncio
import threading
from typing import Optional

from distributedmandelbrot_tpu.serve.cache import DecodedTileCache
from distributedmandelbrot_tpu.serve.gateway import TileGateway
from distributedmandelbrot_tpu.sessions import build_session_service
from distributedmandelbrot_tpu.storage.backends import (ObjectStore,
                                                        ObjectStoreBackend)
from distributedmandelbrot_tpu.storage.store import ChunkStore
from distributedmandelbrot_tpu.utils.metrics import Counters


class _Replica:
    """One threaded gateway over the shared key-value store."""

    def __init__(self, kv: ObjectStore, *, cache_tiles: int,
                 render_cache_tiles: int, max_queue_depth: int,
                 rate: Optional[float], burst: float,
                 read_timeout: Optional[float],
                 sessions: bool = False,
                 session_rate: Optional[float] = None,
                 session_burst: float = 32.0,
                 session_ttl: Optional[float] = 300.0,
                 prefetch_horizon: int = 3,
                 exporter: bool = False) -> None:
        self.counters = Counters()
        self.port: Optional[int] = None
        self.exporter_port: Optional[int] = None
        self._exporter = exporter
        self._kv = kv
        self._gateway_kwargs = dict(
            max_queue_depth=max_queue_depth, rate=rate, burst=burst,
            render_cache_tiles=render_cache_tiles,
            read_timeout=read_timeout)
        self._cache_tiles = cache_tiles
        self._sessions = sessions
        self._session_kwargs = dict(
            session_rate=session_rate, session_burst=session_burst,
            session_ttl=session_ttl, prefetch_horizon=prefetch_horizon)
        self._ready = threading.Event()
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        self._stop_event: Optional[asyncio.Event] = None
        self._error: Optional[BaseException] = None
        self._thread = threading.Thread(target=self._run, daemon=True)

    def start(self) -> None:
        self._thread.start()
        self._ready.wait(timeout=30.0)
        if self._error is not None:
            raise RuntimeError("replica failed to start") from self._error
        if self.port is None:
            raise RuntimeError("replica did not come up within 30s")

    def stop(self) -> None:
        if self._loop is not None and self._stop_event is not None:
            self._loop.call_soon_threadsafe(self._stop_event.set)
        self._thread.join(timeout=30.0)

    def _run(self) -> None:
        try:
            asyncio.run(self._main())
        except BaseException as e:  # surfaced by start()
            self._error = e
            self._ready.set()

    async def _main(self) -> None:
        self._loop = asyncio.get_running_loop()
        self._stop_event = asyncio.Event()
        store = ChunkStore(backend=ObjectStoreBackend(self._kv),
                           registry=self.counters.registry)
        cache = DecodedTileCache(store, capacity=self._cache_tiles,
                                 counters=self.counters)
        service = None
        if self._sessions:
            # No scheduler on a read replica: the service negotiates
            # refinement away and prefetches by cache warming only.
            service = build_session_service(cache, scheduler=None,
                                            counters=self.counters,
                                            **self._session_kwargs)
        gateway = TileGateway(cache, host="127.0.0.1", port=0,
                              counters=self.counters, sessions=service,
                              **self._gateway_kwargs)
        exporter = None
        sampler_task = None
        if self._exporter:
            # A scrapable replica: /varz + /timeseries with role
            # "gateway" so the fleet aggregator (obs/fleet.py) gives it
            # a gateway row with windowed latency percentiles.
            from distributedmandelbrot_tpu.obs.exporter import \
                MetricsExporter
            from distributedmandelbrot_tpu.obs.timeseries import \
                TimeseriesSampler
            sampler = TimeseriesSampler(self.counters.registry)
            exporter = MetricsExporter(
                self.counters.registry, sampler=sampler,
                varz_extra=lambda: {"role": "gateway"},
                host="127.0.0.1", port=0)
            await exporter.start()
            self.exporter_port = exporter.port
            sampler_task = asyncio.ensure_future(sampler.run())
        await gateway.start()
        self.port = gateway.port
        self._ready.set()
        try:
            await self._stop_event.wait()
        finally:
            if sampler_task is not None:
                sampler_task.cancel()
            if exporter is not None:
                await exporter.stop()
            await gateway.stop()


class GatewayFleet:
    """N gateway replicas sharing one object store; context-manageable."""

    def __init__(self, kv: ObjectStore, *, replicas: int = 2,
                 cache_tiles: int = 64, render_cache_tiles: int = 64,
                 max_queue_depth: int = 1024,
                 rate: Optional[float] = None, burst: float = 256.0,
                 read_timeout: Optional[float] = 30.0,
                 sessions: bool = False,
                 session_rate: Optional[float] = None,
                 session_burst: float = 32.0,
                 session_ttl: Optional[float] = 300.0,
                 prefetch_horizon: int = 3,
                 exporter: bool = False) -> None:
        if replicas < 1:
            raise ValueError(f"need >= 1 replica, got {replicas}")
        self.kv = kv
        self._replicas = [
            _Replica(kv, cache_tiles=cache_tiles,
                     render_cache_tiles=render_cache_tiles,
                     max_queue_depth=max_queue_depth, rate=rate,
                     burst=burst, read_timeout=read_timeout,
                     sessions=sessions, session_rate=session_rate,
                     session_burst=session_burst, session_ttl=session_ttl,
                     prefetch_horizon=prefetch_horizon, exporter=exporter)
            for _ in range(replicas)]

    def start(self) -> "GatewayFleet":
        started = []
        try:
            for replica in self._replicas:
                replica.start()
                started.append(replica)
        except BaseException:
            for replica in started:
                replica.stop()
            raise
        return self

    def stop(self) -> None:
        for replica in self._replicas:
            replica.stop()

    def __enter__(self) -> "GatewayFleet":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    @property
    def addresses(self) -> list[tuple[str, int]]:
        return [("127.0.0.1", r.port) for r in self._replicas
                if r.port is not None]

    @property
    def exporter_ports(self) -> list[int]:
        """Bound metrics-exporter ports (``exporter=True`` launches
        only) — feed these to a FleetAggregator as gateway peers."""
        return [r.exporter_port for r in self._replicas
                if r.exporter_port is not None]

    def counter(self, name: str) -> int:
        """Sum of one named counter across every replica."""
        return sum(r.counters.get(name) for r in self._replicas)
