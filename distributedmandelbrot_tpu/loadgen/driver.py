"""Asyncio gateway client for the load generator.

One connection per request, deliberately: an open-loop arrival models an
independent viewer showing up, and a shared pipelined socket would
serialize responses behind the slowest head-of-line tile — the viewer
client's behaviour, which is exactly what the storm harness exists to
NOT do.  Requests round-robin across replica addresses, which is the
whole multi-replica read story: any replica can serve any tile because
they share one object store.

Speaks both gateway framings: the 12-byte raw query (escape-count codec
payload back) and the rendered-tile query (``GATEWAY_RENDER_MAGIC`` +
14-byte tail, palette PNG back).  Response length words pass through the
sanctioned bound check before sizing a read, same as the viewer client.
"""

from __future__ import annotations

import asyncio
import itertools
from typing import Optional

from distributedmandelbrot_tpu.loadgen import recorder as rec
from distributedmandelbrot_tpu.net import framing
from distributedmandelbrot_tpu.net import protocol as proto

_STATUS_OUTCOMES = {
    proto.QUERY_OVERLOADED: rec.OUTCOME_SHED,
    proto.QUERY_NOT_AVAILABLE: rec.OUTCOME_UNAVAILABLE,
    proto.QUERY_REJECT: rec.OUTCOME_UNAVAILABLE,
}


class GatewayDriver:
    """Async request function over one or more gateway replicas.

    Instances are callable with ``(level, index_real, index_imag)`` and
    return ``(outcome, payload_bytes)`` in the recorder's vocabulary, so
    a driver plugs straight into :class:`~distributedmandelbrot_tpu.
    loadgen.runner.OpenLoopRunner`.
    """

    def __init__(self, addresses: list[tuple[str, int]], *,
                 render: bool = False,
                 colormap_id: int = proto.COLORMAP_JET,
                 timeout: Optional[float] = 30.0) -> None:
        if not addresses:
            raise ValueError("need at least one gateway address")
        self.addresses = list(addresses)
        self.render = render
        self.colormap_id = proto.validate_colormap(colormap_id)
        self.timeout = timeout
        self._rr = itertools.cycle(range(len(self.addresses)))

    async def __call__(self, level: int, index_real: int,
                       index_imag: int) -> tuple[str, int]:
        host, port = self.addresses[next(self._rr)]
        try:
            exchange = self._exchange(host, port, level, index_real,
                                      index_imag)
            if self.timeout is not None:
                return await asyncio.wait_for(exchange, self.timeout)
            return await exchange
        except (ConnectionError, OSError, TimeoutError,
                asyncio.TimeoutError, framing.ProtocolError):
            return rec.OUTCOME_ERROR, 0

    async def _exchange(self, host: str, port: int, level: int,
                        index_real: int, index_imag: int) -> tuple[str, int]:
        reader, writer = await asyncio.open_connection(host, port)
        try:
            self._send_query(writer, level, index_real, index_imag)
            await writer.drain()
            status = await framing.read_byte(reader)
            outcome = _STATUS_OUTCOMES.get(status)
            if outcome is not None:
                return outcome, 0
            if status != proto.QUERY_ACCEPT:
                raise framing.ProtocolError(
                    f"unknown query status {status:#x}")
            length = proto.validate_payload_length(
                await framing.read_u32(reader))
            payload = await framing.read_exact(reader, length)
            return rec.OUTCOME_OK, len(payload)
        finally:
            writer.close()
            try:
                await writer.wait_closed()
            except (ConnectionError, OSError):
                pass

    def _send_query(self, writer: asyncio.StreamWriter, level: int,
                    index_real: int, index_imag: int) -> None:
        if self.render:
            framing.write_u32(writer, proto.GATEWAY_RENDER_MAGIC)
            writer.write(proto.RENDER_QUERY_TAIL.pack(
                level, index_real, index_imag, self.colormap_id, 0))
        else:
            writer.write(proto.QUERY.pack(level, index_real, index_imag))
