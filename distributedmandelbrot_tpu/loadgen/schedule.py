"""Arrival schedules: phase specs, Poisson processes, Zipf tile choice.

A run is a list of :class:`Phase` segments played back to back.  Each
phase is a Poisson arrival process — ``steady`` and ``spike`` are
homogeneous (constant rate, sampled by exponential inter-arrival
inversion), ``ramp`` is inhomogeneous (linear rate sweep, sampled by
Lewis-Shedler thinning against the peak rate).  ``spike`` is just a
``steady`` with a scary name: keeping it a distinct kind makes the phase
labels on the latency histogram say what the operator meant.

Tile popularity is Zipfian: rank ``k`` of the level's ``level**2`` keys
is drawn with probability proportional to ``k**-s``, and a seeded
permutation maps ranks onto grid keys so the hot set is scattered across
the level instead of clustered at the origin (which would alias with any
spatial locality in the store layout).

Everything is driven by explicit seeds; the same spec + seed produces
the same schedule byte for byte, which the deterministic tests pin.
"""

from __future__ import annotations

import random
from dataclasses import dataclass
from typing import Optional

import numpy as np

PHASE_KINDS = ("steady", "spike", "ramp")


@dataclass(frozen=True)
class Phase:
    """One schedule segment: ``kind`` at ``rate`` (to ``rate_end`` for
    ramps) arrivals/second for ``duration`` seconds."""

    kind: str
    rate: float
    duration: float
    rate_end: Optional[float] = None
    name: str = ""

    def __post_init__(self) -> None:
        if self.kind not in PHASE_KINDS:
            raise ValueError(f"unknown phase kind {self.kind!r}")
        if self.rate < 0 or self.duration <= 0:
            raise ValueError(f"bad phase {self.kind}:{self.rate}x"
                             f"{self.duration}")
        if self.kind == "ramp" and self.rate_end is None:
            raise ValueError("ramp phase needs an end rate (lo-hi)")

    def rate_at(self, t: float) -> float:
        """Instantaneous rate ``t`` seconds into the phase."""
        if self.kind != "ramp":
            return self.rate
        frac = min(max(t / self.duration, 0.0), 1.0)
        return self.rate + (self.rate_end - self.rate) * frac

    @property
    def peak_rate(self) -> float:
        return max(self.rate, self.rate_end or self.rate)

    @property
    def mean_rate(self) -> float:
        if self.kind == "ramp":
            return (self.rate + self.rate_end) / 2.0
        return self.rate


def parse_phases(spec: str) -> list[Phase]:
    """Parse ``"steady:200x5,spike:2000x2,ramp:200-2000x5"`` into phases.

    Grammar per segment: ``kind:rate[-rate_end]xduration`` — rate in
    arrivals/second, duration in seconds, ``rate_end`` only meaningful
    (and required) for ``ramp``.  Phase names are ``{kind}{index}`` so a
    spec with two spikes labels them apart on the histogram.
    """
    phases: list[Phase] = []
    for index, segment in enumerate(s for s in spec.split(",") if s.strip()):
        try:
            kind, rest = segment.strip().split(":", 1)
            rates, duration = rest.split("x", 1)
            lo, _, hi = rates.partition("-")
            phases.append(Phase(
                kind=kind.strip(), rate=float(lo),
                rate_end=float(hi) if hi else None,
                duration=float(duration), name=f"{kind.strip()}{index}"))
        except (ValueError, TypeError) as e:
            raise ValueError(
                f"bad phase segment {segment!r} (want kind:rate[-hi]xdur, "
                f"e.g. steady:200x5 or ramp:200-2000x5): {e}") from e
    if not phases:
        raise ValueError(f"phase spec {spec!r} parsed to no phases")
    return phases


def poisson_arrivals(phases: list[Phase], *,
                     seed: int = 0) -> list[tuple[float, str]]:
    """Sample one arrival process: sorted ``(time, phase_name)`` pairs.

    Times are absolute seconds from the start of the run; each phase
    occupies ``[sum(prev durations), +duration)``.  Constant-rate phases
    use inter-arrival inversion; ramps thin a peak-rate process down to
    the instantaneous rate, which keeps one stream of randomness per
    phase and is exact for any bounded rate function.
    """
    rng = random.Random(seed)
    arrivals: list[tuple[float, str]] = []
    start = 0.0
    for phase in phases:
        end = start + phase.duration
        peak = phase.peak_rate
        t = start
        while peak > 0:
            t += rng.expovariate(peak)
            if t >= end:
                break
            if phase.kind == "ramp" \
                    and rng.random() * peak > phase.rate_at(t - start):
                continue  # thinned: candidate beyond the current rate
            arrivals.append((t, phase.name or phase.kind))
        start = end
    return arrivals


class ZipfTiles:
    """Zipf(s) sampler over a level's ``level**2`` tile keys.

    ``sample()`` returns ``(level, index_real, index_imag)``; rank ``k``
    (1-based) has probability proportional to ``k**-s``, mapped through a
    seeded permutation of the keyspace.  ``s`` around 1 matches web-like
    popularity (a handful of tiles soak most of the traffic — exactly
    the regime the rendered-tile cache exists for).
    """

    def __init__(self, level: int, *, s: float = 1.1, seed: int = 0) -> None:
        if level < 1:
            raise ValueError(f"level must be >= 1, got {level}")
        self.level = level
        self.s = s
        n = level * level
        weights = np.arange(1, n + 1, dtype=float) ** -float(s)
        self._cdf = np.cumsum(weights)
        self._cdf /= self._cdf[-1]
        self._rng = np.random.default_rng(seed)
        self._keys = self._rng.permutation(n)  # rank -> flat key index

    def sample(self) -> tuple[int, int, int]:
        rank = int(np.searchsorted(self._cdf, self._rng.random(),
                                   side="right"))
        flat = int(self._keys[min(rank, self._keys.size - 1)])
        return (self.level, flat // self.level, flat % self.level)

    def hottest(self, count: int) -> list[tuple[int, int, int]]:
        """The ``count`` most popular keys (rank order) — what a bench
        pre-seeds so the hot set serves from the store, not the farm."""
        out = []
        for flat in self._keys[:count]:
            flat = int(flat)
            out.append((self.level, flat // self.level, flat % self.level))
        return out


@dataclass(frozen=True)
class Request:
    """One scheduled query: issue at ``time`` (seconds from run start)."""

    time: float
    phase: str
    level: int
    index_real: int
    index_imag: int


def build_schedule(phases: list[Phase], sampler: ZipfTiles, *,
                   seed: int = 0) -> list[Request]:
    """Zip a Poisson arrival process with Zipf tile choices."""
    return [Request(t, name, *sampler.sample())
            for t, name in poisson_arrivals(phases, seed=seed)]


def offered_rate(schedule: list[Request]) -> float:
    """Mean offered load of a schedule (arrivals / spanned seconds)."""
    if not schedule:
        return 0.0
    span = schedule[-1].time - schedule[0].time
    if span <= 0:
        return float(len(schedule))
    return len(schedule) / span
