"""Session-trajectory workload model + the sticky session driver.

The storm harness's arrivals model anonymous independent viewers; an
interactive session is the opposite — one viewer issuing a *correlated*
stream of queries as they pan and zoom.  This module models a population
of such sessions: the aggregate arrival process is still Poisson (the
phase machinery unchanged), but each arrival is dealt to a session, and
a session's n-th query continues its own straight-line pan from a
Zipf-sampled anchor at a per-session velocity, *bouncing* off the
level's edges (a viewer pans, they don't teleport — a mod-level wrap
would poison the server's velocity estimate for a whole trajectory
window after every crossing).
``hot_share`` skews the deal toward session 0 — the flash-crowd
fairness scenario where one hot session would starve the rest without
per-session budgets.

:class:`SessionDriver` speaks the ``GATEWAY_SESSION_MAGIC`` framing
with two kinds of stickiness a real viewer has: a session always hits
the same replica (ids are per-gateway, not fleet-global), and its
queries are serialized per session (a viewer doesn't race itself), so
the server observes the trajectory in order — which is what makes the
predictor's velocity estimate, and therefore the measured prefetch hit
ratio, meaningful.  Different sessions still overlap freely; the storm
stays open-loop across the population.
"""

from __future__ import annotations

import asyncio
import random
from dataclasses import dataclass
from typing import Optional

from distributedmandelbrot_tpu.loadgen import recorder as rec
from distributedmandelbrot_tpu.loadgen.driver import _STATUS_OUTCOMES
from distributedmandelbrot_tpu.loadgen.runner import OpenLoopRunner
from distributedmandelbrot_tpu.loadgen.schedule import (Phase, Request,
                                                        ZipfTiles,
                                                        poisson_arrivals)
from distributedmandelbrot_tpu.net import framing
from distributedmandelbrot_tpu.net import protocol as proto


@dataclass(frozen=True)
class SessionRequest(Request):
    """One scheduled session query: a :class:`Request` plus the session
    slot (the model's stable viewer identity — the wire id is issued by
    whichever gateway the slot sticks to)."""

    session: int = 0


# Per-session pan velocities (tiles per query), drawn uniformly: the
# four cardinal pans and the four diagonals.
_VELOCITIES = ((1, 0), (-1, 0), (0, 1), (0, -1),
               (1, 1), (-1, -1), (1, -1), (-1, 1))


def _reflect(x: int, level: int) -> int:
    """Fold an unbounded pan coordinate into [0, level) by reflection
    (triangle wave of period 2*level): ... 5 6 7 7 6 5 ... at level 8."""
    m = x % (2 * level)
    return m if m < level else 2 * level - 1 - m


def build_session_schedule(phases: list[Phase], *, level: int,
                           sessions: int, seed: int = 0,
                           zipf_s: float = 1.1,
                           hot_share: float = 0.0) -> list[SessionRequest]:
    """Deal a Poisson arrival process onto panning sessions.

    Anchors are Zipf-sampled (sessions start where viewers start:
    on popular tiles), velocities are per-session, and the whole thing
    is seed-deterministic like :func:`~distributedmandelbrot_tpu.
    loadgen.schedule.build_schedule`.
    """
    if sessions < 1:
        raise ValueError(f"need >= 1 session, got {sessions}")
    if not 0.0 <= hot_share < 1.0:
        raise ValueError(f"hot_share must be in [0, 1), got {hot_share}")
    rng = random.Random(seed)
    sampler = ZipfTiles(level, s=zipf_s, seed=seed)
    anchors = [sampler.sample() for _ in range(sessions)]
    velocities = [_VELOCITIES[rng.randrange(len(_VELOCITIES))]
                  for _ in range(sessions)]
    counts = [0] * sessions
    schedule: list[SessionRequest] = []
    for t, name in poisson_arrivals(phases, seed=seed + 1):
        if hot_share > 0.0 and sessions > 1 and rng.random() < hot_share:
            slot = 0
        else:
            slot = rng.randrange(sessions)
        step = counts[slot]
        counts[slot] += 1
        _, anchor_real, anchor_imag = anchors[slot]
        d_real, d_imag = velocities[slot]
        schedule.append(SessionRequest(
            t, name, level,
            _reflect(anchor_real + step * d_real, level),
            _reflect(anchor_imag + step * d_imag, level),
            session=slot))
    return schedule


class SessionDriver:
    """Async request function speaking the session framing.

    Callable with a :class:`SessionRequest`; returns ``(outcome,
    nbytes)`` in the recorder's vocabulary, so it plugs into
    :class:`SessionRunner`.  ``ok_by_session`` accumulates per-slot
    goodput for the fairness-spread report.
    """

    def __init__(self, addresses: list[tuple[str, int]], *,
                 colormap_id: int = proto.COLORMAP_JET,
                 caps: int = proto.SESSION_CAPS_MASK,
                 timeout: Optional[float] = 30.0) -> None:
        if not addresses:
            raise ValueError("need at least one gateway address")
        self.addresses = list(addresses)
        self.colormap_id = proto.validate_colormap(colormap_id)
        self.caps = proto.validate_session_flags(caps)
        self.timeout = timeout
        self._sids: dict[int, int] = {}
        self._locks: dict[int, asyncio.Lock] = {}
        self.ok_by_session: dict[int, int] = {}
        self.shed_by_session: dict[int, int] = {}

    async def __call__(self, item: SessionRequest) -> tuple[str, int]:
        slot = item.session
        # Serialize per session so the gateway sees the pan in order;
        # an open-loop backlog queues here, and the wait is honestly
        # part of that session's latency.
        lock = self._locks.setdefault(slot, asyncio.Lock())
        async with lock:
            try:
                exchange = self._exchange(slot, item.level,
                                          item.index_real, item.index_imag)
                if self.timeout is not None:
                    return await asyncio.wait_for(exchange, self.timeout)
                return await exchange
            except (ConnectionError, OSError, TimeoutError,
                    asyncio.TimeoutError, framing.ProtocolError):
                return rec.OUTCOME_ERROR, 0

    async def _exchange(self, slot: int, level: int, index_real: int,
                        index_imag: int) -> tuple[str, int]:
        # Sticky replica: session ids are per-gateway state.
        host, port = self.addresses[slot % len(self.addresses)]
        reader, writer = await asyncio.open_connection(host, port)
        try:
            sid = self._sids.get(slot, 0)
            flags = self.caps if sid == 0 else 0
            framing.write_u32(writer, proto.GATEWAY_SESSION_MAGIC)
            writer.write(proto.SESSION_QUERY_TAIL.pack(
                sid, level, index_real, index_imag, self.colormap_id,
                flags))
            await writer.drain()
            raw = await framing.read_exact(reader,
                                           proto.SESSION_REPLY_WIRE_SIZE)
            new_sid, _caps = proto.SESSION_REPLY.unpack(raw)
            # 0 means the server dropped the session (TTL/LRU): reopen
            # on this slot's next query.
            self._sids[slot] = new_sid
            status = await framing.read_byte(reader)
            outcome = _STATUS_OUTCOMES.get(status)
            if outcome is not None:
                if outcome == rec.OUTCOME_SHED:
                    self.shed_by_session[slot] = \
                        self.shed_by_session.get(slot, 0) + 1
                return outcome, 0
            if status != proto.QUERY_ACCEPT:
                raise framing.ProtocolError(
                    f"unknown query status {status:#x}")
            length = proto.validate_payload_length(
                await framing.read_u32(reader))
            payload = await framing.read_exact(reader, length)
            self.ok_by_session[slot] = self.ok_by_session.get(slot, 0) + 1
            return rec.OUTCOME_OK, len(payload)
        finally:
            writer.close()
            try:
                await writer.wait_closed()
            except (ConnectionError, OSError):
                pass


class SessionRunner(OpenLoopRunner):
    """Open-loop runner whose request function takes the whole
    :class:`SessionRequest` (the driver needs the session slot, not just
    the key)."""

    async def _issue(self, item: Request) -> None:
        t0 = self.timebase.now()
        try:
            outcome, nbytes = await self.request(item)
        except asyncio.CancelledError:
            raise
        except Exception:
            outcome, nbytes = rec.OUTCOME_ERROR, 0
        finally:
            self._inflight -= 1
        self.recorder.record(item.phase, outcome,
                             self.timebase.now() - t0, nbytes)


def ok_spread(ok_by_session: dict[int, int],
              sessions: int) -> tuple[int, int]:
    """``(min, max)`` per-session OK counts over all ``sessions`` slots
    (absent slots count zero) — the bounded-spread fairness criterion
    compares these."""
    counts = [ok_by_session.get(slot, 0) for slot in range(sessions)]
    return min(counts), max(counts)
