"""Open-loop load generation for the gateway read path.

The viewer client is a *closed-loop* load source: it waits for each
response before sending the next query, so when the server slows down the
client automatically offers less — queue collapse is invisible.  A
million independent viewers don't behave that way: arrivals keep coming
at the population's rate no matter how slow responses get.  This package
models that: an **open-loop** runner issues requests on a precomputed
Poisson schedule regardless of in-flight count, tile choice follows a
Zipf popularity law over a level's keyspace, and flash crowds are
scripted as phases (``steady`` / ``spike`` / ``ramp``).

Layout:

- :mod:`.schedule` — phase spec parsing, Poisson arrival generation
  (inversion for constant-rate phases, thinning for ramps), the Zipf
  tile sampler, and :func:`build_schedule` tying them together;
- :mod:`.recorder` — latency/outcome recording into an
  :class:`~distributedmandelbrot_tpu.obs.metrics.Registry` (phase-labeled
  histogram + ``loadgen_*`` counters) and the end-of-run report
  (p50/p99/p999, goodput vs offered, shed fraction);
- :mod:`.runner` — the open-loop runner plus the real/virtual timebases
  (the virtual one makes schedule tests deterministic and instant);
- :mod:`.driver` — the asyncio gateway client (connection per request,
  round-robin across replicas, raw or rendered queries);
- :mod:`.replicas` — :class:`GatewayFleet`: N threaded gateway replicas
  over one shared object store, for horizontal read-scaling runs;
- :mod:`.trajectory` — the interactive-session workload model: panning
  trajectories dealt onto a Poisson arrival process, plus the sticky
  :class:`SessionDriver`/:class:`SessionRunner` pair speaking the
  session framing (prefetch hit ratio and fairness-spread runs).

Everything above imports without jax or matplotlib (``driver`` speaks
only the wire protocol; ``replicas`` rides the jax-free serve stack), so
``dmtpu loadgen --smoke`` runs in the lint-only CI environment.
"""

from distributedmandelbrot_tpu.loadgen.recorder import StormRecorder
from distributedmandelbrot_tpu.loadgen.runner import (OpenLoopRunner,
                                                      RealTimebase,
                                                      VirtualTimebase)
from distributedmandelbrot_tpu.loadgen.schedule import (Phase, Request,
                                                        ZipfTiles,
                                                        build_schedule,
                                                        parse_phases,
                                                        poisson_arrivals)
from distributedmandelbrot_tpu.loadgen.trajectory import (
    SessionDriver, SessionRequest, SessionRunner, build_session_schedule,
    ok_spread)

__all__ = [
    "Phase",
    "Request",
    "ZipfTiles",
    "build_schedule",
    "parse_phases",
    "poisson_arrivals",
    "StormRecorder",
    "OpenLoopRunner",
    "RealTimebase",
    "VirtualTimebase",
    "SessionDriver",
    "SessionRequest",
    "SessionRunner",
    "build_session_schedule",
    "ok_spread",
]
