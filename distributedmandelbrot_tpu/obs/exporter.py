"""Asyncio HTTP exporter: /metrics, /varz, /healthz on loopback.

A hand-rolled ~100-line HTTP/1.0 responder, not a web framework: the
package's only dependencies are numpy and jax, the endpoint serves three
GET routes to trusted scrapers, and the coordinator already owns an
asyncio loop for its two wire services — the exporter is just a third
``asyncio.start_server`` beside them (same lifecycle pattern as the
gateway, ephemeral port by default).

- ``/metrics`` — Prometheus text exposition format v0.0.4 (``# HELP`` /
  ``# TYPE``, ``_bucket{le=...}`` / ``_sum`` / ``_count`` for
  histograms), rendered by :func:`render_prometheus` so tests and
  ``tools/check_metrics.py`` can validate the text without a socket;
- ``/varz`` — JSON snapshot: every instrument, histogram percentiles,
  plus whatever the embedding coordinator contributes through the
  ``varz_extra`` callback (scheduler frontier depth, trace summaries);
- ``/healthz`` — liveness probe, ``ok``;
- ``/trace.json`` — the merged coordinator + worker timeline in Chrome
  trace-event JSON (obs/chrome.py), loadable at ui.perfetto.dev;
- ``/timeseries?name=&window=`` — ring-buffer history from the attached
  :class:`~distributedmandelbrot_tpu.obs.timeseries.TimeseriesSampler`
  (counter rates, gauge traces, histogram percentile series);
- ``/fleet`` — the merged fleet snapshot from an attached
  :class:`~distributedmandelbrot_tpu.obs.fleet.FleetAggregator`;
- ``/flight?window=`` — live flight-recorder ring snapshot from the
  attached :class:`~distributedmandelbrot_tpu.obs.flight.FlightRecorder`
  (the same header + events document the crash dumps carry);
- ``POST /checkpoint`` — on-demand durability checkpoint (admin-only
  write route, present iff the embedding coordinator supplies
  ``checkpoint_cb``; `dmtpu admin checkpoint` posts here).
"""

from __future__ import annotations

import asyncio
import json
import logging
import math
import re
import threading
import urllib.parse
from typing import Callable, Optional

from distributedmandelbrot_tpu.obs.chrome import render_chrome_trace
from distributedmandelbrot_tpu.obs.metrics import (Counter, Gauge, Histogram,
                                                   Registry)
from distributedmandelbrot_tpu.obs.spans import SpanStore, critical_path
from distributedmandelbrot_tpu.obs.trace import TraceLog

logger = logging.getLogger("dmtpu.exporter")

_NAME_OK = re.compile(r"[a-zA-Z_:][a-zA-Z0-9_:]*$")
_READ_TIMEOUT = 10.0


def _sanitize(name: str) -> str:
    """Exposition-legal metric name (ad-hoc shim counters may carry
    characters Prometheus grammar forbids)."""
    if _NAME_OK.match(name):
        return name
    cleaned = re.sub(r"[^a-zA-Z0-9_:]", "_", name)
    if not re.match(r"[a-zA-Z_:]", cleaned):
        cleaned = "_" + cleaned
    return cleaned


def _fmt(value: float) -> str:
    """Exposition float: integers without the trailing .0, specials in
    Prometheus spelling."""
    if math.isnan(value):
        return "NaN"
    if math.isinf(value):
        return "+Inf" if value > 0 else "-Inf"
    if float(value) == int(value) and abs(value) < 1e15:
        return str(int(value))
    return repr(float(value))


def _labels_str(labels, extra: str = "") -> str:
    parts = [f'{_sanitize(k)}="{v}"' for k, v in labels]
    if extra:
        parts.append(extra)
    return "{" + ",".join(parts) + "}" if parts else ""


def render_prometheus(registry: Registry) -> str:
    """The full registry in text exposition format v0.0.4."""
    lines: list[str] = []
    for name, kind, help_text, children in registry.collect():
        name = _sanitize(name)
        if help_text:
            lines.append(f"# HELP {name} {help_text}")
        lines.append(f"# TYPE {name} {kind}")
        for inst in children:
            if isinstance(inst, Counter):
                lines.append(f"{name}{_labels_str(inst.labels)} "
                             f"{_fmt(inst.value)}")
            elif isinstance(inst, Gauge):
                lines.append(f"{name}{_labels_str(inst.labels)} "
                             f"{_fmt(inst.read())}")
            elif isinstance(inst, Histogram):
                counts, total, count = inst.state()
                cum = 0
                for bound, c in zip(inst.bounds, counts):
                    cum += c
                    le = 'le="%s"' % _fmt(bound)
                    lines.append(
                        f"{name}_bucket{_labels_str(inst.labels, le)}"
                        f" {cum}")
                inf = 'le="+Inf"'
                lines.append(
                    f"{name}_bucket{_labels_str(inst.labels, inf)} {count}")
                lines.append(f"{name}_sum{_labels_str(inst.labels)} "
                             f"{_fmt(total)}")
                lines.append(f"{name}_count{_labels_str(inst.labels)} "
                             f"{count}")
    return "\n".join(lines) + "\n"


class MetricsExporter:
    """HTTP telemetry endpoint beside the coordinator's wire services.

    ``varz_extra`` (optional callable -> dict) runs on the exporter's
    event loop per /varz request, so a coordinator can report live
    scheduler state without locking; ``trace`` adds span/skew summaries.
    """

    def __init__(self, registry: Registry, *,
                 trace: Optional[TraceLog] = None,
                 spans: Optional[SpanStore] = None,
                 varz_extra: Optional[Callable[[], dict]] = None,
                 checkpoint_cb: Optional[Callable[[], "asyncio.Future"]]
                 = None,
                 sampler=None, fleet=None, flight=None,
                 host: str = "127.0.0.1", port: int = 0) -> None:
        self.registry = registry
        self.trace = trace
        self.spans = spans
        self.varz_extra = varz_extra
        # Optional TimeseriesSampler (/timeseries), FleetAggregator
        # (/fleet) and FlightRecorder (/flight) — duck-typed so the
        # exporter needs none of those modules.
        self.sampler = sampler
        self.fleet = fleet
        self.flight = flight
        # Async callable -> stats dict; enables the POST /checkpoint
        # admin route (the coordinator wires its RecoveryManager here).
        self.checkpoint_cb = checkpoint_cb
        self.host = host
        self.port = port
        self._server: Optional[asyncio.Server] = None

    async def start(self) -> None:
        self._server = await asyncio.start_server(
            self._handle, self.host, self.port)
        self.port = self._server.sockets[0].getsockname()[1]
        logger.info("metrics exporter on http://%s:%d (/metrics /varz "
                    "/healthz /trace.json)", self.host, self.port)

    async def stop(self) -> None:
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()

    # -- request handling --------------------------------------------------

    async def _handle(self, reader: asyncio.StreamReader,
                      writer: asyncio.StreamWriter) -> None:
        try:
            request = await asyncio.wait_for(reader.readline(),
                                             _READ_TIMEOUT)
            parts = request.decode("latin-1").split()
            if len(parts) < 2:
                return
            method = parts[0].upper()
            path, _, query = parts[1].partition("?")
            # Drain headers; every response closes the connection, so
            # nothing after the header block matters.
            while True:
                line = await asyncio.wait_for(reader.readline(),
                                              _READ_TIMEOUT)
                if line in (b"\r\n", b"\n", b""):
                    break
            if method == "POST" and path == "/checkpoint" \
                    and self.checkpoint_cb is not None:
                # The route takes no arguments; any request body goes
                # unread (HTTP/1.0 — the response closes the connection).
                try:
                    stats = await self.checkpoint_cb()
                    body = (json.dumps(stats, sort_keys=True) + "\n").encode()
                    self._respond(writer, 200, "application/json", body)
                except Exception as e:
                    logger.exception("on-demand checkpoint failed")
                    self._respond(writer, 500,
                                  "text/plain; charset=utf-8",
                                  f"checkpoint failed: {e}\n".encode())
            elif method not in ("GET", "HEAD"):
                self._respond(writer, 405, "text/plain; charset=utf-8",
                              b"method not allowed\n")
            elif path == "/metrics":
                body = render_prometheus(self.registry).encode()
                self._respond(writer, 200,
                              "text/plain; version=0.0.4; charset=utf-8",
                              body, head=method == "HEAD")
            elif path == "/varz":
                body = (json.dumps(self._varz(), indent=1, sort_keys=True)
                        + "\n").encode()
                self._respond(writer, 200, "application/json", body,
                              head=method == "HEAD")
            elif path == "/healthz":
                self._respond(writer, 200, "text/plain; charset=utf-8",
                              b"ok\n", head=method == "HEAD")
            elif path == "/trace.json":
                # Merged farm timeline in Chrome trace-event format —
                # what `dmtpu trace` fetches and ui.perfetto.dev loads.
                body = (json.dumps(render_chrome_trace(self.trace,
                                                       self.spans))
                        + "\n").encode()
                self._respond(writer, 200, "application/json", body,
                              head=method == "HEAD")
            elif path == "/timeseries" and self.sampler is not None:
                params = urllib.parse.parse_qs(query)
                name = (params.get("name") or [None])[0]
                window = None
                try:
                    raw = (params.get("window") or [None])[0]
                    if raw is not None:
                        window = max(0.0, float(raw))
                except ValueError:
                    window = None  # garbage window -> whole history
                doc = self.sampler.to_json(name, window=window)
                status = 404 if "error" in doc else 200
                body = (json.dumps(doc, sort_keys=True) + "\n").encode()
                self._respond(writer, status, "application/json", body,
                              head=method == "HEAD")
            elif path == "/flight" and self.flight is not None:
                params = urllib.parse.parse_qs(query)
                window = None
                try:
                    raw = (params.get("window") or [None])[0]
                    if raw is not None:
                        window = max(0.0, float(raw))
                except ValueError:
                    window = None  # garbage window -> whole ring
                doc = self.flight.snapshot(window=window)
                body = (json.dumps(doc, sort_keys=True) + "\n").encode()
                self._respond(writer, 200, "application/json", body,
                              head=method == "HEAD")
            elif path == "/fleet" and self.fleet is not None:
                body = (json.dumps(self.fleet.snapshot(), sort_keys=True)
                        + "\n").encode()
                self._respond(writer, 200, "application/json", body,
                              head=method == "HEAD")
            else:
                self._respond(writer, 404, "text/plain; charset=utf-8",
                              b"not found (try /metrics /varz /healthz "
                              b"/trace.json /timeseries /fleet /flight)\n")
            await writer.drain()
        except (ConnectionError, TimeoutError, asyncio.TimeoutError,
                asyncio.CancelledError):
            pass
        except Exception:
            logger.exception("exporter request failed")
        finally:
            writer.close()
            try:
                await writer.wait_closed()
            except (ConnectionError, OSError):
                pass

    def _respond(self, writer: asyncio.StreamWriter, status: int,
                 ctype: str, body: bytes, *, head: bool = False) -> None:
        reason = {200: "OK", 404: "Not Found", 405: "Method Not Allowed",
                  500: "Internal Server Error"}.get(status, "?")
        writer.write((f"HTTP/1.0 {status} {reason}\r\n"
                      f"Content-Type: {ctype}\r\n"
                      f"Content-Length: {len(body)}\r\n"
                      f"Connection: close\r\n\r\n").encode())
        if not head:
            writer.write(body)

    def _varz(self) -> dict:
        out = self.registry.snapshot()
        if self.trace is not None:
            spans = self.trace.spans()
            reported = (self.spans.compute_seconds_by_key()
                        if self.spans is not None else None)
            out["trace"] = {
                "recorded": self.trace.recorded,
                "dropped": self.trace.dropped,
                "spans": len(spans),
                "complete_spans": sum(1 for s in spans if s["complete"]),
                "worker_skew": self.trace.worker_skew(reported=reported),
            }
            if self.spans is not None:
                out["trace"]["span_store"] = {
                    "ingested": self.spans.ingested,
                    "workers": len(self.spans.workers()),
                    "unaligned": self.spans.unaligned,
                }
                out["farm_trace"] = critical_path(spans, self.spans)
        if self.varz_extra is not None:
            try:
                out.update(self.varz_extra())
            except Exception:
                logger.exception("varz_extra callback failed")
        return out


class ExporterThread:
    """A MetricsExporter on its own thread-owned loop, for processes
    with no asyncio loop of their own (the synchronous worker, bench
    harnesses).  start() blocks until the port is bound so the caller
    can immediately advertise it."""

    def __init__(self, registry: Registry, *,
                 varz_extra: Optional[Callable[[], dict]] = None,
                 sampler=None, fleet=None, flight=None,
                 host: str = "127.0.0.1", port: int = 0) -> None:
        self.registry = registry
        self.varz_extra = varz_extra
        self.sampler = sampler
        self.fleet = fleet
        self.flight = flight
        self.host = host
        self.port = port
        self._thread: Optional[threading.Thread] = None
        self._ready = threading.Event()
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        self._stop_event: Optional[asyncio.Event] = None
        self._startup_error: Optional[BaseException] = None

    def start(self, timeout: float = 10.0) -> None:
        self._thread = threading.Thread(target=self._run,
                                        name="dmtpu-exporter",
                                        daemon=True)
        self._thread.start()
        if not self._ready.wait(timeout):
            raise RuntimeError("exporter thread did not start in time")
        if self._startup_error is not None:
            raise RuntimeError("exporter thread failed to start") \
                from self._startup_error

    def stop(self, timeout: float = 10.0) -> None:
        if self._loop is not None and self._stop_event is not None:
            self._loop.call_soon_threadsafe(self._stop_event.set)
        if self._thread is not None:
            self._thread.join(timeout)
            self._thread = None

    def _run(self) -> None:
        try:
            asyncio.run(self._main())
        except BaseException as e:
            self._startup_error = e
            self._ready.set()

    async def _main(self) -> None:
        self._loop = asyncio.get_running_loop()
        self._stop_event = asyncio.Event()
        exporter = MetricsExporter(
            self.registry, varz_extra=self.varz_extra,
            sampler=self.sampler, fleet=self.fleet,
            flight=self.flight,
            host=self.host, port=self.port)
        await exporter.start()
        self.port = exporter.port
        sampler_task = (asyncio.create_task(self.sampler.run())
                        if self.sampler is not None else None)
        self._ready.set()
        try:
            await self._stop_event.wait()
        finally:
            if sampler_task is not None:
                sampler_task.cancel()
                try:
                    await sampler_task
                except asyncio.CancelledError:
                    pass
            await exporter.stop()
