"""Fleet aggregation: scrape every exporter, merge by role, serve /fleet.

One process's ``/varz`` answers "how is this shard doing"; nobody runs a
fleet off N browser tabs.  The aggregator is the single pane of glass:

- **discovery** — peers come from an explicit ``--peers`` list
  (``[role@]host:port`` specs), from a ``ring.json`` whose shard entries
  carry ``exporter_port`` (control/ring.py), or both; worker rows
  additionally come from the span-reported per-worker statistics every
  shard already publishes in its ``/varz`` (workers need no exporter of
  their own to be visible, though they may run one);
- **scraping** — plain bounded HTTP GETs of ``/varz`` (and, for
  gateway-bearing peers, ``/timeseries?name=gateway_request_seconds``
  for windowed latency percentiles).  All fetch failures are tolerated:
  the peer is marked stale, ``fleet_scrape_errors`` counts it, and the
  snapshot carries on with the peers that answered.  The fetch function
  is injectable, which is what the fuzz suite drives with malformed /
  truncated / oversized bodies;
- **merging** — per-role aggregates (shard grant throughput, gateway
  latency + cache hit ratios, worker tile rates), fleet totals
  (aggregate Mpix/s = tiles/s x CHUNK_PIXELS), queue depths, worst-case
  SLO burn across peers, and straggler flags (obs/slo.py detector over
  the merged worker rows);
- **serving** — ``snapshot()`` is the ``/fleet`` JSON; attach the
  aggregator to any exporter (``MetricsExporter(fleet=...)``) or run a
  standalone :class:`FleetService` (own thread + loop, the pattern of
  loadgen's replicas) when no coordinator loop is handy.

Rates are computed aggregator-side from its own scrape history
(monotonic counter deltas), so a version-skewed peer that predates
``/timeseries`` still contributes rates — only percentiles degrade.
"""

from __future__ import annotations

import asyncio
import json
import threading
import time
import urllib.error
import urllib.request
from collections import deque
from dataclasses import dataclass, field
from typing import Callable, Optional, Sequence

from distributedmandelbrot_tpu.core.geometry import CHUNK_PIXELS
from distributedmandelbrot_tpu.obs import names as obs_names
from distributedmandelbrot_tpu.obs.metrics import Registry
from distributedmandelbrot_tpu.obs.slo import detect_stragglers
from distributedmandelbrot_tpu.obs.timeseries import family_of

DEFAULT_RATE_WINDOW = 60.0
DEFAULT_SCRAPE_TIMEOUT = 2.0
# A /varz of a busy coordinator is a few tens of KB; 4 MiB is two orders
# of magnitude of headroom, and anything past it is a bug or an attack.
MAX_SCRAPE_BYTES = 4 << 20
# Scrape history per peer: enough for a 1h slow window at 2s scrapes
# would be 1800 entries; 512 bounds memory while covering the rate
# windows the dashboard actually renders.
_HISTORY_CAP = 512

ROLE_SHARD = "shard"
ROLE_COORDINATOR = "coordinator"
ROLE_GATEWAY = "gateway"
ROLE_WORKER = "worker"
ROLE_FLEET = "fleet"


class ScrapeError(Exception):
    """A peer fetch failed or returned something unusable."""


def http_fetch(url: str, timeout: float = DEFAULT_SCRAPE_TIMEOUT,
               max_bytes: int = MAX_SCRAPE_BYTES) -> bytes:
    """Bounded GET; the aggregator's default fetch function."""
    req = urllib.request.Request(url,
                                 headers={"User-Agent": "dmtpu-fleet"})
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            body = resp.read(max_bytes + 1)
    except (urllib.error.URLError, OSError, ValueError) as e:
        raise ScrapeError(str(e)) from None
    if len(body) > max_bytes:
        raise ScrapeError(f"body exceeds {max_bytes} bytes")
    return body


def parse_peer_spec(spec: str) -> tuple[str, Optional[str]]:
    """``[role@]host:port`` or ``[role@]http://host:port`` ->
    ``(base_url, role_hint)``."""
    role: Optional[str] = None
    if "@" in spec and "://" not in spec.split("@", 1)[0]:
        role, spec = spec.split("@", 1)
        role = role.strip() or None
    if not spec.startswith("http://") and not spec.startswith("https://"):
        spec = "http://" + spec
    return spec.rstrip("/"), role


def _num(value) -> Optional[float]:
    """Tolerant numeric read for version-skewed payloads."""
    if isinstance(value, bool) or not isinstance(value, (int, float)):
        return None
    return float(value)


@dataclass
class PeerState:
    """One scraped exporter; history feeds aggregator-side rates."""

    url: str
    role_hint: Optional[str] = None
    role: str = "unknown"
    varz: Optional[dict] = None
    latency_doc: Optional[dict] = None
    last_ok: Optional[float] = None
    scrapes: int = 0
    consecutive_errors: int = 0
    last_error: Optional[str] = None
    # (ts, counter family sums, histogram family counts, worker rows)
    history: deque = field(default_factory=lambda: deque(
        maxlen=_HISTORY_CAP))

    @property
    def healthy(self) -> bool:
        return self.last_ok is not None and self.consecutive_errors == 0

    @property
    def stale(self) -> bool:
        return self.consecutive_errors >= 2 or self.last_ok is None


def _infer_role(varz: dict) -> str:
    if not isinstance(varz, dict):
        return "unknown"
    role = varz.get("role")
    if isinstance(role, str) and role:
        return role
    if "shard" in varz:
        return ROLE_SHARD
    if "worker_id" in varz:
        return ROLE_WORKER
    if "scheduler" in varz:
        return ROLE_COORDINATOR
    counters = varz.get("counters")
    if isinstance(counters, dict) and any(
            family_of(k) == obs_names.GATEWAY_QUERIES for k in counters):
        return ROLE_GATEWAY
    return "unknown"


class FleetAggregator:
    """Scrapes peers, keeps bounded per-peer history, merges a fleet
    snapshot.  Thread contract: ``scrape_once`` runs on one scraping
    thread at a time (CLI thread, or FleetService's executor), while
    ``snapshot`` may run concurrently on an exporter loop — shared
    state is guarded by one lock, and network fetches NEVER happen
    under it."""

    def __init__(self, peers: Sequence[str] = (), *,
                 registry: Optional[Registry] = None,
                 rate_window: float = DEFAULT_RATE_WINDOW,
                 timeout: float = DEFAULT_SCRAPE_TIMEOUT,
                 fetch: Callable[..., bytes] = http_fetch,
                 clock: Callable[[], float] = time.monotonic) -> None:
        self.registry = registry if registry is not None else Registry()
        self.rate_window = float(rate_window)
        self.timeout = float(timeout)
        self.fetch = fetch
        self.clock = clock
        self._lock = threading.Lock()
        self._peers: dict[str, PeerState] = {}
        for spec in peers:
            self.add_peer(spec)

    def add_peer(self, spec: str) -> None:
        url, role_hint = parse_peer_spec(spec)
        with self._lock:
            if url not in self._peers:
                self._peers[url] = PeerState(url, role_hint,
                                             role=role_hint or "unknown")

    @classmethod
    def from_ring(cls, ring, **kwargs) -> "FleetAggregator":
        """Peers from a HashRing whose shards carry exporter ports;
        shards with no exporter bound (port 0) are skipped."""
        agg = cls(**kwargs)
        for info in ring.shards:
            port = getattr(info, "exporter_port", 0)
            if port:
                agg.add_peer(f"{ROLE_SHARD}@{info.host}:{port}")
        return agg

    @property
    def peer_urls(self) -> list[str]:
        with self._lock:
            return sorted(self._peers)

    # -- scraping ----------------------------------------------------------

    def scrape_once(self) -> None:
        """One scrape round over every peer; never raises for peer
        failures (fleet_scrape_errors counts them instead)."""
        with self._lock:
            peers = list(self._peers.values())
        for peer in peers:
            t0 = time.monotonic()
            self._scrape_peer(peer)
            self.registry.observe(obs_names.HIST_FLEET_SCRAPE_SECONDS,
                                  time.monotonic() - t0)
        with self._lock:
            stale = sum(1 for p in self._peers.values() if p.stale)
        self.registry.set_gauge(obs_names.GAUGE_FLEET_PEERS,
                                len(peers))
        self.registry.set_gauge(obs_names.GAUGE_FLEET_PEERS_STALE, stale)
        self.registry.inc(obs_names.FLEET_SCRAPES)

    def _scrape_peer(self, peer: PeerState) -> None:
        try:
            body = self.fetch(peer.url + "/varz", self.timeout)
            varz = json.loads(body.decode("utf-8", errors="replace"))
            if not isinstance(varz, dict):
                raise ScrapeError(
                    f"/varz is {type(varz).__name__}, not an object")
        except (ScrapeError, UnicodeError, json.JSONDecodeError,
                OSError) as e:
            self.registry.inc(obs_names.FLEET_SCRAPE_ERRORS)
            with self._lock:
                peer.consecutive_errors += 1
                peer.last_error = str(e)[:200]
            return
        role = _infer_role(varz)
        if role == "unknown" and peer.role_hint:
            role = peer.role_hint
        latency_doc = None
        if role == ROLE_GATEWAY or (
                role in (ROLE_COORDINATOR, ROLE_SHARD)
                and obs_names.GATEWAY_QUERIES in _counter_families(varz)):
            # Windowed latency percentiles ride /timeseries — but only
            # for peers actually serving gateway traffic; a pure shard
            # has no request histogram, and fetching would double every
            # scrape's cost for nothing.  Peers that predate
            # /timeseries (version skew) just lose the percentile
            # columns.
            try:
                ts_body = self.fetch(
                    peer.url + "/timeseries?name="
                    + obs_names.HIST_GATEWAY_REQUEST_SECONDS
                    + f"&window={self.rate_window:g}", self.timeout)
                doc = json.loads(ts_body.decode("utf-8",
                                                errors="replace"))
                if isinstance(doc, dict) and "error" not in doc:
                    latency_doc = doc
            except (ScrapeError, UnicodeError, json.JSONDecodeError,
                    OSError):
                pass
        now = self.clock()
        entry = (now, _counter_families(varz), _hist_counts(varz),
                 _worker_rows(varz))
        with self._lock:
            peer.role = role
            peer.varz = varz
            peer.latency_doc = latency_doc
            peer.last_ok = now
            peer.scrapes += 1
            peer.consecutive_errors = 0
            peer.last_error = None
            peer.history.append(entry)

    # -- derived math ------------------------------------------------------

    def _peer_rate(self, peer: PeerState, family: str, *,
                   now: Optional[float] = None) -> float:
        """Counter-family rate from this peer's scrape history (first vs
        last point in the trailing rate window)."""
        if now is None:
            now = self.clock()
        cutoff = now - self.rate_window
        pts = [(ts, fams.get(family)) for ts, fams, _, _ in peer.history
               if ts >= cutoff and family in fams]
        if len(pts) < 2:
            return 0.0
        (t0, v0), (t1, v1) = pts[0], pts[-1]
        if t1 <= t0:
            return 0.0
        return max(0.0, (v1 - v0) / (t1 - t0))

    def _hist_rate(self, peer: PeerState, family: str, *,
                   now: Optional[float] = None) -> float:
        if now is None:
            now = self.clock()
        cutoff = now - self.rate_window
        pts = [(ts, hists.get(family)) for ts, _, hists, _ in peer.history
               if ts >= cutoff and family in hists]
        if len(pts) < 2:
            return 0.0
        (t0, v0), (t1, v1) = pts[0], pts[-1]
        if t1 <= t0:
            return 0.0
        return max(0.0, (v1 - v0) / (t1 - t0))

    def _worker_rates(self, now: float) -> dict[str, float]:
        """Per-worker tiles/s from span-reported cumulative tile counts,
        summed across the shards a multi-homed worker reports to."""
        cutoff = now - self.rate_window
        series: dict[str, list[tuple[float, float]]] = {}
        with self._lock:
            peers = list(self._peers.values())
            histories = {p.url: list(p.history) for p in peers}
        # Merge per scrape-round: entries across peers interleave by ts.
        merged: dict[float, dict[str, float]] = {}
        for url, history in histories.items():
            for ts, _, _, workers in history:
                if ts < cutoff or not workers:
                    continue
                bucket = merged.setdefault(round(ts, 1), {})
                for wid, row in workers.items():
                    tiles = _num(row.get("tiles"))
                    if tiles is not None:
                        bucket[wid] = bucket.get(wid, 0.0) + tiles
        for ts in sorted(merged):
            for wid, tiles in merged[ts].items():
                series.setdefault(wid, []).append((ts, tiles))
        rates: dict[str, float] = {}
        for wid, pts in series.items():
            if len(pts) < 2 or pts[-1][0] <= pts[0][0]:
                rates[wid] = 0.0
            else:
                (t0, v0), (t1, v1) = pts[0], pts[-1]
                rates[wid] = max(0.0, (v1 - v0) / (t1 - t0))
        return rates

    # -- snapshot ----------------------------------------------------------

    def snapshot(self) -> dict:
        """The ``/fleet`` document: peers, per-role aggregates, fleet
        totals, merged worker rows with straggler flags, SLO summary."""
        now = self.clock()
        with self._lock:
            peers = list(self._peers.values())
        peer_rows = []
        shards = []
        gateways = []
        worker_rows: dict[str, dict] = {}
        slo_entries: list[dict] = []
        totals = {"tiles_per_s": 0.0, "grants_per_s": 0.0,
                  "queries_per_s": 0.0, "persist_queue_depth": 0.0,
                  "completed": 0, "total_tiles": 0}
        for peer in peers:
            with self._lock:
                role = peer.role
                varz = peer.varz
                latency_doc = peer.latency_doc
                age = None if peer.last_ok is None else now - peer.last_ok
                row = {"url": peer.url, "role": role,
                       "healthy": peer.healthy, "stale": peer.stale,
                       "scrapes": peer.scrapes,
                       "errors": peer.consecutive_errors,
                       "last_error": peer.last_error,
                       "age_s": None if age is None else round(age, 1)}
            peer_rows.append(row)
            if varz is None:
                continue
            for entry in varz.get("slo") or []:
                if isinstance(entry, dict):
                    slo_entries.append({**entry, "peer": peer.url})
            gauges = varz.get("gauges") if isinstance(
                varz.get("gauges"), dict) else {}
            if role in (ROLE_SHARD, ROLE_COORDINATOR):
                shards.append(self._shard_row(peer, varz, gauges, now))
            # Any peer serving gateway traffic gets a gateway row — a
            # dedicated replica, or a coordinator/shard with its
            # gateway enabled (single-process deployments).
            if role == ROLE_GATEWAY or \
                    obs_names.GATEWAY_QUERIES in _counter_families(varz):
                gateways.append(self._gateway_row(peer, varz, gauges,
                                                  latency_doc, now))
            for wid, raw in _worker_rows(varz).items():
                merged = worker_rows.setdefault(
                    wid, {"worker": wid, "tiles": 0, "compute_s": 0.0,
                          "upload_s": 0.0, "lease_to_persist_s": 0.0,
                          "via": []})
                merged["tiles"] += int(_num(raw.get("tiles")) or 0)
                for fld in ("compute_s", "upload_s",
                            "lease_to_persist_s"):
                    merged[fld] += _num(raw.get(fld)) or 0.0
                merged["via"].append(peer.url)
        for s_row in shards:
            totals["tiles_per_s"] += s_row["tiles_per_s"]
            totals["grants_per_s"] += s_row["grants_per_s"]
            totals["persist_queue_depth"] += s_row["persist_queue_depth"]
            totals["completed"] += s_row["completed"]
            totals["total_tiles"] += s_row["total"]
        for g_row in gateways:
            totals["queries_per_s"] += g_row["queries_per_s"]
        worker_rates = self._worker_rates(now)
        stragglers = detect_stragglers(list(worker_rows.values()))
        self.registry.set_gauge(obs_names.GAUGE_FLEET_STRAGGLERS,
                                len(stragglers))
        workers_out = []
        for wid in sorted(worker_rows):
            row = worker_rows[wid]
            tiles = row["tiles"]
            workers_out.append({
                "worker": wid, "tiles": tiles,
                "via": sorted(set(row["via"])),
                "tiles_per_s": round(worker_rates.get(wid, 0.0), 4),
                "compute_s_per_tile": round(
                    row["compute_s"] / tiles, 4) if tiles else 0.0,
                "lease_to_persist_s_per_tile": round(
                    row["lease_to_persist_s"] / tiles, 4) if tiles
                else 0.0,
                "straggler": wid in stragglers,
                "straggler_reasons": stragglers.get(wid, []),
            })
        mpix = totals["tiles_per_s"] * CHUNK_PIXELS / 1e6
        roles: dict[str, dict] = {}
        for row in peer_rows:
            r = roles.setdefault(row["role"], {"count": 0, "healthy": 0})
            r["count"] += 1
            r["healthy"] += 1 if row["healthy"] else 0
        if workers_out:
            roles.setdefault(ROLE_WORKER, {"count": 0, "healthy": 0})
            roles[ROLE_WORKER]["count"] = max(
                roles[ROLE_WORKER]["count"], len(workers_out))
            roles[ROLE_WORKER]["healthy"] = max(
                roles[ROLE_WORKER]["healthy"],
                sum(1 for w in workers_out if w["tiles_per_s"] > 0))
        return {
            "ts": round(now, 3),
            "rate_window_s": self.rate_window,
            "peers": peer_rows,
            "roles": roles,
            "totals": {
                "mpix_per_s": round(mpix, 3),
                "tiles_per_s": round(totals["tiles_per_s"], 4),
                "grants_per_s": round(totals["grants_per_s"], 4),
                "queries_per_s": round(totals["queries_per_s"], 4),
                "persist_queue_depth": totals["persist_queue_depth"],
                "completed": totals["completed"],
                "total_tiles": totals["total_tiles"],
            },
            "shards": shards,
            "gateways": gateways,
            "workers": workers_out,
            "stragglers": sorted(stragglers),
            "slo": _summarize_slo(slo_entries),
        }

    def _shard_row(self, peer: PeerState, varz: dict, gauges: dict,
                   now: float) -> dict:
        sched = varz.get("scheduler") if isinstance(
            varz.get("scheduler"), dict) else {}
        shard_doc = varz.get("shard") if isinstance(
            varz.get("shard"), dict) else {}
        return {
            "url": peer.url,
            "shard": shard_doc.get("shard"),
            "n_shards": shard_doc.get("n_shards"),
            "grants_per_s": round(self._peer_rate(
                peer, obs_names.COORD_WORKLOADS_GRANTED, now=now), 4),
            "tiles_per_s": round(self._peer_rate(
                peer, obs_names.COORD_CHUNKS_SAVED, now=now), 4),
            "frontier_depth": _num(gauges.get(
                obs_names.GAUGE_FRONTIER_DEPTH)) or 0.0,
            "outstanding_leases": _num(gauges.get(
                obs_names.GAUGE_OUTSTANDING_LEASES)) or 0.0,
            "persist_queue_depth": _num(gauges.get(
                obs_names.GAUGE_PERSIST_QUEUE_DEPTH)) or 0.0,
            "completed": int(_num(sched.get("completed")) or 0),
            "total": int(_num(sched.get("total")) or 0),
            "workers": len(_worker_rows(varz)),
        }

    def _gateway_row(self, peer: PeerState, varz: dict, gauges: dict,
                     latency_doc: Optional[dict], now: float) -> dict:
        row = {
            "url": peer.url,
            "queries_per_s": round(self._peer_rate(
                peer, obs_names.GATEWAY_QUERIES, now=now), 4),
            "served_per_s": round(self._peer_rate(
                peer, obs_names.GATEWAY_SERVED, now=now), 4),
            "tier1_hit_ratio": _num(gauges.get(
                obs_names.GAUGE_TIER1_HIT_RATIO)),
            "render_hit_ratio": _num(gauges.get(
                obs_names.GAUGE_RENDER_HIT_RATIO)),
            "sessions_active": _num(gauges.get(
                obs_names.GAUGE_SESSIONS_ACTIVE)),
            "p50_s": None, "p99_s": None,
        }
        if latency_doc is not None:
            row["p50_s"] = _num(latency_doc.get("window_p50"))
            row["p99_s"] = _num(latency_doc.get("window_p99"))
        return row


def _counter_families(varz: dict) -> dict[str, float]:
    counters = varz.get("counters")
    out: dict[str, float] = {}
    if not isinstance(counters, dict):
        return out
    for label, value in counters.items():
        v = _num(value)
        if v is None or not isinstance(label, str):
            continue
        fam = family_of(label)
        out[fam] = out.get(fam, 0.0) + v
    return out


def _hist_counts(varz: dict) -> dict[str, float]:
    hists = varz.get("histograms")
    out: dict[str, float] = {}
    if not isinstance(hists, dict):
        return out
    for label, doc in hists.items():
        if not isinstance(label, str) or not isinstance(doc, dict):
            continue
        v = _num(doc.get("count"))
        if v is None:
            continue
        fam = family_of(label)
        out[fam] = out.get(fam, 0.0) + v
    return out


def _worker_rows(varz: dict) -> dict[str, dict]:
    workers = varz.get("workers")
    out: dict[str, dict] = {}
    if not isinstance(workers, dict):
        return out
    for wid, row in workers.items():
        if isinstance(row, dict):
            out[str(wid)] = row
    return out


def _summarize_slo(entries: list[dict]) -> dict:
    """Worst-case view across peers: per SLO name, the max burns and
    the most alarmed state (firing > hold > ok)."""
    rank = {"ok": 0, "hold": 1, "firing": 2}
    by_name: dict[str, dict] = {}
    for entry in entries:
        name = entry.get("name")
        if not isinstance(name, str):
            continue
        cur = by_name.setdefault(name, {
            "name": name, "state": "ok", "fast_burn": 0.0,
            "slow_burn": 0.0, "objective": entry.get("objective"),
            "peers": 0})
        cur["peers"] += 1
        state = entry.get("state")
        if rank.get(state, 0) > rank.get(cur["state"], 0):
            cur["state"] = state
        for win, key in (("fast", "fast_burn"), ("slow", "slow_burn")):
            doc = entry.get(win)
            if isinstance(doc, dict):
                burn = _num(doc.get("burn"))
                if burn is not None:
                    cur[key] = max(cur[key], burn)
    return {"slos": [by_name[n] for n in sorted(by_name)],
            "worst_state": max((d["state"] for d in by_name.values()),
                               key=lambda s: rank.get(s, 0),
                               default="ok")}


class FleetService:
    """Standalone fleet endpoint: own thread, own loop, an exporter
    serving ``/fleet`` (+ the aggregator's own registry on ``/varz``)
    and a scrape loop driving the aggregator.  Same thread-owned-loop
    lifecycle as loadgen's gateway replicas; scrapes run through the
    loop's executor so the blocking HTTP never stalls the exporter."""

    def __init__(self, aggregator: FleetAggregator, *,
                 scrape_period: float = 2.0, host: str = "127.0.0.1",
                 port: int = 0) -> None:
        self.aggregator = aggregator
        self.scrape_period = float(scrape_period)
        self.host = host
        self.port = port
        self._thread: Optional[threading.Thread] = None
        self._ready = threading.Event()
        self._stop = threading.Event()
        self._startup_error: Optional[BaseException] = None

    def start(self, timeout: float = 10.0) -> None:
        self._thread = threading.Thread(target=self._run,
                                        name="fleet-service", daemon=True)
        self._thread.start()
        if not self._ready.wait(timeout):
            raise RuntimeError("fleet service did not start in time")
        if self._startup_error is not None:
            raise RuntimeError("fleet service failed to start") \
                from self._startup_error

    def stop(self, timeout: float = 10.0) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout)
            self._thread = None

    def _run(self) -> None:
        try:
            asyncio.run(self._main())
        except BaseException as e:  # surfaced by start() when early
            self._startup_error = e
            self._ready.set()

    async def _main(self) -> None:
        # Local import: exporter imports chrome/trace machinery the
        # aggregator itself never needs.
        from distributedmandelbrot_tpu.obs.exporter import MetricsExporter
        exporter = MetricsExporter(
            self.aggregator.registry, fleet=self.aggregator,
            varz_extra=lambda: {"role": ROLE_FLEET},
            host=self.host, port=self.port)
        await exporter.start()
        self.port = exporter.port
        self._ready.set()
        loop = asyncio.get_running_loop()
        next_scrape = 0.0
        try:
            while not self._stop.is_set():
                now = time.monotonic()
                if now >= next_scrape:
                    await loop.run_in_executor(
                        None, self.aggregator.scrape_once)
                    next_scrape = time.monotonic() + self.scrape_period
                await asyncio.sleep(0.05)
        finally:
            await exporter.stop()
