"""`dmtpu top`: ANSI fleet dashboard over /fleet snapshots.

Curses-free on purpose: the renderer is a pure function from a fleet
snapshot (obs/fleet.py) to a string, so it runs identically in the live
loop (clear screen + reprint every interval), in ``--once`` mode for CI
pipelines, and in tests (assert on substrings, no pty needed).  Color
is plain SGR codes behind a flag; ``--no-color`` / non-tty output stays
grep-able.
"""

from __future__ import annotations

from typing import Optional

_RESET = "\x1b[0m"
_BOLD = "\x1b[1m"
_RED = "\x1b[31m"
_GREEN = "\x1b[32m"
_YELLOW = "\x1b[33m"
_DIM = "\x1b[2m"

CLEAR_SCREEN = "\x1b[H\x1b[2J"


def _paint(text: str, code: str, color: bool) -> str:
    return f"{code}{text}{_RESET}" if color else text


def _state_paint(state: str, color: bool) -> str:
    code = {"ok": _GREEN, "hold": _YELLOW, "firing": _RED}.get(state,
                                                               _YELLOW)
    return _paint(state, code, color)


def _num(v, nd: int = 1, unit: str = "") -> str:
    if v is None:
        return "-"
    try:
        return f"{float(v):.{nd}f}{unit}"
    except (TypeError, ValueError):
        return "-"


def _ms(v) -> str:
    if v is None:
        return "-"
    try:
        return f"{float(v) * 1e3:.1f}"
    except (TypeError, ValueError):
        return "-"


def _short_url(url: str) -> str:
    return url.replace("http://", "").replace("https://", "")


def render_top(snap: dict, *, color: bool = True) -> str:
    """One full dashboard frame from a /fleet snapshot."""
    lines: list[str] = []
    peers = snap.get("peers") or []
    totals = snap.get("totals") or {}
    healthy = sum(1 for p in peers if p.get("healthy"))
    stale = sum(1 for p in peers if p.get("stale"))
    head = (f"dmtpu top · {len(peers)} peers "
            f"({healthy} healthy, {stale} stale) · "
            f"{_num(totals.get('mpix_per_s'))} Mpix/s · "
            f"{_num(totals.get('grants_per_s'))} grants/s · "
            f"{_num(totals.get('queries_per_s'))} q/s · "
            f"{totals.get('completed', 0)}/{totals.get('total_tiles', 0)}"
            f" tiles")
    lines.append(_paint(head, _BOLD, color))

    roles = snap.get("roles") or {}
    if roles:
        parts = [f"{name}={doc.get('healthy', 0)}/{doc.get('count', 0)}"
                 for name, doc in sorted(roles.items())]
        lines.append(_paint("roles  " + "  ".join(parts), _DIM, color))

    slo = snap.get("slo") or {}
    slos = slo.get("slos") or []
    if slos:
        lines.append("")
        lines.append(_paint(
            f"{'SLO':<28} {'state':<8} {'fast burn':>10} "
            f"{'slow burn':>10} {'objective':>10}", _BOLD, color))
        for entry in slos:
            state = str(entry.get("state", "?"))
            lines.append(
                f"{str(entry.get('name', '?')):<28} "
                f"{_state_paint(f'{state:<8}', color)} "
                f"{_num(entry.get('fast_burn'), 2):>10} "
                f"{_num(entry.get('slow_burn'), 2):>10} "
                f"{_num(entry.get('objective'), 3):>10}")

    shards = snap.get("shards") or []
    if shards:
        lines.append("")
        lines.append(_paint(
            f"{'SHARD':<6} {'endpoint':<24} {'grants/s':>9} "
            f"{'tiles/s':>8} {'frontier':>9} {'leases':>7} {'queue':>6} "
            f"{'done/total':>12} {'wkrs':>5}", _BOLD, color))
        for row in shards:
            shard_id = row.get("shard")
            lines.append(
                f"{('-' if shard_id is None else str(shard_id)):<6} "
                f"{_short_url(str(row.get('url', ''))):<24} "
                f"{_num(row.get('grants_per_s')):>9} "
                f"{_num(row.get('tiles_per_s'), 2):>8} "
                f"{_num(row.get('frontier_depth'), 0):>9} "
                f"{_num(row.get('outstanding_leases'), 0):>7} "
                f"{_num(row.get('persist_queue_depth'), 0):>6} "
                f"{str(row.get('completed', 0)) + '/' + str(row.get('total', 0)):>12} "
                f"{row.get('workers', 0):>5}")

    gateways = snap.get("gateways") or []
    if gateways:
        lines.append("")
        lines.append(_paint(
            f"{'GATEWAY':<24} {'q/s':>8} {'served/s':>9} "
            f"{'p50 ms':>8} {'p99 ms':>8} {'t1 hit':>7} {'rnd hit':>8} "
            f"{'sess':>5}", _BOLD, color))
        for row in gateways:
            lines.append(
                f"{_short_url(str(row.get('url', ''))):<24} "
                f"{_num(row.get('queries_per_s')):>8} "
                f"{_num(row.get('served_per_s')):>9} "
                f"{_ms(row.get('p50_s')):>8} "
                f"{_ms(row.get('p99_s')):>8} "
                f"{_num(row.get('tier1_hit_ratio'), 2):>7} "
                f"{_num(row.get('render_hit_ratio'), 2):>8} "
                f"{_num(row.get('sessions_active'), 0):>5}")

    workers = snap.get("workers") or []
    if workers:
        lines.append("")
        lines.append(_paint(
            f"{'WORKER':<18} {'tiles':>6} {'tiles/s':>8} "
            f"{'s/tile':>8} {'lease→persist':>14} {'straggler':>10}",
            _BOLD, color))
        for row in workers:
            if row.get("straggler"):
                flag = _paint(
                    "YES " + ",".join(row.get("straggler_reasons") or []),
                    _RED, color)
            else:
                flag = _paint("-", _DIM, color)
            lines.append(
                f"{str(row.get('worker', '?')):<18} "
                f"{row.get('tiles', 0):>6} "
                f"{_num(row.get('tiles_per_s'), 2):>8} "
                f"{_num(row.get('compute_s_per_tile'), 3):>8} "
                f"{_num(row.get('lease_to_persist_s_per_tile'), 3):>14} "
                f"{flag:>10}")

    bad_peers = [p for p in peers if p.get("stale") or not
                 p.get("healthy")]
    if bad_peers:
        lines.append("")
        lines.append(_paint("UNHEALTHY PEERS", _BOLD, color))
        for p in bad_peers:
            detail = p.get("last_error") or "no successful scrape yet"
            lines.append(_paint(
                f" {_short_url(str(p.get('url', '')))} "
                f"[{p.get('role', '?')}] errors={p.get('errors', 0)} "
                f"{detail}", _RED, color))

    return "\n".join(lines) + "\n"


def render_frame(snap: dict, *, color: bool = True,
                 clear: bool = False) -> str:
    """A live-loop frame: optional clear-screen prefix + the dashboard."""
    prefix = CLEAR_SCREEN if clear else ""
    return prefix + render_top(snap, color=color)
