"""Canonical metric names, in one place so layers can never drift apart.

Counters predating the registry grew ad hoc, and two of them collided:
``results_accepted`` counted *submissions the coordinator accepted* in
``worker/worker.py`` but *results ingested* in ``coordinator/distributer
.py`` — the same name for two ends of the same wire, which only worked
because the two processes never shared a ``Counters`` instance.  The
``worker_`` / ``coord_`` prefixes make the owner explicit; the legacy
spellings live on as read-side aliases (:data:`LEGACY_ALIASES`) so the
bench harness, the embedded coordinator's settle loop and third-party
scrapers keep working against either name.
"""

from __future__ import annotations

# -- coordinator: distributer ingest/grant path ---------------------------

COORD_WORKLOADS_GRANTED = "workloads_granted"
COORD_REQUESTS_DENIED = "requests_denied"
COORD_READ_TIMEOUTS = "read_timeouts"
COORD_RESULTS_ACCEPTED = "coord_results_accepted"
COORD_RESULTS_REJECTED = "coord_results_rejected"
COORD_RESULTS_DROPPED = "coord_results_dropped"
COORD_CHUNKS_SAVED = "chunks_saved"
COORD_SAVE_ERRORS = "save_errors"
COORD_PERSIST_US = "persist_us"  # microsecond sum (legacy bench field)
# Malformed/hostile frame dropped the connection (net.protocol validators
# raised ProtocolError, or the purpose byte was unknown).
COORD_FRAMES_REJECTED = "coord_frames_rejected"

# -- coordinator: scheduler lease churn -----------------------------------

COORD_REQUEUES = "coord_requeues"
COORD_LEASES_EXPIRED = "coord_leases_expired"
GAUGE_FRONTIER_DEPTH = "coord_frontier_depth"
GAUGE_OUTSTANDING_LEASES = "coord_outstanding_leases"
GAUGE_COMPLETED_TILES = "coord_completed_tiles"

# -- coordinator latency histograms (seconds) -----------------------------

HIST_GRANT_SECONDS = "coord_grant_seconds"
HIST_ACCEPT_SECONDS = "coord_accept_seconds"
HIST_PERSIST_SECONDS = "coord_persist_seconds"

# -- worker ---------------------------------------------------------------

WORKER_RESULTS_ACCEPTED = "worker_results_accepted"
WORKER_RESULTS_REJECTED = "worker_results_rejected"
WORKER_TILES_COMPUTED = "tiles_computed"
WORKER_LEASE_US = "lease_us"
WORKER_COMPUTE_US = "compute_us"
WORKER_UPLOAD_US = "upload_us"
HIST_WORKER_COMPUTE_SECONDS = "worker_compute_seconds"
HIST_WORKER_UPLOAD_SECONDS = "worker_upload_seconds"

# -- worker: pipelined executor -------------------------------------------

# Per-item stage service time (labels: stage=lease|dispatch|materialize|
# upload) and the end-of-run occupancy/bubble gauges the farm bench
# reads.  Occupancy is busy/wall for the stage thread; bubble is its
# complement — the fraction of the run the stage spent waiting on its
# neighbours (1.0 means the stage never limited throughput).
HIST_PIPELINE_STAGE_SECONDS = "worker_pipeline_stage_seconds"
GAUGE_PIPELINE_STAGE_OCCUPANCY = "worker_pipeline_stage_occupancy"
GAUGE_PIPELINE_WINDOW_FILL = "worker_pipeline_window_fill"
PIPELINE_LEASE_EXCHANGES = "worker_pipeline_lease_exchanges"
PIPELINE_TILES_ABANDONED = "worker_pipeline_tiles_abandoned"

# Stage label values, in pipeline order.
STAGE_LEASE = "lease"
STAGE_DISPATCH = "dispatch"
STAGE_MATERIALIZE = "materialize"
STAGE_UPLOAD = "upload"
PIPELINE_STAGES = (STAGE_LEASE, STAGE_DISPATCH, STAGE_MATERIALIZE,
                   STAGE_UPLOAD)

# Backend-internal phase split (labels: phase=dispatch|materialize) —
# replaces PallasBackend's unsynchronized ``phase_us`` dict, which was
# racy the moment two pipeline threads shared a backend.
HIST_BACKEND_PHASE_SECONDS = "worker_backend_phase_seconds"
PHASE_DISPATCH = "dispatch"
PHASE_MATERIALIZE = "materialize"

# Megakernel fusion route (PallasBackend.dispatch_many): how many fused
# launches ran, how many tiles rode them (tiles/launch = the effective
# fusion width, the dispatch-amortization factor of ROADMAP item 4), and
# how many pixels the bf16 scouting pass predicted escape inside its
# window (advisory census only — counts never cross the precision
# boundary; see ops/mixed_precision.py).
WORKER_KERNEL_FUSED_LAUNCHES = "worker_kernel_fused_launches"
WORKER_KERNEL_FUSED_TILES = "worker_kernel_fused_tiles"
WORKER_KERNEL_BF16_PRUNED = "worker_kernel_bf16_pruned_pixels"

# Mesh megakernel route (one fused launch shard_map'd over every local
# device): launches that took the route, and device-launch equivalents
# (devices per launch summed, so devices/launches = the mesh width the
# route actually spanned; 1-device rings never touch these — the route
# degenerates to the single-device fused launch).
WORKER_MESH_LAUNCHES = "worker_mesh_launches"
WORKER_MESH_DEVICES = "worker_mesh_devices"

# MXU iteration-map gate (ops/mxu_iteration): fused launches that ran
# the matmul-form recurrence (full mode — bit-parity proven on this
# platform), launches demoted to the advisory census because the gate
# was enabled but parity unproven, and the panel pixels that census
# predicted escape for (advisory only, same precision-boundary contract
# as the bf16 scout above).
WORKER_KERNEL_MXU_LAUNCHES = "worker_kernel_mxu_launches"
WORKER_KERNEL_MXU_DEMOTIONS = "worker_kernel_mxu_demotions"
WORKER_KERNEL_MXU_CENSUS = "worker_kernel_mxu_census_pixels"

# -- distributed tracing (cross-process spans) ----------------------------

# Worker-side span push over PURPOSE_SPANS (0x04): records pushed,
# reports sent, and the one-shot degradation counter bumped when a
# legacy coordinator closes the connection on the unknown purpose byte.
WORKER_SPANS_PUSHED = "worker_spans_pushed"
WORKER_SPAN_REPORTS = "worker_span_reports"
WORKER_SPANS_UNSUPPORTED = "worker_spans_unsupported"
WORKER_SPANS_DROPPED = "worker_spans_dropped"
# Coordinator-side ingest.
COORD_SPAN_REPORTS = "coord_span_reports"
COORD_SPANS_INGESTED = "coord_spans_ingested"
COORD_SPAN_SYNC_SAMPLES = "coord_span_sync_samples"
COORD_SPANS_UNALIGNED = "coord_spans_unaligned"

# Span stage label values, in worker pipeline order.  ``prefetch`` is
# the lease exchange that delivered the tile, ``dispatch`` the host-side
# kernel enqueue, ``compute`` the tile's device residency (dispatch
# start -> materialized), ``d2h`` the device wait + device->host copy,
# ``upload`` the submit exchange.  The wire carries these as one-byte
# codes (net/protocol.py SPAN_STAGE_*).
SPAN_PREFETCH = "prefetch"
SPAN_DISPATCH = "dispatch"
SPAN_COMPUTE = "compute"
SPAN_D2H = "d2h"
SPAN_UPLOAD = "upload"
SPAN_STAGES = (SPAN_PREFETCH, SPAN_DISPATCH, SPAN_COMPUTE, SPAN_D2H,
               SPAN_UPLOAD)

# -- persistent session wire (PURPOSE_SESSION, 0x05) -----------------------

# Coordinator side: connections accepted (the session e2e asserts a
# steady-state farm run stays at one per worker lane), sessions
# upgraded, frames carried, and the off-loop RLE decode latency.
COORD_CONNECTIONS_ACCEPTED = "coord_connections_accepted"
COORD_SESSIONS_OPENED = "coord_sessions_opened"
COORD_SESSION_FRAMES = "coord_session_frames"
HIST_COORD_DECODE_SECONDS = "coord_decode_seconds"
# Wire volume split by codec tier, counted identically on both ends
# (coordinator: bodies ingested; worker: bodies sent) — the farm bench
# reads the worker's to report the compression win.
WIRE_RAW_BYTES = "wire_raw_bytes"
WIRE_COMPRESSED_BYTES = "wire_compressed_bytes"
# Worker side: sessions opened, fallbacks onto the legacy
# connection-per-exchange path (legacy coordinator or mid-run session
# loss), re-dials after the coordinator's idle deadline dropped a quiet
# lane (expected under slow backends — tiles can out-wait the read
# timeout between batches), blocking round trips paid (lease exchanges
# + pipelined-ack waits — the bench divides by tiles for
# farm_rtts_per_tile), and the per-lane busy-time histogram behind the
# bench's lane occupancy.
WORKER_SESSIONS_OPENED = "worker_sessions_opened"
WORKER_SESSION_FALLBACKS = "worker_session_fallbacks"
WORKER_SESSION_REDIALS = "worker_session_redials"
WORKER_WIRE_RTTS = "worker_wire_rtts"
HIST_UPLOAD_LANE_BUSY_SECONDS = "worker_upload_lane_busy_seconds"

# Batched lease grants (SESSION_FLAG_GRANTN): GRANTN exchanges served
# and the tiles-per-exchange distribution (the grant-coalescing factor
# the farm bench divides into round trips), plus the depth of the
# accept-path's bounded persist queue (a standing backlog here means
# group commits, not the event loop, are the bottleneck).
COORD_GRANT_BATCHES = "coord_grant_batches"
HIST_COORD_GRANTS_PER_BATCH = "coord_grants_per_batch"
GAUGE_PERSIST_QUEUE_DEPTH = "coord_persist_queue_depth"

# -- sharded control plane (control/ring + shard session frames) ----------

# Coordinator side: ring-info exchanges served (FRAME_RING_REQ), ring
# version skew observed (client offered a different ring version than
# the shard is running — expected transiently during a ring rollout),
# uploads that arrived at the wrong shard (misroutes), and the subset of
# those answered with a FRAME_REDIRECT carrying the authoritative shard
# (SHARD-negotiated sessions; legacy sessions get a plain REJECT ack).
COORD_SHARD_RING_REQS = "coord_shard_ring_reqs"
COORD_SHARD_RING_SKEW = "coord_shard_ring_skew"
COORD_SHARD_MISROUTES = "coord_shard_misroutes"
COORD_SHARD_REDIRECTS = "coord_shard_redirects"
# Worker side: redirects followed (result re-submitted to the
# authoritative shard) and submissions abandoned because the redirect
# chain exceeded MAX_REDIRECT_HOPS (a ring split-brain signature).
WORKER_REDIRECTS = "worker_redirects"
WORKER_REDIRECT_LOOPS = "worker_redirect_loops"
# Gateway side: read queries for keys this shard does not own, answered
# with QUERY_REDIRECT + the authoritative shard.  The dataserver answers
# misrouted raw chunk queries the same way, under its own counter.
GATEWAY_REDIRECTS = "gateway_redirects"
DATASERVER_REDIRECTS = "dataserver_redirects"

# -- chaos suite (control-plane fault schedules) ---------------------------

# Scenario runner accounting: processes killed on schedule, processes
# restarted, grants observed across restarts, and invariant violations
# (exactly-once/golden-parity/grant-fencing breaks — must stay 0).
CHAOS_KILLS = "chaos_kills"
CHAOS_RESTARTS = "chaos_restarts"
CHAOS_INVARIANT_FAILURES = "chaos_invariant_failures"

# -- store ----------------------------------------------------------------

HIST_STORE_READ_SECONDS = "store_read_seconds"
HIST_STORE_WRITE_SECONDS = "store_write_seconds"
# Startup tail repair: a crash mid-append left a truncated final entry
# and setup cut the index back to the last valid boundary.
STORE_TORN_TAILS_REPAIRED = "store_torn_tails_repaired"
# Group commits (put_many): batches flushed with one index append and
# the tiles those flushes carried (tiles/commit = the flush size the
# scale-out bench reports).
STORE_GROUP_COMMITS = "store_group_commits"
STORE_FLUSH_TILES = "store_flush_tiles"

# -- coordinator: durability (checkpoint/restore) -------------------------

# Periodic + on-demand scheduler checkpoints written (and failures).
COORD_CHECKPOINTS_WRITTEN = "coord_checkpoints_written"
COORD_CHECKPOINT_ERRORS = "coord_checkpoint_errors"
HIST_CHECKPOINT_SECONDS = "coord_checkpoint_seconds"
# Restore path: startups that restored from a checkpoint, index entries
# replayed during restore (suffix-only when a checkpoint was used — the
# kill-and-restart e2e asserts replayed < total), and leases rebuilt so
# in-flight workers can land results across the restart.
COORD_RESTORES = "coord_restores"
COORD_REPLAY_ENTRIES = "coord_replay_entries"
COORD_RESTORED_LEASES = "coord_restored_leases"

# -- worker: reconnect ----------------------------------------------------

# Backoff-then-redial cycles after a dropped coordinator connection
# (capped exponential + jitter; a coordinator restart no longer kills
# the farm run).
WORKER_RECONNECTS = "worker_reconnects"

# -- coordinator: legacy dataserver ---------------------------------------

DATASERVER_QUERIES_SERVED = "queries_served"
DATASERVER_QUERIES_REJECTED = "queries_rejected"
DATASERVER_QUERIES_UNAVAILABLE = "queries_unavailable"

# -- serving gateway + caches ---------------------------------------------

GATEWAY_QUERIES = "gateway_queries"
GATEWAY_SERVED = "gateway_served"
GATEWAY_REJECTED = "gateway_rejected"
GATEWAY_OVERLOADED = "gateway_overloaded"
GATEWAY_UNAVAILABLE = "gateway_unavailable"
GATEWAY_BATCHES = "gateway_batches"
# Malformed/hostile frame dropped the connection (batch count outside
# the validator's bounds, garbage framing).
GATEWAY_FRAMES_REJECTED = "gateway_frames_rejected"
HIST_GATEWAY_REQUEST_SECONDS = "gateway_request_seconds"
TILE_CACHE_HITS = "tile_cache_hits"
TILE_CACHE_MISSES = "tile_cache_misses"
TILE_CACHE_EVICTIONS = "tile_cache_evictions"
TILE_CACHE_PROMOTIONS = "tile_cache_promotions"
TILE_CACHE_STORE_MISSES = "tile_cache_store_misses"
GAUGE_TIER1_HIT_RATIO = "tile_cache_tier1_hit_ratio"
GAUGE_TIER2_HIT_RATIO = "tile_cache_tier2_hit_ratio"

# Rendered-tile tier (GATEWAY_RENDER_MAGIC framing): query/serve volume,
# the palette-PNG render cache's movement counters and live hit ratio,
# the render latency histogram, and the named reject counter the fuzz
# suite pins for unknown colormap ids.
GATEWAY_RENDER_QUERIES = "gateway_render_queries"
GATEWAY_RENDER_SERVED = "gateway_render_served"
GATEWAY_RENDER_CACHE_HITS = "gateway_render_cache_hits"
GATEWAY_RENDER_CACHE_MISSES = "gateway_render_cache_misses"
GATEWAY_RENDER_CACHE_EVICTIONS = "gateway_render_cache_evictions"
GATEWAY_RENDER_UNKNOWN_COLORMAP = "gateway_render_unknown_colormap"
GAUGE_RENDER_HIT_RATIO = "gateway_render_hit_ratio"
HIST_GATEWAY_RENDER_SECONDS = "gateway_render_seconds"

# Interactive sessions (GATEWAY_SESSION_MAGIC framing): session
# lifecycle (opens, queries, table expiry/eviction, the live-session
# gauge), the named reject counters the fuzz suite pins (unknown session
# id — soft reject; unknown flag bits — dropped connection; session
# framing hitting a gateway without the subsystem), and per-session fair
# admission (budget-exhausted sheds, counted apart from the global
# GATEWAY_OVERLOADED so a starved flash crowd is tellable from a dry
# global bucket).
SESSION_OPENS = "session_opens"
SESSION_QUERIES = "session_queries"
SESSION_UNKNOWN = "session_unknown"
SESSION_BAD_FLAGS = "session_bad_flags"
SESSION_UNSUPPORTED = "session_unsupported"
SESSION_THROTTLED = "session_throttled"
SESSION_EXPIRED = "session_expired"
SESSION_EVICTED = "session_evicted"
GAUGE_SESSIONS_ACTIVE = "session_active"
HIST_SESSION_REQUEST_SECONDS = "session_request_seconds"

# Predictive prefetch along the session trajectory: tiles the planner
# picked (in-range, not already cached, not already marked), tiles warmed
# into tier 1 from the store, tiles handed to scheduler.prioritize for
# compute-on-read, and the hit/miss split — a hit is a session query
# landing on a tile the planner marked for it, the ratio gauge is the
# live quality signal for the predictor.
PREFETCH_PLANNED = "prefetch_planned"
PREFETCH_WARMED = "prefetch_warmed"
PREFETCH_SCHEDULED = "prefetch_scheduled"
PREFETCH_HITS = "prefetch_hits"
PREFETCH_MISSES = "prefetch_misses"
GAUGE_PREFETCH_HIT_RATIO = "prefetch_hit_ratio"

# Progressive refinement: first paints served from the cheap low-iter
# variant, full-depth workloads handed back to the scheduler, and deep
# variants persisted (which invalidate the stale cache tiers below).
SESSION_FIRST_PAINTS = "session_first_paints"
SESSION_REFINES_SCHEDULED = "session_refines_scheduled"
SESSION_REFINES_COMPLETED = "session_refines_completed"

# Cache-tier invalidation when a deeper-max_iter variant of a cached
# tile persists (the store's payload LRU self-heals on save; the decoded
# and rendered tiers are dropped explicitly so the next read re-reads
# the deep variant).
TILE_CACHE_INVALIDATIONS = "tile_cache_invalidations"
GATEWAY_RENDER_CACHE_INVALIDATIONS = "gateway_render_cache_invalidations"

# Serve-side RLE recompression of cold raw payloads (legacy raw-only data
# dirs): payloads re-encoded on promotion, payloads left raw (estimate
# said RLE cannot win), and wire bytes saved by the re-encode.
SERVE_RLE_RECOMPRESSIONS = "serve_rle_recompressions"
SERVE_RLE_SKIPPED = "serve_rle_skipped"
SERVE_RLE_BYTES_SAVED = "serve_rle_bytes_saved"

COALESCE_LEADERS = "coalesce_leaders"
COALESCE_FOLLOWERS = "coalesce_followers"
ONDEMAND_REQUESTS = "ondemand_requests"
ONDEMAND_TIMEOUTS = "ondemand_timeouts"
ONDEMAND_SERVED = "ondemand_served"
# A tile the scheduler believed completed missed the store for a full
# poll window: the bytes are gone (wiped data dir, foreign store), so
# on-demand un-completed it via ``refine`` and re-granted the compute.
ONDEMAND_HEALED = "ondemand_healed"

# Gateway per-request outcome label values (one histogram, split by how
# the request resolved).
OUTCOME_TIER1 = "tier1_hit"
OUTCOME_STORE = "store_hit"
OUTCOME_COMPUTED = "computed"
OUTCOME_UNAVAILABLE = "unavailable"
OUTCOME_REJECTED = "rejected"
OUTCOME_OVERLOADED = "overloaded"
# Rendered-tile outcomes: served straight from the render cache, or
# rendered on this request (pixels from tier-1/store/compute).
OUTCOME_RENDER_CACHE = "render_hit"
OUTCOME_RENDERED = "rendered"
# Sharded serving: the key belongs to another shard; the client was
# pointed at the authoritative one.
OUTCOME_REDIRECTED = "redirected"
# Interactive sessions: served the cheap low-iter first paint (full
# depth refines in the background), or shed by the session's own
# admission budget rather than the global bucket.
OUTCOME_FIRST_PAINT = "first_paint"
OUTCOME_SESSION_THROTTLED = "session_throttled"

# -- loadgen (open-loop storm harness) --------------------------------------

# Per-phase request accounting (labels: phase=<phase name>): requests
# issued on the open-loop schedule, completions by class (OK payloads,
# OVERLOADED sheds, NOT_AVAILABLE misses, transport/protocol errors),
# and issues dropped because the client itself ran out of in-flight
# budget (counted separately — client saturation must never masquerade
# as server goodput).
LOADGEN_REQUESTS = "loadgen_requests"
LOADGEN_COMPLETED = "loadgen_completed"
LOADGEN_SHED = "loadgen_shed"
LOADGEN_UNAVAILABLE = "loadgen_unavailable"
LOADGEN_ERRORS = "loadgen_errors"
LOADGEN_CLIENT_SATURATED = "loadgen_client_saturated"
LOADGEN_BYTES = "loadgen_bytes"
HIST_LOADGEN_LATENCY_SECONDS = "loadgen_latency_seconds"

# -- fleet observability plane (obs/timeseries + obs/fleet + obs/slo) ------

# Ring-buffer sampler: snapshots taken, per-snapshot cost, and the live
# count of distinct series the history currently carries.
TS_SAMPLES = "ts_samples"
HIST_TS_SAMPLE_SECONDS = "ts_sample_seconds"
GAUGE_TS_SERIES = "ts_series"

# Fleet aggregator: scrape rounds completed, per-peer fetch failures
# (malformed/truncated/oversized bodies, unreachable peers — the peer is
# marked stale, never crashed on), per-fetch latency, and the live
# peer/stale/straggler population gauges the dashboard header reads.
FLEET_SCRAPES = "fleet_scrapes"
FLEET_SCRAPE_ERRORS = "fleet_scrape_errors"
HIST_FLEET_SCRAPE_SECONDS = "fleet_scrape_seconds"
GAUGE_FLEET_PEERS = "fleet_peers"
GAUGE_FLEET_PEERS_STALE = "fleet_peers_stale"
GAUGE_FLEET_STRAGGLERS = "fleet_stragglers"

# SLO layer: live burn-rate gauges (labels: slo=<name>,
# window=fast|slow) and the alert-transition counters (labels:
# slo=<name>) bumped by the state machine in obs/slo.py.
GAUGE_SLO_BURN = "slo_burn_rate"
SLO_ALERTS_FIRED = "slo_alerts_fired"
SLO_ALERTS_RECOVERED = "slo_alerts_recovered"

# -- flight recorder + postmortem (obs/flight + obs/postmortem) ------------

# Black-box ring totals (live gauges read off the recorder — the hot
# note() path never touches the registry), dump files written across
# the exit paths, and the postmortem assembler's load accounting:
# dumps merged, corrupt/truncated lines tolerated-but-counted, and
# anomalies (grant-without-accept, ping-pong, redirect loops, retry
# storms, double-commit evidence) the detectors surfaced.
GAUGE_FLIGHT_EVENTS = "flight_events"
GAUGE_FLIGHT_EVENTS_DROPPED = "flight_events_dropped"
FLIGHT_DUMPS = "flight_dumps"
POSTMORTEM_DUMPS_LOADED = "postmortem_dumps_loaded"
POSTMORTEM_DUMP_ERRORS = "postmortem_dump_errors"
POSTMORTEM_ANOMALIES = "postmortem_anomalies"

# -- legacy aliases -------------------------------------------------------

# canonical name -> the spelling pre-registry call sites read.  Reads of a
# legacy name sum every canonical counter aliased to it, reproducing the
# old shared-Counters semantics (a process that both granted and computed
# saw one merged ``results_accepted``).
LEGACY_ALIASES: dict[str, str] = {
    COORD_RESULTS_ACCEPTED: "results_accepted",
    WORKER_RESULTS_ACCEPTED: "results_accepted",
    COORD_RESULTS_REJECTED: "results_rejected",
    WORKER_RESULTS_REJECTED: "results_rejected",
    COORD_RESULTS_DROPPED: "results_dropped",
}
