"""Per-process black-box flight recorder: last-N state transitions, always on.

Metrics (PR 2) say *that* something went wrong and spans (PR 5) say *how
long* things took, but neither records *what the process was doing when
it died* — the sequence of scheduler, session, persist and checkpoint
transitions leading up to a crash.  :class:`FlightRecorder` is that
black box: a lock-cheap bounded ring of structured events (monotonic
seq, monotonic timestamp, category, optional tile key + lease token,
small k=v payload) that every layer appends to through the module-level
:func:`note` — a no-op costing one global read until a process opts in
via :func:`ensure`.

Event names are registered in obs/events.py (the ``obs-event`` lint
rule keeps call sites honest); the part before the first dot is the
category, which is also the sampling-cap bucket: hot categories are
rate-capped per wall-second so a grant storm cannot starve the ring of
the rare transitions (checkpoint seams, crashpoints) a postmortem
actually needs.

Dumps — a JSONL header line plus one line per ring event — are written
on every exit path once :meth:`FlightRecorder.install` ran (it does
when ``DMTPU_FLIGHT_DIR`` is set): ``sys.excepthook`` and
``threading.excepthook`` (chained), SIGTERM (only when the default
handler was in place; re-raised after the dump so exit codes survive),
``atexit``, armed ``faults.hit`` crashpoints (utils/faults.py calls
back here before ``os._exit``), and a periodic autoflush thread whose
snapshot is what survives a SIGKILL.  The exporter serves the live ring
as ``GET /flight?window=`` and obs/postmortem.py merges the dump files
of a whole fleet into one causally-ordered timeline.

``DMTPU_FLIGHT=0`` disables the recorder entirely (the ``bench.py
--obs`` recorder-off leg); ``DMTPU_FLIGHT_PERIOD`` tunes the autoflush
cadence.
"""

from __future__ import annotations

import atexit
import json
import os
import signal
import socket
import sys
import threading
import time
from collections import deque
from typing import Callable, NamedTuple, Optional

from distributedmandelbrot_tpu.obs import events as obs_events
from distributedmandelbrot_tpu.obs import names as obs_names

Key = tuple[int, int, int]

ENV_VAR = "DMTPU_FLIGHT"  # "0" disables the process recorder
ENV_DIR_VAR = "DMTPU_FLIGHT_DIR"  # dump directory; also enables dumps
ENV_PERIOD_VAR = "DMTPU_FLIGHT_PERIOD"  # autoflush seconds (default 0.5)

DUMP_VERSION = 1
DUMP_KIND = "dmtpu-flight"

# Per-category events per cap-window second.  The caps only bound the
# *rate* each family may claim; the ring bounds total memory.  Rare,
# load-bearing families (ckpt, fault, slo) are deliberately uncapped.
DEFAULT_CAPS = {
    "sched": 2000,
    "sess": 1000,
    "store": 500,
    "gw": 500,
    "wkr": 500,
}


class FlightEvent(NamedTuple):
    seq: int
    t: float  # recorder (monotonic) clock seconds
    cat: str
    name: str  # obs_events.* value
    key: Optional[Key]
    lease: Optional[int]
    kv: Optional[dict]

    def to_doc(self) -> dict:
        doc: dict = {"seq": self.seq, "t": round(self.t, 6),
                     "cat": self.cat, "name": self.name}
        if self.key is not None:
            doc["key"] = list(self.key)
        if self.lease is not None:
            doc["lease"] = self.lease
        if self.kv:
            doc["kv"] = self.kv
        return doc


class FlightRecorder:
    """Bounded, thread-safe ring of flight events with per-category caps.

    ``clock``/``wall`` are injectable (virtual-clock unit tests); every
    event carries only the monotonic clock, and the dump header anchors
    a (wall, mono) pair sampled together so readers can place the whole
    ring on the wall clock without per-event double stamps.
    """

    def __init__(self, capacity: int = 4096, *, role: str = "proc",
                 clock: Callable[[], float] = time.monotonic,
                 wall: Callable[[], float] = time.time,
                 caps: Optional[dict] = None,
                 cap_window: float = 1.0) -> None:
        if capacity < 1:
            raise ValueError("capacity must be >= 1")
        self.capacity = capacity
        self.role = role
        self.clock = clock
        self.wall = wall
        self.enabled = True
        self.pid = os.getpid()
        self.worker_id: Optional[str] = None
        self.shard: Optional[int] = None
        # Coordinator processes point this at their SpanStore so dumps
        # carry the per-worker NTP offsets postmortem aligns with.
        self.offsets_fn: Optional[Callable[[], dict]] = None
        self.dump_dir: Optional[str] = None
        self.dumps_written = 0
        self._caps = dict(DEFAULT_CAPS if caps is None else caps)
        self._cap_window = cap_window
        self._cap_bucket = -1
        self._cap_counts: dict[str, int] = {}
        self._lock = threading.Lock()
        self._dump_lock = threading.Lock()
        self._exited = False
        self._ring: deque[FlightEvent] = deque(maxlen=capacity)
        self._seq = 0
        self._dropped_ring = 0
        self._dropped_sampled: dict[str, int] = {}
        self._registry = None
        self._bound_registries: set[int] = set()
        self._flush_stop: Optional[threading.Event] = None
        self._installed = False
        self._prev_excepthook = None
        self._prev_threading_hook = None

    # -- hot path ------------------------------------------------------

    def note(self, name: str, key: Optional[Key] = None,
             lease: Optional[int] = None, **kv) -> None:
        if not self.enabled:
            return
        cat = name.partition(".")[0]
        now = self.clock()
        with self._lock:
            cap = self._caps.get(cat)
            if cap is not None:
                bucket = int(now / self._cap_window)
                if bucket != self._cap_bucket:
                    self._cap_bucket = bucket
                    self._cap_counts.clear()
                used = self._cap_counts.get(cat, 0)
                if used >= cap:
                    self._dropped_sampled[cat] = \
                        self._dropped_sampled.get(cat, 0) + 1
                    return
                self._cap_counts[cat] = used + 1
            self._seq += 1
            if len(self._ring) == self.capacity:
                self._dropped_ring += 1
            self._ring.append(FlightEvent(self._seq, now, cat, name,
                                          key, lease, kv or None))

    # -- read side -----------------------------------------------------

    @property
    def recorded(self) -> int:
        with self._lock:
            return self._seq

    @property
    def dropped(self) -> int:
        with self._lock:
            return self._dropped_ring + sum(self._dropped_sampled.values())

    def __len__(self) -> int:
        with self._lock:
            return len(self._ring)

    def header(self, reason: str = "live") -> dict:
        """Dump/snapshot header: identity + a (wall, mono) anchor pair
        sampled together, so every ring timestamp places on the wall
        clock, plus the span-derived worker clock offsets when a
        SpanStore is attached (coordinator roles)."""
        with self._lock:
            dropped_sampled = dict(self._dropped_sampled)
            dropped_ring = self._dropped_ring
            seq = self._seq
        doc: dict = {
            "v": DUMP_VERSION, "kind": DUMP_KIND,
            "role": self.role, "pid": self.pid,
            "host": socket.gethostname(),
            "reason": reason,
            "wall0": self.wall(), "mono0": self.clock(),
            "seq": seq,
            "dropped_ring": dropped_ring,
            "dropped_sampled": dropped_sampled,
        }
        if self.worker_id is not None:
            doc["worker_id"] = self.worker_id
        if self.shard is not None:
            doc["shard"] = self.shard
        if self.offsets_fn is not None:
            try:
                doc["offsets"] = self.offsets_fn()
            except Exception:
                doc["offsets"] = {}
        return doc

    def snapshot(self, window: Optional[float] = None,
                 reason: str = "live") -> dict:
        """Live ``{"header", "events"}`` document (the ``/flight``
        route); ``window`` keeps only the trailing seconds of ring."""
        with self._lock:
            events = list(self._ring)
        if window is not None and events:
            cutoff = self.clock() - window
            events = [e for e in events if e.t >= cutoff]
        return {"header": self.header(reason=reason),
                "events": [e.to_doc() for e in events]}

    def tail(self, n: int) -> list[dict]:
        """Last ``n`` events as dicts (SLO alerts attach this)."""
        with self._lock:
            events = list(self._ring)[-n:]
        return [e.to_doc() for e in events]

    # -- registry ------------------------------------------------------

    def bind_registry(self, registry) -> None:
        """Expose ring totals as live gauges on ``registry`` (idempotent
        per registry — embedders construct several coordinators per
        process and each brings its own registry)."""
        if id(registry) in self._bound_registries:
            return
        self._bound_registries.add(id(registry))
        self._registry = registry
        registry.gauge(obs_names.GAUGE_FLIGHT_EVENTS,
                       help="flight-recorder events recorded",
                       fn=lambda: self.recorded)
        registry.gauge(obs_names.GAUGE_FLIGHT_EVENTS_DROPPED,
                       help="flight-recorder events dropped "
                            "(ring overflow + sampling caps)",
                       fn=lambda: self.dropped)

    # -- dumps ---------------------------------------------------------

    @property
    def dump_path(self) -> Optional[str]:
        if self.dump_dir is None:
            return None
        safe_role = "".join(c if c.isalnum() or c in "-_" else "-"
                            for c in self.role)
        return os.path.join(self.dump_dir,
                            f"flight-{safe_role}-{self.pid}.jsonl")

    def dump(self, path: Optional[str] = None,
             reason: str = "manual", *, final: bool = False) -> Optional[str]:
        """Write header + ring as JSONL, atomically (tmp + rename): a
        reader — or the next autoflush — never sees a torn file.

        ``final`` marks a process-exit dump (atexit, signal, crashpoint,
        main-thread excepthook).  The dump lock serializes writers, and
        once a final dump landed, later autoflush dumps become no-ops —
        the daemon flusher outlives atexit callbacks in CPython and must
        not clobber the exit reason."""
        path = path if path is not None else self.dump_path
        if path is None:
            return None
        with self._dump_lock:
            if self._exited and reason == "autoflush":
                return None
            if final:
                self._exited = True
            with self._lock:
                events = list(self._ring)
            lines = [json.dumps(self.header(reason=reason), default=str)]
            lines.extend(json.dumps(e.to_doc(), default=str)
                         for e in events)
            tmp = path + ".tmp"
            with open(tmp, "w", encoding="utf-8") as f:
                f.write("\n".join(lines) + "\n")
            os.replace(tmp, path)
            self.dumps_written += 1
        if self._registry is not None:
            self._registry.inc(obs_names.FLIGHT_DUMPS)
        return path

    # -- exit-path installation ---------------------------------------

    def install(self, dump_dir: str, *, period: float = 0.5) -> None:
        """Arm every exit path to dump into ``dump_dir`` and start the
        autoflush thread (the SIGKILL survivor).  Idempotent."""
        if self._installed:
            return
        self._installed = True
        self.dump_dir = dump_dir
        os.makedirs(dump_dir, exist_ok=True)

        self._prev_excepthook = sys.excepthook

        def _excepthook(tp, value, tb):
            self._safe_dump(f"excepthook:{tp.__name__}", final=True)
            self._prev_excepthook(tp, value, tb)

        sys.excepthook = _excepthook

        self._prev_threading_hook = threading.excepthook

        def _thread_hook(args):
            self._safe_dump(
                f"threading.excepthook:{args.exc_type.__name__}")
            self._prev_threading_hook(args)

        threading.excepthook = _thread_hook
        atexit.register(self._exit_dump)
        # Crashpoints hard-exit via os._exit (no atexit, no excepthook):
        # faults.py calls back just before dying.
        from distributedmandelbrot_tpu.utils import faults

        faults.on_fire(self._on_crashpoint)
        self._install_sigterm()
        if period > 0:
            self._flush_stop = threading.Event()
            t = threading.Thread(target=self._autoflush_loop,
                                 args=(period,), daemon=True,
                                 name="flight-autoflush")
            t.start()

    def uninstall(self) -> None:
        """Restore the chained hooks (test hygiene; the autoflush thread
        stops, signal handlers are left as-is)."""
        if not self._installed:
            return
        self._installed = False
        if self._flush_stop is not None:
            self._flush_stop.set()
        if self._prev_excepthook is not None:
            sys.excepthook = self._prev_excepthook
        if self._prev_threading_hook is not None:
            threading.excepthook = self._prev_threading_hook

    def _safe_dump(self, reason: str, *, final: bool = False) -> None:
        try:
            self.dump(reason=reason, final=final)
        except Exception:
            pass  # a dying process must die its own death, not ours

    def _exit_dump(self) -> None:
        if self._flush_stop is not None:
            self._flush_stop.set()
        self._safe_dump("atexit", final=True)

    def _on_crashpoint(self, point: str, hard_exit: bool) -> None:
        self.note(obs_events.FAULT_CRASHPOINT, point=point,
                  hard_exit=hard_exit)
        if hard_exit:
            self._safe_dump(f"crashpoint:{point}", final=True)

    def _install_sigterm(self) -> None:
        # Only claim SIGTERM when nobody else did (SIG_DFL): asyncio
        # processes (the shard driver) install their own graceful
        # handler after construction and must win; re-raising after the
        # dump preserves the default killed-by-signal exit status for
        # the parent.
        try:
            if signal.getsignal(signal.SIGTERM) is not signal.SIG_DFL:
                return

            def _handler(signum, frame):
                self._safe_dump(f"signal:{signum}", final=True)
                signal.signal(signum, signal.SIG_DFL)
                os.kill(os.getpid(), signum)

            signal.signal(signal.SIGTERM, _handler)
        except (ValueError, OSError):
            pass  # not the main thread, or an embedding forbids signals

    def _autoflush_loop(self, period: float) -> None:
        assert self._flush_stop is not None
        while not self._flush_stop.wait(period):
            self._safe_dump("autoflush")


# -- process-global recorder ----------------------------------------------

_default: Optional[FlightRecorder] = None
_default_lock = threading.Lock()


def get() -> Optional[FlightRecorder]:
    return _default


def set_recorder(recorder: Optional[FlightRecorder]) -> None:
    """Test hook: swap the process-global recorder."""
    global _default
    _default = recorder


def note(name: str, key: Optional[Key] = None,
         lease: Optional[int] = None, **kv) -> None:
    """Record on the process recorder; free (one global read) when no
    layer has called :func:`ensure` or ``DMTPU_FLIGHT=0``."""
    rec = _default
    if rec is not None:
        rec.note(name, key=key, lease=lease, **kv)


def ensure(role: str, *, registry=None,
           environ=os.environ) -> Optional[FlightRecorder]:
    """Create (once) and return the process recorder.

    The first caller names the process — a shard, coordinator or worker
    constructor — and wins; later callers just bind their registry.
    ``DMTPU_FLIGHT=0`` returns None and leaves :func:`note` free;
    ``DMTPU_FLIGHT_DIR`` arms the dump paths + autoflush.
    """
    global _default
    if environ.get(ENV_VAR, "1") == "0":
        return None
    with _default_lock:
        if _default is None:
            _default = FlightRecorder(role=role)
            dump_dir = environ.get(ENV_DIR_VAR)
            if dump_dir:
                _default.install(
                    dump_dir,
                    period=float(environ.get(ENV_PERIOD_VAR, "0.5")))
        rec = _default
    if registry is not None:
        rec.bind_registry(registry)
    return rec
