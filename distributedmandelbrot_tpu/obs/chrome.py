"""Chrome trace-event rendering of the merged farm timeline.

Turns the coordinator's :class:`~distributedmandelbrot_tpu.obs.trace
.TraceLog` lifecycle intervals plus the :class:`~distributedmandelbrot
_tpu.obs.spans.SpanStore`'s aligned worker spans into the Chrome
trace-event JSON format (the ``{"traceEvents": [...]}`` dict), loadable
at https://ui.perfetto.dev or chrome://tracing.

Layout: the coordinator is one process (pid 0) with one thread per
lifecycle phase (queue / in-flight / persist) plus a gateway row for
``served`` instants; each remote worker is its own process (pid 100+i,
named by its 64-bit id) with a prefetch row, a dispatch row, an upload
row, and one thread per device carrying the nested compute/d2h slices.
All timestamps are the coordinator's monotonic clock in microseconds —
worker spans were aligned by the store's per-worker NTP-style offset, so
their absolute placement carries that estimate's error bound (exposed in
each event's ``args.align_error_s``); durations are exact.
"""

from __future__ import annotations

from typing import Optional

from distributedmandelbrot_tpu.obs import names as obs_names

# Coordinator rows (pid 0).
_PID_COORD = 0
_TID_QUEUE = 1
_TID_FLIGHT = 2
_TID_PERSIST = 3
_TID_GATEWAY = 4
# Worker rows: prefetch/dispatch/upload threads, then one per device.
_TID_W_PREFETCH = 1
_TID_W_DISPATCH = 2
_TID_W_UPLOAD = 3
_TID_W_DEVICE0 = 10

_STAGE_TID = {
    obs_names.SPAN_PREFETCH: _TID_W_PREFETCH,
    obs_names.SPAN_DISPATCH: _TID_W_DISPATCH,
    obs_names.SPAN_UPLOAD: _TID_W_UPLOAD,
}


def _us(seconds: float) -> float:
    return round(seconds * 1e6, 3)


def _meta(name: str, pid: int, value: str, tid: int = 0) -> dict:
    return {"name": name, "ph": "M", "pid": pid, "tid": tid,
            "args": {"name": value}}


def _slice(name: str, pid: int, tid: int, t0: float, t1: float,
           args: dict) -> dict:
    return {"name": name, "ph": "X", "ts": _us(t0),
            "dur": _us(max(0.0, t1 - t0)), "pid": pid, "tid": tid,
            "cat": "farm", "args": args}


def _key_str(key) -> str:
    return "/".join(str(int(part)) for part in key)


def render_chrome_trace(trace=None, spans=None) -> dict:
    """Render the merged timeline; both inputs optional (an idle
    coordinator yields an empty but valid trace)."""
    events: list[dict] = []

    events.append(_meta("process_name", _PID_COORD, "coordinator"))
    for tid, label in ((_TID_QUEUE, "queue (scheduled->granted)"),
                       (_TID_FLIGHT, "in flight (granted->received)"),
                       (_TID_PERSIST, "persist"),
                       (_TID_GATEWAY, "gateway serves")):
        events.append(_meta("thread_name", _PID_COORD, label, tid))

    if trace is not None:
        for span in trace.spans():
            key = _key_str(span["key"])
            args = {"key": key}
            if span.get("worker"):
                args["worker"] = span["worker"]
            marks = span.get("events", {})
            sched = marks.get("scheduled")
            granted = marks.get("granted")
            received = marks.get("result_received")
            persisted = marks.get("persisted")
            if sched is not None and granted is not None:
                events.append(_slice("queue", _PID_COORD, _TID_QUEUE,
                                     sched, granted, args))
            if granted is not None and received is not None:
                events.append(_slice("in_flight", _PID_COORD,
                                     _TID_FLIGHT, granted, received,
                                     args))
            if received is not None and persisted is not None:
                events.append(_slice("persist", _PID_COORD,
                                     _TID_PERSIST, received, persisted,
                                     args))
            served = marks.get("served")
            if served is not None:
                events.append({"name": "served", "ph": "i",
                               "ts": _us(served), "pid": _PID_COORD,
                               "tid": _TID_GATEWAY, "s": "t",
                               "cat": "farm", "args": args})

    if spans is not None:
        pids: dict[int, int] = {}
        device_tids: dict[tuple[int, int], int] = {}
        for span in spans.spans():
            wid = span["worker"]
            pid = pids.get(wid)
            if pid is None:
                pid = 100 + len(pids)
                pids[wid] = pid
                events.append(_meta("process_name", pid,
                                    f"worker {wid:016x}"))
                for tid, label in ((_TID_W_PREFETCH, "prefetch"),
                                   (_TID_W_DISPATCH, "dispatch"),
                                   (_TID_W_UPLOAD, "upload")):
                    events.append(_meta("thread_name", pid, label, tid))
            stage = span["stage"]
            tid = _STAGE_TID.get(stage)
            if tid is None:  # compute/d2h nest on the device row
                tid = _TID_W_DEVICE0 + span["device"]
                if (pid, tid) not in device_tids:
                    device_tids[(pid, tid)] = tid
                    events.append(_meta("thread_name", pid,
                                        f"device {span['device']}",
                                        tid))
            events.append(_slice(
                stage, pid, tid, span["t0"], span["t1"],
                {"key": _key_str(span["key"]), "seq": span["seq"],
                 "align_error_s": round(span["align_error_s"], 6)}))

    return {"traceEvents": events, "displayTimeUnit": "ms"}
