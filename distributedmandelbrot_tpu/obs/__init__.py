"""Observability: metrics registry, tile-lifecycle trace, HTTP exporter.

The reference system has no instrumentation at all (survey §5.5); this
package is the telemetry spine of the TPU build:

- :mod:`.metrics` — thread-safe :class:`Registry` of counters, gauges and
  log-bucketed histograms with percentile estimation, stdlib-only;
- :mod:`.names` — the canonical metric names every layer emits, plus the
  legacy-alias table that keeps pre-registry call sites working;
- :mod:`.trace` — a bounded ring buffer of per-tile lifecycle events
  (``scheduled -> granted -> result_received -> persisted -> served``)
  joined into latency spans and a per-worker skew summary;
- :mod:`.exporter` — an asyncio HTTP endpoint serving ``/metrics``
  (Prometheus text exposition v0.0.4), ``/varz`` (JSON snapshot) and
  ``/healthz``, enabled from the coordinator like the gateway is.
"""

from distributedmandelbrot_tpu.obs.exporter import (MetricsExporter,
                                                    render_prometheus)
from distributedmandelbrot_tpu.obs.metrics import (DEFAULT_BUCKETS, Counter,
                                                   Gauge, Histogram, Registry)
from distributedmandelbrot_tpu.obs.trace import TraceEvent, TraceLog

__all__ = ["Counter", "DEFAULT_BUCKETS", "Gauge", "Histogram",
           "MetricsExporter", "Registry", "TraceEvent", "TraceLog",
           "render_prometheus"]
