"""Observability: metrics registry, tile-lifecycle trace, HTTP exporter.

The reference system has no instrumentation at all (survey §5.5); this
package is the telemetry spine of the TPU build:

- :mod:`.metrics` — thread-safe :class:`Registry` of counters, gauges and
  log-bucketed histograms with percentile estimation, stdlib-only;
- :mod:`.names` — the canonical metric names every layer emits, plus the
  legacy-alias table that keeps pre-registry call sites working;
- :mod:`.trace` — a bounded ring buffer of per-tile lifecycle events
  (``scheduled -> granted -> result_received -> persisted -> served``)
  joined into latency spans and a per-worker skew summary;
- :mod:`.spans` — cross-process tracing: the worker-side span recorder,
  the NTP-style clock-offset estimator, and the coordinator-side span
  store that merges wire-pushed worker spans onto the local timeline;
- :mod:`.chrome` — the merged timeline as Chrome trace-event JSON
  (Perfetto-loadable), served by the exporter as ``/trace.json``;
- :mod:`.exporter` — an asyncio HTTP endpoint serving ``/metrics``
  (Prometheus text exposition v0.0.4), ``/varz`` (JSON snapshot),
  ``/healthz``, ``/trace.json`` and the live flight ring as
  ``/flight``, enabled from the coordinator like the gateway is;
- :mod:`.events` — the registered flight-recorder event names (the
  ``obs-event`` rule in ``dmtpu check`` keeps call sites honest);
- :mod:`.flight` — the per-process black-box flight recorder: a
  bounded, sampled ring of state transitions dumped on every exit path
  (``DMTPU_FLIGHT_DIR``), appended to via the free-when-off module
  function :func:`flight.note`;
- :mod:`.postmortem` — the ``dmtpu postmortem`` assembler merging a
  directory of flight dumps into one clock-aligned causal timeline
  with in-flight-lease reconstruction and anomaly detectors.
"""

from distributedmandelbrot_tpu.obs.chrome import render_chrome_trace
from distributedmandelbrot_tpu.obs.exporter import (MetricsExporter,
                                                    render_prometheus)
from distributedmandelbrot_tpu.obs.flight import FlightRecorder
from distributedmandelbrot_tpu.obs.metrics import (DEFAULT_BUCKETS, Counter,
                                                   Gauge, Histogram, Registry)
from distributedmandelbrot_tpu.obs.postmortem import Postmortem
from distributedmandelbrot_tpu.obs.postmortem import \
    assemble as assemble_postmortem
from distributedmandelbrot_tpu.obs.spans import (ClockOffsetEstimator,
                                                 OffsetEstimate, Span,
                                                 SpanRecorder, SpanStore,
                                                 critical_path)
from distributedmandelbrot_tpu.obs.trace import TraceEvent, TraceLog

__all__ = ["ClockOffsetEstimator", "Counter", "DEFAULT_BUCKETS",
           "FlightRecorder", "Gauge", "Histogram", "MetricsExporter",
           "OffsetEstimate", "Postmortem", "Registry", "Span",
           "SpanRecorder", "SpanStore", "TraceEvent", "TraceLog",
           "assemble_postmortem", "critical_path", "render_chrome_trace",
           "render_prometheus"]
