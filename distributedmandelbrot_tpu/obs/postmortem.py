"""Cross-process postmortem: merge flight dumps into one causal timeline.

A chaos run (or a real incident) leaves a directory of per-process
flight-recorder dumps (obs/flight.py): one JSONL file per shard /
coordinator / worker, each a header plus the last-N state transitions
that process saw before it died (or before its latest autoflush — the
SIGKILL case).  This module is the assembler behind ``dmtpu postmortem``:

- **Loading is corruption-tolerant.**  Dumps from killed processes are
  routinely truncated mid-line; fuzzing adds garbage, oversized and
  mixed-version files.  Every unparseable line is *counted*, never
  raised on — a partial timeline always renders.
- **Clock alignment reuses the PR 5 span offsets.**  Every dump header
  anchors a (wall, mono) pair sampled together, so any event places on
  the wall clock; coordinator dumps additionally carry their SpanStore's
  per-worker NTP-midpoint offsets, and a worker dump whose ``worker_id``
  appears there is placed on that coordinator's clock instead
  (``align: "spans"``, with the estimator's half-RTT error bound).
  Shard-to-shard ordering rests on the shared wall clock (same host in
  the chaos farm; cross-host deployments inherit NTP skew — see the
  README caveats).
- **Anomaly detectors** walk the merged timeline: grants still in
  flight at a process's death (and their later re-grants by the
  restarted/surviving shard), lease ping-pong, redirect loops,
  double-commit evidence, retry storms.

The chaos runner attaches :meth:`Postmortem.summary` to failed scenario
reports; the CLI renders text, ``--json``, or ``--chrome`` (Perfetto).
"""

from __future__ import annotations

import json
import os
from dataclasses import dataclass, field
from typing import Optional

from distributedmandelbrot_tpu.obs import events as obs_events
from distributedmandelbrot_tpu.obs import names as obs_names
from distributedmandelbrot_tpu.obs.flight import DUMP_KIND, DUMP_VERSION

Key = tuple[int, int, int]

# A single dump line past this is hostile or corrupt, not data: skip it
# without handing it to the JSON parser (fuzz guard — json.loads on a
# multi-megabyte garbage line is where the time goes).
MAX_LINE_BYTES = 1 << 20

# Detector thresholds.  Deliberately conservative: postmortems attach to
# failure reports, and a noisy detector teaches operators to ignore it.
PING_PONG_GRANTS = 3
REDIRECT_LOOP_COUNT = 3
RETRY_STORM_COUNT = 5
RETRY_STORM_WINDOW_S = 10.0

# Events that settle an open grant for a (process, key) — the complement
# defines "in flight at time of death".
_SETTLING = (obs_events.SCHED_ACCEPT, obs_events.SCHED_EXPIRE,
             obs_events.SCHED_REQUEUE, obs_events.SCHED_RELEASE,
             obs_events.SCHED_REOPEN)


@dataclass
class ProcessDump:
    """One process's parsed dump: header, events (dump order), and the
    count of lines that failed to parse."""
    path: str
    header: dict
    events: list[dict]
    errors: int = 0

    @property
    def proc(self) -> str:
        return (f"{self.header.get('role', 'unknown')}"
                f"@{self.header.get('pid', 0)}")

    @property
    def role(self) -> str:
        return str(self.header.get("role", "unknown"))


def _parse_line(line: str) -> Optional[dict]:
    try:
        doc = json.loads(line)
    except ValueError:
        return None
    return doc if isinstance(doc, dict) else None


def load_dump(path: str) -> ProcessDump:
    """Parse one dump file, swallowing corruption line by line.

    The header is whichever line first claims ``kind == dmtpu-flight``
    (normally line 1; garbage prefixes just count as errors).  A file
    with no header still yields its parseable events — they merge at
    raw monotonic timestamps, which is wrong in absolute terms but
    preserves the process's own ordering.  A version mismatch counts as
    one error and parsing continues best-effort.
    """
    header: dict = {}
    events: list[dict] = []
    errors = 0
    try:
        with open(path, "r", encoding="utf-8", errors="replace") as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                if len(line) > MAX_LINE_BYTES:
                    errors += 1
                    continue
                doc = _parse_line(line)
                if doc is None:
                    errors += 1
                    continue
                if doc.get("kind") == DUMP_KIND:
                    if not header:
                        header = doc
                        if doc.get("v") != DUMP_VERSION:
                            errors += 1
                    continue
                if isinstance(doc.get("name"), str) \
                        and isinstance(doc.get("t"), (int, float)):
                    events.append(doc)
                else:
                    errors += 1
    except OSError:
        errors += 1
    return ProcessDump(path=path, header=header, events=events,
                       errors=errors)


def load_dir(dump_dir: str) -> tuple[list[ProcessDump], int]:
    """Every ``*.jsonl`` under ``dump_dir`` (non-recursive), plus the
    count of files that were entirely unreadable/empty of events."""
    dumps: list[ProcessDump] = []
    file_errors = 0
    try:
        names = sorted(os.listdir(dump_dir))
    except OSError:
        return [], 1
    for name in names:
        if not name.endswith(".jsonl"):
            continue
        dump = load_dump(os.path.join(dump_dir, name))
        if not dump.header and not dump.events:
            file_errors += 1
            continue
        dumps.append(dump)
    return dumps, file_errors


# -- alignment -------------------------------------------------------------


def _best_offset(dumps: list[ProcessDump],
                 worker_id: str) -> Optional[tuple[ProcessDump, dict]]:
    """The coordinator dump holding the tightest (min half-RTT error)
    span offset for ``worker_id``."""
    best: Optional[tuple[ProcessDump, dict]] = None
    for dump in dumps:
        offsets = dump.header.get("offsets")
        if not isinstance(offsets, dict):
            continue
        est = offsets.get(worker_id)
        if not isinstance(est, dict) \
                or not isinstance(est.get("offset"), (int, float)):
            continue
        err = est.get("error")
        err = err if isinstance(err, (int, float)) else float("inf")
        if best is None or err < best[1].get("error", float("inf")):
            best = (dump, {"offset": float(est["offset"]),
                           "error": float(err)})
    return best


def _aligner(dump: ProcessDump, dumps: list[ProcessDump]):
    """(mono -> wall) placement function for one dump's events, plus the
    alignment mode and error bound it carries."""
    wall0 = dump.header.get("wall0")
    mono0 = dump.header.get("mono0")
    worker_id = dump.header.get("worker_id")
    if isinstance(worker_id, str):
        best = _best_offset(dumps, worker_id)
        if best is not None:
            coord, est = best
            c_wall0 = coord.header.get("wall0")
            c_mono0 = coord.header.get("mono0")
            if isinstance(c_wall0, (int, float)) \
                    and isinstance(c_mono0, (int, float)):
                offset = est["offset"]

                def align_spans(t: float) -> float:
                    return c_wall0 + (t + offset - c_mono0)

                return align_spans, "spans", est["error"]
    if isinstance(wall0, (int, float)) and isinstance(mono0, (int, float)):

        def align_wall(t: float) -> float:
            return wall0 + (t - mono0)

        return align_wall, "wall", None
    return (lambda t: t), "none", None


# -- assembly --------------------------------------------------------------


@dataclass
class Postmortem:
    dumps: list[ProcessDump]
    file_errors: int = 0
    timeline: list[dict] = field(default_factory=list)
    in_flight: dict = field(default_factory=dict)
    anomalies: list[dict] = field(default_factory=list)

    @property
    def line_errors(self) -> int:
        return sum(d.errors for d in self.dumps)

    @property
    def errors(self) -> int:
        return self.file_errors + self.line_errors

    # -- outputs ------------------------------------------------------

    def summary(self) -> dict:
        """Compact dict for chaos reports: who dumped, what was in
        flight at death, what the detectors flagged."""
        return {
            "processes": [
                {"proc": d.proc, "path": os.path.basename(d.path),
                 "reason": d.header.get("reason"),
                 "events": len(d.events), "errors": d.errors,
                 "shard": d.header.get("shard")}
                for d in self.dumps],
            "events": len(self.timeline),
            "errors": self.errors,
            "in_flight": {proc: [{"key": list(e["key"]),
                                  "t": round(e["t"], 6)}
                                 for e in entries]
                          for proc, entries in self.in_flight.items()},
            "anomalies": self.anomalies,
        }

    def to_dict(self) -> dict:
        doc = self.summary()
        doc["timeline"] = [
            {**e, "key": list(e["key"]) if e.get("key") else None}
            for e in self.timeline]
        return doc

    def render_text(self, limit: Optional[int] = None) -> str:
        lines: list[str] = []
        for d in self.dumps:
            lines.append(
                f"# {d.proc}: {len(d.events)} events, "
                f"{d.errors} bad lines, reason="
                f"{d.header.get('reason', '?')} "
                f"({os.path.basename(d.path)})")
        if self.file_errors:
            lines.append(f"# {self.file_errors} unreadable dump file(s)")
        events = self.timeline
        t0 = events[0]["t"] if events else 0.0
        shown = events if limit is None else events[-limit:]
        if len(shown) < len(events):
            lines.append(f"# ... {len(events) - len(shown)} earlier "
                         f"events elided (--limit)")
        for e in shown:
            parts = [f"+{e['t'] - t0:9.3f}s", f"{e['proc']:<16}",
                     e["name"]]
            if e.get("key") is not None:
                parts.append("key=" + "/".join(str(k) for k in e["key"]))
            if e.get("lease") is not None:
                parts.append(f"lease={e['lease']}")
            if e.get("kv"):
                parts.append(" ".join(f"{k}={v}"
                                      for k, v in sorted(e["kv"].items())))
            if e.get("align") == "spans":
                parts.append(f"(±{e['align_error_s']:.3f}s)")
            lines.append(" ".join(parts))
        for proc, entries in sorted(self.in_flight.items()):
            keys = ", ".join("/".join(str(k) for k in e["key"])
                             for e in entries)
            lines.append(f"IN-FLIGHT at death of {proc}: {keys}")
        for a in self.anomalies:
            lines.append(f"ANOMALY [{a['type']}] {a['detail']}")
        return "\n".join(lines)

    def to_chrome(self) -> dict:
        """Instant events per process, Perfetto-loadable (timestamps
        relative to the first merged event, microseconds)."""
        events: list[dict] = []
        pids: dict[str, int] = {}
        t0 = self.timeline[0]["t"] if self.timeline else 0.0
        for e in self.timeline:
            pid = pids.get(e["proc"])
            if pid is None:
                pid = len(pids)
                pids[e["proc"]] = pid
                events.append({"name": "process_name", "ph": "M",
                               "pid": pid, "tid": 0,
                               "args": {"name": e["proc"]}})
            args = dict(e.get("kv") or {})
            if e.get("key") is not None:
                args["key"] = "/".join(str(k) for k in e["key"])
            if e.get("lease") is not None:
                args["lease"] = e["lease"]
            args["align"] = e["align"]
            events.append({"name": e["name"], "ph": "i", "s": "p",
                           "ts": round((e["t"] - t0) * 1e6, 3),
                           "pid": pid, "tid": 0, "cat": e["cat"],
                           "args": args})
        return {"traceEvents": events, "displayTimeUnit": "ms"}

    def tile_history(self, key: Key) -> list[dict]:
        return [e for e in self.timeline if e.get("key") == tuple(key)]


def _merge_timeline(dumps: list[ProcessDump]) -> list[dict]:
    merged: list[dict] = []
    for dump in dumps:
        align, mode, err = _aligner(dump, dumps)
        for e in dump.events:
            key = e.get("key")
            if isinstance(key, list) and len(key) == 3:
                try:
                    key = tuple(int(k) for k in key)
                except (TypeError, ValueError):
                    key = None
            else:
                key = None
            entry = {
                "t": align(float(e["t"])),
                "proc": dump.proc, "role": dump.role,
                "seq": e.get("seq", 0),
                "cat": e.get("cat", str(e["name"]).partition(".")[0]),
                "name": e["name"], "key": key,
                "lease": e.get("lease"), "kv": e.get("kv") or {},
                "align": mode,
            }
            if err is not None:
                entry["align_error_s"] = round(err, 6)
            merged.append(entry)
    merged.sort(key=lambda e: (e["t"], e["proc"], e["seq"]))
    return merged


def _find_in_flight(dumps: list[ProcessDump],
                    timeline: list[dict]) -> dict:
    """Per process: grants with no settling event by the end of that
    process's dump — the leases in flight when it died (or when its
    last autoflush ran)."""
    by_proc: dict[str, dict[Key, dict]] = {}
    for e in timeline:
        if e["key"] is None:
            continue
        open_grants = by_proc.setdefault(e["proc"], {})
        if e["name"] == obs_events.SCHED_GRANT:
            open_grants[e["key"]] = e
        elif e["name"] in _SETTLING:
            open_grants.pop(e["key"], None)
    return {proc: sorted(grants.values(), key=lambda e: e["t"])
            for proc, grants in by_proc.items() if grants}


def _detect_anomalies(timeline: list[dict], in_flight: dict) -> list[dict]:
    anomalies: list[dict] = []

    # grant-without-accept: an in-flight lease at a process's death,
    # annotated with its re-grant (by whoever owned the key next) when
    # the merged timeline shows one — the chaos coord-kill signature.
    for proc, entries in sorted(in_flight.items()):
        for e in entries:
            regrant = next(
                (r for r in timeline
                 if r["name"] == obs_events.SCHED_GRANT
                 and r["key"] == e["key"] and r["t"] > e["t"]
                 and (r["proc"] != proc or r["seq"] > e["seq"])),
                None)
            detail = (f"{proc} granted "
                      f"{'/'.join(str(k) for k in e['key'])} at its end "
                      f"of record with no accept")
            doc = {"type": "grant-without-accept", "key": list(e["key"]),
                   "proc": proc, "t": round(e["t"], 6), "detail": detail}
            if regrant is not None:
                doc["regranted_by"] = regrant["proc"]
                doc["t_regrant"] = round(regrant["t"], 6)
                doc["detail"] += (f"; re-granted by {regrant['proc']} "
                                  f"{regrant['t'] - e['t']:.3f}s later")
            anomalies.append(doc)

    by_key: dict[Key, list[dict]] = {}
    for e in timeline:
        if e["key"] is not None:
            by_key.setdefault(e["key"], []).append(e)

    for key, events in sorted(by_key.items()):
        names = [e["name"] for e in events]
        grants = names.count(obs_events.SCHED_GRANT)
        expiries = (names.count(obs_events.SCHED_EXPIRE)
                    + names.count(obs_events.SCHED_REQUEUE))
        if grants >= PING_PONG_GRANTS and expiries >= grants - 1:
            anomalies.append({
                "type": "lease-ping-pong", "key": list(key),
                "grants": grants, "expiries": expiries,
                "detail": f"{'/'.join(str(k) for k in key)} granted "
                          f"{grants}x with {expiries} expiries between "
                          f"— lease timeout likely below service time"})
        redirects = names.count(obs_events.SESS_REDIRECT)
        if redirects >= REDIRECT_LOOP_COUNT:
            anomalies.append({
                "type": "redirect-loop", "key": list(key),
                "redirects": redirects,
                "detail": f"{'/'.join(str(k) for k in key)} redirected "
                          f"{redirects}x — stale ring table in some "
                          f"client"})
        accepts = [e for e in events
                   if e["name"] == obs_events.SCHED_ACCEPT]
        procs = {e["proc"] for e in accepts}
        leases = {e["lease"] for e in accepts if e["lease"] is not None}
        if len(procs) > 1 or len(leases) > 1:
            anomalies.append({
                "type": "double-commit", "key": list(key),
                "procs": sorted(procs),
                "detail": f"{'/'.join(str(k) for k in key)} accepted "
                          f"{len(accepts)}x across {sorted(procs)} — "
                          f"check index dedup held"})
        retries = [e for e in events
                   if e["name"] in (obs_events.SESS_RESULT_REJECTED,
                                    obs_events.SCHED_REQUEUE)]
        for i in range(len(retries) - RETRY_STORM_COUNT + 1):
            window = retries[i + RETRY_STORM_COUNT - 1]["t"] - \
                retries[i]["t"]
            if window <= RETRY_STORM_WINDOW_S:
                anomalies.append({
                    "type": "retry-storm", "key": list(key),
                    "count": RETRY_STORM_COUNT,
                    "window_s": round(window, 3),
                    "detail": f"{'/'.join(str(k) for k in key)}: "
                              f"{RETRY_STORM_COUNT} rejects/requeues in "
                              f"{window:.1f}s"})
                break
    return anomalies


def assemble(dump_dir: str, *, registry=None) -> Postmortem:
    """Load every dump under ``dump_dir`` and build the merged,
    clock-aligned timeline plus the anomaly report.  Never raises on
    dump content; an empty/missing directory yields an empty (but
    renderable) postmortem.  ``registry`` (optional) receives the
    ``postmortem_*`` load accounting."""
    dumps, file_errors = load_dir(dump_dir)
    pm = Postmortem(dumps=dumps, file_errors=file_errors)
    pm.timeline = _merge_timeline(dumps)
    pm.in_flight = _find_in_flight(dumps, pm.timeline)
    pm.anomalies = _detect_anomalies(pm.timeline, pm.in_flight)
    if registry is not None:
        registry.inc(obs_names.POSTMORTEM_DUMPS_LOADED, len(dumps))
        registry.inc(obs_names.POSTMORTEM_DUMP_ERRORS, pm.errors)
        registry.inc(obs_names.POSTMORTEM_ANOMALIES, len(pm.anomalies))
    return pm
