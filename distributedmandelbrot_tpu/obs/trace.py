"""Per-tile lifecycle trace: a bounded ring of timestamped events.

The farm's pipeline is ``scheduled -> granted -> result_received ->
persisted`` (plus ``served`` on the read side and ``lease_expired`` /
``requeued`` on the churn side).  Counters say HOW MANY tiles moved;
this ring says WHERE EACH ONE spent its time — the queue wait, the
worker's compute+upload, the persist tail — and, because grant/receive
events carry the worker's connection id, which worker is the straggler
(the load-balance skew the MPI Mandelbrot literature, arxiv 2007.00745,
shows dominating farm wall-clock).

Deliberately a deque ring, not a log file: at level-1000 scale the full
event stream is millions of entries, and the questions the trace answers
("what does a tile's life look like", "who is slow *right now*") only
need a recent window.  Overwritten events are counted, never silently
dropped.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import NamedTuple, Optional

Key = tuple[int, int, int]

# Pipeline order; spans() validates monotonic timestamps along it.
LIFECYCLE = ("scheduled", "granted", "result_received", "persisted",
             "served")
CHURN = ("lease_expired", "requeued")


class TraceEvent(NamedTuple):
    ts: float  # time.monotonic(); deltas only, never wall-clock
    event: str
    key: Key
    worker: Optional[str]  # connection id ("ip:port") where known


class TraceLog:
    """Thread-safe bounded ring of :class:`TraceEvent`.

    Writers are the coordinator loop and worker threads; readers are the
    exporter and tests.  ``capacity`` bounds memory (~100 bytes/event);
    8192 covers a few thousand tile lifetimes of recent history.
    """

    def __init__(self, capacity: int = 8192, *, clock=time.monotonic) -> None:
        if capacity < 1:
            raise ValueError("capacity must be >= 1")
        self.capacity = capacity
        self._clock = clock
        self._events: deque[TraceEvent] = deque(maxlen=capacity)
        self._lock = threading.Lock()
        self._recorded = 0

    def record(self, event: str, key: Key,
               worker: Optional[str] = None) -> None:
        ev = TraceEvent(self._clock(), event, key, worker)
        with self._lock:
            self._events.append(ev)
            self._recorded += 1

    @property
    def recorded(self) -> int:
        """Total events ever recorded (>= len(events()) once wrapped)."""
        with self._lock:
            return self._recorded

    @property
    def dropped(self) -> int:
        """Events the ring has overwritten."""
        with self._lock:
            return self._recorded - len(self._events)

    def events(self) -> list[TraceEvent]:
        with self._lock:
            return list(self._events)

    # -- derived views -----------------------------------------------------

    def spans(self) -> list[dict]:
        """Join events into per-tile latency breakdowns.

        One dict per tile key present in the window: the latest timestamp
        per event type, the worker that produced the result, and the
        stage latencies where both endpoints are in view (``queue_s`` =
        scheduled->granted, ``compute_s`` = granted->result_received,
        ``persist_s`` = result_received->persisted, ``total_s`` =
        scheduled->persisted).  ``complete`` marks tiles whose four
        write-path events are all present in order.
        """
        latest: dict[Key, dict[str, TraceEvent]] = {}
        requeues: dict[Key, int] = {}
        for ev in self.events():
            if ev.event in CHURN:
                requeues[ev.key] = requeues.get(ev.key, 0) + 1
                continue
            latest.setdefault(ev.key, {})[ev.event] = ev
        out = []
        for key in sorted(latest):
            evs = latest[key]
            ts = {name: e.ts for name, e in evs.items()}
            span: dict = {"key": key, "events": ts,
                          "churn": requeues.get(key, 0)}
            got = evs.get("result_received") or evs.get("granted")
            span["worker"] = got.worker if got is not None else None
            write_path = LIFECYCLE[:4]
            present = [ts[n] for n in write_path if n in ts]
            span["complete"] = (len(present) == len(write_path)
                                and present == sorted(present))
            for label, a, b in (("queue_s", "scheduled", "granted"),
                                ("compute_s", "granted", "result_received"),
                                ("persist_s", "result_received", "persisted"),
                                ("total_s", "scheduled", "persisted")):
                if a in ts and b in ts and ts[b] >= ts[a]:
                    span[label] = ts[b] - ts[a]
            out.append(span)
        return out

    def worker_skew(self,
                    reported: Optional[dict[Key, float]] = None) -> dict:
        """Per-worker load summary over the current window.

        For each worker (connection id) seen on a ``result_received``:
        tiles finished and busy seconds.  ``busy_s`` prefers the
        worker-reported compute-span duration for the tile (``reported``
        maps tile key -> seconds, typically ``SpanStore.compute_seconds
        _by_key()``); without one it falls back to the coordinator-only
        grant->receive interval — which also contains network + upload
        time, so each worker's ``busy_source`` labels what the number
        is: ``"reported"`` (all tiles span-backed), ``"lease"`` (pure
        fallback), or ``"mixed"``.  The headline ``skew`` is max busy /
        mean busy across workers — 1.0 is a perfectly balanced farm; the
        MPI-paper pathology shows up as one worker's skew >> 1 while the
        rest idle.
        """
        reported = reported or {}
        busy: dict[str, float] = {}
        tiles: dict[str, int] = {}
        span_tiles: dict[str, int] = {}
        for span in self.spans():
            worker = span.get("worker")
            if worker is None:
                continue
            if span["key"] in reported:
                dur = reported[span["key"]]
                from_span = True
            elif "compute_s" in span:
                dur = span["compute_s"]
                from_span = False
            else:
                continue
            busy[worker] = busy.get(worker, 0.0) + dur
            tiles[worker] = tiles.get(worker, 0) + 1
            span_tiles[worker] = span_tiles.get(worker, 0) + from_span
        if not busy:
            return {"workers": {}, "skew": None}

        def source(w: str) -> str:
            if span_tiles[w] == tiles[w]:
                return "reported"
            return "lease" if span_tiles[w] == 0 else "mixed"

        mean = sum(busy.values()) / len(busy)
        return {
            "workers": {w: {"tiles": tiles[w],
                            "busy_s": round(busy[w], 6),
                            "busy_source": source(w)}
                        for w in sorted(busy)},
            "skew": round(max(busy.values()) / mean, 3) if mean > 0 else None,
        }
