"""SLO burn rates over sampler history + worker straggler detection.

The serving story needs two standing questions answered continuously:

- **"Is the SLO burning?"** — answered Google-SRE style with
  multi-window burn rates.  An SLO is an objective over a ratio of good
  events (``objective=0.99`` means 1% error budget); the *burn rate* is
  how fast the budget is being spent (``error_rate / (1 - objective)``,
  so burn 1.0 exactly exhausts the budget over the SLO period and burn
  10 exhausts it 10x faster).  An alert fires only when BOTH a fast
  window (default 5 m: catches cliffs quickly) and a slow window
  (default 1 h: ignores blips) exceed the threshold, which is the
  standard trick for alerts that are simultaneously fast and unflappy.
  All windows are read from the :class:`~distributedmandelbrot_tpu.obs
  .timeseries.TimeseriesSampler`'s stored history, so the math is pure
  and virtual-clock testable: feed a ManualClock sampler synthetic
  good/bad streams and the burn values are exact.

- **"Which worker is the straggler?"** — answered from the per-worker
  span statistics the coordinator already ingests (obs/spans.py): a
  worker whose compute seconds-per-tile or lease-to-upload wall time is
  a robust-statistics outlier against the farm median gets flagged
  (ROADMAP item 4's signal; the MPI reference shows rank-level load
  imbalance is exactly this workload's dominant scaling loss).

State machine per SLO: ``ok`` -> (fast AND slow over threshold)
``firing`` -> (fast recovered, slow still burning) ``hold`` -> (slow
recovered) ``ok``; re-entering ``firing`` from ``hold`` does not
re-count a fire unless the alert fully recovered first.
"""

from __future__ import annotations

import bisect
from typing import Callable, NamedTuple, Optional, Sequence

from distributedmandelbrot_tpu.obs import events as obs_events
from distributedmandelbrot_tpu.obs import flight
from distributedmandelbrot_tpu.obs import names as obs_names
from distributedmandelbrot_tpu.obs.timeseries import (TimeseriesSampler,
                                                      family_of)

# Ring events attached to a firing alert's status doc: enough trailing
# flight-recorder context to see what the process was doing when the
# burn crossed the threshold, without shipping the whole ring.
EVIDENCE_TAIL = 40

DEFAULT_FAST_WINDOW = 300.0
DEFAULT_SLOW_WINDOW = 3600.0
DEFAULT_BURN_THRESHOLD = 10.0

# Gateway request outcomes that count against availability.  Everything
# else (cache hits, computes, renders, first paints, redirects — the
# client got a correct answer or a correct pointer) is good.
BAD_OUTCOMES = frozenset({
    obs_names.OUTCOME_UNAVAILABLE,
    obs_names.OUTCOME_REJECTED,
    obs_names.OUTCOME_OVERLOADED,
    obs_names.OUTCOME_SESSION_THROTTLED,
})

STATE_OK = "ok"
STATE_FIRING = "firing"
STATE_HOLD = "hold"


class WindowBurn(NamedTuple):
    window_s: float
    good: int
    bad: int
    error_rate: float
    burn: float


def burn_rate(good: int, bad: int, objective: float) -> float:
    """How fast the error budget burns: 1.0 = exactly on budget."""
    total = good + bad
    if total <= 0:
        return 0.0
    budget = 1.0 - objective
    if budget <= 0:
        return float("inf") if bad else 0.0
    return (bad / total) / budget


def _outcome_of(label: str) -> Optional[str]:
    """``hist{outcome=computed}`` -> ``computed`` (None if unlabeled)."""
    if "{" not in label:
        return None
    body = label.split("{", 1)[1].rstrip("}")
    for part in body.split(","):
        k, _, v = part.partition("=")
        if k == "outcome":
            return v
    return None


class _BaseSLO:
    """Shared window plumbing + the fast/slow alert state machine."""

    def __init__(self, name: str, sampler: TimeseriesSampler, *,
                 objective: float = 0.99,
                 fast_window: float = DEFAULT_FAST_WINDOW,
                 slow_window: float = DEFAULT_SLOW_WINDOW,
                 burn_threshold: float = DEFAULT_BURN_THRESHOLD) -> None:
        if not 0.0 < objective < 1.0:
            raise ValueError(f"objective {objective} outside (0, 1)")
        self.name = name
        self.sampler = sampler
        self.objective = float(objective)
        self.fast_window = float(fast_window)
        self.slow_window = float(slow_window)
        self.burn_threshold = float(burn_threshold)
        self.state = STATE_OK
        self.fired = 0
        self.recovered = 0

    # subclasses: (good, bad) event deltas inside the trailing window
    def _window_counts(self, window: float,
                       now: Optional[float]) -> tuple[int, int]:
        raise NotImplementedError

    def window_burn(self, window: float, *,
                    now: Optional[float] = None) -> WindowBurn:
        good, bad = self._window_counts(window, now)
        total = good + bad
        err = (bad / total) if total > 0 else 0.0
        return WindowBurn(window, good, bad, err,
                          burn_rate(good, bad, self.objective))

    def evaluate(self, *, now: Optional[float] = None) -> dict:
        """Advance the alert state machine one step and report it."""
        fast = self.window_burn(self.fast_window, now=now)
        slow = self.window_burn(self.slow_window, now=now)
        over_fast = fast.burn >= self.burn_threshold
        over_slow = slow.burn >= self.burn_threshold
        reg = self.sampler.registry
        evidence: Optional[list[dict]] = None
        if self.state == STATE_OK:
            if over_fast and over_slow:
                self.state = STATE_FIRING
                self.fired += 1
                reg.inc(obs_names.SLO_ALERTS_FIRED,
                        labels={"slo": self.name})
                evidence = self._on_fire(fast, slow)
        elif self.state == STATE_FIRING:
            if not over_slow:
                self.state = STATE_OK
                self.recovered += 1
                reg.inc(obs_names.SLO_ALERTS_RECOVERED,
                        labels={"slo": self.name})
                self._on_recover()
            elif not over_fast:
                self.state = STATE_HOLD
        else:  # hold: slow window still burning, fast recovered
            if not over_slow:
                self.state = STATE_OK
                self.recovered += 1
                reg.inc(obs_names.SLO_ALERTS_RECOVERED,
                        labels={"slo": self.name})
                self._on_recover()
            elif over_fast:
                self.state = STATE_FIRING
        for win, wb in (("fast", fast), ("slow", slow)):
            reg.set_gauge(obs_names.GAUGE_SLO_BURN, wb.burn,
                          labels={"slo": self.name, "window": win})
        out = {
            "name": self.name, "objective": self.objective,
            "state": self.state, "fired": self.fired,
            "recovered": self.recovered,
            "burn_threshold": self.burn_threshold,
            "fast": {"window_s": fast.window_s, "good": fast.good,
                     "bad": fast.bad,
                     "error_rate": round(fast.error_rate, 6),
                     "burn": round(fast.burn, 4)},
            "slow": {"window_s": slow.window_s, "good": slow.good,
                     "bad": slow.bad,
                     "error_rate": round(slow.error_rate, 6),
                     "burn": round(slow.burn, 4)},
        }
        if evidence is not None:
            out["evidence"] = evidence
        return out

    def _on_fire(self, fast: WindowBurn,
                 slow: WindowBurn) -> Optional[list[dict]]:
        """Fire transition: note the event, dump the black box (an SLO
        fire is a crash-grade moment for postmortems) and return the
        ring tail as alert evidence."""
        flight.note(obs_events.SLO_FIRE, slo=self.name,
                    fast_burn=round(fast.burn, 4),
                    slow_burn=round(slow.burn, 4))
        rec = flight.get()
        if rec is None:
            return None
        if rec.dump_dir is not None:
            try:
                rec.dump(reason=f"slo:{self.name}")
            except Exception:
                pass
        return rec.tail(EVIDENCE_TAIL)

    def _on_recover(self) -> None:
        flight.note(obs_events.SLO_RECOVER, slo=self.name)


class AvailabilitySLO(_BaseSLO):
    """Fraction of gateway requests that resolved to an answer, from
    the per-outcome children of the request histogram family."""

    def __init__(self, sampler: TimeseriesSampler, *,
                 name: str = "gateway_availability",
                 family: str = obs_names.HIST_GATEWAY_REQUEST_SECONDS,
                 bad_outcomes: frozenset[str] = BAD_OUTCOMES,
                 **kwargs) -> None:
        super().__init__(name, sampler, **kwargs)
        self.family = family
        self.bad_outcomes = bad_outcomes

    def _per_outcome(self, s) -> dict[str, int]:
        out: dict[str, int] = {}
        for label, (_counts, _sum, count) in s.hists.items():
            if family_of(label) != self.family:
                continue
            outcome = _outcome_of(label) or ""
            out[outcome] = out.get(outcome, 0) + count
        return out

    def _window_counts(self, window: float,
                       now: Optional[float]) -> tuple[int, int]:
        samples = self.sampler.samples(window=window, now=now)
        if len(samples) < 2:
            return 0, 0
        first = self._per_outcome(samples[0])
        last = self._per_outcome(samples[-1])
        good = bad = 0
        for outcome, n in last.items():
            delta = max(0, n - first.get(outcome, 0))
            if outcome in self.bad_outcomes:
                bad += delta
            else:
                good += delta
        return good, bad


class LatencySLO(_BaseSLO):
    """Fraction of requests at or under ``threshold_s``, from the
    histogram family's merged bucket-count deltas (threshold resolution
    is the bucket grid — pick a threshold on a bucket bound)."""

    def __init__(self, sampler: TimeseriesSampler, *,
                 threshold_s: float = 0.1024,
                 name: Optional[str] = None,
                 family: str = obs_names.HIST_GATEWAY_REQUEST_SECONDS,
                 **kwargs) -> None:
        super().__init__(name or f"gateway_latency_{threshold_s:g}s",
                         sampler, **kwargs)
        self.family = family
        self.threshold_s = float(threshold_s)

    def _window_counts(self, window: float,
                       now: Optional[float]) -> tuple[int, int]:
        pts = self.sampler.hist_points(self.family, window=window, now=now)
        bounds = self.sampler.bounds_for(self.family)
        if len(pts) < 2 or bounds is None:
            return 0, 0
        _, c_first, _, _ = pts[0]
        _, c_last, _, _ = pts[-1]
        delta = [max(0, b - a) for a, b in zip(c_first, c_last)]
        # Buckets are <= bound; nudge the threshold so a threshold set
        # exactly on a bound includes its bucket despite float noise.
        idx = bisect.bisect_right(bounds, self.threshold_s * (1 + 1e-9))
        good = sum(delta[:idx])
        bad = sum(delta[idx:])
        return good, bad


def standard_slos(sampler: TimeseriesSampler, *,
                  availability_objective: float = 0.99,
                  latency_objective: float = 0.95,
                  latency_threshold_s: float = 0.1024,
                  fast_window: float = DEFAULT_FAST_WINDOW,
                  slow_window: float = DEFAULT_SLOW_WINDOW,
                  burn_threshold: float = DEFAULT_BURN_THRESHOLD
                  ) -> list[_BaseSLO]:
    """The pair every gateway-bearing process runs: availability and
    p-latency over the request histogram.  0.1024 s sits exactly on a
    DEFAULT_BUCKETS bound (1e-4 * 2^10)."""
    common = dict(fast_window=fast_window, slow_window=slow_window,
                  burn_threshold=burn_threshold)
    return [
        AvailabilitySLO(sampler, objective=availability_objective,
                        **common),
        LatencySLO(sampler, objective=latency_objective,
                   threshold_s=latency_threshold_s, **common),
    ]


# -- straggler detection ----------------------------------------------------

def _median(values: Sequence[float]) -> float:
    s = sorted(values)
    n = len(s)
    if n == 0:
        return 0.0
    mid = n // 2
    return s[mid] if n % 2 else (s[mid - 1] + s[mid]) / 2.0


def detect_stragglers(rows: Sequence[dict], *, factor: float = 2.0,
                      min_peers: int = 3, min_tiles: int = 2,
                      abs_floor_s: float = 0.05) -> dict[str, list[str]]:
    """Flag workers whose per-tile timings are outliers vs the farm.

    ``rows`` are per-worker dicts (``SpanStore.per_worker_stats`` /
    fleet-merged): ``{"worker": id, "tiles": n, "compute_s": total,
    "lease_to_persist_s": total}``.  A worker is a straggler on a
    signal when its per-tile value exceeds ``factor`` x the farm median
    AND the excess clears ``abs_floor_s`` (a 2x outlier among
    microsecond medians is noise, not a straggler).  Needs at least
    ``min_peers`` qualifying workers — a median of two is meaningless.

    Returns ``{worker_id: [reasons...]}`` for flagged workers only.
    """
    signals = (("compute_s", "slow_compute"),
               ("lease_to_persist_s", "lease_to_persist_skew"))
    flagged: dict[str, list[str]] = {}
    for field, reason in signals:
        per_tile: list[tuple[str, float]] = []
        for row in rows:
            tiles = row.get("tiles", 0)
            total = row.get(field)
            if tiles >= min_tiles and isinstance(total, (int, float)):
                per_tile.append((str(row.get("worker")),
                                 float(total) / tiles))
        if len(per_tile) < min_peers:
            continue
        med = _median([v for _, v in per_tile])
        for worker, v in per_tile:
            if v > factor * med and v - med > abs_floor_s:
                flagged.setdefault(worker, []).append(reason)
    return flagged
