"""Thread-safe metrics registry: counters, gauges, log-bucketed histograms.

Stdlib-only by constraint (pyproject depends on numpy + jax alone) and by
taste: the whole farm needs maybe thirty instruments, and a registry this
size is easier to reason about than a client library.  One lock guards
every instrument — contention is irrelevant at coordinator request rates
(thousands/s at most, each update a few dict operations), and a single
lock makes ``snapshot()`` a consistent cut, which the tests pin.

Histograms use fixed log-spaced bucket bounds (default ~100 µs to ~105 s,
x2 per bucket) and ``observe()`` takes SECONDS; percentiles are estimated
by linear interpolation inside the winning bucket, the standard
Prometheus ``histogram_quantile`` rule, so ``/varz`` and a real scraper
agree on p99 up to bucket resolution.

Instruments may carry labels: ``registry.observe(name, dt, labels={
"outcome": "tier1_hit"})`` materializes one child per distinct label set
under the same family name, rendered the Prometheus way.
"""

from __future__ import annotations

import bisect
import threading
import time
from contextlib import contextmanager
from typing import Callable, Iterator, Mapping, Optional, Sequence

# ~100 µs .. ~105 s, x2 spacing: one histogram shape serves everything
# from a tier-1 cache hit (tens of µs, clamped into the first bucket) to
# a compute-on-read wait bounded by the two-minute on-demand deadline.
DEFAULT_BUCKETS: tuple[float, ...] = tuple(1e-4 * 2 ** i for i in range(21))

LabelKey = tuple[tuple[str, str], ...]


def _label_key(labels: Optional[Mapping[str, str]]) -> LabelKey:
    if not labels:
        return ()
    return tuple(sorted((str(k), str(v)) for k, v in labels.items()))


def quantile_from_counts(bounds: Sequence[float], counts: Sequence[int],
                         q: float) -> float:
    """Deterministic quantile (``q`` in 0..1) from a bucket-count vector.

    ``counts[i]`` holds observations ``<= bounds[i]``; a trailing extra
    entry is the +Inf overflow bucket.  The edge cases are pinned rather
    than left to interpolation:

    - an empty vector returns 0.0 (a timeseries point needs a number,
      and "no observations yet" plots as zero latency, not a gap);
    - ``q >= 1.0`` returns the upper bound of the highest nonempty
      bucket EXACTLY — interpolation at the max must never manufacture
      a value past the last log bucket the data actually reached;
    - the overflow bucket always reports ``bounds[-1]`` (the histogram
      cannot see past its last boundary).
    """
    total = sum(counts)
    if total <= 0:
        return 0.0
    q = min(max(float(q), 0.0), 1.0)
    if q >= 1.0:
        for i in range(len(counts) - 1, -1, -1):
            if counts[i] > 0:
                return bounds[min(i, len(bounds) - 1)]
        return 0.0  # unreachable: total > 0 means some count is nonzero
    rank = q * total
    cum = 0
    for i, c in enumerate(counts):
        cum += c
        if cum >= rank and c > 0:
            if i >= len(bounds):
                return bounds[-1]
            lo = bounds[i - 1] if i > 0 else 0.0
            hi = bounds[i]
            frac = (rank - (cum - c)) / c
            return lo + (hi - lo) * min(max(frac, 0.0), 1.0)
    return bounds[-1]


class Counter:
    """Monotonic integer, incremented under the registry's lock."""

    kind = "counter"
    __slots__ = ("name", "labels", "value", "_lock")

    def __init__(self, name: str, labels: LabelKey,
                 lock: threading.Lock) -> None:
        self.name = name
        self.labels = labels
        self.value = 0
        self._lock = lock

    def inc(self, by: int = 1) -> None:
        with self._lock:
            self.value += by


class Gauge:
    """Last-set value; ``fn`` makes it a live callback gauge, evaluated
    at collection time (frontier depth and the cache hit ratios read
    scheduler/cache state instead of being pushed on every mutation)."""

    kind = "gauge"
    __slots__ = ("name", "labels", "value", "fn", "_lock")

    def __init__(self, name: str, labels: LabelKey, lock: threading.Lock,
                 fn: Optional[Callable[[], float]] = None) -> None:
        self.name = name
        self.labels = labels
        self.value = 0.0
        self.fn = fn
        self._lock = lock

    def set(self, value: float) -> None:
        with self._lock:
            self.value = float(value)

    def read(self) -> float:
        if self.fn is not None:
            try:
                return float(self.fn())
            except Exception:
                return float("nan")  # a broken callback must not kill /metrics
        with self._lock:
            return self.value


class Histogram:
    """Fixed-bound histogram of durations in seconds."""

    kind = "histogram"
    __slots__ = ("name", "labels", "bounds", "counts", "sum", "count",
                 "_lock")

    def __init__(self, name: str, labels: LabelKey, lock: threading.Lock,
                 buckets: Sequence[float] = DEFAULT_BUCKETS) -> None:
        bounds = tuple(sorted(float(b) for b in buckets))
        if not bounds:
            raise ValueError("histogram needs at least one bucket bound")
        self.name = name
        self.labels = labels
        self.bounds = bounds
        # counts[i] observations <= bounds[i]; counts[-1] is the +Inf
        # overflow bucket.
        self.counts = [0] * (len(bounds) + 1)
        self.sum = 0.0
        self.count = 0
        self._lock = lock

    def observe(self, seconds: float) -> None:
        seconds = float(seconds)
        i = bisect.bisect_left(self.bounds, seconds)
        with self._lock:
            self.counts[i] += 1
            self.sum += seconds
            self.count += 1

    def percentile(self, q: float) -> Optional[float]:
        """Estimated q-th percentile (0..100) by linear interpolation
        inside the winning bucket (:func:`quantile_from_counts`); None
        with no observations.  p100 reports the highest nonempty
        bucket's upper bound exactly, and the overflow bucket reports
        its lower bound (the histogram cannot see past its last
        boundary)."""
        with self._lock:
            total = self.count
            counts = list(self.counts)
        if total == 0:
            return None
        return quantile_from_counts(self.bounds, counts,
                                    max(q, 0.0) / 100.0)

    def state(self) -> tuple[list[int], float, int]:
        """Consistent (bucket counts, sum, count) cut for rendering."""
        with self._lock:
            return list(self.counts), self.sum, self.count


class Registry:
    """Get-or-create instrument registry; one per process/coordinator.

    A name is bound to one kind forever — re-registering ``x`` as a gauge
    after it was a counter raises, because a family rendered under two
    TYPEs is the exposition-format bug scrapers choke on.
    """

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._instruments: dict[tuple[str, LabelKey],
                                Counter | Gauge | Histogram] = {}
        self._kinds: dict[str, str] = {}
        self._help: dict[str, str] = {}
        self._buckets: dict[str, tuple[float, ...]] = {}

    # -- instrument access ------------------------------------------------

    def _get(self, name: str, kind: str, labels: Optional[Mapping[str, str]],
             factory) -> Counter | Gauge | Histogram:
        key = (name, _label_key(labels))
        with self._lock:
            inst = self._instruments.get(key)
            if inst is not None:
                if inst.kind != kind:
                    raise ValueError(
                        f"metric {name!r} is a {inst.kind}, not a {kind}")
                return inst
            bound = self._kinds.setdefault(name, kind)
            if bound != kind:
                raise ValueError(
                    f"metric {name!r} is a {bound}, not a {kind}")
            inst = factory(key[1])
            self._instruments[key] = inst
            return inst

    def counter(self, name: str,
                labels: Optional[Mapping[str, str]] = None,
                help: Optional[str] = None) -> Counter:
        if help:
            with self._lock:
                self._help.setdefault(name, help)
        return self._get(name, "counter", labels,
                         lambda lk: Counter(name, lk, self._lock))

    def gauge(self, name: str,
              labels: Optional[Mapping[str, str]] = None,
              help: Optional[str] = None,
              fn: Optional[Callable[[], float]] = None) -> Gauge:
        if help:
            with self._lock:
                self._help.setdefault(name, help)
        g = self._get(name, "gauge", labels,
                      lambda lk: Gauge(name, lk, self._lock, fn=fn))
        if fn is not None:
            g.fn = fn  # re-registering may attach/refresh the callback
        return g

    def histogram(self, name: str,
                  labels: Optional[Mapping[str, str]] = None,
                  buckets: Optional[Sequence[float]] = None,
                  help: Optional[str] = None) -> Histogram:
        # Every child of a family shares the first-registered bounds, or
        # the merged family percentiles would be meaningless.
        with self._lock:
            if help:
                self._help.setdefault(name, help)
            if buckets is not None:
                self._buckets.setdefault(name, tuple(sorted(
                    float(b) for b in buckets)))
            bounds = self._buckets.setdefault(name, DEFAULT_BUCKETS)
        return self._get(name, "histogram", labels,
                         lambda lk: Histogram(name, lk, self._lock, bounds))

    # -- write helpers ----------------------------------------------------

    def inc(self, name: str, by: int = 1,
            labels: Optional[Mapping[str, str]] = None) -> None:
        self.counter(name, labels).inc(by)

    def set_gauge(self, name: str, value: float,
                  labels: Optional[Mapping[str, str]] = None) -> None:
        self.gauge(name, labels).set(value)

    def observe(self, name: str, seconds: float,
                labels: Optional[Mapping[str, str]] = None) -> None:
        self.histogram(name, labels).observe(seconds)

    @contextmanager
    def timed(self, name: str,
              labels: Optional[Mapping[str, str]] = None) -> Iterator[None]:
        """``with registry.timed("store_write_seconds"): ...`` — observes
        the block's duration even when it raises (a failing save is
        exactly the latency an operator needs to see)."""
        t0 = time.monotonic()
        try:
            yield
        finally:
            self.observe(name, time.monotonic() - t0, labels)

    # -- read side --------------------------------------------------------

    def counter_value(self, name: str,
                      labels: Optional[Mapping[str, str]] = None
                      ) -> Optional[int]:
        """Counter value, or None if never registered — NEVER creates."""
        with self._lock:
            inst = self._instruments.get((name, _label_key(labels)))
            if isinstance(inst, Counter):
                return inst.value
            return None

    def collect(self) -> list[tuple[str, str, str,
                                    list[Counter | Gauge | Histogram]]]:
        """Families for exposition: (name, kind, help, children), children
        in first-registration order, families sorted by name."""
        with self._lock:
            items = list(self._instruments.items())
            kinds = dict(self._kinds)
            helps = dict(self._help)
        families: dict[str, list] = {}
        for (name, _), inst in items:
            families.setdefault(name, []).append(inst)
        return [(name, kinds[name], helps.get(name, ""), children)
                for name, children in sorted(families.items())]

    def family_percentile(self, name: str, q: float) -> Optional[float]:
        """Percentile over ALL children of a histogram family merged (the
        children share bounds by construction), e.g. gateway request
        latency across every outcome."""
        children = [inst for (n, _), inst in self._iter_instruments()
                    if n == name and isinstance(inst, Histogram)]
        if not children:
            return None
        merged = Histogram(name, (), threading.Lock(), children[0].bounds)
        for h in children:
            counts, total, count = h.state()
            for i, c in enumerate(counts):
                merged.counts[i] += c
            merged.sum += total
            merged.count += count
        return merged.percentile(q)

    def _iter_instruments(self):
        with self._lock:
            return list(self._instruments.items())

    def snapshot(self) -> dict:
        """Structured JSON-ready snapshot (the /varz payload's core)."""
        counters: dict[str, int] = {}
        gauges: dict[str, float] = {}
        histograms: dict[str, dict] = {}
        for (name, lk), inst in self._iter_instruments():
            label = name if not lk else (
                name + "{" + ",".join(f"{k}={v}" for k, v in lk) + "}")
            if isinstance(inst, Counter):
                counters[label] = inst.value
            elif isinstance(inst, Gauge):
                gauges[label] = inst.read()
            else:
                _, total, count = inst.state()
                histograms[label] = {
                    "count": count,
                    "sum": round(total, 6),
                    "p50": inst.percentile(50),
                    "p90": inst.percentile(90),
                    "p99": inst.percentile(99),
                }
        return {"counters": counters, "gauges": gauges,
                "histograms": histograms}
