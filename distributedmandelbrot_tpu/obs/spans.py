"""Cross-process span tracing: worker recorder, clock alignment, merged store.

PR 2's trace ring is coordinator-local: a tile's whole worker life —
lease prefetch wait, per-device dispatch, kernel residency, D2H, upload
— collapses into one opaque ``granted -> result_received`` interval on
the coordinator's clock.  This module is the other half of the timeline:

- :class:`SpanRecorder` — worker-side, thread-safe, bounded.  The worker
  loop and the pipelined executor record per-stage spans (``prefetch`` /
  ``dispatch`` / ``compute`` / ``d2h`` / ``upload``, names in
  obs/names.py) keyed by tile key + lease sequence, all on the worker's
  ``time.monotonic``.  Drained after each upload and pushed over the
  ``PURPOSE_SPANS`` wire extension (net/protocol.py).
- :class:`ClockOffsetEstimator` — NTP-style per-worker offset from the
  lease round-trip.  The worker samples its clock just before sending a
  lease request (``t_req``) and just after the grant arrives
  (``t_recv``); the coordinator stamped the grant at ``c_grant`` on its
  own clock.  The grant sits somewhere inside the round trip, so
  ``offset = c_grant - (t_req + t_recv) / 2`` with error bounded by half
  the round trip — the classic NTP midpoint with one server timestamp.
  Among many samples the minimum-RTT one wins (least bound).
- :class:`SpanStore` — coordinator-side merge point.  Raw worker-clock
  spans are kept per worker and aligned to the coordinator clock at read
  time, so a later, tighter offset sample retroactively improves every
  span already ingested.
- :func:`critical_path` — attributes each complete tile's life across
  queue / compute / d2h / upload / persist, splitting the coordinator's
  opaque grant->receive blob with the worker-reported stages when they
  are present (surfaced in ``dmtpu stats`` and ``bench.py --farm``).

Durations never need alignment (both endpoints share the skew), so the
skew summary and critical-path attribution stay exact even when the
offset estimate is loose; only absolute placement on the merged timeline
carries the round-trip error bound.
"""

from __future__ import annotations

import random
import threading
import time
from collections import deque
from typing import Iterable, NamedTuple, Optional, Sequence

from distributedmandelbrot_tpu.obs import names as obs_names

Key = tuple[int, int, int]

# Per-key lease-sequence map cap: re-grants of the same tile are rare
# (lease expiry), so the map is cleared wholesale past this size rather
# than carrying LRU machinery for a diagnostic field.
_SEQ_MAP_CAP = 65536


class Span(NamedTuple):
    stage: str  # obs_names.SPAN_* value
    key: Key
    t0: float  # worker monotonic seconds
    t1: float
    device: int = 0
    seq: int = 0  # lease sequence (distinguishes re-grants of one tile)


class SyncSample(NamedTuple):
    key: Key
    t_req: float  # worker clock just before the lease request went out
    t_recv: float  # worker clock just after the grant arrived


class OffsetEstimate(NamedTuple):
    offset: float  # coordinator clock - worker clock, seconds
    error: float  # bound: half the grant round trip of the best sample


class ClockOffsetEstimator:
    """Best-of-N NTP midpoint estimate from lease round trips."""

    def __init__(self) -> None:
        self._best: Optional[OffsetEstimate] = None
        self.samples = 0

    def add_sample(self, c_grant: float, t_req: float,
                   t_recv: float) -> None:
        if t_recv < t_req:
            return  # nonsensical sample (clock stepped); ignore
        self.samples += 1
        est = OffsetEstimate(c_grant - (t_req + t_recv) / 2.0,
                             (t_recv - t_req) / 2.0)
        if self._best is None or est.error < self._best.error:
            self._best = est

    @property
    def estimate(self) -> Optional[OffsetEstimate]:
        return self._best


class SpanRecorder:
    """Worker-side bounded span buffer (thread-safe: the pipeline's four
    stage threads all write; the upload stage drains)."""

    def __init__(self, capacity: int = 8192, *,
                 clock=time.monotonic,
                 worker_id: Optional[int] = None) -> None:
        if capacity < 1:
            raise ValueError("capacity must be >= 1")
        self.capacity = capacity
        self.clock = clock
        # Random 64-bit id: stable across this worker's many short
        # connections, so the coordinator can group spans per process.
        self.worker_id = (worker_id if worker_id is not None
                          else random.getrandbits(64))
        self.enabled = True
        self._lock = threading.Lock()
        self._spans: deque[Span] = deque(maxlen=capacity)
        self._syncs: deque[SyncSample] = deque(maxlen=256)
        self._seq = 0
        self._seq_by_key: dict[Key, int] = {}
        self._dropped = 0

    def note_grant(self, keys: Sequence[Key], t_req: float,
                   t_recv: float) -> None:
        """Record one lease exchange: a clock-sync sample (first granted
        key stands for the exchange) plus a ``prefetch`` span per tile."""
        if not self.enabled or not keys:
            return
        with self._lock:
            self._seq += 1
            seq = self._seq & 0xFFFF
            if len(self._seq_by_key) > _SEQ_MAP_CAP:
                self._seq_by_key.clear()
            for k in keys:
                self._seq_by_key[k] = seq
            self._syncs.append(SyncSample(keys[0], t_req, t_recv))
            for k in keys:
                self._append_locked(Span(obs_names.SPAN_PREFETCH, k,
                                         t_req, t_recv, 0, seq))

    def record(self, stage: str, key: Key, t0: float, t1: float,
               device: int = 0) -> None:
        if not self.enabled:
            return
        with self._lock:
            self._append_locked(Span(stage, key, t0, t1, device,
                                     self._seq_by_key.get(key, 0)))

    def _append_locked(self, span: Span) -> None:
        # Caller holds self._lock (the _locked suffix is the contract;
        # both call sites are inside ``with self._lock`` blocks).
        if len(self._spans) == self.capacity:
            # dmtpu: ignore[lock-guard] — held by caller, see above
            self._dropped += 1
        # dmtpu: ignore[lock-guard] — held by caller, see above
        self._spans.append(span)

    @property
    def dropped(self) -> int:
        with self._lock:
            return self._dropped

    def __len__(self) -> int:
        with self._lock:
            return len(self._spans)

    def drain(self) -> tuple[list[SyncSample], list[Span]]:
        """Take everything recorded so far (called after each upload by
        the span-push path; the buffers start empty again)."""
        with self._lock:
            syncs, spans = list(self._syncs), list(self._spans)
            self._syncs.clear()
            self._spans.clear()
        return syncs, spans


class SpanStore:
    """Coordinator-side merge point for remote worker spans.

    ``note_grant`` is called by the distributer at grant time (same
    moment the ``granted`` trace event is recorded) so later sync
    samples can be paired with the coordinator-clock grant timestamp.
    Ingested spans stay on the worker's clock; :meth:`spans` aligns them
    with the current best per-worker offset at read time.
    """

    def __init__(self, capacity: int = 16384, *,
                 grant_capacity: int = 65536) -> None:
        self._lock = threading.Lock()
        self._spans: deque[tuple[int, Span]] = deque(maxlen=capacity)
        self._grants: dict[Key, float] = {}
        self._grant_order: deque[Key] = deque()
        self._grant_capacity = grant_capacity
        self._estimators: dict[int, ClockOffsetEstimator] = {}
        self.ingested = 0

    # -- coordinator-side bookkeeping ---------------------------------

    def note_grant(self, key: Key, ts: float) -> None:
        with self._lock:
            if key not in self._grants:
                self._grant_order.append(key)
            self._grants[key] = ts
            while len(self._grant_order) > self._grant_capacity:
                old = self._grant_order.popleft()
                self._grants.pop(old, None)

    def grant_time(self, key: Key) -> Optional[float]:
        with self._lock:
            return self._grants.get(key)

    # -- ingest --------------------------------------------------------

    def add_sync(self, worker_id: int, c_grant: float, t_req: float,
                 t_recv: float) -> None:
        with self._lock:
            est = self._estimators.get(worker_id)
            if est is None:
                est = self._estimators[worker_id] = ClockOffsetEstimator()
            est.add_sample(c_grant, t_req, t_recv)

    def ingest(self, worker_id: int, spans: Iterable[Span]) -> int:
        n = 0
        with self._lock:
            for span in spans:
                self._spans.append((worker_id, span))
                n += 1
            self.ingested += n
        return n

    # -- read side -----------------------------------------------------

    def offset(self, worker_id: int) -> Optional[OffsetEstimate]:
        with self._lock:
            est = self._estimators.get(worker_id)
        return est.estimate if est is not None else None

    def workers(self) -> list[int]:
        with self._lock:
            seen = {wid for wid, _ in self._spans}
            seen.update(self._estimators)
        return sorted(seen)

    @property
    def unaligned(self) -> int:
        """Spans held for workers with no usable offset estimate yet."""
        with self._lock:
            return sum(1 for wid, _ in self._spans
                       if self._estimators.get(wid) is None
                       or self._estimators[wid].estimate is None)

    def spans(self) -> list[dict]:
        """Ingested spans aligned to the coordinator clock; workers with
        no offset estimate are omitted (their placement is unknowable)."""
        with self._lock:
            items = list(self._spans)
            offsets = {wid: est.estimate
                       for wid, est in self._estimators.items()}
        out = []
        for wid, span in items:
            est = offsets.get(wid)
            if est is None:
                continue
            out.append({
                "worker": wid, "key": span.key, "stage": span.stage,
                "device": span.device, "seq": span.seq,
                "t0": span.t0 + est.offset, "t1": span.t1 + est.offset,
                "align_error_s": est.error,
            })
        out.sort(key=lambda s: s["t0"])
        return out

    def stage_seconds_by_key(self) -> dict[Key, dict[str, float]]:
        """Per-tile summed stage durations (worker-reported; duration
        needs no clock alignment).  The skew fix and the critical-path
        attribution both read this."""
        with self._lock:
            items = [span for _, span in self._spans]
        out: dict[Key, dict[str, float]] = {}
        for span in items:
            stages = out.setdefault(span.key, {})
            stages[span.stage] = (stages.get(span.stage, 0.0)
                                  + max(0.0, span.t1 - span.t0))
        return out

    def compute_seconds_by_key(self) -> dict[Key, float]:
        """Per-tile worker-reported compute seconds — what
        ``TraceLog.worker_skew`` substitutes for its grant->receive
        fallback (``busy_source: "reported"``)."""
        return {key: stages[obs_names.SPAN_COMPUTE]
                for key, stages in self.stage_seconds_by_key().items()
                if obs_names.SPAN_COMPUTE in stages}

    def per_worker_stats(self, persist_s_by_key: Optional[dict[Key, float]]
                         = None) -> dict[str, dict]:
        """Per-worker roll-up for /varz and the fleet aggregator.

        Durations need no clock alignment, so every ingested span
        contributes even before an offset estimate exists.  ``tiles``
        counts distinct (key, lease seq) with a compute span;
        ``lease_to_persist_s`` sums each tile's prefetch-start ->
        upload-end wall time plus the coordinator-side persist seconds
        when the caller joins them in (``persist_s_by_key`` from the
        trace ring) — the straggler detector's skew signal.  Worker ids
        render as zero-padded hex (JSON keys must be strings)."""
        with self._lock:
            items = list(self._spans)
        per: dict[int, dict] = {}
        tiles: dict[tuple[int, Key, int], dict] = {}
        for wid, span in items:
            w = per.setdefault(wid, {
                "tiles": 0, "compute_s": 0.0, "upload_s": 0.0,
                "prefetch_s": 0.0, "lease_to_persist_s": 0.0})
            dur = max(0.0, span.t1 - span.t0)
            if span.stage == obs_names.SPAN_COMPUTE:
                w["compute_s"] += dur
            elif span.stage == obs_names.SPAN_UPLOAD:
                w["upload_s"] += dur
            elif span.stage == obs_names.SPAN_PREFETCH:
                w["prefetch_s"] += dur
            t = tiles.setdefault((wid, span.key, span.seq), {})
            if span.stage == obs_names.SPAN_PREFETCH:
                t["t0"] = min(t.get("t0", span.t0), span.t0)
            elif span.stage == obs_names.SPAN_UPLOAD:
                t["t1"] = max(t.get("t1", span.t1), span.t1)
            elif span.stage == obs_names.SPAN_COMPUTE:
                t["compute"] = True
        for (wid, key, _seq), t in tiles.items():
            w = per[wid]
            if t.get("compute"):
                w["tiles"] += 1
            if "t0" in t and "t1" in t:
                wall = max(0.0, t["t1"] - t["t0"])
                persist = (persist_s_by_key or {}).get(key, 0.0)
                w["lease_to_persist_s"] += wall + persist
        return {format(wid, "016x"):
                {k: (round(v, 6) if isinstance(v, float) else v)
                 for k, v in w.items()}
                for wid, w in per.items()}


def critical_path(trace_spans: list[dict],
                  store: Optional[SpanStore]) -> dict:
    """Attribute complete tiles' lifetimes across the pipeline.

    ``queue`` and ``persist`` come from the coordinator's own events;
    the opaque grant->receive blob splits into ``compute`` (device
    residency minus the D2H tail), ``d2h``, ``upload`` and ``other``
    (network + worker-internal queueing) when the worker reported spans
    for the tile, and is attributed wholesale to ``compute`` otherwise
    (the lease fallback, as in the pre-tracing skew summary).
    """
    by_key = store.stage_seconds_by_key() if store is not None else {}
    sums = {"queue": 0.0, "compute": 0.0, "d2h": 0.0, "upload": 0.0,
            "persist": 0.0, "other": 0.0}
    tiles = attributed = 0
    total = 0.0
    for span in trace_spans:
        if not span.get("complete"):
            continue
        tiles += 1
        total += span.get("total_s", 0.0)
        sums["queue"] += span.get("queue_s", 0.0)
        sums["persist"] += span.get("persist_s", 0.0)
        blob = span.get("compute_s", 0.0)  # granted -> result_received
        stages = by_key.get(span["key"])
        if stages and obs_names.SPAN_COMPUTE in stages:
            attributed += 1
            d2h = stages.get(obs_names.SPAN_D2H, 0.0)
            compute = max(0.0, stages[obs_names.SPAN_COMPUTE] - d2h)
            upload = stages.get(obs_names.SPAN_UPLOAD, 0.0)
            sums["compute"] += compute
            sums["d2h"] += d2h
            sums["upload"] += upload
            sums["other"] += max(0.0, blob - compute - d2h - upload)
        else:
            sums["compute"] += blob
    out: dict = {"tiles": tiles, "attributed_tiles": attributed,
                 "total_s": round(total, 6)}
    for name, secs in sums.items():
        out[f"{name}_s"] = round(secs, 6)
        out[f"{name}_share"] = round(secs / total, 4) if total > 0 else 0.0
    return out


def flush_spans(recorder: Optional[SpanRecorder], client,
                counters) -> None:
    """Drain ``recorder`` and push over the client's 0x04 exchange.

    One copy of the push-after-upload policy shared by the classic
    worker loop and the pipelined executor: a rejected push (legacy
    coordinator closed the connection) disables the recorder, bumps
    ``worker_spans_unsupported`` once, and is never an error — tracing
    degrades, tiles don't.
    """
    if recorder is None or not recorder.enabled:
        return
    push = getattr(client, "push_spans", None)
    if push is None:  # duck-typed in-process client: no wire, no push
        recorder.enabled = False
        return
    syncs, spans = recorder.drain()
    if not syncs and not spans:
        return
    if push(recorder.worker_id, syncs, spans):
        counters.inc(obs_names.WORKER_SPAN_REPORTS)
        counters.inc(obs_names.WORKER_SPANS_PUSHED, len(spans))
    else:
        recorder.enabled = False
        counters.inc(obs_names.WORKER_SPANS_UNSUPPORTED)
        counters.inc(obs_names.WORKER_SPANS_DROPPED, len(spans))
