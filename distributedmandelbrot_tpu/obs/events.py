"""Canonical flight-recorder event names, in one place (like names.py).

The flight recorder (obs/flight.py) is a black box: its value after a
crash depends entirely on every layer having spelled its state
transitions consistently, because the postmortem assembler
(obs/postmortem.py) joins events *by name* across process dumps —
``sched.grant`` on a killed shard must mean the same thing as
``sched.grant`` on the shard that re-granted the tile.  This module is
the arbiter, exactly as ``obs/names.py`` arbitrates metric names, and
the ``obs-event`` rule (analysis/rules_obs.py) is the enforcement: an
event literal at a ``flight.note(...)`` site must be registered here,
and every registration must be emitted somewhere.

Event names are ``category.transition``; the category (the part before
the first dot) is also the sampling-cap bucket in the recorder, so hot
families (worker stage traffic, gateway sheds under storm) can be
rate-capped without touching rare, load-bearing events (checkpoint
seams, crashpoints).
"""

from __future__ import annotations

# -- scheduler lease lifecycle (coordinator/scheduler.py) -----------------

SCHED_GRANT = "sched.grant"
SCHED_CLAIM = "sched.claim"
SCHED_ACCEPT = "sched.accept"
SCHED_RELEASE = "sched.release"
SCHED_EXPIRE = "sched.expire"
SCHED_REQUEUE = "sched.requeue"
SCHED_PRIORITIZE = "sched.prioritize"
SCHED_REFINE = "sched.refine"
SCHED_REOPEN = "sched.reopen"
SCHED_RESTORE = "sched.restore"

# -- distributer session arms (coordinator/distributer.py) ----------------

SESS_OPEN = "sess.open"
SESS_REJECT_FRAME = "sess.reject_frame"
SESS_REDIRECT = "sess.redirect"
SESS_RESULT_REJECTED = "sess.result_rejected"
SESS_RESULT_DROPPED = "sess.result_dropped"

# -- group-commit writer (coordinator/distributer.py persist loop) --------

STORE_FLUSH = "store.flush"
STORE_SAVE_ERROR = "store.save_error"
STORE_REOPEN = "store.reopen"

# -- checkpoint/restore seams (coordinator/recovery.py) -------------------

CKPT_BEGIN = "ckpt.begin"
CKPT_DONE = "ckpt.done"
CKPT_ERROR = "ckpt.error"
CKPT_RESTORE = "ckpt.restore"

# -- gateway admission (serve/gateway.py) ---------------------------------

GW_REJECT = "gw.reject"
GW_SHED = "gw.shed"
GW_SESSION_THROTTLE = "gw.session_throttle"

# -- worker pipeline + backend demotions (worker/) ------------------------

WKR_STAGE = "wkr.stage"
WKR_DEMOTE = "wkr.demote"

# -- fault injection (utils/faults.py) ------------------------------------

FAULT_CRASHPOINT = "fault.crashpoint"

# -- SLO alerting (obs/slo.py) --------------------------------------------

SLO_FIRE = "slo.fire"
SLO_RECOVER = "slo.recover"
