"""In-process ring-buffer timeseries: Registry snapshots over time.

``/metrics`` and ``/varz`` are instantaneous — they cannot answer "is
goodput degrading?" without an external scrape database.  The sampler
closes that gap with the cheapest thing that works: a fixed-capacity
deque of Registry snapshots taken every ``period`` seconds, from which
rates (counter monotonic deltas), gauge traces, and histogram
percentile series are derived AT READ TIME.  Nothing is precomputed, so
a sample is just "copy the instrument values" — microseconds for the
~60 instruments a coordinator carries — and memory is strictly bounded
by ``capacity * instruments``.

The clock is injectable (``coordinator/clock.py`` ManualClock in tests:
call :meth:`TimeseriesSampler.sample` by hand, advance, sample again)
and the live mode is a plain asyncio task on the owning process's loop
(:meth:`run`), started by the coordinator beside its wire services.

Served as ``GET /timeseries?name=<series>&window=<seconds>`` on the
existing exporter (obs/exporter.py); the SLO layer (obs/slo.py) reads
the same history through :meth:`hist_points` / :meth:`counter_points`.
"""

from __future__ import annotations

import asyncio
import threading
import time
from collections import deque
from typing import Callable, NamedTuple, Optional, Sequence

from distributedmandelbrot_tpu.obs import names as obs_names
from distributedmandelbrot_tpu.obs.metrics import (Registry,
                                                   quantile_from_counts)

DEFAULT_SAMPLE_PERIOD = 2.0
DEFAULT_HISTORY_WINDOW = 600.0

# Percentile series served by default (q in percent).
DEFAULT_QUANTILES = (50.0, 99.0)


class Sample(NamedTuple):
    """One consistent cut of the registry at sampler-clock time ``ts``.

    Keys are the ``/varz`` labeled spellings (``name`` or
    ``name{k=v,...}``); histogram values are ``(bucket_counts, sum,
    count)`` so percentiles and threshold counts can be re-derived for
    any window without having stored them."""

    ts: float
    counters: dict[str, int]
    gauges: dict[str, float]
    hists: dict[str, tuple[tuple[int, ...], float, int]]


def family_of(label: str) -> str:
    """``name{outcome=tier1_hit}`` -> ``name``."""
    return label.split("{", 1)[0]


def _labeled(name: str, label_key) -> str:
    if not label_key:
        return name
    return name + "{" + ",".join(f"{k}={v}" for k, v in label_key) + "}"


class TimeseriesSampler:
    """Bounded history of Registry snapshots with derived series.

    Thread-safe: :meth:`sample` may run on any thread (the asyncio task
    in live mode, the test body under a ManualClock) while exporter
    requests read concurrently.  Capacity is fixed at construction from
    ``window / period`` — the deque, not a policy loop, enforces the
    memory bound.
    """

    def __init__(self, registry: Registry, *,
                 period: float = DEFAULT_SAMPLE_PERIOD,
                 window: float = DEFAULT_HISTORY_WINDOW,
                 clock: Callable[[], float] = time.monotonic) -> None:
        if period <= 0:
            raise ValueError(f"sample period {period} must be > 0")
        if window < period:
            raise ValueError(f"history window {window} < period {period}")
        self.registry = registry
        self.period = float(period)
        self.window = float(window)
        self.clock = clock
        self.capacity = max(2, int(window / period) + 2)
        self._lock = threading.Lock()
        self._samples: deque[Sample] = deque(maxlen=self.capacity)
        self._bounds: dict[str, tuple[float, ...]] = {}

    # -- write side --------------------------------------------------------

    def sample(self) -> Sample:
        """Take one snapshot now; returns it (tests assert on the cut)."""
        t0 = time.monotonic()
        now = self.clock()
        counters: dict[str, int] = {}
        gauges: dict[str, float] = {}
        hists: dict[str, tuple[tuple[int, ...], float, int]] = {}
        bounds: dict[str, tuple[float, ...]] = {}
        for name, kind, _help, children in self.registry.collect():
            for inst in children:
                label = _labeled(name, inst.labels)
                if kind == "counter":
                    counters[label] = inst.value
                elif kind == "gauge":
                    gauges[label] = inst.read()
                else:
                    h_counts, h_sum, h_count = inst.state()
                    hists[label] = (tuple(h_counts), h_sum, h_count)
                    bounds[name] = inst.bounds
        s = Sample(now, counters, gauges, hists)
        with self._lock:
            self._samples.append(s)
            self._bounds.update(bounds)
        self.registry.inc(obs_names.TS_SAMPLES)
        self.registry.set_gauge(obs_names.GAUGE_TS_SERIES,
                                len(counters) + len(gauges) + len(hists))
        self.registry.observe(obs_names.HIST_TS_SAMPLE_SECONDS,
                              time.monotonic() - t0)
        return s

    async def run(self) -> None:
        """Live mode: sample every ``period`` seconds until cancelled.
        A plain task on the owner's loop — ``sample()`` is microseconds
        of dict copying, far below the loop's scheduling noise."""
        while True:
            await asyncio.sleep(self.period)
            self.sample()

    # -- raw history -------------------------------------------------------

    def samples(self, *, window: Optional[float] = None,
                now: Optional[float] = None) -> list[Sample]:
        with self._lock:
            items = list(self._samples)
        if window is None:
            return items
        if now is None:
            now = self.clock()
        cutoff = now - window
        return [s for s in items if s.ts >= cutoff]

    def __len__(self) -> int:
        with self._lock:
            return len(self._samples)

    def bounds_for(self, family: str) -> Optional[tuple[float, ...]]:
        with self._lock:
            return self._bounds.get(family)

    def names(self) -> list[str]:
        """Every series name with at least one stored point: both the
        labeled spellings and the bare family names they sum into."""
        with self._lock:
            items = list(self._samples)
        out: set[str] = set()
        for s in items:
            for label in s.counters:
                out.add(label)
                out.add(family_of(label))
            for label in s.gauges:
                out.add(label)
            for label in s.hists:
                out.add(label)
                out.add(family_of(label))
        return sorted(out)

    # -- derived series ----------------------------------------------------

    def counter_points(self, name: str, *, window: Optional[float] = None,
                       now: Optional[float] = None
                       ) -> list[tuple[float, int]]:
        """(ts, value) per sample; an exact labeled name matches itself,
        a bare family name sums every labeled child."""
        pts: list[tuple[float, int]] = []
        for s in self.samples(window=window, now=now):
            if name in s.counters:
                pts.append((s.ts, s.counters[name]))
                continue
            vals = [v for k, v in s.counters.items()
                    if family_of(k) == name]
            if vals:
                pts.append((s.ts, sum(vals)))
        return pts

    def gauge_points(self, name: str, *, window: Optional[float] = None,
                     now: Optional[float] = None
                     ) -> list[tuple[float, float]]:
        pts = []
        for s in self.samples(window=window, now=now):
            if name in s.gauges:
                pts.append((s.ts, s.gauges[name]))
        return pts

    def hist_points(self, name: str, *, window: Optional[float] = None,
                    now: Optional[float] = None
                    ) -> list[tuple[float, list[int], float, int]]:
        """(ts, merged bucket counts, sum, count) per sample, children of
        the family merged (shared bounds by Registry construction)."""
        out: list[tuple[float, list[int], float, int]] = []
        for s in self.samples(window=window, now=now):
            merged: Optional[list[int]] = None
            total = 0.0
            count = 0
            for k, (h_counts, h_sum, h_count) in s.hists.items():
                if k == name or family_of(k) == name:
                    if merged is None:
                        merged = list(h_counts)
                    else:
                        merged = [a + b for a, b in zip(merged, h_counts)]
                    total += h_sum
                    count += h_count
            if merged is not None:
                out.append((s.ts, merged, total, count))
        return out

    @staticmethod
    def rates_from_points(pts: Sequence[tuple[float, float]]
                          ) -> list[tuple[float, float]]:
        """Consecutive monotonic deltas -> per-second rates.  A negative
        delta (process restart reset the counter) clamps to 0 instead of
        plotting a giant negative spike."""
        out: list[tuple[float, float]] = []
        for (t0, v0), (t1, v1) in zip(pts, pts[1:]):
            dt = t1 - t0
            if dt <= 0:
                continue
            out.append((t1, max(0.0, (v1 - v0) / dt)))
        return out

    def rate(self, name: str, *, window: float = 60.0,
             now: Optional[float] = None) -> float:
        """Average per-second rate of a counter over the trailing window
        (first-to-last stored point inside it); 0.0 with <2 points."""
        pts = self.counter_points(name, window=window, now=now)
        if len(pts) < 2:
            return 0.0
        (t0, v0), (t1, v1) = pts[0], pts[-1]
        if t1 <= t0:
            return 0.0
        return max(0.0, (v1 - v0) / (t1 - t0))

    def percentile_series(self, name: str, q: float, *,
                          window: Optional[float] = None,
                          now: Optional[float] = None
                          ) -> list[tuple[float, float]]:
        """Per-sample q-th percentile (0..100) of the family's *interval*
        observations (bucket-count deltas between consecutive samples);
        an idle interval carries the cumulative percentile forward so a
        quiet gateway plots its steady latency, not zeros."""
        bounds = self.bounds_for(name)
        pts = self.hist_points(name, window=window, now=now)
        if bounds is None or len(pts) < 1:
            return []
        out: list[tuple[float, float]] = []
        for (_, c0, _, n0), (t1, c1, _, n1) in zip(pts, pts[1:]):
            delta = [max(0, b - a) for a, b in zip(c0, c1)]
            if n1 > n0:
                out.append((t1, quantile_from_counts(bounds, delta,
                                                     q / 100.0)))
            else:
                out.append((t1, quantile_from_counts(bounds, c1,
                                                     q / 100.0)))
        return out

    def window_percentile(self, name: str, q: float, *,
                          window: Optional[float] = None,
                          now: Optional[float] = None) -> float:
        """One q-th percentile over every observation inside the window
        (delta of the first vs last stored cut; cumulative when the
        window covers the whole history)."""
        bounds = self.bounds_for(name)
        pts = self.hist_points(name, window=window, now=now)
        if bounds is None or not pts:
            return 0.0
        _, c_last, _, n_last = pts[-1]
        _, c_first, _, n_first = pts[0]
        if len(pts) >= 2 and n_last > n_first:
            delta = [max(0, b - a) for a, b in zip(c_first, c_last)]
            return quantile_from_counts(bounds, delta, q / 100.0)
        return quantile_from_counts(bounds, c_last, q / 100.0)

    # -- /timeseries payloads ----------------------------------------------

    def series_json(self, name: str, *, window: Optional[float] = None,
                    now: Optional[float] = None) -> Optional[dict]:
        """The ``/timeseries?name=`` document for one series, or None if
        the name has no stored points of any kind."""
        if now is None:
            now = self.clock()
        counter_pts = self.counter_points(name, window=window, now=now)
        if counter_pts:
            rates = self.rates_from_points(counter_pts)
            return {
                "name": name, "kind": "counter",
                "points": [[round(t, 3), v] for t, v in counter_pts],
                "rates": [[round(t, 3), round(r, 4)] for t, r in rates],
                "window_rate": round(
                    self.rate(name, window=window or self.window, now=now),
                    4),
            }
        gauge_pts = self.gauge_points(name, window=window, now=now)
        if gauge_pts:
            return {
                "name": name, "kind": "gauge",
                "points": [[round(t, 3), round(v, 6)]
                           for t, v in gauge_pts],
            }
        hist_pts = self.hist_points(name, window=window, now=now)
        if hist_pts:
            doc: dict = {
                "name": name, "kind": "histogram",
                "counts": [[round(t, 3), n] for t, _, _, n in hist_pts],
                "rates": [[round(t, 3), round(r, 4)] for t, r in
                          self.rates_from_points(
                              [(t, n) for t, _, _, n in hist_pts])],
                "percentiles": {},
            }
            for q in DEFAULT_QUANTILES:
                doc["percentiles"][f"p{int(q)}"] = [
                    [round(t, 3), round(v, 6)] for t, v in
                    self.percentile_series(name, q, window=window, now=now)]
                doc[f"window_p{int(q)}"] = round(
                    self.window_percentile(name, q, window=window, now=now),
                    6)
            return doc
        return None

    def to_json(self, name: Optional[str] = None, *,
                window: Optional[float] = None,
                now: Optional[float] = None) -> dict:
        """The full ``/timeseries`` response: one series when ``name``
        is given (``{"error": ...}`` for an unknown one), the catalogue
        otherwise."""
        if name:
            doc = self.series_json(name, window=window, now=now)
            if doc is None:
                return {"error": f"unknown series {name!r}",
                        "series": self.names()}
            return doc
        with self._lock:
            stored = len(self._samples)
        return {"series": self.names(), "samples": stored,
                "period_s": self.period, "window_s": self.window,
                "capacity": self.capacity}
