"""Read-side client and rendering."""

from distributedmandelbrot_tpu.viewer.client import DataClient, FetchStatus
from distributedmandelbrot_tpu.viewer.render import (show, smooth_to_rgba,
                                                     stitch_level,
                                                     value_to_rgba)

__all__ = ["DataClient", "FetchStatus", "value_to_rgba", "smooth_to_rgba",
           "stitch_level", "show"]
