"""Rendering: uint8 pixel values -> RGBA images, plus multi-chunk stitching.

``value_to_rgba`` reproduces the reference viewer's colormap pipeline
exactly (``DistributedMandelbrotViewer.py:110-135``): normalize /256,
invert, apply matplotlib's ``jet``, then paint in-set pixels (value 0,
i.e. inverted 1.0) black.

Stitching a whole level into one image is a natural capability extension
(the reference renders only single chunks).
"""

from __future__ import annotations

from typing import Callable, Optional

import numpy as np

from distributedmandelbrot_tpu.core.geometry import CHUNK_WIDTH


def _masked_colormap(vs: np.ndarray, in_set: np.ndarray,
                     colormap: str) -> np.ndarray:
    """Shared tail of both render paths: colormap ``vs``, paint in-set
    pixels black."""
    import matplotlib

    mapped = matplotlib.colormaps[colormap](vs).astype(float)
    black = np.array((0.0, 0.0, 0.0, 1.0))
    return np.where(in_set[..., None], black, mapped)


def value_to_rgba(values: np.ndarray, colormap: str = "jet") -> np.ndarray:
    """Flat or 2-D uint8 values -> float RGBA array (reference pipeline)."""
    if values.ndim == 1:
        side = int(round(values.size ** 0.5))
        if side * side != values.size:
            raise ValueError(f"cannot square-reshape {values.size} pixels")
        values = values.reshape((side, side))
    vs = 1.0 - values.astype(float) / 256.0
    return _masked_colormap(vs, vs == 1.0, colormap)


def smooth_to_rgba(nu: np.ndarray, max_iter: int,
                   colormap: str = "jet",
                   normalize: bool = False) -> np.ndarray:
    """Continuous escape values (:func:`...ops.escape_smooth`) -> RGBA.

    Same visual convention as :func:`value_to_rgba` — in-set (0) pixels
    black, others through the inverted colormap — but band-free: the
    fractional part of ``nu`` varies continuously across iteration
    boundaries.  Log-scaled so deep zooms (large max_iter) keep contrast.

    ``normalize`` stretches the view's OWN escaped-value range over the
    full colormap (log-domain min-max): deep windows occupy a sliver of
    the absolute scale (a span-1e-10 view at budget 50000 spans ~6% of
    it — near-flat color), and auto-contrast is what makes them
    readable.  View-dependent by construction, so animations must NOT
    use it per-frame (the stretch would flicker as ranges drift).
    """
    nu = np.asarray(nu, float)
    logs = np.log1p(np.maximum(nu, 0.0))
    escaped = nu > 0.0
    if normalize and escaped.any():
        sel = logs[escaped]
        lo, hi = float(sel.min()), float(sel.max())
        vs = (logs - lo) / max(hi - lo, 1e-12)
    else:
        vs = logs / np.log1p(float(max_iter))
    return _masked_colormap(1.0 - np.clip(vs, 0.0, 1.0), nu <= 0.0, colormap)


def stitch_level(fetch: Callable[[int, int], Optional[np.ndarray]],
                 level: int, *, chunk_width: int = CHUNK_WIDTH,
                 fill_value: int = 0) -> np.ndarray:
    """Assemble a full level image from per-chunk fetches.

    ``fetch(index_real, index_imag)`` returns flat uint8 pixels or None for
    missing chunks (filled with ``fill_value``).  Output axis order follows
    the chunk-local convention — row = imaginary axis, column = real axis —
    so chunk (i, j) lands at rows ``j*W:(j+1)*W``, cols ``i*W:(i+1)*W``.
    """
    out = np.full((level * chunk_width, level * chunk_width), fill_value,
                  dtype=np.uint8)
    for i in range(level):
        for j in range(level):
            pixels = fetch(i, j)
            if pixels is None:
                continue
            tile = np.asarray(pixels, dtype=np.uint8).reshape(
                (chunk_width, chunk_width))
            out[j * chunk_width:(j + 1) * chunk_width,
                i * chunk_width:(i + 1) * chunk_width] = tile
    return out


def show(rgba: np.ndarray) -> None:  # pragma: no cover - needs a display
    from matplotlib import pyplot as plt

    plt.imshow(rgba)
    plt.show()
