"""Rendering: uint8 pixel values -> RGBA images, plus multi-chunk stitching.

The colormap core (``value_to_rgba`` / ``smooth_to_rgba`` and their
shared ``_masked_colormap`` tail) lives in
:mod:`distributedmandelbrot_tpu.serve.render` since the gateway renders
the same pipeline server-side; this module re-exports it so every
existing viewer import keeps working, and the golden parity test pins
that both consumers see identical bytes.

Stitching a whole level into one image is a natural capability extension
(the reference renders only single chunks).
"""

from __future__ import annotations

from typing import Callable, Optional

import numpy as np

from distributedmandelbrot_tpu.core.geometry import CHUNK_WIDTH
# Canonical re-exports of the shared colormap core (see module docstring).
from distributedmandelbrot_tpu.serve.render import (  # noqa: F401
    _masked_colormap, smooth_to_rgba, value_to_rgba)


def stitch_level(fetch: Callable[[int, int], Optional[np.ndarray]],
                 level: int, *, chunk_width: int = CHUNK_WIDTH,
                 fill_value: int = 0) -> np.ndarray:
    """Assemble a full level image from per-chunk fetches.

    ``fetch(index_real, index_imag)`` returns flat uint8 pixels or None for
    missing chunks (filled with ``fill_value``).  Output axis order follows
    the chunk-local convention — row = imaginary axis, column = real axis —
    so chunk (i, j) lands at rows ``j*W:(j+1)*W``, cols ``i*W:(i+1)*W``.
    """
    out = np.full((level * chunk_width, level * chunk_width), fill_value,
                  dtype=np.uint8)
    for i in range(level):
        for j in range(level):
            pixels = fetch(i, j)
            if pixels is None:
                continue
            tile = np.asarray(pixels, dtype=np.uint8).reshape(
                (chunk_width, chunk_width))
            out[j * chunk_width:(j + 1) * chunk_width,
                i * chunk_width:(i + 1) * chunk_width] = tile
    return out


def show(rgba: np.ndarray) -> None:  # pragma: no cover - needs a display
    from matplotlib import pyplot as plt

    plt.imshow(rgba)
    plt.show()
