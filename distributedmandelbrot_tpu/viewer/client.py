"""Synchronous client for the DataServer protocol (viewer side).

Same exchange as the reference viewer (``DistributedMandelbrotViewer.py:
62-108``): 12-byte query, status byte, length-prefixed codec payload.
Decoding goes through the shared codec registry instead of a hand-rolled
RLE loop, and straight into numpy (the reference round-trips 16M pixels
through a Python list, ``DistributedMandelbrotViewer.py:102``).

Unlike the reference's connection-per-query, the client keeps one
connection open and pipelines queries over it (the server loops until EOF),
which matters on the stitch path — a level-L image is L^2 fetches.  A
broken connection is re-dialed transparently once per fetch.
"""

from __future__ import annotations

import enum
import socket
from typing import Optional

import numpy as np

from distributedmandelbrot_tpu.core.chunk import Chunk
from distributedmandelbrot_tpu.net import framing
from distributedmandelbrot_tpu.net import protocol as proto


class FetchStatus(enum.Enum):
    OK = "ok"
    NOT_AVAILABLE = "not_available"
    REJECTED = "rejected"
    # Gateway only: admission control shed the request — back off and retry.
    OVERLOADED = "overloaded"
    # Sharded gateway only: this shard does not own the key; the
    # authoritative shard is in :attr:`DataClient.last_redirect`.
    REDIRECTED = "redirected"


_STATUS_BY_BYTE = {
    proto.QUERY_NOT_AVAILABLE: FetchStatus.NOT_AVAILABLE,
    proto.QUERY_REJECT: FetchStatus.REJECTED,
    proto.QUERY_OVERLOADED: FetchStatus.OVERLOADED,
}


class DataClient:
    def __init__(self, host: str, port: int, *,
                 timeout: Optional[float] = 30.0) -> None:
        self.host = host
        self.port = port
        self.timeout = timeout
        # (shard, ring_version) from the most recent REDIRECTED reply.
        self.last_redirect: Optional[tuple[int, int]] = None
        # Session framing state: the id the gateway issued (0 = none
        # yet / reopen on next fetch) and the capability bits — the
        # request until the first exchange, the grant after it.
        self.session_id = 0
        self.session_caps = proto.SESSION_CAPS_MASK
        self._sock: Optional[socket.socket] = None

    def _connected(self) -> socket.socket:
        if self._sock is None:
            self._sock = socket.create_connection((self.host, self.port),
                                                  timeout=self.timeout)
            self._sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        return self._sock

    def close(self) -> None:
        if self._sock is not None:
            try:
                self._sock.close()
            finally:
                self._sock = None

    def __enter__(self) -> "DataClient":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    def fetch(self, level: int, index_real: int, index_imag: int
              ) -> tuple[Optional[np.ndarray], FetchStatus]:
        """Fetch one chunk's flat uint8 pixels; (None, status) if unavailable."""
        try:
            return self._fetch_once(level, index_real, index_imag)
        except (ConnectionError, OSError):
            # Stale persistent connection (server restart, idle teardown):
            # re-dial once and retry; a second failure propagates.
            self.close()
            return self._fetch_once(level, index_real, index_imag)

    def _fetch_once(self, level: int, index_real: int, index_imag: int
                    ) -> tuple[Optional[np.ndarray], FetchStatus]:
        sock = self._connected()
        framing.send_all(sock, proto.QUERY.pack(level, index_real, index_imag))
        return self._read_response(sock)

    def _read_redirect(self, sock: socket.socket) -> None:
        """Consume a QUERY_REDIRECT's fixed-size tail (no length prefix)
        and latch it in :attr:`last_redirect`."""
        shard, ring_version = proto.REDIRECT.unpack(
            framing.recv_exact(sock, proto.REDIRECT_WIRE_SIZE))
        self.last_redirect = (shard, ring_version)

    def _read_response(self, sock: socket.socket
                       ) -> tuple[Optional[np.ndarray], FetchStatus]:
        # The model pairs this reader with the dataserver, which never
        # sheds, so the QUERY_OVERLOADED arm is dead in every explored
        # configuration.  The arm is still live in production: the
        # gateway's plain-query path answers the same framing and DOES
        # send OVERLOADED under admission pressure, but it reads the
        # request as u32 + tail rather than one QUERY struct and so
        # sits outside the extracted exchange pairs.  Audited 2026-08.
        status = framing.recv_byte(sock)  # dmtpu: ignore[fsm-dead-arm]
        miss = _STATUS_BY_BYTE.get(status)
        if miss is not None:
            return None, miss
        if status == proto.QUERY_REDIRECT:
            self._read_redirect(sock)
            return None, FetchStatus.REDIRECTED
        if status != proto.QUERY_ACCEPT:
            raise framing.ProtocolError(f"unknown query status {status:#x}")
        # The length word sizes an allocation: bound it before trusting it
        # (a hostile/corrupt server must not pick our buffer sizes).
        length = proto.validate_payload_length(framing.recv_u32(sock))
        payload = framing.recv_exact(sock, length)
        return Chunk.deserialize_data(payload), FetchStatus.OK

    def fetch_render(self, level: int, index_real: int, index_imag: int,
                     colormap_id: int = proto.COLORMAP_JET
                     ) -> tuple[Optional[bytes], FetchStatus]:
        """Fetch one tile server-rendered as a palette PNG (gateway
        extension): returns the PNG body bytes instead of escape counts.

        Decode with :func:`distributedmandelbrot_tpu.serve.render.
        decode_rendered_png` (or any PNG library) — the bytes are pinned
        bit-identical to rendering the raw tile client-side.  Gateway
        only, like :meth:`fetch_many`.
        """
        try:
            return self._fetch_render_once(level, index_real, index_imag,
                                           colormap_id)
        except (ConnectionError, OSError):
            self.close()
            return self._fetch_render_once(level, index_real, index_imag,
                                           colormap_id)

    def _fetch_render_once(self, level: int, index_real: int,
                           index_imag: int, colormap_id: int
                           ) -> tuple[Optional[bytes], FetchStatus]:
        sock = self._connected()
        framing.send_u32(sock, proto.GATEWAY_RENDER_MAGIC)
        return self._render_exchange(sock, level, index_real, index_imag,
                                     colormap_id)

    def _render_exchange(self, sock: socket.socket, level: int,
                         index_real: int, index_imag: int, colormap_id: int
                         ) -> tuple[Optional[bytes], FetchStatus]:
        """The post-magic exchange: 14-byte tail out, status (+ PNG) in.
        (Split from :meth:`_fetch_render_once` so it mirrors the server's
        post-magic handler frame for frame.)"""
        framing.send_all(sock, proto.RENDER_QUERY_TAIL.pack(
            level, index_real, index_imag, colormap_id, 0))
        status = framing.recv_byte(sock)
        miss = _STATUS_BY_BYTE.get(status)
        if miss is not None:
            return None, miss
        if status == proto.QUERY_REDIRECT:
            self._read_redirect(sock)
            return None, FetchStatus.REDIRECTED
        if status != proto.QUERY_ACCEPT:
            raise framing.ProtocolError(f"unknown query status {status:#x}")
        length = proto.validate_payload_length(framing.recv_u32(sock))
        return framing.recv_exact(sock, length), FetchStatus.OK

    def open_session(self, caps: int = proto.SESSION_CAPS_MASK) -> None:
        """Arm the session framing: the next :meth:`fetch_session` opens
        a session requesting ``caps``; later fetches ride the issued id.

        :attr:`session_id` / :attr:`session_caps` expose what the
        gateway granted after the first exchange.  Gateway only — a
        legacy DataServer drops the connection on the magic, which
        surfaces as the usual transport error.
        """
        self.session_id = 0
        self.session_caps = caps

    def fetch_session(self, level: int, index_real: int, index_imag: int,
                      colormap_id: int = proto.COLORMAP_JET
                      ) -> tuple[Optional[bytes], FetchStatus]:
        """Session-scoped render fetch: like :meth:`fetch_render`, but
        the query carries the session id + viewport hint so the gateway
        tracks the trajectory, prefetches ahead of the pan, and may
        serve a cold tile as a fast low-iter first paint (refined in
        the background).  Call :meth:`open_session` once first.

        A soft ``REJECTED`` with the reply id 0 means the session
        expired server-side; the client resets to reopen on the next
        call, so one retry re-establishes the session.
        """
        try:
            return self._fetch_session_once(level, index_real, index_imag,
                                            colormap_id)
        except (ConnectionError, OSError):
            self.close()
            return self._fetch_session_once(level, index_real, index_imag,
                                            colormap_id)

    def _fetch_session_once(self, level: int, index_real: int,
                            index_imag: int, colormap_id: int
                            ) -> tuple[Optional[bytes], FetchStatus]:
        sock = self._connected()
        framing.send_u32(sock, proto.GATEWAY_SESSION_MAGIC)
        flags = self.session_caps if self.session_id == 0 else 0
        return self._session_exchange(sock, self.session_id, level,
                                      index_real, index_imag, colormap_id,
                                      flags)

    def _session_exchange(self, sock: socket.socket, session_id: int,
                          level: int, index_real: int, index_imag: int,
                          colormap_id: int, flags: int
                          ) -> tuple[Optional[bytes], FetchStatus]:
        """The post-magic exchange: 22-byte tail out, reply header +
        status (+ PNG) in.  (Split from :meth:`_fetch_session_once` so it
        mirrors the server's post-magic handler frame for frame.)"""
        framing.send_all(sock, proto.SESSION_QUERY_TAIL.pack(
            session_id, level, index_real, index_imag, colormap_id, flags))
        sid, caps = proto.SESSION_REPLY.unpack(
            framing.recv_exact(sock, proto.SESSION_REPLY_WIRE_SIZE))
        if sid != 0:
            self.session_id = sid
            self.session_caps = caps
        else:
            # Unknown/expired session: reopen (with the original
            # capability request) on the next fetch.
            self.session_id = 0
        status = framing.recv_byte(sock)
        miss = _STATUS_BY_BYTE.get(status)
        if miss is not None:
            return None, miss
        if status == proto.QUERY_REDIRECT:
            self._read_redirect(sock)
            return None, FetchStatus.REDIRECTED
        if status != proto.QUERY_ACCEPT:
            raise framing.ProtocolError(f"unknown query status {status:#x}")
        length = proto.validate_payload_length(framing.recv_u32(sock))
        return framing.recv_exact(sock, length), FetchStatus.OK

    def fetch_many(self, queries: list[tuple[int, int, int]]
                   ) -> list[tuple[Optional[np.ndarray], FetchStatus]]:
        """Batched fetch (gateway extension): one round trip for N tiles.

        Sends ``GATEWAY_BATCH_MAGIC, count, count x 12-byte queries`` and
        reads ``count`` standard responses back in request order.  Only
        gateways understand this framing — a legacy DataServer would read
        the magic as a (rejected) level — so point it at the gateway port.
        """
        if not queries:
            return []
        try:
            return self._fetch_many_once(queries)
        except (ConnectionError, OSError):
            self.close()
            return self._fetch_many_once(queries)

    def _fetch_many_once(self, queries: list[tuple[int, int, int]]
                         ) -> list[tuple[Optional[np.ndarray], FetchStatus]]:
        sock = self._connected()
        request = bytearray()
        request += proto.BATCH_HEADER.pack(proto.GATEWAY_BATCH_MAGIC,
                                           len(queries))
        for level, index_real, index_imag in queries:
            request += proto.QUERY.pack(level, index_real, index_imag)
        framing.send_all(sock, bytes(request))
        return [self._read_response(sock) for _ in queries]


class ShardedDataClient:
    """Ring-aware read fan-out: one :class:`DataClient` per shard.

    Consults the ring before dispatch, so the common case is a direct
    hit on the authoritative shard; a ``REDIRECTED`` reply (version
    skew: the serving fleet runs a different ring) is chased up to
    :data:`~distributedmandelbrot_tpu.net.protocol.MAX_REDIRECT_HOPS`
    times before surfacing as ``REDIRECTED`` to the caller.

    ``ring`` is duck-typed (``shards``, ``owner_of(key)``) — hand it a
    ``control.ring.HashRing``.  ``use_gateway`` picks each shard's
    gateway port when the ring names one (falling back per shard to the
    legacy dataserver port, which never redirects — ring routing alone
    lands those queries on the right index).
    """

    def __init__(self, ring, *, timeout: Optional[float] = 30.0,
                 use_gateway: bool = True) -> None:
        self.ring = ring
        self.clients = []
        for s in ring.shards:
            port = s.gateway_port if use_gateway and s.gateway_port \
                else s.dataserver_port
            self.clients.append(DataClient(s.host, port, timeout=timeout))

    def close(self) -> None:
        for c in self.clients:
            c.close()

    def __enter__(self) -> "ShardedDataClient":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    def fetch(self, level: int, index_real: int, index_imag: int
              ) -> tuple[Optional[np.ndarray], FetchStatus]:
        return self._route(level, index_real, index_imag,
                           lambda c: c.fetch(level, index_real, index_imag))

    def fetch_render(self, level: int, index_real: int, index_imag: int,
                     colormap_id: int = proto.COLORMAP_JET
                     ) -> tuple[Optional[bytes], FetchStatus]:
        return self._route(
            level, index_real, index_imag,
            lambda c: c.fetch_render(level, index_real, index_imag,
                                     colormap_id))

    def _route(self, level: int, index_real: int, index_imag: int, op):
        shard = self.ring.owner_of((level, index_real, index_imag))
        result = None
        for _ in range(proto.MAX_REDIRECT_HOPS + 1):
            client = self.clients[shard]
            result = op(client)
            if result[1] is not FetchStatus.REDIRECTED:
                return result
            assert client.last_redirect is not None
            nxt = client.last_redirect[0]
            if not 0 <= nxt < len(self.clients) or nxt == shard:
                break  # split-brain ring: don't chase a self-redirect
            shard = nxt
        return result
