"""Synchronous client for the DataServer protocol (viewer side).

Same exchange as the reference viewer (``DistributedMandelbrotViewer.py:
62-108``): 12-byte query, status byte, length-prefixed codec payload.
Decoding goes through the shared codec registry instead of a hand-rolled
RLE loop, and straight into numpy (the reference round-trips 16M pixels
through a Python list, ``DistributedMandelbrotViewer.py:102``).

Unlike the reference's connection-per-query, the client keeps one
connection open and pipelines queries over it (the server loops until EOF),
which matters on the stitch path — a level-L image is L^2 fetches.  A
broken connection is re-dialed transparently once per fetch.
"""

from __future__ import annotations

import enum
import socket
import struct
from typing import Optional

import numpy as np

from distributedmandelbrot_tpu.core.chunk import Chunk
from distributedmandelbrot_tpu.net import framing
from distributedmandelbrot_tpu.net import protocol as proto

_QUERY = struct.Struct("<III")


class FetchStatus(enum.Enum):
    OK = "ok"
    NOT_AVAILABLE = "not_available"
    REJECTED = "rejected"


class DataClient:
    def __init__(self, host: str, port: int, *,
                 timeout: Optional[float] = 30.0) -> None:
        self.host = host
        self.port = port
        self.timeout = timeout
        self._sock: Optional[socket.socket] = None

    def _connected(self) -> socket.socket:
        if self._sock is None:
            self._sock = socket.create_connection((self.host, self.port),
                                                  timeout=self.timeout)
            self._sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        return self._sock

    def close(self) -> None:
        if self._sock is not None:
            try:
                self._sock.close()
            finally:
                self._sock = None

    def __enter__(self) -> "DataClient":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    def fetch(self, level: int, index_real: int, index_imag: int
              ) -> tuple[Optional[np.ndarray], FetchStatus]:
        """Fetch one chunk's flat uint8 pixels; (None, status) if unavailable."""
        try:
            return self._fetch_once(level, index_real, index_imag)
        except (ConnectionError, OSError):
            # Stale persistent connection (server restart, idle teardown):
            # re-dial once and retry; a second failure propagates.
            self.close()
            return self._fetch_once(level, index_real, index_imag)

    def _fetch_once(self, level: int, index_real: int, index_imag: int
                    ) -> tuple[Optional[np.ndarray], FetchStatus]:
        sock = self._connected()
        framing.send_all(sock, _QUERY.pack(level, index_real, index_imag))
        status = framing.recv_byte(sock)
        if status == proto.QUERY_NOT_AVAILABLE:
            return None, FetchStatus.NOT_AVAILABLE
        if status == proto.QUERY_REJECT:
            return None, FetchStatus.REJECTED
        if status != proto.QUERY_ACCEPT:
            raise framing.ProtocolError(f"unknown query status {status:#x}")
        length = framing.recv_u32(sock)
        payload = framing.recv_exact(sock, length)
        return Chunk.deserialize_data(payload), FetchStatus.OK
