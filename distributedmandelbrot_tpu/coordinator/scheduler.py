"""Tile scheduler: frontier queue + lease table + completion dedup.

Semantics preserved from the reference's Distributer (at-least-once with
dedup at ingest):

- a tile is handed out iff it is neither completed nor under an unexpired
  lease (``Distributer.cs:317-330``)
- grants are ordered level-setting by level-setting, ``index_real`` outer,
  ``index_imag`` inner (``Distributer.cs:338-340``)
- a result is accepted iff an unexpired matching lease exists; late
  (expired-lease) and duplicate results are rejected
  (``Distributer.cs:404,447-456``)
- expired leases make the tile grantable again, both lazily and via a
  periodic sweep (``DistributerWorkload.cs:116-120``, ``Distributer.cs:153-160``)
- completion is keyed on ``(level, i, j)`` only, fixing the reference's
  broken hash/equality contract so resume dedup is exact, not best-effort
  (survey caveat on ``DistributerWorkload.cs:50-51``).

Design difference (the TPU build's hot-path fix): the reference rescans the
whole O(sum level^2) grid per request; this scheduler keeps an advancing
cursor over the grid plus a retry queue fed by lease expiry, making grants
O(1) amortized.  A batched acquire leases k tiles in one call — the server
-side half of batched dispatch that keeps a device mesh fed.
"""

from __future__ import annotations

from collections import deque
from dataclasses import dataclass
from typing import TYPE_CHECKING, Callable, Optional, Sequence

from distributedmandelbrot_tpu.coordinator.clock import Clock, MonotonicClock
from distributedmandelbrot_tpu.core.workload import LevelSetting, Workload
from distributedmandelbrot_tpu.net.protocol import DEFAULT_LEASE_TIMEOUT
from distributedmandelbrot_tpu.obs import events as obs_events
from distributedmandelbrot_tpu.obs import flight
from distributedmandelbrot_tpu.obs import names as obs_names

if TYPE_CHECKING:
    from distributedmandelbrot_tpu.obs.metrics import Registry
    from distributedmandelbrot_tpu.obs.trace import TraceLog

Key = tuple[int, int, int]


@dataclass
class Lease:
    workload: Workload
    expires_at: float

    def expired(self, now: float) -> bool:
        return now >= self.expires_at


class TileScheduler:
    """Pure scheduling logic — no I/O, no real time."""

    def __init__(self, level_settings: Sequence[LevelSetting], *,
                 completed: Optional[set[Key]] = None,
                 lease_timeout: float = DEFAULT_LEASE_TIMEOUT,
                 clock: Optional[Clock] = None,
                 registry: Optional["Registry"] = None,
                 trace: Optional["TraceLog"] = None,
                 owns: Optional[Callable[[Key], bool]] = None) -> None:
        if not level_settings:
            raise ValueError("at least one level setting required")
        seen_levels: set[int] = set()
        for s in level_settings:
            if s.level in seen_levels:
                raise ValueError(f"duplicate level {s.level}")
            seen_levels.add(s.level)
        self._levels = seen_levels
        self.level_settings = tuple(level_settings)
        self.lease_timeout = lease_timeout
        self.clock = clock if clock is not None else MonotonicClock()
        self._completed: set[Key] = set(completed or ())
        # Completion counter restricted to the configured grid: the resume
        # set may carry keys from levels this run does not render (index
        # replay keeps every level ever computed), so len(_completed) alone
        # cannot answer is_complete().  Counting membership once here and
        # maintaining the integer on every completion/reopen keeps
        # is_complete() O(1) — the stats loop and embedders call it per
        # tick, and a full-grid rescan is O(sum level^2) at level-1000
        # scale (the rescan cost this scheduler was built to avoid,
        # Distributer.cs:335-353).
        # Keyspace filter for a sharded control plane: a ring slice's
        # ``owns`` restricts the frontier to this coordinator's keys.
        # None (the default) is the unsharded whole-grid scheduler.
        # The owned total is enumerated once up front so is_complete()
        # and the frontier gauge stay O(1) — the same trade the
        # _remaining counter already makes for resume sets.
        self._owns = owns
        if owns is None:
            self._owned_tiles = self.total_tiles
        else:
            self._owned_tiles = sum(
                1 for s in self.level_settings
                for i in range(s.level) for j in range(s.level)
                if owns((s.level, i, j)))
        self._remaining = self._owned_tiles - sum(
            1 for k in self._completed if self._counts(k))
        self._leases: dict[Key, Lease] = {}
        self._claims: dict[Key, tuple[int, Lease]] = {}
        self._claim_seq = 0  # claim identity; see claim()
        self._retry: deque[Workload] = deque()
        # The frontier cursor is a flat position into the grid enumeration
        # (settings in order, index_real outer, index_imag inner) rather
        # than a live generator, so a checkpoint can record it as one
        # integer and a restore resumes the frontier exactly where the
        # crashed coordinator left it (snapshot_state/restore_state).
        self._cursor_pos = 0
        self._cursor_done = False
        # Passive telemetry hooks — the scheduler stays pure logic (no
        # I/O, no real time); both default to None and cost nothing then.
        self._registry = registry
        self._trace = trace

    def _record(self, event: str, key: Key) -> None:
        if self._trace is not None:
            self._trace.record(event, key)

    def _count_requeue(self, key: Key, *, expired: bool = False) -> None:
        if expired:
            self._record("lease_expired", key)
            flight.note(obs_events.SCHED_EXPIRE, key=key)
            if self._registry is not None:
                self._registry.inc(obs_names.COORD_LEASES_EXPIRED)
        self._record("requeued", key)
        flight.note(obs_events.SCHED_REQUEUE, key=key)
        if self._registry is not None:
            self._registry.inc(obs_names.COORD_REQUEUES)

    # -- state inspection -------------------------------------------------

    @property
    def total_tiles(self) -> int:
        return sum(s.tile_count for s in self.level_settings)

    @property
    def owned_tiles(self) -> int:
        """Tiles of the configured grid this scheduler may grant (the
        whole grid unless a ring slice's ``owns`` filter restricts it)."""
        return self._owned_tiles

    @property
    def completed_count(self) -> int:
        """Completed tiles of the CONFIGURED (and owned) grid (resume
        sets may carry keys from other levels or other shards' slices;
        those are excluded so stats can never report more tiles
        complete than this scheduler grants)."""
        return self._owned_tiles - self._remaining

    @property
    def outstanding_leases(self) -> int:
        now = self.clock.now()
        return sum(1 for l in self._leases.values() if not l.expired(now))

    @property
    def frontier_depth(self) -> int:
        """Tiles still to grant: not completed and not under a lease or
        claim.  O(1) from maintained integers (the exporter's frontier
        gauge reads this per scrape); expired-but-unswept leases make it
        a slight undercount until the next sweep, which is the honest
        view of what a worker asking right now would be offered."""
        return max(0,
                   self._remaining - len(self._leases) - len(self._claims))

    def is_complete(self) -> bool:
        """All tiles of all configured levels are done (O(1))."""
        return self._remaining == 0

    def _in_grid(self, key: Key) -> bool:
        level, i, j = key
        return level in self._levels and 0 <= i < level and 0 <= j < level

    def _counts(self, key: Key) -> bool:
        """Does ``key`` count toward _remaining?  In the configured grid
        AND in this scheduler's owned slice — a foreign shard's key must
        never move the completion counter it was not counted into."""
        return self._in_grid(key) and (self._owns is None
                                       or self._owns(key))

    # -- grant path -------------------------------------------------------

    def _workload_at(self, pos: int) -> Optional[Workload]:
        """Grid workload at flat cursor position ``pos`` (grant order:
        settings in sequence, ``index_real`` outer, ``index_imag`` inner,
        ``Distributer.cs:338-340``); None past the end of the grid."""
        for s in self.level_settings:
            if pos < s.tile_count:
                return Workload(s.level, s.max_iter, pos // s.level,
                                pos % s.level)
            pos -= s.tile_count
        return None

    def _grantable(self, w: Workload, now: float) -> bool:
        if self._owns is not None and not self._owns(w.key):
            return False  # another shard's key (cursor walks the grid)
        if w.key in self._completed:
            return False
        claim = self._claims.get(w.key)
        if claim is not None and not claim[1].expired(now):
            return False  # a result for this tile is mid-upload
        lease = self._leases.get(w.key)
        return lease is None or lease.expired(now)

    def _next_needed(self, now: float) -> Optional[Workload]:
        while self._retry:
            w = self._retry.popleft()
            if self._grantable(w, now):
                return w
        while not self._cursor_done:
            w = self._workload_at(self._cursor_pos)
            if w is None:
                self._cursor_done = True
                break
            self._cursor_pos += 1
            if self._grantable(w, now):
                return w
        return None

    def acquire(self) -> Optional[Workload]:
        """Grant the next needed tile and lease it; None if none available.

        None does not mean the run is finished — tiles under unexpired
        leases may yet expire and become grantable (poll again later),
        exactly as in the reference's pull loop.
        """
        now = self.clock.now()
        w = self._next_needed(now)
        if w is None:
            # Lazy expiry, matching the reference's per-request re-check
            # (Distributer.cs:317-330): when the frontier is empty, requeue
            # any expired leases/claims right now instead of making the
            # worker wait for the periodic sweep.  O(|leases|), and only on
            # the otherwise-idle path.
            if self.sweep():
                w = self._next_needed(now)
            if w is None:
                return None
        self._record("scheduled", w.key)
        self._leases[w.key] = Lease(w, now + self.lease_timeout)
        flight.note(obs_events.SCHED_GRANT, key=w.key)
        return w

    def acquire_batch(self, max_count: int) -> list[Workload]:
        """Lease up to ``max_count`` tiles in one call (batched dispatch)."""
        out: list[Workload] = []
        while len(out) < max_count:
            w = self.acquire()
            if w is None:
                break
            out.append(w)
        return out

    # -- ingest path ------------------------------------------------------

    def can_accept(self, w: Workload) -> bool:
        """A result is acceptable iff an unexpired matching lease exists."""
        lease = self._leases.get(w.key)
        return (lease is not None and not lease.expired(self.clock.now())
                and lease.workload.matches(w))

    def claim(self, w: Workload) -> Optional[int]:
        """Atomically consume the matching lease at accept time; returns an
        opaque claim token, or None if the result is not acceptable.

        The reference matches-and-removes the lease when the 16-byte echo
        arrives, *before* the payload (``Distributer.cs:404``); doing the
        same here closes the window where a second worker's submission for
        the same tile could match the lease while the first payload is
        still in flight.  The claim keeps the lease's expiry: a payload
        that dawdles past it is dropped (`finish_claim`), and the sweep
        requeues expired claims just like expired leases.

        The token carries the claim's identity: if this claim expires
        mid-upload and the tile is re-leased and re-claimed by another
        submission, the dawdler's late ``finish_claim``/``release_claim``
        is a no-op instead of consuming the live claim.
        """
        if not self.can_accept(w):
            return None
        self._claim_seq += 1
        self._claims[w.key] = (self._claim_seq, self._leases.pop(w.key))
        flight.note(obs_events.SCHED_CLAIM, key=w.key,
                    lease=self._claim_seq)
        return self._claim_seq

    def finish_claim(self, w: Workload, token: int) -> bool:
        """Record completion after the claimed result's payload landed."""
        entry = self._claims.get(w.key)
        if entry is None or entry[0] != token:
            return False  # claim expired and was swept / superseded
        del self._claims[w.key]
        if entry[1].expired(self.clock.now()):
            self._retry.append(entry[1].workload)
            self._count_requeue(w.key, expired=True)
            return False
        if w.key not in self._completed:
            self._completed.add(w.key)
            if self._counts(w.key):
                # Only owned configured-grid tiles count toward
                # is_complete(); a foreign key slipping through the claim
                # path must not drive _remaining negative and end the run
                # early.
                self._remaining -= 1
        flight.note(obs_events.SCHED_ACCEPT, key=w.key, lease=token)
        return True

    def release_claim(self, w: Workload, token: int) -> None:
        """Abort a claim (payload never arrived); tile grantable again."""
        entry = self._claims.get(w.key)
        if entry is None or entry[0] != token:
            return  # superseded; nothing to release
        del self._claims[w.key]
        flight.note(obs_events.SCHED_RELEASE, key=w.key, lease=token)
        if w.key not in self._completed:
            self._retry.append(entry[1].workload)
            self._count_requeue(w.key)

    def complete(self, w: Workload) -> bool:
        """Record a completed tile; returns False for stale/unknown results.

        Single-step composite of :meth:`claim` + :meth:`finish_claim` for
        callers with no payload phase (tests, embedders).
        """
        token = self.claim(w)
        return token is not None and self.finish_claim(w, token)

    def prioritize(self, w: Workload) -> bool:
        """Move a tile to the front of the grant order (compute-on-read).

        Returns False for tiles this run cannot produce (out of grid) and
        for tiles already completed (the caller should read the store).
        Returns True when the tile is either queued at the frontier head or
        already in flight under an unexpired lease/claim — in both cases a
        result is expected, so the caller may await its arrival.

        A duplicate in the retry queue is harmless: grants re-check
        ``_grantable`` at pop time, so stale entries are skipped.
        """
        if not self._counts(w.key):
            return False
        if w.key in self._completed:
            return False
        if self._grantable(w, self.clock.now()):
            self._retry.appendleft(w)
            flight.note(obs_events.SCHED_PRIORITIZE, key=w.key)
        return True

    def refine(self, w: Workload) -> bool:
        """Re-grant a tile at a different depth (progressive refinement).

        A session's first paint completes the tile's 3-tuple key with a
        cheap low-``max_iter`` workload; serving full quality means
        granting the same key again at full depth.  Completion is keyed
        on the 3-tuple, so this un-completes the tile (if completed) and
        queues ``w`` — which carries the target ``max_iter`` — at the
        frontier head.  Returns False for out-of-grid/out-of-slice keys;
        True means a grant at ``w``'s depth is queued or already in
        flight, so the caller may await the deep save.
        """
        if not self._counts(w.key):
            return False
        if w.key in self._completed:
            self._completed.discard(w.key)
            self._remaining += 1
        flight.note(obs_events.SCHED_REFINE, key=w.key,
                    max_iter=w.max_iter)
        if self._grantable(w, self.clock.now()):
            self._retry.appendleft(w)
        return True

    def reopen(self, w: Workload) -> None:
        """Un-complete a tile whose persistence failed so it is granted again.

        Ingest marks a tile complete before its asynchronous save lands; if
        the save errors, the result's bytes are gone and the tile must go
        back in the frontier or the run would finish with a silent hole.
        """
        if w.key in self._completed and self._counts(w.key):
            # Out-of-grid (and out-of-slice) keys stay in _completed and
            # never enter the frontier: requeueing one would let it be
            # granted and re-completed, corrupting the _remaining
            # counter for tiles this scheduler doesn't grant.
            self._completed.discard(w.key)
            self._remaining += 1
            self._retry.append(w)
            flight.note(obs_events.SCHED_REOPEN, key=w.key)
            self._count_requeue(w.key)

    # -- checkpoint / restore ---------------------------------------------

    def snapshot_state(self, *, exclude: Optional[set[Key]] = None) -> dict:
        """Checkpointable view of the scheduler (coordinator/recovery.py).

        Plain Python structures only — serialization (and the index
        offset the completed set pairs with) is the recovery module's
        business.  ``exclude`` removes keys whose persistence is still
        in flight: a tile completed in memory but without a durable
        index entry must not be checkpointed as done, or a crash before
        its save lands would leave a hole no replay can fill.  Lease
        expiries are captured as *remaining* TTLs against this clock, so
        a restore under a different clock origin (a new process) grants
        workers the time they actually had left.  Claims are folded into
        the lease list: their upload connections die with the process,
        and the worker's retry needs a live lease to land against.
        """
        now = self.clock.now()
        completed = set(self._completed)
        retry = list(self._retry)
        if exclude:
            completed -= exclude
            # An excluded completed tile must also be re-grantable after a
            # restore: if the crash beats its save, no index entry ever
            # appears, its lease is gone (consumed at accept), and the
            # cursor is past it — without a retry entry it would never be
            # granted again and the run could not finish.  restore_state
            # filters retry against the final completed set, so if the
            # save DID land (suffix replay finds it) the entry is dropped.
            max_iters = {s.level: s.max_iter for s in self.level_settings}
            for key in sorted(exclude):
                if key in self._completed and self._counts(key):
                    level, i, j = key
                    retry.append(Workload(level, max_iters[level], i, j))
        leases: list[tuple[Workload, float]] = []
        for lease in self._leases.values():
            leases.append((lease.workload, lease.expires_at - now))
        for _, lease in self._claims.values():
            leases.append((lease.workload, lease.expires_at - now))
        return {
            "cursor_pos": self._cursor_pos,
            "cursor_done": self._cursor_done,
            "completed": completed,
            "retry": retry,
            "leases": leases,
        }

    def restore_state(self, *, cursor_pos: int, cursor_done: bool,
                      retry: Sequence[Workload],
                      leases: Sequence[tuple[Workload, float]]) -> int:
        """Adopt a checkpointed frontier; returns the leases rebuilt.

        The completed set is NOT restored here — the coordinator seeds
        it through the constructor after merging the checkpoint's set
        with the index-suffix replay, and this method filters against
        it: a tile that completed after the checkpoint must drop out of
        the restored retry queue and lease table.  A lease whose
        remaining TTL ran out while the coordinator was down goes
        straight to the retry queue (grantable now) instead of waiting
        for a sweep to notice.
        """
        now = self.clock.now()
        self._cursor_pos = cursor_pos
        self._cursor_done = cursor_done
        self._retry = deque(w for w in retry
                            if w.key not in self._completed)
        rebuilt = 0
        for w, remaining in leases:
            if w.key in self._completed or w.key in self._leases:
                continue
            if remaining > 0:
                self._leases[w.key] = Lease(w, now + remaining)
                rebuilt += 1
            else:
                self._retry.append(w)
                self._count_requeue(w.key, expired=True)
        flight.note(obs_events.SCHED_RESTORE, leases=rebuilt,
                    retry=len(self._retry))
        return rebuilt

    # -- maintenance ------------------------------------------------------

    def sweep(self) -> int:
        """Drop expired leases/claims and requeue their tiles."""
        now = self.clock.now()
        swept = 0
        expired = [k for k, l in self._leases.items() if l.expired(now)]
        for key in expired:
            lease = self._leases.pop(key)
            if key not in self._completed:
                self._retry.append(lease.workload)
                self._count_requeue(key, expired=True)
        swept += len(expired)
        expired = [k for k, (_, l) in self._claims.items() if l.expired(now)]
        for key in expired:
            _, lease = self._claims.pop(key)
            if key not in self._completed:
                self._retry.append(lease.workload)
                self._count_requeue(key, expired=True)
        swept += len(expired)
        return swept
