"""Injectable clocks.

The reference times leases against a ``Stopwatch`` (``Distributer.cs:51-52``);
making the clock injectable turns every scheduler behavior — lease expiry,
redistribution, stale-result rejection — into pure logic testable over
virtual time.
"""

from __future__ import annotations

import time
from typing import Protocol


class Clock(Protocol):
    def now(self) -> float:
        """Monotonic seconds."""
        ...


class MonotonicClock:
    def now(self) -> float:
        return time.monotonic()


class ManualClock:
    """Test clock advanced explicitly."""

    def __init__(self, start: float = 0.0) -> None:
        self._now = start

    def now(self) -> float:
        return self._now

    def advance(self, seconds: float) -> None:
        if seconds < 0:
            raise ValueError("time only moves forward")
        self._now += seconds
