"""Run a Coordinator on a background thread — programmatic embedding.

The process-level entry point is the CLI (``dmtpu coordinator``); this is
the in-process form used by the benchmark farm loop and the test suite:
a coordinator on ephemeral loopback ports with a thread-owned asyncio
loop, driven from synchronous code through the worker/viewer clients.
"""

from __future__ import annotations

import asyncio
import threading
import time

from distributedmandelbrot_tpu.coordinator.app import Coordinator


_UNSET = object()  # "use Coordinator's default" — None must mean "disable"


class EmbeddedCoordinator:
    """Context manager owning a Coordinator in a daemon thread."""

    def __init__(self, data_dir_parent: str, level_settings, *,
                 lease_timeout: float = 3600.0, sweep_period: float = 300.0,
                 read_timeout: float | None = _UNSET, clock=None,
                 gateway: bool = True, exporter: bool = True,
                 checkpoint_period: float = 0.0,
                 **gateway_kwargs) -> None:
        self._ready = threading.Event()
        self._stop: asyncio.Event | None = None
        self._loop: asyncio.AbstractEventLoop | None = None
        self.coordinator: Coordinator | None = None
        self._kwargs = dict(data_dir_parent=data_dir_parent,
                            host="127.0.0.1", distributer_port=0,
                            dataserver_port=0, lease_timeout=lease_timeout,
                            sweep_period=sweep_period, clock=clock,
                            checkpoint_period=checkpoint_period)
        # The embedded form serves tests and benches, so the gateway is on
        # by default (ephemeral port).  gateway_kwargs passes the admission
        # knobs straight through (gateway_max_queue_depth, gateway_rate,
        # gateway_burst, gateway_cache_tiles, gateway_render_tiles,
        # ondemand_deadline).
        if gateway:
            self._kwargs["gateway_port"] = 0
        # The metrics exporter rides along the same way: on by default at
        # an ephemeral loopback port, so tests and benches can scrape
        # /metrics and /varz without reserving a well-known port.
        if exporter:
            self._kwargs["exporter_port"] = 0
        self._kwargs.update(gateway_kwargs)
        if read_timeout is not _UNSET:
            self._kwargs["read_timeout"] = read_timeout
        self._level_settings = level_settings
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._error: BaseException | None = None

    def _run(self) -> None:
        try:
            asyncio.run(self._main())
        except BaseException as e:
            self._error = e
            self._ready.set()

    async def _main(self) -> None:
        self.coordinator = Coordinator(self._level_settings, **self._kwargs)
        self._loop = asyncio.get_running_loop()
        self._stop = asyncio.Event()
        await self.coordinator.start()
        self._ready.set()
        await self._stop.wait()
        await self.coordinator.stop()

    def __enter__(self) -> "EmbeddedCoordinator":
        self._thread.start()
        if not self._ready.wait(timeout=30):
            raise TimeoutError("coordinator failed to start")
        if self._error is not None:
            raise self._error
        return self

    def __exit__(self, *exc) -> None:
        if self._loop is not None and self._stop is not None:
            self._loop.call_soon_threadsafe(self._stop.set)
        self._thread.join(timeout=30)

    @property
    def distributer_port(self) -> int:
        return self.coordinator.distributer_port

    @property
    def dataserver_port(self) -> int:
        return self.coordinator.dataserver_port

    @property
    def gateway_port(self) -> int | None:
        return self.coordinator.gateway_port

    @property
    def exporter_port(self) -> int | None:
        return self.coordinator.exporter_port

    @property
    def registry(self):
        return self.coordinator.registry

    @property
    def trace(self):
        return self.coordinator.trace

    @property
    def spans(self):
        return self.coordinator.spans

    @property
    def scheduler(self):
        return self.coordinator.scheduler

    @property
    def counters(self):
        return self.coordinator.counters

    @property
    def store(self):
        return self.coordinator.store

    def wait_saves_settled(self, expected_accepted: int = 1,
                           timeout: float = 30.0) -> None:
        """Block until >= ``expected_accepted`` results are ingested AND
        their async chunk saves have landed.  (Without an expected count
        there is a race: the client's upload may still be in the server's
        socket buffer when this is called.)"""
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            accepted = self.coordinator.counters.get("results_accepted")
            saved = (self.coordinator.counters.get("chunks_saved")
                     + self.coordinator.counters.get("save_errors"))
            if accepted >= expected_accepted and saved >= accepted:
                return
            time.sleep(0.02)
        raise TimeoutError("chunk saves did not settle")
