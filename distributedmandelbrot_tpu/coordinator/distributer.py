"""Asyncio job-distribution server (the write-side coordinator service).

Wire-compatible with the reference Distributer (``Distributer.cs:207-458``)
— same purpose/status codes, same 16-byte workload frames, same raw
16 MiB result payload — plus the batched-dispatch extension
(:mod:`distributedmandelbrot_tpu.net.protocol`).

Differences by design:

- single asyncio event loop instead of a blocking accept loop + threads;
  chunk persistence runs in a thread pool so ingest never blocks the loop
  (the reference saves on a fire-and-forget Task, ``Distributer.cs:436-442``)
- every receive is exact-length (fixes the 16 MiB short-read bug,
  ``Distributer.cs:415-416``)
- a connection may carry any number of messages back-to-back (the
  reference is connection-per-message; clients that close after one
  message remain fully supported — EOF just ends the session)
- the lease sweep is an asyncio task with the same default 5-minute period
  (``Distributer.cs:24``).
"""

from __future__ import annotations

import asyncio
import logging
import time
from typing import Optional

import numpy as np

from distributedmandelbrot_tpu.codecs.rle import RleCodec
from distributedmandelbrot_tpu.coordinator.scheduler import (Key,
                                                             TileScheduler)
from distributedmandelbrot_tpu.core.chunk import Chunk
from distributedmandelbrot_tpu.core.geometry import CHUNK_PIXELS
from distributedmandelbrot_tpu.core.workload import (WORKLOAD_WIRE_SIZE,
                                                     Workload)
from distributedmandelbrot_tpu.net import framing
from distributedmandelbrot_tpu.net import protocol as proto
from distributedmandelbrot_tpu.obs import events as obs_events
from distributedmandelbrot_tpu.obs import flight
from distributedmandelbrot_tpu.obs import names as obs_names
from distributedmandelbrot_tpu.obs.spans import Span, SpanStore
from distributedmandelbrot_tpu.obs.trace import TraceLog
from distributedmandelbrot_tpu.storage.store import ChunkStore
from distributedmandelbrot_tpu.utils import faults
from distributedmandelbrot_tpu.utils.metrics import Counters

logger = logging.getLogger("dmtpu.distributer")

MAX_BATCH = 4096
# Per-report ceiling on sync samples / span records: a worker drains its
# recorder (8 K ring) after every upload, so an honest report is far
# smaller; a count beyond this is a corrupt or hostile frame.
MAX_SPANS = 65536

# Wire stage code (net/protocol.py SPAN_STAGE_*) -> stage name.
_STAGE_NAMES = {
    proto.SPAN_STAGE_PREFETCH: obs_names.SPAN_PREFETCH,
    proto.SPAN_STAGE_DISPATCH: obs_names.SPAN_DISPATCH,
    proto.SPAN_STAGE_COMPUTE: obs_names.SPAN_COMPUTE,
    proto.SPAN_STAGE_D2H: obs_names.SPAN_D2H,
    proto.SPAN_STAGE_UPLOAD: obs_names.SPAN_UPLOAD,
}


def _peer_id(writer: asyncio.StreamWriter) -> Optional[str]:
    """Connection id for trace events — the per-worker key the skew
    summary groups on (a worker keeps one connection per exchange loop)."""
    peer = writer.get_extra_info("peername")
    if isinstance(peer, (tuple, list)) and len(peer) >= 2:
        return f"{peer[0]}:{peer[1]}"
    return str(peer) if peer else None


class Distributer:
    def __init__(self, scheduler: TileScheduler, store: ChunkStore, *,
                 host: str = "0.0.0.0",
                 port: int = proto.DEFAULT_DISTRIBUTER_PORT,
                 sweep_period: float = proto.DEFAULT_SWEEP_PERIOD,
                 read_timeout: Optional[float] = proto.DEFAULT_READ_TIMEOUT,
                 counters: Optional[Counters] = None,
                 trace: Optional[TraceLog] = None,
                 spans: Optional[SpanStore] = None,
                 accept_spans: bool = True,
                 accept_session: bool = True,
                 on_chunk_saved=None,
                 ring_slice=None) -> None:
        self.scheduler = scheduler
        self.store = store
        # One shard's view of the consistent-hash ring (control/ring.py
        # RingSlice, duck-typed to avoid an import cycle through the
        # control package).  None is the unsharded coordinator: the
        # SHARD capability is never offered and every key is ours.
        self.ring_slice = ring_slice
        self.host = host
        self.port = port
        self.sweep_period = sweep_period
        self.read_timeout = read_timeout
        self.counters = counters if counters is not None else Counters()
        self.registry = self.counters.registry
        self.trace = trace if trace is not None else TraceLog()
        self.spans = spans if spans is not None else SpanStore()
        # False makes this build behave like a legacy coordinator for the
        # 0x04 extension (unknown purpose byte -> drop the connection) —
        # the degradation path the worker tests drive.
        self.accept_spans = accept_spans
        # Same switch for the 0x05 session extension: False drops the
        # hello, which is what pushes a session-capable worker onto its
        # connection-per-exchange fallback.
        self.accept_session = accept_session
        self._rle = RleCodec()
        # Optional ``callback(key)`` fired on this event loop after a chunk
        # is durably persisted — the gateway's on-demand path hangs its
        # arrival notification here.
        self.on_chunk_saved = on_chunk_saved
        self._server: Optional[asyncio.Server] = None
        self._sweep_task: Optional[asyncio.Task] = None
        # Group-commit persistence: accepted tiles go through a bounded
        # queue to one drainer task, which coalesces whatever is backed
        # up into a single ``store.put_many`` flush per wake-up.  The
        # bound is backpressure — a store slower than ingest stalls the
        # uploading sessions instead of growing an unbounded backlog.
        self._persist_queue: Optional[asyncio.Queue] = None
        self._persist_task: Optional[asyncio.Task] = None
        self.persist_queue_depth = 256
        self.persist_flush_tiles = 64
        # Tiles accepted in the scheduler whose asynchronous save has not
        # landed yet.  The recovery manager excludes these from every
        # checkpoint: completed-in-memory without a durable index entry
        # must not be checkpointed as done (coordinator/recovery.py).
        self._pending_saves: set[Key] = set()

    async def _read(self, coro):
        """Apply the configured read deadline (reference: the toggleable
        socket receive timeout, Distributer.cs:17).  A client that stalls
        mid-frame raises TimeoutError and loses the connection instead of
        pinning a handler task (and its claim) until lease expiry."""
        if self.read_timeout is None:
            return await coro
        try:
            return await asyncio.wait_for(coro, self.read_timeout)
        except (TimeoutError, asyncio.TimeoutError):
            # asyncio.TimeoutError only aliases the builtin from 3.11 on;
            # catching both keeps 3.10 (pyproject's floor) correct.
            self.counters.inc("read_timeouts")
            raise

    # -- lifecycle --------------------------------------------------------

    async def start(self) -> None:
        self._server = await asyncio.start_server(
            self._handle_connection, self.host, self.port)
        self.port = self._server.sockets[0].getsockname()[1]
        self._sweep_task = asyncio.create_task(self._sweep_loop())
        self._start_persist_loop()
        self.registry.gauge(
            obs_names.GAUGE_PERSIST_QUEUE_DEPTH,
            fn=lambda: self._persist_queue.qsize()
            if self._persist_queue is not None else 0)
        logger.info("distributer listening on %s:%d", self.host, self.port)

    def _start_persist_loop(self) -> None:
        self._persist_queue = asyncio.Queue(maxsize=self.persist_queue_depth)
        self._persist_task = asyncio.create_task(self._persist_loop())

    async def stop(self) -> None:
        if self._sweep_task is not None:
            self._sweep_task.cancel()
            try:
                await self._sweep_task
            except asyncio.CancelledError:
                pass
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()
        if self._persist_task is not None:
            # Flush: the sentinel trails every enqueued tile, so awaiting
            # the drainer means every accepted result is durable (or
            # reopened) before stop() returns.
            await self._persist_queue.put(None)
            try:
                await self._persist_task
            except asyncio.CancelledError:
                pass
            self._persist_task = None

    async def _sweep_loop(self) -> None:
        while True:
            await asyncio.sleep(self.sweep_period)
            swept = self.scheduler.sweep()
            if swept:
                logger.info("lease sweep requeued %d tiles", swept)

    # -- connection handling ----------------------------------------------

    async def _handle_connection(self, reader: asyncio.StreamReader,
                                 writer: asyncio.StreamWriter) -> None:
        peer = writer.get_extra_info("peername")
        # Counted at accept so the session e2e can assert the steady
        # state really is one connection per worker lane.
        self.counters.inc(obs_names.COORD_CONNECTIONS_ACCEPTED)
        try:
            while True:
                try:
                    # Idle deadline too: a silent client is disconnected
                    # (it re-dials) instead of pinning this task forever.
                    purpose = await framing.read_byte(reader) \
                        if self.read_timeout is None else \
                        await asyncio.wait_for(framing.read_byte(reader),
                                               self.read_timeout)
                except (ConnectionError, TimeoutError,
                        asyncio.TimeoutError):
                    break  # clean EOF / idle close between messages
                if purpose == proto.PURPOSE_REQUEST:
                    await self._handle_request(writer)
                elif purpose == proto.PURPOSE_RESPONSE:
                    await self._handle_response(reader, writer)
                elif purpose == proto.PURPOSE_BATCH_REQUEST:
                    await self._handle_batch_request(reader, writer)
                elif purpose == proto.PURPOSE_BATCH_RESPONSE:
                    await self._handle_batch_response(reader, writer)
                elif purpose == proto.PURPOSE_SPANS and self.accept_spans:
                    await self._handle_spans(reader, writer)
                elif purpose == proto.PURPOSE_SESSION and self.accept_session:
                    await self._handle_session(reader, writer)
                    break  # a session consumes the connection; EOF follows
                else:
                    logger.error("unknown purpose byte %#x from %s",
                                 purpose, peer)
                    self.counters.inc(obs_names.COORD_FRAMES_REJECTED)
                    flight.note(obs_events.SESS_REJECT_FRAME, peer=peer,
                                purpose=purpose)
                    break
                await writer.drain()
        except (ConnectionError, TimeoutError, asyncio.TimeoutError,
                asyncio.CancelledError):
            pass  # per-connection failures never take down the accept loop
        except framing.ProtocolError as e:
            # Malformed or hostile frame: drop the connection, leave a
            # trail, keep the accept loop alive.
            self.counters.inc(obs_names.COORD_FRAMES_REJECTED)
            flight.note(obs_events.SESS_REJECT_FRAME, peer=peer,
                        error=str(e)[:120])
            logger.error("dropping %s: %s", peer, e)
        except Exception:
            logger.exception("error serving %s", peer)
        finally:
            writer.close()
            try:
                await writer.wait_closed()
            except (ConnectionError, OSError):
                pass

    async def _handle_request(self, writer: asyncio.StreamWriter) -> None:
        with self.registry.timed(obs_names.HIST_GRANT_SECONDS):
            w = self.scheduler.acquire()
            if w is None:
                framing.write_byte(writer, proto.WORKLOAD_NOT_AVAILABLE)
                self.counters.inc("requests_denied")
            else:
                framing.write_byte(writer, proto.WORKLOAD_AVAILABLE)
                writer.write(w.to_wire())
                self.counters.inc("workloads_granted")
                self.trace.record("granted", w.key, worker=_peer_id(writer))
                # Grant timestamp for NTP-style clock alignment: paired
                # with the worker's request/receive clock samples when a
                # span report for this key arrives (obs/spans.py).
                self.spans.note_grant(w.key, time.monotonic())
                logger.info("granted %s", w)

    async def _handle_batch_request(self, reader: asyncio.StreamReader,
                                    writer: asyncio.StreamWriter) -> None:
        count = await self._read(framing.read_u32(reader))
        with self.registry.timed(obs_names.HIST_GRANT_SECONDS):
            grants = self.scheduler.acquire_batch(min(count, MAX_BATCH))
            if not grants:
                framing.write_byte(writer, proto.WORKLOAD_NOT_AVAILABLE)
                self.counters.inc("requests_denied")
                return
            framing.write_byte(writer, proto.WORKLOAD_AVAILABLE)
            framing.write_u32(writer, len(grants))
            peer = _peer_id(writer)
            t_grant = time.monotonic()
            for w in grants:
                writer.write(w.to_wire())
                self.trace.record("granted", w.key, worker=peer)
                self.spans.note_grant(w.key, t_grant)
            self.counters.inc("workloads_granted", len(grants))
            logger.info("granted batch of %d tiles", len(grants))

    async def _handle_response(self, reader: asyncio.StreamReader,
                               writer: asyncio.StreamWriter) -> None:
        await self._ingest_one(reader, writer)

    async def _handle_spans(self, reader: asyncio.StreamReader,
                            writer: asyncio.StreamWriter) -> None:
        """Ingest one worker span report (PURPOSE_SPANS, 0x04)."""
        worker_id, sync_data, span_data = await self._read_span_report(reader)
        self._ingest_span_report(worker_id, sync_data, span_data)
        framing.write_byte(writer, proto.SPANS_ACCEPT)

    async def _read_span_report(self, reader: asyncio.StreamReader,
                                declared: Optional[int] = None):
        """Read one span report body (shared by the 0x04 exchange and the
        session's FRAME_SPANS, which also cross-checks the frame header's
        declared length against the report's own counts)."""
        hdr = await self._read(
            framing.read_exact(reader, proto.SPANS_HEADER_WIRE_SIZE))
        worker_id, n_sync, n_spans = proto.SPANS_HEADER.unpack(hdr)
        n_sync = proto.validate_count(
            n_sync, MAX_SPANS, f"sync count from worker {worker_id:016x}")
        n_spans = proto.validate_count(
            n_spans, MAX_SPANS, f"span count from worker {worker_id:016x}")
        if declared is not None and declared != (
                proto.SPANS_HEADER_WIRE_SIZE
                + n_sync * proto.SPAN_SYNC_WIRE_SIZE
                + n_spans * proto.SPAN_RECORD_WIRE_SIZE):
            raise framing.ProtocolError(
                f"span frame length {declared} disagrees with its counts")
        sync_data = await self._read(framing.read_exact(
            reader, n_sync * proto.SPAN_SYNC_WIRE_SIZE))
        span_data = await self._read(framing.read_exact(
            reader, n_spans * proto.SPAN_RECORD_WIRE_SIZE))
        return worker_id, sync_data, span_data

    def _ingest_span_report(self, worker_id: int, sync_data: bytes,
                            span_data: bytes) -> None:
        for level, ir, ii, t_req, t_recv in \
                proto.SPAN_SYNC.iter_unpack(sync_data):
            c_grant = self.spans.grant_time((level, ir, ii))
            if c_grant is None:
                # Grant fell out of the bounded map (or predates this
                # process); the sample cannot be paired.
                self.counters.inc(obs_names.COORD_SPANS_UNALIGNED)
                continue
            self.spans.add_sync(worker_id, c_grant, t_req, t_recv)
            self.counters.inc(obs_names.COORD_SPAN_SYNC_SAMPLES)
        records = []
        for level, ir, ii, stage, device, seq, t0, t1 in \
                proto.SPAN_RECORD.iter_unpack(span_data):
            name = _STAGE_NAMES.get(stage)
            if name is None:
                continue  # future stage code from a newer worker; skip
            records.append(Span(name, (level, ir, ii), t0, t1,
                                device, seq))
        self.counters.inc(obs_names.COORD_SPANS_INGESTED,
                          self.spans.ingest(worker_id, records))
        self.counters.inc(obs_names.COORD_SPAN_REPORTS)

    # -- persistent session (PURPOSE_SESSION, 0x05) ------------------------

    async def _handle_session(self, reader: asyncio.StreamReader,
                              writer: asyncio.StreamWriter) -> None:
        """Run one persistent multiplexed session until the peer hangs up.

        Hello first (echoing the negotiated capability subset), then a
        frame loop: lease requests, uploads (whose acks may piggyback
        fresh grants), span reports.  Client frames must arrive with
        strictly incrementing seqs; any violation or malformed frame
        raises ProtocolError, which drops the whole session.
        """
        hello = await self._read(
            framing.read_exact(reader, proto.SESSION_HELLO_WIRE_SIZE))
        (offered,) = proto.SESSION_HELLO.unpack(hello)
        # SHARD is only echoed by a ring-configured coordinator, so a
        # sharded worker dialing an unsharded one negotiates down to
        # treating it as the sole owner of the keyspace.
        acceptable = proto.SESSION_FLAG_RLE | proto.SESSION_FLAG_GRANTN
        if self.ring_slice is not None:
            acceptable |= proto.SESSION_FLAG_SHARD
        negotiated = offered & acceptable
        framing.write_byte(writer, proto.SESSION_ACCEPT)
        writer.write(proto.SESSION_HELLO.pack(negotiated))
        await writer.drain()
        self.counters.inc(obs_names.COORD_SESSIONS_OPENED)
        peer = _peer_id(writer)
        flight.note(obs_events.SESS_OPEN, peer=peer,
                    negotiated=negotiated)
        expected_seq = 0
        while True:
            try:
                hdr = await self._read(framing.read_exact(
                    reader, proto.SESSION_FRAME_WIRE_SIZE))
            except (ConnectionError, TimeoutError, asyncio.TimeoutError):
                return  # clean end of session (EOF or idle between frames)
            frame_type, seq, length = proto.SESSION_FRAME.unpack(hdr)
            proto.validate_session_seq(seq, expected_seq)
            expected_seq = (expected_seq + 1) & proto.MAX_SESSION_SEQ
            length = proto.validate_payload_length(length)
            self.counters.inc(obs_names.COORD_SESSION_FRAMES)
            if frame_type == proto.FRAME_LEASE_REQ:
                await self._session_lease(reader, writer, seq, length)
            elif frame_type == proto.FRAME_LEASE_REQN:
                await self._session_lease_reqn(reader, writer, seq, length,
                                               negotiated)
            elif frame_type == proto.FRAME_UPLOAD:
                await self._session_upload(reader, writer, seq, length,
                                           negotiated, peer)
            elif frame_type == proto.FRAME_SPANS:
                await self._session_spans(reader, length)
            elif frame_type == proto.FRAME_RING_REQ:
                await self._session_ring_req(reader, writer, seq, length,
                                             negotiated)
            else:
                raise framing.ProtocolError(
                    f"unknown session frame type "
                    f"{proto.frame_name(frame_type)}")
            await writer.drain()

    async def _session_lease(self, reader: asyncio.StreamReader,
                             writer: asyncio.StreamWriter,
                             seq: int, length: int) -> None:
        if length != 4:
            raise framing.ProtocolError(
                f"lease request frame length {length}, expected 4")
        count = proto.validate_count(
            await self._read(framing.read_u32(reader)), MAX_BATCH,
            "session lease count")
        with self.registry.timed(obs_names.HIST_GRANT_SECONDS):
            grants = self.scheduler.acquire_batch(count) if count else []
        if not grants:
            self.counters.inc("requests_denied")
        writer.write(proto.SESSION_FRAME.pack(
            proto.FRAME_LEASE_GRANT, seq,
            4 + len(grants) * WORKLOAD_WIRE_SIZE))
        self._write_grant_list(writer, grants, _peer_id(writer))

    async def _session_lease_reqn(self, reader: asyncio.StreamReader,
                                  writer: asyncio.StreamWriter,
                                  seq: int, length: int,
                                  negotiated: int) -> None:
        """Batched lease request: grant up to ``count`` tiles in one round
        trip, replied as groups no wider than the worker's fusion width so
        the pipeline's dispatch coalescer can hand each group straight to a
        megakernel launch without re-slicing."""
        if not negotiated & proto.SESSION_FLAG_GRANTN:
            raise framing.ProtocolError(
                "batched lease request on a session that did not "
                "negotiate it")
        if length != proto.LEASE_REQN_WIRE_SIZE:
            raise framing.ProtocolError(
                f"batched lease frame length {length}, expected "
                f"{proto.LEASE_REQN_WIRE_SIZE}")
        count, width = proto.LEASE_REQN.unpack(await self._read(
            framing.read_exact(reader, proto.LEASE_REQN_WIRE_SIZE)))
        count = proto.validate_count(count, MAX_BATCH, "batched lease count")
        if count == 0:
            raise framing.ProtocolError(
                "batched lease count 0 (a worker with no room must not ask)")
        width = proto.validate_count(width, count, "grant batch width")
        if width == 0:
            raise framing.ProtocolError("grant batch width 0")
        with self.registry.timed(obs_names.HIST_GRANT_SECONDS):
            grants = self.scheduler.acquire_batch(count)
        if not grants:
            # Empty drain probes are visible as requests_denied; counting
            # them as batches would skew grants-per-batch toward zero.
            self.counters.inc("requests_denied")
        else:
            # Counted BEFORE the reply hits the wire: a client thread can
            # otherwise read the grants and assert on the counter while
            # this coroutine is still a few statements from the inc.
            self.counters.inc(obs_names.COORD_GRANT_BATCHES)
            self.registry.observe(obs_names.HIST_COORD_GRANTS_PER_BATCH,
                                  float(len(grants)))
        batches = [grants[i:i + width] for i in range(0, len(grants), width)]
        writer.write(proto.SESSION_FRAME.pack(
            proto.FRAME_LEASE_GRANTN, seq,
            proto.LEASE_GRANTN_WIRE_SIZE + 4 * len(batches)
            + len(grants) * WORKLOAD_WIRE_SIZE))
        writer.write(proto.LEASE_GRANTN.pack(len(batches), len(grants)))
        peer = _peer_id(writer)
        for batch in batches:
            self._write_grant_list(writer, batch, peer)

    def _write_grant_list(self, writer: asyncio.StreamWriter, grants,
                          peer: Optional[str]) -> None:
        framing.write_u32(writer, len(grants))
        t_grant = time.monotonic()
        for w in grants:
            writer.write(w.to_wire())
            self.trace.record("granted", w.key, worker=peer)
            self.spans.note_grant(w.key, t_grant)
        if grants:
            self.counters.inc("workloads_granted", len(grants))

    async def _session_ring_req(self, reader: asyncio.StreamReader,
                                writer: asyncio.StreamWriter,
                                seq: int, length: int,
                                negotiated: int) -> None:
        """Answer a worker's ring query with this shard's slice identity.

        A stale client ring version is counted but still answered — the
        reply IS the correction; only a session that never negotiated
        sharding asking is a protocol violation."""
        if not negotiated & proto.SESSION_FLAG_SHARD:
            raise framing.ProtocolError(
                "ring request on a session that did not negotiate sharding")
        if length != proto.RING_REQ_WIRE_SIZE:
            raise framing.ProtocolError(
                f"ring request frame length {length}, expected "
                f"{proto.RING_REQ_WIRE_SIZE}")
        (client_version,) = proto.RING_REQ.unpack(await self._read(
            framing.read_exact(reader, proto.RING_REQ_WIRE_SIZE)))
        rs = self.ring_slice
        self.counters.inc(obs_names.COORD_SHARD_RING_REQS)
        if client_version != rs.version:
            self.counters.inc(obs_names.COORD_SHARD_RING_SKEW)
        writer.write(proto.SESSION_FRAME.pack(
            proto.FRAME_RING_INFO, seq, proto.RING_INFO_WIRE_SIZE))
        writer.write(proto.RING_INFO.pack(rs.version, rs.shard,
                                          rs.n_shards))

    def _write_redirect(self, writer: asyncio.StreamWriter, seq: int,
                        owner: int) -> None:
        """Redirect answer for a misrouted upload: the ack slot carries
        the authoritative shard instead of accept/reject."""
        writer.write(proto.SESSION_FRAME.pack(
            proto.FRAME_REDIRECT, seq, proto.REDIRECT_WIRE_SIZE))
        writer.write(proto.REDIRECT.pack(owner, self.ring_slice.version))
        self.counters.inc(obs_names.COORD_SHARD_REDIRECTS)

    def _write_upload_ack(self, writer: asyncio.StreamWriter, seq: int,
                          flag: int, want: int, peer: Optional[str]) -> None:
        """Accept/reject ack for one upload, piggybacking up to ``want``
        fresh grants — the steady-state replacement for a separate lease
        round trip."""
        if want:
            with self.registry.timed(obs_names.HIST_GRANT_SECONDS):
                grants = self.scheduler.acquire_batch(want)
        else:
            grants = []
        writer.write(proto.SESSION_FRAME.pack(
            proto.FRAME_UPLOAD_ACK, seq,
            1 + 4 + len(grants) * WORKLOAD_WIRE_SIZE))
        framing.write_byte(writer, flag)
        self._write_grant_list(writer, grants, peer)

    async def _session_upload(self, reader: asyncio.StreamReader,
                              writer: asyncio.StreamWriter, seq: int,
                              length: int, negotiated: int,
                              peer: Optional[str]) -> None:
        t_accept = time.monotonic()
        min_len = WORKLOAD_WIRE_SIZE + proto.UPLOAD_HEADER_WIRE_SIZE
        if length < min_len:
            raise framing.ProtocolError(
                f"upload frame length {length} below header size {min_len}")
        w = Workload.from_wire(await self._read(
            framing.read_exact(reader, WORKLOAD_WIRE_SIZE)))
        codec, want = proto.UPLOAD_HEADER.unpack(await self._read(
            framing.read_exact(reader, proto.UPLOAD_HEADER_WIRE_SIZE)))
        want = proto.validate_count(want, MAX_BATCH, "piggyback lease count")
        body_len = length - min_len
        if codec == proto.WIRE_CODEC_RAW:
            # An RLE body's length is data-dependent (bounded by the
            # already-validated frame length); a raw body is exact.
            if body_len != CHUNK_PIXELS:
                raise framing.ProtocolError(
                    f"raw upload body {body_len}, expected {CHUNK_PIXELS}")
        elif codec == proto.WIRE_CODEC_RLE:
            if not negotiated & proto.SESSION_FLAG_RLE:
                raise framing.ProtocolError(
                    "RLE upload on a session that did not negotiate it")
        else:
            raise framing.ProtocolError(f"unknown wire codec {codec:#x}")
        if self.ring_slice is not None and not self.ring_slice.owns(w.key):
            # Another shard's key (a worker holding a stale ring, or a
            # ring version rolled mid-flight): drain the body to keep
            # the frame stream in sync, then point at the owner.  Only
            # SHARD-negotiated sessions can legally carry foreign keys'
            # redirects, but a misroute on a down-negotiated session
            # still must not be accepted — reject it there instead.
            await self._read(framing.read_exact(reader, body_len))
            self.counters.inc(obs_names.COORD_SHARD_MISROUTES)
            if negotiated & proto.SESSION_FLAG_SHARD:
                owner = self.ring_slice.owner_of(w.key)
                logger.info("redirecting result for %s to shard %d", w,
                            owner)
                flight.note(obs_events.SESS_REDIRECT, key=w.key,
                            owner=owner, peer=peer)
                self._write_redirect(writer, seq, owner)
            else:
                self.counters.inc(obs_names.COORD_RESULTS_REJECTED)
                flight.note(obs_events.SESS_RESULT_REJECTED, key=w.key,
                            reason="misroute")
                logger.info("rejected result for %s (not this shard's "
                            "key)", w)
                self._write_upload_ack(writer, seq, proto.RESPONSE_REJECT,
                                       want, peer)
            return
        token = self.scheduler.claim(w)
        if token is None:
            # Stale or unknown lease: the body still has to be drained to
            # keep the frame stream in sync before the reject ack.
            await self._read(framing.read_exact(reader, body_len))
            self.counters.inc(obs_names.COORD_RESULTS_REJECTED)
            flight.note(obs_events.SESS_RESULT_REJECTED, key=w.key,
                        reason="stale_lease")
            logger.info("rejected result for %s (stale or unknown lease)", w)
            self._write_upload_ack(writer, seq, proto.RESPONSE_REJECT,
                                   want, peer)
            return
        try:
            body = await self._read(framing.read_exact(reader, body_len))
        except (ConnectionError, TimeoutError, asyncio.TimeoutError,
                framing.ProtocolError):
            self.scheduler.release_claim(w, token)
            self.counters.inc(obs_names.COORD_RESULTS_DROPPED)
            flight.note(obs_events.SESS_RESULT_DROPPED, key=w.key,
                        reason="upload_stalled")
            logger.info("dropped result for %s (session upload stalled "
                        "or connection lost)", w)
            raise
        if codec == proto.WIRE_CODEC_RLE:
            t0 = time.monotonic()
            try:
                # Decode off the loop: np.repeat of 16 Mi pixels is
                # milliseconds of pure CPU the other sessions shouldn't
                # stall behind.  The decoder itself rejects bombs — the
                # run counts must sum to exactly CHUNK_PIXELS before
                # anything is allocated at that size.
                pixels = await asyncio.to_thread(
                    self._rle.decode, body, CHUNK_PIXELS)
            except ValueError as e:
                self.scheduler.release_claim(w, token)
                self.counters.inc(obs_names.COORD_RESULTS_DROPPED)
                flight.note(obs_events.SESS_RESULT_DROPPED, key=w.key,
                            reason="bad_rle")
                raise framing.ProtocolError(
                    f"bad RLE body for {w}: {e}") from None
            self.registry.observe(obs_names.HIST_COORD_DECODE_SECONDS,
                                  time.monotonic() - t0)
            self.counters.inc(obs_names.WIRE_COMPRESSED_BYTES, body_len)
        else:
            pixels = np.frombuffer(body, dtype=np.uint8)
            self.counters.inc(obs_names.WIRE_RAW_BYTES, body_len)
        if not self.scheduler.finish_claim(w, token):
            self.counters.inc(obs_names.COORD_RESULTS_DROPPED)
            flight.note(obs_events.SESS_RESULT_DROPPED, key=w.key,
                        reason="expired_mid_upload")
            logger.info("dropped result for %s (lease expired mid-upload)", w)
            self._write_upload_ack(writer, seq, proto.RESPONSE_REJECT,
                                   want, peer)
            return
        self.counters.inc(obs_names.COORD_RESULTS_ACCEPTED)
        self.registry.observe(obs_names.HIST_ACCEPT_SECONDS,
                              time.monotonic() - t_accept)
        self.trace.record("result_received", w.key, worker=peer)
        chunk = Chunk(w.level, w.index_real, w.index_imag, pixels)
        faults.hit("coord.between_accept_and_persist")
        await self._enqueue_persist(w, chunk)
        self._write_upload_ack(writer, seq, proto.RESPONSE_ACCEPT, want, peer)

    async def _session_spans(self, reader: asyncio.StreamReader,
                             length: int) -> None:
        worker_id, sync_data, span_data = await self._read_span_report(
            reader, declared=length)
        self._ingest_span_report(worker_id, sync_data, span_data)

    async def _handle_batch_response(self, reader: asyncio.StreamReader,
                                     writer: asyncio.StreamWriter) -> None:
        # An honest worker's batch came from acquire_batch, which never
        # grants more than MAX_BATCH — a larger count is a corrupt or
        # hostile frame, and pretending to iterate it would pin this
        # handler on a stream that can only end in EOF.
        count = proto.validate_count(
            await self._read(framing.read_u32(reader)), MAX_BATCH,
            "batch-response count")
        for _ in range(count):
            await self._ingest_one(reader, writer)

    async def _ingest_one(self, reader: asyncio.StreamReader,
                          writer: asyncio.StreamWriter) -> None:
        t_accept = time.monotonic()
        w = Workload.from_wire(
            await self._read(framing.read_exact(reader, WORKLOAD_WIRE_SIZE)))
        # Claim (consume) the lease at echo time, as the reference does
        # (Distributer.cs:404): a concurrent second submission for the same
        # tile is rejected instead of double-matching while this payload is
        # still in flight.
        token = self.scheduler.claim(w)
        if token is None:
            framing.write_byte(writer, proto.RESPONSE_REJECT)
            await writer.drain()
            self.counters.inc(obs_names.COORD_RESULTS_REJECTED)
            flight.note(obs_events.SESS_RESULT_REJECTED, key=w.key,
                        reason="stale_lease")
            logger.info("rejected result for %s (stale or unknown lease)", w)
            return
        try:
            # The accept notification lives inside the claim's guarded
            # region: a peer that vanishes between accept and payload
            # must release the claim, not wait out its expiry.
            framing.write_byte(writer, proto.RESPONSE_ACCEPT)
            await writer.drain()
            data = await self._read(framing.read_exact(reader, CHUNK_PIXELS))
        except (ConnectionError, TimeoutError, asyncio.TimeoutError,
                framing.ProtocolError):
            # read_exact raises ConnectionError on a clean close,
            # ProtocolError on a truncated payload; a stalled upload
            # raises TimeoutError.  Either way the payload never
            # arrived: make the tile grantable again now rather than
            # waiting out the claim's expiry.
            self.scheduler.release_claim(w, token)
            self.counters.inc(obs_names.COORD_RESULTS_DROPPED)
            flight.note(obs_events.SESS_RESULT_DROPPED, key=w.key,
                        reason="upload_stalled")
            logger.info("dropped result for %s (upload stalled or "
                        "connection lost)", w)
            raise
        if not self.scheduler.finish_claim(w, token):
            # Claim expired between accept and payload arrival; drop.
            self.counters.inc(obs_names.COORD_RESULTS_DROPPED)
            flight.note(obs_events.SESS_RESULT_DROPPED, key=w.key,
                        reason="expired_mid_upload")
            logger.info("dropped result for %s (lease expired mid-upload)", w)
            return
        self.counters.inc(obs_names.COORD_RESULTS_ACCEPTED)
        # Accept latency: echo arrival -> payload fully landed (the
        # upload leg of the pipeline as the coordinator sees it).
        self.registry.observe(obs_names.HIST_ACCEPT_SECONDS,
                              time.monotonic() - t_accept)
        self.trace.record("result_received", w.key, worker=_peer_id(writer))
        chunk = Chunk(w.level, w.index_real, w.index_imag,
                      np.frombuffer(data, dtype=np.uint8))
        # Crashpoint: the tile is complete in the scheduler but its save
        # has not reached the writer queue — the widest window where only
        # the pending-save exclusion keeps a checkpoint honest.
        faults.hit("coord.between_accept_and_persist")
        await self._enqueue_persist(w, chunk)

    def pending_save_keys(self) -> set[Key]:
        """Keys whose persistence is in flight (checkpoint exclusion)."""
        return set(self._pending_saves)

    async def _enqueue_persist(self, w: Workload, chunk: Chunk) -> None:
        """Hand an accepted tile to the group-commit drainer.  Lazily
        starts the loop so handler-level tests that never call start()
        still persist; blocks (backpressuring the session) when the
        writer queue is full."""
        self._pending_saves.add(w.key)
        if self._persist_task is None or self._persist_task.done():
            self._start_persist_loop()
        await self._persist_queue.put((w, chunk))

    async def _persist_loop(self) -> None:
        """Drain the writer queue: block for one tile, then greedily
        scoop whatever else is backed up (bounded by the flush size) so
        a busy farm amortises blob writes and index appends into one
        ``put_many`` flush per wake-up."""
        while True:
            item = await self._persist_queue.get()
            if item is None:
                return
            batch = [item]
            while len(batch) < self.persist_flush_tiles:
                try:
                    nxt = self._persist_queue.get_nowait()
                except asyncio.QueueEmpty:
                    break
                if nxt is None:
                    # Sentinel drawn mid-drain: flush this batch, then
                    # let the next wake-up see the sentinel and exit.
                    self._persist_queue.put_nowait(None)
                    break
                batch.append(nxt)
            await self._persist_batch(batch)

    async def _persist_batch(self, batch) -> None:
        try:
            t0 = time.monotonic()
            await asyncio.to_thread(self.store.put_many,
                                    [chunk for _, chunk in batch])
            dt = time.monotonic() - t0
            self.counters.inc(obs_names.COORD_PERSIST_US, int(dt * 1e6))
            self.registry.observe(obs_names.HIST_PERSIST_SECONDS, dt)
            self.counters.inc(obs_names.COORD_CHUNKS_SAVED, len(batch))
            flight.note(obs_events.STORE_FLUSH, tiles=len(batch),
                        seconds=round(dt, 6))
            for _, chunk in batch:
                self.trace.record("persisted", chunk.key)
                if self.on_chunk_saved is not None:
                    try:
                        self.on_chunk_saved(chunk.key)
                    except Exception:
                        # A notification bug must not reopen a saved tile.
                        logger.exception("on_chunk_saved callback failed")
            logger.info("saved %d chunks in one flush", len(batch))
        except Exception:
            # The batch's bytes are lost; reopen the tiles so they are
            # granted again rather than leaving silent holes in a
            # "complete" run.
            logger.exception("failed to save batch of %d chunks; "
                             "reopening tiles", len(batch))
            self.counters.inc("save_errors", len(batch))
            flight.note(obs_events.STORE_SAVE_ERROR, tiles=len(batch))
            for w, _ in batch:
                flight.note(obs_events.STORE_REOPEN, key=w.key)
                self.scheduler.reopen(w)
        finally:
            # Durable (or reopened) either way: checkpoints may include —
            # or, on reopen, re-grant — these tiles from now on.
            for w, _ in batch:
                self._pending_saves.discard(w.key)
