"""Coordinator durability: atomic scheduler checkpoints + restore.

The index alone already makes *completions* durable — replaying
``_index.dat`` rebuilds the completed set (``storage/index.py``).  What
dies with the process is everything else the scheduler knows: the
frontier cursor, the retry queue, the lease table, and which worker is
mid-flight on what.  After a crash the old code restarted from a full
index replay with every lease forgotten, so in-flight workers' uploads
were rejected and they waited out their own leases.

This module checkpoints that state periodically and restores it:

- a **checkpoint** is one immutable blob (``_checkpoint-<levels>.dat``
  beside the index; atomic PUT on every backend) holding the scheduler
  snapshot, the index's logical end offset at snapshot time, and a
  **generation number**;
- the **restore** path loads the checkpoint, seeds the completed set
  from it, replays only the index *suffix* past the recorded offset
  (O(new entries), not O(index)), and rebuilds leases with their
  remaining TTLs so in-flight workers land results across the restart;
- **fencing**: each restore bumps the generation, and a checkpoint
  write refuses to clobber a blob with a higher generation — a stale
  coordinator that lost its data dir to a successor fails loudly
  instead of corrupting the successor's recovery state.

Offset/snapshot ordering (the correctness core): the index offset is
read *before* the scheduler snapshot, and tiles whose persistence is
still in flight are excluded from the checkpointed completed set.
Every key in the checkpoint therefore has a durable index entry at or
below the offset, or will land past it where the suffix replay finds
it; a crash at any interleaving loses no tiles and invents none.

The wire structs live in ``codecs/checkpoint.py`` (one on-disk format,
one owning module); the record layout is documented there.
"""

from __future__ import annotations

import asyncio
import logging
import time
import zlib
from dataclasses import dataclass
from typing import TYPE_CHECKING, Callable, Optional, Sequence

from distributedmandelbrot_tpu.codecs.checkpoint import (
    CHECKPOINT_CRC as _CRC, CHECKPOINT_HEADER as _HEADER,
    CHECKPOINT_KEY as _KEY, CHECKPOINT_LEASE as _LEASE,
    CHECKPOINT_MAGIC as MAGIC, CHECKPOINT_RETRY as _RETRY,
    CHECKPOINT_SETTING as _SETTING, CHECKPOINT_VERSION as VERSION)
from distributedmandelbrot_tpu.coordinator.scheduler import (Key,
                                                             TileScheduler)
from distributedmandelbrot_tpu.core.workload import LevelSetting, Workload
from distributedmandelbrot_tpu.obs import events as obs_events
from distributedmandelbrot_tpu.obs import flight
from distributedmandelbrot_tpu.obs import names as obs_names
from distributedmandelbrot_tpu.storage.store import ChunkStore
from distributedmandelbrot_tpu.utils import faults

if TYPE_CHECKING:
    from distributedmandelbrot_tpu.obs.metrics import Registry

logger = logging.getLogger("dmtpu.recovery")


class CorruptCheckpointError(Exception):
    """The checkpoint blob fails validation (bad magic/version/CRC/shape)."""


class StaleGenerationError(RuntimeError):
    """A newer coordinator generation owns this checkpoint (fencing)."""


def checkpoint_blob_name(level_settings: Sequence[LevelSetting],
                         namespace: str = "") -> str:
    """Per-levels-group blob name, so coordinators sharing a data dir
    with disjoint level sets (which the flock claims permit) keep
    independent checkpoints instead of clobbering one blob.
    ``namespace`` extends the same isolation to ring shards sharing
    every level (``_checkpoint-3-s0of4.dat``)."""
    levels = "_".join(str(s.level) for s in
                      sorted(level_settings, key=lambda s: s.level))
    return f"_checkpoint-{levels}{namespace}.dat"


@dataclass
class Checkpoint:
    """Decoded scheduler checkpoint (see module docstring for the wire)."""

    generation: int
    index_offset: int
    settings: tuple[tuple[int, int], ...]  # (level, max_iter), grant order
    cursor_pos: int
    cursor_done: bool
    completed: set[Key]
    leases: list[tuple[Workload, float]]  # (workload, remaining TTL)
    retry: list[Workload]


def encode_checkpoint(ckpt: Checkpoint) -> bytes:
    out = bytearray()
    out += _HEADER.pack(MAGIC, VERSION, ckpt.generation, ckpt.index_offset,
                        ckpt.cursor_pos, int(ckpt.cursor_done),
                        len(ckpt.settings), len(ckpt.completed),
                        len(ckpt.leases), len(ckpt.retry))
    for level, max_iter in ckpt.settings:
        out += _SETTING.pack(level, max_iter)
    for key in sorted(ckpt.completed):
        out += _KEY.pack(*key)
    for w, remaining in ckpt.leases:
        out += _LEASE.pack(w.level, w.index_real, w.index_imag,
                           w.max_iter or 0, remaining)
    for w in ckpt.retry:
        out += _RETRY.pack(w.level, w.index_real, w.index_imag,
                           w.max_iter or 0)
    out += _CRC.pack(zlib.crc32(bytes(out)))
    return bytes(out)


def decode_checkpoint(data: bytes) -> Checkpoint:
    if len(data) < _HEADER.size + _CRC.size:
        raise CorruptCheckpointError(
            f"checkpoint too short ({len(data)} bytes)")
    body, (crc,) = data[:-_CRC.size], _CRC.unpack(data[-_CRC.size:])
    if zlib.crc32(body) != crc:
        raise CorruptCheckpointError("checkpoint CRC mismatch")
    (magic, version, generation, index_offset, cursor_pos, cursor_done,
     n_settings, n_completed, n_leases, n_retry) = \
        _HEADER.unpack_from(body, 0)
    if magic != MAGIC:
        raise CorruptCheckpointError(f"bad checkpoint magic {magic!r}")
    if version != VERSION:
        raise CorruptCheckpointError(
            f"unsupported checkpoint version {version}")
    expect = (_HEADER.size + n_settings * _SETTING.size
              + n_completed * _KEY.size + n_leases * _LEASE.size
              + n_retry * _RETRY.size)
    if len(body) != expect:
        raise CorruptCheckpointError(
            f"checkpoint length {len(body)} != declared {expect}")
    pos = _HEADER.size
    settings = tuple(_SETTING.unpack_from(body, pos + i * _SETTING.size)
                     for i in range(n_settings))
    pos += n_settings * _SETTING.size
    completed = {_KEY.unpack_from(body, pos + i * _KEY.size)
                 for i in range(n_completed)}
    pos += n_completed * _KEY.size
    leases: list[tuple[Workload, float]] = []
    for i in range(n_leases):
        level, re, im, max_iter, remaining = \
            _LEASE.unpack_from(body, pos + i * _LEASE.size)
        leases.append((Workload(level, max_iter, re, im), remaining))
    pos += n_leases * _LEASE.size
    retry: list[Workload] = []
    for i in range(n_retry):
        level, re, im, max_iter = _RETRY.unpack_from(body,
                                                     pos + i * _RETRY.size)
        retry.append(Workload(level, max_iter, re, im))
    return Checkpoint(generation=generation, index_offset=index_offset,
                      settings=settings, cursor_pos=cursor_pos,
                      cursor_done=bool(cursor_done), completed=completed,
                      leases=leases, retry=retry)


def peek_generation(store: ChunkStore,
                    level_settings: Sequence[LevelSetting],
                    namespace: str = "") -> Optional[int]:
    """Generation of the stored checkpoint from its header alone (the
    fencing read before a write), or None when absent/unreadable."""
    head = store.backend.peek_blob(
        checkpoint_blob_name(level_settings, namespace), _HEADER.size)
    if head is None or len(head) < _HEADER.size:
        return None
    magic, version, generation = _HEADER.unpack_from(head, 0)[:3]
    if magic != MAGIC or version != VERSION:
        return None
    return generation


def load_checkpoint(store: ChunkStore,
                    level_settings: Sequence[LevelSetting],
                    namespace: str = ""
                    ) -> Optional[Checkpoint]:
    """The stored checkpoint, or None when absent or unreadable (a
    corrupt checkpoint degrades to a full index replay, never an error:
    the index remains the source of truth)."""
    data = store.backend.get_blob(
        checkpoint_blob_name(level_settings, namespace))
    if data is None:
        return None
    try:
        return decode_checkpoint(data)
    except CorruptCheckpointError as e:
        logger.warning("ignoring unreadable checkpoint (%s); falling back "
                       "to full index replay", e)
        return None


@dataclass
class RestoreResult:
    """What startup recovery produced (coordinator/app.py consumes it)."""

    completed: set[Key]  # checkpoint set merged with the suffix replay
    generation: int      # this coordinator's fencing generation
    checkpoint: Optional[Checkpoint]  # None -> full replay happened
    replayed_entries: int  # index entries scanned (suffix-only if ckpt)

    def apply(self, scheduler: TileScheduler, *,
              registry: Optional["Registry"] = None) -> int:
        """Adopt the checkpointed frontier/leases; returns leases rebuilt."""
        if self.checkpoint is None:
            return 0
        rebuilt = scheduler.restore_state(
            cursor_pos=self.checkpoint.cursor_pos,
            cursor_done=self.checkpoint.cursor_done,
            retry=self.checkpoint.retry,
            leases=self.checkpoint.leases)
        if registry is not None:
            registry.inc(obs_names.COORD_RESTORED_LEASES, rebuilt)
        return rebuilt


def load_restore_state(store: ChunkStore,
                       level_settings: Sequence[LevelSetting], *,
                       registry: Optional["Registry"] = None,
                       namespace: str = ""
                       ) -> RestoreResult:
    """Startup recovery: checkpoint + index-suffix replay, or full replay.

    A checkpoint is honored only when its level settings match this
    run's exactly and its recorded offset still fits the index (an
    offline compaction rewrites the index and invalidates offsets);
    otherwise the completed set comes from a full replay and only the
    generation number carries over.
    """
    levels = {s.level for s in level_settings}
    expected = tuple((s.level, s.max_iter) for s in level_settings)
    ckpt = load_checkpoint(store, level_settings, namespace)
    generation = 1 if ckpt is None else ckpt.generation + 1
    if ckpt is not None and (ckpt.settings != expected
                             or ckpt.index_offset > store.index_offset()):
        logger.warning(
            "checkpoint does not match this run (settings or index "
            "changed); falling back to full index replay")
        ckpt = None
    if ckpt is not None:
        completed = {k for k in ckpt.completed if k[0] in levels}
        suffix = store.entries_from(ckpt.index_offset)
        for e in suffix:
            if e.level in levels:
                completed.add(e.key)
        replayed = len(suffix)
        logger.info(
            "restored from checkpoint generation %d: %d completed tiles, "
            "%d index entries replayed past offset %d, %d leases pending "
            "rebuild", ckpt.generation, len(completed), replayed,
            ckpt.index_offset, len(ckpt.leases))
        if registry is not None:
            registry.inc(obs_names.COORD_RESTORES)
    else:
        entries = store.entries()
        completed = {e.key for e in entries if e.level in levels}
        replayed = len(entries)
    if registry is not None:
        registry.inc(obs_names.COORD_REPLAY_ENTRIES, replayed)
    flight.note(obs_events.CKPT_RESTORE, generation=generation,
                completed=len(completed), replayed=replayed,
                from_checkpoint=ckpt is not None)
    return RestoreResult(completed=completed, generation=generation,
                         checkpoint=ckpt, replayed_entries=replayed)


class RecoveryManager:
    """Owns periodic + on-demand checkpoints for one live coordinator.

    ``pending_keys_fn`` reports tiles whose asynchronous persistence has
    not landed (the distributer's in-flight save set); they are excluded
    from every checkpoint per the ordering invariant above.  The
    snapshot itself runs on the caller's (event loop) thread — scheduler
    state is only ever mutated there — while encoding + the blob PUT go
    through a worker thread so a multi-megabyte checkpoint never stalls
    grants.
    """

    def __init__(self, store: ChunkStore, scheduler: TileScheduler, *,
                 generation: int = 1, period: float = 0.0,
                 registry: Optional["Registry"] = None,
                 pending_keys_fn: Optional[Callable[[], set[Key]]] = None,
                 namespace: str = ""
                 ) -> None:
        self.store = store
        self.scheduler = scheduler
        self.generation = generation
        self.period = period
        self._registry = registry
        self._pending_keys_fn = pending_keys_fn
        self.namespace = namespace
        self._blob_name = checkpoint_blob_name(scheduler.level_settings,
                                               namespace)
        self._task: Optional[asyncio.Task] = None
        self._fenced = False

    # -- lifecycle --------------------------------------------------------

    async def start(self) -> None:
        if self.period > 0:
            self._task = asyncio.create_task(self._loop())

    async def stop(self, *, final_checkpoint: bool = True) -> None:
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass
            except Exception:
                logger.exception("checkpoint loop had failed")
            self._task = None
        if final_checkpoint and not self._fenced:
            # A clean shutdown's parting checkpoint makes the next
            # restart O(suffix) from the first moment.
            try:
                await self.checkpoint()
            except Exception:
                logger.exception("final checkpoint on stop failed")

    async def _loop(self) -> None:
        while True:
            await asyncio.sleep(self.period)
            try:
                await self.checkpoint()
            except StaleGenerationError:
                # A successor owns the data dir now; keeping our blob
                # writes away from it is the entire point of fencing.
                logger.error(
                    "fenced out: a newer coordinator generation owns the "
                    "checkpoint; disabling further checkpoints")
                flight.note(obs_events.CKPT_ERROR, reason="fenced",
                            generation=self.generation)
                self._fenced = True
                if self._registry is not None:
                    self._registry.inc(obs_names.COORD_CHECKPOINT_ERRORS)
                return
            except Exception as exc:
                logger.exception("periodic checkpoint failed")
                flight.note(obs_events.CKPT_ERROR, reason="exception",
                            error=str(exc)[:120])
                if self._registry is not None:
                    self._registry.inc(obs_names.COORD_CHECKPOINT_ERRORS)

    # -- checkpointing ----------------------------------------------------

    def build(self) -> Checkpoint:
        """Consistent snapshot (call on the scheduler's owning thread).

        The index offset is read BEFORE the scheduler snapshot: a save
        landing between the two reads puts its entry past the offset,
        where the restore-time suffix replay recovers it — the ordering
        that makes crash-at-any-point lossless.
        """
        index_offset = self.store.index_offset()
        pending = set(self._pending_keys_fn()) \
            if self._pending_keys_fn is not None else set()
        snap = self.scheduler.snapshot_state(exclude=pending)
        settings = tuple((s.level, s.max_iter)
                         for s in self.scheduler.level_settings)
        return Checkpoint(generation=self.generation,
                          index_offset=index_offset, settings=settings,
                          cursor_pos=snap["cursor_pos"],
                          cursor_done=snap["cursor_done"],
                          completed=snap["completed"],
                          leases=snap["leases"], retry=snap["retry"])

    async def checkpoint(self) -> dict:
        """Snapshot now, persist off-loop; returns write stats."""
        ckpt = self.build()
        return await asyncio.to_thread(self.write, ckpt)

    def checkpoint_sync(self) -> dict:
        """Blocking snapshot+write for offline callers (CLI, benches)."""
        return self.write(self.build())

    def write(self, ckpt: Checkpoint) -> dict:
        """Encode + fence-check + atomic PUT; returns write stats."""
        t0 = time.monotonic()
        flight.note(obs_events.CKPT_BEGIN, generation=ckpt.generation,
                    leases=len(ckpt.leases), completed=len(ckpt.completed))
        stored = peek_generation(self.store, self.scheduler.level_settings,
                                 self.namespace)
        if stored is not None and stored > ckpt.generation:
            raise StaleGenerationError(
                f"stored checkpoint generation {stored} > ours "
                f"{ckpt.generation}")
        data = encode_checkpoint(ckpt)
        # Crash here and the previous checkpoint survives untouched —
        # the blob PUT below is atomic on every backend.
        faults.hit("recovery.mid_checkpoint")
        self.store.backend.put_blob(self._blob_name, data, fsync=True)
        dt = time.monotonic() - t0
        flight.note(obs_events.CKPT_DONE, generation=ckpt.generation,
                    bytes=len(data), seconds=round(dt, 6))
        if self._registry is not None:
            self._registry.inc(obs_names.COORD_CHECKPOINTS_WRITTEN)
            self._registry.observe(obs_names.HIST_CHECKPOINT_SECONDS, dt)
        logger.info(
            "checkpoint generation %d: %d completed, %d leases, %d retry, "
            "index offset %d, %d bytes in %.3fs", ckpt.generation,
            len(ckpt.completed), len(ckpt.leases), len(ckpt.retry),
            ckpt.index_offset, len(data), dt)
        return {"generation": ckpt.generation,
                "index_offset": ckpt.index_offset,
                "completed": len(ckpt.completed),
                "leases": len(ckpt.leases), "retry": len(ckpt.retry),
                "bytes": len(data), "seconds": dt}
