"""The coordinator process: one event loop hosting both services.

Mirrors the reference's process topology — a single process running the
Distributer and DataServer concurrently over shared storage
(``Program.cs:127-150``) — as one asyncio loop instead of two blocking
threads.  Resume happens here: completed tiles are seeded from the on-disk
index before the distributer starts (``Distributer.cs:124,165-175``) —
via the durability checkpoint when one exists (suffix-only index replay
plus lease/frontier restore, coordinator/recovery.py), full index replay
otherwise.
"""

from __future__ import annotations

import asyncio
import logging
import time
from typing import Optional, Sequence

from distributedmandelbrot_tpu.coordinator.clock import Clock
from distributedmandelbrot_tpu.coordinator.dataserver import DataServer
from distributedmandelbrot_tpu.coordinator.distributer import Distributer
from distributedmandelbrot_tpu.coordinator.recovery import (RecoveryManager,
                                                            load_restore_state)
from distributedmandelbrot_tpu.coordinator.scheduler import TileScheduler
from distributedmandelbrot_tpu.core.workload import LevelSetting
from distributedmandelbrot_tpu.net import protocol as proto
from distributedmandelbrot_tpu.obs import flight
from distributedmandelbrot_tpu.obs import names as obs_names
from distributedmandelbrot_tpu.obs.exporter import MetricsExporter
from distributedmandelbrot_tpu.obs.metrics import Registry
from distributedmandelbrot_tpu.obs.slo import standard_slos
from distributedmandelbrot_tpu.obs.spans import SpanStore
from distributedmandelbrot_tpu.obs.timeseries import (
    DEFAULT_HISTORY_WINDOW, DEFAULT_SAMPLE_PERIOD, TimeseriesSampler)
from distributedmandelbrot_tpu.obs.trace import TraceLog
from distributedmandelbrot_tpu.serve.cache import DecodedTileCache
from distributedmandelbrot_tpu.serve.gateway import TileGateway
from distributedmandelbrot_tpu.serve.ondemand import OnDemandComputer
from distributedmandelbrot_tpu.sessions import (SessionService,
                                                build_session_service)
from distributedmandelbrot_tpu.storage.ownership import LevelClaims
from distributedmandelbrot_tpu.storage.store import ChunkStore
from distributedmandelbrot_tpu.utils.metrics import Counters

logger = logging.getLogger("dmtpu.coordinator")


class Coordinator:
    def __init__(self, level_settings: Sequence[LevelSetting], *,
                 data_dir_parent: str = "",
                 host: str = "0.0.0.0",
                 distributer_port: int = proto.DEFAULT_DISTRIBUTER_PORT,
                 dataserver_port: int = proto.DEFAULT_DATASERVER_PORT,
                 lease_timeout: float = proto.DEFAULT_LEASE_TIMEOUT,
                 sweep_period: float = proto.DEFAULT_SWEEP_PERIOD,
                 read_timeout: Optional[float] = proto.DEFAULT_READ_TIMEOUT,
                 clock: Optional[Clock] = None,
                 fsync_index: bool = False,
                 stats_period: float = 0.0,
                 gateway_port: Optional[int] = None,
                 gateway_cache_tiles: int = 64,
                 gateway_max_queue_depth: int = 1024,
                 gateway_rate: Optional[float] = None,
                 gateway_burst: float = 256.0,
                 gateway_render_tiles: int = 64,
                 gateway_sessions: bool = True,
                 session_rate: Optional[float] = None,
                 session_burst: float = 32.0,
                 session_ttl: Optional[float] = 300.0,
                 session_capacity: int = 1024,
                 prefetch_horizon: int = 3,
                 first_paint_max_iter: int = 64,
                 ondemand_deadline: float = proto.DEFAULT_ONDEMAND_DEADLINE,
                 ondemand_poll_interval: float = 1.0,
                 exporter_port: Optional[int] = None,
                 sample_period: float = DEFAULT_SAMPLE_PERIOD,
                 history_window: float = DEFAULT_HISTORY_WINDOW,
                 accept_spans: bool = True,
                 accept_session: bool = True,
                 checkpoint_period: float = 0.0,
                 ring_slice=None) \
            -> None:
        # One registry + one trace ring + one span store feed every layer
        # of this process; the exporter (opt-in like the gateway:
        # exporter_port=None disables, 0 binds an ephemeral loopback
        # port) serves all three.
        self.registry = Registry()
        self.trace = TraceLog()
        self.spans = SpanStore()
        # ``ring_slice`` (control/ring.py RingSlice, duck-typed to keep
        # the import DAG acyclic) turns this process into one shard of a
        # sharded control plane: the scheduler's frontier is restricted
        # to the slice, the store's index log / checkpoint blob / level
        # claims are namespaced per shard inside the SHARED data dir,
        # and the distributer answers misrouted uploads with redirects.
        self.ring_slice = ring_slice
        namespace = "" if ring_slice is None else ring_slice.namespace
        # Black-box flight recorder: the coordinator names the process
        # (shard-N when sharded) and points the dump header at the span
        # store's per-worker clock offsets so postmortem (obs/
        # postmortem.py) can order this process's events against its
        # workers' causally.
        role = "coordinator" if ring_slice is None \
            else f"shard-{ring_slice.shard}"
        self.flight = flight.ensure(role, registry=self.registry)
        if self.flight is not None:
            if ring_slice is not None:
                self.flight.shard = ring_slice.shard
            self.flight.offsets_fn = self._flight_offsets
        self.store = ChunkStore(data_dir_parent, fsync_index=fsync_index,
                                registry=self.registry,
                                namespace=namespace)
        # Fail loudly if another live coordinator owns any of our levels
        # on this data dir (reference: the static claimed-levels set,
        # Distributer.cs:14,109-115 — file-based here because our
        # coordinators are separate processes).  Released in stop().
        # Shards claim under their namespace: peers legitimately share
        # every level, each owning a disjoint keyspace slice.
        self._level_claims = LevelClaims(
            self.store.data_dir, [s.level for s in level_settings],
            namespace=namespace)
        try:
            # Checkpoint-aware resume: the completed set comes from the
            # last checkpoint plus a replay of only the index entries past
            # its recorded offset; with no (usable) checkpoint this is the
            # classic full index replay.
            restore = load_restore_state(self.store, level_settings,
                                         registry=self.registry,
                                         namespace=namespace)
            if restore.completed:
                logger.info("resume: %d tiles already completed on disk",
                            len(restore.completed))
            self.counters = Counters(registry=self.registry)
            kwargs = {} if clock is None else {"clock": clock}
            self.scheduler = TileScheduler(
                level_settings, completed=restore.completed,
                lease_timeout=lease_timeout, registry=self.registry,
                trace=self.trace,
                owns=None if ring_slice is None else ring_slice.owns,
                **kwargs)
            # Adopt the checkpointed frontier cursor, retry queue, and
            # leases (with remaining TTLs) so in-flight workers from
            # before a restart can land their results against live leases.
            restore.apply(self.scheduler, registry=self.registry)
            # Live scheduler gauges, read at scrape time (plain ints under
            # the GIL — no locking needed for a monitoring read).
            self.registry.gauge(obs_names.GAUGE_FRONTIER_DEPTH,
                                help="tiles grantable right now",
                                fn=lambda: self.scheduler.frontier_depth)
            self.registry.gauge(obs_names.GAUGE_OUTSTANDING_LEASES,
                                help="unexpired leases",
                                fn=lambda: self.scheduler.outstanding_leases)
            self.registry.gauge(obs_names.GAUGE_COMPLETED_TILES,
                                help="completed tiles of the configured grid",
                                fn=lambda: self.scheduler.completed_count)
            self.distributer = Distributer(self.scheduler, self.store,
                                           host=host, port=distributer_port,
                                           sweep_period=sweep_period,
                                           read_timeout=read_timeout,
                                           counters=self.counters,
                                           trace=self.trace,
                                           spans=self.spans,
                                           accept_spans=accept_spans,
                                           accept_session=accept_session,
                                           ring_slice=ring_slice)
            self.dataserver = DataServer(self.store, host=host,
                                         port=dataserver_port,
                                         read_timeout=read_timeout,
                                         counters=self.counters,
                                         ring_slice=ring_slice)
            # The serving gateway is opt-in (gateway_port=None disables);
            # when enabled it shares the store, scheduler, and counters,
            # and hooks the distributer's save path for compute-on-read
            # arrival notification.
            self.gateway: Optional[TileGateway] = None
            self.sessions: Optional[SessionService] = None
            if gateway_port is not None:
                cache = DecodedTileCache(self.store,
                                         capacity=gateway_cache_tiles,
                                         counters=self.counters)
                ondemand = OnDemandComputer(
                    self.scheduler, cache, deadline=ondemand_deadline,
                    poll_interval=ondemand_poll_interval,
                    counters=self.counters)
                if gateway_sessions:
                    self.sessions = build_session_service(
                        cache, scheduler=self.scheduler,
                        counters=self.counters,
                        clock=self.scheduler.clock.now,
                        session_capacity=session_capacity,
                        session_ttl=session_ttl,
                        session_rate=session_rate,
                        session_burst=session_burst,
                        prefetch_horizon=prefetch_horizon,
                        first_paint_max_iter=first_paint_max_iter)
                self.gateway = TileGateway(
                    cache, ondemand=ondemand, host=host, port=gateway_port,
                    read_timeout=read_timeout,
                    max_queue_depth=gateway_max_queue_depth,
                    rate=gateway_rate, burst=gateway_burst,
                    render_cache_tiles=gateway_render_tiles,
                    counters=self.counters, trace=self.trace,
                    ring_slice=ring_slice, sessions=self.sessions)
                gateway = self.gateway

                def _on_chunk_saved(key: tuple[int, int, int]) -> None:
                    # A save may be a deeper-max_iter variant of a tile
                    # the cache tiers hold (progressive refinement, or
                    # simply a re-render at new settings): drop the
                    # stale entries and settle any pending refinement
                    # BEFORE waking on-demand waiters, so a woken read
                    # can only see the fresh bytes.
                    gateway.invalidate_saved(key)
                    ondemand.notify_saved(key)

                self.distributer.on_chunk_saved = _on_chunk_saved
            # Durability checkpoints: periodic when checkpoint_period > 0,
            # on-demand always (POST /checkpoint, final write on stop).
            self.recovery = RecoveryManager(
                self.store, self.scheduler,
                generation=restore.generation,
                period=checkpoint_period, registry=self.registry,
                pending_keys_fn=self.distributer.pending_save_keys,
                namespace=namespace)
            # Fleet observability: the ring-buffer sampler rides the
            # exporter (no exporter, nobody can read the history), and
            # gateway-bearing processes track the standard SLO pair on
            # it; the obs loop (start()) advances both.
            self.exporter: Optional[MetricsExporter] = None
            self.sampler: Optional[TimeseriesSampler] = None
            self.slos: list = []
            self._slo_status: list[dict] = []
            self._worker_stats_cache: tuple[float, Optional[dict]] = \
                (0.0, None)
            if exporter_port is not None:
                self.sampler = TimeseriesSampler(
                    self.registry, period=sample_period,
                    window=history_window)
                if gateway_port is not None:
                    self.slos = standard_slos(self.sampler)
                self.exporter = MetricsExporter(
                    self.registry, trace=self.trace,
                    spans=self.spans,
                    varz_extra=self._varz_extra,
                    checkpoint_cb=self.recovery.checkpoint,
                    sampler=self.sampler,
                    flight=self.flight,
                    host=host, port=exporter_port)
        except BaseException:
            # Construction failed after the claim: release it, or the
            # level stays locked by this live process forever.
            self._level_claims.release()
            raise
        self.stats_period = stats_period
        self._stats_task: Optional[asyncio.Task] = None
        self._obs_task: Optional[asyncio.Task] = None

    async def start(self) -> None:
        try:
            await self.distributer.start()
            await self.dataserver.start()
            if self.gateway is not None:
                await self.gateway.start()
            if self.exporter is not None:
                await self.exporter.start()
        except BaseException:
            # A failed startup (e.g. port already bound) will never reach
            # stop(): shut down whichever service DID start — a
            # half-started distributer would keep granting tiles for a
            # level someone else can now claim — then release the claim
            # (a leaked claim from a live pid would lock the level for
            # the life of this process).  release() sits in a finally:
            # the stops await, and a cancellation landing there must not
            # skip the release (CancelledError is not an Exception).
            try:
                await self.distributer.stop()
                await self.dataserver.stop()
                if self.gateway is not None:
                    await self.gateway.stop()
                if self.exporter is not None:
                    await self.exporter.stop()
            except Exception:
                logger.exception("cleanup after failed startup")
            finally:
                self._level_claims.release()
            raise
        await self.recovery.start()
        if self.stats_period > 0:
            self._stats_task = asyncio.create_task(self._stats_loop())
        if self.sampler is not None:
            self._obs_task = asyncio.create_task(self._obs_loop())

    async def stop(self) -> None:
        if self._obs_task is not None:
            self._obs_task.cancel()
            try:
                await self._obs_task
            except asyncio.CancelledError:
                pass
            except Exception:
                logger.exception("obs sampling task had failed")
        if self._stats_task is not None:
            self._stats_task.cancel()
            try:
                await self._stats_task
            except asyncio.CancelledError:
                pass
            except Exception:
                # A previously-failed stats task must never prevent the
                # services below from shutting down.
                logger.exception("stats task had failed")
        try:
            # Exporter first (scrapes read live scheduler/cache state),
            # then gateway: its in-flight requests read through the store
            # and await distributer saves, so it should stop serving before
            # the services it depends on go away.
            if self.exporter is not None:
                await self.exporter.stop()
            if self.gateway is not None:
                await self.gateway.stop()
            await self.distributer.stop()
            await self.dataserver.stop()
            # Last: distributer.stop() gathered the in-flight save tasks,
            # so the parting checkpoint records every durable tile and the
            # next start replays a zero-length index suffix.
            await self.recovery.stop()
        finally:
            # Claims must release even when a service stop raises.
            self._level_claims.release()

    async def _stats_loop(self) -> None:
        """Periodic progress/throughput report (survey §5.1/§5.5 — the
        reference has no observability at all; operators watch this)."""
        last: dict[str, int] = {}
        while True:
            await asyncio.sleep(self.stats_period)
            try:
                snap = self.counters.snapshot()
                delta = {k: v - last.get(k, 0) for k, v in snap.items()
                         if v != last.get(k, 0)}
                last = snap
                logger.info(
                    "stats: %d/%d tiles complete, %d leased; totals %s; "
                    "last %.0fs %s",
                    self.scheduler.completed_count,
                    self.scheduler.total_tiles,
                    self.scheduler.outstanding_leases, snap,
                    self.stats_period, delta or "(idle)")
            except Exception:
                # Reporting must never kill itself (or shutdown, see stop).
                logger.exception("stats reporting failed")

    async def _obs_loop(self) -> None:
        """Drive the timeseries sampler and the SLO state machines at
        the sample period.  SLO evaluation happens HERE, not per /varz
        request: the state machine must advance on one clock, not at
        whatever rate scrapers poll."""
        assert self.sampler is not None
        while True:
            await asyncio.sleep(self.sampler.period)
            try:
                self.sampler.sample()
                if self.slos:
                    self._slo_status = [slo.evaluate()
                                        for slo in self.slos]
            except Exception:
                # Observability must never kill the services it watches.
                logger.exception("obs sampling failed")

    async def run_forever(self) -> None:
        await self.start()
        try:
            await asyncio.Event().wait()
        finally:
            await self.stop()

    @property
    def distributer_port(self) -> int:
        return self.distributer.port

    @property
    def dataserver_port(self) -> int:
        return self.dataserver.port

    @property
    def gateway_port(self) -> Optional[int]:
        return None if self.gateway is None else self.gateway.port

    @property
    def exporter_port(self) -> Optional[int]:
        return None if self.exporter is None else self.exporter.port

    def _flight_offsets(self) -> dict:
        """Per-worker NTP offsets for the flight-dump header, keyed by
        the hex worker id workers stamp into their own dumps."""
        out = {}
        for wid in self.spans.workers():
            est = self.spans.offset(wid)
            if est is not None:
                out[format(wid, "016x")] = {"offset": est.offset,
                                            "error": est.error}
        return out

    def _varz_extra(self) -> dict:
        """Scheduler frontier state for /varz (beyond the gauge family)."""
        extra = {
            "role": ("shard" if self.ring_slice is not None
                     else "coordinator"),
            "scheduler": {
                "frontier_depth": self.scheduler.frontier_depth,
                "outstanding_leases": self.scheduler.outstanding_leases,
                "completed": self.scheduler.completed_count,
                "total": self.scheduler.total_tiles,
            },
            "recovery": {
                "generation": self.recovery.generation,
                "checkpoint_period": self.recovery.period,
            },
        }
        workers = self._worker_stats_cached()
        if workers:
            extra["workers"] = workers
        if self._slo_status:
            extra["slo"] = self._slo_status
        if self.sessions is not None:
            extra["sessions"] = self.sessions.varz()
        if self.ring_slice is not None:
            extra["shard"] = {
                "shard": self.ring_slice.shard,
                "n_shards": self.ring_slice.n_shards,
                "ring_version": self.ring_slice.version,
                "owned_tiles": self.scheduler.owned_tiles,
            }
        return extra

    def _worker_stats_cached(self) -> dict:
        """Span-reported per-worker roll-up, persist seconds joined from
        the trace ring — what the fleet aggregator merges into its
        worker table (workers need no exporter to be visible).

        Cached for one sample period: the roll-up walks the full trace
        ring and span store (milliseconds on a loaded coordinator, on
        the event loop), and /varz is served per-scraper — a fleet of
        aggregators polling must not multiply that walk.  Worker rates
        are window deltas aggregator-side, so sample-period staleness
        is invisible there."""
        now = time.monotonic()
        ttl = self.sampler.period if self.sampler is not None else 2.0
        cached_at, cached = self._worker_stats_cache
        if cached is not None and now - cached_at < ttl:
            return cached
        persist_by_key = {tuple(s["key"]): s.get("persist_s", 0.0)
                          for s in self.trace.spans()
                          if s.get("complete")}
        workers = self.spans.per_worker_stats(persist_by_key)
        self._worker_stats_cache = (now, workers)
        return workers
