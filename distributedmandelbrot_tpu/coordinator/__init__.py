"""Coordinator: scheduling, leases, and both wire services in one process."""

from distributedmandelbrot_tpu.coordinator.app import Coordinator
from distributedmandelbrot_tpu.coordinator.clock import (Clock, ManualClock,
                                                         MonotonicClock)
from distributedmandelbrot_tpu.coordinator.dataserver import DataServer
from distributedmandelbrot_tpu.coordinator.distributer import Distributer
from distributedmandelbrot_tpu.coordinator.embed import EmbeddedCoordinator
from distributedmandelbrot_tpu.coordinator.scheduler import (Lease,
                                                             TileScheduler)

__all__ = ["Coordinator", "Clock", "ManualClock", "MonotonicClock",
           "DataServer", "Distributer", "EmbeddedCoordinator", "Lease",
           "TileScheduler"]
