"""Asyncio chunk-serving server (the read-side coordinator service).

Wire-compatible with the reference DataServer (``DataServer.cs:82-224``):
12-byte query ``(level, index_real, index_imag)`` each uint32 LE, one status
byte (accept / reject-invalid / not-yet-available), and on accept a
uint32-length-prefixed codec payload.

Improvements: queries on one connection can repeat until EOF; the store's
payload LRU means a hot chunk is served without the decode + re-encode round
trip the reference performs per request (``DataServer.cs:204-221``); index
scanning runs in a thread pool off the event loop.
"""

from __future__ import annotations

import asyncio
import logging
from typing import Optional

from distributedmandelbrot_tpu.net import framing
from distributedmandelbrot_tpu.net import protocol as proto
from distributedmandelbrot_tpu.obs import names as obs_names
from distributedmandelbrot_tpu.storage.store import ChunkStore
from distributedmandelbrot_tpu.utils.metrics import Counters

logger = logging.getLogger("dmtpu.dataserver")


class DataServer:
    def __init__(self, store: ChunkStore, *, host: str = "0.0.0.0",
                 port: int = proto.DEFAULT_DATASERVER_PORT,
                 read_timeout: Optional[float] = proto.DEFAULT_READ_TIMEOUT,
                 counters: Optional[Counters] = None,
                 ring_slice=None) -> None:
        self.store = store
        self.host = host
        self.port = port
        self.read_timeout = read_timeout
        self.counters = counters if counters is not None else Counters()
        # Duck-typed control.ring.RingSlice (owns/owner_of/version).  A
        # sharded coordinator's store holds only its own slice, so a
        # query for a foreign key is answered with QUERY_REDIRECT + the
        # authoritative shard instead of a not-available that would
        # never resolve here.
        self.ring_slice = ring_slice
        self._server: Optional[asyncio.Server] = None

    async def start(self) -> None:
        self._server = await asyncio.start_server(
            self._handle_connection, self.host, self.port)
        self.port = self._server.sockets[0].getsockname()[1]
        logger.info("dataserver listening on %s:%d", self.host, self.port)

    async def stop(self) -> None:
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()

    async def _handle_connection(self, reader: asyncio.StreamReader,
                                 writer: asyncio.StreamWriter) -> None:
        peer = writer.get_extra_info("peername")
        try:
            while True:
                try:
                    # Same per-read deadline as the write side (reference:
                    # DataServer.cs:11): idle or stalled clients are closed
                    # and re-dial instead of pinning this task.
                    raw = await framing.read_exact(reader, proto.QUERY.size) \
                        if self.read_timeout is None else \
                        await asyncio.wait_for(
                            framing.read_exact(reader, proto.QUERY.size),
                            self.read_timeout)
                except (ConnectionError, TimeoutError,
                        asyncio.TimeoutError):
                    break  # clean EOF / idle close between queries
                level, index_real, index_imag = proto.QUERY.unpack(raw)
                await self._serve_query(writer, level, index_real, index_imag)
                await writer.drain()
        except (ConnectionError, asyncio.CancelledError):
            pass
        except framing.ProtocolError as e:
            # Malformed or hostile frame (e.g. a truncated query): drop
            # the connection, leave a trail, keep the accept loop alive.
            self.counters.inc(obs_names.COORD_FRAMES_REJECTED)
            logger.error("dropping %s: %s", peer, e)
        except Exception:
            logger.exception("error serving %s", peer)
        finally:
            writer.close()
            try:
                await writer.wait_closed()
            except (ConnectionError, OSError):
                pass

    async def _serve_query(self, writer: asyncio.StreamWriter, level: int,
                           index_real: int, index_imag: int) -> None:
        if not proto.query_in_range(level, index_real, index_imag):
            framing.write_byte(writer, proto.QUERY_REJECT)
            self.counters.inc("queries_rejected")
            logger.info("rejected invalid query (%d,%d,%d)",
                        level, index_real, index_imag)
            return
        key = (level, index_real, index_imag)
        if self.ring_slice is not None and not self.ring_slice.owns(key):
            framing.write_byte(writer, proto.QUERY_REDIRECT)
            writer.write(proto.REDIRECT.pack(self.ring_slice.owner_of(key),
                                             self.ring_slice.version))
            self.counters.inc(obs_names.DATASERVER_REDIRECTS)
            return
        payload = await asyncio.to_thread(
            self.store.load_payload, level, index_real, index_imag)
        if payload is None:
            framing.write_byte(writer, proto.QUERY_NOT_AVAILABLE)
            self.counters.inc("queries_unavailable")
            return
        framing.write_byte(writer, proto.QUERY_ACCEPT)
        framing.write_u32(writer, len(payload))
        writer.write(payload)
        self.counters.inc("queries_served")
        logger.info("served chunk (%d,%d,%d): %d bytes",
                    level, index_real, index_imag, len(payload))
