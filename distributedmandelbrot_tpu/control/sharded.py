"""One shard of the sharded control plane.

:class:`ShardedCoordinator` is the launcher behind ``dmtpu coord
--shard K/N --ring ring.json``: it resolves the shard's
:class:`~distributedmandelbrot_tpu.control.ring.RingSlice` and runs the
existing Distributer / scheduler / recovery stack
(:class:`~distributedmandelbrot_tpu.coordinator.app.Coordinator`) over
that slice against ONE shared data directory.  Nothing about the inner
stack is shard-aware beyond the slice it is handed: the scheduler's
frontier is filtered to owned keys, the store's index log / checkpoint
blob / level claims carry the ``-sKofN`` namespace, and the distributer
answers misrouted uploads with the authoritative owner.

The ownership function needs only ``K/N`` (ring.py: endpoints never
feed the hash), so a fleet launcher may start all N shards on ephemeral
ports first, then collect the bound ports into ``ring.json`` for the
workers — :meth:`ShardedCoordinator.bound_info` is the per-shard entry
of that table.
"""

from __future__ import annotations

from typing import Optional, Sequence

from distributedmandelbrot_tpu.control.ring import (DEFAULT_REPLICAS,
                                                    RingSlice, ShardInfo,
                                                    load_ring_for_shard,
                                                    parse_shard_spec)
from distributedmandelbrot_tpu.coordinator.app import Coordinator
from distributedmandelbrot_tpu.core.workload import LevelSetting


class ShardedCoordinator:
    """Coordinator shard ``K/N``: the full stack over one ring slice.

    ``ring_path=None`` launches endpoint-blind (ownership from ``K/N``
    alone); every extra keyword argument flows to
    :class:`Coordinator` unchanged, so shards support the whole single-
    coordinator surface (gateway, exporter, checkpoints, fault clocks).
    """

    def __init__(self, level_settings: Sequence[LevelSetting],
                 shard: int, n_shards: int, *,
                 ring_path: Optional[str] = None,
                 ring_version: int = 1,
                 replicas: int = DEFAULT_REPLICAS,
                 **coordinator_kwargs) -> None:
        self.ring_slice: RingSlice = load_ring_for_shard(
            ring_path, shard, n_shards,
            version=ring_version, replicas=replicas)
        self.coordinator = Coordinator(level_settings,
                                       ring_slice=self.ring_slice,
                                       **coordinator_kwargs)

    @classmethod
    def from_spec(cls, level_settings: Sequence[LevelSetting], spec: str,
                  **kwargs) -> "ShardedCoordinator":
        """``"K/N"`` spec form (the CLI's ``--shard`` argument)."""
        shard, n_shards = parse_shard_spec(spec)
        return cls(level_settings, shard, n_shards, **kwargs)

    # -- identity ----------------------------------------------------------

    @property
    def shard(self) -> int:
        return self.ring_slice.shard

    @property
    def n_shards(self) -> int:
        return self.ring_slice.n_shards

    @property
    def namespace(self) -> str:
        return self.ring_slice.namespace

    def bound_info(self, host: str = "127.0.0.1") -> ShardInfo:
        """This shard's row of a post-launch ring table: the ports the
        services actually bound (ephemeral-port launches report real
        ports here after ``start()``)."""
        return ShardInfo(host,
                         distributer_port=self.coordinator.distributer_port,
                         dataserver_port=self.coordinator.dataserver_port,
                         gateway_port=self.coordinator.gateway_port or 0,
                         exporter_port=self.coordinator.exporter_port or 0)

    # -- delegated lifecycle ----------------------------------------------

    async def start(self) -> None:
        await self.coordinator.start()

    async def stop(self) -> None:
        await self.coordinator.stop()

    async def run_forever(self) -> None:
        await self.coordinator.run_forever()

    # -- delegated surface the tests/benches poke --------------------------

    @property
    def scheduler(self):
        return self.coordinator.scheduler

    @property
    def counters(self):
        return self.coordinator.counters

    @property
    def store(self):
        return self.coordinator.store

    @property
    def distributer_port(self) -> int:
        return self.coordinator.distributer_port

    @property
    def dataserver_port(self) -> int:
        return self.coordinator.dataserver_port

    @property
    def gateway_port(self) -> Optional[int]:
        return self.coordinator.gateway_port

    @property
    def exporter_port(self) -> Optional[int]:
        return self.coordinator.exporter_port
