"""Consistent-hash ring: tile key -> coordinator shard.

The MPI reference (PAPERS.md, arxiv 2007.00745) partitions grant
authority statically by rank — rank ``r`` owns every ``r``-th row —
which couples the partition to the process count and reshuffles
*everything* when a rank is added.  A consistent-hash ring owns the
same decision with two properties that matter for an elastic fleet:

- **determinism**: ownership is a pure function of ``(key, n_shards,
  replicas)`` via BLAKE2b over the packed key bytes — every process
  holding the same ring config (or even just ``K/N``) computes the
  same owner, with no coordination and no RPC on the hot path;
- **stability**: growing N to N+1 moves ~1/(N+1) of the keyspace, so a
  scale-out event invalidates a sliver of in-flight leases instead of
  all of them.

The ring config is a small versioned JSON document (``ring.json``)
naming the shard endpoints in shard-index order::

    {
      "format": 1,
      "version": 3,
      "replicas": 64,
      "shards": [
        {"host": "127.0.0.1", "distributer_port": 59010,
         "dataserver_port": 59011, "gateway_port": 59012},
        ...
      ]
    }

``version`` is the skew detector: it rides the wire in
``RING_REQ``/``RING_INFO``/``REDIRECT`` frames (net/protocol.py) so a
worker holding a stale config learns about it on its first exchange.
Ownership itself depends only on ``len(shards)`` and ``replicas`` —
endpoints can be rewritten (ephemeral ports after a restart) without
remapping any key, which is what lets a chaos run SIGKILL a shard and
bring it back on a fresh port under the same ring version.
"""

from __future__ import annotations

import bisect
import hashlib
import json
import struct
from dataclasses import dataclass
from typing import Callable, Optional, Sequence

Key = tuple[int, int, int]  # (level, index_real, index_imag)

RING_FORMAT = 1
# Virtual nodes per shard.  64 keeps the max/min slice ratio under ~1.3
# for small N while the full point table stays tiny (N*64 u64s).
DEFAULT_REPLICAS = 64

# Deliberately NOT net/protocol's QUERY struct, even though the format
# matches today: this is the frozen hash-domain encoding of a tile key,
# and tying it to the wire layout would silently remap every key (and
# orphan every on-disk shard namespace) the day the wire format changes.
_KEY_PACK = struct.Struct("<III")  # dmtpu: ignore[wire-literal]


class RingConfigError(ValueError):
    """The ring config document fails validation."""


def _hash64(data: bytes) -> int:
    return int.from_bytes(hashlib.blake2b(data, digest_size=8).digest(),
                          "little")


@dataclass(frozen=True)
class ShardInfo:
    """One shard's endpoints, in ring-config order."""

    host: str
    distributer_port: int
    dataserver_port: int = 0
    gateway_port: int = 0
    # Metrics exporter endpoint (0 = none bound): what the fleet
    # aggregator scrapes; optional so pre-observability ring files keep
    # loading, and ownership never depends on it.
    exporter_port: int = 0

    def to_config(self) -> dict:
        return {"host": self.host,
                "distributer_port": self.distributer_port,
                "dataserver_port": self.dataserver_port,
                "gateway_port": self.gateway_port,
                "exporter_port": self.exporter_port}

    @classmethod
    def from_config(cls, doc: dict) -> "ShardInfo":
        try:
            return cls(host=str(doc["host"]),
                       distributer_port=int(doc["distributer_port"]),
                       dataserver_port=int(doc.get("dataserver_port", 0)),
                       gateway_port=int(doc.get("gateway_port", 0)),
                       exporter_port=int(doc.get("exporter_port", 0)))
        except (KeyError, TypeError, ValueError) as e:
            raise RingConfigError(f"bad shard entry {doc!r}: {e}") from None


class HashRing:
    """Maps tile keys to shard indices; the config is the identity.

    Two rings with the same ``(n_shards, replicas)`` agree on every
    key regardless of endpoints or version — see the module docstring
    for why that is a feature, not an oversight.
    """

    def __init__(self, shards: Sequence[ShardInfo], *, version: int = 1,
                 replicas: int = DEFAULT_REPLICAS) -> None:
        if not shards:
            raise RingConfigError("a ring needs at least one shard")
        if replicas < 1:
            raise RingConfigError(f"replicas {replicas} < 1")
        if version < 1:
            raise RingConfigError(f"ring version {version} < 1")
        self.shards = tuple(shards)
        self.version = version
        self.replicas = replicas
        points: list[tuple[int, int]] = []
        for shard in range(len(self.shards)):
            for replica in range(replicas):
                points.append((_hash64(b"shard:%d:%d"
                                       % (shard, replica)), shard))
        points.sort()
        self._points = [p for p, _ in points]
        self._owners = [s for _, s in points]

    @property
    def n_shards(self) -> int:
        return len(self.shards)

    def owner(self, level: int, index_real: int, index_imag: int) -> int:
        """The shard index owning tile ``(level, index_real, index_imag)``."""
        h = _hash64(_KEY_PACK.pack(level, index_real, index_imag))
        i = bisect.bisect_left(self._points, h)
        if i == len(self._points):
            i = 0
        return self._owners[i]

    def owner_of(self, key: Key) -> int:
        return self.owner(*key)

    def slice(self, shard: int) -> "RingSlice":
        if not 0 <= shard < self.n_shards:
            raise RingConfigError(
                f"shard {shard} outside [0, {self.n_shards})")
        return RingSlice(self, shard)

    # -- config document ---------------------------------------------------

    def to_config(self) -> dict:
        return {"format": RING_FORMAT, "version": self.version,
                "replicas": self.replicas,
                "shards": [s.to_config() for s in self.shards]}

    @classmethod
    def from_config(cls, doc: dict) -> "HashRing":
        if not isinstance(doc, dict):
            raise RingConfigError(f"ring config is {type(doc).__name__}, "
                                  f"not an object")
        fmt = doc.get("format")
        if fmt != RING_FORMAT:
            raise RingConfigError(f"unsupported ring format {fmt!r}")
        shards_doc = doc.get("shards")
        if not isinstance(shards_doc, list) or not shards_doc:
            raise RingConfigError("ring config has no shards")
        try:
            version = int(doc.get("version", 1))
            replicas = int(doc.get("replicas", DEFAULT_REPLICAS))
        except (TypeError, ValueError) as e:
            raise RingConfigError(str(e)) from None
        return cls([ShardInfo.from_config(s) for s in shards_doc],
                   version=version, replicas=replicas)

    def save(self, path: str) -> None:
        data = json.dumps(self.to_config(), indent=1, sort_keys=True) + "\n"
        with open(path, "w", encoding="utf-8") as f:
            f.write(data)

    @classmethod
    def load(cls, path: str) -> "HashRing":
        try:
            with open(path, "r", encoding="utf-8") as f:
                doc = json.load(f)
        except (OSError, json.JSONDecodeError) as e:
            raise RingConfigError(f"cannot load ring config {path}: {e}") \
                from None
        return cls.from_config(doc)

    @classmethod
    def local(cls, n_shards: int, *, version: int = 1,
              replicas: int = DEFAULT_REPLICAS) -> "HashRing":
        """An all-loopback ring with unbound (0) ports — the shape a
        launcher starts from before it rewrites real bound ports in."""
        return cls([ShardInfo("127.0.0.1", 0) for _ in range(n_shards)],
                   version=version, replicas=replicas)


@dataclass(frozen=True)
class RingSlice:
    """One shard's view of a ring: ``owns()`` is its keyspace filter.

    This is what threads through the coordinator stack — the scheduler
    takes ``owns`` as its frontier filter, the distributer answers
    misrouted uploads with the true ``ring.owner_of(key)``, and the
    storage layer namespaces per-shard state with ``namespace``.
    """

    ring: HashRing
    shard: int

    @property
    def n_shards(self) -> int:
        return self.ring.n_shards

    @property
    def version(self) -> int:
        return self.ring.version

    @property
    def namespace(self) -> str:
        """Blob/lock/index name suffix, e.g. ``-s0of4``.  Depends only
        on the slice identity, never the ring version: a version bump
        that keeps N must not orphan the shard's durable state."""
        return f"-s{self.shard}of{self.n_shards}"

    def owns(self, key: Key) -> bool:
        return self.ring.owner_of(key) == self.shard

    def owner_of(self, key: Key) -> int:
        return self.ring.owner_of(key)


def shard_namespace(shard: int, n_shards: int) -> str:
    """The ``RingSlice.namespace`` string without needing a ring."""
    return f"-s{shard}of{n_shards}"


OwnsFn = Callable[[Key], bool]


def parse_shard_spec(spec: str) -> tuple[int, int]:
    """``"K/N"`` -> ``(K, N)`` with bounds checking (CLI input)."""
    try:
        k_str, n_str = spec.split("/", 1)
        k, n = int(k_str), int(n_str)
    except ValueError:
        raise RingConfigError(
            f"shard spec {spec!r} is not K/N") from None
    if n < 1 or not 0 <= k < n:
        raise RingConfigError(
            f"shard spec {spec!r}: need 0 <= K < N")
    return k, n


def load_ring_for_shard(ring_path: Optional[str], shard: int,
                        n_shards: int, *, version: int = 1,
                        replicas: int = DEFAULT_REPLICAS) -> RingSlice:
    """The slice a shard process runs under.

    With a ring file the slice comes from it (and the file's shard
    count must match ``n_shards`` — a mismatched launch would silently
    re-partition the keyspace).  Without one, ownership needs only
    ``K/N``: a launcher may start shards on ephemeral ports before the
    endpoint table exists.
    """
    if ring_path is not None:
        ring = HashRing.load(ring_path)
        if ring.n_shards != n_shards:
            raise RingConfigError(
                f"ring config has {ring.n_shards} shards, launch asked "
                f"for shard {shard}/{n_shards}")
    else:
        ring = HashRing.local(n_shards, version=version, replicas=replicas)
    return ring.slice(shard)
