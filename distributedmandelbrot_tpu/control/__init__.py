"""Sharded control plane: consistent-hash ring + shard launcher.

One coordinator process owning the whole tile keyspace (the reference
architecture, kept through PR 12) caps the control plane at one event
loop's worth of grant throughput and makes that process a single point
of failure.  This package splits the keyspace across N coordinator
shards with a consistent-hash ring (``ring.py``) and launches each
shard as the existing Distributer/scheduler/recovery stack restricted
to its slice (``sharded.py``), all against one shared object store
with per-shard index/checkpoint namespacing.
"""

from distributedmandelbrot_tpu.control.ring import (HashRing,
                                                    RingConfigError,
                                                    RingSlice, ShardInfo)
from distributedmandelbrot_tpu.control.sharded import ShardedCoordinator

__all__ = ["HashRing", "RingConfigError", "RingSlice", "ShardInfo",
           "ShardedCoordinator"]
