"""Subprocess entrypoints for the chaos suite's live farm.

Two roles, selected by the first argument (modeled on
``tests/coordinator_driver.py``, which this generalizes to shards):

``shard``
    ``python -m distributedmandelbrot_tpu.chaos.driver shard DATA_DIR
    PORT_FILE LEVELS SHARD N_SHARDS [flags]`` — runs one
    :class:`~distributedmandelbrot_tpu.control.sharded.ShardedCoordinator`
    over the shared DATA_DIR on ephemeral loopback ports (exporter on),
    writes the bound ports to PORT_FILE as JSON (atomic rename — the
    runner polls for the file), then serves until SIGTERM (graceful:
    drains in-flight persists via ``stop()``) or SIGKILL (the chaos
    kill).  Crashpoints arm through ``DMTPU_CRASHPOINTS`` and slow
    points through ``DMTPU_SLOWPOINTS`` (utils/faults.py), both read at
    import inside this process.

``worker``
    ``python -m distributedmandelbrot_tpu.chaos.driver worker RING_PATH
    [flags]`` — runs one multi-homed pipelined numpy worker against the
    ring table at RING_PATH: one session per shard, leases
    round-robined, uploads routed by key.  Stateless; the runner kills
    it with SIGKILL (dropped sessions) and just respawns it.

``drain``
    ``python -m distributedmandelbrot_tpu.chaos.driver drain RING_PATH
    --duration S --out OUT.json`` — a grant-storm client for
    ``bench.py --shards``: hammers lease REQN exchanges through a
    multi-homed session group for a fixed wall-clock window (never
    uploading; the bench farm runs near-zero lease timeouts so the
    frontier recycles), re-dialing from the ring file whenever a shard
    dies under it, and reports ``{"grants", "seconds"}`` as JSON.
"""

import argparse
import asyncio
import json
import os
import signal
import sys


def _write_json_atomic(path: str, payload: dict) -> None:
    tmp = path + ".tmp"
    with open(tmp, "w", encoding="utf-8") as f:
        f.write(json.dumps(payload))
    os.replace(tmp, path)  # atomic: the runner polls for this file


async def _run_shard(args: argparse.Namespace) -> None:
    from distributedmandelbrot_tpu.control.sharded import ShardedCoordinator
    from distributedmandelbrot_tpu.core.workload import parse_level_settings

    coordinator = ShardedCoordinator(
        parse_level_settings(args.levels), args.shard, args.n_shards,
        ring_version=args.ring_version,
        data_dir_parent=args.data_dir, host="127.0.0.1",
        distributer_port=0, dataserver_port=0, exporter_port=0,
        stats_period=0.0,
        lease_timeout=args.lease_timeout,
        sweep_period=args.sweep_period,
        checkpoint_period=args.checkpoint_period)
    await coordinator.start()
    _write_json_atomic(args.port_file, {
        "distributer": coordinator.distributer_port,
        "dataserver": coordinator.dataserver_port,
        "exporter": coordinator.exporter_port,
        "pid": os.getpid(),
        "shard": coordinator.shard,
        "n_shards": coordinator.n_shards,
    })
    stop = asyncio.Event()
    # SIGTERM is the *graceful* exit (runner teardown): drain in-flight
    # persists so the post-run invariant read sees a settled index.
    # The chaos kills are SIGKILL / crashpoint hard-exits — no drain.
    asyncio.get_running_loop().add_signal_handler(signal.SIGTERM, stop.set)
    try:
        await stop.wait()
    finally:
        await coordinator.stop()


def _run_worker(args: argparse.Namespace) -> None:
    from distributedmandelbrot_tpu.control.ring import HashRing
    from distributedmandelbrot_tpu.worker.backends import NumpyBackend
    from distributedmandelbrot_tpu.worker.client import DistributerClient
    from distributedmandelbrot_tpu.worker.worker import Worker

    ring = HashRing.load(args.ring)
    # The classic client targets shard 0 — it is only the fallback for
    # a declined session hello; ring mode multi-homes the real path.
    first = ring.shards[0]
    client = DistributerClient(first.host, first.distributer_port,
                               timeout=args.timeout)
    worker = Worker(client, NumpyBackend(),
                    batch_size=args.batch_size, window=args.window,
                    ring=ring)
    worker.run_forever(poll_interval=args.poll_interval)


def _run_drain(args: argparse.Namespace) -> None:
    import time

    from distributedmandelbrot_tpu.control.ring import HashRing
    from distributedmandelbrot_tpu.worker.client import ShardedSessionGroup

    deadline = time.monotonic() + args.duration
    grants = 0
    group = None
    t0 = time.monotonic()
    while time.monotonic() < deadline:
        try:
            if group is None:
                group = ShardedSessionGroup(HashRing.load(args.ring),
                                            timeout=args.timeout)
                if not group.connect():
                    group = None
                    time.sleep(0.05)
                    continue
            got = group.request_batchn(args.batch)
            grants += len(got)
            if not got:
                time.sleep(0.002)  # every shard momentarily dry
        except Exception:
            # A shard died mid-exchange: drop the whole group and
            # re-dial from the ring file (the bench rewrites it with
            # the respawned shard's fresh ports).
            if group is not None:
                group.close()
                group = None
            time.sleep(0.05)
    if group is not None:
        group.close()
    _write_json_atomic(args.out, {"grants": grants,
                                  "seconds": time.monotonic() - t0})


def main(argv=None) -> None:
    parser = argparse.ArgumentParser(prog="dmtpu-chaos-driver")
    sub = parser.add_subparsers(dest="role", required=True)

    p_shard = sub.add_parser("shard")
    p_shard.add_argument("data_dir")
    p_shard.add_argument("port_file")
    p_shard.add_argument("levels")
    p_shard.add_argument("shard", type=int)
    p_shard.add_argument("n_shards", type=int)
    p_shard.add_argument("--ring-version", type=int, default=1)
    p_shard.add_argument("--lease-timeout", type=float, default=5.0)
    p_shard.add_argument("--sweep-period", type=float, default=0.2)
    p_shard.add_argument("--checkpoint-period", type=float, default=0.5)

    p_worker = sub.add_parser("worker")
    p_worker.add_argument("ring")
    p_worker.add_argument("--batch-size", type=int, default=2)
    p_worker.add_argument("--window", type=int, default=4)
    p_worker.add_argument("--poll-interval", type=float, default=0.2)
    p_worker.add_argument("--timeout", type=float, default=10.0)

    p_drain = sub.add_parser("drain")
    p_drain.add_argument("ring")
    p_drain.add_argument("--duration", type=float, default=4.0)
    p_drain.add_argument("--batch", type=int, default=32)
    p_drain.add_argument("--timeout", type=float, default=5.0)
    p_drain.add_argument("--out", required=True)

    args = parser.parse_args(argv)
    if args.role == "shard":
        asyncio.run(_run_shard(args))
    elif args.role == "worker":
        _run_worker(args)
    else:
        _run_drain(args)


if __name__ == "__main__":
    sys.exit(main())
