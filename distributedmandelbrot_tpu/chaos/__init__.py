"""Continuous chaos suite for the sharded control plane.

A scenario runner (:mod:`.runner`) drives a live sharded farm — N
coordinator shard subprocesses over one shared data directory, M
multi-homed numpy worker subprocesses — while killing processes on
spot-preemption-style schedules (SIGKILL and ``utils/faults.py``
hard-exit crashpoints), injecting slow persists (``DMTPU_SLOWPOINTS``)
and dropped sessions, then asserts the invariants the control plane
sells: every tile completed exactly once on disk, payloads
numpy-golden, every index entry owned by the shard that wrote it, and
a bounded restart-to-first-grant blip.

Exposed as ``dmtpu chaos`` (cli.py) and reused by the CI smoke.
"""

from distributedmandelbrot_tpu.chaos.runner import (SCENARIOS, ChaosReport,
                                                    ChaosRunner, KillEvent,
                                                    Scenario)

__all__ = ["ChaosReport", "ChaosRunner", "KillEvent", "Scenario",
           "SCENARIOS"]
