"""Chaos scenario runner: kill a live sharded farm and audit the wreckage.

A :class:`Scenario` names a farm shape (N coordinator shards, M numpy
workers, one shared data dir), a kill schedule (SIGKILL
:class:`KillEvent`\\ s plus spawn-time ``DMTPU_CRASHPOINTS`` hard-exit
points), and fault injections (``DMTPU_SLOWPOINTS`` slow persists;
worker deaths double as dropped sessions).  :class:`ChaosRunner` drives
it live — subprocesses via :mod:`.driver`, endpoint table rewritten in
``ring.json`` as shards come back on fresh ephemeral ports — and then
asserts the invariants the control plane sells:

- **exactly once**: the union of the per-shard namespaced indices is
  exactly the level grid — no tile missing, none duplicated within a
  shard log or across two shards (a cross-shard duplicate or an
  in-shard double entry would mean a grant was issued twice across a
  restart);
- **ownership**: every entry in shard ``k``'s index hashes to ``k`` on
  the ring — misrouted uploads never reached the wrong index;
- **parity**: sampled tiles on disk are byte-identical to the numpy
  golden for their ``(level, max_iter)``;
- **bounded blip**: each coordinator restart reaches its first lease
  grant within ``grant_blip_bound`` seconds, measured by polling the
  respawned shard's ``/varz``.

The catalogue in :data:`SCENARIOS` is the ``dmtpu chaos`` surface; the
CI smoke runs the ``coord-kill`` entry with one worker.
"""

from __future__ import annotations

import collections
import dataclasses
import json
import os
import subprocess
import sys
import tempfile
import time
import urllib.request
from dataclasses import dataclass, field
from typing import Callable, Optional

from distributedmandelbrot_tpu.control.ring import (HashRing, ShardInfo,
                                                    shard_namespace)
from distributedmandelbrot_tpu.core.workload import (Workload,
                                                     parse_level_settings)
from distributedmandelbrot_tpu.obs import names as obs_names
from distributedmandelbrot_tpu.utils.metrics import Counters

_DRIVER_MODULE = "distributedmandelbrot_tpu.chaos.driver"
_REPO_ROOT = os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))))

# The persist-path crash/slow site every disk-shaped scenario targets:
# blob durable, index entry not yet appended (utils/faults.py) — the
# interleaving that forces a regrant after restart.
PERSIST_POINT = "store.after_chunk_write"

_PORT_FILE_TIMEOUT = 30.0
_GRACEFUL_STOP_TIMEOUT = 30.0
_WORKER_RESPAWN_DELAY = 0.4
_VARZ_POLL_PERIOD = 0.2


@dataclass(frozen=True)
class KillEvent:
    """One scheduled SIGKILL: ``target`` (``"coord:K"`` | ``"worker:I"``)
    dies ``at`` seconds into the run, respawns ``restart_after`` later."""

    at: float
    target: str
    restart_after: float = 0.3


@dataclass(frozen=True)
class Scenario:
    name: str
    description: str = ""
    # Real numpy compute on full 4096^2 tiles runs ~0.8s per max_iter
    # unit per tile — 3:4 keeps a 9-tile farm inside a CI minute while
    # still exercising genuine compute + full-size uploads.
    levels: str = "3:4"
    n_shards: int = 2
    n_workers: int = 2
    kills: tuple = ()
    # Spawn-time hard-exit crashpoints per target, DMTPU_CRASHPOINTS
    # syntax — e.g. {"coord:1": "store.after_chunk_write:2"}.  Applied
    # only to the first life of the target: a respawn must be able to
    # finish the slice, not re-die on the same hit count forever.
    crashpoints: dict = field(default_factory=dict)
    slow_persist: float = 0.0  # seconds injected per persist-path hit
    deadline: float = 240.0
    # Worker respawn churn while a shard is down can stack with a full
    # reconnect cycle before the first post-restart grant lands; the
    # bound asserts "a blip, not an outage", not a latency SLO.
    grant_blip_bound: float = 120.0
    parity_samples: int = 2
    batch_size: int = 2
    window: int = 2
    # Must comfortably cover grant-to-upload latency: a granted tile can
    # queue behind a full pipeline window of ~3s-per-tile numpy compute
    # before its upload lands, and an expired lease means regrant thrash.
    lease_timeout: float = 60.0
    checkpoint_period: float = 0.5


SCENARIOS: dict[str, Scenario] = {s.name: s for s in (
    Scenario(
        name="coord-kill",
        description="SIGKILL one coordinator shard mid-farm; its slice "
                    "must finish after the restart with no duplicates.",
        kills=(KillEvent(2.0, "coord:0"),)),
    Scenario(
        name="coord-crashpoint",
        description="Shard 1 hard-exits between blob write and index "
                    "append (the regrant-forcing interleaving); restart "
                    "must re-complete the torn tile exactly once.",
        crashpoints={"coord:1": PERSIST_POINT + ":2"}),
    Scenario(
        name="worker-churn",
        description="SIGKILL every worker once on a stagger (dropped "
                    "sessions); leases must expire and re-grant cleanly.",
        kills=(KillEvent(1.5, "worker:0"), KillEvent(3.0, "worker:1")),
        lease_timeout=15.0),
    Scenario(
        name="slow-persist",
        description="Every persist sleeps on the blob/index seam while a "
                    "coordinator dies mid-run — widens the torn-write "
                    "window a SIGKILL can land in.",
        slow_persist=0.05,
        kills=(KillEvent(2.5, "coord:0"),)),
    Scenario(
        name="storm",
        description="Both shards and a worker die on a spot-preemption "
                    "schedule under slowed persists.",
        kills=(KillEvent(2.0, "coord:0"), KillEvent(3.5, "worker:0"),
               KillEvent(6.0, "coord:1")),
        slow_persist=0.02,
        deadline=360.0),
)}


@dataclass
class ChaosReport:
    scenario: str
    ok: bool
    duration_s: float
    expected_tiles: int
    tiles_on_disk: int
    duplicate_entries: int
    misowned_entries: int
    parity_checked: int
    parity_failures: int
    kills: int
    restarts: int
    # One sample per measured coordinator restart: seconds from respawn
    # to that shard's first lease grant (its /varz workloads_granted).
    restart_to_first_grant_s: list = field(default_factory=list)
    failures: list = field(default_factory=list)
    # Fleet snapshot (obs/fleet.py) scraped from the live shards just
    # before graceful teardown — the per-shard/per-worker rates a chaos
    # postmortem wants next to the invariant verdicts.
    fleet: dict = field(default_factory=dict)
    # Flight-recorder postmortem summary (obs/postmortem.py), assembled
    # from the fleet's crash dumps when the scenario FAILED — which
    # process died holding which leases, and what the anomaly detectors
    # flagged.  Empty on success (the dumps stay on disk either way).
    postmortem: dict = field(default_factory=dict)

    def to_json(self) -> str:
        return json.dumps(dataclasses.asdict(self), indent=1,
                          sort_keys=True)


class _Slot:
    """Bookkeeping for one managed subprocess (a shard or a worker)."""

    def __init__(self, role: str, index: int) -> None:
        self.role = role
        self.index = index
        self.proc: Optional[subprocess.Popen] = None
        self.info: Optional[dict] = None  # shard port-file payload
        self.respawn_at: Optional[float] = None  # monotonic
        self.waiting_port = False
        self.spawned_at = 0.0
        self.measure_from: Optional[float] = None  # blip measurement
        self.last_varz_poll = 0.0
        self.lives = 0

    @property
    def alive(self) -> bool:
        return self.proc is not None and self.proc.poll() is None


class ChaosRunner:
    """Run one :class:`Scenario` against a throwaway data dir.

    ``workdir=None`` uses a temp dir removed afterwards; pass a path to
    keep the farm state (per-process logs land next to the data dir
    either way, as ``coord-K.log`` / ``worker-I.log``).
    """

    def __init__(self, scenario: Scenario, *,
                 workdir: Optional[str] = None,
                 counters: Optional[Counters] = None,
                 log: Optional[Callable[[str], None]] = None) -> None:
        self.scenario = scenario
        self.workdir = workdir
        self.counters = counters if counters is not None else Counters()
        self._log = log if log is not None else (lambda msg: None)
        self.settings = parse_level_settings(scenario.levels)
        self.expected = {(s.level, i, j) for s in self.settings
                         for i in range(s.level) for j in range(s.level)}
        # Ownership is a pure function of N — no endpoints needed.
        self.ring = HashRing.local(scenario.n_shards)
        self.owned_expected = [
            {k for k in self.expected if self.ring.owner_of(k) == shard}
            for shard in range(scenario.n_shards)]
        for ev in scenario.kills:
            self._parse_target(ev.target)  # validate early
        for target in scenario.crashpoints:
            role, _ = self._parse_target(target)
            if role != "coord":
                raise ValueError(
                    f"crashpoints target coordinators, got {target!r}")
        self.coords = [_Slot("coord", k) for k in range(scenario.n_shards)]
        self.workers = [_Slot("worker", i)
                        for i in range(scenario.n_workers)]
        self.kill_count = 0
        self.restart_count = 0
        self.blips: list[float] = []
        self.failures: list[str] = []
        self._stores: dict[int, object] = {}
        self._last_scan: set = set()

    # -- target / process plumbing ----------------------------------------

    def _parse_target(self, target: str) -> tuple[str, int]:
        role, _, idx_s = target.partition(":")
        try:
            idx = int(idx_s)
        except ValueError:
            raise ValueError(f"bad kill target {target!r}") from None
        if role == "coord":
            bound = self.scenario.n_shards
        elif role == "worker":
            bound = self.scenario.n_workers
        else:
            raise ValueError(f"bad kill target {target!r}")
        if not 0 <= idx < bound:
            raise ValueError(f"kill target {target!r} outside farm "
                             f"({bound} {role}s)")
        return role, idx

    def _slot(self, target: str) -> _Slot:
        role, idx = self._parse_target(target)
        return (self.coords if role == "coord" else self.workers)[idx]

    def _base_env(self) -> dict:
        env = dict(os.environ)
        env["PYTHONPATH"] = _REPO_ROOT + os.pathsep \
            + env.get("PYTHONPATH", "")
        env.setdefault("JAX_PLATFORMS", "cpu")
        # Every child keeps a black box; a fast autoflush cadence is
        # what makes SIGKILL evidence land (no exit hook ever runs).
        env.setdefault("DMTPU_FLIGHT_DIR", self.flight_dir)
        env.setdefault("DMTPU_FLIGHT_PERIOD", "0.2")
        if self.scenario.slow_persist > 0:
            env["DMTPU_SLOWPOINTS"] = \
                f"{PERSIST_POINT}:{self.scenario.slow_persist}"
        return env

    def _open_log(self, slot: _Slot):
        path = os.path.join(self.root, f"{slot.role}-{slot.index}.log")
        return open(path, "ab")

    def _port_file(self, shard: int) -> str:
        return os.path.join(self.root, f"ports-{shard}.json")

    def _spawn_coord(self, slot: _Slot) -> None:
        sc = self.scenario
        env = self._base_env()
        crash = sc.crashpoints.get(f"coord:{slot.index}")
        if crash and slot.lives == 0:
            env["DMTPU_CRASHPOINTS"] = crash
        port_file = self._port_file(slot.index)
        if os.path.exists(port_file):
            os.unlink(port_file)  # stale ports from the previous life
        cmd = [sys.executable, "-m", _DRIVER_MODULE, "shard",
               self.parent_dir, port_file, sc.levels,
               str(slot.index), str(sc.n_shards),
               "--lease-timeout", str(sc.lease_timeout),
               "--checkpoint-period", str(sc.checkpoint_period)]
        with self._open_log(slot) as logf:
            slot.proc = subprocess.Popen(cmd, env=env, stdout=logf,
                                         stderr=logf)
        slot.lives += 1
        slot.spawned_at = time.monotonic()
        slot.waiting_port = True
        slot.respawn_at = None
        slot.info = None

    def _spawn_worker(self, slot: _Slot) -> None:
        sc = self.scenario
        cmd = [sys.executable, "-m", _DRIVER_MODULE, "worker",
               self.ring_path,
               "--batch-size", str(sc.batch_size),
               "--window", str(sc.window)]
        with self._open_log(slot) as logf:
            slot.proc = subprocess.Popen(cmd, env=self._base_env(),
                                         stdout=logf, stderr=logf)
        slot.lives += 1
        slot.spawned_at = time.monotonic()
        slot.respawn_at = None

    def _write_ring(self) -> None:
        infos = []
        for slot in self.coords:
            info = slot.info or {}
            infos.append(ShardInfo("127.0.0.1",
                                   distributer_port=info.get(
                                       "distributer", 0),
                                   dataserver_port=info.get(
                                       "dataserver", 0),
                                   exporter_port=info.get(
                                       "exporter", 0)))
        HashRing(infos, version=1).save(self.ring_path)

    # -- observation -------------------------------------------------------

    def _store(self, shard: int):
        store = self._stores.get(shard)
        if store is None:
            from distributedmandelbrot_tpu.storage.store import ChunkStore
            store = ChunkStore(
                self.parent_dir,
                namespace=shard_namespace(shard, self.scenario.n_shards))
            self._stores[shard] = store
        return store

    def _scan_keys(self) -> set:
        """Union of completed keys across every shard's namespaced index.

        Tolerant of mid-append reads (live coordinators): a scan that
        fails keeps the previous observation — the final invariant read
        happens only after a graceful drain.
        """
        keys: set = set()
        try:
            for shard in range(self.scenario.n_shards):
                for entry in self._store(shard).entries():
                    keys.add(entry.key)
        except Exception:
            return self._last_scan
        self._last_scan = keys
        return keys

    def _varz(self, slot: _Slot) -> Optional[dict]:
        info = slot.info or {}
        port = info.get("exporter")
        if not port:
            return None
        try:
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/varz", timeout=0.5) as resp:
                return json.loads(resp.read().decode("utf-8"))
        except Exception:
            return None

    def _capture_postmortem(self) -> dict:
        """Assemble the fleet's flight dumps into a postmortem summary
        for a FAILED scenario report.  Best-effort like the fleet
        snapshot: a postmortem that cannot assemble must not mask the
        invariant verdict it was meant to explain."""
        from distributedmandelbrot_tpu.obs import postmortem
        try:
            pm = postmortem.assemble(self.flight_dir,
                                     registry=self.counters.registry)
            summary = pm.summary()
            summary["dump_dir"] = self.flight_dir
            return summary
        except Exception as e:
            self._log(f"postmortem assembly failed: {e!r}")
            return {}

    def _capture_fleet(self) -> dict:
        """A fleet snapshot (obs/fleet.py) over the still-live shards.

        Best-effort by design: the scenario verdict rests on the
        invariant audit, and a dead exporter at teardown time is a
        normal chaos outcome, not a reason to fail the report.  Two
        scrape rounds a beat apart give the aggregator the pair of
        samples it needs for rates.
        """
        from distributedmandelbrot_tpu.obs.fleet import FleetAggregator
        peers = []
        for slot in self.coords:
            port = (slot.info or {}).get("exporter")
            if slot.alive and port:
                peers.append(f"shard@127.0.0.1:{port}")
        if not peers:
            return {}
        try:
            agg = FleetAggregator(peers, timeout=1.0)
            agg.scrape_once()
            time.sleep(0.25)
            agg.scrape_once()
            return agg.snapshot()
        except Exception as e:
            self._log(f"fleet snapshot failed: {e!r}")
            return {}

    @staticmethod
    def _granted(varz: dict) -> int:
        name = obs_names.COORD_WORKLOADS_GRANTED
        total = 0
        for label, value in varz.get("counters", {}).items():
            if label == name or label.startswith(name + "{"):
                total += int(value)
        return total

    # -- the live loop -----------------------------------------------------

    def _fire_kill(self, ev: KillEvent) -> None:
        slot = self._slot(ev.target)
        if not slot.alive:
            self._log(f"kill {ev.target}: already dead, skipped")
            return
        slot.proc.kill()  # SIGKILL: no drain, flocks released by kernel
        slot.proc.wait()
        slot.respawn_at = time.monotonic() + ev.restart_after
        self.kill_count += 1
        self.counters.inc(obs_names.CHAOS_KILLS)
        self._log(f"killed {ev.target} (SIGKILL) at t="
                  f"{time.monotonic() - self.t0:.1f}s")

    def _monitor_coord(self, slot: _Slot) -> None:
        now = time.monotonic()
        if slot.proc is not None and not slot.alive \
                and slot.respawn_at is None:
            # Died without a scheduled SIGKILL: a crashpoint hard-exit
            # (code 86) is scenario-inflicted; anything else is a bug in
            # the thing under test, surfaced as an invariant failure —
            # but restart either way so the farm can still drain.
            code = slot.proc.returncode
            if code == 86:
                self.kill_count += 1
                self.counters.inc(obs_names.CHAOS_KILLS)
                self._log(f"coord:{slot.index} crashpoint hard-exit")
            else:
                self.failures.append(
                    f"coord:{slot.index} died unexpectedly "
                    f"(exit {code}); see coord-{slot.index}.log")
                self._log(f"coord:{slot.index} died (exit {code})")
            slot.respawn_at = now + 0.3
        if slot.respawn_at is not None and now >= slot.respawn_at:
            self._spawn_coord(slot)
            self._log(f"respawned coord:{slot.index}")
        if slot.waiting_port and slot.alive:
            port_file = self._port_file(slot.index)
            if os.path.exists(port_file):
                with open(port_file, "r", encoding="utf-8") as f:
                    slot.info = json.load(f)
                slot.waiting_port = False
                self._write_ring()  # fresh ephemeral ports for workers
                if slot.lives > 1:
                    self.restart_count += 1
                    self.counters.inc(obs_names.CHAOS_RESTARTS)
                    slot.measure_from = slot.spawned_at
        if slot.measure_from is not None and slot.alive \
                and now - slot.last_varz_poll >= _VARZ_POLL_PERIOD:
            slot.last_varz_poll = now
            varz = self._varz(slot)
            if varz is not None and self._granted(varz) > 0:
                blip = now - slot.measure_from
                self.blips.append(round(blip, 3))
                slot.measure_from = None
                self._log(f"coord:{slot.index} first grant "
                          f"{blip:.2f}s after respawn")
            elif self.owned_expected[slot.index] <= self._last_scan:
                # Slice already complete on disk: nothing left to grant,
                # so there is no blip to measure for this restart.
                slot.measure_from = None

    def _monitor_worker(self, slot: _Slot) -> None:
        now = time.monotonic()
        if slot.proc is not None and not slot.alive \
                and slot.respawn_at is None:
            # Unscheduled worker death = a dropped session (the lease
            # sweeper's problem, not ours) — respawn with a small delay
            # so a down shard can come back before the retry storm.
            slot.respawn_at = now + _WORKER_RESPAWN_DELAY
            self._log(f"worker:{slot.index} died "
                      f"(exit {slot.proc.returncode}); respawning")
        if slot.respawn_at is not None and now >= slot.respawn_at:
            self._spawn_worker(slot)
            self.restart_count += 1
            self.counters.inc(obs_names.CHAOS_RESTARTS)

    # -- lifecycle ---------------------------------------------------------

    def run(self) -> ChaosReport:
        tmp = None
        if self.workdir is None:
            tmp = tempfile.TemporaryDirectory(prefix="dmtpu-chaos-")
            root = tmp.name
        else:
            root = self.workdir
            os.makedirs(root, exist_ok=True)
        try:
            return self._run(root)
        finally:
            self._kill_everything()
            if tmp is not None:
                tmp.cleanup()

    def _run(self, root: str) -> ChaosReport:
        sc = self.scenario
        self.root = root
        self.parent_dir = os.path.join(root, "farm")
        os.makedirs(self.parent_dir, exist_ok=True)
        self.flight_dir = os.path.join(root, "flight")
        os.makedirs(self.flight_dir, exist_ok=True)
        self.ring_path = os.path.join(root, "ring.json")
        self.t0 = time.monotonic()
        self._log(f"scenario {sc.name}: {sc.n_shards} shards, "
                  f"{sc.n_workers} workers, levels {sc.levels}, "
                  f"{len(self.expected)} tiles")

        for slot in self.coords:
            self._spawn_coord(slot)
        port_deadline = time.monotonic() + _PORT_FILE_TIMEOUT
        for slot in self.coords:
            port_file = self._port_file(slot.index)
            while not os.path.exists(port_file):
                if time.monotonic() > port_deadline:
                    raise RuntimeError(
                        f"coord:{slot.index} never wrote its port file")
                if not slot.alive:
                    raise RuntimeError(
                        f"coord:{slot.index} died during startup "
                        f"(exit {slot.proc.returncode}); see "
                        f"coord-{slot.index}.log")
                time.sleep(0.05)
            with open(port_file, "r", encoding="utf-8") as f:
                slot.info = json.load(f)
            slot.waiting_port = False
        self._write_ring()
        for slot in self.workers:
            self._spawn_worker(slot)

        pending = sorted(sc.kills, key=lambda ev: ev.at)
        deadline = self.t0 + sc.deadline
        completed = False
        while time.monotonic() < deadline:
            now_rel = time.monotonic() - self.t0
            while pending and pending[0].at <= now_rel:
                self._fire_kill(pending.pop(0))
            for slot in self.coords:
                self._monitor_coord(slot)
            for slot in self.workers:
                self._monitor_worker(slot)
            if self.expected <= self._scan_keys():
                completed = True
                break
            time.sleep(0.1)
        if not completed:
            self.failures.append(
                f"deadline: {len(self._last_scan & self.expected)}/"
                f"{len(self.expected)} tiles after {sc.deadline:.0f}s")

        fleet_snapshot = self._capture_fleet()
        self._stop_workers()
        self._stop_coords()
        self._check_invariants()
        self.counters.inc(obs_names.CHAOS_INVARIANT_FAILURES,
                          len(self.failures))
        report = ChaosReport(
            scenario=sc.name,
            ok=not self.failures,
            duration_s=round(time.monotonic() - self.t0, 2),
            expected_tiles=len(self.expected),
            tiles_on_disk=self._tiles_on_disk,
            duplicate_entries=self._duplicates,
            misowned_entries=self._misowned,
            parity_checked=self._parity_checked,
            parity_failures=self._parity_failures,
            kills=self.kill_count,
            restarts=self.restart_count,
            restart_to_first_grant_s=self.blips,
            failures=list(self.failures),
            fleet=fleet_snapshot,
            postmortem=self._capture_postmortem()
            if self.failures else {})
        self._log(f"scenario {sc.name}: "
                  f"{'OK' if report.ok else 'FAILED'} in "
                  f"{report.duration_s:.1f}s ({report.kills} kills, "
                  f"{report.restarts} restarts)")
        return report

    def _stop_workers(self) -> None:
        for slot in self.workers:
            if slot.alive:
                slot.proc.kill()  # stateless: nothing to drain
            if slot.proc is not None:
                slot.proc.wait()
            slot.respawn_at = None

    def _stop_coords(self) -> None:
        # SIGTERM is the driver's graceful path: stop() drains in-flight
        # persists, so the invariant read below sees a settled index.
        for slot in self.coords:
            if slot.alive:
                slot.proc.terminate()
        deadline = time.monotonic() + _GRACEFUL_STOP_TIMEOUT
        for slot in self.coords:
            if slot.proc is None:
                continue
            try:
                slot.proc.wait(timeout=max(0.1,
                                           deadline - time.monotonic()))
            except subprocess.TimeoutExpired:
                self.failures.append(
                    f"coord:{slot.index} ignored SIGTERM for "
                    f"{_GRACEFUL_STOP_TIMEOUT:.0f}s (drain hang)")
                slot.proc.kill()
                slot.proc.wait()
            slot.respawn_at = None

    def _kill_everything(self) -> None:
        for slot in self.coords + self.workers:
            if slot.alive:
                slot.proc.kill()
                slot.proc.wait()

    # -- invariants --------------------------------------------------------

    def _check_invariants(self) -> None:
        sc = self.scenario
        per_shard: dict[int, list] = {}
        for shard in range(sc.n_shards):
            try:
                per_shard[shard] = [e.key for e in
                                    self._store(shard).entries()]
            except Exception as e:
                self.failures.append(
                    f"shard {shard}: index unreadable after drain: {e}")
                per_shard[shard] = []

        self._duplicates = 0
        self._misowned = 0
        owners_by_key: dict = collections.defaultdict(set)
        union: set = set()
        for shard, keys in per_shard.items():
            counts = collections.Counter(keys)
            in_shard_dups = sum(n - 1 for n in counts.values())
            if in_shard_dups:
                self._duplicates += in_shard_dups
                self.failures.append(
                    f"shard {shard}: {in_shard_dups} duplicate index "
                    f"entries (a grant was issued twice)")
            misowned = sorted(k for k in counts
                              if self.ring.owner_of(k) != shard)
            if misowned:
                self._misowned += len(misowned)
                self.failures.append(
                    f"shard {shard}: {len(misowned)} entries it does "
                    f"not own (first: {misowned[0]})")
            for k in counts:
                owners_by_key[k].add(shard)
                union.add(k)
        cross = sorted(k for k, owners in owners_by_key.items()
                       if len(owners) > 1)
        if cross:
            self._duplicates += len(cross)
            self.failures.append(
                f"{len(cross)} tiles present in multiple shard indices "
                f"(first: {cross[0]})")
        unexpected = sorted(union - self.expected)
        if unexpected:
            self.failures.append(
                f"{len(unexpected)} tiles outside the level grid "
                f"(first: {unexpected[0]})")
        missing = sorted(self.expected - union)
        if missing:
            self.failures.append(
                f"{len(missing)} tiles never completed "
                f"(first: {missing[0]})")
        self._tiles_on_disk = len(union & self.expected)

        for blip in self.blips:
            if blip > sc.grant_blip_bound:
                self.failures.append(
                    f"restart-to-first-grant {blip:.2f}s exceeds the "
                    f"{sc.grant_blip_bound:.0f}s bound")

        self._parity_checked = 0
        self._parity_failures = 0
        if sc.parity_samples > 0 and union:
            self._check_parity(sorted(union & self.expected)
                               [:sc.parity_samples])

    def _check_parity(self, keys: list) -> None:
        import numpy as np

        from distributedmandelbrot_tpu.worker.backends import NumpyBackend
        max_iter_by_level = {s.level: s.max_iter for s in self.settings}
        backend = NumpyBackend()
        for level, ir, ii in keys:
            shard = self.ring.owner_of((level, ir, ii))
            chunk = self._store(shard).load(level, ir, ii)
            if chunk is None:
                self.failures.append(
                    f"parity: tile ({level},{ir},{ii}) in index but "
                    f"unloadable from shard {shard}")
                self._parity_failures += 1
                continue
            golden = backend.compute_batch(
                [Workload(level, max_iter_by_level[level], ir, ii)])[0]
            self._parity_checked += 1
            if not np.array_equal(np.asarray(chunk.data).ravel(), golden):
                self._parity_failures += 1
                self.failures.append(
                    f"parity: tile ({level},{ir},{ii}) differs from the "
                    f"numpy golden")


def run_scenario(name: str, **overrides) -> ChaosReport:
    """Run one catalogue scenario, with field overrides (CLI surface)."""
    try:
        scenario = SCENARIOS[name]
    except KeyError:
        raise ValueError(
            f"unknown scenario {name!r}; have: "
            f"{', '.join(sorted(SCENARIOS))}") from None
    run_kwargs = {k: overrides.pop(k)
                  for k in ("workdir", "counters", "log")
                  if k in overrides}
    if overrides:
        scenario = dataclasses.replace(scenario, **overrides)
    return ChaosRunner(scenario, **run_kwargs).run()
