"""Exact-length socket framing, sync and asyncio.

The one correct receive pattern in the reference is the viewer's recv-exact
loop (``DistributedMandelbrotViewer.py:19-33``); the coordinator's single
16 MiB ``Receive`` call (``Distributer.cs:415-416``) silently truncates on
TCP short reads.  Here *every* read is exact-length, on both sides.
"""

from __future__ import annotations

import asyncio
import socket
import struct

_U32 = struct.Struct("<I")


class ProtocolError(Exception):
    """Peer violated the wire protocol (bad code, short message, etc.)."""


# -- synchronous (worker/viewer clients) ----------------------------------

def recv_exact(sock: socket.socket, n: int) -> bytes:
    """Receive exactly ``n`` bytes or raise ``ConnectionError`` on EOF."""
    buf = bytearray(n)
    view = memoryview(buf)
    got = 0
    while got < n:
        r = sock.recv_into(view[got:], n - got)
        if r == 0:
            raise ConnectionError(
                f"connection closed after {got} of {n} bytes")
        got += r
    return bytes(buf)


def send_all(sock: socket.socket, data) -> None:
    """Send a complete buffer (bytes or a memoryview — the zero-copy
    upload path hands the ndarray's own buffer straight to the socket)."""
    sock.sendall(data)


def send_parts(sock: socket.socket, parts) -> None:
    """Send several buffers back to back without concatenating them.

    The session upload frame is a small packed header followed by a
    16 MiB pixel body; joining them would re-copy the body and defeat
    the memoryview send path, so each part goes to ``sendall`` as-is.
    """
    for part in parts:
        sock.sendall(part)


def recv_u32(sock: socket.socket) -> int:
    return _U32.unpack(recv_exact(sock, 4))[0]


def send_u32(sock: socket.socket, value: int) -> None:
    sock.sendall(_U32.pack(value))


def recv_byte(sock: socket.socket) -> int:
    return recv_exact(sock, 1)[0]


def send_byte(sock: socket.socket, value: int) -> None:
    sock.sendall(bytes([value]))


# -- asyncio (coordinator servers) ----------------------------------------

async def read_exact(reader: asyncio.StreamReader, n: int) -> bytes:
    """Read exactly ``n`` bytes.

    EOF before the first byte is a clean close (``ConnectionError`` —
    clients hang up between frames all the time); EOF after a partial
    frame is a protocol violation (``ProtocolError``), so servers can
    count truncated frames separately from ordinary disconnects.
    """
    try:
        return await reader.readexactly(n)
    except asyncio.IncompleteReadError as e:
        if e.partial:
            raise ProtocolError(
                f"truncated frame: {len(e.partial)} of {n} bytes") from None
        raise ConnectionError(
            f"connection closed awaiting {n} bytes") from None


async def read_u32(reader: asyncio.StreamReader) -> int:
    return _U32.unpack(await read_exact(reader, 4))[0]


async def read_byte(reader: asyncio.StreamReader) -> int:
    return (await read_exact(reader, 1))[0]


def write_u32(writer: asyncio.StreamWriter, value: int) -> None:
    writer.write(_U32.pack(value))


def write_byte(writer: asyncio.StreamWriter, value: int) -> None:
    writer.write(bytes([value]))
