"""Wire-protocol constants for both coordinator services.

Byte-compatible with the reference's two TCP protocols
(``Distributer.cs:30-45``, ``DataServer.cs:15-20``, defaults
``Program.cs:13-14``), plus a *batched dispatch* extension — the one
server-side addition the TPU build needs so a single worker process can
lease enough tiles to keep a whole device mesh fed.

Distributer protocol (default port 59010).  Connection purpose byte, then:

- ``PURPOSE_REQUEST`` (0x00): server replies ``WORKLOAD_AVAILABLE`` + 16-byte
  workload, or ``WORKLOAD_NOT_AVAILABLE``.
- ``PURPOSE_RESPONSE`` (0x01): client sends 16-byte workload echo; server
  replies ``RESPONSE_ACCEPT`` (then client streams the 16,777,216 raw pixel
  bytes) or ``RESPONSE_REJECT``.
- ``PURPOSE_BATCH_REQUEST`` (0x02, extension): client sends uint32 max
  count; server replies ``WORKLOAD_AVAILABLE`` + uint32 n + n x 16-byte
  workloads, or ``WORKLOAD_NOT_AVAILABLE`` if none.
- ``PURPOSE_BATCH_RESPONSE`` (0x03, extension): client sends uint32 n, then
  n submissions each shaped exactly like a single response (16-byte echo ->
  accept/reject byte -> pixels if accepted).  Per-item dedup semantics are
  identical to singles.
- ``PURPOSE_SPANS`` (0x04, extension): worker pushes a batch of trace
  spans after an upload — ``SPANS_HEADER`` (worker id, sync-sample count,
  span count), then the sync samples and span records; server replies
  ``SPANS_ACCEPT``.  A legacy coordinator treats 0x04 as an unknown
  purpose byte and drops the connection; the worker takes the EOF as
  "spans unsupported", disables the push permanently, and keeps working
  — tracing degrades, tiles don't.
- ``PURPOSE_SESSION`` (0x05, extension): upgrade the connection to a
  persistent multiplexed session.  Hello: client sends ``SESSION_HELLO``
  (a u32 capability bitfield, ``SESSION_FLAG_*``); server replies
  ``SESSION_ACCEPT`` + ``SESSION_HELLO`` echoing the negotiated subset.
  From then on the connection carries ``SESSION_FRAME``-headed frames
  (type u8, seq u16, payload length u32): lease requests/grants, result
  uploads (raw or RLE bodies, per ``WIRE_CODEC_*``), upload acks that
  may piggyback fresh lease grants (steady state: one round trip per
  tile), and fire-and-forget span reports.  Client frames carry a
  strictly incrementing (mod 2^16) seq; server reply frames echo the
  seq of the frame they answer, which is how a pipelined worker
  correlates N in-flight uploads with their accept flags.  When both
  sides offered ``SESSION_FLAG_GRANTN`` the session also carries the
  batched lease exchange: ``FRAME_LEASE_REQN`` asks for up to N tiles
  at a declared fusion width and ``FRAME_LEASE_GRANTN`` answers with
  the grants pre-grouped into dispatch-sized batches, so one round
  trip feeds a whole megakernel fusion window.  A legacy
  coordinator drops the connection on the unknown 0x05 byte; the
  client takes the EOF during the hello as "sessions unsupported" and
  falls back to connection-per-exchange.

DataServer protocol (default port 59011): client sends 3 x uint32 LE
``(level, index_real, index_imag)``; server replies ``QUERY_ACCEPT`` +
uint32 payload length + codec payload, ``QUERY_REJECT`` (invalid indices),
or ``QUERY_NOT_AVAILABLE``.

Gateway rendered-tile query (extension, gateway port only): a query whose
first u32 is ``GATEWAY_RENDER_MAGIC`` is followed by ``RENDER_QUERY_TAIL``
— ``(level, index_real, index_imag, colormap u8, flags u8)`` — and
answered with the standard status byte + length-prefixed body, except the
body is a colormapped palette PNG of the tile (~50-200 KB hot) instead of
the 16 MiB escape-count payload.  ``colormap`` must be a registered
``COLORMAP_*`` id and ``flags`` must be zero (reserved); either violation
drops the connection via the sanctioned validators.  A legacy DataServer
would read the magic as a (rejected) level, so only gateways understand
this framing — same degradation story as ``GATEWAY_BATCH_MAGIC``.

Gateway session query (extension, gateway port only): a query whose first
u32 is ``GATEWAY_SESSION_MAGIC`` is followed by ``SESSION_QUERY_TAIL`` —
``(session id u64, level, index_real, index_imag, colormap u8, flags
u8)`` — and answered with ``SESSION_REPLY`` ``(session id u64, granted
caps u8)`` followed by the standard status byte + rendered-tile body.
Session id 0 opens a new session: ``flags`` carries the client's
requested ``SESSION_CAP_*`` capability bits, and the reply's granted
caps are the intersection with what the gateway enables (capability
negotiation, same shape as the distributer's ``SESSION_FLAG_*`` hello).
A nonzero id names an established session; the gateway tracks its
viewport trajectory for predictive prefetch and charges its per-session
admission budget.  An unknown or expired id is answered softly —
``SESSION_REPLY`` ``(0, 0)`` + ``QUERY_REJECT`` on a live connection —
so the client reopens with id 0 instead of re-dialing.  Flag bits
outside ``SESSION_CAPS_MASK`` drop the connection via the sanctioned
validator.  Legacy queries on the same port are unaffected; a legacy
DataServer would read the magic as a (rejected) level, like the other
gateway framings.
"""

from __future__ import annotations

import struct

# Re-exported so every protocol speaker can take the workload frame size
# from one module; the format itself lives with the Workload dataclass.
from distributedmandelbrot_tpu.core.workload import \
    WORKLOAD_WIRE_SIZE  # noqa: F401  (canonical re-export)
from distributedmandelbrot_tpu.net.framing import ProtocolError

# Distributer: connection purpose
PURPOSE_REQUEST = 0x00
PURPOSE_RESPONSE = 0x01
PURPOSE_BATCH_REQUEST = 0x02  # extension
PURPOSE_BATCH_RESPONSE = 0x03  # extension
PURPOSE_SPANS = 0x04  # extension: worker span report push
PURPOSE_SESSION = 0x05  # extension: persistent multiplexed session

# Distributer: workload availability
WORKLOAD_AVAILABLE = 0x10
WORKLOAD_NOT_AVAILABLE = 0x11

# Distributer: response acceptance
RESPONSE_ACCEPT = 0x20
RESPONSE_REJECT = 0x21

# Distributer: span report acceptance (0x04 extension).  One accept code
# only: a coordinator that speaks 0x04 always ingests; one that doesn't
# closes the connection, which is the worker's degradation signal.
SPANS_ACCEPT = 0x30

# Distributer: session hello acceptance (0x05 extension).  Like spans,
# one code only — a coordinator that doesn't speak sessions closes the
# connection instead, which is the client's fallback signal.
SESSION_ACCEPT = 0x50

# Session capability bitfield (SESSION_HELLO payload).  The server
# replies with the intersection of what both sides offered; a bit the
# server did not echo must never appear on the wire afterwards.
SESSION_FLAG_RLE = 0x1  # uploads may carry WIRE_CODEC_RLE bodies
# Batched lease grants: the session may carry FRAME_LEASE_REQN /
# FRAME_LEASE_GRANTN frames.  A legacy coordinator never echoes this
# bit, so a batched-grant worker negotiates down to the one-list
# FRAME_LEASE_REQ exchange with no wire change it can't parse.
SESSION_FLAG_GRANTN = 0x2
# Sharded control plane: the session may carry FRAME_RING_REQ /
# FRAME_RING_INFO, and a misrouted upload may be answered with
# FRAME_REDIRECT instead of an accept/reject ack.  A legacy (unsharded)
# coordinator never echoes this bit, so a ring-aware worker negotiates
# down to treating that coordinator as the sole owner of the keyspace.
SESSION_FLAG_SHARD = 0x4

# Session frame types (SESSION_FRAME.type).  Deliberately NOT named
# ``PURPOSE_*``: frames live inside an established session, purposes
# select a handler on a fresh connection — the proto-dispatch rule
# discovers purposes by prefix and must not conflate the two layers.
FRAME_LEASE_REQ = 0x01  # client->server: u32 max count
FRAME_LEASE_GRANT = 0x02  # server->client: u32 n + n x 16-byte workloads
FRAME_UPLOAD = 0x03  # client->server: workload echo + UPLOAD_HEADER + body
FRAME_UPLOAD_ACK = 0x04  # server->client: accept byte + piggyback grants
FRAME_SPANS = 0x05  # client->server: span report body; no ack
# Batched lease exchange (SESSION_FLAG_GRANTN only).  The request names
# both how many tiles it wants AND the worker's fusion width, so the
# reply can pre-group grants into dispatch-sized batches.
FRAME_LEASE_REQN = 0x06  # client->server: LEASE_REQN (count, batch_width)
FRAME_LEASE_GRANTN = 0x07  # server->client: LEASE_GRANTN + grant batches
# Sharded control plane (SESSION_FLAG_SHARD only).  A worker asks the
# coordinator which ring slice it owns; the answer carries the ring
# version so a worker holding a stale ring config finds out on its
# first exchange instead of on its first misrouted upload.
FRAME_RING_REQ = 0x08  # client->server: RING_REQ (client's ring version)
FRAME_RING_INFO = 0x09  # server->client: RING_INFO (version, shard, n)
# Misrouted upload answer (replaces FRAME_UPLOAD_ACK for that seq): the
# server does not own the echoed key; the payload names the
# authoritative shard and the server's ring version.  The worker
# re-routes the result there — bounded by MAX_REDIRECT_HOPS.
FRAME_REDIRECT = 0x0A  # server->client: REDIRECT (shard, ring version)

# Wire-value -> symbolic name, for diagnostics.  Protocol errors that
# name the frame (not just its byte) turn a hexdump hunt into a grep.
FRAME_NAMES = {
    FRAME_LEASE_REQ: "FRAME_LEASE_REQ",
    FRAME_LEASE_GRANT: "FRAME_LEASE_GRANT",
    FRAME_UPLOAD: "FRAME_UPLOAD",
    FRAME_UPLOAD_ACK: "FRAME_UPLOAD_ACK",
    FRAME_SPANS: "FRAME_SPANS",
    FRAME_LEASE_REQN: "FRAME_LEASE_REQN",
    FRAME_LEASE_GRANTN: "FRAME_LEASE_GRANTN",
    FRAME_RING_REQ: "FRAME_RING_REQ",
    FRAME_RING_INFO: "FRAME_RING_INFO",
    FRAME_REDIRECT: "FRAME_REDIRECT",
}


def frame_name(frame_type: int) -> str:
    """``FRAME_UPLOAD (0x03)`` for known types, ``0x2a`` for garbage."""
    name = FRAME_NAMES.get(frame_type)
    return f"{name} ({frame_type:#x})" if name else f"{frame_type:#x}"

# Upload result codecs (UPLOAD_HEADER.codec).  RLE reuses the storage
# codec's body format (codecs/rle.py, code 0x01) so wire and disk agree.
WIRE_CODEC_RAW = 0x00
WIRE_CODEC_RLE = 0x01

# DataServer: query status
QUERY_ACCEPT = 0x00
QUERY_REJECT = 0x01
QUERY_NOT_AVAILABLE = 0x02
# Gateway extension: admission control shed the request (token bucket dry
# or serve queue saturated).  Clients should back off and retry; the legacy
# DataServer never emits this, so reference-protocol clients are unaffected.
QUERY_OVERLOADED = 0x03
# Sharded-gateway extension: this endpoint does not own the queried key.
# The status byte is followed by a REDIRECT payload naming the
# authoritative shard and the server's ring version — no length prefix,
# the redirect IS fixed-size.  Legacy servers never emit this; a legacy
# client reading it sees an unknown status byte and drops the
# connection, the same degradation story as QUERY_OVERLOADED.
QUERY_REDIRECT = 0x04

# Gateway batched multi-tile request: a query whose first u32 is this magic
# is a batch header (u32 count + count x 12-byte queries), not a legacy
# query.  The value is an impossible level (a level-4294967295 grid), so
# the two framings can never collide.
GATEWAY_BATCH_MAGIC = 0xFFFFFFFF
# Gateway rendered-tile request: the next impossible level down selects
# the server-side render framing (RENDER_QUERY_TAIL follows the magic).
GATEWAY_RENDER_MAGIC = 0xFFFFFFFE
# Gateway session-scoped render request: the next impossible level down
# selects the session framing (SESSION_QUERY_TAIL follows the magic).
GATEWAY_SESSION_MAGIC = 0xFFFFFFFD

# Viewer-session capability bits (SESSION_QUERY_TAIL.flags on open /
# SESSION_REPLY.caps granted).  Deliberately NOT named SESSION_FLAG_*:
# those are the distributer worker-session hello bits — different wire,
# different peers.
SESSION_CAP_PREFETCH = 0x1  # predictive tile prefetch along the trajectory
SESSION_CAP_REFINE = 0x2  # low-iter first paint + background refinement
SESSION_CAPS_MASK = SESSION_CAP_PREFETCH | SESSION_CAP_REFINE

# Rendered-tile colormap ids (RENDER_QUERY_TAIL.colormap).  The names are
# matplotlib colormap names; the table is the wire registry — an id not
# in it is a protocol violation, not a KeyError deep in the render path.
COLORMAP_JET = 0x00
COLORMAP_VIRIDIS = 0x01
COLORMAP_PLASMA = 0x02
COLORMAPS: dict[int, str] = {
    COLORMAP_JET: "jet",
    COLORMAP_VIRIDIS: "viridis",
    COLORMAP_PLASMA: "plasma",
}

# Canonical precompiled wire structs.  These are THE definitions: server
# and client modules import them instead of re-typing format strings (the
# reference's DataChunk.cs:14-15 drift, mechanically excluded here — the
# wire-literal/wire-parity checkers in analysis/ flag any copy).
#
# DataServer/gateway query: (level, index_real, index_imag), 3 x u32 LE.
QUERY = struct.Struct("<III")
QUERY_WIRE_SIZE = 12
# The query minus its leading u32: what the gateway still has to read
# after sniffing the first u32 for GATEWAY_BATCH_MAGIC.  Must compose
# with QUERY byte-for-byte (checked by the wire-size rule).
QUERY_TAIL = struct.Struct("<II")
QUERY_TAIL_WIRE_SIZE = 8
# Gateway batch header: (GATEWAY_BATCH_MAGIC, count), 2 x u32 LE.
BATCH_HEADER = struct.Struct("<II")
BATCH_HEADER_WIRE_SIZE = 8
# Gateway rendered-tile query minus its leading GATEWAY_RENDER_MAGIC u32:
# (level, index_real, index_imag, colormap u8 COLORMAP_*, flags u8 —
# reserved, must be zero).  Like QUERY_TAIL, this is what the gateway
# still has to read after sniffing the magic.
RENDER_QUERY_TAIL = struct.Struct("<IIIBB")
RENDER_QUERY_TAIL_WIRE_SIZE = 14
# Gateway session query minus its leading GATEWAY_SESSION_MAGIC u32:
# (session id u64 — 0 opens a new session; level, index_real, index_imag;
# colormap u8 COLORMAP_*; flags u8 — SESSION_CAP_* request bits on open,
# ignored on established sessions, bits outside SESSION_CAPS_MASK are a
# protocol violation).
SESSION_QUERY_TAIL = struct.Struct("<QIIIBB")
SESSION_QUERY_TAIL_WIRE_SIZE = 22
# Session reply header, written before the standard status byte:
# (session id u64 — the issued/echoed id, 0 on unknown-session reject;
# granted caps u8 — requested ∩ enabled on open, echoed thereafter).
SESSION_REPLY = struct.Struct("<QB")
SESSION_REPLY_WIRE_SIZE = 9

# Span-report push (PURPOSE_SPANS).  Header: (worker_id u64 — a random
# per-process id, stable across the worker's many short connections;
# n_sync u32; n_spans u32).
SPANS_HEADER = struct.Struct("<QII")
SPANS_HEADER_WIRE_SIZE = 16
# Clock-sync sample: the tile key of a granted workload plus the worker's
# monotonic clock just before the lease request was sent and just after
# the grant arrived.  The coordinator pairs these with its own grant
# timestamp for the same key to estimate the clock offset NTP-style; the
# key triple leads, byte-compatible with QUERY, like every keyed frame.
SPAN_SYNC = struct.Struct("<IIIdd")
SPAN_SYNC_WIRE_SIZE = 28
# Span record: tile key, stage code (u8, SPAN_STAGE_*), device index
# (u8), lease sequence (u16 — distinguishes re-grants of the same tile),
# then [t0, t1) on the worker's monotonic clock (f64 seconds).
SPAN_RECORD = struct.Struct("<IIIBBHdd")
SPAN_RECORD_WIRE_SIZE = 32

# Session hello payload: one u32 capability bitfield (SESSION_FLAG_*),
# sent by the client after PURPOSE_SESSION and echoed (masked) by the
# server after SESSION_ACCEPT.
SESSION_HELLO = struct.Struct("<I")
SESSION_HELLO_WIRE_SIZE = 4
# Session frame header: (frame type u8 FRAME_*, seq u16, payload length
# u32).  Client seqs increment mod 2^16; server frames echo the seq of
# the client frame they answer.
SESSION_FRAME = struct.Struct("<BHI")
SESSION_FRAME_WIRE_SIZE = 7
# Upload frame sub-header, after the 16-byte workload echo: (codec u8
# WIRE_CODEC_*, want_lease u32 — how many fresh grants to piggyback on
# the ack), then the codec body.
UPLOAD_HEADER = struct.Struct("<BI")
UPLOAD_HEADER_WIRE_SIZE = 5
# Batched lease request payload (FRAME_LEASE_REQN): (count u32 — how
# many tiles, in [1, coordinator's MAX_BATCH]; zero is a protocol
# violation, a worker with no room must simply not ask; batch_width u32
# — the worker's fusion width, in [1, count]).  The whole payload IS
# this struct: the frame length must equal LEASE_REQN_WIRE_SIZE.
LEASE_REQN = struct.Struct("<II")
LEASE_REQN_WIRE_SIZE = 8
# Batched grant reply header (FRAME_LEASE_GRANTN): (n_batches u32,
# n_tiles u32), followed by n_batches grant lists each shaped exactly
# like a FRAME_LEASE_GRANT payload (u32 width + width x 16-byte
# workloads).  Widths never exceed the request's batch_width and sum to
# n_tiles; the frame length must equal LEASE_GRANTN_WIRE_SIZE +
# 4 * n_batches + WORKLOAD_WIRE_SIZE * n_tiles.  n_batches == 0 (and so
# n_tiles == 0) is the drained-coordinator reply.
LEASE_GRANTN = struct.Struct("<II")
LEASE_GRANTN_WIRE_SIZE = 8
# Ring query payload (FRAME_RING_REQ): the client's ring config version
# (0 when it has none).  The whole payload IS this struct.
RING_REQ = struct.Struct("<I")
RING_REQ_WIRE_SIZE = 4
# Ring answer payload (FRAME_RING_INFO): (ring version u32, this
# coordinator's shard index u32, shard count u32).  An unsharded
# coordinator never sends this frame (it never echoes
# SESSION_FLAG_SHARD); shard < n_shards always holds.
RING_INFO = struct.Struct("<III")
RING_INFO_WIRE_SIZE = 12
# Redirect payload, shared by the session FRAME_REDIRECT frame and the
# read-path QUERY_REDIRECT status tail: (authoritative shard index u32,
# server's ring version u32).
REDIRECT = struct.Struct("<II")
REDIRECT_WIRE_SIZE = 8

# Client frame seqs wrap at the u16 the header carries.
MAX_SESSION_SEQ = 0xFFFF

# How many redirect hops a client follows for one key before giving up.
# Two coordinators disagreeing about ownership (a ring-version skew
# window) could otherwise bounce a result forever.
MAX_REDIRECT_HOPS = 4

# Wire codes for span stages (names live in obs/names.py; the wire uses
# one byte).  Order matches the worker pipeline.
SPAN_STAGE_PREFETCH = 0
SPAN_STAGE_DISPATCH = 1
SPAN_STAGE_COMPUTE = 2
SPAN_STAGE_D2H = 3
SPAN_STAGE_UPLOAD = 4

# -- input validation ------------------------------------------------------
#
# The one sanctioned decode path for wire integers.  Every count, length,
# or index a peer sends is attacker-controlled until it passes one of
# these (the taint-* rules in analysis/ enforce exactly that): the
# validators either return the value proven in-range or raise
# ``framing.ProtocolError``, which every connection handler maps to
# "drop this connection and bump a *_rejected counter".

# Upper bound for a DataServer/gateway response payload length.  A codec
# payload is at most the raw chunk (16 MiB) plus small codec framing;
# double it for headroom.  Anything above is a corrupt or hostile frame,
# not a big tile.
MAX_PAYLOAD_BYTES = 2 * 16_777_216


def validate_count(n: int, bound: int, what: str = "count") -> int:
    """Bound-check a wire count/length field; raise on hostile values.

    Returns ``n`` unchanged when ``0 <= n <= bound`` so call sites can
    write ``n = proto.validate_count(raw, MAX, "batch count")`` and hand
    downstream code a sanitized value.
    """
    if not 0 <= n <= bound:
        raise ProtocolError(f"{what} {n} outside [0, {bound}]")
    return n


def validate_payload_length(n: int) -> int:
    """Bound-check a response payload length before allocating for it."""
    return validate_count(n, MAX_PAYLOAD_BYTES, "payload length")


def validate_colormap(colormap_id: int) -> int:
    """Check a rendered-tile query's colormap id against the registry.

    Returns the id unchanged when registered; an unknown id is a hostile
    or version-skewed frame and kills the connection like every other
    validator failure (the caller bumps its named counter first).
    """
    if colormap_id not in COLORMAPS:
        raise ProtocolError(f"unknown colormap id {colormap_id:#x}")
    return colormap_id


def validate_session_flags(flags: int) -> int:
    """Check a session query's capability bits against the known mask.

    Returns the bits unchanged when every set bit is a registered
    ``SESSION_CAP_*``; an unknown bit is a hostile or version-skewed
    frame and kills the connection like every other validator failure
    (the caller bumps its named counter first).
    """
    if flags & ~SESSION_CAPS_MASK:
        raise ProtocolError(f"unknown session flag bits {flags:#x}")
    return flags


def validate_session_seq(seq: int, expected: int) -> int:
    """Check a session frame's seq against the stream position.

    Client frames must arrive with strictly incrementing (mod 2^16)
    seqs; a gap means a frame was lost or injected and every later
    ack correlation would be wrong, so the session dies here.
    """
    if seq != expected:
        raise ProtocolError(
            f"session frame seq {seq}, expected {expected}")
    return seq


def validate_shard(shard: int, n_shards: int) -> int:
    """Check a wire shard index against the reader's ring size.

    A redirect or ring answer naming a shard the reader's ring config
    does not know is version skew or corruption; following it would
    dial a socket chosen by the peer, so the exchange dies here.
    """
    if not 0 <= shard < n_shards:
        raise ProtocolError(f"shard index {shard} outside [0, {n_shards})")
    return shard


def query_in_range(level: int, index_real: int, index_imag: int) -> bool:
    """Is ``(level, index_real, index_imag)`` a well-formed tile key?

    A level-``n`` grid has ``n x n`` tiles, so indices live in
    ``[0, level)``; level 0 does not exist, and ``GATEWAY_BATCH_MAGIC``
    / ``GATEWAY_RENDER_MAGIC`` / ``GATEWAY_SESSION_MAGIC`` are reserved
    as framing sentinels, never real levels.  Unlike
    :func:`validate_count` this is a predicate: an out-of-range query
    gets a ``QUERY_REJECT`` reply, not a dropped connection.
    """
    if level < 1 or level in (GATEWAY_BATCH_MAGIC, GATEWAY_RENDER_MAGIC,
                              GATEWAY_SESSION_MAGIC):
        return False
    return 0 <= index_real < level and 0 <= index_imag < level


DEFAULT_DISTRIBUTER_PORT = 59010
DEFAULT_DATASERVER_PORT = 59011
DEFAULT_GATEWAY_PORT = 59012
# HTTP metrics/trace exporter (/metrics, /varz, /healthz) — not part of the
# binary tile protocol, but allocated alongside its ports.
DEFAULT_EXPORTER_PORT = 59013

# Scheduling defaults (reference: Distributer.cs:22,24 — 1 h lease, 5 min sweep)
DEFAULT_LEASE_TIMEOUT = 3600.0
DEFAULT_SWEEP_PERIOD = 300.0

# Gateway on-demand compute: how long a read request may wait for the farm
# to compute a missing tile before it is answered NOT_AVAILABLE.
DEFAULT_ONDEMAND_DEADLINE = 120.0

# Socket read deadline (reference: a 100 ms per-recv timeout on every client
# socket, CLI-toggleable — Distributer.cs:17, DataServer.cs:11,
# Program.cs:259-268).  The asyncio equivalent is a per-read deadline; the
# default is far looser than 100 ms because a read here spans a whole frame
# (up to the 16 MiB payload), not one recv syscall.  None disables.
DEFAULT_READ_TIMEOUT = 60.0
