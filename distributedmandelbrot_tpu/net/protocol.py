"""Wire-protocol constants for both coordinator services.

Byte-compatible with the reference's two TCP protocols
(``Distributer.cs:30-45``, ``DataServer.cs:15-20``, defaults
``Program.cs:13-14``), plus a *batched dispatch* extension — the one
server-side addition the TPU build needs so a single worker process can
lease enough tiles to keep a whole device mesh fed.

Distributer protocol (default port 59010).  Connection purpose byte, then:

- ``PURPOSE_REQUEST`` (0x00): server replies ``WORKLOAD_AVAILABLE`` + 16-byte
  workload, or ``WORKLOAD_NOT_AVAILABLE``.
- ``PURPOSE_RESPONSE`` (0x01): client sends 16-byte workload echo; server
  replies ``RESPONSE_ACCEPT`` (then client streams the 16,777,216 raw pixel
  bytes) or ``RESPONSE_REJECT``.
- ``PURPOSE_BATCH_REQUEST`` (0x02, extension): client sends uint32 max
  count; server replies ``WORKLOAD_AVAILABLE`` + uint32 n + n x 16-byte
  workloads, or ``WORKLOAD_NOT_AVAILABLE`` if none.
- ``PURPOSE_BATCH_RESPONSE`` (0x03, extension): client sends uint32 n, then
  n submissions each shaped exactly like a single response (16-byte echo ->
  accept/reject byte -> pixels if accepted).  Per-item dedup semantics are
  identical to singles.

DataServer protocol (default port 59011): client sends 3 x uint32 LE
``(level, index_real, index_imag)``; server replies ``QUERY_ACCEPT`` +
uint32 payload length + codec payload, ``QUERY_REJECT`` (invalid indices),
or ``QUERY_NOT_AVAILABLE``.
"""

from __future__ import annotations

import struct

# Re-exported so every protocol speaker can take the workload frame size
# from one module; the format itself lives with the Workload dataclass.
from distributedmandelbrot_tpu.core.workload import \
    WORKLOAD_WIRE_SIZE  # noqa: F401  (canonical re-export)

# Distributer: connection purpose
PURPOSE_REQUEST = 0x00
PURPOSE_RESPONSE = 0x01
PURPOSE_BATCH_REQUEST = 0x02  # extension
PURPOSE_BATCH_RESPONSE = 0x03  # extension

# Distributer: workload availability
WORKLOAD_AVAILABLE = 0x10
WORKLOAD_NOT_AVAILABLE = 0x11

# Distributer: response acceptance
RESPONSE_ACCEPT = 0x20
RESPONSE_REJECT = 0x21

# DataServer: query status
QUERY_ACCEPT = 0x00
QUERY_REJECT = 0x01
QUERY_NOT_AVAILABLE = 0x02
# Gateway extension: admission control shed the request (token bucket dry
# or serve queue saturated).  Clients should back off and retry; the legacy
# DataServer never emits this, so reference-protocol clients are unaffected.
QUERY_OVERLOADED = 0x03

# Gateway batched multi-tile request: a query whose first u32 is this magic
# is a batch header (u32 count + count x 12-byte queries), not a legacy
# query.  The value is an impossible level (a level-4294967295 grid), so
# the two framings can never collide.
GATEWAY_BATCH_MAGIC = 0xFFFFFFFF

# Canonical precompiled wire structs.  These are THE definitions: server
# and client modules import them instead of re-typing format strings (the
# reference's DataChunk.cs:14-15 drift, mechanically excluded here — the
# wire-literal/wire-parity checkers in analysis/ flag any copy).
#
# DataServer/gateway query: (level, index_real, index_imag), 3 x u32 LE.
QUERY = struct.Struct("<III")
QUERY_WIRE_SIZE = 12
# The query minus its leading u32: what the gateway still has to read
# after sniffing the first u32 for GATEWAY_BATCH_MAGIC.  Must compose
# with QUERY byte-for-byte (checked by the wire-size rule).
QUERY_TAIL = struct.Struct("<II")
QUERY_TAIL_WIRE_SIZE = 8
# Gateway batch header: (GATEWAY_BATCH_MAGIC, count), 2 x u32 LE.
BATCH_HEADER = struct.Struct("<II")
BATCH_HEADER_WIRE_SIZE = 8

DEFAULT_DISTRIBUTER_PORT = 59010
DEFAULT_DATASERVER_PORT = 59011
DEFAULT_GATEWAY_PORT = 59012
# HTTP metrics/trace exporter (/metrics, /varz, /healthz) — not part of the
# binary tile protocol, but allocated alongside its ports.
DEFAULT_EXPORTER_PORT = 59013

# Scheduling defaults (reference: Distributer.cs:22,24 — 1 h lease, 5 min sweep)
DEFAULT_LEASE_TIMEOUT = 3600.0
DEFAULT_SWEEP_PERIOD = 300.0

# Gateway on-demand compute: how long a read request may wait for the farm
# to compute a missing tile before it is answered NOT_AVAILABLE.
DEFAULT_ONDEMAND_DEADLINE = 120.0

# Socket read deadline (reference: a 100 ms per-recv timeout on every client
# socket, CLI-toggleable — Distributer.cs:17, DataServer.cs:11,
# Program.cs:259-268).  The asyncio equivalent is a per-read deadline; the
# default is far looser than 100 ms because a read here spans a whole frame
# (up to the 16 MiB payload), not one recv syscall.  None disables.
DEFAULT_READ_TIMEOUT = 60.0
