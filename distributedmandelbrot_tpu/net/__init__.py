"""Wire protocols and exact-length framing shared by all processes."""

from distributedmandelbrot_tpu.net import protocol
from distributedmandelbrot_tpu.net.framing import (ProtocolError, read_byte,
                                                   read_exact, read_u32,
                                                   recv_byte, recv_exact,
                                                   recv_u32, send_all,
                                                   send_byte, send_u32,
                                                   write_byte, write_u32)

__all__ = ["protocol", "ProtocolError", "recv_exact", "send_all", "recv_u32",
           "send_u32", "recv_byte", "send_byte", "read_exact", "read_u32",
           "read_byte", "write_u32", "write_byte"]
