"""On-hardware Pallas kernel sweep: block shape x unroll x view x depth.

VERDICT round-2 items: the shipped DEFAULT_BLOCK_H/W=(64,128) and
DEFAULT_UNROLL=32 came from one sweep at 2048^2 depth 1000; this re-runs
the sweep at the production shapes (1024^2 and 4096^2), at deep budgets
(the cycle probe's extra scratch in play), and on worst-case views where
the interior shortcut cannot help — and records everything, so the next
tuning conversation starts from data, not a stale one-off.

Run on a live TPU (aborts cleanly otherwise):

    python tools/kernel_sweep.py [--quick] [--tile 1024] [--out FILE]

Timing methodology = bench.py's device-chained checksum (amortizes the
dev rig's tunnel round trip; see bench.py docstring).  Results append as
JSON lines to tools/sweep_results.jsonl and a best-per-view summary
prints at the end.
"""

from __future__ import annotations

import argparse
import itertools
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

def _views():
    """(name, center, span, depth, burning) rows, derived from bench.py's
    canonical view definitions so the sweep can never tune for windows
    the bench no longer measures."""
    from bench import SEAHORSE, WORST_VIEWS
    views = [("seahorse", (SEAHORSE[0] + 0.01, SEAHORSE[1] + 0.01), 0.02,
              1000, False),
             ("full", (-0.5, 0.0), 4.0, 1000, False)]
    for name, v in WORST_VIEWS.items():
        views.append((name, v["center"], v["span"], v["max_iter"],
                      v["burning"]))
    return views

GRID_FULL = {
    # (32, 128) is the narrowest legal block: the uint8 store granule is
    # 32 sublanes x 128 lanes (_fit_block's floor), so the straggler
    # granule cannot shrink below it — the filament-residual hunt's
    # lever is block_h 32 vs the shipped 64, plus the unroll.
    "block_h": [32, 64, 128, 256],
    "block_w": [128, 256],
    "unroll": [16, 32, 64],
}
GRID_QUICK = {
    "block_h": [32, 64, 128],
    "block_w": [128],
    "unroll": [32, 64],
}


def main() -> int:
    parser = argparse.ArgumentParser()
    parser.add_argument("--quick", action="store_true",
                        help="smaller grid (block_w=128 only, 2 unrolls)")
    parser.add_argument("--tile", type=int, default=1024)
    parser.add_argument("--tiles", type=int, default=8,
                        help="tiles per chained dispatch")
    parser.add_argument("--repeats", type=int, default=2)
    parser.add_argument("--deep", action="store_true",
                        help="add a depth-5000 seahorse config (cycle-probe "
                             "scratch in play)")
    parser.add_argument("--xla", action="store_true",
                        help="also sweep the XLA path's segment size "
                             "(escape_loop's early-exit granularity)")
    parser.add_argument("--views", default=None,
                        help="comma-separated view-name filter "
                             "(e.g. 'filament,ship')")
    parser.add_argument("--out", default=os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "sweep_results.jsonl"))
    args = parser.parse_args()

    from __graft_entry__ import backend_alive
    if not backend_alive():
        print("backend unreachable; sweep needs a live TPU")
        return 1
    import jax
    if jax.default_backend() != "tpu":
        print("default backend is not tpu; aborting")
        return 1

    import numpy as np

    from bench import (_device_fields, _grid_params, _pallas_chain,
                       _time_chain)

    views = _views()
    if args.deep:
        views.append(("seahorse-d5000", (-0.738, 0.1), 0.02, 5000, False))
    if args.views:
        keep = set(args.views.split(","))
        views = [v for v in views if v[0] in keep]

    grid = GRID_QUICK if args.quick else GRID_FULL
    combos = [dict(zip(grid, vals))
              for vals in itertools.product(*grid.values())]
    tile, k = args.tile, args.tiles
    pixels = k * tile * tile
    stamp = time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime())

    best: dict[str, tuple[float, dict]] = {}

    def emit(out_f, rec):
        out_f.write(json.dumps(rec) + "\n")
        out_f.flush()
        print(json.dumps(rec), flush=True)

    with open(args.out, "a") as out_f:
        for (name, center, span, depth, burning) in views:
            params = _grid_params(center, span, tile, k)
            for combo in combos:
                if combo["block_h"] > tile or combo["block_w"] > tile:
                    continue
                for interior in ((False, True) if not burning
                                 else (False,)):
                    kw = dict(combo)
                    kw["interior_check"] = interior
                    if burning:
                        kw["burning"] = True
                    try:
                        # Chained-delta device timing (round-5 verdict
                        # item 4): the 532 pre-round-5 rows in this file
                        # are tunnel-inclusive wall clock, dominated by
                        # the rig's ~70 ms per-call constant — useless
                        # for choosing a block shape.  The objective is
                        # now device_mpix_s; benched kept for context.
                        df = _device_fields(
                            lambda r, kw=kw: _pallas_chain(
                                params, tile, depth, reps=r, **kw),
                            pixels, args.repeats)
                    except Exception as e:
                        print(f"{name} {kw}: FAILED {type(e).__name__}: "
                              f"{e}", flush=True)
                        continue
                    # Rank on device time when the rig resolves it; fall
                    # back to tunnel-inclusive wall clock otherwise so
                    # best-row selection still works on rigs without
                    # device timing (it ranks consistently within one
                    # run of one rig, which is all `best` compares).
                    rate = (df.get("device_mpix_s", 0.0) or 0.0) \
                        or df.get("benched_mpix_s", 0.0) or 0.0
                    rec = {"ts": stamp, "view": name, "depth": depth,
                           "tile": tile, "k": k, **kw,
                           "mpix_s": df["benched_mpix_s"],
                           "device_mpix_s": df.get("device_mpix_s"),
                           "call_overhead_s": df.get("call_overhead_s"),
                           "device_unresolved":
                               df.get("device_unresolved", False)}
                    emit(out_f, rec)
                    key = f"{name}{'' if interior else ':raw'}"
                    if rate > best.get(key, (0.0, {}))[0]:
                        best[key] = (rate, rec)

    if args.xla:
        from bench import _xla_chain
        from distributedmandelbrot_tpu.parallel import tile_mesh
        mesh = tile_mesh()
        print("\n=== XLA segment sweep ===", flush=True)
        xla_best: dict[str, tuple[float, int]] = {}
        with open(args.out, "a") as out_f:
            for (name, center, span, depth, burning) in views:
                if burning:
                    continue  # the sharded XLA chain is Mandelbrot-only
                params = _grid_params(center, span, tile, k)
                mrds = np.full(k, depth, np.int64)
                for segment in (64, 128, 256, 512):
                    try:  # one failing config must not kill the sweep
                        t = _time_chain(
                            _xla_chain(mesh, params, mrds, tile, segment,
                                       np.float32), args.repeats)
                    except Exception as e:
                        print(f"xla {name} segment={segment}: FAILED "
                              f"{type(e).__name__}: {e}", flush=True)
                        continue
                    rate = pixels / t / 1e6
                    emit(out_f, {"ts": stamp, "view": name, "depth": depth,
                                 "tile": tile, "k": k, "path": "xla",
                                 "segment": segment,
                                 "mpix_s": round(rate, 2)})
                    if rate > xla_best.get(name, (0.0, 0))[0]:
                        xla_best[name] = (rate, segment)

    print("\n=== best per view (pallas, device rate; benched fallback) ===")
    for key in sorted(best):
        rate, rec = best[key]
        src = "device" if rec.get("device_mpix_s") else "benched"
        print(f"{key:24s} {rate:8.1f} {src} Mpix/s  "
              f"bh={rec['block_h']} bw={rec['block_w']} "
              f"unroll={rec['unroll']}")
    if args.xla:
        print("\n=== best per view (xla segment) ===")
        for name in sorted(xla_best):
            rate, segment = xla_best[name]
            print(f"{name:24s} {rate:8.1f} Mpix/s  segment={segment}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
