"""Validate the exporter's /metrics text with a minimal Prometheus parser.

The exporter hand-writes text exposition format v0.0.4 (obs/exporter.py)
rather than depending on a client library, so nothing in the test suite
would catch a malformed line a real Prometheus scraper rejects.  This
tool is that check: a from-the-spec line parser plus the format's
structural invariants, run against

1. a synthetic registry exercising every instrument shape (counters,
   callback gauges, NaN gauges, labeled and unlabeled histograms,
   sanitized names), and
2. (default; ``--offline`` skips it) a live embedded coordinator on
   loopback — the same bytes ``dmtpu serve``'s exporter emits, fetched
   over real HTTP.

Tier-1 runnable: JAX_PLATFORMS=cpu, loopback only, no new deps.

Usage: python tools/check_metrics.py [--offline] [--url http://...:P/metrics]
"""

from __future__ import annotations

import argparse
import math
import os
import re
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

# One sample line: name{labels} value  (no timestamps — the exporter
# never emits them; a timestamp here is a bug, not an option).
_NAME = r"[a-zA-Z_:][a-zA-Z0-9_:]*"
_LABEL = rf'{_NAME}="(?:[^"\\]|\\.)*"'
SAMPLE_RE = re.compile(
    rf"^(?P<name>{_NAME})"
    rf"(?:\{{(?P<labels>{_LABEL}(?:,{_LABEL})*)?\}})?"
    rf" (?P<value>[0-9eE+.\-]+|NaN|\+Inf|-Inf)$")
LABEL_RE = re.compile(rf'({_NAME})="((?:[^"\\]|\\.)*)"')


class MetricsFormatError(AssertionError):
    pass


def _value(text: str) -> float:
    if text == "NaN":
        return math.nan
    if text == "+Inf":
        return math.inf
    if text == "-Inf":
        return -math.inf
    return float(text)


def parse_exposition(text: str) -> dict:
    """Parse text exposition v0.0.4 into
    ``{family: {"type": str, "help": str|None, "samples": [...]}}`` where
    each sample is ``(name, labels_dict, value)``.  Raises
    :class:`MetricsFormatError` on any line a spec-following scraper
    would reject."""
    if not text.endswith("\n"):
        raise MetricsFormatError("exposition must end with a newline")
    families: dict[str, dict] = {}
    current: str | None = None
    for lineno, line in enumerate(text.splitlines(), 1):
        if not line.strip():
            continue
        if line.startswith("# HELP "):
            parts = line.split(" ", 3)
            if len(parts) < 4 or not re.fullmatch(_NAME, parts[2]):
                raise MetricsFormatError(f"line {lineno}: bad HELP: {line!r}")
            families.setdefault(parts[2], {"type": None, "samples": []})[
                "help"] = parts[3]
            continue
        if line.startswith("# TYPE "):
            parts = line.split(" ")
            if len(parts) != 4 or parts[3] not in (
                    "counter", "gauge", "histogram", "summary", "untyped"):
                raise MetricsFormatError(f"line {lineno}: bad TYPE: {line!r}")
            fam = families.setdefault(parts[2], {"samples": []})
            if fam.get("type") is not None:
                raise MetricsFormatError(
                    f"line {lineno}: duplicate TYPE for {parts[2]}")
            if fam["samples"]:
                raise MetricsFormatError(
                    f"line {lineno}: TYPE for {parts[2]} after its samples")
            fam["type"] = parts[3]
            current = parts[2]
            continue
        if line.startswith("#"):
            continue  # comment
        m = SAMPLE_RE.match(line)
        if m is None:
            raise MetricsFormatError(f"line {lineno}: bad sample: {line!r}")
        name = m.group("name")
        labels = dict(LABEL_RE.findall(m.group("labels") or ""))
        # Histogram/summary series attach to their base family.
        base = re.sub(r"_(bucket|sum|count)$", "", name)
        fam_name = base if base in families else name
        if fam_name not in families:
            raise MetricsFormatError(
                f"line {lineno}: sample {name!r} has no TYPE line")
        if fam_name != current:
            raise MetricsFormatError(
                f"line {lineno}: sample {name!r} outside its family block")
        families[fam_name]["samples"].append(
            (name, labels, _value(m.group("value"))))
    return families


def check_invariants(families: dict) -> None:
    """Structural invariants beyond line syntax: histogram buckets are
    cumulative with a +Inf bucket equal to _count, counters are finite
    and non-negative, no family is empty."""
    for fam_name, fam in families.items():
        if not fam["samples"]:
            raise MetricsFormatError(f"{fam_name}: TYPE line but no samples")
        if fam["type"] == "counter":
            for name, _, value in fam["samples"]:
                if not (value >= 0 and math.isfinite(value)):
                    raise MetricsFormatError(
                        f"{fam_name}: counter value {value}")
        if fam["type"] != "histogram":
            continue
        # Group the series by their non-le labels (one child per set).
        children: dict[tuple, dict] = {}
        for name, labels, value in fam["samples"]:
            rest = tuple(sorted((k, v) for k, v in labels.items()
                                if k != "le"))
            child = children.setdefault(
                rest, {"buckets": [], "sum": None, "count": None})
            if name == fam_name + "_bucket":
                if "le" not in labels:
                    raise MetricsFormatError(f"{fam_name}: bucket without le")
                child["buckets"].append((_value(labels["le"]), value))
            elif name == fam_name + "_sum":
                child["sum"] = value
            elif name == fam_name + "_count":
                child["count"] = value
            else:
                raise MetricsFormatError(
                    f"{fam_name}: stray histogram series {name!r}")
        for rest, child in children.items():
            if child["sum"] is None or child["count"] is None:
                raise MetricsFormatError(
                    f"{fam_name}{dict(rest)}: missing _sum/_count")
            buckets = child["buckets"]
            if not buckets or buckets[-1][0] != math.inf:
                raise MetricsFormatError(
                    f"{fam_name}{dict(rest)}: no +Inf bucket")
            bounds = [b for b, _ in buckets]
            cums = [c for _, c in buckets]
            if bounds != sorted(bounds):
                raise MetricsFormatError(
                    f"{fam_name}{dict(rest)}: bucket bounds out of order")
            if any(b > a for a, b in zip(cums[1:], cums)):
                raise MetricsFormatError(
                    f"{fam_name}{dict(rest)}: buckets not cumulative")
            if cums[-1] != child["count"]:
                raise MetricsFormatError(
                    f"{fam_name}{dict(rest)}: +Inf bucket {cums[-1]} != "
                    f"_count {child['count']}")


def _sample_registry():
    """Every instrument shape the exporter can render."""
    from distributedmandelbrot_tpu.obs.metrics import Registry
    reg = Registry()
    reg.counter("requests_total", help="plain counter").inc(3)
    reg.counter("by_outcome", labels={"outcome": "tier1_hit"}).inc(2)
    reg.counter("by_outcome", labels={"outcome": "computed"}).inc()
    reg.gauge("depth", help="plain gauge").set(7.5)
    reg.gauge("ratio", fn=lambda: 0.25)
    reg.gauge("broken", fn=lambda: 1 / 0)  # renders NaN, must still parse
    for v in (0.0001, 0.004, 0.25, 2.0, 1e9):  # incl. overflow bucket
        reg.observe("latency_seconds", v)
        reg.observe("latency_seconds", v, labels={"outcome": "store_hit"})
    reg.counter("weird.name-x", help="sanitized on render").inc()
    return reg


def check_rendered() -> int:
    from distributedmandelbrot_tpu.obs.exporter import render_prometheus
    text = render_prometheus(_sample_registry())
    families = parse_exposition(text)
    check_invariants(families)
    # The sample registry's own facts survived the round trip.
    assert families["requests_total"]["samples"][0][2] == 3
    assert families["weird_name_x"]["samples"][0][2] == 1
    lat = families["latency_seconds"]
    assert lat["type"] == "histogram"
    counts = [v for n, labels, v in lat["samples"]
              if n == "latency_seconds_count"]
    assert counts == [5, 5], counts
    print(f"offline: {len(families)} families, "
          f"{sum(len(f['samples']) for f in families.values())} samples OK")
    return len(families)


def check_live(url: str | None) -> None:
    import urllib.request
    if url is None:
        import tempfile
        from distributedmandelbrot_tpu.coordinator import EmbeddedCoordinator
        from distributedmandelbrot_tpu.core.workload import \
            parse_level_settings
        with tempfile.TemporaryDirectory() as tmp, \
                EmbeddedCoordinator(tmp, parse_level_settings("2:16")) as co:
            live = f"http://127.0.0.1:{co.exporter_port}/metrics"
            text = urllib.request.urlopen(live, timeout=10).read().decode()
    else:
        text = urllib.request.urlopen(url, timeout=10).read().decode()
    families = parse_exposition(text)
    check_invariants(families)
    # A coordinator exporter always carries the scheduler gauges.
    if url is None:
        assert "coord_frontier_depth" in families, sorted(families)
        assert families["coord_frontier_depth"]["samples"][0][2] == 4.0
    print(f"live: {len(families)} families OK")


# -- --names: instrumentation-site name audit ------------------------------

def check_names() -> int:
    """Cross-check every metric-name string literal at an instrumentation
    site (``counters.inc("...")``, ``registry.observe("...")``, ...)
    against the canonical registry in obs/names.py.  A literal that is
    not a registered name is exactly how the results_accepted collision
    happened — two spellings, no arbiter.

    The scan itself now lives in ``dmtpu check`` as the ``obs-name``
    rule family; this flag delegates there so the two paths can never
    disagree about what counts as an instrumentation site."""
    from distributedmandelbrot_tpu import analysis
    from distributedmandelbrot_tpu.analysis import rules_obs
    project = analysis.Project.from_root(REPO)
    known = rules_obs.known_names(project)
    if known is None:
        raise MetricsFormatError(
            "obs/names.py not found — cannot audit metric names")
    sites = sum(1 for _ in rules_obs.iter_sites(project))
    unknown = [f for f in rules_obs.check(project) if f.rule == "obs-name"]
    for f in unknown:
        print(f"{f.path}:{f.line}: {f.message}", file=sys.stderr)
    if unknown:
        raise MetricsFormatError(
            f"{len(unknown)} unregistered metric-name literal(s)")
    print(f"names: {sites} instrumentation literals OK "
          f"against {len(known)} registered names")
    return sites


def check_dead() -> int:
    """The reverse audit (``obs-dead`` in ``dmtpu check``): every name
    obs/names.py registers must be instrumented or referenced somewhere,
    or the registry is describing telemetry the fleet no longer emits."""
    from distributedmandelbrot_tpu import analysis
    from distributedmandelbrot_tpu.analysis import rules_obs
    project = analysis.Project.from_root(REPO)
    consts = rules_obs.registered_consts(project)
    if consts is None:
        raise MetricsFormatError(
            "obs/names.py not found — cannot audit registered names")
    dead = [f for f in rules_obs.check(project) if f.rule == "obs-dead"]
    for f in dead:
        print(f"{f.path}:{f.line}: {f.message}", file=sys.stderr)
    if dead:
        raise MetricsFormatError(
            f"{len(dead)} registered-but-uninstrumented name(s)")
    print(f"dead: {len(consts)} registered names all instrumented "
          f"or referenced")
    return len(consts)


def main() -> int:
    parser = argparse.ArgumentParser(
        description="Render and validate Prometheus exposition text.")
    parser.add_argument("--offline", action="store_true",
                        help="skip the live embedded-coordinator fetch")
    parser.add_argument("--url", default=None,
                        help="validate a running exporter's /metrics "
                             "instead of spinning up an embedded one")
    parser.add_argument("--names", action="store_true",
                        help="also audit metric-name literals at "
                             "instrumentation sites against obs/names.py")
    parser.add_argument("--dead", action="store_true",
                        help="also audit obs/names.py registrations for "
                             "names nothing instruments any more")
    args = parser.parse_args()
    check_rendered()
    if args.names:
        check_names()
    if args.dead:
        check_dead()
    if not args.offline:
        check_live(args.url)
    print("check_metrics: OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
