"""Run the opt-in compacted dispatch ON REAL TPU HARDWARE — identity + perf.

Round-5 verdict item 2: the compacted two-phase pipeline
(``ops/compact_escape.py``, opt-in via ``DMTPU_COMPACT=1``) had only ever
executed in CPU interpret mode; its "enable on a stack with healthy
gather bandwidth" advice had no tested enablement path.  This tool runs
the ASSEMBLED ``compact_escape_batch`` on the live chip:

1. byte-identity vs the plain batch-grid kernel on a boundary view and
   on a mixed-budget batch (the two cases the bit-identity matrix covers
   in interpret mode — here on real silicon);
2. one chained-delta perf row (same in-jit repetition methodology as
   bench.py) so the compact-vs-plain comparison measures the device, not
   the tunnel.

Usage (live TPU): python tools/hw_compact.py [--out COMPACT_HW_r05.json]

The artifact records the outcome either way — if the glue still loses on
this stack, that is the documented, now-hardware-tested negative.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def _chain(batch_fn, params_np, mrds_np, reps: int):
    """Chained-delta timing around an arbitrary (params, mrds) -> uint8
    batch function, via the ONE shared repetition idiom
    (``bench._reps_chain``)."""
    import jax.numpy as jnp

    from bench import _reps_chain

    params = jnp.asarray(params_np, jnp.float32)
    mrds = jnp.asarray(mrds_np, jnp.int32).reshape(-1, 1)

    def one_rep(p):
        return jnp.sum(batch_fn(p, mrds).astype(jnp.int32),
                       dtype=jnp.int32)

    return _reps_chain(one_rep, params, reps)


def run(out_path: str, repeats: int = 3) -> dict:
    import jax

    assert jax.default_backend() == "tpu", (
        f"compact hardware check needs the real chip (backend: "
        f"{jax.default_backend()})")

    from functools import partial

    from distributedmandelbrot_tpu.ops.compact_escape import (
        PHASE1_BUDGET, compact_escape_batch)
    from distributedmandelbrot_tpu.ops.pallas_escape import (
        _pallas_escape_batch, fit_blocks)
    from distributedmandelbrot_tpu.parallel.sharding import (
        widen_square_pitch)
    from bench import _grid_params, _time_chain

    tile, k, mi = 1024, 16, 2000
    assert 2 * PHASE1_BUDGET <= mi - 1
    block_h, block_w = fit_blocks(tile, tile)
    # The filament boundary window: deep straggler tails, no provable
    # interior — the view class compaction exists for.
    params = widen_square_pitch(
        _grid_params((-0.7436447, 0.1318252), 2e-3, tile, k))

    kw = dict(k=k, height=tile, width=tile, max_iter=mi, block_h=block_h,
              block_w=block_w, cycle_check=False)
    plain_fn = partial(_pallas_escape_batch, **kw)
    compact_fn = partial(compact_escape_batch, **kw)

    artifact: dict = {
        "device": str(jax.devices()[0]), "jax_version": jax.__version__,
        "view": {"center": (-0.7436447, 0.1318252), "span": 2e-3,
                 "tile": tile, "k": k, "max_iter": mi},
    }
    try:
        artifact["commit"] = subprocess.run(
            ["git", "rev-parse", "--short", "HEAD"], cwd=REPO,
            capture_output=True, text=True).stdout.strip()
    except Exception:
        pass

    import jax.numpy as jnp
    mrds_u = np.full((k, 1), mi, np.int32)
    a = np.asarray(compact_fn(jnp.asarray(params, jnp.float32),
                              jnp.asarray(mrds_u)))
    b = np.asarray(plain_fn(jnp.asarray(params, jnp.float32),
                            jnp.asarray(mrds_u)))
    artifact["identity_uniform"] = bool((a == b).all())
    print(f"uniform-budget identity on hardware: "
          f"{artifact['identity_uniform']} "
          f"({(a != b).sum()} differing bytes)", flush=True)

    # Mixed budgets exercise the per-tile dynamic-budget path through
    # both phases (and the executable-sharing bucket).
    mrds_m = np.asarray([[600, 1000, 2000, 1500][i % 4]
                         for i in range(k)], np.int32).reshape(-1, 1)
    am = np.asarray(compact_fn(jnp.asarray(params, jnp.float32),
                               jnp.asarray(mrds_m)))
    bm = np.asarray(plain_fn(jnp.asarray(params, jnp.float32),
                             jnp.asarray(mrds_m)))
    artifact["identity_mixed_budget"] = bool((am == bm).all())
    print(f"mixed-budget identity on hardware: "
          f"{artifact['identity_mixed_budget']} "
          f"({(am != bm).sum()} differing bytes)", flush=True)

    # Chained-delta perf: pure device time, tunnel excluded.
    pixels = k * tile * tile
    rows = {}
    for name, fn in (("plain", plain_fn), ("compact", compact_fn)):
        t1 = _time_chain(_chain(fn, params, mrds_u, 1), repeats)
        t3 = _time_chain(_chain(fn, params, mrds_u, 3), repeats)
        dev = (t3 - t1) / 2
        rows[name] = {
            "benched_mpix_s": round(pixels / t1 / 1e6, 1),
            "device_mpix_s": round(pixels / dev / 1e6, 1)
            if dev > 0.02 * t1 else None,
        }
        print(f"{name}: benched {rows[name]['benched_mpix_s']} Mpix/s, "
              f"device {rows[name]['device_mpix_s']}", flush=True)
    artifact["perf"] = rows
    if rows["plain"]["device_mpix_s"] and rows["compact"]["device_mpix_s"]:
        artifact["compact_vs_plain_device"] = round(
            rows["compact"]["device_mpix_s"]
            / rows["plain"]["device_mpix_s"], 3)

    with open(out_path, "w") as f:
        json.dump(artifact, f, indent=1)
    print(f"wrote {out_path}")
    return artifact


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--out",
                    default=os.path.join(REPO, "COMPACT_HW_r05.json"))
    ap.add_argument("--repeats", type=int, default=3)
    args = ap.parse_args()
    run(args.out, args.repeats)
    return 0


if __name__ == "__main__":
    sys.exit(main())
