"""Pin the north star's bit-identity clause with TPU-vs-GOLDEN artifacts.

Round-5 verdict item 1: every prior hardware parity check was TPU-vs-TPU
(pallas vs XLA f32, perturbation vs XLA f64); the README's "FMA moves
O(0.02%) of boundary pixels" was disclosed but unpinned.  This tool
computes boundary tiles ON THE REAL CHIP and compares them against the
reference-path golden (``ops/reference.py`` — the semantic pin of the
reference CUDA kernel, ``DistributedMandelbrotWorkerCUDA.py:39-68,96-98``),
then writes a versioned divergence contract:

- **f64 leg**: the XLA escape loop in emulated f64 on the host-f64 grid —
  the same numbers the golden iterates — byte-compared.  The loop is
  mul/add/cmp only, so byte equality is the expected outcome; either way
  the artifact records the measured truth.
- **f32 fast path** (Pallas, the production kernel): quantified exactly —
  pixel count, mismatch count/fraction, max cyclic uint8 band distance,
  max escape-count delta — both against the golden on the kernel's own
  f32 grid (isolating iteration arithmetic from grid quantization) and
  against the golden on the host f64 grid (the end-to-end viewer
  contract).

Usage (on a live TPU backend):

    python tools/hw_parity.py [--out PARITY_r05.json]

The README cites the written artifact instead of an unanchored estimate.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

# Boundary-rich pinned views: the seahorse window every bench round uses,
# and the filament window (no provable interior anywhere — the
# worst-case floor view, where chaotic dynamics amplify FMA divergence).
VIEWS = {
    "seahorse": {"start": (-0.748, 0.09), "span": 0.005, "max_iter": 1000},
    "filament": {"start": (-0.7436447 - 1e-3, 0.1318252 - 1e-3),
                 "span": 2e-3, "max_iter": 2000},
}
SIDE = 256


class _SkipControl(Exception):
    """Internal: caller declined the CPU-control subprocess."""


def _f32_grid(start_real: float, start_imag: float, span: float, side: int):
    """The in-kernel grid convention: f32 start + index * f32 step."""
    step = np.float32(span / (side - 1))
    cr = (np.float32(start_real)
          + np.arange(side, dtype=np.float32) * step)[None, :]
    ci = (np.float32(start_imag)
          + np.arange(side, dtype=np.float32) * step)[:, None]
    return (np.broadcast_to(cr, (side, side)),
            np.broadcast_to(ci, (side, side)))


def _band_stats(got_u8: np.ndarray, want_u8: np.ndarray) -> dict:
    """Exact divergence stats between two uint8 tiles; band distance is
    cyclic (the ceil(v*256/mrd) scaling wraps, so a count off by one can
    land 255 next to 0)."""
    got = got_u8.astype(np.int32).ravel()
    want = want_u8.astype(np.int32).ravel()
    mism = got != want
    n = int(mism.sum())
    out = {"n_pixels": int(got.size), "n_mismatch": n,
           "mismatch_frac": round(n / got.size, 6)}
    if n:
        d = np.abs(got[mism] - want[mism])
        d = np.minimum(d, 256 - d)
        out["max_band_dist"] = int(d.max())
    else:
        out["max_band_dist"] = 0
    return out


def run(out_path: str, *, cpu_control: bool = True) -> dict:
    import jax

    assert jax.default_backend() == "tpu", (
        f"parity pin must run on the real chip (backend: "
        f"{jax.default_backend()})")

    from distributedmandelbrot_tpu.core.geometry import TileSpec
    from distributedmandelbrot_tpu.ops import escape_time
    from distributedmandelbrot_tpu.ops import reference as ref
    from distributedmandelbrot_tpu.ops.pallas_escape import (
        compute_tile_pallas)
    from distributedmandelbrot_tpu.utils.precision import ensure_x64

    artifact: dict = {
        "contract": "TPU-computed tile vs ops/reference.py golden "
                    "(the reference CUDA kernel's semantic pin)",
        "device": str(jax.devices()[0]),
        "jax_version": jax.__version__,
        "side": SIDE,
        "views": {},
    }
    try:
        artifact["commit"] = subprocess.run(
            ["git", "rev-parse", "--short", "HEAD"], cwd=REPO,
            capture_output=True, text=True).stdout.strip()
    except Exception:
        pass

    # Phase 1 — f32 legs with x64 OFF (the Pallas kernel cannot lower
    # 64-bit types; enabling x64 first would leak int64 into it).
    for name, view in VIEWS.items():
        mi = view["max_iter"]
        spec = TileSpec(view["start"][0], view["start"][1],
                        view["span"], view["span"],
                        width=SIDE, height=SIDE)
        row: dict = {"view": {"start_real": view["start"][0],
                              "start_imag": view["start"][1],
                              "span": view["span"], "max_iter": mi}}

        # Golden on the host f64 grid (the e2e viewer contract).
        cr64, ci64 = spec.grid_2d()
        g_counts = ref.escape_counts(cr64, ci64, mi)
        g_u8 = ref.scale_counts_to_uint8(g_counts, mi)
        row["_g_counts"] = g_counts
        row["_g_u8"] = g_u8

        # --- f32 fast path (Pallas production kernel, in-kernel f32
        # grid, device-scaled uint8): end-to-end contract vs the
        # host-grid golden...
        p_u8 = np.asarray(compute_tile_pallas(spec, mi)).reshape(
            SIDE, SIDE)
        row["f32_pallas_vs_golden_hostgrid"] = _band_stats(p_u8, g_u8)
        # ...and vs the golden ITERATED FROM THE KERNEL'S OWN f32
        # grid (grid quantization removed: what remains is f32+FMA
        # iteration arithmetic).
        cr32, ci32 = _f32_grid(view["start"][0], view["start"][1],
                               view["span"], SIDE)
        g32_counts = ref.escape_counts(cr32.astype(np.float64),
                                       ci32.astype(np.float64), mi)
        g32_u8 = ref.scale_counts_to_uint8(g32_counts, mi)
        row["f32_pallas_vs_golden_f32grid"] = _band_stats(p_u8, g32_u8)
        # Escape-count deltas of the f32 XLA twin on the same f32
        # grid (the Pallas kernel emits uint8 only; the XLA f32 path
        # is hardware-parity-pinned against it in revalidate step 2).
        x32_counts = np.asarray(escape_time.escape_counts(
            cr32.copy(), ci32.copy(), max_iter=mi,
            interior_check=False, cycle_check=False))
        dmask = x32_counts != g32_counts
        row["f32_xla_count_delta_f32grid"] = {
            "n_mismatch": int(dmask.sum()),
            "max_count_delta": int(np.abs(
                x32_counts[dmask].astype(np.int64)
                - g32_counts[dmask]).max()) if dmask.any() else 0,
        }
        artifact["views"][name] = row

    # Phase 2 — f64 leg: emulated f64 on the SAME grid as the golden,
    # host-scaled the same way, so any byte difference is iteration
    # arithmetic alone.
    was_x64 = jax.config.jax_enable_x64
    try:
        ensure_x64()
        for name, view in VIEWS.items():
            mi = view["max_iter"]
            spec = TileSpec(view["start"][0], view["start"][1],
                            view["span"], view["span"],
                            width=SIDE, height=SIDE)
            row = artifact["views"][name]
            g_counts = row.pop("_g_counts")
            g_u8 = row.pop("_g_u8")
            cr64, ci64 = spec.grid_2d()
            t_counts = np.asarray(escape_time.escape_counts(
                np.asarray(cr64, np.float64), np.asarray(ci64, np.float64),
                max_iter=mi, interior_check=False, cycle_check=False))
            t_u8 = ref.scale_counts_to_uint8(t_counts, mi)
            n_cmis = int((t_counts != g_counts).sum())
            row["f64_tpu_vs_golden"] = {
                "count_mismatch": n_cmis,
                "byte_identical": bool((t_u8 == g_u8).all()),
                **_band_stats(t_u8, g_u8),
            }
            print(f"{name}: f64 byte-identical="
                  f"{row['f64_tpu_vs_golden']['byte_identical']} "
                  f"(count mismatches {n_cmis}); f32 pallas vs golden "
                  f"hostgrid {row['f32_pallas_vs_golden_hostgrid']}"
                  f" f32grid {row['f32_pallas_vs_golden_f32grid']}",
                  flush=True)
    finally:
        jax.config.update("jax_enable_x64", was_x64)

    # CPU-XLA f64 control (subprocess — backend choice is process-level):
    # separates XLA's FMA/contraction class from TPU f64 emulation.  The
    # reference's OWN CUDA kernel is f64 compiled through NVVM, which
    # contracts multiply-adds by default (nvcc -fmad), so this class —
    # not strict separate-ops IEEE — is what the reference GPU worker
    # itself produces; the byte-exact pins of that strict semantics are
    # the numpy golden and the native C++ anchor (e2e-tested).
    ctrl_src = (
        "import json,sys,numpy as np\n"
        "from distributedmandelbrot_tpu.utils.precision import ensure_x64\n"
        "ensure_x64()\n"
        "from distributedmandelbrot_tpu.core.geometry import TileSpec\n"
        "from distributedmandelbrot_tpu.ops import escape_time\n"
        "from distributedmandelbrot_tpu.ops import reference as ref\n"
        "views=json.loads(sys.argv[1]); side=int(sys.argv[2]); out={}\n"
        "for name,v in views.items():\n"
        "    spec=TileSpec(v['start'][0],v['start'][1],v['span'],v['span'],"
        "width=side,height=side)\n"
        "    cr,ci=spec.grid_2d()\n"
        "    g=ref.escape_counts(cr,ci,v['max_iter'])\n"
        "    t=np.asarray(escape_time.escape_counts(np.asarray(cr,"
        "np.float64),np.asarray(ci,np.float64),max_iter=v['max_iter'],"
        "interior_check=False,cycle_check=False))\n"
        "    out[name]=int((t!=g).sum())\n"
        "print(json.dumps(out))\n")
    try:
        if not cpu_control:
            raise _SkipControl
        env = dict(os.environ, JAX_PLATFORMS="cpu",
                   PALLAS_AXON_POOL_IPS="",
                   PYTHONPATH=REPO + os.pathsep
                   + os.environ.get("PYTHONPATH", ""))
        cp = subprocess.run(
            [sys.executable, "-c", ctrl_src, json.dumps(VIEWS), str(SIDE)],
            capture_output=True, text=True, timeout=600, env=env)
        ctrl = json.loads(cp.stdout.strip().splitlines()[-1])
        for name, n in ctrl.items():
            artifact["views"][name]["f64_xla_cpu_control_count_mismatch"] \
                = n
        print(f"cpu-xla f64 control count mismatches: {ctrl}")
    except _SkipControl:
        pass  # caller opted out (revalidate: control is artifact-only)
    except Exception as e:
        print(f"cpu control skipped: {type(e).__name__}: {e}",
              file=sys.stderr)

    with open(out_path, "w") as f:
        json.dump(artifact, f, indent=1)
    print(f"wrote {out_path}")
    return artifact


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default=os.path.join(REPO, "PARITY_r05.json"))
    args = ap.parse_args()
    run(args.out)
    return 0


if __name__ == "__main__":
    sys.exit(main())
