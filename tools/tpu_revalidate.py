"""Post-outage TPU revalidation: run once after the accelerator comes back.

The dev rig's tunnel dies for hours at a time; several hardware-touching
changes can land while it's down.  This script walks every TPU-sensitive
surface in dependency order and stops at the first failure:

    python tools/tpu_revalidate.py

1. backend up + device visible
2. Pallas single-tile kernel (bucketed compile cap, dynamic budget)
3. mixed-budget executables share one compile bucket
4. sharded Pallas batch path (shard_map + lax.map around pallas_call)
4b. production-shape sharded Pallas: 4096^2 tiles, mixed budgets, Mpix/s
    within 15% of the single-tile rate
5. perturbation scan on device (moderate zoom, parity vs XLA f64)
5b. BLA fast path on hardware (bench_deepslow: bond-point view,
    bit-identical and faster than the exact scan)
6. farm e2e with the auto (Pallas) backend at production chunk size
7. bench headline (prints the JSON line)
7b. bench worst-case boundary views (raw vs shortcut per view)
"""

from __future__ import annotations

import os
import subprocess
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def step(name):
    print(f"\n=== {name} ===", flush=True)


def main() -> int:
    from __graft_entry__ import backend_alive

    step("1. backend probe")
    if not backend_alive():
        print("backend still unreachable; aborting")
        return 1
    import jax

    print("devices:", jax.devices())
    if jax.default_backend() != "tpu":
        # A half-restored tunnel can leave jax silently on CPU: steps 2-5
        # would then compare CPU against CPU (trivially passing) and step
        # 6 would never touch the Pallas backend — a false "revalidated".
        print("default backend is not tpu; aborting (nothing to revalidate)")
        return 1

    import numpy as np

    from distributedmandelbrot_tpu.core.geometry import TileSpec
    from distributedmandelbrot_tpu.ops import escape_time
    from distributedmandelbrot_tpu.ops.pallas_escape import (
        _pallas_escape, compute_tile_pallas)

    step("2. pallas single tile (bucketed cap)")
    spec = TileSpec(-0.748, 0.09, 0.005, 0.005, width=256, height=256)
    got = compute_tile_pallas(spec, 1000)  # cap 1024, budget 1000
    # Same f32 start+index*step convention the kernel generates in-kernel
    # (parametric in the spec — cf. tests/test_pallas.py:xla_f32_reference).
    stepv = np.float32(spec.range_real / (spec.width - 1))
    cr = (np.float32(spec.start_real)
          + np.arange(spec.width, dtype=np.float32) * stepv
          )[None, :].repeat(spec.height, 0)
    ci = (np.float32(spec.start_imag)
          + np.arange(spec.height, dtype=np.float32) * stepv
          )[:, None].repeat(spec.width, 1)
    want = np.asarray(escape_time.scale_counts_to_uint8(
        escape_time.escape_counts(cr, ci, max_iter=1000),
        max_iter=1000)).ravel()
    mism = float((got != want).mean())
    print(f"parity vs XLA f32: {mism:.4%} mismatch")
    assert mism <= 0.02

    step("3. compile-cap sharing")
    before = _pallas_escape._cache_size()
    compute_tile_pallas(spec, 900)   # same 1024 bucket as 1000, same
    shared = _pallas_escape._cache_size() == before  # probe policy (off)
    print("bucket shared:", shared)
    assert shared
    # Since round 5 the probe threshold sits AT this bucket (1024): a
    # budget of exactly 1024 arms the probe, so it must compile a
    # SECOND executable for the same cap — policy resolves from the
    # true budget, and the two variants may not be conflated.
    compute_tile_pallas(spec, 1024)
    split = _pallas_escape._cache_size() == before + 1
    print("probe-armed 1024 split:", split)
    assert split

    step("3b. pallas smooth kernel")
    from distributedmandelbrot_tpu.ops.pallas_escape import (
        compute_tile_smooth_pallas)
    nu = compute_tile_smooth_pallas(spec, 1000)
    want_nu = np.asarray(escape_time.escape_smooth(cr, ci, max_iter=1000))
    agree = float(((nu == 0) == (want_nu == 0)).mean())
    print(f"smooth in-set mask agreement: {agree:.4%}")
    assert agree >= 0.999

    step("3c. shortcut output-identity on hardware (interior + cycle)")
    on = compute_tile_pallas(spec, 1000)
    off = compute_tile_pallas(spec, 1000, interior_check=False,
                              cycle_check=False)
    ident = bool((on == off).all())
    print("interior/cycle shortcuts output-identical:", ident)
    assert ident
    deep_spec = TileSpec(-0.2, 0.7, 0.15, 0.15, width=256, height=256)
    on = compute_tile_pallas(deep_spec, 5000)  # cap 8192 -> cycle probe on
    off = compute_tile_pallas(deep_spec, 5000, cycle_check=False)
    ident = bool((on == off).all())
    print("cycle probe at depth 5000 output-identical:", ident)
    assert ident

    step("3c2. TPU-vs-GOLDEN parity pin (round-5 verdict item 1)")
    # Recompute the divergence contract on the live chip and hold it to
    # the pinned class (PARITY_r05.json): f64 stays in the FMA/
    # contraction class (<= 1% of counts), the f32 fast path within its
    # measured band (<= 20% of pixels on these boundary views).  A
    # kernel change that silently moved either class now fails here
    # instead of passing every TPU-vs-TPU check.
    from tools.hw_parity import run as parity_run
    with tempfile.TemporaryDirectory() as td:
        # cpu_control off: the control subprocess (minutes of CPU f64)
        # only annotates the artifact, and this step's assertions don't
        # read it — the pinned control lives in PARITY_r05.json.
        art = parity_run(os.path.join(td, "parity.json"),
                         cpu_control=False)
    for vname, row in art["views"].items():
        f64row = row["f64_tpu_vs_golden"]
        frac64 = f64row["count_mismatch"] / row["f32_pallas_vs_golden_"
                                               "hostgrid"]["n_pixels"]
        assert frac64 <= 0.01, (vname, f64row)
        assert row["f32_pallas_vs_golden_f32grid"]["mismatch_frac"] \
            <= 0.20, (vname, row["f32_pallas_vs_golden_f32grid"])

    step("3c3. compacted dispatch on hardware (round-5 verdict item 2)")
    # The opt-in DMTPU_COMPACT=1 pipeline, assembled, on real silicon:
    # byte-identity is a hard assert; perf is recorded (the measured
    # negative on this stack is expected and documented).
    from tools.hw_compact import run as compact_run
    with tempfile.TemporaryDirectory() as td:
        cart = compact_run(os.path.join(td, "compact.json"), repeats=2)
    assert cart["identity_uniform"] and cart["identity_mixed_budget"], cart

    step("3d. julia + family kernels on hardware")
    from distributedmandelbrot_tpu.ops.families import escape_counts_family
    from distributedmandelbrot_tpu.ops.pallas_escape import (
        compute_tile_family_pallas, compute_tile_julia_pallas)
    jspec = TileSpec(-1.5, -1.5, 3.0, 3.0, width=256, height=256)
    got = compute_tile_julia_pallas(jspec, -0.8 + 0.156j, 500)
    print("julia pallas levels:", len(np.unique(got)))
    assert len(np.unique(got)) > 10
    sspec = TileSpec(-2.2, -1.2, 2.4, 2.4, width=256, height=256)
    got = compute_tile_family_pallas(sspec, 500, burning=True)
    # Parity vs the XLA family kernel on the same in-kernel grid
    # convention (wide band: the ship's |.| folds amplify FMA differences
    # between the two compiled graphs — see ops/families.py).
    sv = np.float32(sspec.range_real / (sspec.width - 1))
    scr = (np.float32(sspec.start_real)
           + np.arange(sspec.width, dtype=np.float32) * sv
           )[None, :].repeat(sspec.height, 0)
    sci = (np.float32(sspec.start_imag)
           + np.arange(sspec.height, dtype=np.float32) * sv
           )[:, None].repeat(sspec.width, 1)
    ship_want = np.asarray(escape_time.scale_counts_to_uint8(
        escape_counts_family(scr, sci, max_iter=500, burning=True),
        max_iter=500)).ravel()
    ship_mism = float((got != ship_want).mean())
    print(f"burning-ship pallas vs XLA: {ship_mism:.4%} mismatch")
    assert ship_mism <= 0.08

    step("4. sharded pallas batch (mixed budgets)")
    from distributedmandelbrot_tpu.parallel import (
        batched_escape_pixels, batched_escape_pixels_pallas, tile_mesh)
    mesh = tile_mesh()
    params = np.array([[-0.748 + 0.005 * i, 0.09, 0.005 / 1023]
                       for i in range(3)])
    mrds = np.array([200, 1000, 513])
    a = batched_escape_pixels_pallas(mesh, params, mrds, definition=1024)
    b = batched_escape_pixels(mesh, params, mrds, definition=1024,
                              dtype=np.float32)
    mism = float((a != b).mean())
    print(f"sharded parity vs XLA: {mism:.4%}")
    assert mism <= 0.02

    step("4b. production-shape pallas: 4096^2 sharded, mixed budgets")
    # Device-chained timing (bench.py methodology): the sharded Pallas
    # dispatch at the farm's real tile size, mixed mrd exercising the
    # bucket-cap executable-sharing path, vs the single-tile chain —
    # sharded dispatch overhead must stay small at production shape.
    from bench import _grid_params, _pallas_chain, _pallas_sharded_chain, \
        _time_chain
    big = 4096
    k4 = max(4, mesh.devices.size)
    params4 = _grid_params((-0.7436447, 0.1318252), 2e-3, big, k4)
    mixed_mrds = np.array([[1000, 700, 1000, 513][i % 4]
                           for i in range(k4)], np.int64)
    t_shard = _time_chain(_pallas_sharded_chain(mesh, params4, mixed_mrds,
                                                big), 2)
    shard_rate = k4 * big * big / t_shard / 1e6
    t_single = _time_chain(_pallas_chain(params4[:1], big, 1000), 2)
    single_rate = big * big / t_single / 1e6
    print(f"4096^2 sharded mixed-mrd: {shard_rate:.1f} Mpix/s; "
          f"single-tile: {single_rate:.1f} Mpix/s; "
          f"ratio {shard_rate / single_rate:.2f}")
    # Mixed budgets average shallower than the single tile's 1000, so the
    # sharded rate should not fall meaningfully below the single rate.
    assert shard_rate >= 0.85 * single_rate, (
        f"sharded 4096^2 path lost >15% vs single-tile "
        f"({shard_rate:.1f} vs {single_rate:.1f} Mpix/s)")
    # Bidirectional production-shape check (round-3 verdict item 2: the
    # old assert was one-directional, so a slow single-tile baseline
    # passed silently): at MATCHED per-call pixels the 4096^2 shape must
    # stay within 20% of the 1024^2 batch — the r03 "3x gap" was the
    # per-call dispatch constant, not the tile shape (ROUND4_NOTES.md).
    from bench import bench_tileshape
    ts = bench_tileshape(2)
    print(f"4096^2x4 {ts['tile4096x4_mpix_s']} vs 1024^2x64 "
          f"{ts['tile1024x64_mpix_s']} Mpix/s benched "
          f"(device {ts.get('tile4096x4_device_mpix_s', 'n/a')} vs "
          f"{ts.get('tile1024x64_device_mpix_s', 'n/a')}; per-call "
          f"overhead {ts.get('tile4096x4_call_overhead_s', 'n/a')}s)")
    assert ts["tile4096x4_mpix_s"] >= 0.8 * ts["tile1024x64_mpix_s"], (
        "production 4096^2 tile shape fell >20% behind the matched "
        "1024^2 batch")

    step("5. perturbation scan on device")
    from distributedmandelbrot_tpu.ops.perturbation import (
        DeepTileSpec, compute_counts_perturb)
    dspec = DeepTileSpec("-0.74529", "0.11307", 1e-5, width=256, height=256)
    t0 = time.time()
    counts, ng = compute_counts_perturb(dspec, 2000)
    print(f"perturb 256^2 mi=2000: {time.time()-t0:.2f}s, "
          f"{ng} glitch-fixed, {len(np.unique(counts))} levels")
    assert len(np.unique(counts)) > 10

    step("5b. BLA fast path on hardware (bench_deepslow)")
    # The ONE copy of the bond-point benchmark (view, budget, timing
    # methodology) lives in bench.py; this step just runs it and turns
    # its reported fields into hard assertions (safe here: the script
    # aborts unless the backend is TPU, where identity is pinned).
    from bench import bench_deepslow
    ds = bench_deepslow(2)
    print(f"bond: exact {ds['exact_mpix_s']} Mpix/s, bla "
          f"{ds['bla_mpix_s']} (x{ds['bla_speedup']}), "
          f"agreement {ds['bla_agreement']}")
    # The BLA contract is approximate (eps-perturbed deltas); a marginal
    # boundary lane can legitimately flip under an eps/table change, so
    # assert the contract-level bound and only WARN on non-bit-identity
    # (round-3 advisor — bench.py deliberately reports, not asserts).
    assert ds["bla_agreement"] >= 0.999, \
        f"BLA diverged on the bond view (agreement {ds['bla_agreement']})"
    if ds["bla_agreement"] != 1.0:
        print(f"  note: BLA agreement {ds['bla_agreement']} < 1.0 "
              "(within contract; boundary-lane flips)")
    assert ds["bla_speedup"] > 1.0, "BLA slower on its showcase view"

    step("6. farm e2e (auto backend, 4096^2)")
    from distributedmandelbrot_tpu.cli import parse_level_settings
    from distributedmandelbrot_tpu.coordinator import EmbeddedCoordinator
    from distributedmandelbrot_tpu.worker import (DistributerClient, Worker,
                                                  auto_backend)
    with tempfile.TemporaryDirectory() as tmp, \
            EmbeddedCoordinator(tmp, parse_level_settings("2:500")) as co:
        backend = auto_backend()
        print("backend:", type(backend).__name__)
        w = Worker(DistributerClient("127.0.0.1", co.distributer_port),
                   backend, batch_size=4)
        t0 = time.time()
        w.run_until_drained()
        co.wait_saves_settled(expected_accepted=4, timeout=300)
        dt = time.time() - t0
        print(f"4x4096^2 e2e in {dt:.1f}s = {4*16.78e6/dt/1e6:.1f} Mpix/s")

    step("7. bench headline")
    rc = subprocess.run([sys.executable, os.path.join(REPO, "bench.py"),
                         "--repeats", "2"], cwd=REPO).returncode
    assert rc == 0

    step("7b. bench worst-case boundary views (raw vs shortcut)")
    rc = subprocess.run([sys.executable, os.path.join(REPO, "bench.py"),
                         "--worst", "--repeats", "2"], cwd=REPO).returncode
    assert rc == 0
    print("\nALL REVALIDATION STEPS PASSED")
    return 0


if __name__ == "__main__":
    sys.exit(main())
