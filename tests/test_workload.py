import struct

import pytest

from distributedmandelbrot_tpu.core import (WORKLOAD_WIRE_SIZE, LevelSetting,
                                            Workload, parse_level_settings)


def test_wire_roundtrip_little_endian():
    w = Workload(10, 1024, 3, 7)
    wire = w.to_wire()
    assert len(wire) == WORKLOAD_WIRE_SIZE == 16
    assert wire == struct.pack("<IIII", 10, 1024, 3, 7)
    assert Workload.from_wire(wire) == w


def test_wire_rejects_bad_length():
    with pytest.raises(ValueError):
        Workload.from_wire(b"\x00" * 15)


def test_wire_encode_requires_max_iter():
    with pytest.raises(ValueError):
        Workload(10, None, 3, 7).to_wire()


def test_none_max_iter_is_wildcard_in_matches():
    generated = Workload(10, 1024, 3, 7)
    from_disk = Workload(10, None, 3, 7)
    assert from_disk.matches(generated)
    assert generated.matches(from_disk)
    assert not Workload(10, 512, 3, 7).matches(generated)
    assert not Workload(10, None, 3, 6).matches(generated)


def test_key_excludes_max_iter():
    """Completion dedup must work across disk-reloaded (max_iter=None) jobs —
    the reference's broken hash contract (DistributerWorkload.cs:50-51) made
    this best-effort; keying on (level, i, j) fixes it."""
    assert Workload(10, 1024, 3, 7).key == Workload(10, None, 3, 7).key


def test_uint32_range_enforced():
    with pytest.raises(ValueError):
        Workload(2**32, 1, 0, 0)
    with pytest.raises(ValueError):
        Workload(1, -2, 0, 0)


def test_parse_level_settings_canonical():
    settings = parse_level_settings("4:256,10:1024,20:1024")
    assert settings == (LevelSetting(4, 256), LevelSetting(10, 1024),
                        LevelSetting(20, 1024))
    assert sum(s.tile_count for s in settings) == 16 + 100 + 400


@pytest.mark.parametrize("bad", ["", "4", "4:", ":256", "4:256,4:512", "a:b"])
def test_parse_level_settings_rejects(bad):
    with pytest.raises(ValueError):
        parse_level_settings(bad)
