"""Test harness: the embedded coordinator, re-exported under its
historical test name (the implementation moved into the package so the
benchmark farm loop can use it too)."""

from __future__ import annotations

from distributedmandelbrot_tpu.coordinator.embed import EmbeddedCoordinator


class CoordinatorHarness(EmbeddedCoordinator):
    pass
