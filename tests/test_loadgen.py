"""Deterministic (virtual-clock) tests for the open-loop storm harness.

Everything here runs on :class:`VirtualTimebase` — a "10 second" storm
finishes in milliseconds and issue instants are *exact*, so the open-loop
contract (arrivals follow the schedule, not the server) is asserted as
equality, not as a tolerance band.
"""

from __future__ import annotations

import asyncio

import pytest

from distributedmandelbrot_tpu.loadgen import (OpenLoopRunner, Phase,
                                               StormRecorder,
                                               VirtualTimebase, ZipfTiles,
                                               build_schedule, parse_phases,
                                               poisson_arrivals)
from distributedmandelbrot_tpu.loadgen import recorder as rec
from distributedmandelbrot_tpu.loadgen.schedule import offered_rate
from distributedmandelbrot_tpu.obs import names as obs_names


def _drive(runner: OpenLoopRunner, timebase: VirtualTimebase) -> float:
    async def main() -> float:
        task = asyncio.ensure_future(runner.run())
        await timebase.drain(until=task)
        return task.result()

    return asyncio.run(main())


# -- phase spec / arrival process -------------------------------------------

def test_parse_phases_grammar_and_errors():
    phases = parse_phases("steady:200x5, spike:2000x2 ,ramp:200-2000x5")
    assert [p.kind for p in phases] == ["steady", "spike", "ramp"]
    assert phases[0].rate == 200 and phases[0].duration == 5
    assert phases[2].rate == 200 and phases[2].rate_end == 2000
    assert [p.name for p in phases] == ["steady0", "spike1", "ramp2"]
    for bad in ("", "warble:10x5", "steady:x5", "steady:10", "ramp:10x5"):
        with pytest.raises(ValueError):
            parse_phases(bad)


def test_poisson_arrivals_deterministic_in_window_and_near_rate():
    phases = parse_phases("steady:500x4,spike:2000x2")
    a1 = poisson_arrivals(phases, seed=7)
    a2 = poisson_arrivals(phases, seed=7)
    assert a1 == a2  # same seed, same storm, byte for byte
    assert a1 != poisson_arrivals(phases, seed=8)
    times = [t for t, _ in a1]
    assert times == sorted(times)
    steady = [t for t, name in a1 if name == "steady0"]
    spike = [t for t, name in a1 if name == "spike1"]
    assert all(0 <= t < 4 for t in steady)
    assert all(4 <= t < 6 for t in spike)
    # A Poisson(n) count sits within ~5 sigma of its mean.
    assert 500 * 4 * 0.8 < len(steady) < 500 * 4 * 1.2
    assert 2000 * 2 * 0.8 < len(spike) < 2000 * 2 * 1.2


def test_ramp_arrival_density_actually_ramps():
    (phase,) = parse_phases("ramp:100-1900x10")
    arrivals = poisson_arrivals([phase], seed=3)
    first = sum(1 for t, _ in arrivals if t < 5)
    second = len(arrivals) - first
    # Mean rate 600/s in the first half vs 1400/s in the second.
    assert second > 1.5 * first


def test_zipf_sampler_head_heavy_and_in_range():
    sampler = ZipfTiles(8, s=1.2, seed=1)
    counts: dict[tuple[int, int, int], int] = {}
    for _ in range(4000):
        key = sampler.sample()
        level, i, j = key
        assert level == 8 and 0 <= i < 8 and 0 <= j < 8
        counts[key] = counts.get(key, 0) + 1
    ranked = sorted(counts.values(), reverse=True)
    # Zipf head: the hottest key dwarfs the median key.
    assert ranked[0] > 8 * ranked[len(ranked) // 2]
    # hottest() agrees with the empirical head.
    assert sampler.hottest(1)[0] == max(counts, key=counts.get)


# -- open-loop runner -------------------------------------------------------

def test_open_loop_issue_times_independent_of_server_latency():
    """The defining property: a server 100x slower than the schedule's
    inter-arrival gap must not delay a single issue instant."""
    phases = parse_phases("steady:100x3")
    sampler = ZipfTiles(4, seed=0)
    schedule = build_schedule(phases, sampler, seed=0)
    timebase = VirtualTimebase()
    recorder = StormRecorder()

    async def glacial(level, i, j):
        await timebase.sleep(10.0)  # way past the 3s schedule span
        return rec.OUTCOME_OK, 64

    runner = OpenLoopRunner(schedule, glacial, recorder, timebase=timebase)
    duration = _drive(runner, timebase)
    assert runner.issue_times == [item.time for item in schedule]
    assert recorder.registry.counter_value(
        obs_names.LOADGEN_REQUESTS) == len(schedule)
    assert recorder.registry.counter_value(
        obs_names.LOADGEN_COMPLETED) == len(schedule)
    # Run ends when the last straggler lands: last issue + service time.
    assert duration == pytest.approx(schedule[-1].time + 10.0)


def test_phase_labels_follow_transitions():
    phases = parse_phases("steady:200x2,spike:800x1")
    schedule = build_schedule(phases, ZipfTiles(4, seed=0), seed=0)
    for item in schedule:
        assert item.phase == ("steady0" if item.time < 2 else "spike1")
    timebase = VirtualTimebase()
    recorder = StormRecorder()

    async def instant(level, i, j):
        return rec.OUTCOME_OK, 1

    _drive(OpenLoopRunner(schedule, instant, recorder, timebase=timebase),
           timebase)
    report = recorder.report(duration=3.0, offered=offered_rate(schedule),
                             phases=[p.name for p in phases])
    assert set(report["phases"]) == {"steady0", "spike1"}
    assert report["p50"] is not None


def test_shed_accounting_against_stub_gateway():
    """A capacity-64 stub under a 5x-over-capacity spike: every arrival
    settles exactly once, sheds are counted, and the report's shed
    fraction is consistent with the counters."""
    phases = parse_phases("steady:100x2,spike:1000x2,steady:100x2")
    schedule = build_schedule(phases, ZipfTiles(4, seed=2), seed=2)
    timebase = VirtualTimebase()
    recorder = StormRecorder()
    inflight = 0

    async def stub(level, i, j):
        nonlocal inflight
        if inflight >= 64:
            return rec.OUTCOME_SHED, 0
        inflight += 1
        try:
            await timebase.sleep(0.2)  # capacity: 320/s
        finally:
            inflight -= 1
        return rec.OUTCOME_OK, 128

    runner = OpenLoopRunner(schedule, stub, recorder, timebase=timebase)
    duration = _drive(runner, timebase)
    reg = recorder.registry
    issued = reg.counter_value(obs_names.LOADGEN_REQUESTS)
    completed = reg.counter_value(obs_names.LOADGEN_COMPLETED)
    shed = reg.counter_value(obs_names.LOADGEN_SHED)
    assert issued == len(schedule)
    assert completed + shed == issued  # nothing lost, nothing double
    assert shed > 0  # the spike overran capacity
    # The steady phases fit within capacity; sheds belong to the spike.
    spike_issued = sum(1 for item in schedule if item.phase == "spike1")
    assert shed < spike_issued
    report = recorder.report(duration=duration,
                             offered=offered_rate(schedule))
    assert report["shed_fraction"] == pytest.approx(shed / issued,
                                                    abs=1e-4)
    assert report["goodput"] == pytest.approx(completed / duration,
                                              abs=1e-2)
    assert report["bytes"] == 128 * completed


def test_errors_are_recorded_not_raised():
    schedule = build_schedule(parse_phases("steady:50x1"),
                              ZipfTiles(2, seed=0), seed=0)
    timebase = VirtualTimebase()
    recorder = StormRecorder()

    async def broken(level, i, j):
        raise ConnectionError("synthetic transport failure")

    _drive(OpenLoopRunner(schedule, broken, recorder, timebase=timebase),
           timebase)
    assert recorder.registry.counter_value(
        obs_names.LOADGEN_ERRORS) == len(schedule)


def test_virtual_timebase_wakes_in_deadline_order():
    timebase = VirtualTimebase()
    woke: list[tuple[str, float]] = []

    async def sleeper(name: str, dt: float) -> None:
        await timebase.sleep(dt)
        woke.append((name, timebase.now()))

    async def main() -> None:
        task = asyncio.ensure_future(asyncio.gather(
            sleeper("c", 3.0), sleeper("a", 1.0), sleeper("b", 2.0)))
        await timebase.drain(until=task)

    asyncio.run(main())
    assert woke == [("a", 1.0), ("b", 2.0), ("c", 3.0)]
