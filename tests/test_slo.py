"""SLO burn rates on a virtual clock (fire / hold / recover), latency
threshold bucketing, and the worker straggler detector."""

import math

import pytest

from distributedmandelbrot_tpu.coordinator.clock import ManualClock
from distributedmandelbrot_tpu.obs import names as obs_names
from distributedmandelbrot_tpu.obs.metrics import Registry
from distributedmandelbrot_tpu.obs.slo import (AvailabilitySLO, LatencySLO,
                                               burn_rate, detect_stragglers,
                                               standard_slos)
from distributedmandelbrot_tpu.obs.timeseries import TimeseriesSampler


# -- burn-rate arithmetic --------------------------------------------------


def test_burn_rate_math():
    # 1.0 = spending the error budget exactly on schedule.
    assert burn_rate(99, 1, 0.99) == pytest.approx(1.0)
    assert burn_rate(90, 10, 0.99) == pytest.approx(10.0)
    assert burn_rate(0, 0, 0.99) == 0.0
    # Zero budget: any error is an infinite burn, no errors is none.
    assert burn_rate(5, 1, 1.0) == math.inf
    assert burn_rate(5, 0, 1.0) == 0.0


def test_slo_rejects_bad_objective():
    reg = Registry()
    sampler = TimeseriesSampler(reg)
    with pytest.raises(ValueError, match="objective"):
        AvailabilitySLO(sampler, objective=1.5)


# -- availability SLO state machine on a virtual clock ---------------------


class _Farm:
    """Manual-clock sampler fed synthetic gateway request outcomes."""

    def __init__(self, **slo_kwargs):
        self.reg = Registry()
        self.clk = ManualClock()
        self.sampler = TimeseriesSampler(self.reg, period=1.0,
                                         window=120.0, clock=self.clk.now)
        kwargs = dict(objective=0.99, fast_window=10.0, slow_window=60.0,
                      burn_threshold=10.0)
        kwargs.update(slo_kwargs)
        self.slo = AvailabilitySLO(self.sampler, **kwargs)

    def step(self, good=0, bad=0):
        for _ in range(good):
            self.reg.observe(obs_names.HIST_GATEWAY_REQUEST_SECONDS, 0.01,
                             labels={"outcome": obs_names.OUTCOME_TIER1})
        for _ in range(bad):
            self.reg.observe(obs_names.HIST_GATEWAY_REQUEST_SECONDS, 0.01,
                             labels={"outcome":
                                     obs_names.OUTCOME_REJECTED})
        self.clk.advance(1.0)
        self.sampler.sample()


def test_availability_slo_fire_hold_recover():
    farm = _Farm()
    slo = farm.slo
    for _ in range(5):  # t=1..5: healthy traffic
        farm.step(good=10)
    doc = slo.evaluate()
    assert doc["state"] == "ok" and slo.fired == 0
    assert doc["fast"]["burn"] == 0.0

    for _ in range(7):  # t=6..12: half the requests bounce
        farm.step(good=5, bad=5)
    doc = slo.evaluate()
    # Fast AND slow windows both over threshold -> fire, once.
    assert doc["state"] == "firing"
    assert slo.fired == 1
    assert doc["fast"]["burn"] >= 10.0
    assert doc["slow"]["burn"] >= 10.0
    assert farm.reg.counter_value(
        obs_names.SLO_ALERTS_FIRED,
        labels={"slo": slo.name}) == 1

    for _ in range(11):  # t=13..23: healthy again
        farm.step(good=10)
    doc = slo.evaluate()
    # Fast window clean, slow window still burning: hold, not recovered.
    assert doc["state"] == "hold"
    assert doc["fast"]["burn"] < 10.0
    assert doc["slow"]["burn"] >= 10.0
    assert slo.recovered == 0

    for _ in range(72):  # t=24..95: the bad samples age out of 60s
        farm.step(good=10)
    doc = slo.evaluate()
    assert doc["state"] == "ok"
    assert slo.recovered == 1
    assert farm.reg.counter_value(
        obs_names.SLO_ALERTS_RECOVERED,
        labels={"slo": slo.name}) == 1
    # Burn gauges carry the per-window values for /varz and the fleet.
    assert farm.reg.gauge(obs_names.GAUGE_SLO_BURN,
                          labels={"slo": slo.name,
                                  "window": "fast"}).read() == 0.0


def test_availability_slo_refire_from_hold_counts_once():
    farm = _Farm()
    slo = farm.slo
    for _ in range(5):
        farm.step(good=10)
    for _ in range(7):
        farm.step(good=5, bad=5)
    assert slo.evaluate()["state"] == "firing"
    for _ in range(11):
        farm.step(good=10)
    assert slo.evaluate()["state"] == "hold"
    for _ in range(5):  # errors return while the slow window still burns
        farm.step(bad=10)
    doc = slo.evaluate()
    # hold -> firing is a re-entry, not a new alert: fired stays 1.
    assert doc["state"] == "firing"
    assert slo.fired == 1
    assert slo.recovered == 0


def test_availability_slo_quiet_farm_never_fires():
    farm = _Farm()
    for _ in range(30):
        farm.step()  # no traffic at all
        assert farm.slo.evaluate()["state"] == "ok"
    assert farm.slo.fired == 0


# -- latency SLO -----------------------------------------------------------


def test_latency_slo_threshold_bucketing():
    reg = Registry()
    clk = ManualClock()
    sampler = TimeseriesSampler(reg, period=1.0, window=120.0,
                                clock=clk.now)
    slo = LatencySLO(sampler, threshold_s=0.1024, objective=0.95,
                     fast_window=10.0, slow_window=60.0)
    assert slo.name == "gateway_latency_0.1024s"
    # Window counts are first-vs-last deltas, so the family must exist
    # in the opening cut (a live gateway registers it at startup).
    reg.histogram(obs_names.HIST_GATEWAY_REQUEST_SECONDS)
    clk.advance(1.0)
    sampler.sample()
    for _ in range(8):
        reg.observe(obs_names.HIST_GATEWAY_REQUEST_SECONDS, 0.05)
    # Exactly on the threshold (a DEFAULT_BUCKETS bound, 1e-4 * 2^10):
    # still good — the bound's bucket is included despite float noise.
    reg.observe(obs_names.HIST_GATEWAY_REQUEST_SECONDS, 0.1024)
    reg.observe(obs_names.HIST_GATEWAY_REQUEST_SECONDS, 1.0)
    clk.advance(1.0)
    sampler.sample()
    wb = slo.window_burn(10.0)
    assert (wb.good, wb.bad) == (9, 1)
    assert wb.error_rate == pytest.approx(0.1)
    assert wb.burn == pytest.approx(0.1 / 0.05)


def test_standard_slos_pair():
    reg = Registry()
    sampler = TimeseriesSampler(reg)
    slos = standard_slos(sampler)
    assert [s.name for s in slos] == ["gateway_availability",
                                     "gateway_latency_0.1024s"]
    for slo in slos:
        assert slo.evaluate()["state"] == "ok"


# -- straggler detection ---------------------------------------------------


def _worker_row(wid, tiles, compute_per_tile, persist_per_tile=0.2):
    return {"worker": wid, "tiles": tiles,
            "compute_s": compute_per_tile * tiles,
            "lease_to_persist_s": persist_per_tile * tiles}


def test_detect_stragglers_one_slow_of_four():
    rows = [_worker_row("w1", 10, 0.10), _worker_row("w2", 12, 0.11),
            _worker_row("w3", 9, 0.09),
            _worker_row("w4", 10, 1.00, persist_per_tile=1.5)]
    flagged = detect_stragglers(rows)
    assert set(flagged) == {"w4"}
    assert "slow_compute" in flagged["w4"]
    assert "lease_to_persist_skew" in flagged["w4"]


def test_detect_stragglers_needs_enough_peers():
    rows = [_worker_row("w1", 10, 0.1), _worker_row("w2", 10, 1.0)]
    # A median of two is meaningless: no verdicts.
    assert detect_stragglers(rows) == {}


def test_detect_stragglers_absolute_floor_mutes_noise():
    # 10x outlier among microsecond medians is noise, not a straggler.
    rows = [_worker_row(f"w{i}", 10, 1e-6, persist_per_tile=1e-6)
            for i in range(3)]
    rows.append(_worker_row("w9", 10, 1e-5, persist_per_tile=1e-5))
    assert detect_stragglers(rows) == {}


def test_detect_stragglers_skips_thin_workers():
    # A worker with one tile has no meaningful per-tile statistic.
    rows = [_worker_row("w1", 10, 0.1), _worker_row("w2", 10, 0.1),
            _worker_row("w3", 10, 0.1), _worker_row("slow", 1, 50.0)]
    assert detect_stragglers(rows) == {}
