"""Fleet aggregation: peer parsing, role merging, aggregator-side
rates, scrape-failure robustness (fuzzed bodies), the bounded fetch,
the standalone FleetService, and the `dmtpu top` renderer."""

import json
import threading
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import pytest

from distributedmandelbrot_tpu.coordinator.clock import ManualClock
from distributedmandelbrot_tpu.core.geometry import CHUNK_PIXELS
from distributedmandelbrot_tpu.obs import names as obs_names
from distributedmandelbrot_tpu.obs.fleet import (FleetAggregator,
                                                 FleetService, ScrapeError,
                                                 http_fetch,
                                                 parse_peer_spec)
from distributedmandelbrot_tpu.obs.metrics import Registry
from distributedmandelbrot_tpu.obs.top import render_top


# -- peer specs ------------------------------------------------------------


def test_parse_peer_spec():
    assert parse_peer_spec("10.0.0.1:9000") == \
        ("http://10.0.0.1:9000", None)
    assert parse_peer_spec("shard@10.0.0.1:9000") == \
        ("http://10.0.0.1:9000", "shard")
    assert parse_peer_spec("http://h:1/") == ("http://h:1", None)
    assert parse_peer_spec("gateway@https://h:1") == \
        ("https://h:1", "gateway")
    assert parse_peer_spec("@h:1") == ("http://h:1", None)


def test_from_ring_skips_exporterless_shards():
    from distributedmandelbrot_tpu.control.ring import HashRing, ShardInfo

    ring = HashRing([ShardInfo("10.0.0.1", 1, exporter_port=9100),
                     ShardInfo("10.0.0.2", 1)])  # no exporter bound
    agg = FleetAggregator.from_ring(ring)
    assert agg.peer_urls == ["http://10.0.0.1:9100"]


# -- a scriptable fetch ----------------------------------------------------


def make_fetch(responses):
    """``responses[base_url][endpoint]`` -> dict/bytes to serve, an
    Exception to raise, or a zero-arg callable producing either."""

    def fetch(url, timeout=2.0, max_bytes=0):
        for endpoint in ("/varz", "/timeseries"):
            if endpoint in url:
                base = url.split(endpoint)[0]
                body = responses.get(base, {}).get(endpoint[1:])
                break
        else:
            raise AssertionError(f"unexpected scrape url {url}")
        if callable(body):
            body = body()
        if body is None:
            raise ScrapeError("connection refused")
        if isinstance(body, Exception):
            raise body
        if isinstance(body, (dict, list)):
            body = (json.dumps(body) + "\n").encode()
        return body

    return fetch


def shard_varz(grants, saved, *, shard=0, workers=None, slo=None):
    return {
        "shard": {"shard": shard, "n_shards": 2},
        "scheduler": {"completed": 5, "total": 64},
        "counters": {obs_names.COORD_WORKLOADS_GRANTED: grants,
                     obs_names.COORD_CHUNKS_SAVED: saved},
        "gauges": {obs_names.GAUGE_FRONTIER_DEPTH: 7.0,
                   obs_names.GAUGE_OUTSTANDING_LEASES: 3.0,
                   obs_names.GAUGE_PERSIST_QUEUE_DEPTH: 2.0},
        "workers": workers or {},
        "slo": slo or [],
    }


def gateway_varz(queries):
    return {
        "role": "gateway",
        "counters": {
            obs_names.GATEWAY_QUERIES: queries,
            obs_names.GATEWAY_SERVED + "{outcome=tier1_hit}": queries,
        },
        "gauges": {obs_names.GAUGE_TIER1_HIT_RATIO: 0.75,
                   obs_names.GAUGE_RENDER_HIT_RATIO: 0.5,
                   obs_names.GAUGE_SESSIONS_ACTIVE: 2},
        "histograms": {
            obs_names.HIST_GATEWAY_REQUEST_SECONDS
            + "{outcome=tier1_hit}": {"count": queries, "sum": 0.1},
        },
    }


def worker_row(tiles, compute_s, persist_s=1.0):
    return {"tiles": tiles, "compute_s": compute_s, "upload_s": 0.1,
            "lease_to_persist_s": persist_s}


# -- merging and rates -----------------------------------------------------


def test_fleet_merges_roles_rates_and_totals():
    clk = ManualClock()
    counts = {"grants": 100, "saved": 50, "queries": 10}
    responses = {
        "http://s0:1": {"varz": lambda: shard_varz(
            counts["grants"], counts["saved"], shard=0)},
        "http://s1:1": {"varz": lambda: shard_varz(
            counts["grants"], counts["saved"], shard=1)},
        "http://g0:1": {
            "varz": lambda: gateway_varz(counts["queries"]),
            "timeseries": {"name": "gateway_request_seconds",
                           "kind": "histogram",
                           "window_p50": 0.002, "window_p99": 0.05},
        },
        # http://dead:1 has no entry: every fetch raises.
    }
    agg = FleetAggregator(
        ["s0:1", "shard@s1:1", "g0:1", "worker@dead:1"],
        fetch=make_fetch(responses), clock=clk.now, rate_window=60.0)
    agg.scrape_once()
    clk.advance(10.0)
    counts.update(grants=200, saved=150, queries=110)
    agg.scrape_once()

    snap = agg.snapshot()
    assert snap["roles"]["shard"] == {"count": 2, "healthy": 2}
    assert snap["roles"]["gateway"] == {"count": 1, "healthy": 1}
    # The dead peer keeps its spec's role hint and reads unhealthy.
    assert snap["roles"]["worker"]["healthy"] == 0

    # Rates are aggregator-side counter deltas: (200-100)/10s per shard.
    totals = snap["totals"]
    assert totals["grants_per_s"] == pytest.approx(20.0)
    assert totals["tiles_per_s"] == pytest.approx(20.0)
    assert totals["queries_per_s"] == pytest.approx(10.0)
    assert totals["mpix_per_s"] == pytest.approx(
        20.0 * CHUNK_PIXELS / 1e6, rel=1e-3)
    assert totals["completed"] == 10
    assert totals["total_tiles"] == 128
    assert totals["persist_queue_depth"] == 4.0

    [s0, s1] = snap["shards"]
    assert (s0["shard"], s1["shard"]) == (0, 1)
    assert s0["grants_per_s"] == pytest.approx(10.0)
    assert s0["frontier_depth"] == 7.0

    [gw] = snap["gateways"]
    assert gw["queries_per_s"] == pytest.approx(10.0)
    assert gw["tier1_hit_ratio"] == 0.75
    # Windowed percentiles ride the peer's /timeseries document.
    assert gw["p50_s"] == 0.002
    assert gw["p99_s"] == 0.05

    dead = [p for p in snap["peers"] if "dead" in p["url"]][0]
    assert dead["stale"] and not dead["healthy"]
    assert dead["errors"] == 2
    assert agg.registry.counter_value(obs_names.FLEET_SCRAPE_ERRORS) == 2
    assert agg.registry.counter_value(obs_names.FLEET_SCRAPES) == 2


def test_fleet_merges_multihomed_workers_and_flags_stragglers():
    clk = ManualClock()
    responses = {
        # w_both reports through both shards (multi-homed): sums.
        "http://s0:1": {"varz": shard_varz(1, 1, shard=0, workers={
            "w_both": worker_row(10, 1.0),
            "w_a": worker_row(10, 1.0),
            "w_slow": worker_row(10, 100.0, persist_s=200.0)})},
        "http://s1:1": {"varz": shard_varz(1, 1, shard=1, workers={
            "w_both": worker_row(5, 0.5),
            "w_b": worker_row(10, 1.0)})},
    }
    agg = FleetAggregator(["s0:1", "s1:1"], fetch=make_fetch(responses),
                          clock=clk.now)
    agg.scrape_once()
    snap = agg.snapshot()
    rows = {w["worker"]: w for w in snap["workers"]}
    assert rows["w_both"]["tiles"] == 15
    assert rows["w_both"]["via"] == ["http://s0:1", "http://s1:1"]
    assert rows["w_both"]["compute_s_per_tile"] == pytest.approx(0.1)
    assert rows["w_slow"]["straggler"]
    assert "slow_compute" in rows["w_slow"]["straggler_reasons"]
    assert not rows["w_a"]["straggler"]
    assert snap["stragglers"] == ["w_slow"]
    assert snap["roles"]["worker"]["count"] == 4
    assert agg.registry.gauge(
        obs_names.GAUGE_FLEET_STRAGGLERS).read() == 1.0


def test_fleet_summarizes_slo_worst_case():
    slo_doc = lambda state, fast, slow: [{
        "name": "gateway_availability", "objective": 0.99,
        "state": state, "fast": {"burn": fast}, "slow": {"burn": slow}}]
    responses = {
        "http://s0:1": {"varz": shard_varz(
            1, 1, shard=0, slo=slo_doc("ok", 0.1, 0.2))},
        "http://s1:1": {"varz": shard_varz(
            1, 1, shard=1, slo=slo_doc("firing", 25.0, 12.0))},
    }
    agg = FleetAggregator(["s0:1", "s1:1"], fetch=make_fetch(responses))
    agg.scrape_once()
    slo = agg.snapshot()["slo"]
    assert slo["worst_state"] == "firing"
    [entry] = slo["slos"]
    assert entry["peers"] == 2
    assert entry["state"] == "firing"
    assert entry["fast_burn"] == 25.0
    assert entry["slow_burn"] == 12.0


# -- robustness fuzz -------------------------------------------------------


FUZZ_BODIES = [
    b"not json at all",
    b'{"truncated": ',
    b"[1, 2, 3]",             # JSON, but not an object
    b'"a string"',
    b"\xff\xfe\x00garbage",   # undecodable bytes
    b"",
    ScrapeError("body exceeds 4194304 bytes"),   # http_fetch's bound
    ScrapeError("connection refused"),
    OSError("socket burst into flames"),
]


def test_fleet_survives_fuzzed_peer_bodies():
    responses = {f"http://p{i}:1": {"varz": body}
                 for i, body in enumerate(FUZZ_BODIES)}
    agg = FleetAggregator([f"p{i}:1" for i in range(len(FUZZ_BODIES))],
                          fetch=make_fetch(responses))
    for _ in range(2):
        agg.scrape_once()   # must not raise
    snap = agg.snapshot()   # must not raise either
    assert len(snap["peers"]) == len(FUZZ_BODIES)
    assert all(p["stale"] and not p["healthy"] for p in snap["peers"])
    assert all(p["last_error"] for p in snap["peers"])
    assert snap["shards"] == [] and snap["gateways"] == []
    assert agg.registry.counter_value(
        obs_names.FLEET_SCRAPE_ERRORS) == 2 * len(FUZZ_BODIES)
    assert agg.registry.gauge(obs_names.GAUGE_FLEET_PEERS).read() == \
        len(FUZZ_BODIES)
    assert agg.registry.gauge(
        obs_names.GAUGE_FLEET_PEERS_STALE).read() == len(FUZZ_BODIES)


def test_fleet_version_skew_degrades_gracefully():
    # A gateway that predates /timeseries: rates still merge, only the
    # percentile columns go dark.
    responses = {"http://old:1": {
        "varz": gateway_varz(50),
        "timeseries": ScrapeError("404 not found"),
    }}
    agg = FleetAggregator(["old:1"], fetch=make_fetch(responses))
    agg.scrape_once()
    snap = agg.snapshot()
    [gw] = snap["gateways"]
    assert gw["p50_s"] is None and gw["p99_s"] is None
    assert snap["peers"][0]["healthy"]
    # The skewed /timeseries is not a scrape error — never registered.
    assert not agg.registry.counter_value(obs_names.FLEET_SCRAPE_ERRORS)


def test_fleet_peer_going_dark_turns_stale_not_fatal():
    state = {"alive": True}
    responses = {"http://flap:1": {
        "varz": lambda: (shard_varz(1, 1) if state["alive"]
                         else ScrapeError("connection refused"))}}
    agg = FleetAggregator(["flap:1"], fetch=make_fetch(responses))
    agg.scrape_once()
    assert agg.snapshot()["peers"][0]["healthy"]
    state["alive"] = False
    agg.scrape_once()
    peer = agg.snapshot()["peers"][0]
    # One miss: unhealthy but not yet stale (scrape jitter tolerance).
    assert not peer["healthy"] and not peer["stale"]
    agg.scrape_once()
    peer = agg.snapshot()["peers"][0]
    assert peer["stale"] and "refused" in peer["last_error"]
    # The last good varz is retained, so the role survives the outage.
    assert peer["role"] == "shard"


# -- the bounded fetch against a real socket -------------------------------


class _Handler(BaseHTTPRequestHandler):
    def do_GET(self):
        body = b"x" * (4096 if self.path == "/big" else 16)
        self.send_response(200)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def log_message(self, *a):
        pass


def test_http_fetch_bounds_and_failures():
    server = ThreadingHTTPServer(("127.0.0.1", 0), _Handler)
    thread = threading.Thread(target=server.serve_forever, daemon=True)
    thread.start()
    try:
        base = f"http://127.0.0.1:{server.server_address[1]}"
        assert http_fetch(base + "/small") == b"x" * 16
        with pytest.raises(ScrapeError, match="exceeds"):
            http_fetch(base + "/big", max_bytes=1024)
        with pytest.raises(ScrapeError):
            http_fetch("http://127.0.0.1:1/varz", timeout=0.5)
    finally:
        server.shutdown()
        server.server_close()


# -- FleetService over real HTTP (jax-free) --------------------------------


def test_fleet_service_scrapes_a_live_exporter():
    from distributedmandelbrot_tpu.obs.exporter import ExporterThread

    reg = Registry()
    reg.inc(obs_names.COORD_WORKLOADS_GRANTED, 3)
    peer = ExporterThread(reg, varz_extra=lambda: {
        "role": "shard", "shard": {"shard": 0, "n_shards": 1}})
    peer.start()
    service = None
    try:
        agg = FleetAggregator([f"shard@127.0.0.1:{peer.port}"],
                              timeout=5.0)
        service = FleetService(agg, scrape_period=0.05)
        service.start()
        deadline = threading.Event()
        snap = {}
        for _ in range(100):
            body = urllib.request.urlopen(
                f"http://127.0.0.1:{service.port}/fleet",
                timeout=10).read()
            snap = json.loads(body)
            if snap.get("peers") and snap["peers"][0]["healthy"]:
                break
            deadline.wait(0.1)
        assert snap["peers"][0]["healthy"]
        assert snap["roles"]["shard"]["count"] == 1
        assert [s["shard"] for s in snap["shards"]] == [0]
        varz = json.loads(urllib.request.urlopen(
            f"http://127.0.0.1:{service.port}/varz", timeout=10).read())
        assert varz["role"] == "fleet"
    finally:
        if service is not None:
            service.stop()
        peer.stop()


# -- the dashboard renderer ------------------------------------------------


def _rich_snapshot():
    clk = ManualClock()
    responses = {
        "http://s0:1": {"varz": lambda: shard_varz(
            grants.get("n", 100), grants.get("n", 100), shard=0,
            workers={"w1": worker_row(10, 1.0),
                     "w2": worker_row(10, 1.0),
                     "w3": worker_row(10, 1.0),
                     "w_slow": worker_row(10, 99.0, persist_s=150.0)},
            slo=[{"name": "gateway_availability", "objective": 0.99,
                  "state": "firing", "fast": {"burn": 42.0},
                  "slow": {"burn": 17.0}}])},
        "http://g0:1": {"varz": lambda: gateway_varz(grants.get("n", 10)),
                        "timeseries": {"window_p50": 0.002,
                                       "window_p99": 0.05}},
    }
    grants = {"n": 100}
    agg = FleetAggregator(["s0:1", "g0:1", "shard@dead:1"],
                          fetch=make_fetch(responses), clock=clk.now)
    agg.scrape_once()
    clk.advance(10.0)
    grants["n"] = 200
    agg.scrape_once()
    return agg.snapshot()


def test_render_top_plain_and_color():
    snap = _rich_snapshot()
    plain = render_top(snap, color=False)
    assert "\x1b[" not in plain          # grep-able without a tty
    assert "dmtpu top" in plain
    assert "3 peers" in plain
    assert "SHARD" in plain and "GATEWAY" in plain and "WORKER" in plain
    assert "gateway_availability" in plain and "firing" in plain
    assert "w_slow" in plain
    assert "YES slow_compute,lease_to_persist_skew" in plain
    assert "UNHEALTHY PEERS" in plain and "dead:1" in plain
    color = render_top(snap, color=True)
    assert "\x1b[31m" in color           # firing / stragglers in red


def test_render_top_empty_snapshot():
    out = render_top({}, color=False)
    assert "0 peers" in out
    assert out.endswith("\n")
