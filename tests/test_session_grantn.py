"""Batched lease grants (FRAME_LEASE_REQN/GRANTN, SESSION_FLAG_GRANTN):
one round trip carries up to N leases grouped to the fusion width, the
accept path group-commits them through the persist queue, and the farm
output stays bit-identical to the unbatched legacy path."""

import time

import numpy as np

from distributedmandelbrot_tpu.core import LevelSetting
from distributedmandelbrot_tpu.core.geometry import CHUNK_PIXELS
from distributedmandelbrot_tpu.net import protocol as proto
from distributedmandelbrot_tpu.obs import names as obs_names
from distributedmandelbrot_tpu.utils.metrics import Counters
from distributedmandelbrot_tpu.viewer import DataClient, FetchStatus
from distributedmandelbrot_tpu.worker import (DistributerClient,
                                              NativeBackend, NumpyBackend,
                                              Worker)
from distributedmandelbrot_tpu.worker.client import DistributerSession

from harness import CoordinatorHarness

MAX_ITER = 24


def _fast_exact_backend():
    """Native C++ backend when this host can build it (bit-identical to
    the golden NumpyBackend, ~50x faster on full chunks); the golden
    numpy path otherwise, so the farm tests run — just slower —
    everywhere."""
    try:
        return NativeBackend()
    except Exception:
        return NumpyBackend()


def _checker(value_a=0, value_b=200, period=4096):
    """A compressible-but-nontrivial tile: long runs of two values."""
    tile = np.full(CHUNK_PIXELS, value_a, dtype=np.uint8)
    tile.reshape(-1, period)[::2] = value_b
    return tile


# -- direct batched exchange -------------------------------------------------

def test_session_grantn_single_round_trip_and_group_commit(tmp_path):
    """One REQN round trip leases a whole level; the uploads land through
    the group-commit writer as a handful of multi-tile flushes."""
    with CoordinatorHarness(str(tmp_path), [LevelSetting(2, MAX_ITER)]) \
            as farm:
        counters = Counters()
        sess = DistributerSession("127.0.0.1", farm.distributer_port,
                                  counters=counters)
        assert sess.connect()
        assert sess.flags & proto.SESSION_FLAG_GRANTN
        rtts_before = counters.get(obs_names.WORKER_WIRE_RTTS)
        grants = sess.request_batchn(4, batch_width=2)
        # All four tiles of the 2x2 level arrived in ONE round trip,
        # grouped into fusion-width batches server-side.
        assert len(grants) == 4
        assert len({w.key for w in grants}) == 4
        assert counters.get(obs_names.WORKER_WIRE_RTTS) == rtts_before + 1
        assert farm.counters.get(obs_names.COORD_GRANT_BATCHES) == 1

        tile = _checker()
        accepted, piggyback = sess.submit_pipelined(
            [(w, tile) for w in grants])
        assert accepted == [True] * 4
        assert piggyback == []  # frontier drained by the batched grant
        sess.close()
        farm.wait_saves_settled(expected_accepted=4)
        assert farm.scheduler.is_complete()

        # Group commit: every accepted tile went through put_many, and
        # flush sizes sum to the tile count (fewer commits than tiles
        # when the queue coalesces; never more).
        commits = farm.counters.get(obs_names.STORE_GROUP_COMMITS)
        flushed = farm.counters.get(obs_names.STORE_FLUSH_TILES)
        assert commits >= 1
        assert flushed == 4
        assert commits <= flushed

        fetch = DataClient("127.0.0.1", farm.dataserver_port).fetch
        for w in grants:
            pixels, status = fetch(w.level, w.index_real, w.index_imag)
            assert status is FetchStatus.OK
            np.testing.assert_array_equal(pixels, tile)


def test_session_grantn_empty_frontier_returns_no_grants(tmp_path):
    with CoordinatorHarness(str(tmp_path), [LevelSetting(1, MAX_ITER)]) \
            as farm:
        sess = DistributerSession("127.0.0.1", farm.distributer_port,
                                  counters=Counters())
        assert sess.connect()
        first = sess.request_batchn(8)
        assert len(first) == 1  # the only tile
        # Frontier empty now: a well-formed REQN draws an empty GRANTN,
        # not an error, and the session stays usable.  Empty probes do
        # not count as grant batches.
        assert sess.request_batchn(8) == []
        assert farm.counters.get(obs_names.COORD_GRANT_BATCHES) == 1
        accepted, _ = sess.submit_pipelined([(first[0], _checker())])
        assert accepted == [True]
        sess.close()
        farm.wait_saves_settled(expected_accepted=1)


def test_session_grantn_opt_out_negotiates_down(tmp_path):
    """A client built with grantn=False never offers the capability;
    request_batchn transparently degrades to the per-batch legacy
    exchange and the coordinator mints zero batched grants."""
    with CoordinatorHarness(str(tmp_path), [LevelSetting(2, MAX_ITER)]) \
            as farm:
        sess = DistributerSession("127.0.0.1", farm.distributer_port,
                                  grantn=False, counters=Counters())
        assert sess.connect()
        assert not sess.flags & proto.SESSION_FLAG_GRANTN
        grants = sess.request_batchn(3)
        assert len(grants) == 3  # served by the plain LEASE_REQ path
        sess.close()
        assert farm.counters.get(obs_names.COORD_GRANT_BATCHES) == 0


# -- pipelined farm over batched grants --------------------------------------

def test_pipelined_farm_batched_grants_cut_round_trips(tmp_path):
    """A 3x3 level through the pipelined numpy worker: batched grants
    keep blocking round trips below one per tile (the perf contract the
    bench's grants-per-RTT figure reports)."""
    with CoordinatorHarness(str(tmp_path), [LevelSetting(3, MAX_ITER)]) \
            as farm:
        worker = Worker(
            DistributerClient("127.0.0.1", farm.distributer_port),
            _fast_exact_backend(), batch_size=3, window=6, upload_lanes=2,
            grant_batch=6)
        worker.run_until_drained()
        farm.wait_saves_settled(expected_accepted=9)
        assert farm.scheduler.is_complete()
        assert worker.counters.get(obs_names.WORKER_SESSION_FALLBACKS) == 0
        # The level's 9 tiles were minted in a handful of batched grants
        # (the rest piggyback on upload acks), never one-per-exchange.
        batches = farm.counters.get(obs_names.COORD_GRANT_BATCHES)
        assert 1 <= batches <= 4
        # Blocking round trips stay bounded near one per tile even when
        # uploads fragment (the legacy path pays ~3 per tile).
        rtts = worker.counters.get(obs_names.WORKER_WIRE_RTTS)
        assert 0 < rtts <= 2 * 9
        # Round-robin lane feed: neither lane starved.
        lanes = worker.pipeline.stage_stats()["lanes"]
        assert len(lanes) == 2
        assert all(ls["items"] > 0 for ls in lanes)


class _SlowBackend:
    """NumpyBackend that out-waits the coordinator's idle deadline
    between batches (a stand-in for any backend whose tiles take longer
    than the read timeout)."""

    def __init__(self, inner, delay_s):
        self._inner = inner
        self._delay_s = delay_s

    def compute_batch(self, workloads):
        time.sleep(self._delay_s)
        return self._inner.compute_batch(workloads)


def test_pipelined_farm_redials_idle_closed_session(tmp_path):
    """The coordinator drops sessions idle past its read deadline by
    design; a worker whose backend out-waits it between batches must
    re-dial and finish the level instead of dying on the broken pipe."""
    with CoordinatorHarness(str(tmp_path), [LevelSetting(2, 8)],
                            read_timeout=0.2) as farm:
        worker = Worker(
            DistributerClient("127.0.0.1", farm.distributer_port),
            _SlowBackend(_fast_exact_backend(), 0.5), batch_size=1,
            window=1)
        worker.run_until_drained()
        farm.wait_saves_settled(expected_accepted=4)
        assert farm.scheduler.is_complete()
        assert worker.counters.get(obs_names.WORKER_SESSION_REDIALS) >= 1
        # A re-dial is a recovery, not a downgrade: the lanes stayed on
        # the session tier throughout.
        assert worker.counters.get(obs_names.WORKER_SESSION_FALLBACKS) == 0


def test_farm_batched_output_bit_identical_to_legacy(tmp_path):
    """Golden parity through real sockets: the batched-grant session
    farm and the legacy connection-per-exchange farm must land byte-
    identical tiles for the whole level."""
    (tmp_path / "legacy").mkdir()
    with CoordinatorHarness(str(tmp_path / "legacy"),
                            [LevelSetting(2, MAX_ITER)]) as farm:
        worker = Worker(
            DistributerClient("127.0.0.1", farm.distributer_port),
            _fast_exact_backend(), batch_size=2, window=4,
            use_session=False)
        worker.run_until_drained()
        farm.wait_saves_settled(expected_accepted=4)
        assert farm.counters.get(obs_names.COORD_GRANT_BATCHES) == 0
        fetch = DataClient("127.0.0.1", farm.dataserver_port).fetch
        golden = {(ir, ii): fetch(2, ir, ii)[0]
                  for ir in range(2) for ii in range(2)}

    (tmp_path / "batched").mkdir()
    with CoordinatorHarness(str(tmp_path / "batched"),
                            [LevelSetting(2, MAX_ITER)]) as farm2:
        worker = Worker(
            DistributerClient("127.0.0.1", farm2.distributer_port),
            _fast_exact_backend(), batch_size=2, window=4, upload_lanes=2,
            grant_batch=4)
        worker.run_until_drained()
        farm2.wait_saves_settled(expected_accepted=4)
        assert farm2.counters.get(obs_names.COORD_GRANT_BATCHES) >= 1
        fetch = DataClient("127.0.0.1", farm2.dataserver_port).fetch
        for (ir, ii), golden_pixels in golden.items():
            pixels, status = fetch(2, ir, ii)
            assert status is FetchStatus.OK
            np.testing.assert_array_equal(pixels, golden_pixels)
