"""Flight recorder (obs/flight.py) + postmortem assembler
(obs/postmortem.py): virtual-clock recorder units, dump exit paths, the
corrupt-dump fuzz corpus, clock alignment and the anomaly detectors.

No jax, no sockets (the cross-process kill e2e lives in
tests/test_chaos.py): everything here drives the recorder with manual
clocks and hand-built dump directories, so the suite pins the exact
semantics the chaos postmortem depends on.
"""

import json
import os
import random
import sys

import pytest

from distributedmandelbrot_tpu.coordinator.clock import ManualClock
from distributedmandelbrot_tpu.obs import events as obs_events
from distributedmandelbrot_tpu.obs import flight, postmortem
from distributedmandelbrot_tpu.obs import names as obs_names
from distributedmandelbrot_tpu.obs.flight import (DUMP_KIND, DUMP_VERSION,
                                                  FlightRecorder)
from distributedmandelbrot_tpu.obs.metrics import Registry


def _recorder(capacity=16, *, caps=None, cap_window=1.0, role="test"):
    clock = ManualClock(start=100.0)
    wall = ManualClock(start=1_700_000_000.0)
    rec = FlightRecorder(capacity, role=role, clock=clock.now,
                         wall=wall.now, caps=caps, cap_window=cap_window)
    return rec, clock, wall


# -- recorder ring ----------------------------------------------------------


def test_ring_is_bounded_and_seq_is_monotonic():
    rec, clock, _ = _recorder(capacity=4, caps={})
    for i in range(10):
        clock.advance(0.1)
        rec.note(obs_events.SCHED_GRANT, key=(1, 0, i), lease=i)
    assert len(rec) == 4
    assert rec.recorded == 10
    assert rec.dropped == 6  # ring overflow only; no caps armed
    events = rec.tail(10)
    assert [e["seq"] for e in events] == [7, 8, 9, 10]
    assert [e["key"][2] for e in events] == [6, 7, 8, 9]


def test_capacity_must_be_positive():
    with pytest.raises(ValueError):
        FlightRecorder(0)


def test_sampling_caps_bound_hot_category_per_window():
    rec, clock, _ = _recorder(caps={"sched": 2}, cap_window=1.0)
    for _ in range(5):
        rec.note(obs_events.SCHED_GRANT)
    assert len(rec) == 2
    assert rec.dropped == 3
    # The rare family is uncapped even while sched is saturated.
    rec.note(obs_events.CKPT_DONE)
    assert len(rec) == 3
    # A new wall-second opens a fresh budget.
    clock.advance(1.0)
    rec.note(obs_events.SCHED_GRANT)
    assert len(rec) == 4
    assert rec.dropped == 3


def test_event_doc_omits_empty_fields():
    rec, _, _ = _recorder()
    rec.note(obs_events.CKPT_DONE)
    rec.note(obs_events.SCHED_GRANT, key=(2, 1, 1), lease=7, worker=3)
    bare, full = rec.tail(2)
    assert "key" not in bare and "lease" not in bare and "kv" not in bare
    assert full["key"] == [2, 1, 1]
    assert full["lease"] == 7
    assert full["kv"] == {"worker": 3}
    assert full["cat"] == "sched"


def test_header_carries_anchor_pair_and_identity():
    rec, clock, wall = _recorder(role="shard-1")
    rec.shard = 1
    rec.worker_id = "00000000000000ab"
    rec.offsets_fn = lambda: {"00000000000000cd": {"offset": 0.5,
                                                   "error": 0.01}}
    clock.advance(3.0)
    wall.advance(3.0)
    h = rec.header(reason="unit")
    assert h["kind"] == DUMP_KIND and h["v"] == DUMP_VERSION
    assert h["role"] == "shard-1" and h["shard"] == 1
    assert h["worker_id"] == "00000000000000ab"
    assert h["mono0"] == 103.0 and h["wall0"] == 1_700_000_003.0
    assert h["offsets"]["00000000000000cd"]["offset"] == 0.5
    assert h["reason"] == "unit"


def test_header_swallows_offsets_fn_failure():
    rec, _, _ = _recorder()
    rec.offsets_fn = lambda: 1 / 0
    assert rec.header()["offsets"] == {}


def test_snapshot_window_keeps_trailing_seconds():
    rec, clock, _ = _recorder(caps={})
    rec.note(obs_events.SCHED_GRANT, key=(1, 0, 0))
    clock.advance(10.0)
    rec.note(obs_events.SCHED_ACCEPT, key=(1, 0, 0))
    snap = rec.snapshot(window=5.0)
    assert [e["name"] for e in snap["events"]] == [obs_events.SCHED_ACCEPT]
    assert len(rec.snapshot()["events"]) == 2


def test_registry_gauges_track_ring_totals():
    rec, _, _ = _recorder(caps={"sched": 1})
    reg = Registry()
    rec.bind_registry(reg)
    rec.bind_registry(reg)  # idempotent: no duplicate-gauge blowup
    rec.note(obs_events.SCHED_GRANT)
    rec.note(obs_events.SCHED_GRANT)
    snap = reg.snapshot()
    assert snap["gauges"][obs_names.GAUGE_FLIGHT_EVENTS] == 1
    assert snap["gauges"][obs_names.GAUGE_FLIGHT_EVENTS_DROPPED] == 1


# -- dumps ------------------------------------------------------------------


def test_dump_writes_header_plus_events_jsonl(tmp_path):
    rec, _, _ = _recorder(role="shard-0")
    reg = Registry()
    rec.bind_registry(reg)
    rec.note(obs_events.SCHED_GRANT, key=(2, 0, 1), lease=3)
    path = rec.dump(str(tmp_path / "d.jsonl"), reason="unit")
    lines = [json.loads(ln) for ln in
             open(path, "r", encoding="utf-8").read().splitlines()]
    assert lines[0]["kind"] == DUMP_KIND
    assert lines[0]["reason"] == "unit"
    assert lines[1]["name"] == obs_events.SCHED_GRANT
    assert lines[1]["key"] == [2, 0, 1]
    assert not os.path.exists(path + ".tmp")  # atomic: no torn temp
    assert reg.counter_value(obs_names.FLIGHT_DUMPS) == 1


def test_dump_without_a_directory_is_a_noop():
    rec, _, _ = _recorder()
    assert rec.dump() is None
    assert rec.dumps_written == 0


def test_final_dump_wins_over_late_autoflush(tmp_path):
    # CPython daemon threads outlive atexit callbacks: a last autoflush
    # racing the exit dump must not clobber the exit reason.
    rec, _, _ = _recorder()
    rec.dump_dir = str(tmp_path)
    rec.note(obs_events.SCHED_GRANT, key=(1, 0, 0))
    rec.dump(reason="atexit", final=True)
    assert rec.dump(reason="autoflush") is None
    assert postmortem.load_dump(rec.dump_path).header["reason"] == "atexit"


def test_install_dumps_on_excepthook_and_uninstall_restores(tmp_path):
    rec, _, _ = _recorder(role="proc-a")
    prev_hook = sys.excepthook
    rec.install(str(tmp_path), period=0)  # no autoflush thread
    try:
        assert sys.excepthook is not prev_hook
        rec.note(obs_events.SCHED_GRANT, key=(1, 0, 0))
        try:
            raise RuntimeError("boom")
        except RuntimeError:
            sys.excepthook(*sys.exc_info())
        dump = postmortem.load_dump(rec.dump_path)
        assert dump.header["reason"] == "excepthook:RuntimeError"
        assert [e["name"] for e in dump.events] == [obs_events.SCHED_GRANT]
    finally:
        rec.uninstall()
    assert sys.excepthook is prev_hook


def test_crashpoint_callback_notes_and_dumps_on_hard_exit(tmp_path):
    rec, _, _ = _recorder()
    rec.dump_dir = str(tmp_path)
    rec._on_crashpoint("store.after_chunk_write", True)
    dump = postmortem.load_dump(rec.dump_path)
    assert dump.header["reason"] == "crashpoint:store.after_chunk_write"
    assert dump.events[0]["name"] == obs_events.FAULT_CRASHPOINT
    assert dump.events[0]["kv"]["point"] == "store.after_chunk_write"


# -- module-global recorder -------------------------------------------------


def test_ensure_respects_kill_switch_and_first_caller_wins():
    saved = flight.get()
    flight.set_recorder(None)
    try:
        assert flight.ensure("a", environ={"DMTPU_FLIGHT": "0"}) is None
        flight.note(obs_events.SCHED_GRANT)  # free no-op, must not raise
        first = flight.ensure("coordinator", environ={})
        second = flight.ensure("worker", environ={})
        assert first is second
        assert first.role == "coordinator"
        flight.note(obs_events.SCHED_GRANT, key=(1, 0, 0))
        assert first.recorded == 1
    finally:
        flight.set_recorder(saved)


def test_ensure_binds_registry_for_late_callers():
    saved = flight.get()
    flight.set_recorder(None)
    try:
        flight.ensure("coordinator", environ={})
        reg = Registry()
        flight.ensure("gateway", registry=reg, environ={})
        assert obs_names.GAUGE_FLIGHT_EVENTS in reg.snapshot()["gauges"]
    finally:
        flight.set_recorder(saved)


# -- dump loading: the fuzz corpus ------------------------------------------


def _write(path, data):
    mode = "wb" if isinstance(data, bytes) else "w"
    with open(path, mode) as f:
        f.write(data)


def _dump_lines(role, pid, events, *, wall0=1e9, mono0=0.0, **extra):
    header = {"v": DUMP_VERSION, "kind": DUMP_KIND, "role": role,
              "pid": pid, "reason": "test", "wall0": wall0,
              "mono0": mono0, "seq": len(events), **extra}
    return "\n".join([json.dumps(header)]
                     + [json.dumps(e) for e in events]) + "\n"


def _ev(seq, t, name, key=None, lease=None, **kv):
    doc = {"seq": seq, "t": t, "cat": name.partition(".")[0], "name": name}
    if key is not None:
        doc["key"] = list(key)
    if lease is not None:
        doc["lease"] = lease
    if kv:
        doc["kv"] = kv
    return doc


def test_truncated_dump_yields_partial_timeline(tmp_path):
    body = _dump_lines("shard-0", 10, [
        _ev(1, 1.0, obs_events.SCHED_GRANT, key=(1, 0, 0)),
        _ev(2, 2.0, obs_events.SCHED_ACCEPT, key=(1, 0, 0))])
    _write(str(tmp_path / "a.jsonl"), body[:-25])  # cut mid-line
    pm = postmortem.assemble(str(tmp_path))
    assert len(pm.dumps) == 1
    assert pm.errors == 1
    assert [e["name"] for e in pm.timeline] == [obs_events.SCHED_GRANT]
    assert pm.render_text()  # partial timeline still renders


def test_garbage_and_binary_dumps_never_raise(tmp_path):
    rng = random.Random(20260807)
    _write(str(tmp_path / "junk.jsonl"),
           bytes(rng.randrange(256) for _ in range(4096)))
    _write(str(tmp_path / "trap.jsonl"),
           '["not", "a", "dict"]\n42\nnull\n{"kind": "wrong"}\n')
    _write(str(tmp_path / "empty.jsonl"), "")
    _write(str(tmp_path / "ignored.txt"), "not a dump at all")
    good = _dump_lines("shard-1", 11, [
        _ev(1, 1.0, obs_events.SCHED_GRANT, key=(1, 0, 0))])
    _write(str(tmp_path / "z-good.jsonl"), good)
    pm = postmortem.assemble(str(tmp_path))
    assert [d.proc for d in pm.dumps] == ["shard-1@11"]
    assert pm.file_errors >= 2  # junk + empty (trap may parse 0 events)
    assert len(pm.timeline) == 1
    assert pm.render_text() and pm.to_dict() and pm.to_chrome()


def test_oversized_line_is_skipped_not_parsed(tmp_path):
    big = '{"name": "sched.grant", "t": 1.0, "pad": "' \
        + "x" * postmortem.MAX_LINE_BYTES + '"}'
    body = _dump_lines("shard-0", 1, [
        _ev(1, 2.0, obs_events.SCHED_ACCEPT, key=(1, 0, 0))])
    _write(str(tmp_path / "a.jsonl"), body + big + "\n")
    dump = postmortem.load_dump(str(tmp_path / "a.jsonl"))
    assert dump.errors == 1
    assert [e["name"] for e in dump.events] == [obs_events.SCHED_ACCEPT]


def test_version_mismatch_counts_one_error_but_parses_on(tmp_path):
    body = _dump_lines("shard-0", 1, [
        _ev(1, 1.0, obs_events.SCHED_GRANT, key=(1, 0, 0))])
    body = body.replace(f'"v": {DUMP_VERSION}', f'"v": {DUMP_VERSION + 9}')
    _write(str(tmp_path / "a.jsonl"), body)
    dump = postmortem.load_dump(str(tmp_path / "a.jsonl"))
    assert dump.errors == 1
    assert len(dump.events) == 1


def test_missing_directory_yields_empty_renderable_postmortem(tmp_path):
    pm = postmortem.assemble(str(tmp_path / "never-made"))
    assert pm.dumps == [] and pm.file_errors == 1
    assert pm.render_text() is not None
    assert pm.to_chrome()["traceEvents"] == []


def test_fuzzed_event_fields_never_crash_assembly(tmp_path):
    rng = random.Random(7)
    weird = [
        {"seq": "x", "t": 1.0, "name": obs_events.SCHED_GRANT,
         "key": [1, "a", 3]},
        {"t": 2.0, "name": obs_events.SCHED_ACCEPT, "key": [1]},
        {"t": 3.0, "name": obs_events.SCHED_GRANT, "key": None,
         "lease": "not-an-int", "kv": {"deep": {"nest": [1, 2]}}},
        {"t": "4.0", "name": obs_events.SCHED_EXPIRE},  # bad t: dropped
        {"t": 5.0, "name": 9},  # bad name: dropped
    ]
    rng.shuffle(weird)
    body = _dump_lines("shard-0", 1, weird)
    _write(str(tmp_path / "a.jsonl"), body)
    pm = postmortem.assemble(str(tmp_path))
    assert pm.line_errors == 2
    assert len(pm.timeline) == 3  # malformed keys coerce to None
    assert pm.render_text() and pm.to_chrome()


def test_assemble_accounts_into_registry(tmp_path):
    _write(str(tmp_path / "a.jsonl"), _dump_lines("shard-0", 1, [
        _ev(1, 1.0, obs_events.SCHED_GRANT, key=(1, 0, 0))]))
    _write(str(tmp_path / "bad.jsonl"), "garbage\n")
    reg = Registry()
    pm = postmortem.assemble(str(tmp_path), registry=reg)
    assert reg.counter_value(obs_names.POSTMORTEM_DUMPS_LOADED) == 1
    assert reg.counter_value(obs_names.POSTMORTEM_DUMP_ERRORS) == \
        pm.errors
    assert reg.counter_value(obs_names.POSTMORTEM_ANOMALIES) == \
        len(pm.anomalies)


# -- clock alignment --------------------------------------------------------


def test_worker_dump_aligns_through_coordinator_span_offsets(tmp_path):
    wid = "00000000000000ab"
    # Coordinator: wall 1000.0 at mono 50.0; knows the worker's clock
    # runs 30s behind coordinator mono (offset = +30).
    _write(str(tmp_path / "coord.jsonl"), _dump_lines(
        "shard-0", 1,
        [_ev(1, 51.0, obs_events.SCHED_GRANT, key=(1, 0, 0))],
        wall0=1000.0, mono0=50.0,
        offsets={wid: {"offset": 30.0, "error": 0.004}}))
    # Worker event at its own mono 22.0 -> coord mono 52.0 -> wall
    # 1002.0; the worker's own (bogus) wall anchor must NOT be used.
    _write(str(tmp_path / "worker.jsonl"), _dump_lines(
        "worker", 2,
        [_ev(1, 22.0, obs_events.WKR_STAGE, key=(1, 0, 0))],
        wall0=555.0, mono0=20.0, worker_id=wid))
    pm = postmortem.assemble(str(tmp_path))
    by_name = {e["name"]: e for e in pm.timeline}
    grant = by_name[obs_events.SCHED_GRANT]
    stage = by_name[obs_events.WKR_STAGE]
    assert grant["t"] == pytest.approx(1001.0)
    assert stage["t"] == pytest.approx(1002.0)
    assert stage["align"] == "spans"
    assert stage["align_error_s"] == pytest.approx(0.004)
    assert pm.timeline[0] is grant  # causal order across processes


def test_best_offset_prefers_tightest_error_bound(tmp_path):
    wid = "00000000000000ab"
    _write(str(tmp_path / "a.jsonl"), _dump_lines(
        "shard-0", 1, [], wall0=1000.0, mono0=0.0,
        offsets={wid: {"offset": 5.0, "error": 0.5}}))
    _write(str(tmp_path / "b.jsonl"), _dump_lines(
        "shard-1", 2, [], wall0=1000.0, mono0=0.0,
        offsets={wid: {"offset": 7.0, "error": 0.001}}))
    _write(str(tmp_path / "w.jsonl"), _dump_lines(
        "worker", 3, [_ev(1, 1.0, obs_events.WKR_STAGE)],
        wall0=0.0, mono0=0.0, worker_id=wid))
    pm = postmortem.assemble(str(tmp_path))
    assert pm.timeline[0]["t"] == pytest.approx(1008.0)  # b's offset won


def test_wall_anchor_fallback_and_headerless_raw(tmp_path):
    _write(str(tmp_path / "a.jsonl"), _dump_lines(
        "shard-0", 1, [_ev(1, 3.0, obs_events.SCHED_GRANT)],
        wall0=2000.0, mono0=1.0))
    _write(str(tmp_path / "b.jsonl"),
           json.dumps(_ev(1, 4.5, obs_events.SCHED_ACCEPT)) + "\n")
    pm = postmortem.assemble(str(tmp_path))
    by_name = {e["name"]: e for e in pm.timeline}
    assert by_name[obs_events.SCHED_GRANT]["t"] == pytest.approx(2002.0)
    assert by_name[obs_events.SCHED_GRANT]["align"] == "wall"
    assert by_name[obs_events.SCHED_ACCEPT]["t"] == pytest.approx(4.5)
    assert by_name[obs_events.SCHED_ACCEPT]["align"] == "none"


# -- in-flight reconstruction + anomaly detectors ---------------------------


def test_in_flight_grants_reconstructed_per_process(tmp_path):
    _write(str(tmp_path / "a.jsonl"), _dump_lines("shard-0", 1, [
        _ev(1, 1.0, obs_events.SCHED_GRANT, key=(3, 0, 0), lease=1),
        _ev(2, 1.1, obs_events.SCHED_GRANT, key=(3, 0, 1), lease=2),
        _ev(3, 1.5, obs_events.SCHED_ACCEPT, key=(3, 0, 0), lease=1)]))
    pm = postmortem.assemble(str(tmp_path))
    assert list(pm.in_flight) == ["shard-0@1"]
    assert [e["key"] for e in pm.in_flight["shard-0@1"]] == [(3, 0, 1)]
    kinds = {a["type"] for a in pm.anomalies}
    assert "grant-without-accept" in kinds


def test_grant_without_accept_annotates_regrant(tmp_path):
    _write(str(tmp_path / "a.jsonl"), _dump_lines("shard-0", 1, [
        _ev(1, 1.0, obs_events.SCHED_GRANT, key=(3, 0, 0), lease=1)],
        wall0=1000.0, mono0=0.0))
    _write(str(tmp_path / "b.jsonl"), _dump_lines("shard-0", 9, [
        _ev(1, 6.0, obs_events.SCHED_GRANT, key=(3, 0, 0), lease=1),
        _ev(2, 7.0, obs_events.SCHED_ACCEPT, key=(3, 0, 0), lease=1)],
        wall0=1000.0, mono0=0.0))
    pm = postmortem.assemble(str(tmp_path))
    anomaly = next(a for a in pm.anomalies
                   if a["type"] == "grant-without-accept")
    assert anomaly["proc"] == "shard-0@1"
    assert anomaly["regranted_by"] == "shard-0@9"
    assert anomaly["t_regrant"] == pytest.approx(1006.0)
    assert pm.tile_history((3, 0, 0))


def test_lease_ping_pong_detector(tmp_path):
    events = []
    for i in range(3):
        events.append(_ev(2 * i + 1, float(i), obs_events.SCHED_GRANT,
                          key=(3, 1, 1), lease=i))
        events.append(_ev(2 * i + 2, i + 0.5, obs_events.SCHED_EXPIRE,
                          key=(3, 1, 1), lease=i))
    _write(str(tmp_path / "a.jsonl"), _dump_lines("shard-0", 1, events))
    pm = postmortem.assemble(str(tmp_path))
    assert any(a["type"] == "lease-ping-pong" for a in pm.anomalies)


def test_redirect_loop_detector(tmp_path):
    events = [_ev(i + 1, float(i), obs_events.SESS_REDIRECT,
                  key=(3, 2, 2), owner=1) for i in range(3)]
    _write(str(tmp_path / "a.jsonl"), _dump_lines("shard-0", 1, events))
    pm = postmortem.assemble(str(tmp_path))
    assert any(a["type"] == "redirect-loop" for a in pm.anomalies)


def test_double_commit_detector_across_processes(tmp_path):
    _write(str(tmp_path / "a.jsonl"), _dump_lines("shard-0", 1, [
        _ev(1, 1.0, obs_events.SCHED_ACCEPT, key=(3, 0, 2), lease=1)]))
    _write(str(tmp_path / "b.jsonl"), _dump_lines("shard-1", 2, [
        _ev(1, 2.0, obs_events.SCHED_ACCEPT, key=(3, 0, 2), lease=9)]))
    pm = postmortem.assemble(str(tmp_path))
    double = next(a for a in pm.anomalies if a["type"] == "double-commit")
    assert sorted(double["procs"]) == ["shard-0@1", "shard-1@2"]


def test_retry_storm_detector_needs_tight_window(tmp_path):
    storm = [_ev(i + 1, i * 0.5, obs_events.SESS_RESULT_REJECTED,
                 key=(3, 1, 2)) for i in range(5)]
    spread = [_ev(i + 1, i * 100.0, obs_events.SESS_RESULT_REJECTED,
                  key=(3, 2, 1)) for i in range(5)]
    _write(str(tmp_path / "a.jsonl"),
           _dump_lines("shard-0", 1, storm + spread))
    pm = postmortem.assemble(str(tmp_path))
    storms = [a for a in pm.anomalies if a["type"] == "retry-storm"]
    assert [a["key"] for a in storms] == [[3, 1, 2]]


def test_chrome_export_names_processes_and_orders_events(tmp_path):
    _write(str(tmp_path / "a.jsonl"), _dump_lines("shard-0", 1, [
        _ev(1, 1.0, obs_events.SCHED_GRANT, key=(1, 0, 0), lease=4),
        _ev(2, 1.5, obs_events.SCHED_ACCEPT, key=(1, 0, 0), lease=4)]))
    doc = postmortem.assemble(str(tmp_path)).to_chrome()
    metas = [e for e in doc["traceEvents"] if e["ph"] == "M"]
    inst = [e for e in doc["traceEvents"] if e["ph"] == "i"]
    assert metas[0]["args"]["name"] == "shard-0@1"
    assert [e["ts"] for e in inst] == sorted(e["ts"] for e in inst)
    assert inst[0]["args"]["key"] == "1/0/0"


# -- SLO integration --------------------------------------------------------


def test_slo_fire_attaches_flight_evidence():
    from distributedmandelbrot_tpu.obs.slo import _BaseSLO

    class AlwaysBurning(_BaseSLO):
        def _window_counts(self, window, now):
            return 0, 100

    saved = flight.get()
    flight.set_recorder(None)
    try:
        rec = flight.ensure("gateway", environ={})
        rec.note(obs_events.GW_SHED, key=(4, 1, 1))
        reg = Registry()
        from distributedmandelbrot_tpu.obs.timeseries import \
            TimeseriesSampler
        slo = AlwaysBurning("test_slo", TimeseriesSampler(reg, period=1.0))
        doc = slo.evaluate()
        assert doc["state"] == "firing"
        names = [e["name"] for e in doc["evidence"]]
        assert obs_events.GW_SHED in names
        assert names[-1] == obs_events.SLO_FIRE
    finally:
        flight.set_recorder(saved)
