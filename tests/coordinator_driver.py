"""Subprocess coordinator for the kill-and-restart recovery e2e.

Usage: python tests/coordinator_driver.py DATA_DIR PORT_FILE LEVELS

Starts a Coordinator (ephemeral loopback ports, exporter on) over
DATA_DIR, writes the bound ports to PORT_FILE as JSON, then serves until
killed.  Crashpoints come in through the DMTPU_CRASHPOINTS environment
variable (utils/faults.py) — the test arms a hard-exit point, drives the
farm until the process dies mid-level with exit code 86, and restarts
this same driver on the same data dir to exercise restore.
"""

import asyncio
import json
import os
import sys


async def _main() -> None:
    # Package-under-test import; the test launches us with the repo root
    # on PYTHONPATH (it is the pytest rootdir).
    from distributedmandelbrot_tpu.cli import parse_level_settings
    from distributedmandelbrot_tpu.coordinator.app import Coordinator

    data_dir, port_file, levels = sys.argv[1], sys.argv[2], sys.argv[3]
    coordinator = Coordinator(
        parse_level_settings(levels), data_dir_parent=data_dir,
        host="127.0.0.1", distributer_port=0, dataserver_port=0,
        exporter_port=0, stats_period=0.0)
    await coordinator.start()
    payload = json.dumps({"distributer": coordinator.distributer_port,
                          "exporter": coordinator.exporter_port,
                          "pid": os.getpid()})
    tmp = port_file + ".tmp"
    with open(tmp, "w", encoding="utf-8") as f:
        f.write(payload)
    os.replace(tmp, port_file)  # atomic: the test polls for this file
    try:
        await asyncio.Event().wait()
    finally:
        await coordinator.stop()


if __name__ == "__main__":
    asyncio.run(_main())
