"""Durability tests: checkpoint/restore, torn-tail repair, crashpoint
interleavings, worker reconnect backoff, and the kill-and-restart e2e.

The deterministic crashpoints (utils/faults.py) let these tests stop a
store or checkpoint write at the exact interleavings a crash-consistency
argument worries about; the e2e at the bottom does it for real — a
subprocess coordinator hard-exits mid-level at an armed crashpoint and a
restart on the same data dir must drain the farm to the exact tile set.
"""

import json
import os
import random
import socket
import struct
import subprocess
import sys
import time
import urllib.request

import numpy as np
import pytest

from distributedmandelbrot_tpu.coordinator.clock import ManualClock
from distributedmandelbrot_tpu.coordinator.recovery import (
    Checkpoint, CorruptCheckpointError, RecoveryManager, StaleGenerationError,
    checkpoint_blob_name, decode_checkpoint, encode_checkpoint,
    load_restore_state, peek_generation)
from distributedmandelbrot_tpu.coordinator.scheduler import TileScheduler
from distributedmandelbrot_tpu.core import CHUNK_PIXELS, Chunk
from distributedmandelbrot_tpu.core.workload import LevelSetting, Workload
from distributedmandelbrot_tpu.obs import names as obs_names
from distributedmandelbrot_tpu.obs.metrics import Registry
from distributedmandelbrot_tpu.storage.store import ChunkStore
from distributedmandelbrot_tpu.utils import faults
from distributedmandelbrot_tpu.utils.metrics import Counters
from distributedmandelbrot_tpu.worker.client import DistributerClient

SETTINGS = [LevelSetting(8, 100)]


@pytest.fixture(autouse=True)
def _disarm_crashpoints():
    yield
    faults.disarm()


def make_store(tmp_path) -> ChunkStore:
    store = ChunkStore(str(tmp_path))
    store.setup()
    return store


# -- codec ----------------------------------------------------------------


def test_checkpoint_codec_roundtrip():
    ck = Checkpoint(generation=7, index_offset=1234,
                    settings=((8, 100), (16, 250)), cursor_pos=42,
                    cursor_done=False,
                    completed={(8, 0, 0), (8, 3, 3), (16, 9, 1)},
                    leases=[(Workload(8, 100, 1, 2), 17.5),
                            (Workload(16, 250, 0, 0), -3.0)],
                    retry=[Workload(8, 100, 2, 2)])
    assert decode_checkpoint(encode_checkpoint(ck)) == ck


def test_checkpoint_codec_rejects_corruption():
    data = encode_checkpoint(Checkpoint(
        generation=1, index_offset=0, settings=((8, 100),), cursor_pos=0,
        cursor_done=False, completed=set(), leases=[], retry=[]))
    with pytest.raises(CorruptCheckpointError):
        decode_checkpoint(data[:-1])  # truncated
    flipped = bytearray(data)
    flipped[10] ^= 0xFF
    with pytest.raises(CorruptCheckpointError):
        decode_checkpoint(bytes(flipped))  # CRC catches a bit flip
    with pytest.raises(CorruptCheckpointError):
        decode_checkpoint(b"NOPE" + data[4:])  # bad magic


def test_blob_name_is_per_level_set():
    assert checkpoint_blob_name(SETTINGS) == "_checkpoint-8.dat"
    two = [LevelSetting(16, 1), LevelSetting(8, 1)]
    assert checkpoint_blob_name(two) == "_checkpoint-8_16.dat"


# -- checkpoint round trip with a virtual clock ---------------------------


def test_lease_ttls_survive_restore(tmp_path):
    """Remaining lease TTLs are carried as durations: a restore in a new
    process (fresh clock origin) gives workers the time they had left;
    a lease that expired while the coordinator was down is grantable
    immediately."""
    store = make_store(tmp_path)
    clock = ManualClock()
    sched = TileScheduler(SETTINGS, clock=clock, lease_timeout=100.0)
    w_live = sched.acquire()
    clock.advance(60.0)
    w_dying = sched.acquire()  # expires_at = 160
    clock.advance(10.0)        # now 70: live has 30 left, dying has 90
    mgr = RecoveryManager(store, sched, generation=1)
    mgr.checkpoint_sync()

    # Restart after 50 virtual seconds of downtime: w_live's 30 s ran
    # out, w_dying still has 40 s.
    res = load_restore_state(store, SETTINGS)
    clock2 = ManualClock()
    sched2 = TileScheduler(SETTINGS, completed=res.completed, clock=clock2,
                           lease_timeout=100.0)
    # Downtime is modeled by the TTLs themselves; shrink them by hand to
    # simulate 50 s passing while down.
    ck = res.checkpoint
    aged = [(w, remaining - 50.0) for w, remaining in ck.leases]
    rebuilt = sched2.restore_state(cursor_pos=ck.cursor_pos,
                                   cursor_done=ck.cursor_done,
                                   retry=ck.retry, leases=aged)
    assert rebuilt == 1  # only w_dying still holds a lease
    assert sched2.can_accept(w_dying)
    assert not sched2.can_accept(w_live)
    # The expired tile went to the retry queue: it is granted again
    # (possibly among frontier tiles, so scan a few grants).
    granted = {sched2.acquire().key for _ in range(3)}
    assert w_live.key in granted


def test_restore_replays_only_suffix(tmp_path):
    store = make_store(tmp_path)
    sched = TileScheduler(SETTINGS)
    for _ in range(4):
        w = sched.acquire()
        sched.complete(w)
        store.save(Chunk.never(w.level, w.index_real, w.index_imag))
    RecoveryManager(store, sched, generation=1).checkpoint_sync()
    for _ in range(3):  # land past the checkpoint
        w = sched.acquire()
        sched.complete(w)
        store.save(Chunk.never(w.level, w.index_real, w.index_imag))

    registry = Registry()
    res = load_restore_state(store, SETTINGS, registry=registry)
    assert res.checkpoint is not None
    assert res.replayed_entries == 3
    assert len(res.completed) == 7
    assert res.generation == 2
    assert registry.counter_value(obs_names.COORD_RESTORES) == 1
    assert registry.counter_value(obs_names.COORD_REPLAY_ENTRIES) == 3


def test_restore_discards_checkpoint_on_settings_change(tmp_path):
    store = make_store(tmp_path)
    sched = TileScheduler(SETTINGS)
    w = sched.acquire()
    sched.complete(w)
    store.save(Chunk.never(w.level, w.index_real, w.index_imag))
    RecoveryManager(store, sched, generation=3).checkpoint_sync()

    changed = [LevelSetting(8, 999)]  # same level, different max_iter
    res = load_restore_state(store, changed)
    assert res.checkpoint is None  # full replay fallback
    assert res.completed == {w.key}
    assert res.generation == 4  # generation still carries over


def test_pending_save_excluded_but_regrantable(tmp_path):
    """The pending-save window: a tile completed in the scheduler whose
    save never lands is excluded from the checkpointed completed set AND
    parked in its retry queue — after restore it is granted again, not
    stuck in limbo."""
    store = make_store(tmp_path)
    sched = TileScheduler(SETTINGS)
    w = sched.acquire()
    sched.complete(w)  # accepted, but its save will "never land"
    mgr = RecoveryManager(store, sched, generation=1,
                          pending_keys_fn=lambda: {w.key})
    mgr.checkpoint_sync()

    res = load_restore_state(store, SETTINGS)
    assert w.key not in res.completed
    sched2 = TileScheduler(SETTINGS, completed=res.completed)
    res.apply(sched2)
    granted = {sched2.acquire().key for _ in range(2)}
    assert w.key in granted

    # Counter-case: the save DID land (entry in the suffix) — the parked
    # retry entry must be dropped, not re-granted.
    store.save(Chunk.never(w.level, w.index_real, w.index_imag))
    res2 = load_restore_state(store, SETTINGS)
    assert w.key in res2.completed
    sched3 = TileScheduler(SETTINGS, completed=res2.completed)
    res2.apply(sched3)
    for _ in range(sched3.total_tiles):
        g = sched3.acquire()
        assert g is None or g.key != w.key


# -- fencing ---------------------------------------------------------------


def test_generation_fencing(tmp_path):
    store = make_store(tmp_path)
    sched = TileScheduler(SETTINGS)
    old = RecoveryManager(store, sched, generation=1)
    old.checkpoint_sync()
    assert peek_generation(store, SETTINGS) == 1
    new = RecoveryManager(store, sched, generation=5)
    new.checkpoint_sync()
    assert peek_generation(store, SETTINGS) == 5
    with pytest.raises(StaleGenerationError):
        old.checkpoint_sync()  # the fenced-out predecessor
    assert peek_generation(store, SETTINGS) == 5  # untouched


def test_mid_checkpoint_crash_preserves_previous(tmp_path):
    """A crash between encode and PUT leaves the previous checkpoint
    fully intact (the blob PUT is atomic)."""
    store = make_store(tmp_path)
    sched = TileScheduler(SETTINGS)
    w = sched.acquire()
    sched.complete(w)
    mgr = RecoveryManager(store, sched, generation=1)
    mgr.checkpoint_sync()

    w2 = sched.acquire()
    sched.complete(w2)
    faults.arm("recovery.mid_checkpoint")
    with pytest.raises(faults.CrashPointError):
        mgr.checkpoint_sync()
    res = load_restore_state(store, SETTINGS)
    assert res.checkpoint is not None
    assert res.checkpoint.completed == {w.key}  # first checkpoint, intact


# -- store crashpoint interleavings ---------------------------------------


def patterned_chunk(level=8, i=1, j=2):
    return Chunk(level, i, j,
                 (np.arange(CHUNK_PIXELS) % 97).astype(np.uint8))


def test_crash_before_chunk_write(tmp_path):
    store = make_store(tmp_path)
    faults.arm("store.before_chunk_write")
    with pytest.raises(faults.CrashPointError):
        store.save(patterned_chunk())
    # Nothing landed: no index entry, tile will be recomputed.
    assert store.completed_keys() == set()
    store.save(patterned_chunk())  # clean retry succeeds
    assert store.completed_keys() == {(8, 1, 2)}


def test_crash_between_chunk_and_index(tmp_path):
    """The nasty one: blob durable, index entry missing.  The tile must
    NOT count as completed (replay is index-driven), so it is recomputed
    — an orphan blob, never a lost tile."""
    store = make_store(tmp_path)
    faults.arm("store.after_chunk_write")
    with pytest.raises(faults.CrashPointError):
        store.save(patterned_chunk())
    assert store.completed_keys() == set()
    store2 = ChunkStore(str(tmp_path))
    store2.setup()
    assert store2.completed_keys() == set()
    store2.save(patterned_chunk())  # retry lands under a fresh blob name
    assert store2.completed_keys() == {(8, 1, 2)}
    got = store2.load(8, 1, 2)
    assert got is not None and np.array_equal(got.data,
                                              patterned_chunk().data)


def test_crash_after_index_append(tmp_path):
    store = make_store(tmp_path)
    faults.arm("store.after_index_append")
    with pytest.raises(faults.CrashPointError):
        store.save(patterned_chunk())
    # The append is the commit point: the tile IS durably completed.
    assert store.completed_keys() == {(8, 1, 2)}
    store2 = ChunkStore(str(tmp_path))
    store2.setup()
    assert store2.completed_keys() == {(8, 1, 2)}


# -- torn-tail repair ------------------------------------------------------


def test_torn_tail_repaired_before_post_restart_append(tmp_path):
    """Regression: a crash mid-append leaves a torn final entry; the old
    "ab"-mode reopen would land the next append AFTER the torn bytes,
    turning a tolerated torn tail into an interior CorruptIndexError.
    setup() must truncate to the last valid entry boundary first."""
    store = make_store(tmp_path)
    store.save(Chunk.never(8, 0, 0))
    store.save(Chunk.never(8, 1, 1))
    index_path = os.path.join(str(tmp_path), "Data", "_index.dat")
    size = os.path.getsize(index_path)
    with open(index_path, "ab") as f:  # simulate the torn append
        f.write(struct.pack("<IIIi", 8, 2, 2, 1)[:7])

    registry = Registry()
    store2 = ChunkStore(str(tmp_path), registry=registry)
    store2.setup()
    assert registry.counter_value(
        obs_names.STORE_TORN_TAILS_REPAIRED) == 1
    assert os.path.getsize(index_path) == size  # cut back to the boundary
    store2.save(Chunk.never(8, 3, 3))  # post-restart append
    # The whole index parses cleanly — no interior corruption.
    assert store2.completed_keys() == {(8, 0, 0), (8, 1, 1), (8, 3, 3)}
    store3 = ChunkStore(str(tmp_path))
    store3.setup()
    assert store3.completed_keys() == {(8, 0, 0), (8, 1, 1), (8, 3, 3)}


def test_interior_corruption_still_raises(tmp_path):
    """Repair is strictly a tail operation: interior garbage is damage,
    not a crash artifact, and keeps raising as before."""
    store = make_store(tmp_path)
    store.save(Chunk.never(8, 0, 0))
    index_path = os.path.join(str(tmp_path), "Data", "_index.dat")
    with open(index_path, "r+b") as f:
        f.seek(12)
        f.write(struct.pack("<i", 99))  # invalid entry type mid-file
    store2 = ChunkStore(str(tmp_path))
    store2.setup()  # setup leaves the bytes alone...
    from distributedmandelbrot_tpu.storage.index import CorruptIndexError
    with pytest.raises(CorruptIndexError):
        store2.entries()  # ...and reads still fail loudly


# -- property test: random interleavings ----------------------------------


@pytest.mark.parametrize("seed", [1, 7, 2026])
def test_random_interleavings_preserve_completed_set(tmp_path, seed):
    """Random save/claim/complete/checkpoint/crash/restore sequences:
    after every crash+restore, the restored completed set equals an
    index replay exactly (no lost tiles, no phantom completions)."""
    rng = random.Random(seed)
    settings = [LevelSetting(4, 50)]
    levels = [4]
    store = ChunkStore(str(tmp_path / f"s{seed}"))
    store.setup()
    clock = ManualClock()
    sched = TileScheduler(settings, clock=clock, lease_timeout=30.0)
    pending: set = set()  # accepted tiles whose save has not landed
    mgr = RecoveryManager(store, sched, generation=1,
                          pending_keys_fn=lambda: set(pending))

    for _ in range(300):
        op = rng.choice(["accept", "accept", "persist", "persist",
                         "lease", "advance", "checkpoint", "crash"])
        if op == "accept":
            w = sched.acquire()
            if w is not None and sched.complete(w):
                pending.add(w.key)
        elif op == "persist" and pending:
            key = pending.pop()
            store.save(Chunk.never(*key))
        elif op == "lease":
            sched.acquire()  # grant and abandon (expires later)
        elif op == "advance":
            clock.advance(rng.uniform(0.0, 20.0))
        elif op == "checkpoint":
            mgr.checkpoint_sync()
        elif op == "crash":
            # The process dies: in-flight saves and the scheduler vanish.
            pending.clear()
            res = load_restore_state(store, settings)
            assert res.completed == store.completed_keys(levels=levels), \
                f"restore diverged from index replay (seed={seed})"
            clock = ManualClock()
            sched = TileScheduler(settings, completed=res.completed,
                                  clock=clock, lease_timeout=30.0)
            res.apply(sched)
            mgr = RecoveryManager(store, sched,
                                  generation=res.generation,
                                  pending_keys_fn=lambda: set(pending))

    # Final crash: same invariant at the end of every sequence.
    res = load_restore_state(store, settings)
    assert res.completed == store.completed_keys(levels=levels)


# -- worker reconnect backoff ---------------------------------------------


def test_reconnect_backoff_schedule():
    counters = Counters()
    client = DistributerClient("127.0.0.1", 1, reconnect_attempts=4,
                               reconnect_base=0.1, reconnect_cap=0.5,
                               counters=counters,
                               rng=random.Random(42))
    sleeps: list = []
    client._sleep = sleeps.append
    calls = {"n": 0}

    def flaky():
        calls["n"] += 1
        if calls["n"] <= 3:
            raise OSError("connection refused")
        return "ok"

    assert client._with_reconnect(flaky) == "ok"
    assert calls["n"] == 4
    assert counters.get(obs_names.WORKER_RECONNECTS) == 3
    # Capped exponential envelope with jitter in [0.5, 1.0): attempt n
    # sleeps within (0.5, 1.0] * min(cap, base * 2^n).
    for n, s in enumerate(sleeps):
        hi = min(0.5, 0.1 * (2 ** n))
        assert hi * 0.5 <= s < hi


def test_reconnect_exhaustion_raises():
    client = DistributerClient("127.0.0.1", 1, reconnect_attempts=2)
    client._sleep = lambda _s: None

    def always_down():
        raise OSError("down")

    with pytest.raises(OSError):
        client._with_reconnect(always_down)


def test_reconnect_never_retries_protocol_errors():
    from distributedmandelbrot_tpu.net import framing
    client = DistributerClient("127.0.0.1", 1, reconnect_attempts=5)
    client._sleep = lambda _s: pytest.fail("must not sleep")

    def hostile():
        raise framing.ProtocolError("garbage")

    with pytest.raises(framing.ProtocolError):
        client._with_reconnect(hostile)


def test_reconnect_default_off():
    # Historical fail-fast behavior is the default: port 1 refuses.
    client = DistributerClient("127.0.0.1", 1, timeout=0.5)
    client._sleep = lambda _s: pytest.fail("must not sleep")
    with pytest.raises(OSError):
        client.request()


# -- kill-and-restart e2e --------------------------------------------------


DRIVER = os.path.join(os.path.dirname(__file__), "coordinator_driver.py")
E2E_LEVELS = "3:50"  # 9 tiles; 16 MiB payloads keep this honest but quick


def _spawn_coordinator(data_dir, port_file, crashpoints=None,
                       timeout=30.0):
    env = dict(os.environ)
    env.pop("DMTPU_CRASHPOINTS", None)
    if crashpoints:
        env["DMTPU_CRASHPOINTS"] = crashpoints
    # python puts the driver's dir (tests/) on sys.path, not the repo
    # root the package lives in.
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env["PYTHONPATH"] = repo_root + os.pathsep + env.get("PYTHONPATH", "")
    proc = subprocess.Popen([sys.executable, DRIVER, str(data_dir),
                             str(port_file), E2E_LEVELS], env=env)
    deadline = time.monotonic() + timeout
    while not os.path.exists(port_file):
        if proc.poll() is not None:
            raise AssertionError(
                f"coordinator died during startup: rc={proc.returncode}")
        if time.monotonic() > deadline:
            proc.kill()
            raise AssertionError("coordinator did not write its ports")
        time.sleep(0.05)
    with open(port_file, encoding="utf-8") as f:
        ports = json.load(f)
    return proc, ports


def _varz(port: int) -> dict:
    with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/varz", timeout=10) as resp:
        return json.loads(resp.read().decode())


def _wait_saved(port: int, n: int, timeout=30.0) -> None:
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if _varz(port)["counters"].get("chunks_saved", 0) >= n:
            return
        time.sleep(0.05)
    raise AssertionError(f"saves never reached {n}")


def test_kill_and_restart_drains_exact_tile_set(tmp_path):
    """The whole story end to end: a coordinator crashes at an armed
    crashpoint mid-level (hard exit 86 after the 4th index append), a
    restart on the same data dir restores from the checkpoint replaying
    only the index suffix, an in-flight worker lands its pre-crash lease
    against the restarted process, and the farm drains to the exact tile
    set — no lost tiles, no stuck leases."""
    pixels = np.zeros(CHUNK_PIXELS, dtype=np.uint8)
    port_file = tmp_path / "ports1.json"
    proc, ports = _spawn_coordinator(
        tmp_path, port_file, crashpoints="store.after_index_append:4")
    client = DistributerClient("127.0.0.1", ports["distributer"],
                               timeout=10.0)
    try:
        # An in-flight worker: holds a lease across the crash.
        w_held = client.request()
        assert w_held is not None

        # Two tiles land, then a checkpoint (so the restart has both a
        # checkpointed prefix and a replayable suffix).
        for _ in range(2):
            w = client.request()
            assert client.submit(w, pixels)
        _wait_saved(ports["exporter"], 2)
        req = urllib.request.Request(
            f"http://127.0.0.1:{ports['exporter']}/checkpoint",
            data=b"", method="POST")
        with urllib.request.urlopen(req, timeout=10) as resp:
            stats = json.loads(resp.read().decode())
        assert stats["completed"] == 2 and stats["leases"] == 1

        # Keep submitting: the 4th index append hard-exits the process.
        submitted_after = 0
        try:
            for _ in range(6):
                w = client.request()
                if w is None:
                    break
                if client.submit(w, pixels):
                    submitted_after += 1
                time.sleep(0.1)  # let the async save (and the crash) run
        except OSError:
            pass  # the process died under us — expected
        assert proc.wait(timeout=30) == faults.CRASH_EXIT_CODE
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait()

    # Restart on the same data dir, no crashpoints.
    port_file2 = tmp_path / "ports2.json"
    proc2, ports2 = _spawn_coordinator(tmp_path, port_file2)
    try:
        varz = _varz(ports2["exporter"])
        # Restored from the checkpoint: suffix-only replay (> 0 because
        # tiles landed after the checkpoint, < total because the prefix
        # came from the checkpoint), and the held lease was rebuilt.
        counters = varz["counters"]
        assert counters["coord_restores"] == 1
        total_durable = 2 + 2  # pre-checkpoint + index appends 3 and 4
        assert 0 < counters["coord_replay_entries"] < total_durable
        assert counters["coord_restored_leases"] >= 1
        assert varz["recovery"]["generation"] == 2

        # The in-flight worker lands its pre-crash lease post-restart.
        client2 = DistributerClient("127.0.0.1", ports2["distributer"],
                                    timeout=10.0)
        assert client2.submit(w_held, pixels)

        # Drain the farm to completion.
        deadline = time.monotonic() + 120
        while time.monotonic() < deadline:
            w = client2.request()
            if w is None:
                if _varz(ports2["exporter"])["scheduler"]["completed"] == 9:
                    break
                time.sleep(0.2)
                continue
            client2.submit(w, pixels)
        sched = _varz(ports2["exporter"])["scheduler"]
        assert sched["completed"] == sched["total"] == 9
        assert sched["outstanding_leases"] == 0  # no stuck leases
        # The scheduler counts a tile at accept, a beat before its async
        # save appends the index — wait for the 5 post-restart saves
        # (9 total minus the 4 appends durable before the crash) so the
        # kill below cannot race the last tile out of the index.
        _wait_saved(ports2["exporter"], 9 - 4)
    finally:
        proc2.kill()
        proc2.wait()

    # The exact tile set, from the index itself.
    store = ChunkStore(str(tmp_path))
    store.setup()
    assert store.completed_keys() == {(3, i, j)
                                      for i in range(3) for j in range(3)}
