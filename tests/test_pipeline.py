"""Pipelined worker executor tests (CPU-only, no jax, no sockets).

Overlap is proven on a VIRTUAL clock: fake stages "sleep" in virtual
seconds, a driver thread advances time to the earliest pending deadline
once every sleeper is parked, and the assertions compare VIRTUAL
elapsed time — so a loaded CI box can stretch real wall-clock without
touching the numbers.  The remaining tests (crash propagation, window
accounting, worker delegation) run on the real clock with zero delays.
"""

from __future__ import annotations

import threading
import time

import numpy as np
import pytest

from distributedmandelbrot_tpu.core.workload import Workload
from distributedmandelbrot_tpu.worker.pipeline import (PipelineExecutor,
                                                       SyncDispatcher,
                                                       as_dispatcher)

PIXELS = 16  # tiny payload; the executor never inspects pixel counts


class VirtualClock:
    """Deterministic time: ``sleep(dt)`` parks the caller until virtual
    ``now`` reaches its deadline; a driver thread advances ``now`` to
    the earliest deadline whenever the sleeper set has been stable for
    a short real grace period (pipeline handoffs between sleeps take
    microseconds, so stability means everyone who will sleep is
    sleeping)."""

    GRACE_S = 0.02

    def __init__(self) -> None:
        self._cond = threading.Condition()
        self._now = 0.0
        self._sleepers: dict[int, float] = {}
        self._next_id = 0
        self._shutdown = False
        self._driver = threading.Thread(target=self._drive, daemon=True)
        self._driver.start()

    def now(self) -> float:
        with self._cond:
            return self._now

    def sleep(self, dt: float) -> None:
        with self._cond:
            sid = self._next_id
            self._next_id += 1
            deadline = self._now + dt
            self._sleepers[sid] = deadline
            self._cond.notify_all()
            while self._now < deadline and not self._shutdown:
                self._cond.wait(0.2)
            del self._sleepers[sid]
            self._cond.notify_all()

    def _drive(self) -> None:
        while True:
            with self._cond:
                if self._shutdown:
                    return
                snapshot = set(self._sleepers)
            time.sleep(self.GRACE_S)
            with self._cond:
                if self._shutdown:
                    return
                if not self._sleepers or set(self._sleepers) != snapshot:
                    continue  # not yet stable; re-observe
                self._now = min(self._sleepers.values())
                self._cond.notify_all()

    def close(self) -> None:
        with self._cond:
            self._shutdown = True
            self._cond.notify_all()
        self._driver.join()


@pytest.fixture()
def vclock():
    clk = VirtualClock()
    yield clk
    clk.close()


class FakeClient:
    """In-memory Distributer: hands out ``n_tiles`` workloads, accepts
    every submit, and tracks the peak leased-but-unsubmitted count —
    the lease-hoarding metric the window test pins."""

    def __init__(self, n_tiles: int, clock: VirtualClock | None = None,
                 lease_s: float = 0.0, upload_s: float = 0.0) -> None:
        self._tiles = [Workload(64, 50, i % 64, i // 64)
                       for i in range(n_tiles)]
        self._i = 0
        self._lock = threading.Lock()
        self.clock = clock
        self.lease_s = lease_s
        self.upload_s = upload_s
        self.submitted: list[Workload] = []
        self.outstanding = 0
        self.max_outstanding = 0
        self.fail_request_after: int | None = None
        self.fail_submit_after: int | None = None

    def _sleep(self, dt: float) -> None:
        if dt > 0 and self.clock is not None:
            self.clock.sleep(dt)

    def _take(self, n: int) -> list[Workload]:
        with self._lock:
            if self.fail_request_after is not None \
                    and self._i >= self.fail_request_after:
                raise RuntimeError("lease exchange blew up")
            got = self._tiles[self._i:self._i + n]
            self._i += len(got)
            self.outstanding += len(got)
            self.max_outstanding = max(self.max_outstanding,
                                       self.outstanding)
        return got

    def request(self):
        self._sleep(self.lease_s)
        got = self._take(1)
        return got[0] if got else None

    def request_batch(self, max_count: int):
        self._sleep(self.lease_s)
        return self._take(max_count)

    def submit(self, workload, pixels) -> bool:
        return self.submit_batch([(workload, pixels)])[0]

    def submit_batch(self, results):
        self._sleep(self.upload_s)
        with self._lock:
            if self.fail_submit_after is not None \
                    and len(self.submitted) + len(results) \
                    > self.fail_submit_after:
                raise RuntimeError("submit exchange blew up")
            self.submitted.extend(w for w, _ in results)
            self.outstanding -= len(results)
        return [True] * len(results)


class FakeDispatcher:
    """TileDispatcher with injectable per-stage virtual delays and
    optional crash points."""

    label = "FakeDispatcher"

    def __init__(self, clock: VirtualClock | None = None,
                 dispatch_s: float = 0.0, materialize_s: float = 0.0,
                 n_devices: int = 1) -> None:
        self.clock = clock
        self.dispatch_s = dispatch_s
        self.materialize_s = materialize_s
        self.n_devices = n_devices
        self.fail_dispatch_after: int | None = None
        self.fail_materialize_after: int | None = None
        self.dispatched = 0
        self.materialized = 0
        self.seen_devices: set[int] = set()
        self._lock = threading.Lock()

    def _sleep(self, dt: float) -> None:
        if dt > 0 and self.clock is not None:
            self.clock.sleep(dt)

    def devices(self) -> list:
        return list(range(self.n_devices))

    def dispatch(self, workload, device):
        with self._lock:
            if self.fail_dispatch_after is not None \
                    and self.dispatched >= self.fail_dispatch_after:
                raise RuntimeError("kernel dispatch blew up")
            self.dispatched += 1
            self.seen_devices.add(device)
        self._sleep(self.dispatch_s)
        return (workload, device)

    def materialize(self, handle):
        with self._lock:
            if self.fail_materialize_after is not None \
                    and self.materialized >= self.fail_materialize_after:
                raise RuntimeError("materialize blew up")
            self.materialized += 1
        self._sleep(self.materialize_s)
        return np.zeros(PIXELS, dtype=np.uint8)


# -- overlap on the virtual clock -------------------------------------------

def test_wall_clock_tracks_max_stage_not_sum(vclock):
    """8 tiles through stage delays lease=0.05 / dispatch=0.2 /
    materialize=0.1 / upload=0.1 virtual-s: serial cost would be
    8 * 0.45 = 3.6 vs; pipelined, the 0.2 vs dispatch stage dominates
    and everything else hides behind it."""
    n = 8
    client = FakeClient(n, clock=vclock, lease_s=0.05, upload_s=0.1)
    disp = FakeDispatcher(clock=vclock, dispatch_s=0.2, materialize_s=0.1)
    pipe = PipelineExecutor(client, disp, window=4, depth=2,
                            clock=vclock.now)
    t0 = vclock.now()
    rounds = pipe.run()
    elapsed = vclock.now() - t0

    assert rounds == n
    assert len(client.submitted) == n
    serial = n * (0.05 + 0.2 + 0.1 + 0.1)
    # Must beat serial decisively (the whole point) but cannot beat the
    # slowest stage's total service time.
    assert elapsed >= n * 0.2 - 1e-6
    assert elapsed <= 0.6 * serial, (
        f"virtual wall {elapsed:.2f}s vs serial {serial:.2f}s: "
        f"stages are not overlapping")
    stats = pipe.stage_stats()
    # The dominant stage is near-saturated; its neighbours mostly bubble.
    assert stats["stages"]["dispatch"]["occupancy"] > 0.6
    assert stats["stages"]["lease"]["occupancy"] < 0.5


def test_stage_busy_accounting_matches_injected_delays(vclock):
    n = 6
    client = FakeClient(n, clock=vclock, lease_s=0.01, upload_s=0.02)
    disp = FakeDispatcher(clock=vclock, dispatch_s=0.05, materialize_s=0.03)
    pipe = PipelineExecutor(client, disp, window=3, clock=vclock.now)
    pipe.run()
    stages = pipe.stage_stats()["stages"]
    assert stages["dispatch"]["busy_s"] == pytest.approx(n * 0.05, abs=1e-6)
    assert stages["materialize"]["busy_s"] == pytest.approx(n * 0.03,
                                                            abs=1e-6)
    assert stages["upload"]["items"] == n


# -- crash propagation ------------------------------------------------------

@pytest.mark.parametrize("stage", ["lease", "dispatch", "materialize",
                                   "upload"])
def test_crash_in_any_stage_propagates_and_drains_window(stage):
    n = 12
    client = FakeClient(n)
    disp = FakeDispatcher()
    if stage == "lease":
        client.fail_request_after = 4
    elif stage == "dispatch":
        disp.fail_dispatch_after = 3
    elif stage == "materialize":
        disp.fail_materialize_after = 3
    else:
        client.fail_submit_after = 2
    pipe = PipelineExecutor(client, disp, window=5, batch_size=2)
    with pytest.raises(RuntimeError, match="blew up"):
        pipe.run()
    # No orphaned in-flight tiles: every leased tile was either
    # submitted or explicitly abandoned (lease expiry re-issues those).
    assert pipe.in_flight == 0
    from distributedmandelbrot_tpu.obs import names as obs_names
    abandoned = pipe.counters.get(obs_names.PIPELINE_TILES_ABANDONED)
    assert len(client.submitted) + abandoned == client._i


def test_external_stop_drains_window():
    client = FakeClient(50)
    disp = FakeDispatcher()
    stop = threading.Event()
    pipe = PipelineExecutor(client, disp, window=4)
    done: list[int] = []
    t = threading.Thread(
        target=lambda: done.append(pipe.run(poll_interval=0.01, stop=stop)),
        daemon=True)
    t.start()
    time.sleep(0.15)
    stop.set()
    t.join(timeout=10)
    assert not t.is_alive()
    assert pipe.in_flight == 0


# -- lease prefetch stays inside the window ---------------------------------

def test_lease_prefetch_never_exceeds_window():
    """A slow downstream (real 5 ms per dispatch) piles leased tiles up
    against the window; the client-side peak of leased-but-unsubmitted
    must never pass it — lease hoarding would starve other workers."""
    window = 3
    client = FakeClient(14)

    class SlowDispatcher(FakeDispatcher):
        def dispatch(self, workload, device):
            time.sleep(0.005)
            return super().dispatch(workload, device)

    pipe = PipelineExecutor(client, SlowDispatcher(), window=window,
                            batch_size=2)
    pipe.run()
    assert len(client.submitted) == 14
    assert client.max_outstanding <= window, (
        f"peak {client.max_outstanding} leased-but-unsubmitted tiles "
        f"exceeds window {window}")


def test_upload_lanes_fed_round_robin_no_starvation(vclock):
    """Regression for batched-grant lane starvation: with a single
    shared upload queue, one lane could win every dequeue race while a
    batch of grants drained, leaving its siblings idle.  The materialize
    stage now routes tiles round-robin across per-lane queues, so the
    split is exact whatever the upload timing."""
    n = 8
    client = FakeClient(n, clock=vclock, upload_s=0.05)
    disp = FakeDispatcher(clock=vclock)
    pipe = PipelineExecutor(client, disp, window=4, batch_size=1,
                            upload_lanes=2, clock=vclock.now)
    pipe.run()
    assert len(client.submitted) == n
    lanes = pipe.stage_stats()["lanes"]
    assert len(lanes) == 2
    assert [ls["items"] for ls in lanes] == [n // 2, n // 2]
    assert all(ls["busy_s"] > 0 for ls in lanes)


def test_round_robin_covers_all_devices():
    client = FakeClient(12)
    disp = FakeDispatcher(n_devices=3)
    pipe = PipelineExecutor(client, disp, window=6, depth=2)
    pipe.run()
    assert disp.seen_devices == {0, 1, 2}


# -- dispatcher adapters and worker delegation ------------------------------

def test_as_dispatcher_picks_sync_wrapper_for_plain_backend():
    class Plain:
        def compute_batch(self, workloads):
            return [np.zeros(PIXELS, dtype=np.uint8) for _ in workloads]

    d = as_dispatcher(Plain())
    assert isinstance(d, SyncDispatcher)
    assert d.devices() == [None]
    out = d.materialize(d.dispatch(Workload(64, 10, 0, 0), None))
    assert out.shape == (PIXELS,)


def test_worker_window_delegates_to_pipeline():
    from distributedmandelbrot_tpu.worker import Worker

    class Plain:
        def compute_batch(self, workloads):
            return [np.full(PIXELS, 7, dtype=np.uint8) for _ in workloads]

    client = FakeClient(9)
    worker = Worker(client, Plain(), batch_size=2, window=4)
    rounds = worker.run_until_drained()
    assert rounds >= 1
    assert len(client.submitted) == 9
    assert worker.pipeline is not None
    assert worker.pipeline.in_flight == 0
    stats = worker.pipeline.stage_stats()
    assert stats["stages"]["upload"]["items"] == 9
    assert worker.counters.get("tiles_computed") == 9
    assert worker.counters.get("results_accepted") == 9


def test_worker_window_zero_keeps_classic_path():
    from distributedmandelbrot_tpu.worker import Worker

    class Plain:
        def compute_batch(self, workloads):
            return [np.zeros(PIXELS, dtype=np.uint8) for _ in workloads]

    client = FakeClient(4)
    worker = Worker(client, Plain(), batch_size=2, window=0)
    worker.run_until_drained()
    assert len(client.submitted) == 4
    assert worker.pipeline is None


def test_run_forever_pipelined_stops_on_event():
    from distributedmandelbrot_tpu.worker import Worker

    class Plain:
        def compute_batch(self, workloads):
            return [np.zeros(PIXELS, dtype=np.uint8) for _ in workloads]

    client = FakeClient(6)
    worker = Worker(client, Plain(), window=3)
    stop = threading.Event()
    t = threading.Thread(target=worker.run_forever,
                         kwargs=dict(poll_interval=0.01, stop=stop),
                         daemon=True)
    t.start()
    deadline = time.monotonic() + 10
    while len(client.submitted) < 6 and time.monotonic() < deadline:
        time.sleep(0.005)
    stop.set()
    t.join(timeout=10)
    assert not t.is_alive()
    assert len(client.submitted) == 6


def test_window_and_depth_validation():
    client = FakeClient(1)
    with pytest.raises(ValueError):
        PipelineExecutor(client, FakeDispatcher(), window=0)
    with pytest.raises(ValueError):
        PipelineExecutor(client, FakeDispatcher(), depth=0)
    from distributedmandelbrot_tpu.worker import Worker
    with pytest.raises(ValueError):
        Worker(client, FakeDispatcher(), window=-1)


# -- mesh fusion leg ---------------------------------------------------------

class FakeMeshDispatcher(FakeDispatcher):
    """FakeDispatcher with a fused mesh entry point: dispatch_many
    records (batch_size, device) per launch; mesh_width>1 advertises
    the mesh route so the executor spreads permits per device."""

    def __init__(self, mesh_width: int = 1, dispatch_real_s: float = 0.0,
                 **kw) -> None:
        super().__init__(**kw)
        self.mesh_width = mesh_width
        self.dispatch_real_s = dispatch_real_s
        self.launches: list[tuple[int, object]] = []

    def dispatch_many(self, workloads, device=None):
        with self._lock:
            self.dispatched += len(workloads)
            self.launches.append((len(workloads), device))
        if self.dispatch_real_s:
            time.sleep(self.dispatch_real_s)
        return [(w, device) for w in workloads]


def test_mesh_dispatcher_scales_fusion_and_spreads_permits():
    """With mesh_width=4 and depth=1 the fusion cap is depth*mesh = 4
    (not depth): fused launches carry device=None (the mesh places the
    shards), permits spread one-per-tile across the device semaphores
    (the run completes — unbalanced release would deadlock or crash),
    and stage_stats reports the mesh launches."""
    client = FakeClient(n_tiles=12)
    disp = FakeMeshDispatcher(mesh_width=4, n_devices=4,
                              dispatch_real_s=0.03)
    pipe = PipelineExecutor(client, disp, window=8, depth=1,
                            batch_size=8)
    pipe.run()
    assert len(client.submitted) == 12
    assert pipe.in_flight == 0
    assert disp.dispatched == 12
    fused = [(n, d) for n, d in disp.launches if n > 1]
    assert fused, "no launch ever coalesced a batch"
    assert all(d is None for _, d in fused), \
        "a mesh launch was pinned to one device"
    assert max(n for n, _ in disp.launches) <= 4  # depth * mesh_width
    assert any(n > 1 for n, _ in disp.launches)
    stats = pipe.stage_stats()["fusion"]
    assert stats["mesh_width"] == 4
    assert stats["mesh_launches"] == len(fused)
    assert stats["tiles"] == 12


def test_single_width_dispatcher_keeps_per_launch_device():
    """mesh_width=1 (or absent) keeps the pre-mesh contract: fused
    launches are pinned to one round-robin device and mesh_launches
    stays zero."""
    client = FakeClient(n_tiles=8)
    disp = FakeMeshDispatcher(mesh_width=1, n_devices=2,
                              dispatch_real_s=0.02)
    pipe = PipelineExecutor(client, disp, window=8, depth=2,
                            batch_size=8)
    pipe.run()
    assert len(client.submitted) == 8
    assert all(d is not None for n, d in disp.launches if n > 1)
    assert max((n for n, _ in disp.launches), default=1) <= 2  # depth
    assert pipe.stage_stats()["fusion"]["mesh_launches"] == 0
