"""Malformed-frame fuzzing of the three network surfaces.

A deterministic corpus of hostile frames — truncated mid-frame,
oversized counts, out-of-range tile keys, wrong payload lengths — is
thrown at the Distributer, DataServer, and gateway of a live embedded
coordinator.  Every case must end the same way: the offending
connection is dropped, a named obs counter records the rejection, and
the event loop keeps serving well-formed clients afterwards.  This is
the runtime proof of the boundary the taint-* rules enforce
statically: no peer-controlled integer reaches an allocation, loop, or
index without passing ``net.protocol``'s validators.
"""

from __future__ import annotations

import socket
import struct
import threading
import time

import numpy as np
import pytest

from distributedmandelbrot_tpu.core import LevelSetting
from distributedmandelbrot_tpu.core.geometry import CHUNK_PIXELS
from distributedmandelbrot_tpu.core.workload import Workload
from distributedmandelbrot_tpu.net import protocol as proto
from distributedmandelbrot_tpu.obs import names as obs_names
from distributedmandelbrot_tpu.utils.metrics import Counters
from distributedmandelbrot_tpu.worker.client import DistributerSession

from harness import CoordinatorHarness

MAX_ITER = 12
U32 = struct.Struct("<I")


def _dial(port: int) -> socket.socket:
    sock = socket.create_connection(("127.0.0.1", port), timeout=10)
    sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
    return sock


def _recv_all(sock: socket.socket) -> bytes:
    """Read until the server closes; proves the connection was dropped."""
    chunks = []
    try:
        while True:
            data = sock.recv(65536)
            if not data:
                break
            chunks.append(data)
    except (ConnectionError, socket.timeout, OSError):
        pass
    return b"".join(chunks)


def _wait_counter(farm, name: str, minimum: int, timeout: float = 10.0) -> int:
    """Rejections are counted when the handler unwinds, a beat after the
    socket closes on our side — poll briefly instead of racing it."""
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        value = farm.counters.get(name)
        if value >= minimum:
            return value
        time.sleep(0.005)
    raise AssertionError(
        f"counter {name} = {farm.counters.get(name)}, wanted >= {minimum}")


def _assert_distributer_alive(farm) -> None:
    """A well-formed request on a fresh connection still gets served."""
    with _dial(farm.distributer_port) as sock:
        sock.sendall(bytes([proto.PURPOSE_REQUEST]))
        status = sock.recv(1)
        assert status and status[0] in (proto.WORKLOAD_AVAILABLE,
                                        proto.WORKLOAD_NOT_AVAILABLE)


def _assert_dataserver_alive(farm) -> None:
    with _dial(farm.dataserver_port) as sock:
        sock.sendall(proto.QUERY.pack(1, 0, 0))
        status = sock.recv(1)
        assert status and status[0] in (proto.QUERY_ACCEPT,
                                        proto.QUERY_NOT_AVAILABLE)


def _assert_gateway_alive(farm) -> None:
    # An out-of-range single query draws an immediate REJECT reply —
    # the loop must be alive to write it (a valid missing-tile query
    # would park in the on-demand wait instead).
    with _dial(farm.gateway_port) as sock:
        sock.sendall(proto.QUERY.pack(0, 0, 0))
        status = sock.recv(1)
        assert status and status[0] == proto.QUERY_REJECT


def test_distributer_rejects_malformed_frames_and_stays_alive(tmp_path):
    with CoordinatorHarness(str(tmp_path), [LevelSetting(1, MAX_ITER)],
                            exporter=False) as farm:
        rejected = 0

        # Unknown purpose byte: dropped + counted.
        with _dial(farm.distributer_port) as sock:
            sock.sendall(bytes([0x7F]))
            assert _recv_all(sock) == b""
        rejected = _wait_counter(farm, obs_names.COORD_FRAMES_REJECTED,
                                 rejected + 1)
        _assert_distributer_alive(farm)

        # Truncated workload echo: 8 of 16 bytes, then close.
        with _dial(farm.distributer_port) as sock:
            sock.sendall(bytes([proto.PURPOSE_RESPONSE]) + b"\x00" * 8)
        rejected = _wait_counter(farm, obs_names.COORD_FRAMES_REJECTED,
                                 rejected + 1)
        _assert_distributer_alive(farm)

        # Oversized batch-response count: a u32 far past MAX_BATCH.
        with _dial(farm.distributer_port) as sock:
            sock.sendall(bytes([proto.PURPOSE_BATCH_RESPONSE])
                         + U32.pack(0xFFFF_FFFE))
            assert _recv_all(sock) == b""
        rejected = _wait_counter(farm, obs_names.COORD_FRAMES_REJECTED,
                                 rejected + 1)
        _assert_distributer_alive(farm)

        # Oversized span-report header: sync count past MAX_SPANS.
        with _dial(farm.distributer_port) as sock:
            sock.sendall(bytes([proto.PURPOSE_SPANS])
                         + proto.SPANS_HEADER.pack(1, 1 << 20, 0))
            assert _recv_all(sock) == b""
        rejected = _wait_counter(farm, obs_names.COORD_FRAMES_REJECTED,
                                 rejected + 1)
        _assert_distributer_alive(farm)


def test_distributer_short_payload_releases_claim_and_counts(tmp_path):
    with CoordinatorHarness(str(tmp_path), [LevelSetting(1, MAX_ITER)],
                            exporter=False) as farm:
        # Lease the only tile, echo it, get ACCEPT, then send a wrong
        # (short) payload and hang up mid-frame.
        with _dial(farm.distributer_port) as sock:
            sock.sendall(bytes([proto.PURPOSE_REQUEST]))
            status = sock.recv(1)
            assert status[0] == proto.WORKLOAD_AVAILABLE
            wire = b""
            while len(wire) < 16:
                wire += sock.recv(16 - len(wire))
            sock.sendall(bytes([proto.PURPOSE_RESPONSE]) + wire)
            accept = sock.recv(1)
            assert accept[0] == proto.RESPONSE_ACCEPT
            sock.sendall(b"\x00" * 100)  # 100 of 16,777,216 bytes
        _wait_counter(farm, obs_names.COORD_RESULTS_DROPPED, 1)
        _wait_counter(farm, obs_names.COORD_FRAMES_REJECTED, 1)
        # The claim was released, not leaked: the tile is grantable
        # again right now, without waiting out the lease.
        deadline = time.monotonic() + 10
        regranted = False
        while time.monotonic() < deadline and not regranted:
            with _dial(farm.distributer_port) as sock:
                sock.sendall(bytes([proto.PURPOSE_REQUEST]))
                status = sock.recv(1)
                regranted = status[0] == proto.WORKLOAD_AVAILABLE
        assert regranted, "dropped tile never returned to the frontier"


def _session_hello(sock: socket.socket,
                   want: int = proto.SESSION_FLAG_RLE) -> int:
    """Run a well-formed session hello; returns the negotiated flags."""
    sock.sendall(bytes([proto.PURPOSE_SESSION])
                 + proto.SESSION_HELLO.pack(want))
    status = sock.recv(1)
    assert status and status[0] == proto.SESSION_ACCEPT
    reply = b""
    while len(reply) < proto.SESSION_HELLO_WIRE_SIZE:
        more = sock.recv(proto.SESSION_HELLO_WIRE_SIZE - len(reply))
        assert more, "hello reply truncated"
        reply += more
    return proto.SESSION_HELLO.unpack(reply)[0]


def test_session_rejects_malformed_frames_and_stays_alive(tmp_path):
    """The malformed-session corpus: every case must drop the offending
    session, bump COORD_FRAMES_REJECTED, and leave the loop serving."""
    with CoordinatorHarness(str(tmp_path), [LevelSetting(1, MAX_ITER)],
                            exporter=False) as farm:
        rejected = 0

        # Truncated session hello: 2 of 4 capability bytes, then close.
        with _dial(farm.distributer_port) as sock:
            sock.sendall(bytes([proto.PURPOSE_SESSION]) + b"\x00\x00")
        rejected = _wait_counter(farm, obs_names.COORD_FRAMES_REJECTED,
                                 rejected + 1)
        _assert_distributer_alive(farm)

        # Interleaved frame with a bad seq: client seqs must strictly
        # increment from 0; opening with seq 5 kills the session.
        with _dial(farm.distributer_port) as sock:
            _session_hello(sock)
            sock.sendall(proto.SESSION_FRAME.pack(proto.FRAME_LEASE_REQ,
                                                  5, 4) + U32.pack(1))
            assert _recv_all(sock) == b""
        rejected = _wait_counter(farm, obs_names.COORD_FRAMES_REJECTED,
                                 rejected + 1)
        _assert_distributer_alive(farm)

        # Unknown frame type after a clean hello.
        with _dial(farm.distributer_port) as sock:
            _session_hello(sock)
            sock.sendall(proto.SESSION_FRAME.pack(0x7F, 0, 0))
            assert _recv_all(sock) == b""
        rejected = _wait_counter(farm, obs_names.COORD_FRAMES_REJECTED,
                                 rejected + 1)
        _assert_distributer_alive(farm)

        # Oversized frame payload: declared length past MAX_PAYLOAD_BYTES
        # is rejected before a single payload byte is read or allocated.
        with _dial(farm.distributer_port) as sock:
            _session_hello(sock)
            sock.sendall(proto.SESSION_FRAME.pack(proto.FRAME_UPLOAD, 0,
                                                  0xFFFF_FFFF))
            assert _recv_all(sock) == b""
        rejected = _wait_counter(farm, obs_names.COORD_FRAMES_REJECTED,
                                 rejected + 1)
        _assert_distributer_alive(farm)

        # Raw upload with a wrong body length (the "oversized compressed
        # payload" shape for the exact-size codec).
        with _dial(farm.distributer_port) as sock:
            _session_hello(sock)
            body_len = 10
            sock.sendall(proto.SESSION_FRAME.pack(
                proto.FRAME_UPLOAD, 0,
                16 + proto.UPLOAD_HEADER_WIRE_SIZE + body_len))
            sock.sendall(b"\x00" * 16
                         + proto.UPLOAD_HEADER.pack(proto.WIRE_CODEC_RAW, 0)
                         + b"\x00" * body_len)
            assert _recv_all(sock) == b""
        rejected = _wait_counter(farm, obs_names.COORD_FRAMES_REJECTED,
                                 rejected + 1)
        _assert_distributer_alive(farm)


def test_session_rle_bomb_releases_claim_and_stays_alive(tmp_path):
    """A compression bomb — a tiny RLE body whose declared run lengths
    sum to far more than a tile — must be rejected by the decoder's
    total-size check (before any allocation at the claimed size), drop
    the session, release the claim, and leave the loop alive."""
    with CoordinatorHarness(str(tmp_path), [LevelSetting(1, MAX_ITER)],
                            exporter=False) as farm:
        with _dial(farm.distributer_port) as sock:
            flags = _session_hello(sock)
            assert flags & proto.SESSION_FLAG_RLE
            # Lease the only tile over the session so the upload passes
            # the claim check and actually reaches the decoder.
            sock.sendall(proto.SESSION_FRAME.pack(proto.FRAME_LEASE_REQ,
                                                  0, 4) + U32.pack(1))
            hdr = b""
            while len(hdr) < proto.SESSION_FRAME_WIRE_SIZE:
                hdr += sock.recv(proto.SESSION_FRAME_WIRE_SIZE - len(hdr))
            frame_type, seq, length = proto.SESSION_FRAME.unpack(hdr)
            assert frame_type == proto.FRAME_LEASE_GRANT and seq == 0
            payload = b""
            while len(payload) < length:
                payload += sock.recv(length - len(payload))
            assert U32.unpack(payload[:4])[0] == 1
            wire = payload[4:20]
            # 1000 runs of 0xFFFF_FFFF pixels each: ~4 TiB declared in a
            # 5 KB body.
            bomb = struct.pack("<IB", 0xFFFF_FFFF, 7) * 1000
            sock.sendall(proto.SESSION_FRAME.pack(
                proto.FRAME_UPLOAD, 1,
                16 + proto.UPLOAD_HEADER_WIRE_SIZE + len(bomb)))
            sock.sendall(wire
                         + proto.UPLOAD_HEADER.pack(proto.WIRE_CODEC_RLE, 0)
                         + bomb)
            assert _recv_all(sock) == b""
        _wait_counter(farm, obs_names.COORD_FRAMES_REJECTED, 1)
        _wait_counter(farm, obs_names.COORD_RESULTS_DROPPED, 1)
        # The claim was released: the tile is grantable again, and serving
        # the probe at all proves the loop survived the bomb.
        deadline = time.monotonic() + 10
        regranted = False
        while time.monotonic() < deadline and not regranted:
            with _dial(farm.distributer_port) as sock:
                sock.sendall(bytes([proto.PURPOSE_REQUEST]))
                status = sock.recv(1)
                regranted = status[0] == proto.WORKLOAD_AVAILABLE
        assert regranted, "bombed tile never returned to the frontier"


def test_session_rejects_malformed_batched_lease_frames(tmp_path):
    """The GRANTN fuzz corpus: every malformed REQN drops the session,
    bumps COORD_FRAMES_REJECTED, and leaves the loop serving."""
    with CoordinatorHarness(str(tmp_path), [LevelSetting(1, MAX_ITER)],
                            exporter=False) as farm:
        rejected = 0
        want = proto.SESSION_FLAG_RLE | proto.SESSION_FLAG_GRANTN

        # Zero-count REQN: a worker with no room must not ask.
        with _dial(farm.distributer_port) as sock:
            flags = _session_hello(sock, want)
            assert flags & proto.SESSION_FLAG_GRANTN
            sock.sendall(proto.SESSION_FRAME.pack(
                proto.FRAME_LEASE_REQN, 0, proto.LEASE_REQN_WIRE_SIZE)
                + proto.LEASE_REQN.pack(0, 1))
            assert _recv_all(sock) == b""
        rejected = _wait_counter(farm, obs_names.COORD_FRAMES_REJECTED,
                                 rejected + 1)
        _assert_distributer_alive(farm)

        # Oversized count: a u32 far past MAX_BATCH, rejected before any
        # scheduler work or allocation sized by it.
        with _dial(farm.distributer_port) as sock:
            _session_hello(sock, want)
            sock.sendall(proto.SESSION_FRAME.pack(
                proto.FRAME_LEASE_REQN, 0, proto.LEASE_REQN_WIRE_SIZE)
                + proto.LEASE_REQN.pack(0xFFFF_FFFE, 1))
            assert _recv_all(sock) == b""
        rejected = _wait_counter(farm, obs_names.COORD_FRAMES_REJECTED,
                                 rejected + 1)
        _assert_distributer_alive(farm)

        # Group width past the requested count.
        with _dial(farm.distributer_port) as sock:
            _session_hello(sock, want)
            sock.sendall(proto.SESSION_FRAME.pack(
                proto.FRAME_LEASE_REQN, 0, proto.LEASE_REQN_WIRE_SIZE)
                + proto.LEASE_REQN.pack(2, 3))
            assert _recv_all(sock) == b""
        rejected = _wait_counter(farm, obs_names.COORD_FRAMES_REJECTED,
                                 rejected + 1)
        _assert_distributer_alive(farm)

        # Wrong declared frame length for a REQN.
        with _dial(farm.distributer_port) as sock:
            _session_hello(sock, want)
            sock.sendall(proto.SESSION_FRAME.pack(proto.FRAME_LEASE_REQN,
                                                  0, 4) + U32.pack(1))
            assert _recv_all(sock) == b""
        rejected = _wait_counter(farm, obs_names.COORD_FRAMES_REJECTED,
                                 rejected + 1)
        _assert_distributer_alive(farm)

        # Truncated REQN tail: 4 of 8 payload bytes, then close.
        with _dial(farm.distributer_port) as sock:
            _session_hello(sock, want)
            sock.sendall(proto.SESSION_FRAME.pack(
                proto.FRAME_LEASE_REQN, 0, proto.LEASE_REQN_WIRE_SIZE)
                + b"\x00" * 4)
        rejected = _wait_counter(farm, obs_names.COORD_FRAMES_REJECTED,
                                 rejected + 1)
        _assert_distributer_alive(farm)

        # REQN on a session that never negotiated the capability.
        with _dial(farm.distributer_port) as sock:
            flags = _session_hello(sock)  # RLE only
            assert not flags & proto.SESSION_FLAG_GRANTN
            sock.sendall(proto.SESSION_FRAME.pack(
                proto.FRAME_LEASE_REQN, 0, proto.LEASE_REQN_WIRE_SIZE)
                + proto.LEASE_REQN.pack(1, 1))
            assert _recv_all(sock) == b""
        rejected = _wait_counter(farm, obs_names.COORD_FRAMES_REJECTED,
                                 rejected + 1)
        _assert_distributer_alive(farm)


def test_session_duplicate_upload_in_one_batch_rejected_not_fatal(tmp_path):
    """The same lease submitted twice in one pipelined batch: the first
    copy lands, the duplicate draws an in-band REJECT ack (its claim was
    already consumed and released), and nothing leaks."""
    with CoordinatorHarness(str(tmp_path), [LevelSetting(1, MAX_ITER)],
                            exporter=False) as farm:
        sess = DistributerSession("127.0.0.1", farm.distributer_port,
                                  counters=Counters())
        assert sess.connect()
        grants = sess.request_batchn(1)
        assert len(grants) == 1
        tile = np.full(CHUNK_PIXELS, 9, dtype=np.uint8)
        accepted, _ = sess.submit_pipelined([(grants[0], tile),
                                             (grants[0], tile)])
        assert accepted == [True, False]
        sess.close()
        _wait_counter(farm, obs_names.COORD_RESULTS_REJECTED, 1)
        farm.wait_saves_settled(expected_accepted=1)
        assert farm.scheduler.is_complete()
        _assert_distributer_alive(farm)


def test_client_rejects_truncated_batched_grant_tail():
    """A coordinator that dies mid-GRANTN must surface as a clean
    ConnectionError on the client — never a hang, never an allocation
    sized by the promised-but-undelivered tile count."""
    srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    srv.bind(("127.0.0.1", 0))
    srv.listen(1)
    port = srv.getsockname()[1]

    def serve() -> None:
        conn, _ = srv.accept()
        with conn:
            hello = b""
            while len(hello) < 1 + proto.SESSION_HELLO_WIRE_SIZE:
                more = conn.recv(1 + proto.SESSION_HELLO_WIRE_SIZE
                                 - len(hello))
                if not more:
                    return
                hello += more
            conn.sendall(bytes([proto.SESSION_ACCEPT])
                         + proto.SESSION_HELLO.pack(
                             proto.SESSION_FLAG_GRANTN))
            want = (proto.SESSION_FRAME_WIRE_SIZE
                    + proto.LEASE_REQN_WIRE_SIZE)
            req = b""
            while len(req) < want:
                more = conn.recv(want - len(req))
                if not more:
                    return
                req += more
            # Promise one group of 4 tiles, deliver only the first, then
            # hang up mid-tail.
            conn.sendall(proto.SESSION_FRAME.pack(
                proto.FRAME_LEASE_GRANTN, 0,
                proto.LEASE_GRANTN_WIRE_SIZE + 4 + 4 * 16))
            conn.sendall(proto.LEASE_GRANTN.pack(1, 4) + U32.pack(4)
                         + Workload(64, 50, 0, 0).to_wire())

    t = threading.Thread(target=serve, daemon=True)
    t.start()
    try:
        sess = DistributerSession("127.0.0.1", port, compress=False,
                                  timeout=10, counters=Counters())
        assert sess.connect()
        assert sess.flags & proto.SESSION_FLAG_GRANTN
        with pytest.raises(ConnectionError):
            sess.request_batchn(4)
        sess.close()
    finally:
        srv.close()
        t.join(timeout=10)


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    data = b""
    while len(data) < n:
        more = sock.recv(n - len(data))
        if not more:
            raise ConnectionError(f"peer closed after {len(data)}/{n} bytes")
        data += more
    return data


def _shard_farm(tmp_path, level: int = 4):
    """A 2-shard ring with a live coordinator serving shard 0's slice."""
    from distributedmandelbrot_tpu.control.ring import HashRing

    ring = HashRing.local(2)
    farm = CoordinatorHarness(str(tmp_path), [LevelSetting(level, MAX_ITER)],
                              exporter=False, ring_slice=ring.slice(0))
    return ring, farm


def test_session_ring_exchange_counts_skew_and_rejects_malformed(tmp_path):
    """The ring-exchange fuzz corpus: a stale client version is counted
    as skew but still answered (the reply IS the correction); every
    protocol violation drops the session, bumps COORD_FRAMES_REJECTED,
    and leaves the loop serving."""
    ring, farm = _shard_farm(tmp_path)
    with farm:
        want = proto.SESSION_FLAG_RLE | proto.SESSION_FLAG_SHARD

        # Well-formed exchange, matching version: RING_INFO, no skew.
        with _dial(farm.distributer_port) as sock:
            flags = _session_hello(sock, want)
            assert flags & proto.SESSION_FLAG_SHARD
            sock.sendall(proto.SESSION_FRAME.pack(
                proto.FRAME_RING_REQ, 0, proto.RING_REQ_WIRE_SIZE)
                + proto.RING_REQ.pack(ring.version))
            frame_type, seq, length = proto.SESSION_FRAME.unpack(
                _recv_exact(sock, proto.SESSION_FRAME_WIRE_SIZE))
            assert (frame_type, seq, length) == (
                proto.FRAME_RING_INFO, 0, proto.RING_INFO_WIRE_SIZE)
            assert proto.RING_INFO.unpack(
                _recv_exact(sock, proto.RING_INFO_WIRE_SIZE)) \
                == (ring.version, 0, 2)

            # Wrong ring version on the same session: answered (with the
            # authoritative version), but counted as skew.
            sock.sendall(proto.SESSION_FRAME.pack(
                proto.FRAME_RING_REQ, 1, proto.RING_REQ_WIRE_SIZE)
                + proto.RING_REQ.pack(99))
            frame_type, seq, length = proto.SESSION_FRAME.unpack(
                _recv_exact(sock, proto.SESSION_FRAME_WIRE_SIZE))
            assert frame_type == proto.FRAME_RING_INFO and seq == 1
            version, shard, n_shards = proto.RING_INFO.unpack(
                _recv_exact(sock, proto.RING_INFO_WIRE_SIZE))
            assert version == ring.version  # the correction, not an echo
        assert farm.counters.get(obs_names.COORD_SHARD_RING_REQS) == 2
        assert farm.counters.get(obs_names.COORD_SHARD_RING_SKEW) == 1
        rejected = 0

        # Ring request on a session that never negotiated sharding.
        with _dial(farm.distributer_port) as sock:
            flags = _session_hello(sock)  # RLE only
            assert not flags & proto.SESSION_FLAG_SHARD
            sock.sendall(proto.SESSION_FRAME.pack(
                proto.FRAME_RING_REQ, 0, proto.RING_REQ_WIRE_SIZE)
                + proto.RING_REQ.pack(ring.version))
            assert _recv_all(sock) == b""
        rejected = _wait_counter(farm, obs_names.COORD_FRAMES_REJECTED,
                                 rejected + 1)
        _assert_distributer_alive(farm)

        # Wrong declared frame length for a ring request.
        with _dial(farm.distributer_port) as sock:
            _session_hello(sock, want)
            sock.sendall(proto.SESSION_FRAME.pack(proto.FRAME_RING_REQ,
                                                  0, 2) + b"\x00\x00")
            assert _recv_all(sock) == b""
        rejected = _wait_counter(farm, obs_names.COORD_FRAMES_REJECTED,
                                 rejected + 1)
        _assert_distributer_alive(farm)

        # Truncated ring request: 2 of 4 payload bytes, then close.
        with _dial(farm.distributer_port) as sock:
            _session_hello(sock, want)
            sock.sendall(proto.SESSION_FRAME.pack(
                proto.FRAME_RING_REQ, 0, proto.RING_REQ_WIRE_SIZE)
                + b"\x00\x00")
        rejected = _wait_counter(farm, obs_names.COORD_FRAMES_REJECTED,
                                 rejected + 1)
        _assert_distributer_alive(farm)


def test_session_misrouted_upload_draws_redirect_not_accept(tmp_path):
    """A key outside this shard's slice: a SHARD session's upload is
    answered with FRAME_REDIRECT naming the authoritative shard (and the
    session survives); a down-negotiated session gets a plain REJECT
    ack.  Either way the misroute is counted and nothing is stored."""
    ring, farm = _shard_farm(tmp_path)
    with farm:
        foreign = next(Workload(4, MAX_ITER, i, j)
                       for i in range(4) for j in range(4)
                       if ring.owner_of((4, i, j)) == 1)

        with _dial(farm.distributer_port) as sock:
            _session_hello(sock,
                           proto.SESSION_FLAG_RLE | proto.SESSION_FLAG_SHARD)
            sock.sendall(proto.SESSION_FRAME.pack(
                proto.FRAME_UPLOAD, 0,
                16 + proto.UPLOAD_HEADER_WIRE_SIZE + CHUNK_PIXELS))
            sock.sendall(foreign.to_wire()
                         + proto.UPLOAD_HEADER.pack(proto.WIRE_CODEC_RAW, 0))
            sock.sendall(b"\x00" * CHUNK_PIXELS)
            frame_type, seq, length = proto.SESSION_FRAME.unpack(
                _recv_exact(sock, proto.SESSION_FRAME_WIRE_SIZE))
            assert (frame_type, seq, length) == (
                proto.FRAME_REDIRECT, 0, proto.REDIRECT_WIRE_SIZE)
            owner, version = proto.REDIRECT.unpack(
                _recv_exact(sock, proto.REDIRECT_WIRE_SIZE))
            assert owner == 1 and version == ring.version
            # The redirect is an ack, not a drop: the same session still
            # serves a lease request afterwards.
            sock.sendall(proto.SESSION_FRAME.pack(proto.FRAME_LEASE_REQ,
                                                  1, 4) + U32.pack(1))
            frame_type, seq, _ = proto.SESSION_FRAME.unpack(
                _recv_exact(sock, proto.SESSION_FRAME_WIRE_SIZE))
            assert frame_type == proto.FRAME_LEASE_GRANT and seq == 1
        assert farm.counters.get(obs_names.COORD_SHARD_MISROUTES) == 1
        assert farm.counters.get(obs_names.COORD_SHARD_REDIRECTS) == 1

        # A legacy (down-negotiated) session can't be redirected — the
        # misroute draws an in-band REJECT ack instead of an accept.
        with _dial(farm.distributer_port) as sock:
            flags = _session_hello(sock)  # RLE only, no SHARD
            assert not flags & proto.SESSION_FLAG_SHARD
            sock.sendall(proto.SESSION_FRAME.pack(
                proto.FRAME_UPLOAD, 0,
                16 + proto.UPLOAD_HEADER_WIRE_SIZE + CHUNK_PIXELS))
            sock.sendall(foreign.to_wire()
                         + proto.UPLOAD_HEADER.pack(proto.WIRE_CODEC_RAW, 0))
            sock.sendall(b"\x00" * CHUNK_PIXELS)
            frame_type, seq, _ = proto.SESSION_FRAME.unpack(
                _recv_exact(sock, proto.SESSION_FRAME_WIRE_SIZE))
            assert frame_type == proto.FRAME_UPLOAD_ACK and seq == 0
            assert _recv_exact(sock, 1)[0] == proto.RESPONSE_REJECT
        assert farm.counters.get(obs_names.COORD_SHARD_MISROUTES) == 2
        _wait_counter(farm, obs_names.COORD_RESULTS_REJECTED, 1)
        assert farm.scheduler.completed_count == 0
        _assert_distributer_alive(farm)


class _StubRing:
    """Duck-typed ring for client-side redirect fuzzing: every key is
    owned by shard ``owner``, endpoints are the fake servers'."""

    version = 1

    def __init__(self, ports, owner: int = 0) -> None:
        class _S:
            def __init__(self, port: int) -> None:
                self.host = "127.0.0.1"
                self.distributer_port = port
        self.shards = [_S(p) for p in ports]
        self._owner = owner

    def owner_of(self, key) -> int:
        return self._owner


def _fake_shard_server(shard: int, n_shards: int, redirect_to: int,
                       truncate: bool = False):
    """One-connection fake coordinator: negotiates SHARD, answers ring
    requests honestly, and answers EVERY upload with a REDIRECT to
    ``redirect_to`` (truncated mid-payload when ``truncate``)."""
    srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    srv.bind(("127.0.0.1", 0))
    srv.listen(1)

    def serve() -> None:
        conn, _ = srv.accept()
        with conn:
            try:
                hello = _recv_exact(conn, 1 + proto.SESSION_HELLO_WIRE_SIZE)
                (offered,) = proto.SESSION_HELLO.unpack(hello[1:])
                conn.sendall(bytes([proto.SESSION_ACCEPT])
                             + proto.SESSION_HELLO.pack(
                                 offered & proto.SESSION_FLAG_SHARD))
                while True:
                    hdr = _recv_exact(conn,
                                      proto.SESSION_FRAME_WIRE_SIZE)
                    frame_type, seq, length = proto.SESSION_FRAME.unpack(
                        hdr)
                    _recv_exact(conn, length)
                    if frame_type == proto.FRAME_RING_REQ:
                        conn.sendall(proto.SESSION_FRAME.pack(
                            proto.FRAME_RING_INFO, seq,
                            proto.RING_INFO_WIRE_SIZE)
                            + proto.RING_INFO.pack(1, shard, n_shards))
                    elif frame_type == proto.FRAME_UPLOAD:
                        redirect = proto.REDIRECT.pack(redirect_to, 1)
                        if truncate:
                            conn.sendall(proto.SESSION_FRAME.pack(
                                proto.FRAME_REDIRECT, seq,
                                proto.REDIRECT_WIRE_SIZE) + redirect[:4])
                            return  # hang up mid-redirect
                        conn.sendall(proto.SESSION_FRAME.pack(
                            proto.FRAME_REDIRECT, seq,
                            proto.REDIRECT_WIRE_SIZE) + redirect)
            except (ConnectionError, OSError):
                return

    t = threading.Thread(target=serve, daemon=True)
    t.start()
    return srv, t, srv.getsockname()[1]


def test_client_caps_self_redirect_as_loop():
    """A shard redirecting a result back at itself is a split-brain
    ring, not a routing error: the client must count it in
    worker_redirect_loops and report the result rejected — never chase."""
    from distributedmandelbrot_tpu.worker.client import ShardedSessionGroup

    srv, t, port = _fake_shard_server(0, 1, redirect_to=0)
    counters = Counters()
    try:
        group = ShardedSessionGroup(_StubRing([port]), timeout=10,
                                    counters=counters)
        assert group.connect()
        tile = np.zeros(CHUNK_PIXELS, dtype=np.uint8)
        accepted, grants = group.submit_pipelined(
            [(Workload(4, MAX_ITER, 0, 0), tile)])
        assert accepted == [False] and grants == []
        assert counters.get(obs_names.WORKER_REDIRECTS) == 1
        assert counters.get(obs_names.WORKER_REDIRECT_LOOPS) == 1
        group.close()
    finally:
        srv.close()
        t.join(timeout=10)


def test_client_caps_redirect_pingpong_at_hop_budget():
    """Two shards bouncing a result between each other: the chase stops
    at MAX_REDIRECT_HOPS, counts a loop, and reports the result
    rejected — bounded work under a fully adversarial ring."""
    from distributedmandelbrot_tpu.worker.client import ShardedSessionGroup

    srv_a, t_a, port_a = _fake_shard_server(0, 2, redirect_to=1)
    srv_b, t_b, port_b = _fake_shard_server(1, 2, redirect_to=0)
    counters = Counters()
    try:
        group = ShardedSessionGroup(_StubRing([port_a, port_b]), timeout=10,
                                    counters=counters)
        assert group.connect()
        tile = np.zeros(CHUNK_PIXELS, dtype=np.uint8)
        accepted, grants = group.submit_pipelined(
            [(Workload(4, MAX_ITER, 0, 0), tile)])
        assert accepted == [False] and grants == []
        # One redirect per hop plus the budget-exhausting last upload.
        assert counters.get(obs_names.WORKER_REDIRECTS) \
            == proto.MAX_REDIRECT_HOPS + 1
        assert counters.get(obs_names.WORKER_REDIRECT_LOOPS) == 1
        group.close()
    finally:
        srv_a.close()
        srv_b.close()
        t_a.join(timeout=10)
        t_b.join(timeout=10)


def test_client_rejects_truncated_redirect():
    """A coordinator that dies mid-REDIRECT must surface as a clean
    ConnectionError on the client — never a hang, never a partial
    redirect treated as routable."""
    from distributedmandelbrot_tpu.worker.client import ShardedSessionGroup

    srv, t, port = _fake_shard_server(0, 1, redirect_to=0, truncate=True)
    try:
        group = ShardedSessionGroup(_StubRing([port]), timeout=10,
                                    counters=Counters())
        assert group.connect()
        tile = np.zeros(CHUNK_PIXELS, dtype=np.uint8)
        with pytest.raises(ConnectionError):
            group.submit_pipelined([(Workload(4, MAX_ITER, 0, 0), tile)])
        group.close()
    finally:
        srv.close()
        t.join(timeout=10)


def test_dataserver_rejects_malformed_queries_and_stays_alive(tmp_path):
    with CoordinatorHarness(str(tmp_path), [LevelSetting(1, MAX_ITER)],
                            exporter=False) as farm:
        # Out-of-range tile keys: a REJECT reply + counter, per query.
        for key in ((0, 0, 0), (1, 1, 0), (1, 0, 1),
                    (proto.GATEWAY_BATCH_MAGIC, 0, 0)):
            with _dial(farm.dataserver_port) as sock:
                sock.sendall(proto.QUERY.pack(*key))
                status = sock.recv(1)
                assert status[0] == proto.QUERY_REJECT
        _wait_counter(farm, obs_names.DATASERVER_QUERIES_REJECTED, 4)

        # Truncated query: 6 of 12 bytes, then close.
        with _dial(farm.dataserver_port) as sock:
            sock.sendall(proto.QUERY.pack(1, 0, 0)[:6])
        _wait_counter(farm, obs_names.COORD_FRAMES_REJECTED, 1)
        _assert_dataserver_alive(farm)


def test_gateway_rejects_malformed_frames_and_stays_alive(tmp_path):
    with CoordinatorHarness(str(tmp_path), [LevelSetting(1, MAX_ITER)],
                            exporter=False) as farm:
        rejected = 0

        # Out-of-range single query: REJECT reply + counter.
        with _dial(farm.gateway_port) as sock:
            sock.sendall(proto.QUERY.pack(0, 3, 3))
            status = sock.recv(1)
            assert status[0] == proto.QUERY_REJECT
        assert _wait_counter(farm, obs_names.GATEWAY_REJECTED, 1) >= 1

        # Oversized batch count: dropped + counted.
        with _dial(farm.gateway_port) as sock:
            sock.sendall(U32.pack(proto.GATEWAY_BATCH_MAGIC)
                         + U32.pack(1 << 20))
            assert _recv_all(sock) == b""
        rejected = _wait_counter(farm, obs_names.GATEWAY_FRAMES_REJECTED,
                                 rejected + 1)
        _assert_gateway_alive(farm)

        # Empty batch: also a protocol violation (the magic promised
        # queries).
        with _dial(farm.gateway_port) as sock:
            sock.sendall(U32.pack(proto.GATEWAY_BATCH_MAGIC) + U32.pack(0))
            assert _recv_all(sock) == b""
        rejected = _wait_counter(farm, obs_names.GATEWAY_FRAMES_REJECTED,
                                 rejected + 1)
        _assert_gateway_alive(farm)

        # Truncated query tail: the first u32 arrived, the 8-byte tail
        # stops after 4.
        with _dial(farm.gateway_port) as sock:
            sock.sendall(U32.pack(2) + b"\x00" * 4)
        rejected = _wait_counter(farm, obs_names.GATEWAY_FRAMES_REJECTED,
                                 rejected + 1)
        _assert_gateway_alive(farm)


def test_gateway_rejects_malformed_render_frames_and_stays_alive(tmp_path):
    with CoordinatorHarness(str(tmp_path), [LevelSetting(1, MAX_ITER)],
                            exporter=False) as farm:
        rejected = 0

        # Truncated render tail: magic promised 14 bytes, 6 arrive.
        with _dial(farm.gateway_port) as sock:
            sock.sendall(U32.pack(proto.GATEWAY_RENDER_MAGIC)
                         + proto.RENDER_QUERY_TAIL.pack(
                             1, 0, 0, proto.COLORMAP_JET, 0)[:6])
            assert _recv_all(sock) == b""
        rejected = _wait_counter(farm, obs_names.GATEWAY_FRAMES_REJECTED,
                                 rejected + 1)
        _assert_gateway_alive(farm)

        # Unknown colormap id: clean drop, its own named counter (a fleet
        # of version-skewed viewers must show up as a spike), plus the
        # generic frames-rejected trail.
        with _dial(farm.gateway_port) as sock:
            sock.sendall(U32.pack(proto.GATEWAY_RENDER_MAGIC)
                         + proto.RENDER_QUERY_TAIL.pack(1, 0, 0, 0xEE, 0))
            assert _recv_all(sock) == b""
        assert _wait_counter(
            farm, obs_names.GATEWAY_RENDER_UNKNOWN_COLORMAP, 1) >= 1
        rejected = _wait_counter(farm, obs_names.GATEWAY_FRAMES_REJECTED,
                                 rejected + 1)
        _assert_gateway_alive(farm)

        # Reserved flags set: dropped before any render work happens.
        with _dial(farm.gateway_port) as sock:
            sock.sendall(U32.pack(proto.GATEWAY_RENDER_MAGIC)
                         + proto.RENDER_QUERY_TAIL.pack(
                             1, 0, 0, proto.COLORMAP_JET, 0x80))
            assert _recv_all(sock) == b""
        rejected = _wait_counter(farm, obs_names.GATEWAY_FRAMES_REJECTED,
                                 rejected + 1)
        _assert_gateway_alive(farm)

        # Out-of-range render query (level 0): an in-band REJECT, not a
        # drop — same contract as the raw framing.
        with _dial(farm.gateway_port) as sock:
            sock.sendall(U32.pack(proto.GATEWAY_RENDER_MAGIC)
                         + proto.RENDER_QUERY_TAIL.pack(
                             0, 0, 0, proto.COLORMAP_JET, 0))
            status = sock.recv(1)
            assert status[0] == proto.QUERY_REJECT
        _assert_gateway_alive(farm)


def test_gateway_rejects_malformed_session_frames_and_stays_alive(tmp_path):
    """The session-query fuzz corpus: truncated tails and unknown flag
    bits drop the connection behind named counters; a bad session id is
    a *soft* reject (the reply says "reopen") on a live connection."""
    with CoordinatorHarness(str(tmp_path), [LevelSetting(1, MAX_ITER)],
                            exporter=False) as farm:
        rejected = 0

        # Truncated session tail: magic promised 22 bytes, 10 arrive.
        with _dial(farm.gateway_port) as sock:
            sock.sendall(U32.pack(proto.GATEWAY_SESSION_MAGIC)
                         + proto.SESSION_QUERY_TAIL.pack(
                             0, 1, 0, 0, proto.COLORMAP_JET, 0)[:10])
            assert _recv_all(sock) == b""
        rejected = _wait_counter(farm, obs_names.GATEWAY_FRAMES_REJECTED,
                                 rejected + 1)
        _assert_gateway_alive(farm)

        # Unknown capability flag bits: named counter + drop, before any
        # session state is touched.
        with _dial(farm.gateway_port) as sock:
            sock.sendall(U32.pack(proto.GATEWAY_SESSION_MAGIC)
                         + proto.SESSION_QUERY_TAIL.pack(
                             0, 1, 0, 0, proto.COLORMAP_JET, 0x80))
            assert _recv_all(sock) == b""
        assert _wait_counter(farm, obs_names.SESSION_BAD_FLAGS, 1) >= 1
        rejected = _wait_counter(farm, obs_names.GATEWAY_FRAMES_REJECTED,
                                 rejected + 1)
        _assert_gateway_alive(farm)

        # Unknown colormap on the session tail: same named counter as
        # the render framing, same drop.
        with _dial(farm.gateway_port) as sock:
            sock.sendall(U32.pack(proto.GATEWAY_SESSION_MAGIC)
                         + proto.SESSION_QUERY_TAIL.pack(
                             0, 1, 0, 0, 0xEE, 0))
            assert _recv_all(sock) == b""
        assert _wait_counter(
            farm, obs_names.GATEWAY_RENDER_UNKNOWN_COLORMAP, 1) >= 1
        rejected = _wait_counter(farm, obs_names.GATEWAY_FRAMES_REJECTED,
                                 rejected + 1)
        _assert_gateway_alive(farm)

        # Never-issued session id: soft reject.  The reply header carries
        # sid 0 ("reopen on your next query") + an in-band REJECT — the
        # connection must stay open, because id expiry is a normal
        # lifecycle event, not a protocol violation.
        with _dial(farm.gateway_port) as sock:
            sock.sendall(U32.pack(proto.GATEWAY_SESSION_MAGIC)
                         + proto.SESSION_QUERY_TAIL.pack(
                             0xDEAD_BEEF, 1, 0, 0, proto.COLORMAP_JET, 0))
            sid, caps = proto.SESSION_REPLY.unpack(
                _recv_exact(sock, proto.SESSION_REPLY_WIRE_SIZE))
            assert (sid, caps) == (0, 0)
            assert _recv_exact(sock, 1)[0] == proto.QUERY_REJECT
        assert _wait_counter(farm, obs_names.SESSION_UNKNOWN, 1) >= 1
        _assert_gateway_alive(farm)

        # Out-of-range key on a fresh open: the session IS issued (the
        # viewport hint is bad, the viewer is not), then an in-band
        # REJECT — and the issued id is honoured on the next query.
        with _dial(farm.gateway_port) as sock:
            sock.sendall(U32.pack(proto.GATEWAY_SESSION_MAGIC)
                         + proto.SESSION_QUERY_TAIL.pack(
                             0, 0, 0, 0, proto.COLORMAP_JET,
                             proto.SESSION_CAPS_MASK))
            sid, caps = proto.SESSION_REPLY.unpack(
                _recv_exact(sock, proto.SESSION_REPLY_WIRE_SIZE))
            assert sid != 0
            assert caps & proto.SESSION_CAP_PREFETCH
            assert _recv_exact(sock, 1)[0] == proto.QUERY_REJECT
        with _dial(farm.gateway_port) as sock:
            sock.sendall(U32.pack(proto.GATEWAY_SESSION_MAGIC)
                         + proto.SESSION_QUERY_TAIL.pack(
                             sid, 0, 0, 0, proto.COLORMAP_JET, 0))
            sid2, _ = proto.SESSION_REPLY.unpack(
                _recv_exact(sock, proto.SESSION_REPLY_WIRE_SIZE))
            assert sid2 == sid
            assert _recv_exact(sock, 1)[0] == proto.QUERY_REJECT
        _assert_gateway_alive(farm)
