"""Store backend tests: object-store kv semantics, the segmented-index
layout (tail objects, rotation, atomic manifest), and ChunkStore parity
across LocalFileBackend and ObjectStoreBackend."""

import os

import numpy as np
import pytest

from distributedmandelbrot_tpu.core import CHUNK_PIXELS, Chunk
from distributedmandelbrot_tpu.storage import (ChunkStore, DataDirError,
                                               DirObjectStore,
                                               LocalFileBackend,
                                               MemoryObjectStore,
                                               ObjectStoreBackend)


def patterned_chunk(level=8, i=1, j=2, period=97):
    data = (np.arange(CHUNK_PIXELS) % period).astype(np.uint8)
    return Chunk(level, i, j, data)


# -- raw kv stores ---------------------------------------------------------


@pytest.fixture(params=["memory", "dir"])
def kv(request, tmp_path):
    if request.param == "memory":
        return MemoryObjectStore()
    return DirObjectStore(str(tmp_path / "kv"))


def test_kv_put_get_size_delete(kv):
    assert kv.get("a/b") is None
    assert kv.size("a/b") is None
    kv.put("a/b", b"hello")
    assert kv.get("a/b") == b"hello"
    assert kv.size("a/b") == 5
    kv.put("a/b", b"clobbered")  # puts replace atomically
    assert kv.get("a/b") == b"clobbered"
    kv.delete("a/b")
    assert kv.get("a/b") is None
    kv.delete("a/b")  # idempotent


def test_kv_list_prefix(kv):
    kv.put("index/tail-000000000001", b"x")
    kv.put("index/tail-000000000002", b"y")
    kv.put("blobs/8;1;2", b"z")
    assert sorted(kv.list("index/")) == ["index/tail-000000000001",
                                         "index/tail-000000000002"]
    assert kv.list("blobs/") == ["blobs/8;1;2"]
    assert kv.list("nope/") == []


def test_dir_object_store_rejects_escapes(tmp_path):
    kv = DirObjectStore(str(tmp_path / "kv"))
    with pytest.raises(ValueError):
        kv.put("../escape", b"x")
    with pytest.raises(ValueError):
        kv.put("/absolute", b"x")


# -- object-store index layout --------------------------------------------


def test_object_backend_append_offsets_and_read():
    be = ObjectStoreBackend(MemoryObjectStore())
    be.setup()
    assert be.index_size() == 0
    assert be.append_index(b"aaaa") == 4
    assert be.append_index(b"bbbbbb") == 10
    assert be.read_index() == b"aaaabbbbbb"
    assert be.read_index(4) == b"bbbbbb"
    assert be.read_index(7) == b"bbb"  # mid-object offsets work
    assert be.index_size() == 10


def test_object_backend_rotation_seals_segments():
    kv = MemoryObjectStore()
    be = ObjectStoreBackend(kv, rotate_threshold=3)
    be.setup()
    for i in range(7):
        be.append_index(bytes([i]) * 2)
    assert be.read_index() == b"".join(bytes([i]) * 2 for i in range(7))
    # Rotation merged tails into sealed segment objects and committed a
    # manifest; leftover tails (< threshold) stay as tail objects.
    assert any(k.startswith("index/seg-") for k in kv.list("index/"))
    assert kv.get("index/manifest") is not None
    # A fresh handle over the same kv reconstructs the identical stream.
    be2 = ObjectStoreBackend(kv)
    be2.setup()
    assert be2.read_index() == be.read_index()
    assert be2.index_size() == be.index_size()
    # And appends continue at the right offset.
    end = be2.append_index(b"zz")
    assert end == be.index_size() + 2
    assert be2.read_index(be.index_size()) == b"zz"


def test_object_backend_truncate_tail():
    be = ObjectStoreBackend(MemoryObjectStore(), rotate_threshold=100)
    be.setup()
    be.append_index(b"aaaa")
    be.append_index(b"bb")
    be.truncate_index(4)  # drop the torn tail object
    assert be.read_index() == b"aaaa"
    assert be.index_size() == 4
    be.append_index(b"cc")
    assert be.read_index() == b"aaaacc"


def test_object_backend_truncate_below_sealed_raises():
    be = ObjectStoreBackend(MemoryObjectStore(), rotate_threshold=2)
    be.setup()
    for _ in range(4):
        be.append_index(b"xxxx")  # forces at least one sealed segment
    with pytest.raises(ValueError):
        be.truncate_index(1)


def test_object_backend_blobs():
    be = ObjectStoreBackend(MemoryObjectStore())
    be.setup()
    assert be.get_blob("8;1;2") is None
    assert not be.blob_exists("8;1;2")
    be.put_blob("8;1;2", b"payload")
    assert be.get_blob("8;1;2") == b"payload"
    assert be.blob_exists("8;1;2")
    assert be.peek_blob("8;1;2", 3) == b"pay"
    assert be.list_blobs() == ["8;1;2"]


# -- ChunkStore over each backend -----------------------------------------


@pytest.fixture(params=["local", "object-memory", "object-dir"])
def backend_factory(request, tmp_path):
    """Callable returning a NEW backend handle over the SAME storage, so
    tests can simulate process restarts."""
    if request.param == "local":
        return lambda: LocalFileBackend(str(tmp_path))
    if request.param == "object-memory":
        kv = MemoryObjectStore()
        return lambda: ObjectStoreBackend(kv)
    kv_root = str(tmp_path / "objects")
    return lambda: ObjectStoreBackend(DirObjectStore(kv_root))


def test_chunkstore_roundtrip_any_backend(backend_factory):
    store = ChunkStore(backend=backend_factory())
    store.setup()
    c = patterned_chunk()
    store.save(c)
    store.save(Chunk.never(8, 0, 0))
    assert store.completed_keys() == {(8, 1, 2), (8, 0, 0)}
    got = store.load(8, 1, 2)
    assert got is not None and np.array_equal(got.data, c.data)
    # "Restart": a fresh store over the same storage sees everything.
    store2 = ChunkStore(backend=backend_factory())
    store2.setup()
    assert store2.completed_keys() == {(8, 1, 2), (8, 0, 0)}
    got2 = store2.load(8, 1, 2)
    assert got2 is not None and np.array_equal(got2.data, c.data)


def test_chunkstore_suffix_replay_any_backend(backend_factory):
    store = ChunkStore(backend=backend_factory())
    store.setup()
    store.save(Chunk.never(8, 0, 0))
    offset = store.index_offset()
    store.save(Chunk.never(8, 1, 1))
    store.save(Chunk.never(8, 2, 2))
    suffix = store.entries_from(offset)
    assert [e.key for e in suffix] == [(8, 1, 1), (8, 2, 2)]


def test_local_backend_layout_unchanged(tmp_path):
    """The default backend writes the historical on-disk layout: a
    ``Data/`` dir, ``_index.dat`` inside it, ``level;re;im`` blobs."""
    store = ChunkStore(str(tmp_path))
    store.setup()
    store.save(patterned_chunk())
    data_dir = tmp_path / "Data"
    assert (data_dir / "_index.dat").is_file()
    assert (data_dir / "8;1;2").is_file()
    # Raw index bytes == what the backend reports (byte compatibility).
    raw = (data_dir / "_index.dat").read_bytes()
    assert store.backend.read_index() == raw


def test_local_backend_unwritable_parent():
    with pytest.raises(DataDirError):
        ChunkStore(backend=LocalFileBackend(
            os.path.join(os.sep, "proc", "definitely", "not",
                         "writable"))).setup()
