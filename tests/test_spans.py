"""Distributed-tracing tests: NTP-style clock alignment, the worker span
recorder, the coordinator span store merge, critical-path attribution,
Chrome trace rendering, and the 0x04 wire push end-to-end (including the
legacy-coordinator degradation path)."""

import json
import threading
import urllib.request

import pytest

from distributedmandelbrot_tpu.core import LevelSetting
from distributedmandelbrot_tpu.obs import names as obs_names
from distributedmandelbrot_tpu.obs.chrome import render_chrome_trace
from distributedmandelbrot_tpu.obs.spans import (ClockOffsetEstimator, Span,
                                                 SpanRecorder, SpanStore,
                                                 critical_path)
from distributedmandelbrot_tpu.obs.trace import TraceLog
from distributedmandelbrot_tpu.worker import (DistributerClient, NumpyBackend,
                                              Worker)

from harness import CoordinatorHarness


# -- clock-offset estimation ------------------------------------------------

def test_offset_estimator_skewed_clocks_asymmetric_rtt():
    """Two virtual clocks with a known skew and an asymmetric round trip:
    the NTP midpoint lands within the advertised error bound even though
    the uplink/downlink split is 10x lopsided."""
    true_offset = 123.456  # coordinator clock - worker clock
    uplink, downlink = 0.02, 0.18

    est = ClockOffsetEstimator()
    t_req = 5.0  # worker clock at request send
    c_grant = t_req + true_offset + uplink  # coordinator stamps the grant
    t_recv = t_req + uplink + downlink
    est.add_sample(c_grant, t_req, t_recv)

    got = est.estimate
    assert got is not None
    assert got.error == pytest.approx((uplink + downlink) / 2)
    assert abs(got.offset - true_offset) <= got.error
    # The bound is tight here: the midpoint is off by exactly the
    # asymmetry, (downlink - uplink) / 2.
    assert abs(got.offset - true_offset) == pytest.approx(
        (downlink - uplink) / 2)


def test_offset_estimator_prefers_min_rtt_sample():
    true_offset = -42.0
    est = ClockOffsetEstimator()
    est.add_sample(10.0 + true_offset + 0.1, 10.0, 10.4)  # rtt 0.4
    loose = est.estimate
    assert loose.error == pytest.approx(0.2)
    # A later, tighter (symmetric) round trip takes over...
    est.add_sample(20.0 + true_offset + 0.005, 20.0, 20.01)
    tight = est.estimate
    assert tight.error == pytest.approx(0.005)
    assert tight.offset == pytest.approx(true_offset)
    # ...and a subsequent looser one does not regress the estimate.
    est.add_sample(30.0 + true_offset + 0.5, 30.0, 31.0)
    assert est.estimate == tight
    assert est.samples == 3
    # A clock-stepped (t_recv < t_req) sample is ignored outright.
    est.add_sample(40.0, 40.0, 39.0)
    assert est.samples == 3


# -- the worker-side recorder ----------------------------------------------

def test_recorder_grant_record_drain():
    rec = SpanRecorder(worker_id=7)
    keys = [(4, 0, 0), (4, 0, 1)]
    rec.note_grant(keys, 1.0, 1.2)
    rec.record(obs_names.SPAN_COMPUTE, keys[0], 1.3, 2.0)
    syncs, spans = rec.drain()
    # One sync sample per lease exchange (first key stands for it).
    assert len(syncs) == 1
    assert syncs[0].key == keys[0]
    assert (syncs[0].t_req, syncs[0].t_recv) == (1.0, 1.2)
    # A prefetch span per granted key + the recorded compute span, all
    # carrying the exchange's lease sequence.
    stages = sorted(s.stage for s in spans)
    assert stages == [obs_names.SPAN_COMPUTE, obs_names.SPAN_PREFETCH,
                      obs_names.SPAN_PREFETCH]
    assert {s.seq for s in spans} == {1}
    # drain() cleared everything.
    assert rec.drain() == ([], [])


def test_recorder_bounded_and_disableable():
    rec = SpanRecorder(capacity=2)
    for i in range(5):
        rec.record(obs_names.SPAN_COMPUTE, (1, 0, i), 0.0, 1.0)
    assert len(rec) == 2
    assert rec.dropped == 3
    rec.enabled = False
    rec.record(obs_names.SPAN_COMPUTE, (1, 0, 9), 0.0, 1.0)
    rec.note_grant([(1, 0, 9)], 0.0, 1.0)
    _, spans = rec.drain()
    assert all(s.key != (1, 0, 9) for s in spans)


# -- the coordinator-side store --------------------------------------------

def test_store_aligns_spans_at_read_time():
    """Coordinator base clock ~1000, worker base clock ~5: after one sync
    sample the worker's compute span lands inside the coordinator's
    granted->received interval, within the estimate's error bound — and
    a later, tighter sample retroactively improves the placement."""
    wid = 99
    store = SpanStore()
    key = (3, 1, 2)
    store.note_grant(key, 1000.2)
    assert store.grant_time(key) == 1000.2

    span = Span(obs_names.SPAN_COMPUTE, key, 5.3, 6.0, device=0, seq=1)
    assert store.ingest(wid, [span]) == 1
    # No sync sample yet: the span cannot be placed.
    assert store.unaligned == 1
    assert store.spans() == []

    # Worker sent the lease request at 5.0 (its clock), got the grant at
    # 5.2; the coordinator stamped it at 1000.2.  True offset is ~995.2
    # (grant stamped near t_recv), estimate 995.1 +/- 0.1.
    store.add_sync(wid, 1000.2, 5.0, 5.2)
    assert store.unaligned == 0
    [aligned] = store.spans()
    est = store.offset(wid)
    assert est.error == pytest.approx(0.1)
    assert aligned["t0"] == pytest.approx(5.3 + est.offset)
    assert aligned["align_error_s"] == pytest.approx(est.error)
    # Placement error is within the bound of the coordinator interval.
    assert aligned["t0"] >= 1000.2 - est.error
    # Durations never needed alignment.
    assert aligned["t1"] - aligned["t0"] == pytest.approx(0.7)

    # A tighter sample arriving LATER re-places the already-ingested
    # span (alignment happens at read time): the new offset is exactly
    # 995.0 +/- 0.01, moving t0 from ~1000.4 to 1000.3.
    store.add_sync(wid, 1000.7, 5.69, 5.71)
    [better] = store.spans()
    assert better["align_error_s"] == pytest.approx(0.01)
    assert better["t0"] == pytest.approx(5.3 + 995.0)

    # Per-tile stage seconds are offset-free.
    assert store.compute_seconds_by_key() == {key: pytest.approx(0.7)}


def test_store_spans_sorted_and_per_worker_offsets():
    store = SpanStore()
    store.add_sync(1, 100.0, 0.0, 0.0)  # worker 1: offset exactly +100
    store.add_sync(2, 500.0, 0.0, 0.0)  # worker 2: offset exactly +500
    store.ingest(2, [Span(obs_names.SPAN_UPLOAD, (1, 0, 0), 1.0, 2.0)])
    store.ingest(1, [Span(obs_names.SPAN_COMPUTE, (1, 0, 0), 3.0, 4.0)])
    out = store.spans()
    assert [s["t0"] for s in out] == [103.0, 501.0]  # merged order, not
    assert [s["worker"] for s in out] == [1, 2]      # ingest order


# -- worker_skew busy-source fix -------------------------------------------

def _ticking_clock():
    t = [0.0]

    def clock():
        t[0] += 1.0
        return t[0]

    return clock


def test_worker_skew_busy_source_labels():
    """busy_s derives from worker-reported compute spans when present
    (labeled "reported"); the grant->receive fallback — which also
    contains network + upload time — is labeled "lease", a mix "mixed"."""
    log = TraceLog(clock=_ticking_clock())
    keys = [(4, 0, 0), (4, 0, 1)]
    for key in keys:
        log.record("granted", key, worker="w:1")
        log.record("result_received", key, worker="w:1")
    skew = log.worker_skew()
    w1 = skew["workers"]["w:1"]
    assert w1["busy_source"] == "lease"
    assert w1["busy_s"] == pytest.approx(2.0)  # two 1 s lease intervals

    reported = {keys[0]: 0.25, keys[1]: 0.5}
    w1 = log.worker_skew(reported=reported)["workers"]["w:1"]
    assert w1["busy_source"] == "reported"
    assert w1["busy_s"] == pytest.approx(0.75)

    w1 = log.worker_skew(reported={keys[0]: 0.25})["workers"]["w:1"]
    assert w1["busy_source"] == "mixed"
    assert w1["busy_s"] == pytest.approx(1.25)


# -- critical-path attribution ---------------------------------------------

def test_critical_path_splits_blob_with_reported_stages():
    store = SpanStore()
    key_a, key_b = (5, 0, 0), (5, 0, 1)
    store.ingest(1, [
        Span(obs_names.SPAN_COMPUTE, key_a, 0.0, 0.7),  # includes d2h
        Span(obs_names.SPAN_D2H, key_a, 0.5, 0.7),
        Span(obs_names.SPAN_UPLOAD, key_a, 0.7, 0.8),
    ])
    trace_spans = [
        {"key": key_a, "complete": True, "total_s": 2.0, "queue_s": 0.5,
         "compute_s": 1.0, "persist_s": 0.3},
        {"key": key_b, "complete": True, "total_s": 1.0, "queue_s": 0.1,
         "compute_s": 0.6, "persist_s": 0.2},
        {"key": (5, 1, 1), "complete": False},  # ignored
    ]
    out = critical_path(trace_spans, store)
    assert out["tiles"] == 2
    assert out["attributed_tiles"] == 1
    # key_a splits: compute 0.5 (0.7 - d2h 0.2), d2h 0.2, upload 0.1,
    # other 0.2 (the 1.0 s blob's remainder).  key_b has no spans: its
    # whole 0.6 s blob falls to compute (lease fallback).
    assert out["compute_s"] == pytest.approx(1.1)
    assert out["d2h_s"] == pytest.approx(0.2)
    assert out["upload_s"] == pytest.approx(0.1)
    assert out["other_s"] == pytest.approx(0.2)
    assert out["queue_s"] == pytest.approx(0.6)
    assert out["persist_s"] == pytest.approx(0.5)
    assert out["total_s"] == pytest.approx(3.0)
    assert out["queue_share"] == pytest.approx(0.2)
    # No store at all: everything still attributes (to the fallback).
    bare = critical_path(trace_spans, None)
    assert bare["attributed_tiles"] == 0
    assert bare["compute_s"] == pytest.approx(1.6)


# -- Chrome trace rendering -------------------------------------------------

def _assert_valid_trace_events(doc):
    assert isinstance(doc["traceEvents"], list)
    for ev in doc["traceEvents"]:
        assert ev["ph"] in ("X", "M", "i"), ev
        assert isinstance(ev["pid"], int) and isinstance(ev["tid"], int)
        if ev["ph"] == "X":
            assert ev["ts"] >= 0 and ev["dur"] >= 0, ev
        if ev["ph"] == "M":
            assert ev["name"] in ("process_name", "thread_name")


def test_chrome_render_empty_and_merged():
    empty = render_chrome_trace(None, None)
    _assert_valid_trace_events(empty)
    assert empty["displayTimeUnit"] == "ms"
    assert all(e["ph"] == "M" for e in empty["traceEvents"])

    log = TraceLog(clock=_ticking_clock())
    key = (2, 0, 0)
    for name in ("scheduled", "granted", "result_received", "persisted",
                 "served"):
        log.record(name, key, worker="w:1")
    store = SpanStore()
    store.add_sync(7, 2.0, 0.0, 0.0)
    store.ingest(7, [Span(obs_names.SPAN_COMPUTE, key, 0.5, 1.0, device=1)])
    doc = render_chrome_trace(log, store)
    _assert_valid_trace_events(doc)
    names = {e["name"] for e in doc["traceEvents"]}
    assert {"queue", "in_flight", "persist", "served",
            obs_names.SPAN_COMPUTE} <= names
    # The worker's process row exists and the compute slice nests on a
    # device thread of it.
    [proc] = [e for e in doc["traceEvents"]
              if e["ph"] == "M" and e["name"] == "process_name"
              and e["pid"] >= 100]
    assert proc["args"]["name"] == f"worker {7:016x}"
    [compute] = [e for e in doc["traceEvents"]
                 if e["name"] == obs_names.SPAN_COMPUTE]
    assert compute["pid"] == proc["pid"] and compute["tid"] == 11


# -- end-to-end over the wire ----------------------------------------------

def _drain_in_threads(farm, n_workers, **worker_kwargs):
    workers = [Worker(DistributerClient("127.0.0.1", farm.distributer_port),
                      NumpyBackend(), **worker_kwargs)
               for _ in range(n_workers)]
    threads = [threading.Thread(target=w.run_until_drained, daemon=True)
               for w in workers]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=300)
    assert not any(t.is_alive() for t in threads)
    return workers


def test_farm_drain_produces_loadable_nested_trace_json(tmp_path):
    """Acceptance: a 2-worker drain yields /trace.json whose per-tile
    worker compute/upload spans nest inside the coordinator's granted ->
    result_received interval after clock alignment (within the
    advertised error bound, plus the ack tail for upload ends)."""
    with CoordinatorHarness(str(tmp_path), [LevelSetting(2, 12)]) as farm:
        workers = _drain_in_threads(farm, 2, batch_size=2)
        farm.wait_saves_settled(expected_accepted=4)

        pushed = sum(w.counters.get(obs_names.WORKER_SPANS_PUSHED)
                     for w in workers)
        assert pushed > 0
        assert farm.counters.get(obs_names.COORD_SPANS_INGESTED) == pushed
        assert all(w.counters.get(obs_names.WORKER_SPANS_UNSUPPORTED) == 0
                   for w in workers)

        url = f"http://127.0.0.1:{farm.exporter_port}/trace.json"
        doc = json.loads(urllib.request.urlopen(url, timeout=10).read())
    _assert_valid_trace_events(doc)
    events = doc["traceEvents"]
    in_flight = {e["args"]["key"]: e for e in events
                 if e["name"] == "in_flight"}
    assert len(in_flight) == 4
    checked = 0
    for name, end_slack_s in ((obs_names.SPAN_COMPUTE, 0.05),
                              (obs_names.SPAN_UPLOAD, 1.0)):
        for ev in (e for e in events if e["name"] == name):
            blob = in_flight[ev["args"]["key"]]
            tol_us = ev["args"]["align_error_s"] * 1e6 + 50_000
            assert ev["ts"] >= blob["ts"] - tol_us, (name, ev, blob)
            # Upload ends after the coordinator's ack reaches the
            # worker, so its tail gets extra slack beyond clock error.
            assert (ev["ts"] + ev["dur"]
                    <= blob["ts"] + blob["dur"] + tol_us
                    + end_slack_s * 1e6), (name, ev, blob)
            checked += 1
    # Every tile has a compute span and an upload span in view.
    assert checked >= 8


def test_legacy_coordinator_degrades_span_push(tmp_path):
    """Against a coordinator that rejects 0x04 (accept_spans=False: the
    unknown-purpose drop, exactly a pre-tracing build's behavior), the
    worker completes the drain with span push disabled — one
    worker_spans_unsupported bump, results all accepted, zero errors."""
    with CoordinatorHarness(str(tmp_path), [LevelSetting(2, 12)],
                            accept_spans=False) as farm:
        [worker] = _drain_in_threads(farm, 1, batch_size=4)
        farm.wait_saves_settled(expected_accepted=4)
        assert farm.scheduler.is_complete()
    assert worker.counters.get(obs_names.WORKER_RESULTS_ACCEPTED) == 4
    assert worker.counters.get(obs_names.WORKER_SPANS_UNSUPPORTED) == 1
    assert worker.counters.get(obs_names.WORKER_SPANS_PUSHED) == 0
    assert worker.counters.get(obs_names.WORKER_SPANS_DROPPED) > 0
    assert worker.client.span_push_disabled
    assert not worker.spans.enabled


def test_exporter_varz_carries_span_store_and_farm_trace(tmp_path):
    with CoordinatorHarness(str(tmp_path), [LevelSetting(1, 12)]) as farm:
        _drain_in_threads(farm, 1)
        farm.wait_saves_settled(expected_accepted=1)
        url = f"http://127.0.0.1:{farm.exporter_port}/varz"
        out = json.loads(urllib.request.urlopen(url, timeout=10).read())
    assert out["trace"]["span_store"]["workers"] == 1
    assert out["trace"]["span_store"]["ingested"] > 0
    ft = out["farm_trace"]
    assert ft["tiles"] == 1 and ft["attributed_tiles"] == 1
    # The skew summary upgraded to worker-reported busy time.
    [w] = out["trace"]["worker_skew"]["workers"].values()
    assert w["busy_source"] == "reported"
