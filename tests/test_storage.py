import io
import struct

import numpy as np
import pytest

from distributedmandelbrot_tpu.core import CHUNK_PIXELS, Chunk
from distributedmandelbrot_tpu.storage import (ChunkStore, CorruptIndexError,
                                               EntryType, IndexEntry,
                                               scan_entries)


def patterned_chunk(level=4, i=1, j=2, period=97):
    data = (np.arange(CHUNK_PIXELS) % period).astype(np.uint8)
    return Chunk(level, i, j, data)


def test_index_entry_byte_format():
    """Byte-compatible with the reference: u32 x3 + int32 type
    (+ int32 len + ASCII name for Regular)."""
    e = IndexEntry(10, 3, 7, EntryType.REGULAR, "10;3;7")
    assert e.to_bytes() == struct.pack("<IIIi", 10, 3, 7, 0) + \
        struct.pack("<i", 6) + b"10;3;7"
    n = IndexEntry(10, 3, 7, EntryType.NEVER)
    assert n.to_bytes() == struct.pack("<IIIi", 10, 3, 7, 1)
    i = IndexEntry(10, 3, 7, EntryType.IMMEDIATE)
    assert i.to_bytes() == struct.pack("<IIIi", 10, 3, 7, 2)


def test_index_scan_roundtrip():
    entries = [IndexEntry(4, 0, 0, EntryType.NEVER),
               IndexEntry(4, 1, 2, EntryType.REGULAR, "4;1;2"),
               IndexEntry(20, 19, 19, EntryType.IMMEDIATE)]
    blob = b"".join(e.to_bytes() for e in entries)
    assert list(scan_entries(io.BytesIO(blob))) == entries


def test_index_scan_tolerates_torn_tail():
    good = IndexEntry(4, 0, 0, EntryType.NEVER).to_bytes()
    torn = IndexEntry(4, 1, 2, EntryType.REGULAR, "4;1;2").to_bytes()[:-3]
    got = list(scan_entries(io.BytesIO(good + torn)))
    assert len(got) == 1 and got[0].key == (4, 0, 0)
    with pytest.raises(CorruptIndexError):
        list(scan_entries(io.BytesIO(good + torn), tolerate_torn_tail=False))


def test_index_scan_rejects_bad_type():
    blob = struct.pack("<IIIi", 4, 0, 0, 99)
    with pytest.raises(CorruptIndexError):
        list(scan_entries(io.BytesIO(blob)))


def test_entry_validation():
    with pytest.raises(ValueError):
        IndexEntry(4, 0, 0, EntryType.REGULAR)  # missing filename
    with pytest.raises(ValueError):
        IndexEntry(4, 0, 0, EntryType.NEVER, "oops")


def test_store_save_load_regular(tmp_path):
    store = ChunkStore(str(tmp_path))
    chunk = patterned_chunk()
    entry = store.save(chunk)
    assert entry.type == EntryType.REGULAR
    assert entry.filename == "4;1;2"
    loaded = store.load(4, 1, 2)
    np.testing.assert_array_equal(loaded.data, chunk.data)


def test_store_save_special_chunks_are_tag_only(tmp_path):
    store = ChunkStore(str(tmp_path))
    store.save(Chunk.never(4, 0, 0))
    store.save(Chunk.immediate(4, 0, 1))
    # No chunk files written, only the index.
    files = {p.name for p in (tmp_path / "Data").iterdir()}
    assert files == {"_index.dat"}
    assert store.load(4, 0, 0).is_never
    assert store.load(4, 0, 1).is_immediate


def test_store_missing_chunk_returns_none(tmp_path):
    store = ChunkStore(str(tmp_path))
    assert store.load(4, 3, 3) is None


def test_store_filename_collision_suffix(tmp_path):
    store = ChunkStore(str(tmp_path))
    c1 = patterned_chunk(period=11)
    c2 = patterned_chunk(period=13)
    e1 = store.save(c1)
    e2 = store.save(c2)  # same key -> collision -> suffix
    assert e1.filename == "4;1;2"
    assert e2.filename == "4;1;20"
    # Duplicate keys: the most recent save wins on load.
    np.testing.assert_array_equal(store.load(4, 1, 2).data, c2.data)


def test_store_load_many_single_scan(tmp_path):
    store = ChunkStore(str(tmp_path))
    store.save(Chunk.never(4, 0, 0))
    store.save(patterned_chunk(4, 1, 2))
    got = store.load_many([(4, 0, 0), (4, 3, 3), (4, 1, 2)])
    assert got[0].is_never
    assert got[1] is None
    assert got[2].key == (4, 1, 2)


def test_store_completed_keys_resume_filtering(tmp_path):
    store = ChunkStore(str(tmp_path))
    store.save(Chunk.never(4, 0, 0))
    store.save(Chunk.never(10, 5, 5))
    store.save(Chunk.never(20, 7, 7))
    assert store.completed_keys() == {(4, 0, 0), (10, 5, 5), (20, 7, 7)}
    assert store.completed_keys(levels=[4, 20]) == {(4, 0, 0), (20, 7, 7)}
    # A fresh store instance over the same dir sees the same state (restart).
    store2 = ChunkStore(str(tmp_path))
    assert store2.completed_keys(levels=[10]) == {(10, 5, 5)}


def test_store_payload_cache_roundtrip(tmp_path):
    store = ChunkStore(str(tmp_path))
    chunk = patterned_chunk()
    store.save(chunk)
    p1 = store.load_payload(4, 1, 2)
    p2 = store.load_payload(4, 1, 2)  # cached
    assert p1 is p2
    np.testing.assert_array_equal(Chunk.deserialize_data(p1), chunk.data)
    assert store.load_payload(4, 3, 3) is None


def test_store_survives_torn_index_tail(tmp_path):
    store = ChunkStore(str(tmp_path))
    store.save(Chunk.never(4, 0, 0))
    with open(store.index_path, "ab") as f:
        f.write(b"\x04\x00\x00")  # torn append
    store2 = ChunkStore(str(tmp_path))
    assert store2.completed_keys() == {(4, 0, 0)}


def test_unwritable_data_dir_raises_clean_error(tmp_path):
    """Reference parity: Program.cs:159-176 probes -o writability and
    fails cleanly; ChunkStore.setup raises DataDirError (not a raw
    OSError traceback) for an unwritable or file-occupied path."""
    import os

    import pytest

    from distributedmandelbrot_tpu.storage.store import ChunkStore, DataDirError

    # Path occupied by a regular file.
    occupied = tmp_path / "occupied"
    (occupied / "Data").parent.mkdir(exist_ok=True)
    (occupied / "Data").write_text("a file, not a directory")
    with pytest.raises(DataDirError, match="cannot create data directory"):
        ChunkStore(str(occupied))

    # Read-only directory (skip when running as root: chmod is advisory).
    ro = tmp_path / "ro"
    (ro / "Data").mkdir(parents=True)
    (ro / "Data").chmod(0o555)
    try:
        if os.access(str(ro / "Data"), os.W_OK):
            pytest.skip("running as root; chmod cannot make dir unwritable")
        with pytest.raises(DataDirError, match="not writable"):
            ChunkStore(str(ro))
    finally:
        (ro / "Data").chmod(0o755)


def test_level_ownership_locks(tmp_path):
    """Two coordinators on one data dir with overlapping levels must fail
    loudly (reference: the static claimed-levels set,
    Distributer.cs:14,109-115); disjoint levels coexist; a leftover lock
    file with no live flock (crashed coordinator) is claimable; release()
    frees the level."""
    import os

    import pytest

    from distributedmandelbrot_tpu.storage.ownership import (LevelClaims,
                                                             LevelOwnedError)

    data_dir = str(tmp_path)
    a = LevelClaims(data_dir, [4, 10])
    # Overlap -> loud failure, and the failed claimant must not leave
    # partial locks behind (level 20 stays claimable).
    with pytest.raises(LevelOwnedError, match="level 10"):
        LevelClaims(data_dir, [20, 10])
    b = LevelClaims(data_dir, [20])
    b.release()
    # Release frees the level for a new claimant.
    a.release()
    c = LevelClaims(data_dir, [4])
    c.release()
    # A crashed coordinator leaves the file behind but the kernel drops
    # its flock with the process — the level is simply claimable; there
    # is no stale state to reclaim (the point of flock over pid files).
    leftover = os.path.join(data_dir, "_level_7.lock")
    with open(leftover, "w") as f:
        f.write("999999999")  # junk content; ownership is the flock
    d = LevelClaims(data_dir, [7])
    d.release()


def test_coordinator_level_ownership_e2e(tmp_path):
    """A second embedded coordinator on the same data dir + level fails at
    startup; after the first stops, the level is claimable again."""
    import pytest

    from distributedmandelbrot_tpu.coordinator import EmbeddedCoordinator
    from distributedmandelbrot_tpu.core.workload import LevelSetting
    from distributedmandelbrot_tpu.storage.ownership import LevelOwnedError

    with EmbeddedCoordinator(str(tmp_path), [LevelSetting(2, 16)]):
        with pytest.raises(LevelOwnedError):
            with EmbeddedCoordinator(str(tmp_path), [LevelSetting(2, 16)]):
                pass
    # Clean shutdown released the claim: restart-resume still works.
    with EmbeddedCoordinator(str(tmp_path), [LevelSetting(2, 16)]):
        pass


def test_level_claims_released_on_failed_startup(tmp_path):
    """A Coordinator whose startup fails after claiming (e.g. port in
    use) must release its level claims — a leaked claim from a live pid
    would lock the level for the life of the process."""
    import asyncio
    import socket

    from distributedmandelbrot_tpu.coordinator.app import Coordinator
    from distributedmandelbrot_tpu.core.workload import LevelSetting

    blocker = socket.socket()
    blocker.bind(("127.0.0.1", 0))
    blocker.listen(1)
    port = blocker.getsockname()[1]
    try:
        async def failing_start():
            co = Coordinator([LevelSetting(2, 16)],
                             data_dir_parent=str(tmp_path),
                             host="127.0.0.1", distributer_port=port,
                             dataserver_port=0)
            try:
                await co.start()
            except OSError:
                return True
            await co.stop()
            return False

        assert asyncio.run(failing_start()), "expected bind failure"
    finally:
        blocker.close()
    # The claim from the failed startup must be gone.
    from distributedmandelbrot_tpu.coordinator import EmbeddedCoordinator
    with EmbeddedCoordinator(str(tmp_path), [LevelSetting(2, 16)]):
        pass


def test_compact_rewrites_index_and_removes_orphans(tmp_path):
    """compact(): one last-wins entry per tile, orphaned chunk-file
    versions removed, loads identical before/after, coordinator lock
    respected."""
    import os

    import numpy as np
    import pytest

    from distributedmandelbrot_tpu.core.chunk import Chunk
    from distributedmandelbrot_tpu.storage.ownership import LevelClaims, \
        LevelOwnedError
    from distributedmandelbrot_tpu.storage.store import ChunkStore, compact

    store = ChunkStore(str(tmp_path))
    rng = np.random.default_rng(11)

    def chunk(level, i, j, fill=None):
        data = (np.full(CHUNK_PIXELS, fill, np.uint8) if fill is not None
                else rng.integers(0, 255, CHUNK_PIXELS, np.uint8))
        return Chunk(level, i, j, data)

    c1 = chunk(2, 0, 0)
    c1b = chunk(2, 0, 0)   # re-save: duplicate entry + suffixed file
    c2 = chunk(2, 1, 1, fill=0)   # Never (tag-only)
    c3 = chunk(3, 2, 2)
    for c in (c1, c1b, c2, c3):
        store.save(c)
    assert len(store.entries()) == 4
    files_before = [n for n in os.listdir(store.data_dir)
                    if not n.startswith("_")]
    assert len(files_before) == 3  # base, suffixed dupe, c3

    # A live coordinator (level claim held) blocks compaction.
    claims = LevelClaims(store.data_dir, [2])
    with pytest.raises(LevelOwnedError):
        compact(str(tmp_path))
    claims.release()

    want = {k: store.load(*k).data.tobytes()
            for k in [(2, 0, 0), (2, 1, 1), (3, 2, 2)]}
    stats = compact(str(tmp_path))
    assert stats["entries_before"] == 4 and stats["entries_after"] == 3
    assert stats["orphans_removed"] == 1  # c1's superseded file version

    store2 = ChunkStore(str(tmp_path))
    assert len(store2.entries()) == 3
    for k, data in want.items():
        assert store2.load(*k).data.tobytes() == data
    # Idempotent.
    stats2 = compact(str(tmp_path))
    assert stats2["entries_before"] == 3 and stats2["orphans_removed"] == 0


# -- group commit (put_many) -------------------------------------------------

def test_put_many_commits_batch_with_one_index_append(tmp_path):
    from distributedmandelbrot_tpu.utils import faults

    store = ChunkStore(str(tmp_path))
    assert store.put_many([]) == []
    chunks = [patterned_chunk(10, i, 0, period=11 + i) for i in range(5)]
    # The whole batch shares ONE index append (the commit point): the
    # after_index_append crash point fires once, after everything is
    # durable.
    faults.arm("store.after_index_append", after=1)
    try:
        with pytest.raises(faults.CrashPointError):
            store.put_many(chunks)
    finally:
        faults.disarm()
    store2 = ChunkStore(str(tmp_path))
    assert store2.completed_keys(levels=[10]) == {c.key for c in chunks}
    for c in chunks:
        np.testing.assert_array_equal(store2.load(*c.key).data, c.data)
    assert len(store2.entries()) == len(chunks)


def test_put_many_mixed_special_and_regular(tmp_path):
    store = ChunkStore(str(tmp_path))
    batch = [Chunk.never(4, 0, 0), patterned_chunk(4, 1, 2),
             Chunk.immediate(4, 0, 1)]
    entries = store.put_many(batch)
    assert [e.type for e in entries] == [EntryType.NEVER, EntryType.REGULAR,
                                         EntryType.IMMEDIATE]
    assert store.load(4, 0, 0).is_never
    assert store.load(4, 0, 1).is_immediate
    np.testing.assert_array_equal(store.load(4, 1, 2).data, batch[1].data)


def test_put_many_is_all_or_nothing_across_crash_interleavings(tmp_path):
    """Property test over random batch sizes x crash points: wherever a
    crash lands inside a group commit, a restart sees either every tile
    of the batch or none of it, and re-running the missing tiles
    converges with zero lost and zero duplicated entries."""
    from distributedmandelbrot_tpu.utils import faults

    rng = np.random.default_rng(20260805)
    points = ("store.before_chunk_write", "store.after_chunk_write",
              "store.after_index_append")
    for trial in range(10):
        d = tmp_path / f"t{trial}"
        d.mkdir()
        n = int(rng.integers(1, 7))
        point = points[int(rng.integers(len(points)))]
        # Blob-phase points fire once per chunk; the index append fires
        # once per batch.
        after = 1 if point == "store.after_index_append" \
            else int(rng.integers(1, n + 1))
        chunks = [patterned_chunk(10, i, trial, period=7 + i)
                  for i in range(n)]
        store = ChunkStore(str(d))
        faults.arm(point, after=after)
        try:
            with pytest.raises(faults.CrashPointError):
                store.put_many(chunks)
        finally:
            faults.disarm()
        # Restart over the same directory (runs the torn-tail scan).
        store2 = ChunkStore(str(d))
        done = store2.completed_keys(levels=[10])
        if point == "store.after_index_append":
            # Crash AFTER the commit point: the whole batch is durable.
            assert done == {c.key for c in chunks}, (trial, point, after)
        else:
            # Crash before it: none of the batch is visible, however
            # many blobs already landed (orphans, reaped by compact).
            assert done == set(), (trial, point, after)
        missing = [c for c in chunks if c.key not in done]
        store2.put_many(missing)
        final = ChunkStore(str(d))
        assert final.completed_keys(levels=[10]) == {c.key for c in chunks}
        assert len(final.entries()) == n, (trial, point, after)
        for c in chunks:
            np.testing.assert_array_equal(final.load(*c.key).data, c.data)
