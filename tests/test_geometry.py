import numpy as np
import pytest

from distributedmandelbrot_tpu.core import (CHUNK_PIXELS, CHUNK_WIDTH,
                                            MAX_AXIS, MIN_AXIS, TileSpec,
                                            chunk_origin, level_chunk_range,
                                            validate_indices)


def test_domain_constants():
    assert (MIN_AXIS, MAX_AXIS) == (-2.0, 2.0)
    assert CHUNK_WIDTH == 4096
    assert CHUNK_PIXELS == 4096 * 4096


@pytest.mark.parametrize("level,expected", [(1, 4.0), (4, 1.0), (10, 0.4)])
def test_level_chunk_range(level, expected):
    assert level_chunk_range(level) == pytest.approx(expected)


def test_chunk_origin_corners():
    assert chunk_origin(4, 0, 0) == (-2.0, -2.0)
    # Top corner chunk starts one chunk-range short of the max axis.
    r, i = chunk_origin(4, 3, 3)
    assert r == pytest.approx(1.0) and i == pytest.approx(1.0)


def test_validate_indices_rejects_out_of_range():
    with pytest.raises(ValueError):
        validate_indices(4, 4, 0)
    with pytest.raises(ValueError):
        validate_indices(4, 0, -1)
    with pytest.raises(ValueError):
        validate_indices(0, 0, 0)


def test_axes_match_reference_linspace():
    """Grid must be bit-identical to the reference worker's np.linspace call
    (inclusive endpoints, pitch = range/4095)."""
    spec = TileSpec.for_chunk(10, 3, 7)
    re, im = spec.axes()
    start_r = MIN_AXIS + level_chunk_range(10) * 3
    start_i = MIN_AXIS + level_chunk_range(10) * 7
    np.testing.assert_array_equal(
        re, np.linspace(start_r, start_r + 0.4, num=4096))
    np.testing.assert_array_equal(
        im, np.linspace(start_i, start_i + 0.4, num=4096))
    assert re[0] == start_r and re[-1] == start_r + 0.4


def test_adjacent_chunks_share_boundary_column():
    left = TileSpec.for_chunk(10, 3, 0).axes()[0]
    right = TileSpec.for_chunk(10, 4, 0).axes()[0]
    assert left[-1] == pytest.approx(right[0])


def test_grid_flat_is_real_fastest():
    spec = TileSpec(0.0, 1.0, 1.0, 1.0, width=4, height=3)
    re_flat, im_flat = spec.grid_flat()
    assert re_flat.shape == (12,) and im_flat.shape == (12,)
    # Real values cycle fastest; imag constant within a row.
    np.testing.assert_array_equal(re_flat[:4], re_flat[4:8])
    assert (im_flat[:4] == im_flat[0]).all()
    assert im_flat[4] != im_flat[0]


def test_grid_2d_matches_flat():
    spec = TileSpec(-1.0, -1.0, 2.0, 2.0, width=8, height=8)
    re2, im2 = spec.grid_2d()
    re_flat, im_flat = spec.grid_flat()
    np.testing.assert_array_equal(re2.ravel(), re_flat)
    np.testing.assert_array_equal(im2.ravel(), im_flat)
