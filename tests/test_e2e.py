"""End-to-end without real hardware: coordinator + worker (JAX-CPU backend)
+ viewer-decoder on loopback; tile bytes compared to the numpy golden."""

import numpy as np
import pytest

from distributedmandelbrot_tpu.core import (CHUNK_WIDTH, LevelSetting,
                                            TileSpec)
from distributedmandelbrot_tpu.ops import reference as ref
from distributedmandelbrot_tpu.viewer import (DataClient, FetchStatus,
                                              stitch_level, value_to_rgba)
from distributedmandelbrot_tpu.worker import (DistributerClient, JaxBackend,
                                              NumpyBackend, Worker)

from harness import CoordinatorHarness

MAX_ITER = 24  # keep full-size 4096^2 tiles cheap on the CPU backend


def golden_tile(level, i, j, max_iter=MAX_ITER):
    spec = TileSpec.for_chunk(level, i, j)
    cr, ci = spec.grid_2d()
    return ref.scale_counts_to_uint8(
        ref.escape_counts(cr, ci, max_iter), max_iter).ravel()


def test_full_farm_level1_bit_exact_vs_golden(tmp_path):
    """The 'one model running' milestone: request a level-1 tile, compute
    (f64 JAX), persist, fetch, compare bytes to the numpy golden."""
    with CoordinatorHarness(str(tmp_path), [LevelSetting(1, MAX_ITER)]) as farm:
        worker = Worker(
            DistributerClient("127.0.0.1", farm.distributer_port),
            JaxBackend(dtype=np.float64), overlap_io=False)
        rounds = worker.run_until_drained()
        assert rounds == 1
        farm.wait_saves_settled(expected_accepted=1)
        assert farm.scheduler.is_complete()

        pixels, status = DataClient("127.0.0.1", farm.dataserver_port) \
            .fetch(1, 0, 0)
        assert status is FetchStatus.OK
        golden = golden_tile(1, 0, 0)
        mismatch = (pixels != golden).mean()
        assert mismatch <= 5e-4, f"{mismatch:.2%} pixels diverge from golden"

        # Restart resume: a fresh coordinator over the same dir sees the
        # completed tile and hands out nothing.
    with CoordinatorHarness(str(tmp_path), [LevelSetting(1, MAX_ITER)]) as farm2:
        assert DistributerClient(
            "127.0.0.1", farm2.distributer_port).request() is None
        assert farm2.scheduler.is_complete()


def test_batched_farm_level2_f32_and_stitching(tmp_path):
    """Batched dispatch end-to-end: one worker leases all 4 level-2 tiles in
    one exchange, computes them on the f32 fast path, and the stitched level
    image is consistent with per-tile fetches."""
    with CoordinatorHarness(str(tmp_path), [LevelSetting(2, MAX_ITER)]) as farm:
        worker = Worker(
            DistributerClient("127.0.0.1", farm.distributer_port),
            JaxBackend(dtype=np.float32), batch_size=4)
        worker.run_until_drained()
        farm.wait_saves_settled(expected_accepted=4)
        assert farm.scheduler.is_complete()
        assert worker.counters.get("tiles_computed") == 4

        data_client = DataClient("127.0.0.1", farm.dataserver_port)

        def fetch(i, j):
            pixels, status = data_client.fetch(2, i, j)
            assert status is FetchStatus.OK
            return pixels

        image = stitch_level(fetch, 2)
        assert image.shape == (2 * CHUNK_WIDTH, 2 * CHUNK_WIDTH)
        # The Mandelbrot set is symmetric about the real axis; level 2 splits
        # exactly there, so the two image halves must mirror.
        np.testing.assert_array_equal(image[:CHUNK_WIDTH],
                                      image[CHUNK_WIDTH:][::-1])
        # f32 fast path stays within tolerance of the golden per tile.
        golden = golden_tile(2, 0, 0)
        mismatch = (fetch(0, 0) != golden).mean()
        assert mismatch < 0.01, f"{mismatch:.2%} f32 divergence"


def test_numpy_backend_is_bit_exact(tmp_path):
    """The parity-anchor backend must produce byte-identical persisted tiles."""
    with CoordinatorHarness(str(tmp_path), [LevelSetting(1, 12)]) as farm:
        worker = Worker(
            DistributerClient("127.0.0.1", farm.distributer_port),
            NumpyBackend(), overlap_io=False)
        worker.run_until_drained()
        farm.wait_saves_settled(expected_accepted=1)
        pixels, _ = DataClient("127.0.0.1", farm.dataserver_port).fetch(1, 0, 0)
        np.testing.assert_array_equal(pixels, golden_tile(1, 0, 0, 12))


def test_native_backend_is_bit_exact(tmp_path):
    """The native C++ backend (the fast bit-exact anchor, including its
    closed-form interior shortcut) must also persist byte-identical
    tiles through the full farm pipeline."""
    from distributedmandelbrot_tpu.worker import NativeBackend
    try:
        backend = NativeBackend()
    except Exception:
        pytest.skip("native library unavailable on this host")
    with CoordinatorHarness(str(tmp_path), [LevelSetting(1, 12)]) as farm:
        worker = Worker(
            DistributerClient("127.0.0.1", farm.distributer_port),
            backend, overlap_io=False)
        worker.run_until_drained()
        farm.wait_saves_settled(expected_accepted=1)
        pixels, _ = DataClient("127.0.0.1", farm.dataserver_port).fetch(1, 0, 0)
        np.testing.assert_array_equal(pixels, golden_tile(1, 0, 0, 12))


def test_trace_spans_complete_for_full_render(tmp_path):
    """Telemetry end-to-end: a full embedded render leaves a complete,
    ordered lifecycle span (scheduled -> granted -> result_received ->
    persisted) with worker attribution for EVERY persisted tile, and the
    coordinator's grant/persist latency histograms saw the traffic."""
    from distributedmandelbrot_tpu.obs import names as obs_names

    with CoordinatorHarness(str(tmp_path), [LevelSetting(2, MAX_ITER)]) as farm:
        worker = Worker(
            DistributerClient("127.0.0.1", farm.distributer_port),
            JaxBackend(dtype=np.float32), batch_size=2, overlap_io=False)
        worker.run_until_drained()
        farm.wait_saves_settled(expected_accepted=4)

        persisted = farm.store.completed_keys(levels=[2])
        assert len(persisted) == 4
        spans = {s["key"]: s for s in farm.trace.spans()}
        for key in persisted:
            span = spans[key]
            assert span["complete"], (key, span)
            assert span["worker"] is not None
            for stage in ("queue_s", "compute_s", "persist_s", "total_s"):
                assert span[stage] >= 0.0
            assert span["churn"] == 0
        # One worker connection did everything: skew is exactly balanced.
        skew = farm.trace.worker_skew()
        assert sum(w["tiles"] for w in skew["workers"].values()) == 4
        # The latency histograms the exporter serves are nonzero too.
        for family in (obs_names.HIST_GRANT_SECONDS,
                       obs_names.HIST_ACCEPT_SECONDS,
                       obs_names.HIST_PERSIST_SECONDS):
            assert farm.registry.family_percentile(family, 50) is not None, \
                family


def test_rgba_rendering_matches_reference_semantics():
    """In-set pixels (value 0) must render black; others via inverted jet."""
    values = np.zeros((8, 8), dtype=np.uint8)
    values[0, 0] = 128
    rgba = value_to_rgba(values)
    assert rgba.shape == (8, 8, 4)
    np.testing.assert_array_equal(rgba[1, 1], [0.0, 0.0, 0.0, 1.0])  # in-set
    assert rgba[0, 0, :3].sum() > 0  # escaped pixel is colored


def test_worker_crash_lease_expiry_redistribution_over_the_wire(tmp_path):
    """Fault injection end-to-end (survey §5.3): worker A leases a tile and
    goes silent (crash); after the lease expires the coordinator re-grants
    the SAME tile to worker B, accepts B's result, and rejects A's late
    submission — at-least-once with dedup, over the real wire."""
    import time

    with CoordinatorHarness(str(tmp_path), [LevelSetting(1, 12)],
                            lease_timeout=0.5, sweep_period=0.2) as farm:
        client_a = DistributerClient("127.0.0.1", farm.distributer_port)
        client_b = DistributerClient("127.0.0.1", farm.distributer_port)

        wl_a = client_a.request()
        assert wl_a is not None  # A holds the only tile...
        assert client_b.request() is None  # ...so B gets nothing
        # Precompute now so B's own lease can't expire mid-compute below
        # (a full golden tile takes seconds; the lease here is 0.5 s).
        pixels = NumpyBackend().compute_batch([wl_a])[0]
        time.sleep(0.8)  # A "crashed"; lease expires

        wl_b = client_b.request()  # redistribution
        assert wl_b is not None and wl_b.key == wl_a.key
        assert client_b.submit(wl_b, pixels) is True
        # A comes back from the dead: duplicate result must be rejected.
        assert client_a.submit(wl_a, pixels) is False
        farm.wait_saves_settled(expected_accepted=1)
        assert farm.scheduler.is_complete()


def test_coordinator_stats_reporting(tmp_path, caplog):
    """The periodic stats loop (survey §5.1/§5.5) logs progress with
    counter totals and deltas."""
    import asyncio
    import logging
    import time

    caplog.set_level(logging.INFO, logger="dmtpu.coordinator")
    with CoordinatorHarness(str(tmp_path), [LevelSetting(1, 16)]) as h:
        h.coordinator.stats_period = 0.05
        h._loop.call_soon_threadsafe(
            lambda: setattr(h.coordinator, "_stats_task",
                            asyncio.ensure_future(
                                h.coordinator._stats_loop())))
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline:
            if any("stats:" in r.message for r in caplog.records):
                break
            time.sleep(0.05)
    stats_lines = [r for r in caplog.records if "stats:" in r.message]
    assert stats_lines, "no stats line logged within 5s"
    assert "0/1 tiles complete" in stats_lines[0].message


def test_concurrent_fetch_burst_during_writes(tmp_path):
    """8 viewer threads hammer the DataServer while the worker is still
    uploading: every fetch must return either NOT_AVAILABLE or the
    exact golden bytes — never a torn/corrupted payload — and the
    server must stay healthy for a final full sweep."""
    import threading

    with CoordinatorHarness(str(tmp_path), [LevelSetting(2, MAX_ITER)]) as farm:
        # The two j-halves are exact mirrors across the real axis (the
        # symmetry test_batched_farm asserts) — compute 2, flip for 4.
        goldens = {(i, 0): golden_tile(2, i, 0) for i in range(2)}
        for i in range(2):
            goldens[(i, 1)] = goldens[(i, 0)].reshape(
                CHUNK_WIDTH, CHUNK_WIDTH)[::-1].ravel()
        errors: list = []
        stop = threading.Event()

        def reader(seed: int) -> None:
            import random
            rng = random.Random(seed)
            client = DataClient("127.0.0.1", farm.dataserver_port)
            try:
                while not stop.is_set():
                    i, j = rng.randrange(2), rng.randrange(2)
                    pixels, status = client.fetch(2, i, j)
                    if status is FetchStatus.OK:
                        mism = (pixels != goldens[(i, j)]).mean()
                        assert mism <= 5e-4, \
                            f"torn/corrupt read of ({i},{j}): {mism:.2%}"
                    else:
                        assert status is FetchStatus.NOT_AVAILABLE
                        # Back off while nothing exists yet: unthrottled
                        # NOT_AVAILABLE spin would contend with the
                        # compile/compute window and flake slow hosts.
                        stop.wait(0.005)
            except BaseException as e:
                errors.append(e)

        readers = [threading.Thread(target=reader, args=(50 + t,))
                   for t in range(8)]
        for t in readers:
            t.start()
        try:
            worker = Worker(
                DistributerClient("127.0.0.1", farm.distributer_port),
                JaxBackend(dtype=np.float32), batch_size=2)
            worker.run_until_drained()
            farm.wait_saves_settled(expected_accepted=4, timeout=300)
        finally:
            stop.set()
            for t in readers:
                t.join(timeout=60)
        assert not any(t.is_alive() for t in readers)
        assert not errors, errors[:2]
        # Server healthy after the burst: every tile fetches golden.
        client = DataClient("127.0.0.1", farm.dataserver_port)
        for (i, j), want in goldens.items():
            pixels, status = client.fetch(2, i, j)
            assert status is FetchStatus.OK
            assert (pixels != want).mean() <= 5e-4
