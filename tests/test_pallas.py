"""Pallas kernel functional tests (interpreter mode).

Skipped where pallas cannot even be imported — on some builds the TPU
platform plugin must be live for the import to succeed (this repo's
CPU-forced test processes are such a build; the kernel runs for real on
TPU workers and in the driver's TPU bench environment).
"""

import numpy as np
import pytest

from distributedmandelbrot_tpu.core import TileSpec
from distributedmandelbrot_tpu.ops import escape_time
from distributedmandelbrot_tpu.ops.pallas_escape import (compute_tile_pallas,
                                                         pallas_importable)

pytestmark = pytest.mark.skipif(not pallas_importable(),
                                reason="pallas not importable on this build")


def xla_f32_reference(spec, max_iter):
    step = np.float32(spec.range_real / (spec.width - 1))
    idx = np.arange(spec.width, dtype=np.float32)
    cr = (np.float32(spec.start_real) + idx * step)[None, :].repeat(
        spec.height, 0)
    ci = (np.float32(spec.start_imag) + idx * step)[:, None].repeat(
        spec.width, 1)
    counts = np.asarray(escape_time.escape_counts(
        cr.astype(np.float32), ci.astype(np.float32), max_iter=max_iter))
    return np.asarray(escape_time.scale_counts_to_uint8(
        counts, max_iter=max_iter)).ravel()


@pytest.mark.parametrize("max_iter", [1, 40, 200])
def test_pallas_matches_xla_f32_path(max_iter):
    spec = TileSpec(-0.8, 0.1, 0.2, 0.2, width=128, height=128)
    got = compute_tile_pallas(spec, max_iter, block_h=32, interpret=True)
    want = xla_f32_reference(spec, max_iter)
    mism = float((got != want).mean())
    assert mism <= 0.02, f"{mism:.2%} mismatch vs XLA f32 path"


def test_pallas_block_granular_exit_consistency():
    """Different block heights partition the early-exit differently but must
    not change results."""
    spec = TileSpec(-2.0, -2.0, 4.0, 4.0, width=128, height=128)
    a = compute_tile_pallas(spec, 64, block_h=32, interpret=True)
    b = compute_tile_pallas(spec, 64, block_h=128, interpret=True)
    np.testing.assert_array_equal(a, b)
