"""Pallas kernel functional tests (interpreter mode on the CPU config).

The kernel runs for real on TPU workers and in the driver's TPU bench
environment; here it executes through ``interpret=True``, which needs no
TPU plugin (conftest keeps the "tpu" platform *name* registered so the
pallas import itself succeeds on the CPU-forced build).
"""

import numpy as np
import pytest

from distributedmandelbrot_tpu.core import TileSpec
from distributedmandelbrot_tpu.ops import escape_time
from distributedmandelbrot_tpu.ops.pallas_escape import (compute_tile_pallas,
                                                         pallas_available,
                                                         pallas_importable)

pytestmark = pytest.mark.skipif(not pallas_importable(),
                                reason="pallas not importable on this build")

# Two views with different escape profiles: a seahorse-valley zoom
# (boundary-dense, deep pixels) and the full domain (mostly fast sky
# plus the in-set interior).
VIEWS = {
    "seahorse": TileSpec(-0.8, 0.1, 0.2, 0.2, width=128, height=128),
    "full": TileSpec(-2.0, -2.0, 4.0, 4.0, width=128, height=128),
}


def kernel_grid(spec):
    """(cr, ci) f32 grids in the kernel's own coordinate convention
    (start + index * step in f32, per-axis pitch, matching in-kernel
    generation) — the single copy used by every parity comparison here."""
    step_r = np.float32(spec.range_real / (spec.width - 1))
    step_i = np.float32(spec.range_imag / (spec.height - 1))
    cr = (np.float32(spec.start_real)
          + np.arange(spec.width, dtype=np.float32) * step_r)[None, :].repeat(
              spec.height, 0)
    ci = (np.float32(spec.start_imag)
          + np.arange(spec.height, dtype=np.float32) * step_i)[:, None].repeat(
              spec.width, 1)
    return cr, ci


def xla_f32_reference(spec, max_iter):
    """The XLA f32 path fed the kernel's coordinate convention."""
    cr, ci = kernel_grid(spec)
    counts = np.asarray(escape_time.escape_counts(
        cr, ci, max_iter=max_iter))
    return np.asarray(escape_time.scale_counts_to_uint8(
        counts, max_iter=max_iter)).ravel()


@pytest.mark.parametrize("view", sorted(VIEWS))
@pytest.mark.parametrize("max_iter", [1, 40, 200])
def test_pallas_matches_xla_f32_path(view, max_iter):
    spec = VIEWS[view]
    got = compute_tile_pallas(spec, max_iter, block_h=32, interpret=True)
    want = xla_f32_reference(spec, max_iter)
    mism = float((got != want).mean())
    assert mism <= 0.02, f"{view}: {mism:.2%} mismatch vs XLA f32 path"


def test_pallas_block_granular_exit_consistency():
    """Different block shapes partition the early-exit differently but must
    not change results."""
    spec = TileSpec(-2.0, -2.0, 4.0, 4.0, width=128, height=128)
    a = compute_tile_pallas(spec, 64, block_h=32, interpret=True)
    b = compute_tile_pallas(spec, 64, block_h=128, interpret=True)
    c = compute_tile_pallas(spec, 64, block_h=64, block_w=128,
                            unroll=16, interpret=True)
    np.testing.assert_array_equal(a, b)
    # A different unroll shifts where the compiler may contract mul+add
    # chains into FMAs, so O(1) chaotic-boundary pixels can move one
    # iteration bucket (see ops/escape_time.py module docstring) — the
    # comparison is statistical, not bit-exact.
    assert float((a != c).mean()) <= 0.001


def test_pallas_interior_check_is_output_identical():
    """The closed-form interior shortcut must not change a single pixel —
    it only changes how much work the block loop does."""
    for view in ("seahorse", "full"):
        spec = VIEWS[view]
        on = compute_tile_pallas(spec, 300, block_h=32, interpret=True,
                                 interior_check=True)
        off = compute_tile_pallas(spec, 300, block_h=32, interpret=True,
                                  interior_check=False)
        np.testing.assert_array_equal(on, off)


def test_pallas_cycle_check_is_output_identical():
    """Brent periodicity probe in the block kernel: work-only, no output
    change (period-3 bulb view — in-set pixels the closed forms miss)."""
    spec = TileSpec(-0.2, 0.7, 0.15, 0.15, width=128, height=64)
    base = compute_tile_pallas(spec, 200, block_h=32, interpret=True,
                               interior_check=False, cycle_check=False)
    cyc = compute_tile_pallas(spec, 200, block_h=32, interpret=True,
                              interior_check=False, cycle_check=True)
    np.testing.assert_array_equal(base, cyc)
    assert (cyc == 0).sum() > 0  # the view does contain in-set pixels


def test_pallas_smooth_interior_check_is_output_identical():
    from distributedmandelbrot_tpu.ops.pallas_escape import (
        compute_tile_smooth_pallas)
    spec = VIEWS["seahorse"]
    on = compute_tile_smooth_pallas(spec, 300, block_h=32, interpret=True,
                                    interior_check=True)
    off = compute_tile_smooth_pallas(spec, 300, block_h=32, interpret=True,
                                     interior_check=False)
    np.testing.assert_array_equal(on, off)


def test_pallas_julia_matches_xla_f32_path():
    """Julia mode: z0 = grid, c from SMEM — parity vs the XLA Julia
    kernel fed the same in-kernel coordinate convention."""
    from distributedmandelbrot_tpu.ops.pallas_escape import (
        compute_tile_julia_pallas)
    spec = TileSpec(-1.5, -1.5, 3.0, 3.0, width=128, height=128)
    c = -0.8 + 0.156j
    got = compute_tile_julia_pallas(spec, c, 100, block_h=32, interpret=True)
    zr, zi = kernel_grid(spec)
    counts = np.asarray(escape_time.escape_counts_julia(
        zr, zi, c, max_iter=100))
    want = np.asarray(escape_time.scale_counts_to_uint8(
        counts, max_iter=100)).ravel()
    mism = float((got != want).mean())
    assert mism <= 0.02, f"julia pallas: {mism:.2%} mismatch vs XLA"


def test_pallas_smooth_julia_matches_escape_smooth():
    from distributedmandelbrot_tpu.ops.pallas_escape import (
        compute_tile_smooth_pallas)
    import jax.numpy as jnp
    spec = TileSpec(-1.5, -1.5, 3.0, 3.0, width=128, height=64)
    c = -0.4 + 0.1j
    got = compute_tile_smooth_pallas(spec, 100, block_h=32, interpret=True,
                                     julia_c=c)
    zr, zi = kernel_grid(spec)
    want = np.asarray(escape_time.escape_smooth_julia(
        jnp.asarray(zr), jnp.asarray(zi), c, max_iter=100))
    inset_agree = float(((got == 0) == (want == 0)).mean())
    assert inset_agree >= 0.995
    both = (got != 0) & (want != 0)
    assert float(np.abs(got[both] - want[both]).max()) <= 0.05


def test_pallas_family_matches_xla_path():
    """Multibrot-3 and Burning Ship through the block kernel vs the XLA
    family kernel on the kernel's own coordinate convention."""
    from distributedmandelbrot_tpu.ops.families import escape_counts_family
    from distributedmandelbrot_tpu.ops.pallas_escape import (
        compute_tile_family_pallas)

    # Ship band is wider: its |.| folds amplify FMA-contraction
    # differences between the two compiled graphs into outright
    # trajectory divergence (see ops/families.py parity note).
    for power, burning, tol, spec in [
        (3, False, 0.03, TileSpec(-1.2, -1.2, 2.4, 2.4, width=128,
                                  height=64)),
        (2, True, 0.08, TileSpec(-2.2, -1.2, 2.4, 2.4, width=128,
                                 height=64)),
    ]:
        got = compute_tile_family_pallas(spec, 100, power=power,
                                         burning=burning, block_h=32,
                                         interpret=True)
        cr, ci = kernel_grid(spec)
        counts = np.asarray(escape_counts_family(
            cr, ci, max_iter=100, power=power, burning=burning))
        want = np.asarray(escape_time.scale_counts_to_uint8(
            counts, max_iter=100)).ravel()
        mism = float((got != want).mean())
        assert mism <= tol, (
            f"family pallas (d={power}, ship={burning}): "
            f"{mism:.2%} mismatch vs XLA")


def test_pallas_family_validation_matches_xla_contract():
    from distributedmandelbrot_tpu.ops.pallas_escape import (
        compute_tile_family_pallas, compute_tile_pallas_device)
    spec = TileSpec(-1.2, -1.2, 2.4, 2.4, width=128, height=64)
    with pytest.raises(ValueError, match="degree"):
        compute_tile_family_pallas(spec, 50, power=1, interpret=True)
    with pytest.raises(ValueError, match="degree 2"):
        compute_tile_family_pallas(spec, 50, power=3, burning=True,
                                   interpret=True)
    with pytest.raises(ValueError, match="degree-2"):
        compute_tile_pallas_device(spec, 50, power=3, julia_c=0.1 + 0.1j,
                                   interpret=True)


@pytest.mark.parametrize("power,burning,inset_tol,quantile,frac_tol,spec", [
    (3, False, 0.995, 0.99, 0.005,
     TileSpec(-1.2, -1.2, 2.4, 2.4, width=128, height=64)),
    # Wider ship bands throughout: its folds amplify FMA differences
    # between the two compiled graphs into outright trajectory
    # divergence on several percent of pixels (matching the integer
    # kernel's 8% band above).
    (2, True, 0.97, 0.90, 0.08,
     TileSpec(-2.2, -1.2, 2.4, 2.4, width=128, height=64)),
])
def test_pallas_smooth_family_matches_xla(power, burning, inset_tol,
                                          quantile, frac_tol, spec):
    """Smooth family mode vs the XLA smooth family kernel (in-set
    classification + bounded nu difference)."""
    from distributedmandelbrot_tpu.ops.families import escape_smooth_family
    from distributedmandelbrot_tpu.ops.pallas_escape import (
        compute_tile_smooth_pallas)
    import jax.numpy as jnp
    got = compute_tile_smooth_pallas(spec, 100, power=power, burning=burning,
                                     block_h=32, interpret=True)
    cr, ci = kernel_grid(spec)
    want = np.asarray(escape_smooth_family(jnp.asarray(cr), jnp.asarray(ci),
                                           max_iter=100, power=power,
                                           burning=burning))
    assert float(((got == 0) == (want == 0)).mean()) >= inset_tol
    both = (got != 0) & (want != 0)
    diff = np.abs(got[both] - want[both])
    # Statistical band: FMA differences between the two compiled graphs
    # can shift chaotic-boundary orbits whole iterations, so the max is
    # unbounded — the bulk must agree tightly.
    assert float(np.quantile(diff, quantile)) <= 0.05
    assert float((diff > 1.0).mean()) <= frac_tol


def test_pallas_smooth_cycle_check_is_output_identical():
    from distributedmandelbrot_tpu.ops.pallas_escape import (
        compute_tile_smooth_pallas)
    spec = TileSpec(-0.2, 0.7, 0.15, 0.15, width=128, height=64)
    base = compute_tile_smooth_pallas(spec, 200, block_h=32, interpret=True,
                                      interior_check=False,
                                      cycle_check=False)
    cyc = compute_tile_smooth_pallas(spec, 200, block_h=32, interpret=True,
                                     interior_check=False, cycle_check=True)
    np.testing.assert_array_equal(base, cyc)


def test_pallas_non_multiple_height():
    """Heights that aren't a multiple of the default block fall back to a
    fitting power-of-two divisor (160 = 32*5 -> block_h 32)."""
    spec = TileSpec(-0.8, 0.1, 0.2, 0.2, width=128, height=160)
    got = compute_tile_pallas(spec, 40, interpret=True)
    assert got.shape == (128 * 160,)
    want = xla_f32_reference(spec, 40)
    assert float((got != want).mean()) <= 0.02


def test_pallas_unsupported_height_raises():
    spec = TileSpec(-0.8, 0.1, 0.2, 0.2, width=128, height=28)
    with pytest.raises(ValueError, match="unsupported"):
        compute_tile_pallas(spec, 40, interpret=True)


def test_pallas_anisotropic_pitch():
    """A TileSpec whose imag pitch differs from its real pitch must render
    the view the spec describes, not a square-pitch distortion of it
    (round-2 defect: one step scalar was applied to both axes)."""
    spec = TileSpec(-0.8, 0.1, 0.2, 0.05, width=128, height=128)
    got = compute_tile_pallas(spec, 60, block_h=32, interpret=True)
    want = xla_f32_reference(spec, 60)
    assert float((got != want).mean()) <= 0.02
    # The old square-pitch reading of the same spec (imag pitch taken
    # from range_real) must NOT match: the two views genuinely differ
    # (guards against the test going vacuous).
    square = TileSpec(-0.8, 0.1, 0.2, 0.2, width=128, height=128)
    assert float((xla_f32_reference(square, 60) != want).mean()) > 0.05


def test_pallas_smooth_anisotropic_pitch():
    from distributedmandelbrot_tpu.ops.pallas_escape import (
        compute_tile_smooth_pallas)
    spec = TileSpec(-0.8, 0.1, 0.2, 0.05, width=128, height=128)
    got = compute_tile_smooth_pallas(spec, 60, block_h=32, interpret=True)
    cr, ci = kernel_grid(spec)
    want = np.asarray(escape_time.escape_smooth(cr, ci, max_iter=60))
    close = np.isclose(got, want, rtol=1e-4, atol=1e-4)
    assert float((~close).mean()) <= 0.02


def test_pallas_clamp_mode():
    """clamp=True pins the escape ceiling at 255 instead of wrapping."""
    spec = TileSpec(-2.0, -2.0, 4.0, 4.0, width=128, height=128)
    wrapped = compute_tile_pallas(spec, 300, interpret=True)
    clamped = compute_tile_pallas(spec, 300, clamp=True, interpret=True)
    # Same pixels are in-set (0 from never-escaping) either way; clamped
    # output can only differ where wrap produced small values.
    assert clamped.max() <= 255
    differing = wrapped != clamped
    assert (clamped[differing] == 255).all()


@pytest.mark.skipif(not pallas_available(),
                    reason="no live TPU backend in this process")
def test_pallas_on_tpu_matches_xla():
    """Compiled-path parity on real hardware (runs only on a TPU build)."""
    spec = TileSpec(-0.748, 0.09, 0.005, 0.005, width=256, height=256)
    got = compute_tile_pallas(spec, 1000)
    want = xla_f32_reference(spec, 1000)
    assert float((got != want).mean()) <= 0.02


def test_pallas_sharded_batch_matches_xla_batch():
    """The shard_map-wrapped Pallas path must agree with the XLA sharded
    path on a mixed-budget batch over the virtual 8-device mesh (each
    tile keeps its own traced budget under one static cap)."""
    from distributedmandelbrot_tpu.parallel import (
        batched_escape_pixels, batched_escape_pixels_pallas, tile_mesh)

    mesh = tile_mesh()
    k = 10  # exercises the ragged pad (10 tiles on 8 devices)
    params = np.empty((k, 3))
    mrds = np.empty(k, dtype=np.int64)
    for i in range(k):
        spec = TileSpec(-0.8 + 0.05 * (i % 4), 0.05 + 0.05 * (i // 4),
                        0.2, 0.2, width=128, height=128)
        params[i] = (spec.start_real, spec.start_imag, 0.2 / 127)
        mrds[i] = (40, 90, 200)[i % 3]
    got = batched_escape_pixels_pallas(mesh, params, mrds, definition=128,
                                       interpret=True)
    want = batched_escape_pixels(mesh, params, mrds, definition=128,
                                 dtype=np.float32)
    assert got.shape == want.shape == (k, 128, 128)
    mism = float((got != want).mean())
    assert mism <= 0.02, f"{mism:.2%} mismatch vs XLA sharded path"


def test_mesh_backend_pallas_kernel_selection():
    """kernel='pallas' forces the Pallas path (interpret off-TPU) and
    produces golden-consistent chunks; granule-unfittable tiles raise."""
    from distributedmandelbrot_tpu.core.workload import Workload
    from distributedmandelbrot_tpu.ops import reference as ref
    from distributedmandelbrot_tpu.parallel import MeshBackend

    backend = MeshBackend(definition=128, kernel="pallas")
    w = Workload(2, 48, 0, 1)
    got = backend.compute_batch([w])[0]
    spec = TileSpec.for_chunk(2, 0, 1, definition=128)
    step = spec.range_real / 127
    cr = np.float32(spec.start_real) + np.arange(128, dtype=np.float32) * \
        np.float32(step)
    ci = np.float32(spec.start_imag) + np.arange(128, dtype=np.float32) * \
        np.float32(step)
    want = ref.scale_counts_to_uint8(
        ref.escape_counts(np.broadcast_to(cr, (128, 128)).astype(np.float64),
                          np.broadcast_to(ci[:, None], (128, 128))
                          .astype(np.float64), 48), 48).ravel()
    assert float((got != want).mean()) <= 0.01

    small = MeshBackend(definition=64, kernel="pallas")
    with pytest.raises(ValueError):
        small.compute_batch([w])

    # auto falls back to XLA for the same unfittable shape instead.
    auto = MeshBackend(definition=64, kernel="auto")
    assert auto.compute_batch([w])[0].shape == (64 * 64,)


def test_pallas_smooth_matches_escape_smooth_f32():
    """The Pallas smooth kernel must agree with the XLA smooth path:
    identical in-set mask, small relative error on escape values (both
    f32; FMA placement differs)."""
    from distributedmandelbrot_tpu.ops.pallas_escape import (
        compute_tile_smooth_pallas)

    spec = TileSpec(-0.748, 0.09, 0.01, 0.01, width=128, height=128)
    got = compute_tile_smooth_pallas(spec, 300, block_h=32, interpret=True)
    step = np.float32(spec.range_real / 127)
    cr = (np.float32(spec.start_real)
          + np.arange(128, dtype=np.float32) * step)[None, :].repeat(128, 0)
    ci = (np.float32(spec.start_imag)
          + np.arange(128, dtype=np.float32) * step)[:, None].repeat(128, 1)
    want = np.asarray(escape_time.escape_smooth(cr, ci, max_iter=300))
    inset_agree = float(((got == 0) == (want == 0)).mean())
    assert inset_agree >= 0.999, f"in-set mask agreement {inset_agree:.2%}"
    both = (got > 0) & (want > 0)
    relerr = np.abs(got[both] - want[both]) / np.maximum(want[both], 1.0)
    assert float(np.median(relerr)) < 1e-5
    assert float((relerr < 0.02).mean()) > 0.995


def test_pallas_smooth_unsupported_budget_raises():
    from distributedmandelbrot_tpu.ops.pallas_escape import (
        compute_tile_smooth_pallas)
    from distributedmandelbrot_tpu.ops.escape_time import INT32_SCALE_LIMIT

    spec = TileSpec(-0.8, 0.1, 0.2, 0.2, width=128, height=128)
    with pytest.raises(ValueError):
        compute_tile_smooth_pallas(spec, INT32_SCALE_LIMIT + 2,
                                   interpret=True)


def test_pallas_unsupported_is_dedicated_type():
    """The intentional shape/budget rejections raise PallasUnsupported (a
    ValueError subclass) so fall-back sites can catch exactly them and a
    genuine kernel bug surfacing as ValueError propagates (round-2
    advisor finding)."""
    from distributedmandelbrot_tpu.ops.escape_time import INT32_SCALE_LIMIT
    from distributedmandelbrot_tpu.ops.pallas_escape import (
        PallasUnsupported, compute_tile_pallas_device, fit_blocks)

    assert issubclass(PallasUnsupported, ValueError)
    with pytest.raises(PallasUnsupported):
        fit_blocks(28, 128)  # below the 32-sublane granule
    spec = TileSpec(-0.8, 0.1, 0.2, 0.2, width=128, height=128)
    with pytest.raises(PallasUnsupported):
        compute_tile_pallas_device(spec, INT32_SCALE_LIMIT + 2,
                                   interpret=True)


def test_pallas_first_propagates_non_unsupported_errors(monkeypatch):
    """cli._pallas_first falls back ONLY on PallasUnsupported; any other
    ValueError from the kernel chain must surface."""
    from distributedmandelbrot_tpu import cli
    from distributedmandelbrot_tpu.ops import pallas_escape as pe

    monkeypatch.setattr(pe, "pallas_available", lambda: True)
    monkeypatch.setattr(pe, "compute_tile_pallas",
                        lambda *a, **k: (_ for _ in ()).throw(
                            ValueError("genuine bug")),
                        raising=False)
    with pytest.raises(ValueError, match="genuine bug"):
        cli._pallas_first("compute_tile_pallas", None, 10)
    monkeypatch.setattr(pe, "compute_tile_pallas",
                        lambda *a, **k: (_ for _ in ()).throw(
                            pe.PallasUnsupported("declined")),
                        raising=False)
    assert cli._pallas_first("compute_tile_pallas", None, 10) is None


def test_cycle_probe_follows_requested_budget(monkeypatch):
    """The Brent probe resolves from the tile's ACTUAL budget, not the
    bucketed compile cap: max_iter=600 buckets to a 1024 cap (>= the
    probe threshold) but must not pay the probe (round-2 advisor
    finding)."""
    from distributedmandelbrot_tpu.ops import pallas_escape as pe
    from distributedmandelbrot_tpu.ops.escape_time import (
        CYCLE_CHECK_MIN_ITER)

    seen = {}
    real = pe._pallas_escape

    def spy(*args, **kwargs):
        seen.update(kwargs)
        return real(*args, **kwargs)

    monkeypatch.setattr(pe, "_pallas_escape", spy)
    # Sky-only view: every pixel escapes in the first segment, so the
    # deep budget costs nothing in interpret mode.
    spec = TileSpec(1.5, 1.5, 0.1, 0.1, width=128, height=32)
    pe.compute_tile_pallas_device(spec, 600, interpret=True)
    assert seen["max_iter"] == pe.bucket_cap(600) >= CYCLE_CHECK_MIN_ITER
    assert seen["cycle_check"] is False
    pe.compute_tile_pallas_device(spec, CYCLE_CHECK_MIN_ITER,
                                  interpret=True)
    assert seen["cycle_check"] is True


def test_pallas_declines_sub_f32_resolution_views():
    """A view whose pixel pitch aliases in f32 raises PallasUnsupported
    (adjacent in-kernel coordinates would collapse to the same float —
    a banded render no block size can fix); callers fall back to the
    f64/perturbation paths."""
    from distributedmandelbrot_tpu.ops.pallas_escape import (
        PallasUnsupported, compute_tile_pallas_device)

    spec = TileSpec(-0.74529, 0.11307, 1e-5, 1e-5, width=1024, height=1024)
    with pytest.raises(PallasUnsupported, match="f32 resolution"):
        compute_tile_pallas_device(spec, 100, interpret=True)


# --- Batch-grid kernel (tiles as leading grid axis) -------------------------


def test_batch_grid_matches_single_tile_kernel():
    """_pallas_escape_batch must be bit-identical to k single-tile calls:
    mixed windows (boundary, interior, sky), mixed budgets under one
    bucketed cap, cycle probe armed (deep bucket)."""
    import jax.numpy as jnp

    from distributedmandelbrot_tpu.ops.pallas_escape import (
        _pallas_escape, _pallas_escape_batch, bucket_cap)

    tile = 128
    rows = [[-0.7436, 0.1317, 2e-3 / (tile - 1), 2e-3 / (tile - 1)],
            [-0.2, -0.05, 0.1 / (tile - 1), 0.1 / (tile - 1)],
            [1.5, 1.5, 0.1 / (tile - 1), 0.1 / (tile - 1)]]
    mis = [5000, 4500, 4200]
    cap = bucket_cap(max(mis))
    params = jnp.asarray(rows, jnp.float32)
    mrds = jnp.asarray([[m] for m in mis], jnp.int32)
    out = _pallas_escape_batch(params, mrds, k=3, height=tile, width=tile,
                               block_h=32, max_iter=cap, interpret=True)
    for t in range(3):
        ref = _pallas_escape(params[t][None, :],
                             jnp.asarray([[mis[t]]], jnp.int32),
                             height=tile, width=tile, block_h=32,
                             max_iter=cap, interpret=True)
        assert np.array_equal(np.asarray(out[t]), np.asarray(ref)), \
            f"tile {t} diverged from the single-tile kernel"


@pytest.mark.parametrize("mode", ["ship", "julia"])
def test_batch_grid_families(mode):
    """Batch-grid parity for the non-default families (the ship's abs
    fold; julia's SMEM constant)."""
    import jax.numpy as jnp

    from distributedmandelbrot_tpu.ops.pallas_escape import (
        _pallas_escape, _pallas_escape_batch, bucket_cap)

    tile = 128
    kw = ({"burning": True} if mode == "ship"
          else {"julia": True})
    rows = [[-1.75, -0.04, 0.01 / (tile - 1), 0.01 / (tile - 1)],
            [-1.76, -0.03, 0.02 / (tile - 1), 0.02 / (tile - 1)]]
    if mode == "julia":
        rows = [[-1.5, -1.5, 3.0 / (tile - 1), 3.0 / (tile - 1),
                 -0.8, 0.156],
                [-1.5, -1.5, 3.0 / (tile - 1), 3.0 / (tile - 1),
                 0.285, 0.01]]
    mis = [300, 200]
    cap = bucket_cap(max(mis))
    params = jnp.asarray(rows, jnp.float32)
    mrds = jnp.asarray([[m] for m in mis], jnp.int32)
    out = _pallas_escape_batch(params, mrds, k=2, height=tile, width=tile,
                               block_h=32, max_iter=cap, interpret=True,
                               interior_check=False, **kw)
    for t in range(2):
        ref = _pallas_escape(params[t][None, :],
                             jnp.asarray([[mis[t]]], jnp.int32),
                             height=tile, width=tile, block_h=32,
                             max_iter=cap, interpret=True,
                             interior_check=False, **kw)
        assert np.array_equal(np.asarray(out[t]), np.asarray(ref))


def test_batched_pallas_sharded_uses_batch_grid_for_deep_budgets():
    """The sharded dispatch routes deep-budget shards through the
    batch-grid kernel (k per device > 1 engages it); output must stay
    identical to per-tile single-kernel calls.  The golden here is the
    single-tile PALLAS kernel, not the XLA path: at depth >= 4096 a
    last-ulp f32 difference between the two compilations (XLA-CPU fuses
    FMAs; the kernel's op order is fixed) diverges on chaotic boundary
    pixels, so cross-path equality is asserted at shallow budgets (and
    on hardware by tools/tpu_revalidate.py), while THIS test pins the
    dispatch/packing plumbing at the batch-grid depths."""
    from distributedmandelbrot_tpu.core.geometry import TileSpec
    from distributedmandelbrot_tpu.ops.pallas_escape import (
        BATCH_GRID_MIN_ITER, compute_tile_pallas_device)
    from distributedmandelbrot_tpu.parallel.mesh import tile_mesh
    from distributedmandelbrot_tpu.parallel.sharding import (
        batched_escape_pixels_pallas)

    tile = 128
    mesh = tile_mesh()
    n_dev = mesh.devices.size
    k = 2 * n_dev  # two tiles per device: the batch-grid branch engages
    rng_rows = [[-0.7436 + 1e-4 * t, 0.1317, 2e-3 / (tile - 1)]
                for t in range(k)]
    ss = np.array(rng_rows, np.float32)
    mrds = np.array([BATCH_GRID_MIN_ITER + (t % 3) * 50 for t in range(k)],
                    np.int64)
    got = batched_escape_pixels_pallas(mesh, ss, mrds, definition=tile,
                                       interpret=True)
    for t in range(k):
        spec = TileSpec(ss[t, 0], ss[t, 1], ss[t, 2] * (tile - 1),
                        ss[t, 2] * (tile - 1), width=tile, height=tile)
        want = np.asarray(compute_tile_pallas_device(
            spec, int(mrds[t]), interpret=True))
        assert np.array_equal(got[t], want), f"tile {t} diverged"


# --- Packed multi-tile kernel (interleaved states) ---------------------------


@pytest.mark.parametrize("cycle_check", [None, True])
def test_packed_tiles_match_single_tile_kernel(cycle_check):
    """compute_tiles_packed_pallas: byte-lane packing of 2..4 interleaved
    tiles unpacks to exactly the single-tile kernel's planes (mixed
    windows and budgets).  ``cycle_check=True`` forces the Brent probe —
    the per-state snapshot refs and stride-6 scratch layout — which
    budgets this small would otherwise never arm (it's the production
    deep-view configuration, so it must not ship untested)."""
    from distributedmandelbrot_tpu.ops.pallas_escape import (
        compute_tile_pallas_device, compute_tiles_packed_pallas)

    tile = 128
    specs = [TileSpec(-0.7436, 0.1317, 2e-3, 2e-3, width=tile, height=tile),
             TileSpec(-0.2, -0.05, 0.1, 0.1, width=tile, height=tile),
             TileSpec(1.5, 1.5, 0.1, 0.1, width=tile, height=tile),
             TileSpec(-0.8, 0.1, 0.2, 0.2, width=tile, height=tile)]
    mis = [300, 150, 80, 260]
    for n in (1, 2, 3, 4):
        got = compute_tiles_packed_pallas(specs[:n], mis[:n], block_h=32,
                                          interpret=True,
                                          cycle_check=cycle_check)
        assert len(got) == n
        for s in range(n):
            ref = compute_tile_pallas_device(specs[s], mis[s], block_h=32,
                                             interpret=True,
                                             cycle_check=cycle_check)
            assert np.array_equal(np.asarray(got[s]), np.asarray(ref)), \
                f"pack={n} state {s} diverged"


def test_packed_tiles_julia_and_guards():
    """Julia packing parity plus the dispatch guards: shape mismatch and
    oversized packs raise PallasUnsupported."""
    from distributedmandelbrot_tpu.ops.pallas_escape import (
        PallasUnsupported, compute_tile_pallas_device,
        compute_tiles_packed_pallas)

    tile = 128
    spec = TileSpec(-1.5, -1.5, 3.0, 3.0, width=tile, height=tile)
    cs = [-0.8 + 0.156j, 0.285 + 0.01j]
    got = compute_tiles_packed_pallas([spec, spec], [200, 300], block_h=32,
                                      interpret=True, julia_cs=cs)
    for s, c in enumerate(cs):
        ref = compute_tile_pallas_device(spec, [200, 300][s], block_h=32,
                                         interpret=True, julia_c=c)
        assert np.array_equal(np.asarray(got[s]), np.asarray(ref))

    other = TileSpec(-1.5, -1.5, 3.0, 3.0, width=tile, height=64)
    with pytest.raises(PallasUnsupported, match="share"):
        compute_tiles_packed_pallas([spec, other], [100, 100],
                                    interpret=True)
    with pytest.raises(PallasUnsupported, match="pack"):
        compute_tiles_packed_pallas([spec] * 5, [100] * 5, interpret=True)


# --- Megakernel (fused-launch default dispatch route) ------------------------


@pytest.mark.parametrize("cycle_check", [None, True])
def test_mega_matches_single_tile_kernel(cycle_check):
    """compute_tiles_mega_pallas must be bit-identical to k single-tile
    dispatches across mixed windows (deep seahorse boundary, interior
    bulb, fast-escaping sky) and mixed budgets under one bucketed cap —
    the pipelined prologue and the in-kernel uint8 write-out reorder
    independent work, never change it.  ``cycle_check=True`` forces the
    Brent probe (snapshot scratch refs) at budgets that would not arm
    it."""
    from distributedmandelbrot_tpu.ops.pallas_escape import (
        compute_tile_pallas_device, compute_tiles_mega_pallas)

    tile = 128
    specs = [TileSpec(-0.7436, 0.1317, 2e-3, 2e-3, width=tile, height=tile),
             TileSpec(-0.2, -0.05, 0.1, 0.1, width=tile, height=tile),
             TileSpec(1.5, 1.5, 0.1, 0.1, width=tile, height=tile),
             TileSpec(-0.8, 0.1, 0.2, 0.2, width=tile, height=tile)]
    mis = [300, 150, 80, 260]
    tiles, scout = compute_tiles_mega_pallas(specs, mis, block_h=32,
                                             interpret=True,
                                             cycle_check=cycle_check)
    assert tiles.shape == (4, tile, tile)
    assert scout.shape == (4, 1)
    for s in range(4):
        ref = compute_tile_pallas_device(specs[s], mis[s], block_h=32,
                                         interpret=True,
                                         cycle_check=cycle_check)
        assert np.array_equal(np.asarray(tiles[s]), np.asarray(ref)), \
            f"tile {s} diverged from the single-tile kernel"


def test_mega_families_and_guards():
    """Megakernel parity for julia/ship plus the dispatch guards (shape
    mismatch raises PallasUnsupported; empty batch raises)."""
    from distributedmandelbrot_tpu.ops.pallas_escape import (
        PallasUnsupported, compute_tile_pallas_device,
        compute_tiles_mega_pallas)

    tile = 128
    spec = TileSpec(-1.5, -1.5, 3.0, 3.0, width=tile, height=tile)
    cs = [-0.8 + 0.156j, 0.285 + 0.01j]
    tiles, _ = compute_tiles_mega_pallas([spec, spec], [200, 300],
                                         block_h=32, interpret=True,
                                         julia_cs=cs)
    for s, c in enumerate(cs):
        ref = compute_tile_pallas_device(spec, [200, 300][s], block_h=32,
                                         interpret=True, julia_c=c)
        assert np.array_equal(np.asarray(tiles[s]), np.asarray(ref))

    ship = TileSpec(-1.76, -0.04, 0.02, 0.02, width=tile, height=tile)
    tiles, _ = compute_tiles_mega_pallas([ship, ship], [300, 200],
                                         block_h=32, interpret=True,
                                         burning=True,
                                         interior_check=False)
    for s, mi in enumerate([300, 200]):
        ref = compute_tile_pallas_device(ship, mi, block_h=32,
                                         interpret=True, burning=True,
                                         interior_check=False)
        assert np.array_equal(np.asarray(tiles[s]), np.asarray(ref))

    other = TileSpec(-1.5, -1.5, 3.0, 3.0, width=tile, height=64)
    with pytest.raises(PallasUnsupported, match="share"):
        compute_tiles_mega_pallas([spec, other], [100, 100],
                                  interpret=True)
    with pytest.raises(ValueError, match="empty"):
        compute_tiles_mega_pallas([], [], interpret=True)


def test_mega_golden_parity_against_numpy_backend():
    """Golden parity of the fused dispatch route end to end through the
    worker backend: a fast-escaping sky tile is BIT-IDENTICAL to the
    f64 NumpyBackend (every pixel escapes within a few iterations, so
    f32/f64 agree exactly); the bulb-straddling tile is bit-identical
    on the f64-proven interior mask (saturated counts); the deep
    seahorse-valley tile allows only the usual f32-vs-f64 boundary
    jitter off the provable pixels (same bound as the per-tile
    backend parity test above)."""
    from distributedmandelbrot_tpu.core.workload import Workload
    from distributedmandelbrot_tpu.worker.backends import (MegaTileHandle,
                                                           NumpyBackend,
                                                           PallasBackend)

    sky = Workload(4, 300, 3, 3)        # [1,2]x[1,2]: all-escaping
    bulb = Workload(4, 300, 1, 1)       # [-1,0]^2: bulb + cardioid lobe
    seahorse = Workload(4, 900, 1, 2)   # [-1,0]x[0,1]: seahorse valley
    ws = [sky, bulb, seahorse]
    backend = PallasBackend(definition=128)
    handles = backend.dispatch_many(ws)
    assert all(isinstance(h, MegaTileHandle) for h in handles)
    got = [backend.materialize_tile(h) for h in handles]
    golden = NumpyBackend(definition=128).compute_batch(ws)

    assert np.array_equal(got[0], golden[0]), "sky tile diverged"

    for i, w in ((1, bulb), (2, seahorse)):
        spec = TileSpec.for_chunk(w.level, w.index_real, w.index_imag,
                                  definition=128)
        cr, ci = spec.grid_2d()
        mask = np.asarray(escape_time.mandelbrot_interior(cr, ci)).ravel()
        assert np.array_equal(got[i][mask], golden[i][mask]), \
            f"tile {i}: proven-interior pixels diverged from the golden"
        off = float((got[i][~mask] != golden[i][~mask]).mean())
        assert off <= 0.02, f"tile {i}: {off:.2%} off-mask mismatch"


def test_mega_bf16_scout_never_changes_counts():
    """The mixed-precision guard: scout on vs scout off must be
    bit-identical for every tile (the bf16 pass is advisory only — the
    f32 loop always runs from z0 and alone decides counts), while the
    census proves the scout actually executed (nonzero on tiles with
    fast escapes, zero when disarmed)."""
    from distributedmandelbrot_tpu.ops.pallas_escape import (
        compute_tiles_mega_pallas)

    tile = 128
    specs = [TileSpec(-0.7436, 0.1317, 2e-3, 2e-3, width=tile, height=tile),
             TileSpec(-0.2, -0.05, 0.1, 0.1, width=tile, height=tile),
             TileSpec(1.5, 1.5, 0.1, 0.1, width=tile, height=tile)]
    mis = [500, 400, 300]
    on, census_on = compute_tiles_mega_pallas(specs, mis, block_h=32,
                                              interpret=True,
                                              scout_segments=2)
    off, census_off = compute_tiles_mega_pallas(specs, mis, block_h=32,
                                                interpret=True,
                                                scout_segments=0)
    assert np.array_equal(np.asarray(on), np.asarray(off)), \
        "bf16 scouting changed a final escape count"
    census_on = np.asarray(census_on).ravel()
    assert int(census_on[2]) == tile * tile, \
        "scout missed the all-escaping sky tile"
    assert int(census_on[0]) > 0, "scout saw no escapes on a boundary tile"
    assert not np.asarray(census_off).any(), "disarmed scout reported work"


def test_pallas_backend_dispatch_many_fuses_and_falls_back(monkeypatch):
    """dispatch_many parity + the two demotion paths: a singleton batch
    and DMTPU_MEGA=0 both take the per-tile route (no MegaTileHandle),
    while the fused route slices per-tile handles off one launch and
    counts it in the worker_kernel_* registry."""
    from distributedmandelbrot_tpu.core.workload import Workload
    from distributedmandelbrot_tpu.obs import names as obs_names
    from distributedmandelbrot_tpu.worker.backends import (MegaTileHandle,
                                                           PallasBackend)

    ws = [Workload(4, 300, 3, 3), Workload(4, 300, 1, 1)]
    backend = PallasBackend(definition=128)
    handles = backend.dispatch_many(ws)
    assert all(isinstance(h, MegaTileHandle) for h in handles)
    assert backend.registry.counter_value(
        obs_names.WORKER_KERNEL_FUSED_LAUNCHES) == 1
    assert backend.registry.counter_value(
        obs_names.WORKER_KERNEL_FUSED_TILES) == 2
    fused = [np.asarray(backend.materialize_tile(h)) for h in handles]
    per_tile = [np.asarray(backend.materialize_tile(
        backend.dispatch_tile(w))) for w in ws]
    for f, p in zip(fused, per_tile):
        assert np.array_equal(f, p)
    # The deep tile had fast escapes in the scout window -> the pruned-
    # pixels census counter moved at materialize time.
    assert (backend.registry.counter_value(
        obs_names.WORKER_KERNEL_BF16_PRUNED) or 0) > 0

    single = backend.dispatch_many(ws[:1])
    assert len(single) == 1
    assert not isinstance(single[0], MegaTileHandle)

    monkeypatch.setenv("DMTPU_MEGA", "0")
    gated = PallasBackend(definition=128)
    assert not any(isinstance(h, MegaTileHandle)
                   for h in gated.dispatch_many(ws))


def test_pipeline_executor_fuses_dispatch_batches():
    """End-to-end fusion through the pipelined executor: with
    batch_tiles > 1 the dispatch stage coalesces queued leases into
    megakernel launches (stage_stats reports the fusion rate), and
    every submitted tile stays bit-identical to a direct single-tile
    dispatch."""
    from distributedmandelbrot_tpu.core.workload import Workload
    from distributedmandelbrot_tpu.ops.pallas_escape import (
        compute_tile_pallas_device)
    from distributedmandelbrot_tpu.worker import PallasBackend
    from distributedmandelbrot_tpu.worker.pipeline import (PipelineExecutor,
                                                           as_dispatcher)

    class MiniClient:
        def __init__(self, tiles):
            self._tiles = list(tiles)
            self.submitted = []

        def request(self):
            return self._tiles.pop(0) if self._tiles else None

        def request_batch(self, n):
            got = self._tiles[:n]
            del self._tiles[:n]
            return got

        def submit(self, w, p):
            self.submitted.append((w, p))
            return True

        def submit_batch(self, results):
            self.submitted.extend(results)
            return [True] * len(results)

    tiles = [Workload(4, 300, i % 4, i // 4) for i in range(8)]
    client = MiniClient(tiles)
    backend = PallasBackend(definition=128)
    pipe = PipelineExecutor(client, as_dispatcher(backend),
                            window=8, depth=4, batch_size=4,
                            batch_tiles=4)
    pipe.run()
    assert len(client.submitted) == 8
    assert pipe.in_flight == 0
    fusion = pipe.stage_stats()["fusion"]
    assert fusion["tiles"] == 8
    assert fusion["fused_launches"] >= 1
    assert fusion["tiles_per_launch"] > 1.0
    for w, pixels in client.submitted:
        spec = TileSpec.for_chunk(w.level, w.index_real, w.index_imag,
                                  definition=128)
        want = np.asarray(compute_tile_pallas_device(
            spec, w.max_iter, interpret=True)).reshape(-1)
        assert np.array_equal(np.asarray(pixels), want)


# --- Interior fast path + device-targeted dispatch (worker backends) ---------


def test_backend_interior_fast_path_bit_identical_on_bulb_straddling_tile():
    """Satellite check for the closed-form interior shortcut end to end
    through the worker backends: a tile covering x,y in [-1,0]^2
    straddles the period-2 bulb (center -1+0i, r=1/4) AND the main
    cardioid's lower-left lobe.  Every pixel the f64 closed form proves
    interior must be BIT-IDENTICAL between the Pallas fast path and the
    NumpyBackend golden (both are exactly the saturated max_iter
    count); off the proven mask only the usual f32-vs-f64 boundary
    jitter is allowed."""
    from distributedmandelbrot_tpu.core.workload import Workload
    from distributedmandelbrot_tpu.worker.backends import (NumpyBackend,
                                                           PallasBackend)

    w = Workload(4, 300, 1, 1)
    golden = NumpyBackend(definition=128).compute_batch([w])[0]
    fast = PallasBackend(definition=128).compute_batch([w])[0]

    spec = TileSpec.for_chunk(4, 1, 1, definition=128)
    cr, ci = spec.grid_2d()
    mask = np.asarray(escape_time.mandelbrot_interior(cr, ci)).ravel()
    assert mask.mean() > 0.05, "fixture view misses the bulb/cardioid"
    assert np.array_equal(fast[mask], golden[mask]), \
        "interior fast path diverged from the golden on proven pixels"
    off = float((fast[~mask] != golden[~mask]).mean())
    assert off <= 0.02, f"{off:.2%} mismatch off the proven-interior mask"


def test_device_targeted_dispatch_pins_output_and_matches_default():
    """compute_tile_pallas_device(device=...) commits the dispatch to
    that chip (here a virtual CPU device) without changing a pixel —
    the property the pipelined executor's round-robin rests on."""
    import jax

    from distributedmandelbrot_tpu.ops.pallas_escape import (
        compute_tile_pallas_device)

    spec = VIEWS["seahorse"]
    base = np.asarray(compute_tile_pallas_device(spec, 120, interpret=True))
    target = jax.devices()[-1]
    out = compute_tile_pallas_device(spec, 120, interpret=True,
                                     device=target)
    assert out.devices() == {target}
    assert np.array_equal(np.asarray(out), base)


def test_pallas_backend_devices_follow_mesh_placement_order():
    from distributedmandelbrot_tpu.parallel.mesh import device_ring
    from distributedmandelbrot_tpu.worker import PallasBackend

    backend = PallasBackend(definition=128)
    assert backend.devices() == device_ring()
    assert len(backend.devices()) >= 2  # conftest's 8 virtual devices


def test_pipeline_executor_drives_pallas_backend_across_devices():
    """End-to-end pipelined executor over the real PallasBackend
    (interpret kernels, virtual CPU devices): every submitted tile is
    bit-identical to a direct single-tile dispatch, whatever device the
    round-robin placed it on."""
    from distributedmandelbrot_tpu.core.workload import Workload
    from distributedmandelbrot_tpu.ops.pallas_escape import (
        compute_tile_pallas_device)
    from distributedmandelbrot_tpu.worker import PallasBackend
    from distributedmandelbrot_tpu.worker.pipeline import (PipelineExecutor,
                                                           as_dispatcher)

    class MiniClient:
        def __init__(self, tiles):
            self._tiles = list(tiles)
            self.submitted = []

        def request(self):
            return self._tiles.pop(0) if self._tiles else None

        def request_batch(self, n):
            got = self._tiles[:n]
            del self._tiles[:n]
            return got

        def submit(self, w, p):
            self.submitted.append((w, p))
            return True

        def submit_batch(self, results):
            self.submitted.extend(results)
            return [True] * len(results)

    tiles = [Workload(2, 48, i % 2, i // 2) for i in range(4)]
    client = MiniClient(tiles)
    backend = PallasBackend(definition=128)
    pipe = PipelineExecutor(client, as_dispatcher(backend),
                            window=4, depth=2, batch_size=2)
    rounds = pipe.run()
    assert rounds >= 1
    assert len(client.submitted) == 4
    assert pipe.in_flight == 0
    for w, pixels in client.submitted:
        spec = TileSpec.for_chunk(w.level, w.index_real, w.index_imag,
                                  definition=128)
        want = np.asarray(compute_tile_pallas_device(
            spec, w.max_iter, interpret=True)).reshape(-1)
        assert np.array_equal(np.asarray(pixels), want)


# --- Mesh megakernel route (shard_map over the tiles axis) -------------------


def test_mesh_mega_matches_single_device_and_single_tile():
    """Golden bit-parity triangle of the mesh route on the canonical
    chunk trio (fast-escaping sky, bulb-straddling, deep seahorse
    valley): the shard_map'd fused launch must be bit-identical to the
    single-device megakernel AND to per-tile single dispatches —
    pixels and scout census both — with k=3 exercising the
    trivial-tile padding on the 8-device ring."""
    from distributedmandelbrot_tpu.ops.pallas_escape import (
        compute_tiles_mega_pallas)
    from distributedmandelbrot_tpu.parallel.sharding import (
        compute_tiles_mega_sharded)

    specs = [TileSpec.for_chunk(4, 3, 3, definition=128),   # sky
             TileSpec.for_chunk(4, 1, 1, definition=128),   # bulb
             TileSpec.for_chunk(4, 1, 2, definition=128)]   # seahorse
    mis = [300, 300, 900]
    mesh_t, mesh_s = compute_tiles_mega_sharded(specs, mis,
                                                interpret=True)
    mega_t, mega_s = compute_tiles_mega_pallas(specs, mis,
                                               interpret=True)
    mesh_t, mesh_s = np.asarray(mesh_t), np.asarray(mesh_s)
    assert mesh_t.shape == (3, 128, 128)
    assert mesh_s.shape == (3, 1)
    assert np.array_equal(mesh_t, np.asarray(mega_t)), \
        "mesh pixels diverged from the single-device megakernel"
    assert np.array_equal(mesh_s, np.asarray(mega_s)), \
        "mesh scout census diverged from the single-device megakernel"
    # sky escapes everywhere inside the scout window; the census must
    # have seen it through the mesh route too.
    assert int(mesh_s[0, 0]) > 0


def test_mesh_mega_single_tile_parity_per_tile():
    """Per-tile leg of the parity triangle, kept separate so a failure
    names the diverging window."""
    from distributedmandelbrot_tpu.ops.pallas_escape import (
        compute_tile_pallas_device)
    from distributedmandelbrot_tpu.parallel.sharding import (
        compute_tiles_mega_sharded)

    specs = [TileSpec.for_chunk(4, 3, 3, definition=128),
             TileSpec.for_chunk(4, 1, 1, definition=128),
             TileSpec.for_chunk(4, 1, 2, definition=128)]
    mis = [300, 300, 900]
    mesh_t, _ = compute_tiles_mega_sharded(specs, mis, interpret=True)
    mesh_t = np.asarray(mesh_t)
    names = ["sky", "bulb-straddling", "deep-seahorse"]
    for i, (sp, mi) in enumerate(zip(specs, mis)):
        single = np.asarray(compute_tile_pallas_device(sp, mi,
                                                       interpret=True))
        assert np.array_equal(mesh_t[i], single), \
            f"{names[i]} chunk diverged from the single-tile kernel"


def test_mesh_one_device_degenerates_exactly():
    """A 1-device mesh must produce bit-identical pixels AND scout to
    the existing single-device fused route — the degeneration contract
    the backend's mesh_width gate relies on."""
    import jax
    from jax.sharding import Mesh

    from distributedmandelbrot_tpu.ops.pallas_escape import (
        compute_tiles_mega_pallas)
    from distributedmandelbrot_tpu.parallel.mesh import TILE_AXIS
    from distributedmandelbrot_tpu.parallel.sharding import (
        compute_tiles_mega_sharded)

    specs = [TileSpec.for_chunk(4, 3, 3, definition=128),
             TileSpec.for_chunk(4, 1, 2, definition=128)]
    mis = [200, 500]
    one = Mesh(np.array(jax.devices()[:1]), (TILE_AXIS,))
    mesh_t, mesh_s = compute_tiles_mega_sharded(specs, mis, mesh=one,
                                                interpret=True)
    mega_t, mega_s = compute_tiles_mega_pallas(specs, mis,
                                               interpret=True)
    assert np.array_equal(np.asarray(mesh_t), np.asarray(mega_t))
    assert np.array_equal(np.asarray(mesh_s), np.asarray(mega_s))


def test_backend_mesh_route_counters_and_hatch(monkeypatch):
    """dispatch_many over the >1-device ring takes the mesh route
    (worker_mesh_* counters move, one device-launch equivalent per ring
    member) with pixels bit-identical to per-tile dispatches; under
    DMTPU_MESH=0 the route is off (mesh_width 1, counters untouched)
    and output is unchanged."""
    from distributedmandelbrot_tpu.core.workload import Workload
    from distributedmandelbrot_tpu.obs import names as obs_names
    from distributedmandelbrot_tpu.worker.backends import (MegaTileHandle,
                                                           PallasBackend)

    ws = [Workload(4, 300, 3, 3), Workload(4, 300, 1, 1),
          Workload(4, 900, 1, 2)]
    backend = PallasBackend(definition=128)
    n_dev = len(backend.devices())
    assert backend.mesh_width == n_dev >= 2
    handles = backend.dispatch_many(ws)
    assert all(isinstance(h, MegaTileHandle) for h in handles)
    got = [np.asarray(backend.materialize_tile(h)) for h in handles]
    per_tile = [np.asarray(backend.materialize_tile(
        backend.dispatch_tile(w))) for w in ws]
    for g, p in zip(got, per_tile):
        assert np.array_equal(g, p)
    assert backend.registry.counter_value(
        obs_names.WORKER_MESH_LAUNCHES) == 1
    assert backend.registry.counter_value(
        obs_names.WORKER_MESH_DEVICES) == n_dev
    # A device-pinned launch must NOT take the mesh route.
    dev0 = backend.devices()[0]
    backend.dispatch_many(ws, device=dev0)
    assert backend.registry.counter_value(
        obs_names.WORKER_MESH_LAUNCHES) == 1

    monkeypatch.setenv("DMTPU_MESH", "0")
    gated = PallasBackend(definition=128)
    assert gated.mesh_width == 1
    hatch = [np.asarray(gated.materialize_tile(h))
             for h in gated.dispatch_many(ws)]
    for h, p in zip(hatch, per_tile):
        assert np.array_equal(h, p)
    assert gated.registry.counter_value(
        obs_names.WORKER_MESH_LAUNCHES) is None


# --- MXU iteration map (ops/mxu_iteration) -----------------------------------


def test_mxu_step_is_the_complex_square():
    """The 2x2 rotation-matrix matmul form computes z^2 + c (numerical
    agreement with the direct complex form; bit-identity is platform-
    dependent and probed separately)."""
    import jax.numpy as jnp

    from distributedmandelbrot_tpu.ops.mxu_iteration import mxu_step

    rng = np.random.default_rng(7)
    zr = rng.uniform(-1.5, 1.5, (8, 16)).astype(np.float32)
    zi = rng.uniform(-1.5, 1.5, (8, 16)).astype(np.float32)
    cr = rng.uniform(-2.0, 1.0, (8, 16)).astype(np.float32)
    ci = rng.uniform(-1.5, 1.5, (8, 16)).astype(np.float32)
    out_r, out_i = mxu_step(jnp.asarray(zr), jnp.asarray(zi),
                            jnp.asarray(cr), jnp.asarray(ci))
    z = (zr + 1j * zi).astype(np.complex64)
    want = z * z + (cr + 1j * ci)
    np.testing.assert_allclose(np.asarray(out_r), want.real, rtol=1e-5,
                               atol=1e-5)
    np.testing.assert_allclose(np.asarray(out_i), want.imag, rtol=1e-5,
                               atol=1e-5)


def test_mxu_gate_resolution(monkeypatch):
    """The DMTPU_MXU gate: off by default; enabled resolves to full
    ONLY with proven bit-parity, census otherwise — and the parity
    verdict is a real probe result, not an assumption."""
    from distributedmandelbrot_tpu.ops import mxu_iteration as mxu

    monkeypatch.delenv(mxu.MXU_ENV, raising=False)
    assert mxu.mxu_mode() == "off"
    monkeypatch.setenv(mxu.MXU_ENV, "0")
    assert mxu.mxu_mode() == "off"
    monkeypatch.setenv(mxu.MXU_ENV, "1")
    proven = mxu.mxu_parity_proven()
    assert mxu.mxu_mode() == ("full" if proven else "census")
    # Force each verdict through the cache to pin the mapping.
    import jax
    key = jax.default_backend()
    mxu._parity_cache[key] = True
    assert mxu.mxu_mode() == "full"
    mxu._parity_cache[key] = False
    assert mxu.mxu_mode() == "census"
    mxu.reset_mxu_cache()
    assert key not in mxu._parity_cache


def test_mxu_full_mode_bit_parity_where_proven():
    """Wherever the parity contract claims bit-identity (full mode on a
    parity-proven platform), the MXU-form megakernel must match the
    single-tile VPU kernel exactly.  Skipped on platforms where the
    probe demotes to census — there the contract claims nothing."""
    from distributedmandelbrot_tpu.ops.mxu_iteration import (
        mxu_parity_proven)
    from distributedmandelbrot_tpu.ops.pallas_escape import (
        compute_tile_pallas_device, compute_tiles_mega_pallas)

    if not mxu_parity_proven():
        pytest.skip("MXU/VPU bit-parity unproven on this platform; "
                    "gate demotes to the census (no parity claimed)")
    specs = [TileSpec.for_chunk(4, 3, 3, definition=128),
             TileSpec.for_chunk(4, 1, 2, definition=128)]
    mis = [300, 900]
    tiles, _ = compute_tiles_mega_pallas(specs, mis, interpret=True,
                                         use_mxu=True)
    for i, (sp, mi) in enumerate(zip(specs, mis)):
        single = np.asarray(compute_tile_pallas_device(sp, mi,
                                                       interpret=True))
        assert np.array_equal(np.asarray(tiles[i]), single)


def test_mxu_guards_and_census():
    """use_mxu is power-2 Mandelbrot/Julia-form only (burning ship's
    abs breaks the rotation-matrix embedding); the census-only fallback
    counts sky escapes at full panel occupancy and near-none on the
    all-interior window."""
    from distributedmandelbrot_tpu.ops.mxu_iteration import (
        CENSUS_PANEL, mxu_census_counts)
    from distributedmandelbrot_tpu.ops.pallas_escape import (
        PallasUnsupported, _params_row, compute_tiles_mega_pallas)

    sky = TileSpec.for_chunk(4, 3, 3, definition=128)
    with pytest.raises(PallasUnsupported, match="[Mm][Xx][Uu]"):
        compute_tiles_mega_pallas([sky, sky], [100, 100], interpret=True,
                                  use_mxu=True, burning=True,
                                  interior_check=False)

    bulb = TileSpec(-0.1, -0.05, 0.02, 0.02, width=128, height=128)
    rows = [_params_row(sky), _params_row(bulb)]
    counts = mxu_census_counts(rows, [300, 300], height=128, width=128)
    assert counts.shape == (2,)
    assert int(counts[0]) == CENSUS_PANEL * CENSUS_PANEL, \
        "census missed escapes on the all-escaping sky window"
    assert int(counts[1]) <= CENSUS_PANEL, \
        "census claimed escapes across the cardioid interior"


def test_backend_mxu_census_mode_counters(monkeypatch):
    """DMTPU_MXU=1 on an unproven platform: outputs stay bit-identical
    (the census is advisory), the demotion is counted, and the census
    pixel counter moves; on a proven platform the launch counter moves
    instead."""
    from distributedmandelbrot_tpu.core.workload import Workload
    from distributedmandelbrot_tpu.obs import names as obs_names
    from distributedmandelbrot_tpu.ops.mxu_iteration import (
        mxu_parity_proven)
    from distributedmandelbrot_tpu.worker.backends import PallasBackend

    ws = [Workload(4, 300, 3, 3), Workload(4, 300, 1, 1)]
    base = PallasBackend(definition=128)
    want = [np.asarray(base.materialize_tile(h))
            for h in base.dispatch_many(ws)]

    monkeypatch.setenv("DMTPU_MXU", "1")
    backend = PallasBackend(definition=128)
    got = [np.asarray(backend.materialize_tile(h))
           for h in backend.dispatch_many(ws)]
    for g, w in zip(got, want):
        assert np.array_equal(g, w)
    cv = backend.registry.counter_value
    if mxu_parity_proven():
        assert cv(obs_names.WORKER_KERNEL_MXU_LAUNCHES) == 1
        assert cv(obs_names.WORKER_KERNEL_MXU_DEMOTIONS) is None
    else:
        assert cv(obs_names.WORKER_KERNEL_MXU_DEMOTIONS) == 1
        assert cv(obs_names.WORKER_KERNEL_MXU_LAUNCHES) is None
        # The sky tile's panel escapes entirely -> census pixels moved.
        assert (cv(obs_names.WORKER_KERNEL_MXU_CENSUS) or 0) > 0
