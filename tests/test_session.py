"""Persistent-session wire tier (PURPOSE_SESSION, 0x05): zero-copy
uploads, the compression heuristic, piggybacked grants, the
connection-budget guarantee, and legacy interop in both directions."""

import numpy as np
import pytest

from distributedmandelbrot_tpu.codecs.rle import RleCodec, estimate_ratio
from distributedmandelbrot_tpu.core import LevelSetting
from distributedmandelbrot_tpu.core.geometry import CHUNK_PIXELS
from distributedmandelbrot_tpu.net import protocol as proto
from distributedmandelbrot_tpu.obs import names as obs_names
from distributedmandelbrot_tpu.utils.metrics import Counters
from distributedmandelbrot_tpu.viewer import DataClient, FetchStatus
from distributedmandelbrot_tpu.worker import (DistributerClient, JaxBackend,
                                              NumpyBackend, Worker)
from distributedmandelbrot_tpu.worker.client import DistributerSession

from harness import CoordinatorHarness

MAX_ITER = 24


# -- zero-copy upload buffers ----------------------------------------------

def test_pixel_bytes_is_zero_copy_for_contiguous_uint8():
    arr = np.zeros(CHUNK_PIXELS, dtype=np.uint8)
    view = DistributerClient._pixel_bytes(arr)
    assert isinstance(view, memoryview)
    assert len(view) == CHUNK_PIXELS
    # The memoryview aliases the array's own buffer — no copy was made.
    assert np.shares_memory(np.frombuffer(view, dtype=np.uint8), arr)
    arr[123] = 45
    assert view[123] == 45


def test_pixel_bytes_copies_only_when_it_must():
    # 2-D C-contiguous uint8 still aliases (ravel of a contiguous array
    # is a view).
    arr2d = np.zeros((4096, 4096), dtype=np.uint8)
    view = DistributerClient._pixel_bytes(arr2d)
    assert np.shares_memory(np.frombuffer(view, dtype=np.uint8), arr2d)
    # A strided slice cannot be aliased flat: one normalizing copy.
    strided = np.zeros(2 * CHUNK_PIXELS, dtype=np.uint8)[::2]
    view = DistributerClient._pixel_bytes(strided)
    assert len(view) == CHUNK_PIXELS
    assert not np.shares_memory(np.frombuffer(view, dtype=np.uint8), strided)
    # Wrong dtype: converted, not aliased.
    wide = np.zeros(CHUNK_PIXELS, dtype=np.uint16)
    view = DistributerClient._pixel_bytes(wide)
    assert len(view) == CHUNK_PIXELS
    with pytest.raises(ValueError):
        DistributerClient._pixel_bytes(np.zeros(7, dtype=np.uint8))


# -- compression heuristic -------------------------------------------------

def test_estimate_ratio_flat_vs_noise():
    flat = np.zeros(CHUNK_PIXELS, dtype=np.uint8)
    assert estimate_ratio(flat) > 100.0
    rng = np.random.default_rng(7)
    noise = rng.integers(0, 256, CHUNK_PIXELS, dtype=np.uint8)
    # No value dominates the strided sample: the histogram stage bails
    # out without ever scanning run boundaries.
    assert estimate_ratio(noise) == 1.0


def test_estimate_ratio_tracks_exact_encoded_size():
    # Alternating values: every run has length 1, so the boundary-count
    # estimate equals the exact encoded size and must agree with the
    # codec (a sub-1.0 "ratio" — RLE would inflate this tile).
    data = np.zeros(1 << 20, dtype=np.uint8)
    data[1::2] = 3
    est = estimate_ratio(data)
    exact = data.size / len(RleCodec().encode(data))
    assert est == pytest.approx(exact, rel=0.01)


# -- direct session exchanges ----------------------------------------------

def _checker(value_a=0, value_b=200, period=4096):
    """A compressible-but-nontrivial tile: long runs of two values."""
    tile = np.full(CHUNK_PIXELS, value_a, dtype=np.uint8)
    tile.reshape(-1, period)[::2] = value_b
    return tile


def test_session_roundtrip_compressed_and_raw_bit_identical(tmp_path):
    """Both wire codecs must land byte-identical chunks on disk, and the
    codec choice must follow the per-tile heuristic."""
    with CoordinatorHarness(str(tmp_path), [LevelSetting(2, MAX_ITER)]) \
            as farm:
        counters = Counters()
        sess = DistributerSession("127.0.0.1", farm.distributer_port,
                                  counters=counters)
        assert sess.connect()
        assert sess.flags & proto.SESSION_FLAG_RLE
        grants = sess.request_batch(2)
        assert len(grants) == 2
        rng = np.random.default_rng(3)
        compressible = _checker()
        noise = rng.integers(0, 256, CHUNK_PIXELS, dtype=np.uint8)
        accepted, piggyback = sess.submit_pipelined(
            [(grants[0], compressible), (grants[1], noise)], want_lease=2)
        assert accepted == [True, True]
        # The ack on the last upload piggybacked the remaining tiles.
        assert len(piggyback) == 2
        # One tile went RLE (far above the 2x bar), one went raw.
        assert 0 < counters.get(obs_names.WIRE_COMPRESSED_BYTES) \
            < CHUNK_PIXELS // 4
        assert counters.get(obs_names.WIRE_RAW_BYTES) == CHUNK_PIXELS
        accepted, rest = sess.submit_pipelined(
            [(piggyback[0], compressible), (piggyback[1], noise)])
        assert accepted == [True, True] and rest == []
        sess.close()
        farm.wait_saves_settled(expected_accepted=4)

        fetch = DataClient("127.0.0.1", farm.dataserver_port).fetch
        for w, sent in [(grants[0], compressible), (grants[1], noise),
                        (piggyback[0], compressible), (piggyback[1], noise)]:
            pixels, status = fetch(w.level, w.index_real, w.index_imag)
            assert status is FetchStatus.OK
            np.testing.assert_array_equal(pixels, sent)
        assert farm.counters.get(obs_names.WIRE_COMPRESSED_BYTES) \
            == counters.get(obs_names.WIRE_COMPRESSED_BYTES)
        assert farm.counters.get(obs_names.WIRE_RAW_BYTES) \
            == counters.get(obs_names.WIRE_RAW_BYTES)


def test_session_compress_disabled_negotiates_raw(tmp_path):
    with CoordinatorHarness(str(tmp_path), [LevelSetting(1, MAX_ITER)]) \
            as farm:
        counters = Counters()
        sess = DistributerSession("127.0.0.1", farm.distributer_port,
                                  compress=False, counters=counters)
        assert sess.connect()
        assert not sess.flags & proto.SESSION_FLAG_RLE
        (w,) = sess.request_batch(1)
        accepted, _ = sess.submit_pipelined([(w, _checker())])
        assert accepted == [True]
        sess.close()
        farm.wait_saves_settled(expected_accepted=1)
        # Even a perfectly compressible tile ships raw when RLE was not
        # negotiated.
        assert counters.get(obs_names.WIRE_COMPRESSED_BYTES) == 0
        assert counters.get(obs_names.WIRE_RAW_BYTES) == CHUNK_PIXELS


# -- pipelined farm over sessions ------------------------------------------

def test_pipelined_farm_one_connection_per_lane(tmp_path):
    """The connection-budget acceptance check: a whole pipelined run
    costs one TCP connect per upload lane plus one for the lease
    thread, with piggybacked grants carrying the steady state."""
    with CoordinatorHarness(str(tmp_path), [LevelSetting(2, MAX_ITER)]) \
            as farm:
        worker = Worker(
            DistributerClient("127.0.0.1", farm.distributer_port),
            JaxBackend(dtype=np.float32),
            batch_size=2, window=4, upload_lanes=2)
        worker.run_until_drained()
        farm.wait_saves_settled(expected_accepted=4)
        assert farm.scheduler.is_complete()
        assert worker.counters.get(obs_names.WORKER_SESSION_FALLBACKS) == 0
        assert worker.counters.get(obs_names.WORKER_SESSIONS_OPENED) == 3
        assert farm.counters.get(obs_names.COORD_SESSIONS_OPENED) == 3
        assert farm.counters.get(obs_names.COORD_CONNECTIONS_ACCEPTED) == 3
        assert farm.counters.get(obs_names.COORD_SESSION_FRAMES) > 0
        # Blocking round trips stay near one per tile (lease exchanges
        # plus pipelined ack waits; drain probes add a small constant).
        rtts = worker.counters.get(obs_names.WORKER_WIRE_RTTS)
        assert 0 < rtts <= 2 * 4 + 4
        stats = worker.pipeline.stage_stats()
        assert len(stats["lanes"]) == 2
        assert sum(ls["items"] for ls in stats["lanes"]) == 4


# -- legacy interop, both directions ---------------------------------------

def test_session_worker_against_legacy_coordinator_falls_back(tmp_path):
    """A session-speaking worker against a coordinator that predates
    0x05: hello EOFs, every stage falls back to connection-per-exchange,
    and the stored tile is still bit-identical to the golden path."""
    with CoordinatorHarness(str(tmp_path), [LevelSetting(1, 12)],
                            accept_session=False) as farm:
        worker = Worker(
            DistributerClient("127.0.0.1", farm.distributer_port),
            NumpyBackend(), batch_size=1, window=2, upload_lanes=2)
        worker.run_until_drained()
        farm.wait_saves_settled(expected_accepted=1)
        assert farm.scheduler.is_complete()
        assert worker.counters.get(obs_names.WORKER_SESSIONS_OPENED) == 0
        assert worker.counters.get(obs_names.WORKER_SESSION_FALLBACKS) == 3
        assert farm.counters.get(obs_names.COORD_SESSIONS_OPENED) == 0
        pixels, status = DataClient(
            "127.0.0.1", farm.dataserver_port).fetch(1, 0, 0)
        assert status is FetchStatus.OK
    (tmp_path / "b").mkdir()
    with CoordinatorHarness(str(tmp_path / "b"), [LevelSetting(1, 12)]) \
            as farm2:
        # Same tile through the session path: byte-identical on disk.
        worker = Worker(
            DistributerClient("127.0.0.1", farm2.distributer_port),
            NumpyBackend(), batch_size=1, window=2)
        worker.run_until_drained()
        farm2.wait_saves_settled(expected_accepted=1)
        session_pixels, status = DataClient(
            "127.0.0.1", farm2.dataserver_port).fetch(1, 0, 0)
        assert status is FetchStatus.OK
        np.testing.assert_array_equal(pixels, session_pixels)


def test_legacy_worker_against_session_coordinator(tmp_path):
    """The other direction: a worker pinned to the legacy protocol
    (use_session=False) against a session-capable coordinator."""
    with CoordinatorHarness(str(tmp_path), [LevelSetting(1, 12)]) as farm:
        worker = Worker(
            DistributerClient("127.0.0.1", farm.distributer_port),
            NumpyBackend(), batch_size=1, window=2, use_session=False)
        worker.run_until_drained()
        farm.wait_saves_settled(expected_accepted=1)
        assert farm.scheduler.is_complete()
        assert worker.counters.get(obs_names.WORKER_SESSIONS_OPENED) == 0
        assert farm.counters.get(obs_names.COORD_SESSIONS_OPENED) == 0
        pixels, status = DataClient(
            "127.0.0.1", farm.dataserver_port).fetch(1, 0, 0)
        assert status is FetchStatus.OK
