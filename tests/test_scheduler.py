"""Pure scheduler logic over virtual time — no sockets, no real clock."""

import pytest

from distributedmandelbrot_tpu.coordinator import ManualClock, TileScheduler
from distributedmandelbrot_tpu.core import LevelSetting, Workload


def make(levels=((2, 64),), completed=None, timeout=3600.0):
    clock = ManualClock()
    sched = TileScheduler([LevelSetting(l, m) for l, m in levels],
                          completed=completed, lease_timeout=timeout,
                          clock=clock)
    return sched, clock


def test_grant_order_matches_reference_grid_walk():
    """Level settings in order; index_real outer, index_imag inner."""
    sched, _ = make(levels=((2, 64), (3, 128)))
    got = [sched.acquire() for _ in range(4)]
    assert [(w.level, w.index_real, w.index_imag) for w in got] == \
        [(2, 0, 0), (2, 0, 1), (2, 1, 0), (2, 1, 1)]
    nxt = sched.acquire()
    assert (nxt.level, nxt.max_iter) == (3, 128)


def test_leased_tiles_not_regranted():
    sched, _ = make()
    grants = {sched.acquire().key for _ in range(4)}
    assert len(grants) == 4
    assert sched.acquire() is None  # all leased, none completed


def test_complete_and_dedup():
    sched, _ = make()
    w = sched.acquire()
    assert sched.complete(w)
    assert not sched.complete(w)  # duplicate result rejected
    assert sched.completed_count == 1


def test_unknown_result_rejected():
    sched, _ = make()
    stray = Workload(2, 64, 1, 1)  # never granted
    assert not sched.can_accept(stray)
    assert not sched.complete(stray)


def test_max_iter_mismatch_rejected_wildcard_accepted():
    sched, _ = make()
    w = sched.acquire()
    wrong = Workload(w.level, 999, w.index_real, w.index_imag)
    assert not sched.can_accept(wrong)
    wildcard = Workload(w.level, None, w.index_real, w.index_imag)
    assert sched.can_accept(wildcard)


def test_lease_expiry_redistributes_after_sweep():
    sched, clock = make(timeout=10.0)
    w = sched.acquire()
    # Exhaust the rest of the grid so only expiry can yield w again.
    while sched.acquire() is not None:
        pass
    clock.advance(11.0)
    assert sched.sweep() == 4  # all four leases expired
    regrant = sched.acquire()
    assert regrant.key == w.key  # FIFO requeue: first-leased comes back first


def test_stale_result_rejected_after_expiry():
    """A worker returning past the lease deadline is rejected even before
    any sweep runs (lazy expiry)."""
    sched, clock = make(timeout=10.0)
    w = sched.acquire()
    clock.advance(10.0)
    assert not sched.can_accept(w)
    assert not sched.complete(w)


def test_redistributed_tile_rejects_first_workers_late_result():
    """At-least-once: after expiry + regrant, the new lease accepts and the
    result is recorded once."""
    sched, clock = make(timeout=10.0)
    w1 = sched.acquire()
    clock.advance(11.0)
    sched.sweep()
    w2 = sched.acquire()
    assert w2.key == w1.key
    assert sched.complete(w2)
    assert not sched.complete(w2)


def test_completed_seed_skips_tiles():
    """Resume: disk-seeded completions (keyed without max_iter) are never
    regranted — the fix for the reference's broken hash contract."""
    sched, _ = make(completed={(2, 0, 0), (2, 1, 1)})
    grants = []
    while (w := sched.acquire()) is not None:
        grants.append(w.key)
    assert grants == [(2, 0, 1), (2, 1, 0)]


def test_is_complete():
    sched, _ = make()
    while (w := sched.acquire()) is not None:
        sched.complete(w)
    assert sched.is_complete()
    assert sched.acquire() is None


def test_acquire_batch():
    sched, _ = make(levels=((3, 64),))
    batch = sched.acquire_batch(5)
    assert len(batch) == 5
    assert len({w.key for w in batch}) == 5
    rest = sched.acquire_batch(100)
    assert len(rest) == 4  # 9 total
    assert sched.acquire_batch(3) == []


def test_reopen_after_failed_persistence():
    """A tile whose save failed must become grantable again, not a silent
    hole in a 'complete' run."""
    sched, _ = make(levels=((1, 16),))
    w = sched.acquire()
    assert sched.complete(w)
    assert sched.is_complete()
    sched.reopen(w)
    assert not sched.is_complete()
    w2 = sched.acquire()
    assert w2.key == w.key
    assert sched.complete(w2)
    sched.reopen(Workload(1, 16, 0, 0))  # idempotent for completed...
    sched.reopen(Workload(1, 16, 0, 0))  # ...and for already-reopened
    assert sched.acquire() is not None


def test_duplicate_levels_rejected():
    with pytest.raises(ValueError):
        TileScheduler([LevelSetting(2, 64), LevelSetting(2, 128)])


def test_outstanding_leases_tracks_expiry():
    sched, clock = make(timeout=10.0)
    sched.acquire()
    sched.acquire()
    assert sched.outstanding_leases == 2
    clock.advance(11.0)
    assert sched.outstanding_leases == 0


def test_claim_consumes_lease_so_concurrent_submit_rejected():
    """The ingest race: while worker B's payload is in flight (lease
    claimed), worker A's late echo for the same tile must be rejected —
    the lease is matched exactly once (reference Distributer.cs:404)."""
    sched, clock = make(levels=((1, 12),), timeout=10.0)
    w = sched.acquire()
    clock.advance(11)       # A's lease expires
    w_b = sched.acquire()   # redistribution to B
    assert w_b.key == w.key
    tok_b = sched.claim(w_b)             # B's echo arrives, payload starts
    assert tok_b is not None
    assert sched.claim(w) is None        # A's echo mid-upload: rejected
    assert sched.can_accept(w) is False
    assert sched.acquire() is None       # claimed tile is not re-granted
    assert sched.finish_claim(w_b, tok_b) is True
    assert sched.is_complete()


def test_release_claim_requeues_tile():
    """Connection dies mid-payload: the claim is released and the tile is
    immediately grantable again."""
    sched, clock = make(levels=((1, 12),), timeout=10.0)
    w = sched.acquire()
    tok = sched.claim(w)
    assert tok is not None
    sched.release_claim(w, tok)
    w2 = sched.acquire()
    assert w2 is not None and w2.key == w.key
    assert sched.complete(w2) is True


def test_claim_expiring_mid_upload_drops_result_and_requeues():
    sched, clock = make(levels=((1, 12),), timeout=10.0)
    w = sched.acquire()
    tok = sched.claim(w)
    clock.advance(11)                    # payload dawdles past the expiry
    assert sched.finish_claim(w, tok) is False
    w2 = sched.acquire()                 # tile grantable again
    assert w2 is not None and w2.key == w.key


def test_sweep_requeues_expired_claims():
    sched, clock = make(levels=((1, 12),), timeout=10.0)
    w = sched.acquire()
    assert sched.claim(w) is not None
    clock.advance(11)
    assert sched.sweep() == 1
    assert sched.acquire() is not None


def test_stale_claim_token_cannot_consume_superseding_claim():
    """A's claim expires mid-upload; B re-leases and re-claims the tile.
    A's late finish/release with its stale token must be a no-op — B's
    live claim survives and B's result is the one accepted."""
    sched, clock = make(levels=((1, 12),), timeout=10.0)
    w_a = sched.acquire()
    tok_a = sched.claim(w_a)
    assert tok_a is not None
    clock.advance(11)                    # A's claim expires mid-upload
    w_b = sched.acquire()                # lazy sweep requeues; B re-leases
    assert w_b is not None and w_b.key == w_a.key
    tok_b = sched.claim(w_b)
    assert tok_b is not None
    # A's dawdling payload lands / connection dies: both are no-ops now.
    assert sched.finish_claim(w_a, tok_a) is False
    sched.release_claim(w_a, tok_a)
    assert sched.acquire() is None       # B's claim still blocks granting
    assert sched.finish_claim(w_b, tok_b) is True
    assert sched.is_complete()


def test_grant_complete_cycle_scales_linearly():
    """Frontier-cursor scheduling must stay O(1) amortized per grant —
    the reference rescans the whole grid per request (O(total) each,
    Distributer.cs:335-353); a regression to that shape turns this
    10k-tile cycle quadratic and blows the time box."""
    import time

    sched = TileScheduler([LevelSetting(100, 16)])  # 10,000 tiles
    t0 = time.perf_counter()
    granted = 0
    while True:
        w = sched.acquire()
        if w is None:
            break
        token = sched.claim(w)
        assert token is not None
        assert sched.finish_claim(w, token)
        granted += 1
    dt = time.perf_counter() - t0
    assert granted == 10_000
    assert sched.is_complete()
    assert dt < 5.0, f"10k grant/complete cycles took {dt:.1f}s"


def test_is_complete_ignores_foreign_resume_keys():
    """Resume sets replay EVERY level ever persisted; keys outside the
    configured grid (other levels, out-of-range indices) must neither
    satisfy nor corrupt completion accounting."""
    foreign = {(7, 0, 0), (7, 6, 6), (2, 5, 5), (3, 0, 0), (3, 2, 1)}
    sched, _ = make(completed=foreign | {(2, 0, 0)})
    assert not sched.is_complete()  # 5 foreign keys != 4 grid tiles
    done = 1
    while (w := sched.acquire()) is not None:
        sched.complete(w)
        done += 1
    assert done == 4
    assert sched.is_complete()


def test_reopen_keeps_completion_count_consistent():
    sched, _ = make()
    grants = []
    while (w := sched.acquire()) is not None:
        grants.append(w)
        sched.complete(w)
    assert sched.is_complete()
    sched.reopen(grants[0])
    assert not sched.is_complete()
    w = sched.acquire()
    assert w.key == grants[0].key
    sched.complete(w)
    assert sched.is_complete()


def test_drain_at_level_512_scale_with_flat_grant_cost():
    """Round-5 verdict item 5: the O(1)-amortized-grant claim demonstrated
    at the scale the frontier design exists for — a level-512 grid
    (262,144 tiles), virtual clock, no sockets.  Per-grant cost over the
    last tenth of the drain must stay within a small factor of the first
    tenth (the reference's rescan shape degrades linearly with progress,
    which at this scale is a >100x first-vs-last spread), and
    is_complete() must be O(1) so a stats loop polling it cannot go
    quadratic late in huge runs."""
    import time

    level = 512
    total = level * level
    sched = TileScheduler([LevelSetting(level, 16)])
    tenth = total // 10
    seg_times = []
    granted = 0
    t0 = time.perf_counter()
    while True:
        batch = sched.acquire_batch(256)
        if not batch:
            break
        for w in batch:
            assert sched.complete(w)
        granted += len(batch)
        if granted % tenth < 256:  # segment boundary just crossed
            seg_times.append(time.perf_counter())
    assert granted == total
    assert sched.is_complete()
    first = seg_times[0] - t0
    last = seg_times[-1] - seg_times[-2]
    # Flat within noise: allow 4x for allocator/GC jitter; the rescan
    # shape would put this ratio in the hundreds.
    assert last < 4 * first + 0.05, (
        f"per-grant cost grew across the drain: first tenth {first:.3f}s, "
        f"last tenth {last:.3f}s")
    # is_complete is a counter comparison, not a grid rescan: polling it
    # 10k times on the full 262k grid must be effectively free.
    t0 = time.perf_counter()
    for _ in range(10_000):
        assert sched.is_complete()
    dt = time.perf_counter() - t0
    assert dt < 0.5, f"10k is_complete() polls took {dt:.2f}s (not O(1))"


def test_prioritize_moves_tile_to_front_of_grant_order():
    """Compute-on-read: a prioritized tile is granted before the frontier
    walk's natural next tile, and duplicates in the retry queue are
    harmless (re-checked at grant time)."""
    sched, _ = make()
    hot = Workload(2, 64, 1, 1)  # naturally last in the level-2 walk
    assert sched.prioritize(hot)
    assert sched.prioritize(hot)  # idempotent (dup entry skipped at grant)
    assert sched.acquire().key == hot.key
    assert sched.acquire().key == (2, 0, 0)  # frontier resumes normally


def test_prioritize_rejects_out_of_grid_and_completed():
    sched, _ = make()
    assert not sched.prioritize(Workload(9, 64, 0, 0))  # foreign level
    w = sched.acquire()
    assert sched.complete(w)
    assert not sched.prioritize(w)  # already done: read the store instead


def test_prioritize_inflight_tile_is_awaitable_not_requeued():
    """A tile under an unexpired lease is already being computed: the
    caller may await it, and no duplicate retry entry is planted that
    would re-grant it to a second worker."""
    sched, _ = make()
    w = sched.acquire()
    assert sched.prioritize(w)  # True: arrival is imminent
    remaining = {sched.acquire().key for _ in range(3)}
    assert w.key not in remaining  # not re-granted while leased
    assert sched.acquire() is None


def test_finish_claim_foreign_key_cannot_corrupt_remaining():
    """A key outside the configured grid must never decrement _remaining
    and fire is_complete() early (ADVICE round-5 finding).  White-box: a
    foreign lease cannot arise through acquire(), so inject one."""
    from distributedmandelbrot_tpu.coordinator.scheduler import Lease

    sched, clock = make(levels=((1, 64),))
    stray = Workload(7, 64, 3, 3)
    sched._leases[stray.key] = Lease(stray, clock.now() + 3600.0)
    token = sched.claim(stray)
    assert token is not None
    assert sched.finish_claim(stray, token)
    assert sched.completed_count == 0  # grid untouched
    assert not sched.is_complete()  # the single level-1 tile is still open
    w = sched.acquire()
    assert sched.complete(w)
    assert sched.is_complete()
