"""Randomized worker-churn stress: sustained crash/join cycles.

The single-crash redistribution path is covered in test_e2e; this drives
the failure-detection machinery (leases + lazy expiry + sweep + stale
rejection, survey §5.3) under *sustained* churn: several concurrent
workers that randomly abandon leased batches mid-round (the over-the-wire
shape of a worker crash — work leased, never submitted) and keep pulling.
The farm must still complete every tile exactly once on disk, with the
abandoned leases re-granted and any straggler submissions rejected, and
the persisted tiles must match the numpy golden.
"""

from __future__ import annotations

import random
import threading

import numpy as np
import pytest

from distributedmandelbrot_tpu import native as _native

pytestmark = pytest.mark.skipif(not _native.native_supported(),
                                reason="native toolchain unavailable")

from distributedmandelbrot_tpu.core import LevelSetting, TileSpec
from distributedmandelbrot_tpu.ops import reference as ref
from distributedmandelbrot_tpu.worker import (DistributerClient,
                                              NativeBackend, Worker)

from harness import CoordinatorHarness

LEVEL, MAX_ITER = 3, 16  # 9 full-size tiles, shallow budget


def test_randomized_worker_churn_completes_exactly(tmp_path):
    rng = random.Random(1234)
    with CoordinatorHarness(str(tmp_path), [LevelSetting(LEVEL, MAX_ITER)],
                            lease_timeout=1.5, sweep_period=0.3) as farm:
        stop = threading.Event()
        errors: list[BaseException] = []

        def churn_worker(seed: int) -> None:
            wrng = random.Random(seed)
            try:
                # Constructed INSIDE the try: concurrent first
                # construction is part of what this test exercises (it
                # caught the native build's first-use race), and any
                # failure must surface through `errors`, not vanish as
                # an unhandled thread exception.
                client = DistributerClient("127.0.0.1",
                                           farm.distributer_port)
                backend = NativeBackend()
                while not stop.is_set():
                    grants = client.request_batch(2)
                    if not grants:
                        if farm.scheduler.is_complete():
                            return
                        stop.wait(0.2)  # leases pending elsewhere
                        continue
                    if wrng.random() < 0.4:
                        # Simulated crash: abandon the leased batch.  The
                        # lease expires and the tiles are re-granted.
                        continue
                    pixels = backend.compute_batch(grants)
                    client.submit_batch(list(zip(grants, pixels)))
            except BaseException as e:  # surfaced by the main thread
                errors.append(e)

        threads = [threading.Thread(target=churn_worker, args=(100 + i,))
                   for i in range(3)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=600)
        stop.set()
        assert not any(t.is_alive() for t in threads), "worker thread hung"
        assert not errors, errors
        assert farm.scheduler.is_complete()
        farm.wait_saves_settled(expected_accepted=LEVEL * LEVEL, timeout=300)

        snap = farm.counters.snapshot()
        # Abandonment forces re-grants beyond the tile count (the first
        # abandon decision is deterministic under the seeded RNGs)...
        assert snap["workloads_granted"] > LEVEL * LEVEL, snap
        # ...but exactly one accepted result per tile reaches disk.
        assert snap["results_accepted"] == LEVEL * LEVEL, snap

        # Every persisted tile is golden (exactly-once, uncorrupted).
        i, j = rng.randrange(LEVEL), rng.randrange(LEVEL)
        chunk = farm.coordinator.store.load(LEVEL, i, j)
        spec = TileSpec.for_chunk(LEVEL, i, j)
        cr, ci = spec.grid_2d()
        want = ref.scale_counts_to_uint8(
            ref.escape_counts(cr, ci, MAX_ITER), MAX_ITER).ravel()
        got = np.asarray(chunk.data, np.uint8).ravel()
        np.testing.assert_array_equal(got, want)
