"""Real 2-process ``jax.distributed`` exercise of parallel/multihost.py.

Two subprocesses (2 virtual CPU devices each -> a 4-device global mesh)
initialize the distributed runtime against a shared coordinator port,
build :func:`global_tile_mesh`, compute a tiny tile batch through
:func:`batched_escape_pixels_multihost`, and each verifies its local
results against the numpy golden.  This is the CI-scale stand-in for a
multi-host TPU slice (BASELINE.md config 5's topology), same as the
virtual-device substitution the rest of the suite uses.
"""

from __future__ import annotations

import os
import socket
import subprocess
import sys

import pytest

_WORKER = r"""
import os, sys

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"

import jax

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)  # the f64 parity path below
try:  # drop any tunnel-blocking plugin, keep names known (see conftest.py)
    import jax._src.xla_bridge as _xb
    for _p in ("axon", "tpu"):
        _xb._backend_factories.pop(_p, None)
    for _p in ("axon", "tpu"):
        _xb._experimental_plugins.add(_p)
except Exception:
    pass

import numpy as np

from distributedmandelbrot_tpu.parallel import multihost
from distributedmandelbrot_tpu.core.geometry import TileSpec
from distributedmandelbrot_tpu.ops import reference as ref

port, pid, n_proc = sys.argv[1], int(sys.argv[2]), int(sys.argv[3])
multihost.initialize(coordinator_address="127.0.0.1:" + port,
                     num_processes=n_proc, process_id=pid)
assert jax.process_count() == n_proc, jax.process_count()
assert multihost.is_primary() == (pid == 0)

mesh = multihost.global_tile_mesh()
assert mesh.devices.size == 2 * n_proc, mesh.devices.size

# Process p contributes tiles (level, 64, i, p) for i in 0..1: global
# batch of 2*n_proc.  level = n_proc keeps j=pid a valid grid index at
# any rank count (per-rank shard coverage: every rank checks ITS tiles).
definition = 64
level, mrd = max(2, n_proc), 48
params = np.empty((2, 3))
specs = []
for i in range(2):
    spec = TileSpec.for_chunk(level, i, pid, definition=definition)
    specs.append(spec)
    params[i] = (spec.start_real, spec.start_imag,
                 spec.range_real / (definition - 1))
mrds = np.full(2, mrd, np.int64)

local = multihost.batched_escape_pixels_multihost(
    mesh, params, mrds, definition=definition, dtype=np.float64)
assert local.shape == (2, definition, definition), local.shape
assert local.dtype == np.uint8

# Pallas leg: the same batch through the sharded Pallas kernel across
# both processes (interpreter off-TPU), within the f32 statistical band
# of the XLA f32 result.  definition 128 = the kernel's lane granule.
pdef = 128
pparams = np.empty((2, 3))
for i in range(2):
    pspec = TileSpec.for_chunk(level, i, pid, definition=pdef)
    pparams[i] = (pspec.start_real, pspec.start_imag,
                  pspec.range_real / (pdef - 1))
pal = multihost.batched_escape_pixels_multihost(
    mesh, pparams, mrds, definition=pdef, dtype=np.float32,
    kernel="pallas", interpret=True)
xla32 = multihost.batched_escape_pixels_multihost(
    mesh, pparams, mrds, definition=pdef, dtype=np.float32)
mism = float((pal != xla32).mean())
assert mism <= 0.001, f"multihost pallas vs xla: {mism:.2%}"

for i, spec in enumerate(specs):
    # Device grids are start + k*step (not linspace), so compare against
    # the golden on the same grid: exact in f64 up to FMA contraction.
    step = spec.range_real / (definition - 1)
    cr = spec.start_real + np.arange(definition)[None, :] * step
    ci = spec.start_imag + np.arange(definition)[:, None] * step
    want = ref.scale_counts_to_uint8(
        ref.escape_counts(np.broadcast_to(cr, (definition, definition)),
                          np.broadcast_to(ci, (definition, definition)), mrd),
        mrd)
    mism = (local[i] != want).mean()
    assert mism <= 0.001, f"tile {i}: {mism:.2%} vs golden"

print(f"proc {pid} OK")
"""


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _run_ranks(tmp_path, source: str, n_proc: int, extra_args=(),
               timeout: float = 240, name: str = "mh_worker.py"
               ) -> list[str]:
    """Launch ``source`` as n_proc jax.distributed ranks; return outputs."""
    port = _free_port()
    script = tmp_path / name
    script.write_text(source)
    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)
    env.pop("XLA_FLAGS", None)
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    procs = [subprocess.Popen(
        [sys.executable, str(script), str(port), str(pid), str(n_proc),
         *map(str, extra_args)],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        text=True) for pid in range(n_proc)]
    outs = []
    for p in procs:
        try:
            out, _ = p.communicate(timeout=timeout)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            raise
        outs.append(out)
    for pid, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"proc {pid} failed:\n{out[-3000:]}"
    return outs


def test_two_process_distributed_mesh(tmp_path):
    outs = _run_ranks(tmp_path, _WORKER, 2)
    for pid, out in enumerate(outs):
        assert f"proc {pid} OK" in out


def test_four_process_distributed_mesh(tmp_path):
    """Round-5 verdict item 8: 4 ranks (8-device global mesh) catches the
    rank-arithmetic errors (shard offsets, process-order concatenation)
    that 2 ranks can mask — each rank verifies ITS local shard of the
    global batch against the numpy golden."""
    outs = _run_ranks(tmp_path, _WORKER, 4, timeout=420)
    for pid, out in enumerate(outs):
        assert f"proc {pid} OK" in out


_FARM_WORKER = r"""
import os, sys

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"

import jax

jax.config.update("jax_platforms", "cpu")
try:
    import jax._src.xla_bridge as _xb
    for _p in ("axon", "tpu"):
        _xb._backend_factories.pop(_p, None)
    for _p in ("axon", "tpu"):
        _xb._experimental_plugins.add(_p)
except Exception:
    pass

from distributedmandelbrot_tpu.parallel import multihost

mh_port, pid, n_proc, farm_port = (sys.argv[1], int(sys.argv[2]),
                                   int(sys.argv[3]), int(sys.argv[4]))
multihost.initialize(coordinator_address="127.0.0.1:" + mh_port,
                     num_processes=n_proc, process_id=pid)
rounds = multihost.run_spmd_worker("127.0.0.1", farm_port)
print(f"proc {pid} farm OK rounds={rounds}")
"""


def _spmd_farm(tmp_path, n_proc: int, expected_rounds: int,
               check_all_tiles: bool) -> None:
    """Drain a level-3 grid (9 tiles) through run_spmd_worker on n_proc
    jax.distributed ranks (2 virtual devices each) against a real
    coordinator on loopback, then verify persisted tiles vs the golden."""
    import numpy as np

    from distributedmandelbrot_tpu.coordinator import EmbeddedCoordinator
    from distributedmandelbrot_tpu.core.geometry import TileSpec
    from distributedmandelbrot_tpu.core.workload import LevelSetting
    from distributedmandelbrot_tpu.ops import reference as ref

    with EmbeddedCoordinator(str(tmp_path), [LevelSetting(3, 12)]) as co:
        outs = _run_ranks(tmp_path, _FARM_WORKER, n_proc,
                          extra_args=(co.distributer_port,), timeout=900,
                          name="mh_farm_worker.py")
        for pid, out in enumerate(outs):
            assert f"proc {pid} farm OK rounds={expected_rounds}" in out, \
                out[-2000:]
        co.wait_saves_settled(expected_accepted=9, timeout=600)
        assert co.scheduler.is_complete()
        # Verify persisted tiles against the golden: checking EVERY tile
        # covers every rank's shard of every round (tiles are distributed
        # across ranks in process order), so a rank-offset error anywhere
        # shows up as a wrong tile here.
        tiles = [(1, 0)] if not check_all_tiles else \
            [(i, j) for i in range(3) for j in range(3)]
        for i, j in tiles:
            chunk = co.coordinator.store.load(3, i, j)
            spec = TileSpec.for_chunk(3, i, j)
            cr, ci = spec.grid_2d()
            want = ref.scale_counts_to_uint8(
                ref.escape_counts(cr, ci, 12), 12).ravel()
            got = np.asarray(chunk.data, np.uint8).ravel()
            mism = float((got != want).mean())
            assert mism <= 5e-4, f"tile ({i},{j}): {mism:.2%} vs golden"


def test_two_process_spmd_farm(tmp_path):
    """The slice-spanning SPMD worker end-to-end: a real coordinator on
    loopback, two jax.distributed processes (2 virtual devices each)
    running run_spmd_worker — the primary leases and uploads, both
    compute — and the persisted tiles match the numpy golden.

    Level 3 (9 tiles) against a 4-row batch forces THREE rounds with a
    ragged final round (1 grant + 3 trivial pad rows), covering the
    broadcast pad path and pad exclusion from upload."""
    _spmd_farm(tmp_path, 2, expected_rounds=3, check_all_tiles=False)


def test_four_process_spmd_farm(tmp_path):
    """Round-5 verdict item 8 (farm leg): 4 ranks, 8-device global mesh,
    k_global=8 — TWO rounds with a ragged final round (1 grant + 7 pads).
    Every persisted tile is checked against the golden, which asserts
    per-rank shard coverage: round 1 spreads tiles (0,0)..(2,1) across
    all four ranks' shards, so any rank computing the wrong window
    corrupts a specific tile."""
    _spmd_farm(tmp_path, 4, expected_rounds=2, check_all_tiles=True)
