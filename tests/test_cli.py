"""CLI surface tests: the render paths (integer / smooth / julia / deep)
and argument plumbing that e2e farm tests don't touch."""

import os

import numpy as np
import pytest

from distributedmandelbrot_tpu import cli


def _png_size(path):
    import struct
    with open(path, "rb") as f:
        data = f.read(24)
    assert data[:8] == b"\x89PNG\r\n\x1a\n"
    w, h = struct.unpack(">II", data[16:24])
    return w, h


def test_render_integer_counts(tmp_path):
    out = tmp_path / "m.png"
    rc = cli.main(["render", "--definition", "64", "--max-iter", "64",
                   "--out", str(out)])
    assert rc == 0
    assert _png_size(out) == (64, 64)


def test_render_julia_negative_constant(tmp_path):
    out = tmp_path / "j.png"
    rc = cli.main(["render", "--fractal", "julia", "--c", "-0.8,0.156",
                   "--definition", "64", "--max-iter", "64",
                   "--out", str(out)])
    assert rc == 0
    assert _png_size(out) == (64, 64)


def test_render_smooth(tmp_path):
    out = tmp_path / "s.png"
    rc = cli.main(["render", "--smooth", "--definition", "64",
                   "--max-iter", "64", "--span", "0.01",
                   "--center", "-0.748,0.09", "--out", str(out)])
    assert rc == 0
    assert _png_size(out) == (64, 64)


def test_render_deep_flag_and_auto_switch(tmp_path):
    out = tmp_path / "d.png"
    rc = cli.main(["render", "--deep", "--definition", "64",
                   "--max-iter", "300", "--span", "1e-6",
                   "--center", "-0.74529,0.11307", "--out", str(out)])
    assert rc == 0
    assert _png_size(out) == (64, 64)
    # span below 1e-12 auto-selects the deep path (would be a blank or
    # aliased tile on the direct f64 path at definition 64)
    out2 = tmp_path / "d2.png"
    rc = cli.main(["render", "--definition", "64", "--max-iter", "300",
                   "--span", "1e-14",
                   "--center", "-0.77568377,0.13646737", "--out", str(out2)])
    assert rc == 0


def test_render_deep_julia(tmp_path):
    """Deep Julia zoom via perturbation (center = a z-plane location near
    the Julia set of c; renders rather than erroring)."""
    out = tmp_path / "dj.png"
    rc = cli.main(["render", "--deep", "--fractal", "julia",
                   "--c", "-0.8,0.156",
                   "--center", "1.5275031186,-0.0759121783",
                   "--span", "1e-6", "--definition", "64",
                   "--max-iter", "300", "--out", str(out)])
    assert rc == 0
    assert _png_size(out) == (64, 64)


def test_render_bla_guard_follows_routing(tmp_path):
    """--bla applicability gates on the ACTUAL routing decision, not the
    raw span threshold (round-3 advisor): a span above the f64 deep
    threshold that _auto_deep still routes to f32 perturbation (pitch
    below f32 resolution) legitimately accepts --bla; a genuinely
    shallow direct-kernel view still rejects it loudly."""
    out = tmp_path / "bla.png"
    # span 1e-8 at 64^2 near |c|~0.75: pitch ~1.6e-10 << f32 ulp ~9e-8,
    # so the f32 render auto-routes to perturbation — --bla applies.
    rc = cli.main(["render", "--bla", "--dtype", "f32",
                   "--span", "1e-8", "--definition", "64",
                   "--max-iter", "128",
                   "--center", "-0.74529,0.11307", "--out", str(out)])
    assert rc == 0
    assert _png_size(out) == (64, 64)
    with pytest.raises(SystemExit, match="direct kernels"):
        cli.main(["render", "--bla", "--span", "0.01", "--definition",
                  "64", "--max-iter", "64",
                  "--center", "-0.748,0.09", "--out", str(out)])


def test_render_bla_tristate(tmp_path):
    """--bla/--no-bla are mutually exclusive; --no-bla forces the exact
    scan on a deep render (tri-state plumbing to the perturbation
    layer — the bla=None auto-probe default is covered in
    test_perturbation.test_auto_bla_probe_decisions)."""
    out = tmp_path / "nb.png"
    with pytest.raises(SystemExit, match="mutually exclusive"):
        cli.main(["render", "--bla", "--no-bla", "--deep",
                  "--span", "1e-13", "--definition", "64",
                  "--max-iter", "64", "--out", str(out)])
    rc = cli.main(["render", "--no-bla", "--deep", "--span", "1e-13",
                   "--definition", "64", "--max-iter", "128",
                   "--center", "-0.74529,0.11307", "--out", str(out)])
    assert rc == 0
    assert _png_size(out) == (64, 64)


def test_viewer_prompt_mode(tmp_path, monkeypatch):
    """`dmtpu viewer` with no arguments prompts for server and chunk
    indices with the reference viewer's exact prompt strings
    (DistributedMandelbrotViewer.py:147-152), then fetches and renders
    like the flag-driven path."""
    from distributedmandelbrot_tpu.coordinator import EmbeddedCoordinator
    from distributedmandelbrot_tpu.core.workload import parse_level_settings
    from distributedmandelbrot_tpu.worker import (DistributerClient,
                                                  NumpyBackend, Worker)

    with EmbeddedCoordinator(str(tmp_path),
                             parse_level_settings("1:12")) as co:
        worker = Worker(DistributerClient("127.0.0.1", co.distributer_port),
                        NumpyBackend())
        worker.run_until_drained()
        co.wait_saves_settled(expected_accepted=1)

        prompts = []
        answers = iter(["127.0.0.1", str(co.dataserver_port),
                        "1", "0", "0"])

        def fake_input(prompt):
            prompts.append(prompt)
            return next(answers)

        monkeypatch.setattr("builtins.input", fake_input)
        out = tmp_path / "prompted.png"
        rc = cli.main(["viewer", "--out", str(out)])
        assert rc == 0
        assert prompts == ["Server Addr> ", "Server Port> ", "Level> ",
                           "Index Re> ", "Index Im> "]
        assert _png_size(out) == (4096, 4096)
    # --stitch without a level is flag-driven and must reject loudly,
    # not fall into prompt mode; closed stdin exits with a usage error,
    # not an EOFError traceback.
    with pytest.raises(SystemExit):
        cli.main(["viewer", "--stitch"])
    def eof_input(prompt):
        raise EOFError
    monkeypatch.setattr("builtins.input", eof_input)
    with pytest.raises(SystemExit):
        cli.main(["viewer"])


def test_trace_command_empty_farm(tmp_path, capsys):
    """`dmtpu trace` against a coordinator with no workers dumps an
    empty-but-valid Chrome trace (coordinator metadata only) and exits
    0 — both to a file and to stdout."""
    import json

    from distributedmandelbrot_tpu.coordinator import EmbeddedCoordinator
    from distributedmandelbrot_tpu.core.workload import parse_level_settings

    out = tmp_path / "trace.json"
    with EmbeddedCoordinator(str(tmp_path / "data"),
                             parse_level_settings("1:12")) as co:
        rc = cli.main(["trace", "--port", str(co.exporter_port),
                       "--out", str(out)])
        assert rc == 0
        assert "wrote" in capsys.readouterr().out
        rc = cli.main(["trace", "--port", str(co.exporter_port)])
        assert rc == 0
        stdout_doc = json.loads(capsys.readouterr().out)
    doc = json.loads(out.read_text())
    assert doc == stdout_doc
    assert isinstance(doc["traceEvents"], list)
    # No workers ran: only metadata rows, every one well-formed.
    assert doc["traceEvents"], "coordinator metadata rows expected"
    for ev in doc["traceEvents"]:
        assert ev["ph"] == "M"
        assert isinstance(ev["pid"], int) and isinstance(ev["tid"], int)
    # A dead port is a loud SystemExit, not a traceback.
    with pytest.raises(SystemExit, match="cannot fetch"):
        cli.main(["trace", "--port", "1", "--timeout", "0.5"])


def test_worker_backend_validation():
    with pytest.raises(SystemExit):
        cli.main(["worker", "--backend", "pallas", "--dtype", "f64"])


def test_parse_level_settings_roundtrip():
    from distributedmandelbrot_tpu.core.workload import parse_level_settings
    s = parse_level_settings("4:256,10:1024")
    assert [(x.level, x.max_iter) for x in s] == [(4, 256), (10, 1024)]


def test_render_deep_smooth(tmp_path):
    out = tmp_path / "ds.png"
    rc = cli.main(["render", "--deep", "--smooth", "--definition", "64",
                   "--max-iter", "400", "--span", "1e-6",
                   "--center", "-0.74529,0.11307", "--out", str(out)])
    assert rc == 0
    assert _png_size(out) == (64, 64)


def test_animate_spans_shallow_and_deep(tmp_path):
    """A 3-frame sweep crossing the deep threshold renders every frame
    (direct kernel for shallow frames, perturbation below 1e-12)."""
    rc = cli.main(["animate", "--center", "-0.77568377,0.13646737",
                   "--span-start", "1e-4", "--span-end", "1e-13",
                   "--frames", "3", "--definition", "48",
                   "--max-iter", "200", "--out-dir", str(tmp_path)])
    assert rc == 0
    frames = sorted(p.name for p in tmp_path.iterdir())
    assert frames == ["frame_0000.png", "frame_0001.png", "frame_0002.png"]
    assert _png_size(tmp_path / "frame_0002.png") == (48, 48)


def test_render_no_pallas_flag(tmp_path):
    """--no-pallas forces the XLA/host-grid path (grid-convention escape
    hatch documented in the render help; on the CPU config both paths
    already agree, so this exercises the flag plumbing)."""
    out = tmp_path / "np.png"
    rc = cli.main(["render", "--definition", "64", "--max-iter", "64",
                   "--span", "3.0", "--no-pallas", "--out", str(out)])
    assert rc == 0 and out.exists()


def test_dtype_auto_upgrades_below_f32_resolution():
    """Spans whose pixel pitch aliases in f32 (between the perturbation
    threshold and ~1e-4 near |c|=1) default to the f64 quality path —
    the reference's CUDA kernel is always f64, so an f32 default there
    would produce banded renders the reference never shows."""
    import argparse

    import numpy as np

    from distributedmandelbrot_tpu.cli import _resolve_dtype

    def ns(**kw):
        kw.setdefault("smooth", False)
        return argparse.Namespace(dtype=None, deep=False, **kw)

    # Shallow span: f32 fast path as before.
    assert _resolve_dtype(ns(span=0.01, definition=1024),
                          center=(-0.75, 0.1)) == np.float32
    # Sub-resolution span near |c|~0.75, no perturbation path (families):
    # silently upgrade to f64.
    assert _resolve_dtype(ns(span=1e-5, definition=1024),
                          center=(-0.74529, 0.11307)) == np.float64
    # With a perturbation path (Mandelbrot/Julia) the default stays f32 —
    # the render routes through f32 delta orbits instead.
    assert _resolve_dtype(ns(span=1e-5, definition=1024),
                          center=(-0.74529, 0.11307),
                          can_perturb=True) == np.float32
    # Explicit --dtype always wins.
    n = ns(span=1e-5, definition=1024)
    n.dtype = "f32"
    assert _resolve_dtype(n, center=(-0.74529, 0.11307)) == np.float32
    # At center 0 (Julia default) f32 precision scales with the span:
    # no upgrade needed.
    assert _resolve_dtype(ns(span=1e-5, definition=1024),
                          center=(0.0, 0.0)) == np.float32
    # Perturbation territory stays f32 (deltas are the designed path).
    assert _resolve_dtype(ns(span=1e-13, definition=1024),
                          center=(-0.75, 0.1)) == np.float32
    # Smooth keeps its f64 quality promise even when sub-resolution and
    # perturbation-capable: f64 resolves every span above the threshold.
    assert _resolve_dtype(ns(span=1e-5, definition=1024, smooth=True),
                          center=(-0.74529, 0.11307),
                          can_perturb=True) == np.float64


def test_render_normalize_flag(tmp_path):
    """--normalize stretches a deep window's sliver of the absolute
    scale over the full colormap; rejected without --smooth."""
    import numpy as np

    from distributedmandelbrot_tpu.viewer import smooth_to_rgba

    # Narrow band of values: absolute scaling is near-flat, normalized
    # spans the map.
    nu = np.linspace(300.0, 567.0, 64 * 64).reshape(64, 64)
    nu[0, 0] = 0.0  # one in-set pixel stays black either way
    flat = smooth_to_rgba(nu, 50_000)
    stretched = smooth_to_rgba(nu, 50_000, normalize=True)
    def n_colors(img):
        return len(np.unique(img.reshape(-1, img.shape[-1]), axis=0))
    assert n_colors(stretched) > 4 * n_colors(flat)
    assert (stretched[0, 0] == flat[0, 0]).all()  # in-set convention kept

    out = tmp_path / "n.png"
    rc = cli.main(["render", "--smooth", "--normalize", "--definition",
                   "48", "--max-iter", "64", "--span", "3.0",
                   "--out", str(out)])
    assert rc == 0 and out.exists()
    with pytest.raises(SystemExit, match="--smooth renders only"):
        cli.main(["render", "--normalize", "--definition", "48",
                  "--out", str(tmp_path / "x.png")])


def test_animate_gif_assembly(tmp_path):
    """--gif assembles the rendered frames into an animated GIF."""
    from PIL import Image

    out_dir = tmp_path / "frames"
    gif = tmp_path / "zoom.gif"
    rc = cli.main(["animate", "--center=-0.745,0.11", "--span-start", "2.0",
                   "--span-end", "0.5", "--frames", "3", "--definition",
                   "48", "--max-iter", "32", "--out-dir", str(out_dir),
                   "--gif", str(gif), "-q"])
    assert rc == 0 and gif.exists()
    with Image.open(gif) as img:
        assert getattr(img, "n_frames", 1) == 3


def test_render_deep_all_inset_warns(tmp_path, caplog):
    """A deep render whose every pixel exhausts the budget (value 0)
    must warn that the flat output means an under-budgeted zoom —
    escape depths grow with depth (seahorse Misiurewicz: min escape
    ~3250 at span 1e-10), so a shallow frame's budget silently flattens
    a few octaves deeper."""
    import logging

    out = tmp_path / "flat.png"
    with caplog.at_level(logging.WARNING, logger="dmtpu.cli"):
        rc = cli.main(["render", "--deep", "--definition", "32",
                       "--max-iter", "300", "--span", "1e-14",
                       "--center",
                       "-0.743643887037158704752191506114774,"
                       "0.131825904205311970493132056385139",
                       "--out", str(out)])
    assert rc == 0
    assert any("no pixel escaped" in r.message for r in caplog.records)
    # An adequately budgeted shallow deep-render must NOT warn.
    caplog.clear()
    out2 = tmp_path / "ok.png"
    with caplog.at_level(logging.WARNING, logger="dmtpu.cli"):
        rc = cli.main(["render", "--deep", "--definition", "32",
                       "--max-iter", "300", "--span", "1e-6",
                       "--center", "-0.74529,0.11307", "--out", str(out2)])
    assert rc == 0
    assert not any("no pixel escaped" in r.message
                   for r in caplog.records)


def test_animate_max_iter_end_interpolates(tmp_path, capsys):
    """--max-iter-end sweeps the budget geometrically alongside the
    span: shallow frames stop overpaying for the deep frames' needs."""
    rc = cli.main(["animate", "--center", "-0.74529,0.11307",
                   "--span-start", "1e-2", "--span-end", "1e-4",
                   "--frames", "3", "--definition", "32",
                   "--max-iter", "100", "--max-iter-end", "400",
                   "--out-dir", str(tmp_path)])
    assert rc == 0
    out = capsys.readouterr().out
    assert "mi 100" in out and "mi 200" in out and "mi 400" in out
    for f in range(3):
        assert (tmp_path / f"frame_{f:04d}.png").exists()


def test_render_supersample(tmp_path):
    """--supersample renders N mean-zero subpixel samples and averages in
    color space: same geometry, anti-aliased values — the image differs
    from the plain render on a boundary view but agrees on the vast
    majority of pixels (only boundary pixels blend)."""
    import numpy as np
    from PIL import Image

    plain = tmp_path / "plain.png"
    ss = tmp_path / "ss.png"
    view = ["--center=-0.7436,0.1318", "--span", "0.002",
            "--definition", "64", "--max-iter", "100"]
    assert cli.main(["render", *view, "--out", str(plain)]) == 0
    assert cli.main(["render", *view, "--supersample", "4",
                     "--out", str(ss)]) == 0
    a = np.asarray(Image.open(plain), float)
    b = np.asarray(Image.open(ss), float)
    assert a.shape == b.shape
    diff = (a != b).any(axis=-1)
    assert 0 < diff.mean() < 1.0  # blending happened, geometry unchanged


def test_render_supersample_packed_matches_sequential(monkeypatch):
    """The packed-kernel fast path (one interleaved pass for all
    samples) must produce exactly the sequential per-sample output.
    pallas_available is forced so the packed branch runs in interpret
    mode on the CPU config.  Definition 128 — the kernel's lane floor —
    so the packed call genuinely SUCCEEDS (at 64 it would decline with
    PallasUnsupported and the comparison would be sequential-vs-itself);
    the spy asserts on the successful return, not just the invocation."""
    import numpy as np

    from distributedmandelbrot_tpu.ops import pallas_escape as pe

    kw = dict(smooth=False, np_dtype=np.float32, colormap="jet",
              deep=None, julia_c=None, family=None, no_pallas=False,
              normalize=False)
    args = ("-0.7436", "0.1318", 2e-3, 128, 100)

    # Both runs must use the PALLAS grid convention (start + i*step in
    # f32): the XLA fallback's host-linspace grid differs at the last
    # ulp on chaotic boundary pixels, which is the documented
    # --no-pallas distinction, not a packing bug.  pallas_available is
    # monkeypatched True, so interpret=True is forced everywhere (the
    # auto-select would pick compiled mode on the CPU backend).
    monkeypatch.setattr(pe, "pallas_available", lambda: True)
    real_single = pe.compute_tile_pallas
    monkeypatch.setattr(
        pe, "compute_tile_pallas",
        lambda *a, **k: real_single(*a, **{**k, "interpret": True}))
    real_packed = pe.compute_tiles_packed_pallas

    def declined(*a, **k):
        raise pe.PallasUnsupported("forced sequential for the test")

    monkeypatch.setattr(pe, "compute_tiles_packed_pallas", declined)
    seq = cli._render_view(*args, **kw, supersample=2)

    returned = {"planes": None}

    def spy(*a, **k):
        returned["planes"] = real_packed(*a, **{**k, "interpret": True})
        return returned["planes"]

    monkeypatch.setattr(pe, "compute_tiles_packed_pallas", spy)
    packed = cli._render_view(*args, **kw, supersample=2)
    assert returned["planes"] is not None and len(returned["planes"]) == 2, \
        "packed fast path did not engage (or declined the shape)"
    assert np.array_equal(np.asarray(seq), np.asarray(packed))


def test_animate_supersample(tmp_path):
    """animate --supersample threads through to every frame (the flag's
    contract is shared with render via _render_view)."""
    import numpy as np
    from PIL import Image

    out_dir = tmp_path / "frames"
    rc = cli.main(["animate", "--center=-0.7436,0.1318",
                   "--span-start", "0.01", "--span-end", "0.008",
                   "--frames", "2", "--definition", "64",
                   "--max-iter", "64", "--supersample", "2",
                   "--out-dir", str(out_dir)])
    assert rc == 0
    plain_dir = tmp_path / "plain"
    rc = cli.main(["animate", "--center=-0.7436,0.1318",
                   "--span-start", "0.01", "--span-end", "0.008",
                   "--frames", "2", "--definition", "64",
                   "--max-iter", "64", "--out-dir", str(plain_dir)])
    assert rc == 0
    a = np.asarray(Image.open(out_dir / "frame_0000.png"), float)
    b = np.asarray(Image.open(plain_dir / "frame_0000.png"), float)
    assert a.shape == b.shape
    assert (a != b).any()  # the samples blended


def test_render_supersample_deep(tmp_path):
    """Supersampling composes with the perturbation deep path: subpixel
    centers shift via Decimal (full precision preserved), each sample
    rendering through compute_counts_perturb."""
    out = tmp_path / "ssd.png"
    rc = cli.main(["render", "--deep", "--supersample", "2",
                   "--center", "-0.74529,0.11307", "--span", "1e-6",
                   "--definition", "48", "--max-iter", "300",
                   "--out", str(out)])
    assert rc == 0
    assert _png_size(out) == (48, 48)


def test_enable_compile_cache_env_and_knob(tmp_path, monkeypatch):
    """The default-on persistent XLA compilation cache (round 5): the
    CLI points JAX_COMPILATION_CACHE_DIR at a writable default (or the
    DMTPU_COMPILE_CACHE override), pushes the flags through
    jax.config.update when a site hook imported jax before main(), and
    DMTPU_COMPILE_CACHE=0 / a pre-set env disable it entirely."""
    import sys

    calls = {}

    class _Cfg:
        @staticmethod
        def update(k, v):
            calls[k] = v

    class _FakeJax:
        config = _Cfg()

    cache_dir = tmp_path / "xc"
    monkeypatch.setenv("DMTPU_COMPILE_CACHE", str(cache_dir))
    # setenv-then-delenv so monkeypatch RECORDS prior absence and the
    # teardown restores it even though _enable_compile_cache mutates
    # os.environ directly (a bare delenv(raising=False) on an absent
    # var records nothing, leaking the test's values into the session).
    for var in ("JAX_COMPILATION_CACHE_DIR",
                "JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS"):
        monkeypatch.setenv(var, "sentinel")
        monkeypatch.delenv(var)
    monkeypatch.setitem(sys.modules, "jax", _FakeJax())
    cli._enable_compile_cache()
    assert os.environ["JAX_COMPILATION_CACHE_DIR"] == str(cache_dir)
    assert cache_dir.is_dir()
    assert calls["jax_compilation_cache_dir"] == str(cache_dir)
    assert calls["jax_persistent_cache_min_compile_time_secs"] == 0.1

    # The explicit DMTPU knob outranks an inherited ambient setting (the
    # more specific instruction must not be silently ignored)...
    calls.clear()
    monkeypatch.setenv("JAX_COMPILATION_CACHE_DIR", "/operator/choice")
    cli._enable_compile_cache()
    assert os.environ["JAX_COMPILATION_CACHE_DIR"] == str(cache_dir)
    assert calls["jax_compilation_cache_dir"] == str(cache_dir)

    # ...but with no DMTPU knob, ambient configuration wins untouched.
    calls.clear()
    monkeypatch.delenv("DMTPU_COMPILE_CACHE")
    monkeypatch.setenv("JAX_COMPILATION_CACHE_DIR", "/operator/choice")
    cli._enable_compile_cache()
    assert not calls
    assert os.environ["JAX_COMPILATION_CACHE_DIR"] == "/operator/choice"

    # Opt-out.
    calls.clear()
    monkeypatch.delenv("JAX_COMPILATION_CACHE_DIR")
    monkeypatch.setenv("DMTPU_COMPILE_CACHE", "0")
    cli._enable_compile_cache()
    assert not calls
    assert "JAX_COMPILATION_CACHE_DIR" not in os.environ


def test_check_fsm_dump_roundtrip(tmp_path, capsys):
    dot = tmp_path / "fsm.dot"
    assert cli.main(["check", "--fsm-dump", str(dot)]) == 0
    out = capsys.readouterr().out
    assert "wrote 4 exchange automaton pair(s)" in out
    text = dot.read_text(encoding="utf-8")
    assert text.startswith("digraph fsm {")
    assert text.rstrip().endswith("}")
    # two endpoint clusters per exchange pair
    assert text.count("subgraph") == 8
    for exchange in ("session", "query", "render_query", "session_query"):
        assert exchange in text
    # send/recv edge labels carry the !/? convention
    assert "!" in text and "?" in text


def test_check_profile_prints_per_family_timings(capsys):
    assert cli.main(["check", "--profile"]) == 0
    captured = capsys.readouterr()
    # timings go to stderr so --json output stays machine-parseable
    assert "rules_fsm" in captured.err
    assert "total" in captured.err
    assert "rules_fsm" not in captured.out
