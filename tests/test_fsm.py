"""Automaton extraction + product exploration (``fsm-*``, v4).

Fixture pairs go through ``Project.from_sources`` with the real
endpoint qualnames (the builders key on them), so the extractor lifts
exactly the code under test; the real-tree cases then pin the
properties the ISSUE's acceptance criteria name — every capability
product explored clean, the one audited dead arm, and crash-seam
coverage.  No disk fixtures, no jax.
"""

from __future__ import annotations

import pytest

from distributedmandelbrot_tpu.analysis import Project, check_project
from distributedmandelbrot_tpu.analysis import engine, explore, fsm

P = "distributedmandelbrot_tpu"

CLIENT_REL = f"{P}/viewer/client.py"
SERVER_REL = f"{P}/coordinator/dataserver.py"

QUERY_CLIENT = f'''
from distributedmandelbrot_tpu.net import framing
from distributedmandelbrot_tpu.net import protocol as proto


class DataClient:
    def _fetch_once(self, sock, level, ir, ii):
        framing.send_all(sock, proto.QUERY.pack(level, ir, ii))
        status = framing.recv_byte(sock)
        if status == proto.QUERY_REJECT:
            return None
        if status != proto.QUERY_ACCEPT:
            raise framing.ProtocolError("bad status")
        return b"tile"
'''

QUERY_SERVER = f'''
from distributedmandelbrot_tpu.net import framing
from distributedmandelbrot_tpu.net import protocol as proto


class DataServer:
    def _handle_connection(self, conn):
        level, ir, ii = proto.QUERY.unpack(
            framing.recv_exact(conn, proto.QUERY.size))
        if self._have(level, ir, ii):
            framing.send_byte(conn, proto.QUERY_ACCEPT)
        else:
            framing.send_byte(conn, proto.QUERY_REJECT)

    def _have(self, level, ir, ii):
        return True
'''

# Reads the query struct TWICE for the client's single send: the
# product must wedge with the client waiting on the status byte and
# the server waiting on the second struct.
QUERY_SERVER_DESYNCED = f'''
from distributedmandelbrot_tpu.net import framing
from distributedmandelbrot_tpu.net import protocol as proto


class DataServer:
    def _handle_connection(self, conn):
        first = proto.QUERY.unpack(
            framing.recv_exact(conn, proto.QUERY.size))
        second = proto.QUERY.unpack(
            framing.recv_exact(conn, proto.QUERY.size))
        framing.send_byte(conn, proto.QUERY_ACCEPT)
'''

QUERY_SERVER_LOOP = f'''
from distributedmandelbrot_tpu.net import framing
from distributedmandelbrot_tpu.net import protocol as proto


class DataServer:
    def _handle_connection(self, conn):
        while True:
            try:
                level, ir, ii = proto.QUERY.unpack(
                    framing.recv_exact(conn, proto.QUERY.size))
            except ConnectionError:
                return
            framing.send_byte(conn, proto.QUERY_ACCEPT)
'''

# An unbounded sender: termination must come from the exploration's
# queue bound, not from the fixture being well-behaved.
QUERY_SERVER_FLOOD = f'''
from distributedmandelbrot_tpu.net import framing
from distributedmandelbrot_tpu.net import protocol as proto


class DataServer:
    def _handle_connection(self, conn):
        level, ir, ii = proto.QUERY.unpack(
            framing.recv_exact(conn, proto.QUERY.size))
        while True:
            framing.send_byte(conn, proto.QUERY_ACCEPT)
'''


def query_pair(server_src: str, client_src: str = QUERY_CLIENT):
    project = Project.from_sources({CLIENT_REL: client_src,
                                    SERVER_REL: server_src})
    pairs = fsm.build_pairs(project)
    assert len(pairs) == 1 and pairs[0].kind == "query"
    return project, pairs[0]


# -- extraction -------------------------------------------------------------

def test_branch_extraction_query_pair():
    _, pair = query_pair(QUERY_SERVER)
    csends = {e.label for e in pair.client.edges if e.kind == "send"}
    crecvs = {e.label for e in pair.client.edges if e.kind == "recv"}
    assert "QUERY" in csends
    # both status branches became receive arms
    assert {"QUERY_ACCEPT", "QUERY_REJECT"} <= crecvs
    srecvs = {e.label for e in pair.server.edges if e.kind == "recv"}
    ssends = {e.label for e in pair.server.edges if e.kind == "send"}
    assert "QUERY" in srecvs
    assert {"QUERY_ACCEPT", "QUERY_REJECT"} <= ssends


def test_loop_extraction_gets_eos_fault_arm():
    _, pair = query_pair(QUERY_SERVER_LOOP)
    # the recv inside try/except ConnectionError grew the fault arm
    # that lets the loop observe the client hanging up
    assert any(e.kind == "recv" and e.label == "EOS" and e.fault
               for e in pair.server.edges)
    rep = explore.explore_pair(pair)
    assert not rep.violations
    for cfg in rep.configs:
        assert cfg.complete and cfg.terminal_reached


def test_clean_pair_explores_clean():
    _, pair = query_pair(QUERY_SERVER)
    rep = explore.explore_pair(pair)
    assert not rep.violations
    assert rep.visited_caps == {frozenset(), frozenset({"SHARDED"})}
    for cfg in rep.configs:
        assert cfg.complete and cfg.terminal_reached
        assert cfg.n_states < 200  # tiny exchange, tiny product


def test_exploration_terminates_on_unbounded_sender():
    _, pair = query_pair(QUERY_SERVER_FLOOD)
    rep = explore.explore_pair(pair)  # returning at all IS the point
    for cfg in rep.configs:
        assert cfg.complete
        assert cfg.truncations > 0  # the queue bound did the cutting


# -- the rules on fixture trees ---------------------------------------------

def fsm_findings(sources: dict, rule: str) -> list:
    return [f for f in check_project(Project.from_sources(sources),
                                     ["fsm"])
            if f.rule == rule]


def test_desynced_pair_reports_deadlock_with_both_states():
    findings = fsm_findings({CLIENT_REL: QUERY_CLIENT,
                             SERVER_REL: QUERY_SERVER_DESYNCED},
                            "fsm-deadlock")
    assert findings, "desynced fixture must deadlock"
    msg = findings[0].message
    # the finding names the stuck client/server state pair
    assert "client@" in msg and "server@" in msg
    assert "wait forever" in msg


def test_clean_pair_has_no_fsm_findings():
    project = Project.from_sources({CLIENT_REL: QUERY_CLIENT,
                                    SERVER_REL: QUERY_SERVER})
    assert [f for f in check_project(project, ["fsm"])] == []


def test_fixture_without_endpoints_is_skipped():
    project = Project.from_sources(
        {f"{P}/serve/other.py": "class X:\n    def f(self):\n        pass\n"})
    assert fsm.build_pairs(project) == []
    assert check_project(project, ["fsm"]) == []


# -- real tree --------------------------------------------------------------

@pytest.fixture(scope="module")
def real_report():
    project = engine.Project.from_root(engine.default_root())
    pairs = fsm.build_pairs(project)
    return project, pairs, explore.explore_all(pairs)


def test_real_tree_extracts_all_exchanges(real_report):
    _, pairs, _ = real_report
    assert {p.name for p in pairs} == {
        "session", "query", "render_query", "session_query"}


def test_real_tree_visits_legacy_and_fully_negotiated(real_report):
    _, pairs, rep = real_report
    session = next(p for p in rep.pairs if p.pair.name == "session")
    visited = session.visited_caps
    assert frozenset() in visited                      # legacy product
    assert frozenset({"RLE", "GRANTN", "SHARD",       # fully negotiated
                      "SHARDED"}) in visited
    assert len(visited) == 12
    for cfg in session.configs:
        assert cfg.complete and cfg.terminal_reached
        assert cfg.truncations == 0


def test_real_tree_has_no_violations(real_report):
    _, _, rep = real_report
    assert rep.violations == []


def test_real_tree_session_has_cap_guarded_edges(real_report):
    _, pairs, _ = real_report
    session = next(p for p in pairs if p.name == "session")
    guards = {atom for auto in (session.client, session.server)
              for e in auto.edges for atom in e.pos}
    assert {"RLE", "GRANTN", "SHARD"} <= guards


def test_real_tree_only_audited_dead_arm(real_report):
    _, _, rep = real_report
    dead = rep.dead_arms()
    assert len(dead) == 1
    (origin, label), = dead
    assert label == "QUERY_OVERLOADED"
    assert origin[0].endswith("viewer/client.py")


# -- crash-interleaving model ----------------------------------------------

def test_crash_model_clean_and_covers_every_seam():
    rep = explore.explore_crash_model()
    assert rep.violations == []
    assert rep.seams_fired == set(explore.CRASH_SEAMS)
    assert rep.quiescent_ok > 0


def test_crash_model_claim_dedup_off_double_commits():
    rep = explore.explore_crash_model(
        explore.CrashSpec(claim_dedup=False))
    assert {v.kind for v in rep.violations} == {"crash-dual"}


def test_crash_model_pending_exclusion_off_loses_the_tile():
    rep = explore.explore_crash_model(
        explore.CrashSpec(pending_exclusion=False))
    assert {v.kind for v in rep.violations} == {"crash-lost"}


def test_crash_seams_match_registered_crashpoints(real_report):
    # exact two-way coverage: every faults.hit literal in the tree is
    # a modeled seam (the fsm-dead-arm rule enforces that direction)
    # AND every modeled seam exists in the code (the model must not
    # outgrow the crashpoints it claims to cover)
    import ast

    from distributedmandelbrot_tpu.analysis.astutil import (attr_chain,
                                                            cached_walk)
    project, _, _ = real_report
    hits: set[str] = set()
    for sf in project.files.values():
        for node in cached_walk(sf.tree):
            if isinstance(node, ast.Call) and node.args \
                    and isinstance(node.args[0], ast.Constant) \
                    and isinstance(node.args[0].value, str):
                chain = attr_chain(node.func)
                if chain and chain[-1] == "hit" \
                        and "faults" in chain[:-1]:
                    hits.add(node.args[0].value)
    assert hits == set(explore.CRASH_SEAMS)


# -- DOT export -------------------------------------------------------------

def test_to_dot_renders_every_pair():
    _, pair = query_pair(QUERY_SERVER)
    dot = fsm.to_dot([pair])
    assert dot.startswith("digraph fsm {")
    assert "!QUERY" in dot and "?QUERY" in dot
    assert "doublecircle" in dot  # accepting states marked
    assert dot.count("subgraph") == 2
