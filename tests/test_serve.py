"""Serving gateway: cache tiers, single-flight coalescing, compute-on-read,
admission control — units plus end-to-end against the embedded coordinator."""

import asyncio
import threading
import time

import numpy as np
import pytest

from distributedmandelbrot_tpu.core import LevelSetting
from distributedmandelbrot_tpu.core.chunk import Chunk
from distributedmandelbrot_tpu.core.geometry import CHUNK_PIXELS
from distributedmandelbrot_tpu.serve import (DecodedTileCache, SingleFlight,
                                             TokenBucket)
from distributedmandelbrot_tpu.utils.metrics import Counters
from distributedmandelbrot_tpu.viewer import DataClient, FetchStatus
from distributedmandelbrot_tpu.worker import (DistributerClient, NumpyBackend,
                                              Worker)

from harness import CoordinatorHarness
from test_e2e import golden_tile

MAX_ITER = 12  # NumpyBackend is the bit-exact golden at any depth


# -- cache tiers ----------------------------------------------------------

class StubStore:
    """load_payload-only store double; counts reads per key."""

    def __init__(self, payloads):
        self.payloads = payloads
        self.reads = Counters()

    def load_payload(self, level, i, j):
        self.reads.inc(str((level, i, j)))
        return self.payloads.get((level, i, j))


def test_cache_promotion_hit_and_counters():
    store = StubStore({(1, 0, 0): b"payload-a"})
    counters = Counters()
    cache = DecodedTileCache(store, capacity=4, counters=counters)
    assert cache.get_cached((1, 0, 0)) is None  # cold tier 1
    entry = cache.load((1, 0, 0))  # store fallthrough promotes
    assert entry.payload == b"payload-a"
    assert counters.get("tile_cache_promotions") == 1
    assert cache.get_cached((1, 0, 0)).payload == b"payload-a"
    assert counters.get("tile_cache_hits") == 1
    # A tier-1 hit inside load() must not re-read the store.
    cache.load((1, 0, 0))
    assert store.reads.get(str((1, 0, 0))) == 1
    # Absent everywhere: miss, no promotion.
    assert cache.load((1, 0, 1)) is None
    assert counters.get("tile_cache_promotions") == 1


def test_cache_lru_eviction_order_and_counter():
    counters = Counters()
    cache = DecodedTileCache(StubStore({}), capacity=2, counters=counters)
    cache.put((1, 0, 0), b"a")
    cache.put((2, 0, 0), b"b")
    cache.get_cached((1, 0, 0))  # touch: (1,0,0) is now most recent
    cache.put((2, 0, 1), b"c")  # evicts (2,0,0), the least recent
    assert counters.get("tile_cache_evictions") == 1
    assert len(cache) == 2
    assert cache.get_cached((2, 0, 0)) is None
    assert cache.get_cached((1, 0, 0)) is not None


def test_cached_tile_decodes_pixels_lazily():
    chunk = Chunk.filled(1, 0, 0, 7)
    cache = DecodedTileCache(StubStore({}), capacity=1)
    entry = cache.put((1, 0, 0), chunk.serialize())
    pixels = entry.pixels
    assert pixels.shape == (CHUNK_PIXELS,)
    assert (pixels == 7).all()
    assert entry.pixels is pixels  # decoded once, then cached
    with pytest.raises(ValueError):
        pixels[0] = 0  # decoded view is read-only


# -- single-flight coalescing ---------------------------------------------

def test_single_flight_many_callers_one_supplier_call():
    counters = Counters()
    flight = SingleFlight(counters)
    calls = []

    async def supplier():
        calls.append(1)
        await asyncio.sleep(0.05)
        return b"tile"

    async def main():
        results = await asyncio.gather(
            *(flight.run("k", supplier) for _ in range(32)))
        return results

    results = asyncio.run(main())
    assert len(calls) == 1
    assert all(r == b"tile" for r in results)
    assert counters.get("coalesce_leaders") == 1
    assert counters.get("coalesce_followers") == 31
    assert flight.inflight_count == 0


def test_single_flight_error_fans_out_then_resets():
    flight = SingleFlight()

    async def boom():
        await asyncio.sleep(0.01)
        raise RuntimeError("store exploded")

    async def ok():
        return b"fine"

    async def main():
        results = await asyncio.gather(
            *(flight.run("k", boom) for _ in range(4)),
            return_exceptions=True)
        assert all(isinstance(r, RuntimeError) for r in results)
        # The failed flight is unregistered: a retry starts fresh.
        assert await flight.run("k", ok) == b"fine"

    asyncio.run(main())


def test_single_flight_distinct_keys_do_not_coalesce():
    flight = SingleFlight()
    calls = []

    async def supplier(k):
        calls.append(k)
        await asyncio.sleep(0.01)
        return k

    async def main():
        return await asyncio.gather(
            flight.run("a", lambda: supplier("a")),
            flight.run("b", lambda: supplier("b")))

    assert asyncio.run(main()) == ["a", "b"]
    assert sorted(calls) == ["a", "b"]


def test_single_flight_follower_cancel_leaves_flight_alive():
    """A follower timing out must not cancel the shared flight."""
    flight = SingleFlight()

    async def slow():
        await asyncio.sleep(0.2)
        return b"eventually"

    async def main():
        leader = asyncio.ensure_future(flight.run("k", slow))
        await asyncio.sleep(0.01)
        with pytest.raises(asyncio.TimeoutError):
            await asyncio.wait_for(flight.run("k", slow), 0.01)
        return await leader

    assert asyncio.run(main()) == b"eventually"


# -- token bucket ---------------------------------------------------------

def test_token_bucket_burst_refill_and_disabled():
    t = [0.0]
    bucket = TokenBucket(rate=10.0, burst=2.0, clock=lambda: t[0])
    assert bucket.try_acquire()
    assert bucket.try_acquire()
    assert not bucket.try_acquire()  # burst drained
    t[0] += 0.1  # one token refilled at 10/s
    assert bucket.try_acquire()
    assert not bucket.try_acquire()
    t[0] += 100.0  # refill clamps at burst, not 1000 tokens
    assert bucket.try_acquire()
    assert bucket.try_acquire()
    assert not bucket.try_acquire()
    assert TokenBucket(rate=None, burst=0.0).try_acquire()  # disabled


# -- end-to-end against the embedded coordinator --------------------------

def _worker_thread(farm, stop):
    worker = Worker(DistributerClient("127.0.0.1", farm.distributer_port),
                    NumpyBackend(), overlap_io=False)
    t = threading.Thread(target=worker.run_forever,
                         kwargs=dict(poll_interval=0.05, stop=stop),
                         daemon=True)
    t.start()
    return t


def test_ondemand_roundtrip_golden_then_cache_hit(tmp_path):
    """Acceptance: a gateway request for an absent tile is computed on
    demand and byte-identical to the numpy golden; a second request is a
    decoded-cache hit with no second compute."""
    with CoordinatorHarness(str(tmp_path), [LevelSetting(1, MAX_ITER)],
                            ondemand_deadline=120.0) as farm:
        stop = threading.Event()
        wt = _worker_thread(farm, stop)
        try:
            client = DataClient("127.0.0.1", farm.gateway_port, timeout=120)
            pixels, status = client.fetch(1, 0, 0)
            assert status is FetchStatus.OK
            np.testing.assert_array_equal(
                pixels, golden_tile(1, 0, 0, MAX_ITER))
            assert farm.counters.get("ondemand_served") == 1
            assert farm.counters.get("workloads_granted") == 1

            hits_before = farm.counters.get("tile_cache_hits")
            pixels2, status2 = client.fetch(1, 0, 0)
            assert status2 is FetchStatus.OK
            np.testing.assert_array_equal(pixels2, pixels)
            assert farm.counters.get("tile_cache_hits") == hits_before + 1
            assert farm.counters.get("workloads_granted") == 1  # no recompute
            assert farm.counters.get("ondemand_requests") == 1
        finally:
            stop.set()
            wt.join(timeout=30)


def test_coalesced_storm_single_compute(tmp_path):
    """Acceptance: 32 concurrent requests for the same uncomputed tile
    cause exactly one scheduler grant and one store write, and every
    client receives identical correct bytes."""
    n_clients = 32
    with CoordinatorHarness(str(tmp_path), [LevelSetting(1, MAX_ITER)],
                            ondemand_deadline=120.0) as farm:
        stop = threading.Event()
        results: dict[int, tuple] = {}
        errors: list = []
        barrier = threading.Barrier(n_clients)

        def storm(idx):
            try:
                client = DataClient("127.0.0.1", farm.gateway_port,
                                    timeout=120)
                barrier.wait()
                results[idx] = client.fetch(1, 0, 0)
                client.close()
            except BaseException as e:
                errors.append(e)

        threads = [threading.Thread(target=storm, args=(i,), daemon=True)
                   for i in range(n_clients)]
        for t in threads:
            t.start()
        # Start the worker only after the storm is in flight so every
        # request sees an uncomputed tile.
        time.sleep(0.3)
        wt = _worker_thread(farm, stop)
        try:
            for t in threads:
                t.join(timeout=180)
            assert not any(t.is_alive() for t in threads)
            assert not errors, errors[:2]
        finally:
            stop.set()
            wt.join(timeout=30)

        golden = golden_tile(1, 0, 0, MAX_ITER)
        assert len(results) == n_clients
        for pixels, status in results.values():
            assert status is FetchStatus.OK
            np.testing.assert_array_equal(pixels, golden)
        # The whole storm cost ONE farm compute and ONE store write.
        assert farm.counters.get("workloads_granted") == 1
        assert farm.counters.get("chunks_saved") == 1
        assert farm.counters.get("results_accepted") == 1
        assert farm.counters.get("coalesce_leaders") == 1
        assert farm.counters.get("coalesce_followers") == n_clients - 1


def test_ondemand_deadline_expiry(tmp_path):
    """No worker: an on-demand wait must end at the deadline with
    NOT_AVAILABLE, not hang."""
    with CoordinatorHarness(str(tmp_path), [LevelSetting(1, MAX_ITER)],
                            ondemand_deadline=0.3) as farm:
        client = DataClient("127.0.0.1", farm.gateway_port, timeout=30)
        t0 = time.monotonic()
        pixels, status = client.fetch(1, 0, 0)
        elapsed = time.monotonic() - t0
        assert status is FetchStatus.NOT_AVAILABLE
        assert pixels is None
        assert elapsed < 10.0
        assert farm.counters.get("ondemand_timeouts") == 1


def test_ondemand_heals_completed_tile_missing_from_store(tmp_path):
    """A tile the scheduler recorded as completed but whose bytes are gone
    (wiped data dir) must be un-completed and recomputed on read, not left
    to time out forever."""
    with CoordinatorHarness(str(tmp_path), [LevelSetting(1, MAX_ITER)],
                            ondemand_deadline=120.0) as farm:
        # Simulate the loss: complete the only tile without saving bytes.
        w = farm.scheduler.acquire()
        assert farm.scheduler.complete(w)
        assert farm.scheduler.is_complete()

        stop = threading.Event()
        wt = _worker_thread(farm, stop)
        try:
            client = DataClient("127.0.0.1", farm.gateway_port, timeout=120)
            pixels, status = client.fetch(1, 0, 0)
            assert status is FetchStatus.OK
            np.testing.assert_array_equal(
                pixels, golden_tile(1, 0, 0, MAX_ITER))
            assert farm.counters.get("ondemand_healed") == 1
            assert farm.counters.get("ondemand_served") == 1
        finally:
            stop.set()
            wt.join(timeout=30)


def test_gateway_load_shed_overloaded(tmp_path):
    """Queue-depth load shedding: with one serving slot occupied by an
    on-demand wait, the next miss is shed with an explicit OVERLOADED."""
    with CoordinatorHarness(str(tmp_path), [LevelSetting(2, MAX_ITER)],
                            ondemand_deadline=8.0,
                            gateway_max_queue_depth=1) as farm:
        parked: list = []

        def slow_fetch():
            client = DataClient("127.0.0.1", farm.gateway_port, timeout=30)
            parked.append(client.fetch(2, 0, 0))

        t = threading.Thread(target=slow_fetch, daemon=True)
        t.start()
        deadline = time.monotonic() + 5
        while farm.counters.get("ondemand_requests") < 1:
            assert time.monotonic() < deadline, "first fetch never parked"
            time.sleep(0.02)
        # The slot is held by the parked on-demand wait: shed this one.
        _, status = DataClient("127.0.0.1", farm.gateway_port,
                               timeout=30).fetch(2, 1, 1)
        assert status is FetchStatus.OVERLOADED
        assert farm.counters.get("gateway_overloaded") == 1
        t.join(timeout=30)
        assert not t.is_alive()
        assert parked[0][1] is FetchStatus.NOT_AVAILABLE


def test_gateway_token_bucket_sheds_after_burst(tmp_path):
    """Rate admission: with a one-token bucket and no refill, the second
    miss in a burst is OVERLOADED."""
    with CoordinatorHarness(str(tmp_path), [LevelSetting(2, MAX_ITER)],
                            ondemand_deadline=0.2,
                            gateway_rate=0.001, gateway_burst=1.0) as farm:
        client = DataClient("127.0.0.1", farm.gateway_port, timeout=30)
        _, status1 = client.fetch(2, 0, 0)  # consumes the only token
        assert status1 is FetchStatus.NOT_AVAILABLE  # no worker: times out
        _, status2 = client.fetch(2, 0, 1)
        assert status2 is FetchStatus.OVERLOADED


def test_gateway_legacy_protocol_and_batch(tmp_path):
    """The gateway speaks the legacy 12-byte query byte-for-byte (REJECT /
    NOT_AVAILABLE / ACCEPT) and the batched framing returns per-item
    responses in request order."""
    with CoordinatorHarness(str(tmp_path), [LevelSetting(1, MAX_ITER)],
                            ondemand_deadline=0.2) as farm:
        # Persist a tile directly (no farm round trip needed here).
        chunk = Chunk.filled(1, 0, 0, 9)
        farm.store.save(chunk)

        client = DataClient("127.0.0.1", farm.gateway_port, timeout=30)
        pixels, status = client.fetch(1, 0, 0)
        assert status is FetchStatus.OK
        assert (pixels == 9).all()
        _, status = client.fetch(0, 0, 0)  # invalid: level 0
        assert status is FetchStatus.REJECTED
        _, status = client.fetch(3, 5, 0)  # invalid: index >= level
        assert status is FetchStatus.REJECTED

        got = client.fetch_many([(1, 0, 0), (1, 0, 0), (9, 0, 0), (5, 7, 7)])
        statuses = [s for _, s in got]
        assert statuses == [FetchStatus.OK, FetchStatus.OK,
                            FetchStatus.NOT_AVAILABLE, FetchStatus.REJECTED]
        assert (got[0][0] == 9).all() and (got[1][0] == 9).all()
        assert farm.counters.get("gateway_batches") == 1


def test_dataserver_unchanged_alongside_gateway(tmp_path):
    """Wire-compat guard: the legacy DataServer port still serves the
    reference protocol while the gateway runs in the same coordinator."""
    with CoordinatorHarness(str(tmp_path), [LevelSetting(1, MAX_ITER)]) as farm:
        chunk = Chunk.filled(1, 0, 0, 3)
        farm.store.save(chunk)
        legacy = DataClient("127.0.0.1", farm.dataserver_port, timeout=30)
        pixels, status = legacy.fetch(1, 0, 0)
        assert status is FetchStatus.OK
        assert (pixels == 3).all()
        _, status = legacy.fetch(2, 0, 0)  # absent: DataServer never computes
        assert status is FetchStatus.NOT_AVAILABLE
