"""Deterministic, jax-free unit tests for the sessions subsystem.

Everything here runs on injected clocks (ManualClock for the table,
scheduler, and fairness buckets) or on pure functions (the predictor,
the trajectory workload model) — no sockets, no wall-clock sleeps, no
accelerator.  The wire-level session behavior lives in
tests/test_fuzz_frames.py and tests/test_gateway.py.
"""

from __future__ import annotations

import numpy as np
import pytest

from distributedmandelbrot_tpu.core.chunk import Chunk
from distributedmandelbrot_tpu.core.geometry import CHUNK_PIXELS
from distributedmandelbrot_tpu.core.workload import LevelSetting, Workload
from distributedmandelbrot_tpu.coordinator.clock import ManualClock
from distributedmandelbrot_tpu.coordinator.scheduler import TileScheduler
from distributedmandelbrot_tpu.loadgen import (build_session_schedule,
                                               ok_spread, parse_phases)
from distributedmandelbrot_tpu.loadgen.trajectory import _reflect
from distributedmandelbrot_tpu.net import protocol as proto
from distributedmandelbrot_tpu.obs import names as obs_names
from distributedmandelbrot_tpu.serve.cache import (DecodedTileCache,
                                                   RenderedTileCache)
from distributedmandelbrot_tpu.sessions import (PrefetchPlanner,
                                                RefinementTracker,
                                                SessionService,
                                                SessionState, SessionTable,
                                                build_session_service,
                                                predict_tiles)
from distributedmandelbrot_tpu.sessions.table import ViewportObs
from distributedmandelbrot_tpu.storage.backends import (MemoryObjectStore,
                                                        ObjectStoreBackend)
from distributedmandelbrot_tpu.storage.store import ChunkStore
from distributedmandelbrot_tpu.utils.metrics import Counters

SETTINGS = [LevelSetting(8, 100)]


def obs_at(points, dt=1.0):
    """ViewportObs sequence from (level, i, j) keys, dt apart."""
    return tuple(ViewportObs(k * dt, level, i, j)
                 for k, (level, i, j) in enumerate(points))


def make_cache(level=8, tiles=(), counters=None):
    """DecodedTileCache over a memory store seeded with ``tiles``."""
    store = ChunkStore(backend=ObjectStoreBackend(MemoryObjectStore()))
    pixels = np.ones(CHUNK_PIXELS, dtype=np.uint8)
    for (lvl, i, j) in tiles:
        store.save(Chunk(lvl, i, j, pixels))
    return DecodedTileCache(store, counters=counters)


# -- predictor ------------------------------------------------------------


def test_predictor_pure_pan_extrapolates_exactly():
    # Steady +1 pan along index_real at a fixed level: predictions are
    # exactly the next tiles on the line, nearest first.
    traj = obs_at([(8, i, 3) for i in range(4)])
    assert predict_tiles(traj, horizon=3) == [(8, 4, 3), (8, 5, 3),
                                              (8, 6, 3)]


def test_predictor_diagonal_pan():
    traj = obs_at([(8, i, i) for i in range(2, 6)])
    assert predict_tiles(traj, horizon=2) == [(8, 6, 6), (8, 7, 7)]


def test_predictor_stationary_returns_nothing():
    traj = obs_at([(8, 4, 4)] * 5)
    assert predict_tiles(traj, horizon=3) == []


def test_predictor_needs_two_observations_and_advancing_clock():
    assert predict_tiles(obs_at([(8, 1, 1)]), horizon=3) == []
    frozen = (ViewportObs(5.0, 8, 1, 1), ViewportObs(5.0, 8, 2, 1))
    assert predict_tiles(frozen, horizon=3) == []


def test_predictor_zoom_rescales_pan_onto_target_grid():
    # Doubling the level each step: predictions land on the finer grid,
    # not on level-8 indices carried verbatim.
    traj = (ViewportObs(0.0, 4, 2, 2), ViewportObs(1.0, 8, 4, 4))
    predicted = predict_tiles(traj, horizon=1)
    assert predicted == [(12, 6, 6)]


def test_predictor_dedups_current_tile_and_repeats():
    # A slow pan (1 tile per 4 steps) predicts sub-tile drift: steps that
    # round back onto the current tile are dropped, and a repeated target
    # is emitted once.
    traj = obs_at([(8, 0, 3), (8, 0, 3), (8, 0, 3), (8, 1, 3), (8, 1, 3)])
    assert predict_tiles(traj, horizon=3) == [(8, 2, 3)]


# -- session table: issuance, TTL, LRU ------------------------------------


def test_table_issues_monotonic_nonzero_ids():
    table = SessionTable(counters=Counters())
    a, b = table.open(0), table.open(0)
    assert (a.session_id, b.session_id) == (1, 2)
    assert table.touch(1) is a
    assert table.touch(999) is None


def test_table_ttl_expires_lazily_on_touch():
    clock = ManualClock()
    counters = Counters()
    table = SessionTable(ttl=10.0, clock=clock.now, counters=counters)
    sid = table.open(0).session_id
    clock.advance(10.0)  # exactly ttl: still alive (strict >)
    assert table.touch(sid) is not None
    clock.advance(10.5)
    assert table.touch(sid) is None
    assert counters.get(obs_names.SESSION_EXPIRED) == 1
    assert len(table) == 0


def test_table_touch_refreshes_idle_clock():
    clock = ManualClock()
    table = SessionTable(ttl=10.0, clock=clock.now, counters=Counters())
    sid = table.open(0).session_id
    for _ in range(5):
        clock.advance(8.0)
        assert table.touch(sid) is not None  # kept alive by activity


def test_table_sweep_expires_in_bulk():
    clock = ManualClock()
    counters = Counters()
    table = SessionTable(ttl=10.0, clock=clock.now, counters=counters)
    for _ in range(3):
        table.open(0)
    clock.advance(11.0)
    survivor = table.open(0).session_id
    assert table.sweep() == 3
    assert counters.get(obs_names.SESSION_EXPIRED) == 3
    assert table.touch(survivor) is not None


def test_table_capacity_evicts_least_recently_touched():
    counters = Counters()
    table = SessionTable(capacity=2, ttl=None, counters=counters)
    a = table.open(0).session_id
    b = table.open(0).session_id
    table.touch(a)  # b is now LRU
    c = table.open(0).session_id
    assert counters.get(obs_names.SESSION_EVICTED) == 1
    assert table.touch(b) is None
    assert table.touch(a) is not None and table.touch(c) is not None


def test_table_varz_counts():
    table = SessionTable(capacity=8, ttl=300.0, counters=Counters())
    table.open(0)
    table.open(0)
    varz = table.varz()
    assert varz["active"] == 2 and varz["issued"] == 2
    assert varz["opened"] == 2 and varz["evicted"] == 0


# -- per-session fairness budgets -----------------------------------------


def test_session_budget_throttles_and_refills_on_injected_clock():
    clock = ManualClock()
    state = SessionState(1, 0, rate=2.0, burst=2.0, clock=clock.now)
    assert state.admit() and state.admit()
    assert not state.admit()  # burst exhausted
    clock.advance(1.0)  # refill 2 tokens
    assert state.admit() and state.admit()
    assert not state.admit()


def test_session_weight_scales_rate_and_burst():
    clock = ManualClock()
    heavy = SessionState(1, 0, weight=2.0, rate=2.0, burst=2.0,
                         clock=clock.now)
    admitted = sum(heavy.admit() for _ in range(10))
    assert admitted == 4  # burst * weight
    clock.advance(1.0)
    assert sum(heavy.admit() for _ in range(10)) == 4  # rate * weight


def test_session_no_rate_admits_everything():
    state = SessionState(1, 0, rate=None)
    assert all(state.admit() for _ in range(1000))


# -- prefetch marks + planner ---------------------------------------------


def test_prefetch_marks_consume_once():
    state = SessionState(1, proto.SESSION_CAP_PREFETCH)
    assert state.mark_prefetched((8, 1, 1))
    assert not state.mark_prefetched((8, 1, 1))  # no replanning
    assert state.consume_prefetch((8, 1, 1))
    assert not state.consume_prefetch((8, 1, 1))  # hit scored once


def test_planner_marks_all_predictions_but_returns_only_cold_keys():
    clock = ManualClock()
    counters = Counters()
    # (8, 4, 3) is already resident in tier 1; (8, 5, 3) and (8, 6, 3)
    # are cold.
    cache = make_cache(tiles=[(8, 4, 3)], counters=counters)
    assert cache.load((8, 4, 3)) is not None
    planner = PrefetchPlanner(cache, counters=counters)
    state = SessionState(1, proto.SESSION_CAP_PREFETCH, clock=clock.now)
    for i in range(4):
        state.observe(8, i, 3, float(i))
    picked = planner.plan(state)
    # The resident tile is marked (prediction accuracy counts it) but
    # not picked for warming.
    assert picked == [(8, 5, 3), (8, 6, 3)]
    assert counters.get(obs_names.PREFETCH_PLANNED) == 3
    assert state.consume_prefetch((8, 4, 3))
    # Replanning the same trajectory marks nothing new.
    assert planner.plan(state) == []


def test_planner_drops_out_of_range_predictions():
    # Pan off the grid edge: predictions past index 7 at level 8 are
    # discarded, not marked.
    cache = make_cache()
    planner = PrefetchPlanner(cache, counters=Counters())
    state = SessionState(1, proto.SESSION_CAP_PREFETCH)
    for k, i in enumerate(range(4, 8)):
        state.observe(8, i, 0, float(k))
    assert planner.plan(state) == []


def test_planner_execute_warms_cache_and_schedules_cold_compute():
    import asyncio
    clock = ManualClock()
    counters = Counters()
    cache = make_cache(tiles=[(8, 5, 3)], counters=counters)
    sched = TileScheduler(SETTINGS, clock=clock)
    planner = PrefetchPlanner(cache, scheduler=sched, counters=counters)
    asyncio.run(planner.execute([(8, 5, 3), (8, 6, 3)]))
    assert counters.get(obs_names.PREFETCH_WARMED) == 1
    assert cache.contains((8, 5, 3))
    assert counters.get(obs_names.PREFETCH_SCHEDULED) == 1
    # The scheduled tile is at the frontier head, at full depth.
    w = sched.acquire()
    assert w == Workload(8, 100, 6, 3)


# -- progressive refinement ----------------------------------------------


def test_scheduler_refine_uncompletes_and_regrants_at_depth():
    clock = ManualClock()
    sched = TileScheduler(SETTINGS, clock=clock)
    shallow = sched.acquire()
    assert sched.complete(shallow)
    done = sched.completed_count
    deep = Workload(shallow.level, 5000, shallow.index_real,
                    shallow.index_imag)
    assert sched.refine(deep)
    assert sched.completed_count == done - 1
    regrant = sched.acquire()
    assert regrant == deep  # frontier head, at the refined depth
    assert sched.complete(regrant)
    assert sched.completed_count == done


def test_scheduler_refine_rejects_out_of_grid():
    sched = TileScheduler(SETTINGS, clock=ManualClock())
    assert not sched.refine(Workload(16, 100, 0, 0))


def test_refinement_tracker_idempotent_until_saved():
    clock = ManualClock()
    counters = Counters()
    sched = TileScheduler(SETTINGS, clock=clock)
    tracker = RefinementTracker(sched, counters=counters)
    deep = Workload(8, 5000, 2, 2)
    assert tracker.schedule(deep)
    assert tracker.schedule(deep)  # in flight: no double-queue
    assert counters.get(obs_names.SESSION_REFINES_SCHEDULED) == 1
    assert tracker.pending == 1
    tracker.on_saved((8, 9, 9))  # unrelated save: ignored
    assert tracker.pending == 1
    tracker.on_saved(deep.key)
    assert tracker.pending == 0
    assert counters.get(obs_names.SESSION_REFINES_COMPLETED) == 1
    tracker.on_saved(deep.key)  # completion counted once
    assert counters.get(obs_names.SESSION_REFINES_COMPLETED) == 1


def test_cache_invalidation_drops_shallow_variants():
    counters = Counters()
    cache = make_cache(tiles=[(8, 1, 1)], counters=counters)
    assert cache.load((8, 1, 1)) is not None
    assert cache.invalidate((8, 1, 1))
    assert not cache.contains((8, 1, 1))
    assert not cache.invalidate((8, 1, 1))  # second drop is a no-op
    assert counters.get(obs_names.TILE_CACHE_INVALIDATIONS) == 1

    rendered = RenderedTileCache(counters=counters)
    rendered.put((8, 1, 1, 0), b"png0")
    rendered.put((8, 1, 1, 1), b"png1")
    rendered.put((8, 2, 2, 0), b"keep")
    assert rendered.invalidate_tile((8, 1, 1)) == 2  # every colormap
    assert rendered.get((8, 2, 2, 0)) == b"keep"
    assert counters.get(
        obs_names.GATEWAY_RENDER_CACHE_INVALIDATIONS) == 2


# -- session service facade ----------------------------------------------


def test_service_negotiates_caps_from_construction():
    cache = make_cache()
    # No scheduler: prefetch-by-warming only, refine negotiated away.
    read_only = build_session_service(cache, counters=Counters())
    assert read_only.caps == proto.SESSION_CAP_PREFETCH
    full = build_session_service(cache, scheduler=TileScheduler(
        SETTINGS, clock=ManualClock()), counters=Counters())
    assert full.caps == proto.SESSION_CAPS_MASK
    # Requested ∩ granted.
    state = read_only.open(proto.SESSION_CAPS_MASK)
    assert state.caps == proto.SESSION_CAP_PREFETCH


def test_service_scores_hits_and_misses_on_marked_tiles():
    clock = ManualClock()
    counters = Counters()
    service = build_session_service(make_cache(counters=counters),
                                    clock=clock.now, counters=counters)
    state = service.open(proto.SESSION_CAP_PREFETCH)
    for i in range(4):
        clock.advance(1.0)
        service.note_query(state, 8, i, 3)
    # First two queries precede any prediction (cold misses); once the
    # pan is established, each query lands on a marked tile.
    assert counters.get(obs_names.PREFETCH_MISSES) == 2
    assert counters.get(obs_names.PREFETCH_HITS) == 2
    clock.advance(1.0)
    service.note_query(state, 8, 4, 3)  # predicted continuation: hit
    assert counters.get(obs_names.PREFETCH_HITS) == 3
    clock.advance(1.0)
    service.note_query(state, 8, 0, 0)  # swerve: miss
    assert counters.get(obs_names.PREFETCH_HITS) == 3
    assert counters.get(obs_names.PREFETCH_MISSES) == 3


def test_service_without_prefetch_cap_scores_nothing():
    clock = ManualClock()
    counters = Counters()
    service = build_session_service(make_cache(counters=counters),
                                    clock=clock.now, counters=counters)
    state = service.open(0)  # prefetch not requested
    for i in range(5):
        clock.advance(1.0)
        assert service.note_query(state, 8, i, 3) == []
    assert counters.get(obs_names.PREFETCH_HITS) == 0
    assert counters.get(obs_names.PREFETCH_MISSES) == 0


def test_service_first_paint_iter_gating():
    cache = make_cache()
    sched = TileScheduler(SETTINGS, clock=ManualClock())
    service = build_session_service(cache, scheduler=sched,
                                    first_paint_max_iter=64,
                                    counters=Counters())
    assert service.first_paint_iter(2500) == 64
    assert service.first_paint_iter(64) is None  # already that cheap
    assert service.first_paint_iter(None) is None  # unknown level
    read_only = build_session_service(cache, counters=Counters())
    assert read_only.first_paint_iter(2500) is None


# -- trajectory workload model --------------------------------------------


def test_reflect_bounces_inside_grid():
    level = 8
    walk = [_reflect(x, level) for x in range(-3, 3 * level)]
    assert all(0 <= x < level for x in walk)
    # A straight pan folds into ... 6 7 7 6 ... — adjacent positions
    # never jump more than one tile (no teleports to poison velocity).
    assert all(abs(a - b) <= 1 for a, b in zip(walk, walk[1:]))


def test_session_schedule_is_deterministic_and_correlated():
    phases = parse_phases("steady:50x2")
    kwargs = dict(level=8, sessions=4, seed=7, hot_share=0.0)
    a = build_session_schedule(phases, **kwargs)
    assert a == build_session_schedule(phases, **kwargs)
    assert {r.session for r in a} <= set(range(4))
    assert all(proto.query_in_range(r.level, r.index_real, r.index_imag)
               for r in a)
    # Per-session streams are straight-line pans: consecutive queries of
    # one session move at most one tile per axis.
    for slot in range(4):
        stream = [r for r in a if r.session == slot]
        for prev, cur in zip(stream, stream[1:]):
            assert abs(cur.index_real - prev.index_real) <= 1
            assert abs(cur.index_imag - prev.index_imag) <= 1


def test_session_schedule_hot_share_skews_to_slot_zero():
    phases = parse_phases("steady:200x2")
    schedule = build_session_schedule(phases, level=8, sessions=8,
                                      seed=0, hot_share=0.6)
    hot = sum(1 for r in schedule if r.session == 0)
    assert hot / len(schedule) > 0.5


def test_session_schedule_validates_inputs():
    phases = parse_phases("steady:10x1")
    with pytest.raises(ValueError):
        build_session_schedule(phases, level=8, sessions=0)
    with pytest.raises(ValueError):
        build_session_schedule(phases, level=8, sessions=2, hot_share=1.0)


def test_ok_spread_counts_absent_slots_as_zero():
    assert ok_spread({0: 10, 2: 4}, 4) == (0, 10)
    assert ok_spread({}, 3) == (0, 0)
